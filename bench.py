"""Headline benchmark: BOTH BASELINE.json metrics in one artifact.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Fields (BASELINE.json "metric" names both quantities):
- value: Higgs-1M-shaped histogram build, M-rows/sec/chip — 1M rows x 28
  features x 255 bins x 32 nodes (the widest level of the depth-6 config,
  which dominates training time). vs_baseline is the ratio to the CPU
  reference kernel measured on this same machine (the reference published
  no numbers; north-star target >= 5x on a v5e-8).
- value_64bin_optin + ab_ratio_64bin: the transposed-kernel opt-in
  contract, measured INTERLEAVED with the 255-bin arm in one process
  (docs/PERF.md protocol — adjacent separate runs through the tunnel
  wash out the ratio; round-3's artifact did exactly that).
- e2e_train_s: metric #2 — the Higgs-1M depth-6 x 100-tree build
  wallclock, fused multi-round dispatch.
- predict_mrows_per_sec: the 10M-row x 1000-tree scoring config,
  device-resident batch (upload excluded; predict_total_s records the
  everything-included wallclock for context).
- split_agreement / auc_delta: cheap real-chip vs CPU-oracle training
  parity re-witnessed every run (experiments/chip_parity.py measured the
  cross-platform seam once; this keeps it measured).

Every floored quantity fails the bench loudly when it regresses past the
known-bad boundary; floors sit below every observed tunnel noise band.

Runs on whatever platform jax defaults to (the real TPU chip under the
driver; floors and parity apply only there). The CPU reference uses the
native C++ kernel when built, else NumPy np.add.at — the stronger
(faster) of the two is the honest baseline.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import uuid

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Artifact schema stamp (tools/benchwatch keys history on these instead
# of filenames): bump when a metric's meaning — not just its value —
# changes. v2 (training-megakernel round): e2e_implied_hist_mrows counts
# EFFECTIVE levels when the sibling-subtraction trick is active (levels
# past the root cost half a build), and hist_roofline_hbm_util stopped
# being banded higher-is-better — the VMEM-streaming kernel LOWERS
# bytes-accessed by design (the roofline verdict flipping hbm -> compute
# is the goal, not a regression).
BENCH_SCHEMA = 2


def _git_rev() -> str | None:
    """Short HEAD rev of the repo this bench ran from, or None outside a
    work tree — provenance for the artifact, never a failure cause."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def _injected_faults_active() -> bool:
    """True when a chaos-harness fault plan is active in this process
    (robustness/faultplan.py) — stamped into the artifact so benchwatch
    keeps chaos numbers out of bench history."""
    try:
        from ddt_tpu.robustness import faultplan
    except ImportError:
        return False
    return faultplan.active_plan() is not None

# Perf-regression floors (SURVEY.md §4). Histogram: RATCHETED for the
# VMEM-streaming kernel rewrite (training-megakernel round): the old
# kernel measured 40-64 Mrows/s/chip across tunnel bands and its ~250
# MB/build of prologue HBM traffic (int32 input copy + the [R, 2N]
# weighted one-hot) is gone — the rewrite targets >= 2x (>= 90) with a
# compute (not hbm) roofline verdict. Floor 60 sits under the worst old
# band shifted by the smallest credible rewrite win (~1.5x on the
# slowest band) while sitting ABOVE every old-kernel band: a silent
# fallback to the old traffic pattern or the matmul path (~26) trips it
# from any band. Re-calibrate against the first two post-landing
# artifacts if the measured bands land differently. E2E: the
# fused dispatch builds the 100-tree config in 11-23 s across bands;
# 32 s clears the slow band with margin. A ~3x granular-dispatch
# regression lands at 33-69 s and is caught from any band; note a
# smaller regression inside a fast band can hide under a fixed ceiling —
# the histogram floor covers the kernel side of that risk. Predict
# (round-5 formulation round, docs/PERF.md): the resident arm overlaps
# the [10M] f32 score fetch with compute (paired-protocol 1.33x over
# the old serial fetch; measured 2.4-3.9 Mrows/s across one run's band
# samples) — 1.2 sits below that band while catching the catastrophic
# scalar-gather descent regression (~0.3-0.4) and a slow-band loss of
# the overlap. The compute-only arm has no row-sized transfers in the
# timed region (the regression class the old 0.8 floor was really
# guarding): 4.2-4.4 Mrows/s in the pure-compute sweep, 3.56 in the
# first bench artifact (whose hist sample, 55.4, sat in a HIGH band —
# the arm's 5 per-chunk dispatch+sync round-trips still ride the
# tunnel, so scale by the band range: the hist floor admits bands down
# to 35, and 3.56 x 35/55.4 = 2.25 is the worst legit extrapolation).
# 2.2 sits just under that and catches the scalar-gather catastrophe
# (~0.3) and low/mid-band tree_chunk-misdispatch (~1.4-2.0) from any
# band; a high-band misdispatch (~2.3) and the per-level-descent mode
# (~2.7) land inside the band and stay covered by the phase
# experiments, not this floor.
TPU_FLOOR_MROWS = 60.0
# One-dispatch headline twin (round 5, experiments/hist_dispatch_ab.py
# + docs/PERF.md): iters kernel invocations in ONE jitted fori_loop —
# 7.6% within-window spread vs 33% for the dispatch-loop protocol
# (whose min-of-reps reports transient fast-tail excursions as the
# run's value). The device rate itself DRIFTS externally across roughly
# 45-65 on a minutes timescale (docs/PERF.md round-5 drift analysis),
# so this floor still tolerates the full span — but the tight
# within-window spread (3-8%) means a trip is far more likely a kernel
# regression than drift luck. The floored statistic is the MEDIAN of
# reps (round-5 advisor finding: min-of-reps is the same
# fast-tail-promoting stat the dispatch-loop docstring criticizes; the
# min is still recorded as *_min for artifact comparability). Note the
# median THROUGHPUT sits at or below the min-of-reps throughput
# (dt_med >= dt_min), so the historical 43.9-65.5 min-of-reps samples
# are an UPPER envelope for it: with the protocol's 3-8% within-window
# spread, the worst observed window's median lands near ~40-42. Floor
# 38 still sits under that — thinner margin than against the min, so
# treat an early trip near the floor as "re-measure, then bisect" —
# and stays above the matmul-fallback known-bad mode (~26).
# Five-probe calibration — refine as median artifacts accumulate.
# RATCHETED with the VMEM-streaming kernel (same rationale as
# TPU_FLOOR_MROWS above: old one-dispatch medians sat ~40-60; the
# rewrite's >= 2x target puts the new band at ~80-130, and 70 sits
# between every old-kernel band and the worst credible new one).
TPU_ONE_DISPATCH_FLOOR_MROWS = 70.0
E2E_CEILING_S = 32.0
# Predict floors, RAISED for the Pallas traversal kernel (inference
# overhaul PR): the one-hot path was bound by the comparison matrix's
# HBM traffic (~644 GB per 10M x 1000 scoring pass; compute-only
# 3.56-3.76 across five round-5 artifacts) — the VMEM-resident kernel
# removes that traffic, targeting >= 2x compute throughput (>= 7.5
# Mrows/s on the binned 10M x 1000 config). Compute floor 4.5 = the
# round-5 worst-band extrapolation (2.25) x the 2x kernel contract —
# below every expected band, above the one-hot ceiling (~3.8), so a
# silent fallback to the one-hot path (mis-dispatch, VMEM-guard
# regression) trips it from any band. Resident stays D2H-bound (the
# 40 MB score fetch is ~65% of wallclock), so its floor moves only to
# 1.5: above the old overlapped floor, below the 2.4-3.9 observed band
# shifted up by the compute saving. The PALLAS_AB floor guards the
# kernel's actual win: the paired pallas/one-hot ratio (median of
# order-alternating pairs, both arms sharing the band) must clear 1.3 —
# a kernel regressed to parity (~1.0) fails loudly while real bands
# (expected ~2x) keep margin.
PREDICT_FLOOR_MROWS = 1.5
PREDICT_COMPUTE_FLOOR_MROWS = 4.5
PREDICT_PALLAS_AB_FLOOR = 1.3
# e2e self-consistency (round-4 verdict item 9): the training loop is
# histogram-dominated, so rows x levels x trees / e2e_train_s — the
# throughput the e2e wallclock IMPLIES — must sit near the kernel
# throughput measured minutes earlier in the same process. The
# DENOMINATOR is the band-stable one-dispatch metric (median-of-reps,
# 3-8% within-window spread), NOT the dispatch-loop headline: round 5's
# 0.65 bound had to absorb the headline's min-of-reps fast-tail
# excursions (33% within-window spread, spuriously FAST samples
# promoted to the run's value, deflating legit ratios) on top of the
# real external drift, leaving the bound only ~6% below the
# max-adverse legitimate ratio — a flaky-gate margin (round-5 advisor
# finding). Against od_v that excursion term is gone: the median
# cannot report a transient, so the denominator tracks the window's
# true band, and the adverse combination is drift-only — the od
# window at the drift's fast end (~61 median; excursions past the
# band no longer reach the statistic) while the e2e minutes later
# rides the slow end (~44, x0.95 shape mix -> ~42 implied), ratio
# 0.74. Lower bound 0.70 sits under that corner with margin, is
# TIGHTER than the old 0.65 exactly because the denominator lost its
# fast-tail inflation, and a >=2x fused-path slowdown (typical ratios
# ~0.8-1.3 halving to 0.4-0.65) still breaches it from every drift
# combination observed. Upper bound 1.40 covers the reverse split
# (e2e fast / od window at the slow end, ~1.33 max adverse) while
# still catching a work miscount (fewer trees/levels than the config
# claims). The dispatch-loop ratio stays in the artifact
# (e2e_consistency_ratio_dispatch_loop) for cross-round comparability
# but is no longer floored.
E2E_CONSISTENCY_RATIO = (0.70, 1.40)
# The 64-bin opt-in's paired ratio measured 1.13-1.22 across three runs
# (median of 10 order-alternating pairs); losing the transposed kernel
# (e.g. a dispatch change silently routing n_bins<=128 to the row-major
# form) would put the ratio at ~1.0. 1.05 separates the two — and since
# the Bp=64 sublane layout was promoted to automatic dispatch for
# n_bins <= 64 (half the old 128-lane padding's OH footprint), the
# ratio should only widen; the floor stays the loss detector.
AB64_RATIO_FLOOR = 1.05
# Fused-round sibling subtraction (ops/grow.level_histograms): levels
# past the root build only left children (half the kernel work), so the
# paired per-tree ratio vs the full-build level loop should land near
# the work ratio (~1.3-1.6x once routing overhead dilutes it). A trick
# that silently fell out of the dispatch measures ~1.0; 1.05 separates
# the two from any tunnel band (both arms of a pair share the band).
HIST_FUSED_AB_FLOOR = 1.05
# Split-comms paired ratio (ISSUE 10, chip only): reduce-scatter split
# finding cuts per-level collective bytes >= 2x (the payload_ratio stamp
# is deterministic math and asserted in tests; at the Higgs shape over 8
# shards it is ~3.5x) and must never cost wallclock — ratio ~1.0 on a
# single-host mesh (localhost "wire"), > 1.0 once a real ICI/DCN fabric
# carries the histograms. ENCODED-BUT-UNWITNESSED: no post-landing chip
# artifact exists yet (rounds 7+ ran CPU-only); re-calibrate against the
# first two chip artifacts per docs/PERF.md "Histogram comms"
# (Re-calibration status), ratcheting UP if the fabric win is real.
HIST_COMMS_AB_FLOOR = 1.0
# 2D-mesh paired ratio (ISSUE 11, chip only): at the wide bench shape
# (F >= 1k) the 2D (rows x features) mesh cuts the per-device
# reduce-scatter slab another Pf-fold vs the 1D row mesh on the same
# device count (payload_ratio is deterministic counter math, asserted
# in tests/test_mesh2d.py) and must never cost wallclock — ratio ~1.0
# on a one-host virtual mesh, > 1.0 once a real fabric carries the
# slabs. ENCODED-BUT-UNWITNESSED like every post-r05 floor (rounds
# 6-11 ran CPU-only); re-calibrate against the first two chip
# artifacts per docs/PERF.md "2D sharding" (Re-calibration status).
HIST_2D_AB_FLOOR = 1.0
# Quantized-gradient paired ratio (ISSUE 14, chip only): int8 g/h cut
# the per-level g/h HBM stream 4x (the payload_ratio stamp is
# deterministic byte math — telemetry.counters.grad_stream_bytes,
# asserted in tests/test_grad_quant.py) and the integer dot rides the
# MXU's native s8 path, so the quantized arm must never cost wallclock
# — ratio ~1.0 is the never-regress bar, > 1.0 once real HBM bandwidth
# is the constraint. ENCODED-BUT-UNWITNESSED like every post-r05 floor
# (this round ran CPU-only); re-calibrate against the first two chip
# artifacts per docs/PERF.md "Quantized gradients" (Re-calibration
# status), ratcheting UP if the HBM win is real.
HIST_QUANT_AB_FLOOR = 1.0
# Cross-platform training parity (experiments/chip_parity.py): 2-4/155
# split flips from MXU f32 summation order straddling bf16 gain-rounding
# ties; quality-equivalent. Wider divergence means a real kernel bug.
PARITY_MIN_AGREEMENT = 0.95
PARITY_MAX_AUC_DELTA = 0.01
# Serving tier (ISSUE 8 acceptance, enforced on EVERY platform — the
# queueing/coalescing behavior under test is host code): a single-row
# request's p99 through the admission-batched engine must beat a COLD
# api.predict call on the same model by >= 10x (the cold call pays
# first-call compile + CompiledEnsemble build + upload — the exact path
# `cli serve` exists to replace; measured cold/p99 ratios sit in the
# hundreds-to-thousands, so 10x is a loud-failure floor, not a band),
# and the open-loop arms must show real coalescing (>= 8 requests in
# one dispatch at the saturating QPS point — below that the batcher has
# degenerated to per-request dispatch). The deterministic >= 8 witness
# also lives in tests/test_serve.py behind a thread barrier; this floor
# keeps it measured under open-loop load.
SERVE_COLD_OVER_P99_FLOOR = 10.0
SERVE_COALESCE_MIN = 8
# Quantized LUT paired ratio (chip only): the int8 path cuts per-request
# HBM row traffic 4x, so per-batch traversal should clear the f32 arm
# by >= 1.5x at the bench shape; parity (~1.0) means the quantized
# dispatch silently fell back. If the measured ratio lands between 1.0
# and 1.5 on a real chip, record the roofline explanation in
# docs/PERF.md "Serving latency" instead of shipping a lower floor.
PREDICT_LUT_AB_FLOOR = 1.5
# int4-vs-int8 paired ratio (chip only; ISSUE 12): the bit-packed tier
# halves the int8 tier's threshold/leaf table bytes again, but tables
# are the SMALL term at the 4M-row batch shape (rows dominate and both
# arms stream identical uint8 rows), so the expected batch-shape edge
# is modest — the tier's real win is the resident single-row footprint.
# 1.1 says "the pack must not LOSE to int8 and should show its table
# saving"; parity below 1.0 means the in-VPU unpack is costing more
# than the bytes it saves. ENCODED-BUT-UNWITNESSED per the docs/PERF.md
# post-r05 re-calibration convention: no chip image has run since this
# floor landed — the first chip bench must re-calibrate it from the
# measured band before trusting a failure.
PREDICT_LUT4_AB_FLOOR = 1.1
# Express lane (every platform — host behavior): at an EMPTY queue a
# single-row request through the lane must beat the coalesced path's
# admission-window floor (its p99 sits BELOW max_wait_ms, where the
# lane-off path's p50 sits ABOVE it — measured CPU: 2.0 ms vs 23.5 ms
# at the 20 ms bench window, gain ~12x); and under SATURATION the lane
# must be invisible (closed), so express-on p99 may not exceed
# express-off p99 by more than the noise slack.
SERVE_EXPRESS_SAT_SLACK = 1.5


def _parity_check() -> dict:
    """5-tree real-chip vs CPU-oracle training parity (the round-3
    measurement, re-witnessed per run): split-field agreement and
    held-out AUC delta."""
    from ddt_tpu import api
    from ddt_tpu.data import datasets
    from ddt_tpu.data.quantizer import quantize
    from ddt_tpu.utils.metrics import auc

    X, y = datasets.synthetic_binary(24_000, n_features=12, seed=31)
    Xt, yt, Xv, yv = X[:20_000], y[:20_000], X[20_000:], y[20_000:]
    Xb, mapper = quantize(Xt, n_bins=255, seed=31)
    Xvb = mapper.transform(Xv)
    kw = dict(n_trees=5, max_depth=4, n_bins=255, binned=True,
              log_every=10**9)
    tpu = api.train(Xb, yt, backend="tpu", **kw).ensemble
    cpu = api.train(Xb, yt, backend="cpu", **kw).ensemble
    agree = float((tpu.feature == cpu.feature).mean())
    d_auc = abs(auc(yv, tpu.predict_raw(Xvb, binned=True))
                - auc(yv, cpu.predict_raw(Xvb, binned=True)))
    return {"split_agreement": round(agree, 4),
            "auc_delta": round(float(d_auc), 5)}


def main() -> None:
    from ddt_tpu.backends.tpu import enable_persistent_compile_cache
    from ddt_tpu.bench import bench_histogram, bench_histogram_ab, \
        bench_histogram_one_dispatch, bench_predict_both, bench_train

    enable_persistent_compile_cache()

    import jax

    on_tpu = jax.default_backend() == "tpu"
    rows, features, bins, n_nodes = 1_000_000, 28, 255, 32

    # Metric #1: histogram throughput — 255-bin headline and 64-bin
    # opt-in, interleaved so the ratio survives the tunnel's noise bands.
    ab = bench_histogram_ab(
        bins_a=bins, bins_b=64, rows=rows, features=features,
        n_nodes=n_nodes, iters=10, reps=10,
    )
    value = ab["mrows_a"]

    # Band-stable one-dispatch twin of the headline (floored; kept
    # alongside the dispatch-loop headline for artifact comparability).
    od = bench_histogram_one_dispatch(
        rows=rows, features=features, bins=bins, n_nodes=n_nodes,
        iters=10, reps=8,
    )

    # CPU reference baseline: fewer rows (row-linear shape), normalised.
    cpu = bench_histogram(
        backend="cpu", rows=200_000, features=features, bins=bins,
        n_nodes=n_nodes, iters=2, reps=8,
    )
    baseline = cpu["mrows_per_sec_per_chip"]

    # Metric #2: the 100-tree end-to-end build (fused dispatch).
    depth = 6
    tr = bench_train(backend="tpu", rows=rows, features=features,
                     bins=bins, trees=100, depth=depth)
    # Effective histogram work per tree: with the sibling-subtraction
    # trick active (hist_subtraction='auto' resolves on-chip), every
    # level past the root builds only LEFT children — half a build — so
    # the self-consistency ratio must count 1 + (depth-1)/2 effective
    # levels, not depth, or the trick itself would read as a >1.4x
    # "work miscount" (E2E_CONSISTENCY_RATIO calibration).
    from ddt_tpu.ops.grow import resolve_hist_subtraction

    lvl_eff = (1 + (depth - 1) / 2
               if resolve_hist_subtraction("auto") else depth)
    implied = rows * lvl_eff * tr["trees"] / tr["wallclock_s"] / 1e6

    # Fused-round A/B (subtraction ON vs OFF, paired protocol) with the
    # roofline stamp for the ON arm. Real chip only: off-TPU the level
    # loop's pallas kernels run the interpreter.
    fab = None
    if on_tpu:
        from ddt_tpu.bench import bench_hist_fused_ab

        fab = bench_hist_fused_ab(rows=rows, features=features, bins=bins,
                                  depth=depth)

    # Split-comms paired A/B (ISSUE 10): allreduce vs reduce_scatter
    # split finding on the pod mesh. Real chip only in the headline run
    # (the CPU multi-device twin lives in tier-1 as
    # tests/test_comms.py::test_bench_hist_comms_ab_smoke); the
    # deterministic payload ratio is stamped either way via the counter
    # model.
    cab = None
    if on_tpu and len(jax.devices()) > 1:
        from ddt_tpu.bench import bench_hist_comms_ab

        cab = bench_hist_comms_ab(rows=rows, features=features, bins=bins,
                                  depth=depth)

    # 2D-mesh paired A/B (ISSUE 11): 1D row mesh vs (rows x features)
    # at a WIDE shape (F >= 1k, where feature replication hurts) on the
    # same device count. Real chip only in the headline run (the CPU
    # multi-device twin lives in tier-1 as
    # tests/test_mesh2d.py::test_bench_hist_2d_smoke); the payload
    # ratio is deterministic counter math either way.
    h2d = None
    if on_tpu and len(jax.devices()) >= 2:
        from ddt_tpu.bench import bench_hist_2d

        h2d = bench_hist_2d()

    # Quantized-gradient paired A/B (ISSUE 14): f32 vs int8 whole-tree
    # fused level loop on one chip. Real chip only in the headline run
    # (the CPU twin lives in tier-1 as tests/test_grad_quant.py::
    # test_bench_hist_quant_ab_smoke); the g/h HBM-stream payload ratio
    # is deterministic byte math and stamped on every platform.
    qab = None
    if on_tpu:
        from ddt_tpu.bench import bench_hist_quant_ab

        qab = bench_hist_quant_ab(rows=rows, features=features, bins=bins,
                                  depth=depth)
    from ddt_tpu.telemetry.counters import grad_stream_bytes

    quant_payload_ratio = round(
        grad_stream_bytes(rows, depth, "f32")
        / grad_stream_bytes(rows, depth, "int8"), 3)

    # Scoring config: device-resident (floored) + total (context) +
    # compute-only (floored, band-stable), one shared
    # dataset/ensemble/warm-up.
    pr, pr_total, pr_comp = bench_predict_both(rows=10_000_000, trees=1000,
                                               depth=6)

    # Pallas traversal kernel vs one-hot A/B (paired, order-alternating,
    # median-of-reps — the histogram protocol); exactness asserted inside.
    # Real chip only: the interpret-mode pallas arm takes minutes off-TPU.
    pab = None
    if on_tpu:
        from ddt_tpu.bench import bench_predict_pallas_ab

        pab = bench_predict_pallas_ab(rows=4_000_000, trees=1000, depth=6)

    # Serving-tier latency-under-load arm (ISSUE 8): admission-batched
    # single-row requests vs a cold api.predict on the same model. The
    # behavior under test (queueing, coalescing, pre-traced buckets) is
    # host code, so the arm runs on EVERY platform — the CPU numbers
    # are the acceptance evidence, the chip numbers the serving SLO.
    from ddt_tpu.bench import bench_serve_latency

    sv = bench_serve_latency()

    # Quantized-vs-f32 paired A/B (TreeLUT int8 fast path). Real chip
    # only: off-TPU both Pallas arms run the interpreter.
    lab = None
    if on_tpu:
        from ddt_tpu.bench import bench_predict_lut_ab

        lab = bench_predict_lut_ab(rows=4_000_000, trees=1000, depth=6)

    # int4 bit-packed tier + express lane (ISSUE 12): the paired
    # int8-vs-int4 arm is chip-gated like the other Pallas A/Bs
    # (ab=on_tpu), but the express-lane two-regime arm is host code and
    # runs — and is FLOORED — on every platform.
    from ddt_tpu.bench import bench_predict_lut4_ab

    l4 = bench_predict_lut4_ab(ab=on_tpu)

    parity = _parity_check() if on_tpu else {}

    # Honest-baseline context (round-1 verdict): record what the CPU
    # comparator actually was. This box exposes a single CPU core, so the
    # OpenMP-built native kernel runs effectively single-threaded; on a
    # many-core host the all-core native number is the comparator to
    # quote.
    rec = {
        "metric": "higgs1m_histogram_throughput",
        # Provenance stamp (benchwatch satellite): a unique id per bench
        # RUN, the artifact schema version, and the git rev the numbers
        # were measured at — history keying that survives file renames.
        "run_id": uuid.uuid4().hex[:12],
        "bench_schema": BENCH_SCHEMA,
        "git_rev": _git_rev(),
        # Chaos stamp (docs/ROBUSTNESS.md): True when a fault-injection
        # plan was active during this bench — benchwatch excludes such
        # artifacts from bench history (recovery tests, not perf data).
        "injected_faults": _injected_faults_active(),
        "value": round(value, 2),
        "unit": "Mrows/s/chip",
        "vs_baseline": round(value / baseline, 2),
        "baseline_mrows_per_sec": round(baseline, 2),
        "baseline_impl": cpu["impl"],
        "baseline_cpu_count": os.cpu_count(),
        "baseline_omp_threads": _omp_threads(),
        "floor_mrows_per_sec": TPU_FLOOR_MROWS if on_tpu else None,
        "hist_one_dispatch_mrows_per_sec":
            round(od["mrows_per_sec_per_chip"], 2),
        "hist_one_dispatch_mrows_per_sec_min":
            round(od["mrows_per_sec_per_chip_min"], 2),
        "hist_one_dispatch_floor_mrows_per_sec":
            TPU_ONE_DISPATCH_FLOOR_MROWS if on_tpu else None,
        "value_64bin_optin": round(ab["mrows_b"], 2),
        "ab_ratio_64bin": round(ab["ratio_b_over_a"], 3),
        "e2e_train_s": round(tr["wallclock_s"], 2),
        "e2e_ms_per_tree": round(1000 * tr["wallclock_s"] / tr["trees"], 1),
        "e2e_ceiling_s": E2E_CEILING_S if on_tpu else None,
        "e2e_implied_hist_mrows": round(implied, 2),
        "e2e_effective_levels": lvl_eff,
        "e2e_consistency_ratio":
            round(implied / od["mrows_per_sec_per_chip"], 3),
        "e2e_consistency_ratio_dispatch_loop": round(implied / value, 3),
        "hist_fused_mrows_per_sec":
            round(fab["mrows_on"], 2) if fab else None,
        "hist_fused_ab_ratio":
            round(fab["ratio_on_over_off"], 3) if fab else None,
        "hist_fused_roofline_flops_util":
            fab.get("hist_fused_roofline_flops_util") if fab else None,
        "hist_fused_roofline_hbm_util":
            fab.get("hist_fused_roofline_hbm_util") if fab else None,
        # Split-comms A/B (ISSUE 10): paired wallclock ratio (chip pod
        # mesh only) + the deterministic per-tree payload ratio from the
        # corrected hist_allreduce_bytes model — >= 2x is the acceptance
        # bar, witnessed in-process by tests/test_comms.py.
        "hist_comms_ab_ratio":
            round(cab["ratio_allreduce_over_rs"], 3) if cab else None,
        "hist_comms_payload_ratio":
            cab["payload_ratio"] if cab else None,
        "hist_comms_rs_mrows_per_sec":
            round(cab["mrows_rs"], 2) if cab else None,
        # 2D-mesh A/B (ISSUE 11): paired wallclock ratio (chip only) +
        # the deterministic payload ratio from the second-axis-aware
        # hist_allreduce_bytes model — per-device slab <= 1/(Pr·Pf) of
        # the replicated-feature baseline, witnessed in-process by
        # tests/test_mesh2d.py.
        "hist_2d_ab_ratio":
            round(h2d["ratio_1d_over_2d"], 3) if h2d else None,
        "hist_2d_payload_ratio":
            h2d["payload_ratio"] if h2d else None,
        "hist_2d_mrows_per_sec":
            round(h2d["mrows_2d"], 2) if h2d else None,
        # Quantized-gradient A/B (ISSUE 14): paired wallclock ratio
        # (chip only) + the deterministic g/h HBM-stream payload ratio
        # (grad_stream_bytes byte model — 4x for int8), witnessed
        # in-process by tests/test_grad_quant.py's counter tests.
        "hist_quant_ab_ratio":
            round(qab["ratio_f32_over_quant"], 3) if qab else None,
        "hist_quant_payload_ratio":
            qab["payload_ratio"] if qab else quant_payload_ratio,
        "hist_quant_mrows_per_sec":
            round(qab["mrows_quant"], 2) if qab else None,
        "predict_mrows_per_sec": round(pr["mrows_per_sec"], 2),
        "predict_total_s": round(pr_total["wallclock_s"], 2),
        "predict_compute_mrows_per_sec": round(pr_comp["mrows_per_sec"], 2),
        "predict_impl": pr["impl"],
        "predict_floor_mrows_per_sec":
            PREDICT_FLOOR_MROWS if on_tpu else None,
        "predict_compute_floor_mrows_per_sec":
            PREDICT_COMPUTE_FLOOR_MROWS if on_tpu else None,
        "predict_pallas_mrows_per_sec":
            round(pab["pallas_mrows_per_sec"], 2) if pab else None,
        "predict_onehot_mrows_per_sec":
            round(pab["onehot_mrows_per_sec"], 2) if pab else None,
        "predict_pallas_ab_ratio":
            round(pab["ratio_pallas_over_onehot"], 3) if pab else None,
        # Serving tier (ISSUE 8): admission-batched single-row latency
        # (headline = the middle open-loop QPS point), the cold-call
        # comparator it replaces, and coalescing evidence. Latency
        # metrics band LOWER-is-better in benchwatch; the cold/p99
        # ratio (>= 10x is the acceptance bar) bands higher.
        "serve_p50_ms": round(sv["serve_p50_ms"], 4),
        "serve_p99_ms": round(sv["serve_p99_ms"], 4),
        "serve_p999_ms": round(sv["serve_p999_ms"], 4),
        "serve_qps": sv["serve_qps"],
        "serve_coalesce_mean": sv["serve_coalesce_mean"],
        "serve_coalesce_max": sv["serve_coalesce_max"],
        "serve_cold_predict_ms": sv["cold_predict_ms"],
        "serve_cold_over_p99": sv["serve_cold_over_p99"],
        # Quantized LUT A/B (chip only): paired speedup + the witnessed
        # error-vs-bound pair (the bound is the tables' computed
        # contract; err must sit under it or the arm itself asserts).
        "predict_lut_mrows_per_sec":
            round(lab["lut_mrows_per_sec"], 2) if lab else None,
        "predict_lut_ab_ratio":
            round(lab["ratio_lut_over_f32"], 3) if lab else None,
        "predict_lut_max_abs_err":
            lab["lut_max_abs_err"] if lab else None,
        # int4 bit-packed tier (chip only) + express lane (every
        # platform): the int8-vs-int4 paired ratio with its witnessed
        # error/bound pair, and the two-regime single-row latencies —
        # empty-queue express p99 bands lower-is-better next to the
        # other serve latencies; express_gain (coalesced/express at an
        # empty queue) bands higher.
        "predict_lut4_mrows_per_sec":
            round(l4["lut4_mrows_per_sec"], 2)
            if "lut4_mrows_per_sec" in l4 else None,
        "predict_lut4_ab_ratio":
            round(l4["ratio_int4_over_int8"], 3)
            if "ratio_int4_over_int8" in l4 else None,
        "predict_lut4_max_abs_err":
            l4.get("lut4_max_abs_err"),
        "serve_express_empty_p99_ms": l4["express_empty_p99_ms"],
        "serve_express_gain": l4["express_gain"],
        "serve_express_saturated_p99_ms": l4["express_saturated_p99_ms"],
        "serve_coalesced_saturated_p99_ms":
            l4["coalesced_saturated_p99_ms"],
        # Roofline utilization stamps (device-truth cost observatory):
        # achieved/peak fractions from XLA's own cost model at the
        # measured wallclocks (telemetry/costmodel.py; benchwatch bands
        # the flops/predict fractions higher-is-better — a dispatch
        # regression that hides inside wallclock drift still collapses
        # utilization). hist_roofline_hbm_util is recorded as CONTEXT
        # only since schema v2: the VMEM-streaming kernel lowers
        # bytes-accessed by design, so a drop vs pre-rewrite history is
        # the campaign landing, not a regression.
        "hist_roofline_flops_util": ab.get("hist_roofline_flops_util"),
        "hist_roofline_hbm_util": ab.get("hist_roofline_hbm_util"),
        "predict_roofline_flops_util":
            pr_comp.get("predict_roofline_flops_util"),
        "predict_roofline_hbm_util":
            pr_comp.get("predict_roofline_hbm_util"),
        **parity,
    }
    print(json.dumps(rec))

    # Serving floors apply on every platform (host-code behavior).
    serve_fails = []
    if sv["serve_cold_over_p99"] is not None \
            and sv["serve_cold_over_p99"] < SERVE_COLD_OVER_P99_FLOOR:
        serve_fails.append(
            f"serve p99 {sv['serve_p99_ms']:.2f} ms is only "
            f"{sv['serve_cold_over_p99']:.1f}x under the cold predict "
            f"call ({sv['cold_predict_ms']:.1f} ms) — floor "
            f"{SERVE_COLD_OVER_P99_FLOOR}x (admission batching or the "
            "pre-traced bucket path regressed; docs/SERVING.md)")
    if sv["serve_coalesce_max"] < SERVE_COALESCE_MIN:
        serve_fails.append(
            f"serve coalesce width max {sv['serve_coalesce_max']} < "
            f"{SERVE_COALESCE_MIN} across open-loop arms — the batcher "
            "has degenerated to per-request dispatch (docs/SERVING.md)")
    # Express lane, both regimes (ISSUE 12 acceptance; host behavior,
    # enforced on every platform like the serving floors above).
    if l4["express_empty_p99_ms"] >= l4["express_max_wait_ms"]:
        serve_fails.append(
            f"express-lane empty-queue p99 "
            f"{l4['express_empty_p99_ms']:.2f} ms is not below the "
            f"coalesced path's {l4['express_max_wait_ms']:.0f} ms "
            "admission-window floor — the lane is not bypassing the "
            "window (docs/SERVING.md 'Express lane')")
    if l4["express_saturated_p99_ms"] > SERVE_EXPRESS_SAT_SLACK * max(
            l4["coalesced_saturated_p99_ms"], 1e-9):
        serve_fails.append(
            f"express-on saturated p99 "
            f"{l4['express_saturated_p99_ms']:.2f} ms exceeds "
            f"{SERVE_EXPRESS_SAT_SLACK}x the express-off p99 "
            f"({l4['coalesced_saturated_p99_ms']:.2f} ms) — the lane "
            "is leaking into the loaded regime instead of closing "
            "(docs/SERVING.md 'Express lane')")

    if not on_tpu:
        if serve_fails:
            raise SystemExit("PERF REGRESSION:\n- "
                             + "\n- ".join(serve_fails))
        return
    fails = serve_fails
    if value < TPU_FLOOR_MROWS:
        fails.append(
            f"histogram {value:.1f} Mrows/s/chip < {TPU_FLOOR_MROWS} floor "
            "(wrong-path dispatch or kernel regression — docs/PERF.md)")
    od_v = od["mrows_per_sec_per_chip"]
    if od_v < TPU_ONE_DISPATCH_FLOOR_MROWS:
        fails.append(
            f"one-dispatch histogram {od_v:.1f} Mrows/s/chip < "
            f"{TPU_ONE_DISPATCH_FLOOR_MROWS} floor (3-8% within-window "
            "spread makes this far more likely a kernel regression than "
            "drift luck; experiments/hist_dispatch_ab.py, docs/PERF.md "
            "drift analysis)")
    if tr["wallclock_s"] > E2E_CEILING_S:
        fails.append(
            f"e2e train {tr['wallclock_s']:.1f}s > {E2E_CEILING_S}s ceiling "
            "(fused-dispatch regression; 11-23s expected across bands)")
    lo, hi = E2E_CONSISTENCY_RATIO
    if not (lo <= implied / od_v <= hi):
        fails.append(
            f"e2e-implied histogram throughput {implied:.1f} Mrows/s is "
            f"{implied / od_v:.2f}x the band-stable one-dispatch kernel "
            f"({od_v:.1f}) — outside [{lo}, {hi}] (in-band fused-path "
            "regression or work miscount; calibration comment at "
            "E2E_CONSISTENCY_RATIO)")
    if pr["mrows_per_sec"] < PREDICT_FLOOR_MROWS:
        fails.append(
            f"resident predict {pr['mrows_per_sec']:.2f} Mrows/s < "
            f"{PREDICT_FLOOR_MROWS} floor (overlapped-fetch or "
            "descent-path regression)")
    if pr_comp["mrows_per_sec"] < PREDICT_COMPUTE_FLOOR_MROWS:
        fails.append(
            f"compute-only predict {pr_comp['mrows_per_sec']:.2f} Mrows/s "
            f"< {PREDICT_COMPUTE_FLOOR_MROWS} floor (Pallas traversal "
            "kernel regression or silent one-hot fallback — "
            f"impl={pr['impl']}; docs/PERF.md Prediction)")
    if pab is not None \
            and pab["ratio_pallas_over_onehot"] < PREDICT_PALLAS_AB_FLOOR:
        fails.append(
            f"pallas/one-hot paired ratio "
            f"{pab['ratio_pallas_over_onehot']:.3f} < "
            f"{PREDICT_PALLAS_AB_FLOOR} (the VMEM traversal kernel lost "
            "its edge over the HBM-bound one-hot path; docs/PERF.md "
            "Prediction)")
    if ab["ratio_b_over_a"] < AB64_RATIO_FLOOR:
        fails.append(
            f"64-bin paired ratio {ab['ratio_b_over_a']:.3f} < "
            f"{AB64_RATIO_FLOOR} (transposed-kernel dispatch lost? "
            "measured 1.13-1.22)")
    if fab is not None and fab["ratio_on_over_off"] < HIST_FUSED_AB_FLOOR:
        fails.append(
            f"fused-round subtraction paired ratio "
            f"{fab['ratio_on_over_off']:.3f} < {HIST_FUSED_AB_FLOOR} "
            "(the sibling-subtraction trick fell out of the level loop — "
            "ops/grow.level_histograms; docs/PERF.md Training kernel)")
    if cab is not None \
            and cab["ratio_allreduce_over_rs"] < HIST_COMMS_AB_FLOOR:
        fails.append(
            f"split-comms paired ratio "
            f"{cab['ratio_allreduce_over_rs']:.3f} < {HIST_COMMS_AB_FLOOR} "
            "(reduce-scatter split finding costs wallclock on a real "
            "fabric — parallel/comms.py; docs/PERF.md Histogram comms)")
    if h2d is not None and h2d["ratio_1d_over_2d"] < HIST_2D_AB_FLOOR:
        fails.append(
            f"2D-mesh paired ratio {h2d['ratio_1d_over_2d']:.3f} < "
            f"{HIST_2D_AB_FLOOR} (feature sharding costs wallclock at "
            "the wide shape — parallel/mesh.py SpecLayout; docs/PERF.md "
            "'2D sharding')")
    if qab is not None \
            and qab["ratio_f32_over_quant"] < HIST_QUANT_AB_FLOOR:
        fails.append(
            f"quantized-gradient paired ratio "
            f"{qab['ratio_f32_over_quant']:.3f} < {HIST_QUANT_AB_FLOOR} "
            "(the integer histogram path costs wallclock on chip — the "
            "s8 MXU dot or the narrow g/h stream degraded; ops/grad.py "
            "+ ops/hist_pallas.py; floor is encoded-but-unwitnessed, "
            "re-calibrate per docs/PERF.md 'Quantized gradients' before "
            "trusting a failure)")
    if lab is not None \
            and lab["ratio_lut_over_f32"] < PREDICT_LUT_AB_FLOOR:
        fails.append(
            f"quantized LUT paired ratio "
            f"{lab['ratio_lut_over_f32']:.3f} < {PREDICT_LUT_AB_FLOOR} "
            "(the int8 path lost its HBM-traffic edge or silently fell "
            "back to f32 — ops/predict_lut.py; if the ratio is real and "
            "between 1.0 and 1.5, record the roofline explanation in "
            "docs/PERF.md 'Serving latency')")
    if "ratio_int4_over_int8" in l4 \
            and l4["ratio_int4_over_int8"] < PREDICT_LUT4_AB_FLOOR:
        fails.append(
            f"int4-vs-int8 paired ratio "
            f"{l4['ratio_int4_over_int8']:.3f} < {PREDICT_LUT4_AB_FLOOR} "
            "(the bit-packed tier's in-VPU unpack is costing more than "
            "the table bytes it saves, or the lut4 dispatch silently "
            "degraded — ops/predict_lut.py; floor is encoded-but-"
            "unwitnessed, re-calibrate per docs/PERF.md 'Serving "
            "latency' before trusting a failure)")
    if parity and (parity["split_agreement"] < PARITY_MIN_AGREEMENT
                   or parity["auc_delta"] > PARITY_MAX_AUC_DELTA):
        fails.append(
            f"chip-vs-oracle parity {parity} beyond the measured seam "
            "(2-4/155 flips, |dAUC|<0.01 — experiments/chip_parity.py)")
    if fails:
        raise SystemExit("PERF REGRESSION:\n- " + "\n- ".join(fails))


def _omp_threads() -> int:
    """Effective OpenMP thread count: first entry of OMP_NUM_THREADS (the
    spec allows a comma-separated per-nesting-level list, and empty values
    occur in the wild), falling back to the core count."""
    raw = os.environ.get("OMP_NUM_THREADS", "").split(",")[0].strip()
    try:
        n = int(raw)
        if n > 0:
            return n
    except ValueError:
        pass
    return os.cpu_count() or 1


if __name__ == "__main__":
    main()
