"""Headline benchmark: HistogramBuilder throughput vs the CPU reference.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric (BASELINE.json): Higgs-1M-shaped histogram build, M-rows/sec/chip —
1M rows x 28 features x 255 bins x 32 nodes (the widest level of the depth-6
config, which dominates training time). vs_baseline is the ratio to the CPU
reference kernel's throughput measured on this same machine (BASELINE.md: the
reference published no numbers; its CPU-reference comparison is the defined
baseline, north-star target >= 5x).

Runs on whatever platform jax defaults to (the real TPU chip under the
driver). The CPU reference uses the native C++ kernel when built, else NumPy
np.add.at — the stronger (faster) of the two is the honest baseline.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Perf-regression floor (SURVEY.md §4, round-2 verdict weak #2): the
# shipped Pallas kernel measures 45-64 Mrows/s/chip on the v5e across
# tunnel noise bands; a silent regression (e.g. a Mosaic toolchain change
# re-breaking the int32 compare domain, or a dispatch falling back to the
# ~26 Mrows/s matmul path) must FAIL the bench, not quietly ship a number.
# 40 sits below every observed noise band but above every known-bad mode.
TPU_FLOOR_MROWS = 40.0


def main() -> None:
    from ddt_tpu.backends.tpu import enable_persistent_compile_cache
    from ddt_tpu.bench import bench_histogram

    enable_persistent_compile_cache()

    rows, features, bins, n_nodes = 1_000_000, 28, 255, 32

    tpu = bench_histogram(
        backend="tpu", rows=rows, features=features, bins=bins,
        n_nodes=n_nodes, iters=15, reps=8,
    )
    # The 64-bin opt-in contract (transposed kernel, docs/PERF.md round-3
    # addendum) — secondary evidence field, not the headline metric.
    tpu64 = bench_histogram(
        backend="tpu", rows=rows, features=features, bins=64,
        n_nodes=n_nodes, iters=10, reps=4,
    )

    # CPU reference baseline: fewer rows (np.add.at is slow; throughput is
    # row-linear at this shape), normalised to M-rows/sec.
    cpu = bench_histogram(
        backend="cpu", rows=200_000, features=features, bins=bins,
        n_nodes=n_nodes, iters=2, reps=8,
    )

    value = tpu["mrows_per_sec_per_chip"]
    baseline = cpu["mrows_per_sec_per_chip"]
    # Honest-baseline context (round-1 verdict, Weak #6): record what the
    # CPU comparator actually was. This box exposes a single CPU core
    # (os.cpu_count() below), so the OpenMP-built native kernel runs
    # effectively single-threaded; on a many-core host the all-core native
    # number is the comparator to quote.
    import jax

    on_tpu = jax.default_backend() == "tpu"
    print(json.dumps({
        "metric": "higgs1m_histogram_throughput",
        "value": round(value, 2),
        "unit": "Mrows/s/chip",
        "vs_baseline": round(value / baseline, 2),
        "baseline_mrows_per_sec": round(baseline, 2),
        "baseline_impl": cpu["impl"],
        "baseline_cpu_count": os.cpu_count(),
        "baseline_omp_threads": _omp_threads(),
        "floor_mrows_per_sec": TPU_FLOOR_MROWS if on_tpu else None,
        "value_64bin_optin": round(tpu64["mrows_per_sec_per_chip"], 2),
    }))
    if on_tpu and value < TPU_FLOOR_MROWS:
        raise SystemExit(
            f"PERF REGRESSION: {value:.1f} Mrows/s/chip is below the "
            f"{TPU_FLOOR_MROWS} floor (docs/PERF.md; previously measured "
            "45-64 across tunnel noise). A wrong-path dispatch or kernel "
            "regression shipped — investigate before trusting this build."
        )


def _omp_threads() -> int:
    """Effective OpenMP thread count: first entry of OMP_NUM_THREADS (the
    spec allows a comma-separated per-nesting-level list, and empty values
    occur in the wild), falling back to the core count."""
    raw = os.environ.get("OMP_NUM_THREADS", "").split(",")[0].strip()
    try:
        n = int(raw)
        if n > 0:
            return n
    except ValueError:
        pass
    return os.cpu_count() or 1


if __name__ == "__main__":
    main()
