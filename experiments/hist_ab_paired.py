"""Paired-ratio A/B for the 255-bin kernel forms (sweep 11 epilogue).

Three interleaved min-of-reps runs of sweep 11 gave CONTRADICTORY
winners (row-major 42.6/42.6/52.4 vs transposed-Bp256 49.6/50.0/39.8
Mrows/s): each arm sticks to a ~40 or ~50 Mrows/s band for a whole
~30 s timing window, so even interleaved minimums compare across bands,
not kernels. This harness measures the PER-REP PAIRED RATIO instead —
arm order alternates every rep (A,B / B,A), reps spread over ~4-6
minutes sample many band states, and the median of per-rep ratios is
robust to any band structure that affects both arms of a pair. The
protocol scaffolding lives in experiments/paired_protocol.py.

Run: python -u experiments/hist_ab_paired.py
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from experiments.hist_sweep11 import F, N, R, build  # noqa: E402
from experiments.paired_protocol import paired_ab  # noqa: E402
from ddt_tpu.utils.device import device_sync  # noqa: E402

REPS, ITERS = 40, 8


def main() -> None:
    print(f"platform={jax.default_backend()}  {R}x{F}, N={N}, 255 bins",
          flush=True)
    rng = np.random.default_rng(0)
    Xb = rng.integers(0, 255, (R, F), dtype=np.uint8)
    Xi = jax.device_put(Xb.astype(np.int32))
    Xt = jax.device_put(np.ascontiguousarray(Xb.T).astype(np.int32))
    g = jax.device_put(rng.standard_normal(R).astype(np.float32))
    h = jax.device_put(rng.random(R).astype(np.float32))
    ni = jax.device_put(rng.integers(0, N, R).astype(np.int32))

    arm_a = ("control", 512)
    arm_b = ("prologue_t", 2048)
    for form, tile in (arm_a, arm_b):
        device_sync(build(Xi, Xt, g, h, ni, form, tile))   # compile

    def bout(form, tile):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = build(Xi, Xt, g, h, ni, form, tile)
        device_sync(out)
        return (time.perf_counter() - t0) / ITERS

    paired_ab(
        functools.partial(bout, *arm_a), functools.partial(bout, *arm_b),
        name_a="control", name_b="T-form", reps=REPS,
        scale=R / 1e6, unit="Mrows/s",
    )


if __name__ == "__main__":
    main()
