"""Sweep round 7: bf16-domain one-hot compare.

If the VPU processes 2-packed bf16 elementwise ops at double rate, doing the
bin compare+select in bf16 (x and iota both bf16; bins <= 255 are exact)
halves the dominant VPU cost. sweep5's attempt died on a bf16
broadcasted_iota VerificationError — here the iota is generated as int32 and
converted ONCE per tile, and x arrives as bf16 from the XLA prologue.

Also: int16-domain compare (x int16, iota int16) as a second packing probe.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import _bins_pad, build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B, N = 1_000_000, 28, 255, 32
ITERS = 15
REPS = 4


def _kernel(xb_ref, a_ref, out_ref, *, n_feat, bins_pad, stages, cmp_dtype):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                                  # [T, F] in cmp domain
    t = x.shape[0]
    a = a_ref[:]
    bin_iota = jax.lax.broadcasted_iota(
        jnp.int32, (t, bins_pad), 1).astype(cmp_dtype)
    fs = -(-n_feat // stages)
    for s in range(stages):
        f0, f1 = s * fs, min((s + 1) * fs, n_feat)
        slabs = [(x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
                 for f in range(f0, f1)]
        oh = jnp.concatenate(slabs, axis=1) if len(slabs) > 1 else slabs[0]
        out_ref[:, f0 * bins_pad:f1 * bins_pad] += jax.lax.dot_general(
            a, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_nodes", "tile_r", "stages",
                                             "cmp"))
def hist_cmp(Xb, g, h, ni, n_nodes, tile_r, stages, cmp="bf16"):
    Rr, Fq = Xb.shape
    bins_pad = _bins_pad(B)
    cmp_dtype = {"bf16": jnp.bfloat16, "i16": jnp.int16,
                 "i32": jnp.int32}[cmp]
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0).astype(jnp.float32)
    hz = jnp.where(active, h, 0.0).astype(jnp.float32)
    noh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)
    A = jnp.concatenate([noh * gz[:, None], noh * hz[:, None]],
                        axis=1).astype(jnp.bfloat16)
    Xi = Xb.astype(cmp_dtype)
    n_tiles = -(-Rr // tile_r)
    pad = n_tiles * tile_r - Rr
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A = jnp.pad(A, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_kernel, n_feat=Fq, bins_pad=bins_pad,
                          stages=stages, cmp_dtype=cmp_dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_r, Fq), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * n_nodes), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, Fq * bins_pad),
                               lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, Fq * bins_pad),
                                       jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * n_nodes * Fq * bins_pad * n_tiles * tile_r,
            bytes_accessed=Rr * Fq * 4 + Rr * 4 * n_nodes
            + 2 * n_nodes * Fq * bins_pad * 4,
            transcendentals=0),
    )(Xi, A)
    out = out.reshape(2, n_nodes, Fq, bins_pad)[..., :B]
    return out.transpose(1, 2, 3, 0)


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni_np = rng.integers(0, N, size=R).astype(np.int32)
    ni_np[:1000] = -1
    ni = jnp.asarray(ni_np)

    ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=512)
    device_sync(ref)

    cands = [("v0 i32 lib    tile_r=512",
              lambda: build_histograms_pallas(Xb, g, h, ni, N, B,
                                              tile_r=512))]
    for tr in (512, 768):
        for cmp in ("bf16", "i16", "i32"):
            for st in (1, 4):
                cands.append((
                    f"cmp={cmp:4s} st{st} tile_r={tr}",
                    lambda tr=tr, cmp=cmp, st=st: hist_cmp(
                        Xb, g, h, ni, N, tr, st, cmp)))

    best = {}
    live = []
    for name, fn in cands:
        try:
            out = fn()
            device_sync(out)
            if not bool(jnp.allclose(out, ref, rtol=2e-2, atol=2e-2)):
                print(f"{name:28s} WRONG RESULT")
                continue
            live.append((name, fn))
            best[name] = np.inf
        except Exception as e:  # noqa: BLE001
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:90]}")

    for _ in range(REPS):
        for name, fn in live:
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = fn()
            device_sync(out)
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
    for name, _ in live:
        dt = best[name]
        print(f"{name:28s} {dt*1e3:8.2f} ms  {R/dt/1e6:7.1f} Mrows/s")


if __name__ == "__main__":
    main()
