"""Sweep round 6: fused-prologue kernel (vG).

v0 pays an XLA prologue per call: cast Xb to int32 (112 MB HBM write),
build A = node-one-hot x (g|h) in XLA ([R,64] bf16, ~128 MB traffic), pad.
vG reads the uint8 bins directly (28 MB) plus a packed [R,4] f32 side-car
(g, h, node, unused) and builds A's tile in-kernel (ops over 64 lanes —
negligible next to the 7168-lane one-hot). Variants: x as int8 vs int32
input; stage count; tile_r.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import _bins_pad, build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B, N = 1_000_000, 28, 255, 32
ITERS = 20
REPS = 4


def _kernel_vG(xb_ref, ghn_ref, out_ref, *, n_feat, bins_pad, n_nodes,
               stages):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:].astype(jnp.int32)            # [T, F]
    t = x.shape[0]
    ghn = ghn_ref[:]                           # [T, 4] f32: g, h, node, pad
    g = ghn[:, 0:1]
    h = ghn[:, 1:2]
    ni = ghn[:, 2:3].astype(jnp.int32)         # -1 => inactive row

    lane = jax.lax.broadcasted_iota(jnp.int32, (t, 2 * n_nodes), 1)
    node_lane = lane - jnp.where(lane >= n_nodes, n_nodes, 0)
    gh = jnp.where(lane < n_nodes, g, h)       # [T, 2N] broadcast of g|h
    a = jnp.where(node_lane == ni, gh, 0.0).astype(jnp.bfloat16)

    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, bins_pad), 1)
    fs = -(-n_feat // stages)
    for s in range(stages):
        f0, f1 = s * fs, min((s + 1) * fs, n_feat)
        slabs = [(x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
                 for f in range(f0, f1)]
        oh = jnp.concatenate(slabs, axis=1) if len(slabs) > 1 else slabs[0]
        out_ref[:, f0 * bins_pad:f1 * bins_pad] += jax.lax.dot_general(
            a, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_nodes", "tile_r", "stages",
                                             "x_int8"))
def hist_vG(Xb, g, h, ni, n_nodes, tile_r, stages, x_int8=True):
    Rr, Fq = Xb.shape
    bins_pad = _bins_pad(B)
    Xi = Xb.astype(jnp.int8 if x_int8 else jnp.int32)
    ghn = jnp.stack(
        [g, h, ni.astype(jnp.float32), jnp.zeros_like(g)], axis=1)
    n_tiles = -(-Rr // tile_r)
    pad = n_tiles * tile_r - Rr
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        ghn = jnp.pad(ghn, ((0, pad), (0, 0)),
                      constant_values=-1.0)      # padded rows: node=-1
    out = pl.pallas_call(
        functools.partial(_kernel_vG, n_feat=Fq, bins_pad=bins_pad,
                          n_nodes=n_nodes, stages=stages),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_r, Fq), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 4), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, Fq * bins_pad),
                               lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, Fq * bins_pad),
                                       jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * n_nodes * Fq * bins_pad * n_tiles * tile_r,
            bytes_accessed=Rr * Fq + Rr * 16
            + 2 * n_nodes * Fq * bins_pad * 4,
            transcendentals=0),
    )(Xi, ghn)
    out = out.reshape(2, n_nodes, Fq, bins_pad)[..., :B]
    return out.transpose(1, 2, 3, 0)


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni_np = rng.integers(0, N, size=R).astype(np.int32)
    ni_np[:1000] = -1                            # exercise inactive rows
    ni = jnp.asarray(ni_np)

    ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=512)
    device_sync(ref)

    cands = [("v0 concat      tile_r=512",
              lambda: build_histograms_pallas(Xb, g, h, ni, N, B,
                                              tile_r=512))]
    for tr in (512, 768):
        for st in (1, 4):
            cands.append((f"vG i8  st{st} tile_r={tr}",
                          lambda tr=tr, st=st: hist_vG(Xb, g, h, ni, N, tr,
                                                       st, True)))
        cands.append((f"vG i32 st4 tile_r={tr}",
                      lambda tr=tr: hist_vG(Xb, g, h, ni, N, tr, 4, False)))

    best = {}
    live = []
    for name, fn in cands:
        try:
            out = fn()
            device_sync(out)
            if not bool(jnp.allclose(out, ref, rtol=2e-2, atol=2e-2)):
                print(f"{name:30s} WRONG RESULT")
                continue
            live.append((name, fn))
            best[name] = np.inf
        except Exception as e:  # noqa: BLE001
            print(f"{name:30s} FAILED: {type(e).__name__}: {str(e)[:140]}")

    for _ in range(REPS):
        for name, fn in live:
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = fn()
            device_sync(out)
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
    for name, _ in live:
        dt = best[name]
        print(f"{name:30s} {dt*1e3:8.2f} ms  {R/dt/1e6:7.1f} Mrows/s")


if __name__ == "__main__":
    main()
