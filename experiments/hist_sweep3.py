"""Sweep round 3: per-slab dots (no concat, VPU/MXU pipelining) + robust
interleaved timing (round-robin repetitions, report min-of-reps to cut the
±20% tunnel noise seen between sweep runs).

  v0   library kernel (concat + one big dot)
  v7   per-feature slab: build [T,Bp] one-hot, dot into out slice, no concat
  v7s  v7 + scratch accumulator in f32 VMEM... (same as out revisit; skip)
  v8   v7 with slab PAIRS (two features per dot, [T, 2*Bp])
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import _bins_pad, build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B, N = 1_000_000, 28, 255, 32
ITERS = 10
REPS = 3


def _kernel_v7(xb_ref, a_ref, out_ref, *, n_feat, bins_pad, pair):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]
    t = x.shape[0]
    a = a_ref[:]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, bins_pad), 1)
    step = 2 if pair else 1
    for f in range(0, n_feat, step):
        if pair:
            oh = jnp.concatenate([
                (x[:, f][:, None] == bin_iota).astype(jnp.bfloat16),
                (x[:, f + 1][:, None] == bin_iota).astype(jnp.bfloat16),
            ], axis=1)
            sl = slice(f * bins_pad, (f + 2) * bins_pad)
        else:
            oh = (x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
            sl = slice(f * bins_pad, (f + 1) * bins_pad)
        out_ref[:, sl] += jax.lax.dot_general(
            a, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "tile_r", "pair"))
def hist_v7(Xb, g, h, node_index, n_nodes, tile_r, pair=False):
    R_, F_ = Xb.shape
    bins_pad = _bins_pad(B)
    active = node_index >= 0
    idx = jnp.where(active, node_index, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    node_oh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)
    A = jnp.concatenate(
        [node_oh * gz[:, None], node_oh * hz[:, None]], axis=1
    ).astype(jnp.bfloat16)
    Xi = Xb.astype(jnp.int32)
    n_tiles = -(-R_ // tile_r)
    pad = n_tiles * tile_r - R_
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A = jnp.pad(A, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_kernel_v7, n_feat=F_, bins_pad=bins_pad,
                          pair=pair),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_r, F_), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * n_nodes), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, F_ * bins_pad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, F_ * bins_pad),
                                       jnp.float32),
    )(Xi, A)
    out = out.reshape(2, n_nodes, F_, bins_pad)[..., :B]
    return out.transpose(1, 2, 3, 0)


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni = jnp.asarray(rng.integers(0, N, size=R).astype(np.int32))

    ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=512)
    device_sync(ref)

    cands = []
    for tr in (256, 384, 512):
        cands.append((f"v0 concat   tile_r={tr}",
                      lambda tr=tr: build_histograms_pallas(
                          Xb, g, h, ni, N, B, tile_r=tr)))
    for tr in (256, 512, 1024):
        cands.append((f"v7 slabdot  tile_r={tr}",
                      lambda tr=tr: hist_v7(Xb, g, h, ni, N, tr)))
        cands.append((f"v8 pairdot  tile_r={tr}",
                      lambda tr=tr: hist_v7(Xb, g, h, ni, N, tr, pair=True)))

    best = {}
    live = []
    for name, fn in cands:   # compile + verify once
        try:
            out = fn()
            device_sync(out)
            if not bool(jnp.allclose(out, ref, rtol=2e-2, atol=2e-2)):
                print(f"{name:28s} WRONG RESULT")
                continue
            live.append((name, fn))
            best[name] = np.inf
        except Exception as e:  # noqa: BLE001
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:100]}")

    for rep in range(REPS):   # interleaved timing
        for name, fn in live:
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = fn()
            device_sync(out)
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
    for name, _ in live:
        dt = best[name]
        print(f"{name:28s} {dt*1e3:8.2f} ms  {R/dt/1e6:7.1f} Mrows/s")


if __name__ == "__main__":
    main()
