"""Sweep round 4: attack the VPU one-hot build (the measured bottleneck).

Hypothesis from sweep3: v0 (concat + one dot) is VPU-bound — per tile the
one-hot costs ~3 full passes over [T, F*Bp] (compare, select, concat copy)
vs ~1 MXU-equivalent pass for the dot, and the single long dependency chain
limits VPU/MXU overlap. Candidates:

  v0   library kernel (baseline)
  vA   full-width one-hot in ONE compare: lane-repeat x to [T, F*Bp] once,
       compare against (iota & 255) — drops the concat pass
  vB   slabs written straight into a VMEM scratch at lane offsets (the write
       IS the concat), one dot from scratch
  vC   explicit 2-stage software pipeline: build half-1 one-hot, dot half-1,
       build half-2, dot half-2 — gives Mosaic an MXU op to overlap with the
       second build
  vE   feature-split grid (n_tiles, 2): half the features per step, half the
       one-hot VMEM -> allows tile_r=1024 at the same budget
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import _bins_pad, build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B, N = 1_000_000, 28, 255, 32
ITERS = 10
REPS = 3


def _prologue(Xb, g, h, ni, n_nodes, tile_r):
    Rr, Fq = Xb.shape
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0).astype(jnp.float32)
    hz = jnp.where(active, h, 0.0).astype(jnp.float32)
    noh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)
    A = jnp.concatenate([noh * gz[:, None], noh * hz[:, None]],
                        axis=1).astype(jnp.bfloat16)
    Xi = Xb.astype(jnp.int32)
    n_tiles = -(-Rr // tile_r)
    pad = n_tiles * tile_r - Rr
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A = jnp.pad(A, ((0, pad), (0, 0)))
    return Xi, A, n_tiles


def _epilogue(out, n_nodes, n_feat, bins_pad):
    out = out.reshape(2, n_nodes, n_feat, bins_pad)[..., :B]
    return out.transpose(1, 2, 3, 0)


# ---------------------------------------------------------------- vA: repeat
def _kernel_vA(xb_ref, a_ref, out_ref, *, n_feat, bins_pad):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                              # [T, F]
    t = x.shape[0]
    xr = pltpu.repeat(x, bins_pad, axis=1)     # [T, F*Bp] lane-repeat
    lane = jax.lax.broadcasted_iota(jnp.int32, (t, n_feat * bins_pad), 1)
    oh = (xr == (lane & (bins_pad - 1))).astype(jnp.bfloat16)
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------- vB: scratch
def _kernel_vB(xb_ref, a_ref, out_ref, oh_ref, *, n_feat, bins_pad):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]
    t = x.shape[0]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, bins_pad), 1)
    for f in range(n_feat):
        oh_ref[:, f * bins_pad:(f + 1) * bins_pad] = (
            x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------- vC: 2-stage
def _kernel_vC(xb_ref, a_ref, out_ref, *, n_feat, bins_pad, stages):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]
    t = x.shape[0]
    a = a_ref[:]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, bins_pad), 1)
    fs = -(-n_feat // stages)
    for s in range(stages):
        f0, f1 = s * fs, min((s + 1) * fs, n_feat)
        slabs = [(x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
                 for f in range(f0, f1)]
        oh = jnp.concatenate(slabs, axis=1) if len(slabs) > 1 else slabs[0]
        out_ref[:, f0 * bins_pad:f1 * bins_pad] += jax.lax.dot_general(
            a, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


# ---------------------------------------------------------------- vE: f-grid
def _kernel_vE(xb_ref, a_ref, out_ref, *, f_half, bins_pad):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                              # [T, f_half] window
    t = x.shape[0]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, bins_pad), 1)
    slabs = [(x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
             for f in range(f_half)]
    oh = jnp.concatenate(slabs, axis=1)
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_nodes", "tile_r", "which",
                                             "stages"))
def hist_v(Xb, g, h, ni, n_nodes, tile_r, which, stages=2):
    Rr, Fq = Xb.shape
    bins_pad = _bins_pad(B)
    Xi, A, n_tiles = _prologue(Xb, g, h, ni, n_nodes, tile_r)
    shape = jax.ShapeDtypeStruct((2 * n_nodes, Fq * bins_pad), jnp.float32)
    xspec = pl.BlockSpec((tile_r, Fq), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    aspec = pl.BlockSpec((tile_r, 2 * n_nodes), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((2 * n_nodes, Fq * bins_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    cost = pl.CostEstimate(
        flops=2 * 2 * n_nodes * Fq * bins_pad * n_tiles * tile_r,
        bytes_accessed=Rr * Fq * 4 + Rr * 4 * n_nodes
        + 2 * n_nodes * Fq * bins_pad * 4,
        transcendentals=0)

    if which == "vA":
        out = pl.pallas_call(
            functools.partial(_kernel_vA, n_feat=Fq, bins_pad=bins_pad),
            grid=(n_tiles,), in_specs=[xspec, aspec], out_specs=ospec,
            out_shape=shape, cost_estimate=cost)(Xi, A)
    elif which == "vB":
        out = pl.pallas_call(
            functools.partial(_kernel_vB, n_feat=Fq, bins_pad=bins_pad),
            grid=(n_tiles,), in_specs=[xspec, aspec], out_specs=ospec,
            out_shape=shape, cost_estimate=cost,
            scratch_shapes=[pltpu.VMEM((tile_r, Fq * bins_pad),
                                       jnp.bfloat16)])(Xi, A)
    elif which == "vC":
        out = pl.pallas_call(
            functools.partial(_kernel_vC, n_feat=Fq, bins_pad=bins_pad,
                              stages=stages),
            grid=(n_tiles,), in_specs=[xspec, aspec], out_specs=ospec,
            out_shape=shape, cost_estimate=cost)(Xi, A)
    elif which == "vE":
        assert Fq % 2 == 0
        fh = Fq // 2
        out = pl.pallas_call(
            functools.partial(_kernel_vE, f_half=fh, bins_pad=bins_pad),
            grid=(n_tiles, 2),
            in_specs=[
                pl.BlockSpec((tile_r, fh), lambda i, j: (i, j),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((tile_r, 2 * n_nodes), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((2 * n_nodes, fh * bins_pad),
                                   lambda i, j: (0, j),
                                   memory_space=pltpu.VMEM),
            out_shape=shape, cost_estimate=cost)(Xi, A)
    else:
        raise ValueError(which)
    return _epilogue(out, n_nodes, Fq, bins_pad)


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni = jnp.asarray(rng.integers(0, N, size=R).astype(np.int32))

    ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=512)
    device_sync(ref)

    cands = [("v0 concat   tile_r=512",
              lambda: build_histograms_pallas(Xb, g, h, ni, N, B,
                                              tile_r=512))]
    for tr in (512, 768):
        cands.append((f"vA repeat   tile_r={tr}",
                      lambda tr=tr: hist_v(Xb, g, h, ni, N, tr, "vA")))
        cands.append((f"vB scratch  tile_r={tr}",
                      lambda tr=tr: hist_v(Xb, g, h, ni, N, tr, "vB")))
        cands.append((f"vC stage2   tile_r={tr}",
                      lambda tr=tr: hist_v(Xb, g, h, ni, N, tr, "vC", 2)))
        cands.append((f"vC stage4   tile_r={tr}",
                      lambda tr=tr: hist_v(Xb, g, h, ni, N, tr, "vC", 4)))
    for tr in (512, 1024):
        cands.append((f"vE f-grid   tile_r={tr}",
                      lambda tr=tr: hist_v(Xb, g, h, ni, N, tr, "vE")))

    best = {}
    live = []
    for name, fn in cands:
        try:
            out = fn()
            device_sync(out)
            if not bool(jnp.allclose(out, ref, rtol=2e-2, atol=2e-2)):
                print(f"{name:28s} WRONG RESULT")
                continue
            live.append((name, fn))
            best[name] = np.inf
        except Exception as e:  # noqa: BLE001
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:120]}")

    for _ in range(REPS):
        for name, fn in live:
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = fn()
            device_sync(out)
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
    for name, _ in live:
        dt = best[name]
        print(f"{name:28s} {dt*1e3:8.2f} ms  {R/dt/1e6:7.1f} Mrows/s")


if __name__ == "__main__":
    main()
