"""Sweep round 5: refine the staged-dot pipeline (sweep4's winner).

vC stage4 @ tile_r=768 measured 58.1 Mrows/s (v0 baseline 46-54). Explore:
stage count x tile_r grid; bf16-compare slabs (drop the int->bf16 convert);
3D-broadcast one-shot compare (single compare, no per-feature loop).
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import _bins_pad, build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B, N = 1_000_000, 28, 255, 32
ITERS = 10
REPS = 3


def _prologue(Xb, g, h, ni, n_nodes, tile_r, x_dtype=jnp.int32):
    Rr, Fq = Xb.shape
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0).astype(jnp.float32)
    hz = jnp.where(active, h, 0.0).astype(jnp.float32)
    noh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)
    A = jnp.concatenate([noh * gz[:, None], noh * hz[:, None]],
                        axis=1).astype(jnp.bfloat16)
    Xi = Xb.astype(x_dtype)
    n_tiles = -(-Rr // tile_r)
    pad = n_tiles * tile_r - Rr
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A = jnp.pad(A, ((0, pad), (0, 0)))
    return Xi, A, n_tiles


def _epilogue(out, n_nodes, n_feat, bins_pad):
    out = out.reshape(2, n_nodes, n_feat, bins_pad)[..., :B]
    return out.transpose(1, 2, 3, 0)


def _kernel_stage(xb_ref, a_ref, out_ref, *, n_feat, bins_pad, stages,
                  bf16_cmp):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]
    t = x.shape[0]
    a = a_ref[:]
    it_dt = jnp.bfloat16 if bf16_cmp else jnp.int32
    bin_iota = jax.lax.broadcasted_iota(it_dt, (t, bins_pad), 1)
    fs = -(-n_feat // stages)
    for s in range(stages):
        f0, f1 = s * fs, min((s + 1) * fs, n_feat)
        slabs = [(x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
                 for f in range(f0, f1)]
        oh = jnp.concatenate(slabs, axis=1) if len(slabs) > 1 else slabs[0]
        out_ref[:, f0 * bins_pad:f1 * bins_pad] += jax.lax.dot_general(
            a, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _kernel_bcast(xb_ref, a_ref, out_ref, *, n_feat, bins_pad, stages):
    """One-shot compare per stage via [t, fs, Bp] broadcast + reshape."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]
    t = x.shape[0]
    a = a_ref[:]
    fs = n_feat // stages
    iota3 = jax.lax.broadcasted_iota(jnp.int32, (t, fs, bins_pad), 2)
    for s in range(stages):
        xs = x[:, s * fs:(s + 1) * fs]                   # [t, fs]
        oh3 = (xs[:, :, None] == iota3).astype(jnp.bfloat16)
        oh = oh3.reshape(t, fs * bins_pad)
        out_ref[:, s * fs * bins_pad:(s + 1) * fs * bins_pad] += (
            jax.lax.dot_general(a, oh, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32))


@functools.partial(jax.jit, static_argnames=("n_nodes", "tile_r", "stages",
                                             "which"))
def hist_v(Xb, g, h, ni, n_nodes, tile_r, stages, which="stage"):
    Rr, Fq = Xb.shape
    bins_pad = _bins_pad(B)
    bf16_cmp = which == "bf16"
    x_dt = jnp.bfloat16 if bf16_cmp else jnp.int32
    Xi, A, n_tiles = _prologue(Xb, g, h, ni, n_nodes, tile_r, x_dt)
    shape = jax.ShapeDtypeStruct((2 * n_nodes, Fq * bins_pad), jnp.float32)
    xspec = pl.BlockSpec((tile_r, Fq), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    aspec = pl.BlockSpec((tile_r, 2 * n_nodes), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((2 * n_nodes, Fq * bins_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    cost = pl.CostEstimate(
        flops=2 * 2 * n_nodes * Fq * bins_pad * n_tiles * tile_r,
        bytes_accessed=Rr * Fq * 4 + Rr * 4 * n_nodes
        + 2 * n_nodes * Fq * bins_pad * 4,
        transcendentals=0)
    if which == "bcast":
        kern = functools.partial(_kernel_bcast, n_feat=Fq, bins_pad=bins_pad,
                                 stages=stages)
    else:
        kern = functools.partial(_kernel_stage, n_feat=Fq, bins_pad=bins_pad,
                                 stages=stages, bf16_cmp=bf16_cmp)
    out = pl.pallas_call(kern, grid=(n_tiles,), in_specs=[xspec, aspec],
                         out_specs=ospec, out_shape=shape,
                         cost_estimate=cost)(Xi, A)
    return _epilogue(out, n_nodes, Fq, bins_pad)


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni = jnp.asarray(rng.integers(0, N, size=R).astype(np.int32))

    ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=512)
    device_sync(ref)

    cands = [("v0 concat      tile_r=512",
              lambda: build_histograms_pallas(Xb, g, h, ni, N, B,
                                              tile_r=512))]
    for tr in (768, 1024):
        for st in (2, 4, 7, 14):
            cands.append((f"vC stage{st:<2d}    tile_r={tr}",
                          lambda tr=tr, st=st: hist_v(Xb, g, h, ni, N, tr,
                                                      st)))
    for tr in (768, 1024):
        cands.append((f"vD bf16 st4    tile_r={tr}",
                      lambda tr=tr: hist_v(Xb, g, h, ni, N, tr, 4, "bf16")))
        cands.append((f"vF bcast st4   tile_r={tr}",
                      lambda tr=tr: hist_v(Xb, g, h, ni, N, tr, 4, "bcast")))

    best = {}
    live = []
    for name, fn in cands:
        try:
            out = fn()
            device_sync(out)
            if not bool(jnp.allclose(out, ref, rtol=2e-2, atol=2e-2)):
                print(f"{name:30s} WRONG RESULT")
                continue
            live.append((name, fn))
            best[name] = np.inf
        except Exception as e:  # noqa: BLE001
            print(f"{name:30s} FAILED: {type(e).__name__}: {str(e)[:120]}")

    for _ in range(REPS):
        for name, fn in live:
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = fn()
            device_sync(out)
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
    for name, _ in live:
        dt = best[name]
        print(f"{name:30s} {dt*1e3:8.2f} ms  {R/dt/1e6:7.1f} Mrows/s")


if __name__ == "__main__":
    main()
