"""Sweep round 11: MXU-broadcast one-hot — attack the 255-bin relayout
bound (round-3 verdict item 7, docs/PERF.md round-3 addendum).

The documented bound: the row-major kernel's cost is the per-(feature,
tile) [T, 1] -> [T, Bp] LANE broadcast — a Mosaic relayout executed
F=28x per tile, flat in bin count/dtype. Every round-1-3 variant that
still needed per-feature broadcasts (in-kernel A-build, hi/lo split,
int8) died on the same class.

This sweep's idea: do the broadcast ON THE MXU instead of the VPU.
  XB[T, F*Bp] = x[T, F] @ E[F, F*Bp],  E[f, l] = 1 iff l // Bp == f
replicates x[t, f] across the f-th Bp-lane block as a single bf16
matmul (exact: bin ids <= 255 are integers <= 2^8, bf16 represents
integers to 2^8; products are x*1; each output sums ONE product). Then
the one-hot is ONE relayout-free elementwise compare against the lane
iota's low bits:
  OH = (XB == iota_lane & (Bp - 1))
MXU cost added: [T, F] @ [F, F*Bp] = F x F*Bp x T MACs ~ 44% of the main
dot's 2N x T x F*Bp — affordable because the kernel was measured NOT
MXU-bound (sweep 9: int8 pure-counts bound only +7%).

Arms (all 255-bin contract shape, interleaved per rep, min-of-reps):
  control     shipped row-major kernel (per-feature lane broadcast)
  mxu-bcast   row-major, one-hot via x @ E + single compare
  mxu-bcast-T transposed: (E_t @ Xt) with sublane iota, dot contracts T
  resident-T  sweep-10 transposed form at Bp=256 fed an ALREADY
              feature-major Xt (no prologue transpose) — is the
              documented break-even the prologue's fault?

Correctness: every arm's output is checked against the control before
timing (exact f32 equality is not expected across forms — allclose).

Run on the real TPU:  python -u experiments/hist_sweep11.py
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ddt_tpu.utils.device import device_sync  # noqa: E402

R, F, N, BINS, BP = 1_024_000, 28, 32, 255, 256


def _prologue(g, h, ni, oh_dtype):
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    noh = jax.nn.one_hot(idx, N, dtype=jnp.float32)
    return jnp.concatenate(
        [noh * gz[:, None], noh * hz[:, None]], axis=1
    ).astype(oh_dtype)                                   # [R, 2N]


# ---------------------------------------------------------------- control
def _kernel_rm(xb_ref, a_ref, out_ref, *, oh_dtype):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                                        # [T, F] int32
    tile_r = x.shape[0]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (tile_r, BP), 1)
    slabs = [
        (x[:, f][:, None] == bin_iota).astype(oh_dtype) for f in range(F)
    ]
    oh = jnp.concatenate(slabs, axis=1)                  # [T, F*Bp]
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


# ------------------------------------------------------- mxu-bcast (row)
def _kernel_mxu(xb_ref, a_ref, out_ref, *, oh_dtype):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:].astype(oh_dtype)                       # [T, F] exact <=255
    tile_r = x.shape[0]
    # E[f, l] = (l // Bp == f): built from two iotas, [F, F*Bp] — small.
    lane_f = jax.lax.broadcasted_iota(jnp.int32, (F, F * BP), 1) // BP
    feat = jax.lax.broadcasted_iota(jnp.int32, (F, F * BP), 0)
    e = (lane_f == feat).astype(oh_dtype)
    xb = jax.lax.dot_general(                            # [T, F*Bp] f32
        x, e, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    mod = (jax.lax.broadcasted_iota(jnp.int32, (tile_r, F * BP), 1)
           & (BP - 1)).astype(jnp.float32)
    oh = (xb == mod).astype(oh_dtype)
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


# ------------------------------------------------ mxu-bcast (transposed)
def _kernel_mxu_t(xt_ref, a_ref, out_ref, *, oh_dtype):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xt = xt_ref[:].astype(oh_dtype)                      # [F, T]
    tile_r = xt.shape[1]
    # E_t[l, f] = (l // Bp == f): [F*Bp, F].
    lane_f = jax.lax.broadcasted_iota(jnp.int32, (F * BP, F), 0) // BP
    feat = jax.lax.broadcasted_iota(jnp.int32, (F * BP, F), 1)
    e = (lane_f == feat).astype(oh_dtype)
    xbt = jax.lax.dot_general(                           # [F*Bp, T] f32
        e, xt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    mod = (jax.lax.broadcasted_iota(jnp.int32, (F * BP, tile_r), 0)
           & (BP - 1)).astype(jnp.float32)
    oh = (xbt == mod).astype(oh_dtype)                   # [F*Bp, T]
    out_ref[:] += jax.lax.dot_general(
        oh, a_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


# ------------------------------------------------- resident transposed
def _kernel_t(xt_ref, a_ref, out_ref, *, oh_dtype):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xt = xt_ref[:]                                       # [F, T] int32
    tile_r = xt.shape[1]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (BP, tile_r), 0)
    slabs = [
        (xt[f, :][None, :] == bin_iota).astype(oh_dtype) for f in range(F)
    ]
    oh = jnp.concatenate(slabs, axis=0)                  # [F*Bp, T]
    out_ref[:] += jax.lax.dot_general(
        oh, a_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _call_rowmajor(kernel, Xi, A, tile_r, oh_dtype):
    n_tiles = R // tile_r
    return pl.pallas_call(
        functools.partial(kernel, oh_dtype=oh_dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_r, F), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * N), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * N, F * BP), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * N, F * BP), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(Xi, A)


def _call_transposed(kernel, Xt, A, tile_r, oh_dtype):
    n_tiles = R // tile_r
    return pl.pallas_call(
        functools.partial(kernel, oh_dtype=oh_dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((F, tile_r), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * N), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((F * BP, 2 * N), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F * BP, 2 * N), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(Xt, A)


@functools.partial(jax.jit, static_argnames=("form", "tile_r"))
def build(Xi, Xt, g, h, ni, form, tile_r):
    A = _prologue(g, h, ni, jnp.bfloat16)
    if form == "control":
        out = _call_rowmajor(_kernel_rm, Xi, A, tile_r, jnp.bfloat16)
    elif form == "prologue_t":
        out = _call_transposed(_kernel_t, Xi.T, A, tile_r, jnp.bfloat16)
    elif form == "mxu":
        out = _call_rowmajor(_kernel_mxu, Xi, A, tile_r, jnp.bfloat16)
    elif form == "mxu_t":
        out = _call_transposed(_kernel_mxu_t, Xt, A, tile_r, jnp.bfloat16)
    elif form == "resident_t":
        out = _call_transposed(_kernel_t, Xt, A, tile_r, jnp.bfloat16)
    else:
        raise ValueError(form)
    if form in ("mxu_t", "resident_t", "prologue_t"):
        # [F*Bp, 2N] -> [2N, F*Bp] for comparison parity with control.
        out = out.T
    return out


def main():
    print(f"platform={jax.default_backend()}  {R}x{F}, N={N}, "
          f"bins={BINS} (Bp={BP})", flush=True)
    rng = np.random.default_rng(0)
    Xb = rng.integers(0, BINS, (R, F), dtype=np.uint8)
    Xi = jax.device_put(Xb.astype(np.int32))
    Xt = jax.device_put(np.ascontiguousarray(Xb.T).astype(np.int32))
    g = jax.device_put(rng.standard_normal(R).astype(np.float32))
    h = jax.device_put(rng.random(R).astype(np.float32))
    ni = jax.device_put(rng.integers(0, N, R).astype(np.int32))

    # The FULL arm set the round-4 refutation numbers came from (the
    # MXU-broadcast forms measured 28-38 vs control 42.6 in-run; the
    # transposed forms were settled by hist_ab_paired.py's pairing
    # protocol after interleaved runs here contradicted each other).
    # Keep every arm so the REFUTED verdicts reproduce from this script.
    arms = [
        ("control  tile=512", "control", 512),
        ("mxu      tile=128", "mxu", 128),
        ("mxu      tile=256", "mxu", 256),
        ("mxu      tile=512", "mxu", 512),
        ("mxu_t    tile=128", "mxu_t", 128),
        ("mxu_t    tile=256", "mxu_t", 256),
        ("mxu_t    tile=512", "mxu_t", 512),
        ("residentT tile=1024", "resident_t", 1024),
        ("residentT tile=2048", "resident_t", 2048),
        ("prologueT tile=1024", "prologue_t", 1024),
        ("prologueT tile=2048", "prologue_t", 2048),
    ]
    # Correctness vs control, then warm-up.
    want = None
    live = []
    for name, form, tile_r in arms:
        try:
            out = build(Xi, Xt, g, h, ni, form, tile_r)
            device_sync(out)
            got = np.asarray(out)
            if want is None:
                want = got
            else:
                np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
            live.append({"name": name, "form": form, "tile_r": tile_r,
                         "dt": float("inf")})
        except Exception as e:
            print(f"{name:22s} FAILED: {type(e).__name__}: "
                  f"{str(e)[:160]}", flush=True)
    # Interleaved timing: every arm samples every rep's noise band.
    iters, reps = 8, 10
    for rep in range(reps):
        for arm in live:
            t0 = time.perf_counter()
            for _ in range(iters):
                out = build(Xi, Xt, g, h, ni, arm["form"], arm["tile_r"])
            device_sync(out)
            arm["dt"] = min(arm["dt"],
                            (time.perf_counter() - t0) / iters)
    print(f"\ninterleaved min-of-{reps} (x{iters} iters):")
    for arm in live:
        print(f"{arm['name']:22s} {R / arm['dt'] / 1e6:8.1f} Mrows/s   "
              f"{arm['dt'] * 1e3:7.2f} ms", flush=True)


if __name__ == "__main__":
    main()
