"""Sweep round 9 (round-2 verdict item 2): int8 one-hot operands and a
reduced-bin lane-packed variant, measured on the real chip.

Hypotheses under test:

1. **int8 one-hot**: the v5e MXU's int8 rate is 2x bf16. The bin one-hot
   is exactly representable in int8; if the [T, F*Bp] operand rides the
   int8 path while A keeps the f32/bf16 gradient weights, the dot gets
   cheaper. Suspicion: the MXU has no mixed int8 x bf16 mode — XLA will
   convert int8 -> bf16 first (extra VPU work, same dot). A pure
   int8 x int8 variant (A = UNWEIGHTED node one-hot; counts-only, NOT the
   kernel contract) bounds the best case the int8 path could ever give.

2. **Reduced-bin lane packing**: the kernel is VPU-bound on the one-hot
   build (2 ops x F x Bp per row; docs/PERF.md). The shipped padding rule
   pads Bp to >= 256 lanes even for small bin counts; at n_bins <= 128 a
   Bp = 128 layout halves the VPU work per row — the candidate opt-in
   speed knob for a 64-bin contract.

Run on the real TPU:  python experiments/hist_sweep9.py
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ddt_tpu.utils.device import device_sync  # noqa: E402

R, F, N = 1_000_000, 28, 32
TILE_R = 512


def _kernel(xb_ref, a_ref, out_ref, *, n_feat, bins_pad, oh_dtype,
            acc_dtype):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]
    tile_r = x.shape[0]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (tile_r, bins_pad), 1)
    slabs = [
        (x[:, f][:, None] == bin_iota).astype(oh_dtype)
        for f in range(n_feat)
    ]
    oh = jnp.concatenate(slabs, axis=1)
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh, (((0,), (0,)), ((), ())),
        preferred_element_type=acc_dtype,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_bins", "bins_pad", "oh_dtype", "a_dtype"))
def variant(Xb, g, h, ni, n_bins, bins_pad, oh_dtype, a_dtype):
    acc_dtype = jnp.int32 if a_dtype == jnp.int8 else jnp.float32
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    noh = jax.nn.one_hot(idx, N, dtype=jnp.float32)
    if a_dtype == jnp.int8:
        # counts-only bound: A is the unweighted node one-hot twice
        A = jnp.concatenate([noh, noh], axis=1).astype(jnp.int8)
    else:
        A = jnp.concatenate(
            [noh * gz[:, None], noh * hz[:, None]], axis=1
        ).astype(a_dtype)
    Xi = Xb.astype(jnp.int32)
    n_tiles = R // TILE_R
    out = pl.pallas_call(
        functools.partial(_kernel, n_feat=F, bins_pad=bins_pad,
                          oh_dtype=oh_dtype, acc_dtype=acc_dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((TILE_R, F), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_R, 2 * N), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * N, F * bins_pad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * N, F * bins_pad), acc_dtype),
        interpret=jax.default_backend() != "tpu",
    )(Xi, A)
    return out


def run(name, n_bins, bins_pad, oh_dtype, a_dtype, iters=10, reps=5):
    rng = np.random.default_rng(0)
    # device_put ONCE — numpy inputs would re-upload ~40 MB through the
    # tunnel per call and time the H2D link instead of the kernel.
    Xb = jax.device_put(rng.integers(0, n_bins, (R, F), dtype=np.uint8))
    g = jax.device_put(rng.standard_normal(R).astype(np.float32))
    h = jax.device_put(rng.random(R).astype(np.float32))
    ni = jax.device_put(rng.integers(0, N, R).astype(np.int32))
    try:
        out = variant(Xb, g, h, ni, n_bins, bins_pad, oh_dtype, a_dtype)
        device_sync(out)
        dt = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = variant(Xb, g, h, ni, n_bins, bins_pad, oh_dtype,
                              a_dtype)
            device_sync(out)
            dt = min(dt, (time.perf_counter() - t0) / iters)
        print(f"{name:42s} {R / dt / 1e6:8.1f} Mrows/s   "
              f"{dt * 1e3:7.2f} ms")
    except Exception as e:
        print(f"{name:42s} FAILED: {type(e).__name__}: {str(e)[:120]}")


if __name__ == "__main__":
    print(f"platform={jax.default_backend()}  shape {R}x{F}, N={N}")
    run("dense 255b Bp=256 bf16 (shipped)", 255, 256, jnp.bfloat16,
        jnp.bfloat16)
    run("dense 255b Bp=256 OH=int8 A=bf16", 255, 256, jnp.int8,
        jnp.bfloat16)
    run("dense 255b Bp=256 int8xint8 (counts bound)", 255, 256, jnp.int8,
        jnp.int8)
    run("64b Bp=256 bf16 (shipped padding)", 64, 256, jnp.bfloat16,
        jnp.bfloat16)
    run("64b Bp=128 bf16 (lane-packed knob)", 64, 128, jnp.bfloat16,
        jnp.bfloat16)
    run("64b Bp=128 int8xint8 (counts bound)", 64, 128, jnp.int8,
        jnp.int8)
    run("32b Bp=128 bf16", 32, 128, jnp.bfloat16, jnp.bfloat16)
