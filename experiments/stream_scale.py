"""Config-5 at this box's full capacity: a MEASURED out-of-core run
(round-3 verdict item 3). 20M rows x 64 features of pre-binned uint8
shards (1.28 GB on disk — 5.1 GB as the float32 matrix the in-memory
path would need) trained end to end with fit_streaming over
directory_chunks on the real chip, reporting:

  - streamed throughput per pass (rows/s of data visited) and s/tree
  - peak RSS vs the post-import baseline (the O(chunk) claim, witnessed
    at 20M rows; the 5M-row suite twin with hard assertions is
    tests/test_stream_scale.py)

Through this box's remote chip tunnel the pipeline is transfer-bound at
~18 MB/s H2D (docs/PERF.md round-2 streaming section), so the absolute
rate measures the LINK, not the kernels — the number that matters for
the pod config is that rate x chips on a PCIe/DMA host, where the same
code is compute-bound at the histogram kernel's rate.

Run: python -u experiments/stream_scale.py [rows] [features] [off]
(third arg "off" disables the device chunk cache — the round-4 A/B).
"""

import json
import os
import resource
import shutil
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax  # noqa: E402

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

import numpy as np  # noqa: E402

from ddt_tpu.backends import get_backend  # noqa: E402
from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.data import chunks as chunks_mod  # noqa: E402
from ddt_tpu.streaming import fit_streaming  # noqa: E402

ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000_000
FEATURES = int(sys.argv[2]) if len(sys.argv) > 2 else 64
N_CHUNKS, BINS, TREES, DEPTH = 40, 63, 2, 3
WORK = "/tmp/ddt_stream_scale"


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main() -> None:
    print(f"platform={jax.default_backend()}  {ROWS}x{FEATURES}, "
          f"{N_CHUNKS} chunks, {TREES} trees depth {DEPTH}", flush=True)
    jax.devices()
    base = rss_mb()

    shard_dir = os.path.join(WORK, "shards")
    shutil.rmtree(shard_dir, ignore_errors=True)
    t0 = time.perf_counter()
    chunks_mod.shard_stress_chunks(shard_dir, ROWS, N_CHUNKS,
                                   n_features=FEATURES, seed=7,
                                   n_bins=BINS)
    t_shard = time.perf_counter() - t0
    print(f"sharded {ROWS * FEATURES / 1e9:.2f} GB in {t_shard:.0f}s "
          f"(rss {rss_mb():.0f} MB)", flush=True)

    cache = (sys.argv[3] if len(sys.argv) > 3 else "on") != "off"
    cfg = TrainConfig(n_trees=TREES, max_depth=DEPTH, n_bins=BINS,
                      backend="tpu")
    be = get_backend(cfg)
    src = chunks_mod.directory_chunks(shard_dir)
    t0 = time.perf_counter()
    ens = fit_streaming(src, src.n_chunks, cfg, backend=be,
                        device_chunk_cache=cache)
    t_train = time.perf_counter() - t0

    # Data visits per tree: one histogram pass per level + the leaf pass
    # (the round-start pred-update is folded into the first pass).
    passes = TREES * (DEPTH + 1)
    visited = passes * ROWS
    rec = {
        "rows": ROWS, "features": FEATURES, "n_chunks": N_CHUNKS,
        "bins": BINS, "trees": TREES, "depth": DEPTH,
        "device_chunk_cache": cache,
        "shard_s": round(t_shard, 1),
        "train_s": round(t_train, 1),
        "s_per_tree": round(t_train / TREES, 1),
        "passes": passes,
        "mrows_per_sec_per_pass": round(visited / t_train / 1e6, 3),
        "effective_h2d_mb_s": round(
            visited * FEATURES / t_train / 1e6, 1),
        "rss_baseline_mb": round(base, 1),
        "rss_peak_mb": round(rss_mb(), 1),
        "dataset_binned_mb": round(ROWS * FEATURES / 1e6, 1),
        "n_trees_grown": ens.n_trees,
    }
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
