"""Round-5 fuzz campaign: streamed == in-memory identity over RANDOM
configs INCLUDING sampling (the surface round 5 added).

The suite's fuzz (tests/test_config_fuzz.py) runs 5 seeds per run; this
campaign widens the net the way round 4's 340/210-case campaigns did for
the deterministic streamed contract: each case draws a random config
(loss x missing x cat x bins x depth x SUBSAMPLE x COLSAMPLE), random
chunk boundaries, and a random device-cache budget, trains in-memory and
streamed on the tpu backend (CPU XLA), and asserts the tie-proving
comparator contract. Root-cause ties are counted, not hidden.

Usage: python experiments/fuzz_sampling_campaign.py [n_cases] [seed0] [chip]
(third arg "chip" runs on the default platform — the real TPU under the
driver — so the streamed==in-memory contract is witnessed ON HARDWARE;
both arms share the platform, so the cross-platform seam does not
apply. Default pins the 8-virtual-device CPU mesh.)
"""

from __future__ import annotations

import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

import jax                                          # noqa: E402

if (sys.argv[3] if len(sys.argv) > 3 else "") != "chip":
    jax.config.update("jax_platforms", "cpu")

import numpy as np                                  # noqa: E402

from ddt_tpu.backends import get_backend            # noqa: E402
from ddt_tpu.driver import Driver                   # noqa: E402
from ddt_tpu.streaming import fit_streaming         # noqa: E402
from test_config_fuzz import _random_case           # noqa: E402
from tree_compare import assert_trees_match_mod_ties  # noqa: E402


def main():
    n_cases = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    seed0 = int(sys.argv[2]) if len(sys.argv) > 2 else 1000
    print(f"# platform={jax.default_backend()}", flush=True)
    failures = []
    sampled = 0
    for i in range(n_cases):
        case = seed0 + i
        rng = np.random.default_rng((211, case))
        Xb, y, cfg = _random_case(rng)
        cfg = cfg.replace(backend="tpu")
        if cfg.subsample < 1.0 or cfg.colsample_bytree < 1.0:
            sampled += 1
        try:
            full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(
                Xb, y)
            rows = len(y)
            n_chunks = int(rng.integers(2, 6))
            bounds = np.linspace(0, rows, n_chunks + 1).astype(int)

            def chunk_fn(c):
                return (Xb[bounds[c]:bounds[c + 1]],
                        y[bounds[c]:bounds[c + 1]])

            chunk_fn.labels = lambda c: y[bounds[c]:bounds[c + 1]]
            chunk_fn.n_features = Xb.shape[1]
            budget = int(rng.integers(0, Xb.nbytes + 1))
            streamed = fit_streaming(chunk_fn, n_chunks, cfg,
                                     device_chunk_cache=budget)
            assert_trees_match_mod_ties(full, streamed,
                                        cfg.min_split_gain)
            status = "ok"
        except Exception:
            status = "FAIL"
            failures.append(case)
            traceback.print_exc()
        print(f"case {case}: {status}  (loss={cfg.loss} bins={cfg.n_bins} "
              f"depth={cfg.max_depth} sub={cfg.subsample} "
              f"col={cfg.colsample_bytree} "
              f"miss={cfg.missing_policy} cat={bool(cfg.cat_features)})",
              flush=True)
    print(json.dumps({"cases": n_cases, "sampled_cases": sampled,
                      "failures": failures}), flush=True)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
