"""Config-3 at-scale witness: 1M-row categorical training over 4 virtual
partitions (round-4 verdict item 7).

The distributed-categorical BASELINE config (Criteo-like, 4 partitions)
had e2e miniatures and toy-size partition identity tests but no
at-capacity witness the way config-5 got its 20M-row run. This script
trains the Criteo shape — 13 numeric + 26 high-cardinality (Zipf,
100k-card) categorical columns, frequency-encoded, one-vs-rest splits —
at >= 1M rows on a 4-device virtual CPU mesh, asserts BIT-IDENTITY of
the grown trees against the single-device run, and records wallclock +
peak RSS for docs/PERF.md.

Run OFF the chip (pure CPU; the virtual mesh is the point):
    python experiments/config3_scale.py [rows] [trees]
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()

import jax                                          # noqa: E402

# sitecustomize pins the axon platform at interpreter startup; the env
# var is overwritten, so the config call is the only working override
# (must precede first device use).
jax.config.update("jax_platforms", "cpu")

from ddt_tpu.backends import get_backend            # noqa: E402
from ddt_tpu.config import TrainConfig              # noqa: E402
from ddt_tpu.data.categorical import fit_categorical_encoder  # noqa: E402
from ddt_tpu.data.datasets import synthetic_ctr     # noqa: E402
from ddt_tpu.data.quantizer import fit_bin_mapper   # noqa: E402
from ddt_tpu.driver import Driver                   # noqa: E402


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    trees = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    bins = 63
    t0 = time.perf_counter()
    Xn, Xc, y = synthetic_ctr(rows, seed=5)
    enc = fit_categorical_encoder(Xc, n_bins=bins)
    X = np.concatenate([Xn, enc.transform(Xc).astype(np.float32)], axis=1)
    cat = tuple(range(Xn.shape[1], X.shape[1]))
    m = fit_bin_mapper(X, n_bins=bins, cat_features=cat)
    Xb = m.transform(X)
    prep_s = time.perf_counter() - t0
    print(f"# prepared {rows} x {X.shape[1]} (26 cat cols, card<=100k "
          f"-> {bins}-bin frequency encoding) in {prep_s:.1f}s",
          flush=True)

    results = {}
    ens = {}
    for parts in (1, 4):
        # min_split_gain carries the documented noise floor (ops/split.py
        # "Determinism boundary"): a signal-free node's best gain is
        # ~1e-8 f32 cancellation noise whose ORDER-dependent sign flips
        # between the single matmul and the 4-shard psum; at 0.0 the
        # split/no-split decision sits on that razor edge and ~1% of
        # deep nodes legitimately diverge (observed at 1M rows before
        # this floor was set — the same rule every identity fuzz uses).
        cfg = TrainConfig(n_trees=trees, max_depth=6, n_bins=bins,
                          backend="tpu", n_partitions=parts,
                          min_split_gain=1e-3,
                          cat_features=cat)
        be = get_backend(cfg)
        t0 = time.perf_counter()
        ens[parts] = Driver(be, cfg, log_every=5).fit(Xb, y)
        dt = time.perf_counter() - t0
        results[parts] = dt
        print(f"# n_partitions={parts}: {dt:.1f}s "
              f"({rows * trees / dt / 1e6:.2f} Mrow-trees/s)", flush=True)

    # Identity contract at this scale (measured, docs/PERF.md round-5):
    # the 4-shard psum's f32 summation order differs from the single
    # matmul's, so bf16-boundary candidate ties can flip — the same seam
    # as chunked accumulation (ops/split.py "Determinism boundary"),
    # whose incidence grows with row count. The checkable claim:
    #   (a) every tree BEFORE the first divergence is bitwise identical;
    #   (b) the first divergent tree's root causes are PROVABLE ties
    #       (tie comparator, per-tree, leaf tolerance widened for
    #       1M-row f32 leaf-sum drift);
    #   (c) later trees legitimately cascade (they train on the
    #       residuals the tied choice changed) — quality equivalence is
    #       asserted instead (holdout AUC delta).
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tests"))
    from tree_compare import assert_prefix_identity_mod_ties

    prefix_n, first_div = assert_prefix_identity_mod_ties(
        ens[1], ens[4], 1e-3)
    agreement = float((ens[1].feature == ens[4].feature).mean())

    hold_n, hold_seed = 200_000, 77
    Xn_h, Xc_h, y_h = synthetic_ctr(hold_n, seed=hold_seed)
    Xh = np.concatenate(
        [Xn_h, enc.transform(Xc_h).astype(np.float32)], axis=1)
    Xhb = m.transform(Xh)
    from ddt_tpu.utils.metrics import auc
    auc1 = auc(y_h, ens[1].predict_raw(Xhb, binned=True))
    auc4 = auc(y_h, ens[4].predict_raw(Xhb, binned=True))
    assert abs(auc1 - auc4) < 1e-3, (auc1, auc4)

    n_cat_splits = int(np.isin(ens[4].feature[~ens[4].is_leaf],
                               list(cat)).sum())
    assert n_cat_splits > 0, "no categorical splits grew; data too easy"

    peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    print(json.dumps({
        "rows": rows, "trees": trees, "bins": bins,
        "features": X.shape[1], "cat_features": len(cat),
        "wallclock_1part_s": round(results[1], 1),
        "wallclock_4part_s": round(results[4], 1),
        "bitwise_prefix_trees": (first_div if first_div is not None
                                 else trees),
        "first_divergent_tree": first_div,
        "split_agreement": round(agreement, 4),
        "holdout_auc_1part": round(auc1, 5),
        "holdout_auc_4part": round(auc4, 5),
        "n_cat_splits": n_cat_splits,
        "peak_rss_mb": round(peak_mb, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
