"""Real-chip vs CPU-oracle training parity: MEASUREMENT, not assertion.

Round-3 finding (first time real-chip training was ever compared to the
oracle — the test suite's backend parity runs both backends on ONE
platform): cross-PLATFORM training is quality-equivalent but NOT
bit-identical. Measured (20k rows x 12 features, depth-4):

  - 5 trees: 2-4/155 split-feature mismatches, 6-9 threshold
    mismatches — EQUAL at 255 bins (row-major kernel, shipped since r1)
    and 64 bins (transposed kernel), so not a kernel-variant bug.
  - min_split_gain=1e-3 does NOT remove them (unlike same-platform
    noise-floor flips) and matmul_input_dtype=float32 does NOT either:
    the divergence is f32 summation ORDER (MXU systolic accumulation vs
    the CPU reference's sequential loop), which straddles bf16
    gain-rounding boundaries on exact near-ties. No dtype knob can fix
    ordering.
  - 20 trees: ~89% split-field agreement (one early flip diverges its
    subtree and, through pred, later trees), held-out AUC within 0.004
    and logloss within 0.003 of each other IN BOTH DIRECTIONS at both
    bin widths — the flips pick gains within float noise of each other,
    so model quality is unaffected.

Scope of the repo's bit-identity contract, restated: WITHIN a platform,
every backend/partition-count/streaming path grows identical trees
(tested exhaustively on the CPU suite); ACROSS platforms (real v5e vs
CPU), split decisions agree except on bf16-boundary-straddling exact
near-ties. See ops/split.py "Determinism boundary".

Run: python -u experiments/chip_parity.py
"""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

import numpy as np  # noqa: E402

from ddt_tpu import api  # noqa: E402
from ddt_tpu.data import datasets  # noqa: E402
from ddt_tpu.data.quantizer import quantize  # noqa: E402
from ddt_tpu.utils.metrics import auc, logloss  # noqa: E402

X, y = datasets.synthetic_binary(24_000, n_features=12, seed=31)
Xt, yt, Xv, yv = X[:20_000], y[:20_000], X[20_000:], y[20_000:]
ok = True
for bins in (255, 64):
    Xb, mapper = quantize(Xt, n_bins=bins, seed=31)
    Xvb = mapper.transform(Xv)
    kw = dict(n_trees=20, max_depth=4, n_bins=bins, binned=True,
              log_every=10**9)
    tpu = api.train(Xb, yt, backend="tpu", **kw).ensemble
    cpu = api.train(Xb, yt, backend="cpu", **kw).ensemble
    agree = float((tpu.feature == cpu.feature).mean())
    a_t, a_c = auc(yv, tpu.predict_raw(Xvb, binned=True)), \
        auc(yv, cpu.predict_raw(Xvb, binned=True))
    print(f"bins={bins}: split agreement {agree:.4f}  "
          f"auc tpu={a_t:.5f} cpu={a_c:.5f}", flush=True)
    ok &= agree > 0.8 and abs(a_t - a_c) < 0.01
print("QUALITY-EQUIVALENT" if ok else "DIVERGED BEYOND TOLERANCE")
sys.exit(0 if ok else 1)
