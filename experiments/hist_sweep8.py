"""Sweep round 8: adaptive hi/lo bin-split histograms for SHALLOW levels.

Motivation (docs/PERF.md cost model): the dense kernel's per-level cost is
~constant in n_nodes — the VPU one-hot build is 2 ops x F x 256 per ROW
regardless of how many nodes exist — so levels 0-2 cost as much as level 5.
Round-1's nibble note ("wins ONLY for n_nodes < 8") dismissed exactly the
levels that are NOT cheap.

Formulation: split bin index b = n_hi*? no — b = hi * n_lo + lo with
n_hi * n_lo = 256, both powers of two. Then

    hist[n, f, hi*n_lo+lo] = sum_r a[r,n] * 1[hi_rf==hi] * 1[lo_rf==lo]
                           = sum_r W_f[r, (n,hi)] * LO_f[r, lo]

W_f = A2 * (hi_col == hi_iota) where A2 is A lane-repeated n_hi times
(done in the XLA prologue — tiny HBM traffic at small N, avoids in-kernel
lane relayouts that sank the vG experiment). VPU cost per row per feature:
2*(2N*n_hi) + 2*n_lo  vs dense 2*256. Optimal n_hi ~ sqrt(128/N):

    N=1: (8,32) -> 96 ops  (5.3x less VPU)    N=8:  (4,64) -> 256 (2x)
    N=2: (8,32) -> 128 (4x)                   N=16: (4,64) -> 384 (1.3x)
    N=4: (8,32) -> 192 (2.7x)                 N=32: dense wins (tie at best)

MXU flops are IDENTICAL to dense (2*2N*256*T*F) — only the dot shapes
change ([2N*n_hi, T]@[T, n_lo] per feature).

Run on the real TPU:  python experiments/hist_sweep8.py
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B = 1_000_000, 28, 255
ITERS = 10
REPS = 4
TILE_R = 512


def _kernel_split(xb_ref, a2_ref, out_ref, *, n_feat, n_nodes, n_hi, n_lo):
    """out[(n,hi), (f,lo)] += W_f^T @ LO_f per feature slab."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                                   # [T, F] int32 bins
    a2 = a2_ref[:]                                  # [T, 2N*n_hi] bf16
    t = x.shape[0]
    shift = {2: 1, 4: 2, 8: 3, 16: 4, 32: 5, 64: 6, 128: 7}[n_lo]
    hi = x >> shift                                  # [T, F] in [0, n_hi)
    lo = x & (n_lo - 1)

    w_lanes = 2 * n_nodes * n_hi
    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (t, w_lanes), 1) & (n_hi - 1)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (t, n_lo), 1)

    for f in range(n_feat):
        w = jnp.where(hi[:, f][:, None] == hi_iota, a2, 0.0)   # [T, 2N*n_hi]
        lo_oh = (lo[:, f][:, None] == lo_iota).astype(jnp.bfloat16)
        out_ref[:, f * n_lo:(f + 1) * n_lo] += jax.lax.dot_general(
            w, lo_oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )


@functools.partial(
    jax.jit, static_argnames=("n_nodes", "n_hi", "tile_r", "x_dtype")
)
def hist_split(Xb, g, h, ni, n_nodes, n_hi, tile_r=TILE_R,
               x_dtype=jnp.int32):
    n_lo = 256 // n_hi
    Rr, Fq = Xb.shape
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    noh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)
    A = jnp.concatenate([noh * gz[:, None], noh * hz[:, None]],
                        axis=1).astype(jnp.bfloat16)            # [R, 2N]
    A2 = jnp.repeat(A, n_hi, axis=1)                            # [R, 2N*n_hi]
    Xi = Xb.astype(x_dtype)
    n_tiles = -(-Rr // tile_r)
    pad = n_tiles * tile_r - Rr
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A2 = jnp.pad(A2, ((0, pad), (0, 0)))
    w_lanes = 2 * n_nodes * n_hi
    out = pl.pallas_call(
        functools.partial(_kernel_split, n_feat=Fq, n_nodes=n_nodes,
                          n_hi=n_hi, n_lo=n_lo),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_r, Fq), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, w_lanes), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((w_lanes, Fq * n_lo), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((w_lanes, Fq * n_lo), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * w_lanes * Fq * n_lo * n_tiles * tile_r,
            bytes_accessed=Rr * Fq * 4 + Rr * w_lanes * 2
            + w_lanes * Fq * n_lo * 4,
            transcendentals=0),
    )(Xi, A2)
    # [(2,N,hi), (F,lo)] -> [N, F, hi*n_lo+lo=256, 2] -> slice bins
    out = out.reshape(2, n_nodes, n_hi, Fq, n_lo)
    out = out.transpose(1, 3, 2, 4, 0).reshape(n_nodes, Fq, 256, 2)
    return out[:, :, :B, :]


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))

    for N in (1, 2, 4, 8, 16, 32):
        ni_np = rng.integers(0, N, size=R).astype(np.int32)
        ni_np[:1000] = -1
        ni = jnp.asarray(ni_np)

        ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=TILE_R)
        device_sync(ref)

        cands = [(f"N={N:2d} v0 dense", lambda N=N, ni=ni:
                  build_histograms_pallas(Xb, g, h, ni, N, B,
                                          tile_r=TILE_R))]
        for n_hi in (4, 8, 16):
            if 2 * N * n_hi > 1024:       # accumulator sublane sanity cap
                continue
            cands.append((f"N={N:2d} split hi{n_hi:2d}xlo{256 // n_hi:3d}",
                          lambda N=N, ni=ni, n_hi=n_hi:
                          hist_split(Xb, g, h, ni, N, n_hi)))

        best, live = {}, []
        for name, fn in cands:
            try:
                out = fn()
                device_sync(out)
                if not bool(jnp.allclose(out, ref, rtol=2e-2, atol=2e-2)):
                    print(f"{name:28s} WRONG RESULT")
                    continue
                live.append((name, fn))
                best[name] = np.inf
            except Exception as e:  # noqa: BLE001
                print(f"{name:28s} FAILED: {type(e).__name__}: "
                      f"{str(e)[:120]}")

        for _ in range(REPS):
            for name, fn in live:
                t0 = time.perf_counter()
                for _ in range(ITERS):
                    out = fn()
                device_sync(out)
                best[name] = min(best[name],
                                 (time.perf_counter() - t0) / ITERS)
        for name, _ in live:
            dt = best[name]
            print(f"{name:28s} {dt * 1e3:8.2f} ms  {R / dt / 1e6:7.1f} "
                  f"Mrows/s")


if __name__ == "__main__":
    main()
