"""Paired-ratio A/B: fused grow_tree dispatch at 255 vs 64 bins.

Round 3's interleaved grow A/B (grow_ab_bins.py) measured ~1.3x for the
64-bin opt-in at the whole-tree dispatch level; round 4's sweep-11
epilogue showed that protocol can still compare arms across the
tunnel's persistent wallclock bands. This re-measures the claim with
the amended protocol (docs/PERF.md round-4 addendum): per-rep PAIRED
ratios, arm order alternating every rep, pairs spread over minutes,
median reported (scaffolding: experiments/paired_protocol.py).

Measured 2026-07-30 (24 pairs): median 255b/64b = 1.281,
IQR [1.150, 1.407] — round-3's ~1.3x fused-dispatch claim CONFIRMED.

Run: python -u experiments/grow_ab_paired.py
"""
import functools
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

import numpy as np  # noqa: E402

from experiments.paired_protocol import paired_ab  # noqa: E402
from ddt_tpu.backends import get_backend  # noqa: E402
from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.utils.device import device_sync  # noqa: E402

R, REPS, ITERS = 1_000_000, 24, 4


def main() -> None:
    rng = np.random.default_rng(0)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    arms = {}
    for bins in (255, 64):
        cfg = TrainConfig(n_trees=1, max_depth=6, n_bins=bins,
                          backend="tpu")
        be = get_backend(cfg)
        Xb = rng.integers(0, bins, (R, 28), dtype=np.uint8)
        args = (be.upload(Xb), be._put_rows(g), be._put_rows(h))
        _, delta = be.grow_tree(*args)
        device_sync(delta)                       # compile + first run
        arms[bins] = (be, args)

    def bout(bins):
        be, args = arms[bins]
        t0 = time.perf_counter()
        for _ in range(ITERS):
            _, delta = be.grow_tree(*args)
        device_sync(delta)
        return (time.perf_counter() - t0) / ITERS

    paired_ab(
        functools.partial(bout, 255), functools.partial(bout, 64),
        name_a="255b", name_b="64b", reps=REPS,
    )


if __name__ == "__main__":
    main()
