"""Paired-ratio A/B: fused grow_tree dispatch at 255 vs 64 bins.

Round 3's interleaved grow A/B (grow_ab_bins.py) measured ~1.3x for the
64-bin opt-in at the whole-tree dispatch level; round 4's sweep-11
epilogue showed that protocol can still compare arms across the
tunnel's persistent wallclock bands. This re-measures the claim with
the amended protocol (docs/PERF.md round-4 addendum): per-rep PAIRED
ratios, arm order alternating every rep, pairs spread over minutes,
median reported.

Run: python -u experiments/grow_ab_paired.py
"""
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

import numpy as np  # noqa: E402

from ddt_tpu.backends import get_backend  # noqa: E402
from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.utils.device import device_sync  # noqa: E402

R, REPS, ITERS = 1_000_000, 24, 4


def main() -> None:
    rng = np.random.default_rng(0)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    arms = {}
    for bins in (255, 64):
        cfg = TrainConfig(n_trees=1, max_depth=6, n_bins=bins,
                          backend="tpu")
        be = get_backend(cfg)
        Xb = rng.integers(0, bins, (R, 28), dtype=np.uint8)
        args = (be.upload(Xb), be._put_rows(g), be._put_rows(h))
        _, delta = be.grow_tree(*args)
        device_sync(delta)                       # compile + first run
        arms[bins] = (be, args)

    def bout(bins):
        be, args = arms[bins]
        t0 = time.perf_counter()
        for _ in range(ITERS):
            _, delta = be.grow_tree(*args)
        device_sync(delta)
        return (time.perf_counter() - t0) / ITERS

    ratios = []
    for rep in range(REPS):
        order = (255, 64) if rep % 2 == 0 else (64, 255)
        ts = {b: bout(b) for b in order}
        ratios.append(ts[255] / ts[64])
        print(f"rep {rep:02d}  255b {ts[255] * 1e3:6.1f} ms  "
              f"64b {ts[64] * 1e3:6.1f} ms  ratio {ratios[-1]:.3f}",
              flush=True)
        time.sleep(4)
    med = float(np.median(ratios))
    q1, q3 = np.percentile(ratios, [25, 75])
    print(f"\nmedian paired ratio 255b/64b = {med:.3f}  "
          f"IQR [{q1:.3f}, {q3:.3f}]", flush=True)


if __name__ == "__main__":
    main()
