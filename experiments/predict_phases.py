"""Phase breakdown of the resident 10M x 1000-tree scoring config.

Round-4 verdict item 1: predict (BASELINE config 4) never had a perf
round — no phase breakdown, no formulation A/B under the paired
protocol. This script produces the breakdown that decides where any
optimisation effort goes:

  P1 comp-matrix : per (row-chunk, tree-chunk), the bf16 one-hot matmul
                   colval = Xc . onehot(feat) and the > threshold compare
                   (ops/predict._descend_comp's precompute)
  P2 descent     : + the 6-level one-hot path-bit selection
  P3 leaf-select : + bottom-level one-hot leaf-value select
  P4 full-compute: the real predict_raw, result REDUCED on device (no
                   vector fetch) — adds the class-scatter matmul + scan
                   plumbing over P3
  P5 full+D2H    : predict_raw with the [10M] f32 scores fetched to host
                   (the bench's resident arm) — P5 - P4 is the tunnel's
                   D2H share, the part no kernel work can move

Each phase program runs the whole 10M x 1000 volume (row chunks x tree
chunks under lax.scan, identical chunking to predict_raw) and returns a
scalar, so inter-phase deltas isolate the added stage. The input batch
is GENERATED ON DEVICE (random bins — traversal cost is data-blind):
uploading 280 MB through the ~18 MB/s tunnel would add minutes and
nothing else. Timings are min-of-reps with device_sync (tunnel protocol,
docs/PERF.md); phase RATIOS within one run share the band, so the
breakdown is meaningful even when absolute Mrows/s drifts.

Usage: python experiments/predict_phases.py [rows_millions]
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                          # noqa: E402
import jax.numpy as jnp                             # noqa: E402

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402
from ddt_tpu.ops.predict import (                   # noqa: E402
    _descend_comp, _effective_arrays, predict_raw)
from ddt_tpu.utils.device import device_sync        # noqa: E402

T, DEPTH, F, B = 1000, 6, 28, 255
TREE_CHUNK, ROW_CHUNK = 64, 8192
N = 2 ** (DEPTH + 1) - 1
N_INT = (1 << DEPTH) - 1


def build_model(seed=0):
    rng = np.random.default_rng(seed)
    feature = rng.integers(0, F, size=(T, N)).astype(np.int32)
    thr = rng.integers(0, B - 1, size=(T, N)).astype(np.int32)
    is_leaf = np.zeros((T, N), bool)
    is_leaf[:, N // 2:] = True
    leaf_value = rng.standard_normal((T, N)).astype(np.float32)
    return feature, thr, is_leaf, leaf_value


def device_batch(rows, seed=0):
    """Random binned batch generated ON device (skips the tunnel)."""
    @jax.jit
    def gen(key):
        return jax.random.randint(key, (rows, F), 0, B, dtype=jnp.int32
                                  ).astype(jnp.uint8)
    x = gen(jax.random.PRNGKey(seed))
    device_sync(x)
    return x


def _padded_effective(feature, thr, is_leaf, leaf_value):
    """predict_raw's tree padding (all-leaf value-0 trees to a TREE_CHUNK
    multiple) + leaf pushdown, reshaped into tree chunks."""
    T_ = feature.shape[0]
    n_tc = -(-T_ // TREE_CHUNK)
    tpad = n_tc * TREE_CHUNK - T_

    def pad_t(a, fill=0):
        return jnp.pad(a, ((0, tpad), (0, 0)), constant_values=fill)

    ef, et, ev, _ = _effective_arrays(
        pad_t(feature, -1), pad_t(thr), pad_t(is_leaf, True),
        pad_t(leaf_value), DEPTH)
    featp = ef.reshape(n_tc, TREE_CHUNK, -1)
    thrp = et.reshape(n_tc, TREE_CHUNK, -1)
    valp = ev[:, N_INT:].reshape(n_tc, TREE_CHUNK, -1)
    return featp, thrp, valp


@functools.partial(jax.jit, static_argnames=("stage",))
def staged(feature, thr, is_leaf, leaf_value, Xc, *, stage):
    """predict_raw's exact chunking with the per-tree-chunk body cut at
    `stage`; returns a f32 scalar so nothing row-sized leaves the chip."""
    Xc = Xc.astype(jnp.int32)
    R = Xc.shape[0]
    featp, thrp, valp = _padded_effective(feature, thr, is_leaf,
                                          leaf_value)
    n_rc = R // ROW_CHUNK
    Xp = Xc.reshape(n_rc, ROW_CHUNK, F)

    def row_body(acc_r, xrc):
        def tree_body(acc, args):
            f, t, v = args
            if stage == "comp":
                foh = (f[:, :N_INT, None] == jnp.arange(
                    F, dtype=jnp.int32)[None, None, :]).astype(jnp.bfloat16)
                colval = jax.lax.dot_general(
                    xrc.astype(jnp.bfloat16),
                    foh.reshape(TREE_CHUNK * N_INT, F),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.bfloat16,
                ).reshape(ROW_CHUNK, TREE_CHUNK, N_INT)
                comp = colval > t[None, :, :N_INT].astype(jnp.bfloat16)
                return acc + comp.sum(dtype=jnp.float32), None
            k = _descend_comp(f, t, xrc, DEPTH)
            if stage == "descend":
                return acc + k.sum().astype(jnp.float32), None
            W = v.shape[1]
            noh = (k[:, :, None]
                   == jnp.arange(W, dtype=jnp.int32)[None, None, :])
            vals = jnp.sum(jnp.where(noh, v[None, :, :], 0.0), axis=-1)
            return acc + vals.sum(), None            # stage == "leaf"

        acc, _ = jax.lax.scan(tree_body, jnp.float32(0),
                              (featp, thrp, valp))
        return acc_r + acc, None

    out, _ = jax.lax.scan(row_body, jnp.float32(0), Xp)
    return out


def timed(fn, reps=5):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        device_sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    enable_persistent_compile_cache()
    rows_m = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    rows = int(rows_m * 1e6) // ROW_CHUNK * ROW_CHUNK
    feature, thr, is_leaf, leaf_value = build_model()
    fd = jax.device_put(feature)
    td = jax.device_put(thr)
    ld = jax.device_put(is_leaf)
    vd = jax.device_put(leaf_value)
    Xd = device_batch(rows)
    print(f"# rows={rows} trees={T} depth={DEPTH} "
          f"platform={jax.default_backend()}", flush=True)

    full = functools.partial(
        predict_raw, fd, td, ld, vd, Xd, max_depth=DEPTH,
        learning_rate=0.1, base=0.0, n_classes=1,
        tree_chunk=TREE_CHUNK, row_chunk=ROW_CHUNK)

    @jax.jit
    def full_nofetch(x):
        return predict_raw(fd, td, ld, vd, x, max_depth=DEPTH,
                           learning_rate=0.1, base=0.0, n_classes=1,
                           tree_chunk=TREE_CHUNK, row_chunk=ROW_CHUNK).sum()

    phases = {}
    # warm every program first (compiles), then time coldest-first
    for name in ("comp", "descend", "leaf"):
        device_sync(staged(fd, td, ld, vd, Xd, stage=name))
    device_sync(full_nofetch(Xd))
    np.asarray(full())

    for name in ("comp", "descend", "leaf"):
        phases[name] = timed(
            lambda n=name: staged(fd, td, ld, vd, Xd, stage=n))
    phases["full_nofetch"] = timed(lambda: full_nofetch(Xd))
    phases["full_d2h"] = timed(lambda: np.asarray(full()), reps=3)

    rec = {"rows": rows, "trees": T,
           **{k: round(v, 3) for k, v in phases.items()},
           "mrows_resident": round(rows / phases["full_d2h"] / 1e6, 2),
           "d2h_share": round(
               1 - phases["full_nofetch"] / phases["full_d2h"], 3)}
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
