"""Paired A/B: is the headline hist metric's band swing dispatch jitter?

The round-4 verdict's standing complaint: the headline 255-bin number
swings 40-64 Mrows/s across tunnel bands, so the captured artifact is
"band luck". The bench already amortizes dispatch (10 async dispatches,
one sync), but each dispatch still crosses the tunneled remote runtime.
Hypothesis to kill or confirm: a ONE-dispatch variant — K kernel
invocations inside a single jitted lax.fori_loop, two round-trips total
— removes per-dispatch jitter; if its per-rep spread is much tighter
than the dispatch-loop's IN THE SAME WINDOW, the band story is partly
dispatch-side and a band-stable headline metric exists; if the spreads
match, the bands are device/runtime execution-rate variance and the
sealed diagnosis stands with direct evidence.

Method: interleaved reps (A, B, A, B, ...) of
  A: bench-style loop of K async dispatches + one device_sync;
  B: jit(fori_loop(K, hist ∘ perturb)) + one device_sync
with a data dependence (g advanced by a tiny function of the previous
histogram) so XLA cannot hoist the loop body. Same inputs, same shapes
as bench.py's headline arm (1M x 28, 255 bins, 32 nodes).

Usage: python experiments/hist_dispatch_ab.py [reps] [K]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                          # noqa: E402
import jax.numpy as jnp                             # noqa: E402

from ddt_tpu.backends.tpu import (                  # noqa: E402
    enable_persistent_compile_cache)
from ddt_tpu.ops import histogram as hist_ops       # noqa: E402


def main():
    enable_persistent_compile_cache()
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 12
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    R, F, B, N = 1_000_000, 28, 255, 32

    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, (R, F), np.uint8))
    g0 = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni = jnp.asarray(rng.integers(0, N, R).astype(np.int32))

    def hist(g):
        return hist_ops.build_histograms(Xb, g, h, ni, N, B)

    one = jax.jit(hist)

    @jax.jit
    def k_in_one(g):
        def body(_, carry):
            g2, acc = carry
            out = hist_ops.build_histograms(Xb, g2, h, ni, N, B)
            s = out[0, 0, 0, 0] * jnp.float32(1e-30)   # cheap dependence
            return g2 + s, acc + s
        return jax.lax.fori_loop(0, K, body, (g, jnp.float32(0.0)))[1]

    # Warm both programs.
    float(jnp.sum(one(g0)))
    float(k_in_one(g0))

    rows_a, rows_b = [], []
    for rep in range(reps):
        t0 = time.perf_counter()
        out = None
        for _ in range(K):
            out = one(g0)
        float(jnp.sum(out))
        dt_a = (time.perf_counter() - t0) / K

        t0 = time.perf_counter()
        float(k_in_one(g0))
        dt_b = (time.perf_counter() - t0) / K

        a, b = R / dt_a / 1e6, R / dt_b / 1e6
        rows_a.append(a)
        rows_b.append(b)
        print(f"rep {rep:02d}  dispatch-loop {a:6.1f} Mrows/s   "
              f"one-dispatch {b:6.1f} Mrows/s", flush=True)

    def stats(v):
        v = np.array(v)
        return dict(median=round(float(np.median(v)), 2),
                    q1=round(float(np.percentile(v, 25)), 2),
                    q3=round(float(np.percentile(v, 75)), 2),
                    spread_pct=round(100 * (v.max() - v.min())
                                     / np.median(v), 1))

    rec = {"dispatch_loop": stats(rows_a), "one_dispatch": stats(rows_b),
           "reps": reps, "K": K}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
