"""Paired A/B: overlapped per-chunk D2H vs the old serial end fetch.

experiments/predict_phases.py measured the resident 10M x 1000 scoring
config at ~65% device->host fetch (the [10M] f32 score vector through
the tunnel) paid SERIALLY after all compute. The round-5 predict path
(backends/tpu.py predict_raw, single-chip branch) starts every chunk's
host copy asynchronously so the link drains while later chunks compute.
This script times OLD (device-side concatenate + one blocking fetch)
against NEW (the shipped overlapped path) under the paired per-rep-ratio
protocol. Identical outputs are asserted before timing.

Usage: python experiments/predict_fetch_ab.py [rows_millions] [reps]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                          # noqa: E402
import jax.numpy as jnp                             # noqa: E402

from ddt_tpu.backends import get_backend            # noqa: E402
from ddt_tpu.backends.tpu import (                  # noqa: E402
    enable_persistent_compile_cache)
from ddt_tpu.config import TrainConfig              # noqa: E402
from ddt_tpu.models.tree import empty_ensemble      # noqa: E402
from experiments.paired_protocol import paired_ab   # noqa: E402
from experiments.predict_phases import (            # noqa: E402
    B, DEPTH, F, N, T, build_model, device_batch)


def main():
    enable_persistent_compile_cache()
    rows_m = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 12
    rows = int(rows_m * 1e6)
    feature, thr, is_leaf, leaf_value = build_model()
    ens = empty_ensemble(T, DEPTH, F, 0.1, 0.0, "logloss")
    ens.feature[:] = feature
    ens.threshold_bin[:] = thr
    ens.is_leaf[:] = is_leaf
    ens.leaf_value[:] = leaf_value
    Xd = device_batch(rows)
    be = get_backend(TrainConfig(backend="tpu", n_bins=B))
    chunk = be.PREDICT_ROW_CHUNK
    print(f"# rows={rows} chunk={chunk} platform={jax.default_backend()}",
          flush=True)

    fn, ens_dev = be._predict_fn(ens)

    def old_path():
        outs = [fn(*ens_dev, Xd[i:i + chunk])
                for i in range(0, rows, chunk)]
        return np.asarray(jnp.concatenate(outs))[:rows]

    new = be.predict_raw(ens, Xd)                   # warm + reference
    old = old_path()
    np.testing.assert_array_equal(old, new)
    print("# exactness: overlapped fetch == serial fetch, bitwise",
          flush=True)

    def bout(f):
        def g():
            t0 = time.perf_counter()
            f()
            return time.perf_counter() - t0
        return g

    res = paired_ab(bout(old_path), bout(lambda: be.predict_raw(ens, Xd)),
                    name_a="serial", name_b="overlap", reps=reps,
                    sleep_s=6.0, scale=rows / 1e6, unit="Mrows/s")
    print(json.dumps({"rows": rows,
                      "median_ratio_serial_over_overlap": res["median"],
                      "q1": res["q1"], "q3": res["q3"]}), flush=True)


if __name__ == "__main__":
    main()
