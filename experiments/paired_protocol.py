"""The round-4 on-chip A/B protocol, as a shared harness.

docs/PERF.md round-4 addendum: the tunnel's wallclock sits in bands
that persist across whole timing windows, so per-arm minimums — even
interleaved — can compare arms across bands and reverse a conclusion
run to run. The robust procedure: time the arms as PAIRS with the order
alternating every rep, spread the pairs over minutes (sleep between so
the band state evolves), and report the MEDIAN of per-rep ratios — a
statistic invariant to any band state shared within a pair.

Every A/B experiment in this directory routes through paired_ab() so a
future protocol amendment lands in exactly one place.
"""

from __future__ import annotations

import time

import numpy as np


def paired_ab(
    bout_a,
    bout_b,
    *,
    name_a: str = "A",
    name_b: str = "B",
    reps: int = 24,
    sleep_s: float = 4.0,
    scale: float | None = None,
    unit: str = "ms",
) -> dict:
    """Run `reps` order-alternating (bout_a, bout_b) pairs; print per-rep
    times and ratios; return {"ratios", "median", "q1", "q3"}.

    Each bout_* is a zero-arg callable returning the measured seconds for
    one timing bout (the caller owns iters-per-bout and device syncs).
    `scale` renders times as scale/seconds (e.g. rows -> Mrows/s via
    scale=rows/1e6); None prints milliseconds. The reported ratio is
    time_a / time_b (>1 means B is faster)."""
    ratios = []
    for rep in range(reps):
        order = ((name_a, bout_a), (name_b, bout_b))
        if rep % 2:
            order = order[::-1]
        ts = {}
        for name, bout in order:
            ts[name] = bout()
        ratios.append(ts[name_a] / ts[name_b])

        def fmt(t):
            return (f"{scale / t:8.1f} {unit}" if scale is not None
                    else f"{t * 1e3:7.1f} ms")
        print(f"rep {rep:02d}  {name_a} {fmt(ts[name_a])}  "
              f"{name_b} {fmt(ts[name_b])}  "
              f"ratio({name_a}/{name_b}) {ratios[-1]:.3f}", flush=True)
        if rep + 1 < reps:
            time.sleep(sleep_s)
    med = float(np.median(ratios))
    q1, q3 = (float(q) for q in np.percentile(ratios, [25, 75]))
    verdict = (f"{name_b} faster" if med > 1.02
               else f"{name_a} faster" if med < 0.98 else "parity")
    print(f"\nmedian paired ratio {name_a}/{name_b} = {med:.3f}  "
          f"IQR [{q1:.3f}, {q3:.3f}]  ({verdict})", flush=True)
    return {"ratios": ratios, "median": med, "q1": q1, "q3": q3}
