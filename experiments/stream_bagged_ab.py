"""Paired A/B: does bagging tax the streamed pipeline at scale?

Round 5 made fit_streaming accept sampling (stateless counter masks
computed ON DEVICE per chunk). The expected marginal cost is ~zero —
one uint32 hash + f32 multiply per row against a histogram matmul —
but through this tunnel only the paired per-rep-ratio protocol can
prove a null effect (docs/PERF.md). Each bout trains the full config-5
miniature (5M x 64 pre-binned shards, device chunk cache ON, 2 trees
depth 3) end to end; arms differ ONLY in cfg.subsample.

Usage: python -u experiments/stream_bagged_ab.py [rows_millions] [reps]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax  # noqa: E402

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

from ddt_tpu.backends import get_backend  # noqa: E402
from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.data import chunks as chunks_mod  # noqa: E402
from ddt_tpu.streaming import fit_streaming  # noqa: E402
from experiments.paired_protocol import paired_ab  # noqa: E402

FEATURES, N_CHUNKS, BINS, TREES, DEPTH = 64, 10, 63, 2, 3
WORK = "/tmp/ddt_stream_bagged_ab"


def main() -> None:
    rows = int(float(sys.argv[1]) * 1e6) if len(sys.argv) > 1 else 5_000_000
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 6
    print(f"platform={jax.default_backend()} rows={rows}", flush=True)
    shard_dir = os.path.join(WORK, "shards")
    shutil.rmtree(shard_dir, ignore_errors=True)
    chunks_mod.shard_stress_chunks(shard_dir, rows, N_CHUNKS,
                                   n_features=FEATURES, seed=7,
                                   n_bins=BINS)
    src = chunks_mod.directory_chunks(shard_dir)

    def bout_for(subsample):
        cfg = TrainConfig(n_trees=TREES, max_depth=DEPTH, n_bins=BINS,
                          backend="tpu", subsample=subsample, seed=3)
        be = get_backend(cfg)

        def bout():
            t0 = time.perf_counter()
            ens = fit_streaming(src, src.n_chunks, cfg, backend=be,
                                device_chunk_cache=True)
            dt = time.perf_counter() - t0
            assert ens.n_trees == TREES
            return dt

        bout()                           # warm: compiles + fills cache
        return bout

    det = bout_for(1.0)
    bag = bout_for(0.8)
    res = paired_ab(det, bag, name_a="det", name_b="bagged", reps=reps,
                    sleep_s=5.0, scale=rows * (DEPTH + 1) * TREES / 1e6,
                    unit="Mrow-visits/s")
    print(json.dumps({"rows": rows,
                      "median_ratio_det_over_bagged": res["median"],
                      "q1": res["q1"], "q3": res["q3"]}), flush=True)


if __name__ == "__main__":
    main()
