"""Paired A/B: packed-bitword descent vs the shipped one-hot descent.

Candidate from the round-4 verdict's predict formulation round: the
shipped binned descent (ops/predict._descend_comp) selects the path bit
per level with a [R, Tc, 2^d] one-hot compare + AND + any — ~3*(2^D - 1)
VPU ops per (row, tree) across the levels. The candidate packs each
level's comparison bits into ONE uint32 lane per (row, tree) (2^d <= 32
bits for depth <= 6), then descends with a shift+mask per level:
~(2^D - 1) packing ops + 2*D bit ops — roughly a third of the VPU work,
same exact semantics (bit-identical leaf indices, asserted before
timing).

Both arms time the FULL 10M x 1000 volume with a scalar on-device
reduction (no D2H — the fetch is identical either way and would only
dilute the compute ratio this A/B exists to measure), under the paired
per-rep-ratio protocol (experiments/paired_protocol.py — the only
statistic that survives the tunnel's bands).

Usage: python experiments/predict_ab_packed.py [rows_millions] [reps]
"""

from __future__ import annotations

import functools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                          # noqa: E402
import jax.numpy as jnp                             # noqa: E402

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402
from ddt_tpu.ops.predict import (                   # noqa: E402
    _descend_comp, _effective_arrays)
from ddt_tpu.utils.device import device_sync        # noqa: E402
from experiments.paired_protocol import paired_ab   # noqa: E402
from experiments.predict_phases import (            # noqa: E402
    B, DEPTH, F, N, N_INT, ROW_CHUNK, T, TREE_CHUNK, build_model,
    device_batch)


def _comp_matrix(eff_feat, eff_thr, Xc):
    """The shared bf16 comparison-matrix precompute (ops/predict P1)."""
    Tc = eff_feat.shape[0]
    foh = (
        eff_feat[:, :N_INT, None]
        == jnp.arange(F, dtype=jnp.int32)[None, None, :]
    ).astype(jnp.bfloat16)
    colval = jax.lax.dot_general(
        Xc.astype(jnp.bfloat16), foh.reshape(Tc * N_INT, F),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.bfloat16,
    ).reshape(Xc.shape[0], Tc, N_INT)
    return colval > eff_thr[None, :, :N_INT].astype(jnp.bfloat16)


def _descend_packed(eff_feat, eff_thr, Xc, max_depth):
    """Candidate: per-level bitword packing + shift/mask descent."""
    comp = _comp_matrix(eff_feat, eff_thr, Xc)
    R, Tc = comp.shape[:2]
    words = []
    for d in range(max_depth):
        lo, w = (1 << d) - 1, 1 << d
        c = comp[:, :, lo:lo + w].astype(jnp.uint32)
        word = jnp.zeros((R, Tc), jnp.uint32)
        for n in range(w):
            word = word | (c[:, :, n] << np.uint32(n))
        words.append(word)
    k = jnp.zeros((R, Tc), jnp.uint32)
    for d in range(max_depth):
        bit = (words[d] >> k) & jnp.uint32(1)
        k = 2 * k + bit
    return k.astype(jnp.int32)


def volume_fn(descend, fd, td, ld, vd):
    """Full-volume scorer with `descend` plugged in; scalar output."""
    from experiments.predict_phases import _padded_effective

    featp, thrp, valp = _padded_effective(fd, td, ld, vd)

    @jax.jit
    def run(Xd):
        Xp = Xd.astype(jnp.int32).reshape(-1, ROW_CHUNK, F)

        def row_body(acc_r, xrc):
            def tree_body(acc, args):
                f, t, v = args
                k = descend(f, t, xrc, DEPTH)
                W = v.shape[1]
                noh = (k[:, :, None]
                       == jnp.arange(W, dtype=jnp.int32)[None, None, :])
                vals = jnp.sum(jnp.where(noh, v[None, :, :], 0.0), axis=-1)
                return acc + vals.sum(), None

            acc, _ = jax.lax.scan(tree_body, jnp.float32(0),
                                  (featp, thrp, valp))
            return acc_r + acc, None

        out, _ = jax.lax.scan(row_body, jnp.float32(0), Xp)
        return out

    return run


def main():
    enable_persistent_compile_cache()
    rows_m = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    rows = int(rows_m * 1e6) // ROW_CHUNK * ROW_CHUNK
    feature, thr, is_leaf, leaf_value = build_model()
    fd, td = jax.device_put(feature), jax.device_put(thr)
    ld, vd = jax.device_put(is_leaf), jax.device_put(leaf_value)
    Xd = device_batch(rows)
    print(f"# rows={rows} platform={jax.default_backend()}", flush=True)

    # Exactness gate before any timing: identical leaf indices on a chunk.
    ef, et, _, _ = _effective_arrays(fd, td, ld, vd, DEPTH)
    xc = Xd[:ROW_CHUNK].astype(jnp.int32)
    ka = _descend_comp(ef[:TREE_CHUNK], et[:TREE_CHUNK], xc, DEPTH)
    kb = _descend_packed(ef[:TREE_CHUNK], et[:TREE_CHUNK], xc, DEPTH)
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(kb))
    print("# exactness: packed == one-hot descent, bitwise", flush=True)

    run_a = volume_fn(_descend_comp, fd, td, ld, vd)
    run_b = volume_fn(_descend_packed, fd, td, ld, vd)
    device_sync(run_a(Xd))
    device_sync(run_b(Xd))

    import time

    def bout(run):
        def f():
            t0 = time.perf_counter()
            device_sync(run(Xd))
            return time.perf_counter() - t0
        return f

    res = paired_ab(bout(run_a), bout(run_b), name_a="onehot",
                    name_b="packed", reps=reps, sleep_s=8.0,
                    scale=rows / 1e6, unit="Mrows/s")
    print(json.dumps({"rows": rows, "median_ratio_onehot_over_packed":
                      res["median"], "q1": res["q1"], "q3": res["q3"]}),
          flush=True)


if __name__ == "__main__":
    main()
