"""Sweep round 2: kill prologue HBM traffic; vary one-hot build strategy.

  v4  in-kernel A build, uint8 X streamed directly (int32 fallback),
      1-D grid, per-feature slab one-hot (like v0)
  v5  v4 + single-compare one-hot: prologue computes xoff = x + 256*f
      (fused, cheap); kernel does repeat(xoff, Bp) == global column iota
  v6  v4 + feature-group inner loop (static python loop over fgroups inside
      the kernel, smaller dot_generals)
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import _bins_pad, build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B, N = 1_000_000, 28, 255, 32
ITERS = 10


def _build_A(ni, gh, n_nodes, t):
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (t, n_nodes), 1)
    m = node_iota == ni  # [T, N] bool (ni broadcast from [T,1])
    zero = jnp.zeros((), jnp.float32)
    Ag = jnp.where(m, gh[:, 0:1], zero)
    Ah = jnp.where(m, gh[:, 1:2], zero)
    return jnp.concatenate([Ag, Ah], axis=1).astype(jnp.bfloat16)


# ---------------------------------------------------------------------- v4
def _kernel_v4(xb_ref, ni_ref, gh_ref, out_ref, *, n_feat, bins_pad,
               n_nodes):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:].astype(jnp.int32)
    t = x.shape[0]
    A = _build_A(ni_ref[:], gh_ref[:], n_nodes, t)
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, bins_pad), 1)
    slabs = [
        (x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
        for f in range(n_feat)
    ]
    oh = jnp.concatenate(slabs, axis=1)
    out_ref[:] += jax.lax.dot_general(
        A, oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------- v5
def _kernel_v5(xoff_ref, ni_ref, gh_ref, out_ref, *, n_feat, bins_pad,
               n_nodes):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xoff = xoff_ref[:]                                    # [T, F] int32
    t = xoff.shape[0]
    A = _build_A(ni_ref[:], gh_ref[:], n_nodes, t)
    xrep = jnp.repeat(xoff, bins_pad, axis=1)             # [T, F*Bp]
    col = jax.lax.broadcasted_iota(jnp.int32, (t, n_feat * bins_pad), 1)
    oh = (xrep == col).astype(jnp.bfloat16)
    out_ref[:] += jax.lax.dot_general(
        A, oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------- v6
def _kernel_v6(xb_ref, ni_ref, gh_ref, out_ref, *, n_feat, bins_pad,
               n_nodes, fg):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:].astype(jnp.int32)
    t = x.shape[0]
    A = _build_A(ni_ref[:], gh_ref[:], n_nodes, t)
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, bins_pad), 1)
    for j in range(0, n_feat, fg):
        slabs = [
            (x[:, f][:, None] == bin_iota).astype(jnp.bfloat16)
            for f in range(j, j + fg)
        ]
        oh = jnp.concatenate(slabs, axis=1)
        out_ref[:, j * bins_pad:(j + fg) * bins_pad] += jax.lax.dot_general(
            A, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _common(Xb, g, h, node_index, n_nodes, tile_r, x_dtype, offset):
    R_, F_ = Xb.shape
    bins_pad = _bins_pad(B)
    active = node_index >= 0
    ni = jnp.where(active, node_index, -1).astype(jnp.int32)[:, None]
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    gh = jnp.stack([gz, hz], axis=1).astype(jnp.float32)
    Xi = Xb.astype(x_dtype)
    if offset:
        Xi = Xi.astype(jnp.int32) + (
            jnp.arange(F_, dtype=jnp.int32) * bins_pad)[None, :]
    n_tiles = -(-R_ // tile_r)
    pad = n_tiles * tile_r - R_
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        ni = jnp.pad(ni, ((0, pad), (0, 0)), constant_values=-1)
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    return Xi, ni, gh, n_tiles, bins_pad


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "tile_r", "variant", "fg",
                                    "x_dtype"))
def hist_v456(Xb, g, h, node_index, n_nodes, tile_r, variant, fg=7,
              x_dtype=jnp.int32):
    R_, F_ = Xb.shape
    offset = variant == 5
    Xi, ni, gh, n_tiles, bins_pad = _common(
        Xb, g, h, node_index, n_nodes, tile_r, x_dtype, offset)
    if variant == 4:
        kern = functools.partial(_kernel_v4, n_feat=F_, bins_pad=bins_pad,
                                 n_nodes=n_nodes)
    elif variant == 5:
        kern = functools.partial(_kernel_v5, n_feat=F_, bins_pad=bins_pad,
                                 n_nodes=n_nodes)
    else:
        kern = functools.partial(_kernel_v6, n_feat=F_, bins_pad=bins_pad,
                                 n_nodes=n_nodes, fg=fg)
    out = pl.pallas_call(
        kern,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_r, F_), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, F_ * bins_pad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, F_ * bins_pad),
                                       jnp.float32),
    )(Xi, ni, gh)
    out = out.reshape(2, n_nodes, F_, bins_pad)[..., :B]
    return out.transpose(1, 2, 3, 0)


def bench(fn, name, ref=None):
    try:
        out = fn()
        s = device_sync(out)
    except Exception as e:  # noqa: BLE001
        print(f"{name:36s} FAILED: {type(e).__name__}: {str(e)[:120]}")
        return
    if ref is not None and not bool(jnp.allclose(out, ref, rtol=2e-2,
                                                 atol=2e-2)):
        print(f"{name:36s} WRONG RESULT (sum={s:.3f})")
        return
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn()
    device_sync(out)
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:36s} {dt*1e3:8.2f} ms  {R/dt/1e6:7.1f} Mrows/s")


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni = jnp.asarray(rng.integers(0, N, size=R).astype(np.int32))

    ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=512)
    device_sync(ref)

    for tr in (128, 192, 256):
        bench(lambda tr=tr: build_histograms_pallas(
            Xb, g, h, ni, N, B, tile_r=tr), f"v0 concat        tile_r={tr}",
            ref)
    for tr in (128, 256, 512):
        bench(lambda tr=tr: hist_v456(Xb, g, h, ni, N, tr, 4),
              f"v4 inkernelA     tile_r={tr}", ref)
        bench(lambda tr=tr: hist_v456(Xb, g, h, ni, N, tr, 4,
                                      x_dtype=jnp.uint8),
              f"v4 inkernelA/u8  tile_r={tr}", ref)
    for tr in (128, 256, 512):
        bench(lambda tr=tr: hist_v456(Xb, g, h, ni, N, tr, 5),
              f"v5 repeat-cmp    tile_r={tr}", ref)
    for tr, fg in ((256, 7), (256, 14), (512, 7), (512, 4)):
        bench(lambda tr=tr, fg=fg: hist_v456(Xb, g, h, ni, N, tr, 6, fg),
              f"v6 fgroup-loop   tile_r={tr} fg={fg}", ref)


if __name__ == "__main__":
    main()
