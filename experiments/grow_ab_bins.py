"""A/B: fused grow_tree dispatch time at 255 vs 64 bins (interleaved,
min-of-reps) — does the transposed kernel's standalone win survive the
full grow composition? Run on the real TPU."""
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ddt_tpu.backends.tpu import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

import numpy as np  # noqa: E402

from ddt_tpu.backends import get_backend  # noqa: E402
from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.utils.device import device_sync  # noqa: E402

rng = np.random.default_rng(0)
R = 1_000_000
g = rng.standard_normal(R).astype(np.float32)
h = rng.random(R).astype(np.float32)
for bins in (255, 64, 255, 64):
    cfg = TrainConfig(n_trees=1, max_depth=6, n_bins=bins, backend="tpu")
    be = get_backend(cfg)
    Xb = rng.integers(0, bins, (R, 28), dtype=np.uint8)
    data = be.upload(Xb)
    gd, hd = be._put_rows(g), be._put_rows(h)
    handle, delta = be.grow_tree(data, gd, hd)
    device_sync(delta)
    dt = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(5):
            handle, delta = be.grow_tree(data, gd, hd)
        device_sync(delta)
        dt = min(dt, (time.perf_counter() - t0) / 5)
    print(f"grow_tree bins={bins}: {dt * 1e3:.1f} ms/tree", flush=True)
