"""Sweep round 10: TRANSPOSED one-hot layout for the histogram kernel.

Sweep 9's finding: throughput is FLAT (~48-52 Mrows/s) across bin count
(255 vs 64 vs 32), one-hot lane width (Bp 256 vs 128) and operand dtype
(bf16 vs int8) — so the kernel is NOT bound by one-hot element count or
MXU rate. The invariant cost is per-(feature, tile) column handling: the
current form broadcasts x[:, f] as [T, 1] -> [T, Bp] across LANES, a VPU
relayout Mosaic executes per feature (28x per tile) — the same relayout
class that sank the in-kernel A-build (docs/PERF.md round 1) and the
hi/lo split (round 2).

Hypothesis: transpose the tile. With Xt [F, T] each feature is a
contiguous sublane ROW; the one-hot build becomes
(bin_iota[Bp, 1] == x_row[1, T]) -> [Bp, T], broadcasting along
SUBLANES (cheap row replication) instead of lanes. The dot contracts T:
[F*Bp, T] @ [T, 2N] -> [F*Bp, 2N]; same MXU flops, same VMEM budget.

Run on the real TPU:  python -u experiments/hist_sweep10.py
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ddt_tpu.utils.device import device_sync  # noqa: E402

R, F, N = 1_000_000, 28, 32


def _kernel_t(xt_ref, a_ref, out_ref, *, n_feat, bins_pad, oh_dtype):
    """Transposed form: xt [F, T] int32, a [T, 2N], out [F*bins_pad, 2N]."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xt = xt_ref[:]                                     # [F, T]
    tile_r = xt.shape[1]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (bins_pad, tile_r), 0)
    slabs = [
        (xt[f, :][None, :] == bin_iota).astype(oh_dtype)   # [Bp, T]
        for f in range(n_feat)
    ]
    oh = jnp.concatenate(slabs, axis=0)                # [F*Bp, T]
    out_ref[:] += jax.lax.dot_general(
        oh, a_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("n_bins", "bins_pad",
                                             "tile_r", "oh_dtype"))
def variant_t(Xt, g, h, ni, n_bins, bins_pad, tile_r, oh_dtype):
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    noh = jax.nn.one_hot(idx, N, dtype=jnp.float32)
    A = jnp.concatenate(
        [noh * gz[:, None], noh * hz[:, None]], axis=1
    ).astype(oh_dtype)                                 # [R, 2N]
    n_tiles = R // tile_r
    out = pl.pallas_call(
        functools.partial(_kernel_t, n_feat=F, bins_pad=bins_pad,
                          oh_dtype=oh_dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((F, tile_r), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * N), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((F * bins_pad, 2 * N), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F * bins_pad, 2 * N), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(Xt, A)
    return out


def run(name, fn, args, iters=10, reps=5):
    try:
        out = fn(*args)
        device_sync(out)
        dt = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            device_sync(out)
            dt = min(dt, (time.perf_counter() - t0) / iters)
        print(f"{name:44s} {R / dt / 1e6:8.1f} Mrows/s   "
              f"{dt * 1e3:7.2f} ms")
    except Exception as e:
        print(f"{name:44s} FAILED: {type(e).__name__}: {str(e)[:140]}")


if __name__ == "__main__":
    print(f"platform={jax.default_backend()}  shape {R}x{F}, N={N}")
    rng = np.random.default_rng(0)
    Xb = rng.integers(0, 255, (R, F), dtype=np.uint8)
    Xt = jax.device_put(np.ascontiguousarray(Xb.T).astype(np.int32))
    Xt64 = jax.device_put(
        np.ascontiguousarray((Xb % 64).T).astype(np.int32))
    g = jax.device_put(rng.standard_normal(R).astype(np.float32))
    h = jax.device_put(rng.random(R).astype(np.float32))
    ni = jax.device_put(rng.integers(0, N, R).astype(np.int32))

    for tile_r in (256, 512, 1024):
        run(f"T-form 255b Bp=256 bf16 tile={tile_r}", variant_t,
            (Xt, g, h, ni, 255, 256, tile_r, jnp.bfloat16))
    run("T-form 64b Bp=128 bf16 tile=512", variant_t,
        (Xt64, g, h, ni, 64, 128, 512, jnp.bfloat16))
    run("T-form 64b Bp=128 bf16 tile=1024", variant_t,
        (Xt64, g, h, ni, 64, 128, 1024, jnp.bfloat16))


# ---- integration questions: prologue transpose, shallow levels, tile 2048
@functools.partial(jax.jit, static_argnames=("n_bins", "bins_pad",
                                             "tile_r", "oh_dtype", "n"))
def variant_t_rowmajor(Xb, g, h, ni, n_bins, bins_pad, tile_r, oh_dtype,
                       n=N):
    """Production-shaped entry: row-major uint8 Xb, transpose in the XLA
    prologue (what the real kernel would do)."""
    active = ni >= 0
    idx = jnp.where(active, ni, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    noh = jax.nn.one_hot(idx, n, dtype=jnp.float32)
    A = jnp.concatenate(
        [noh * gz[:, None], noh * hz[:, None]], axis=1
    ).astype(oh_dtype)
    Xt = Xb.astype(jnp.int32).T                        # prologue transpose
    n_tiles = R // tile_r
    out = pl.pallas_call(
        functools.partial(_kernel_t, n_feat=F, bins_pad=bins_pad,
                          oh_dtype=oh_dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((F, tile_r), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * n), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((F * bins_pad, 2 * n), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F * bins_pad, 2 * n), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(Xt, A)
    return out


if __name__ == "__main__":
    import os
    if os.environ.get("SWEEP10B"):
        Xb64 = jax.device_put((Xb % 64))
        run("T-form 64b tile=2048 (pre-transposed)", variant_t,
            (Xt64, g, h, ni, 64, 128, 2048, jnp.bfloat16))
        run("T-form 64b tile=1024 ROW-MAJOR prologue", variant_t_rowmajor,
            (Xb64, g, h, ni, 64, 128, 1024, jnp.bfloat16))
        run("T-form 64b tile=2048 ROW-MAJOR prologue", variant_t_rowmajor,
            (Xb64, g, h, ni, 64, 128, 2048, jnp.bfloat16))
        ni1 = jax.device_put(np.zeros(R, np.int32))
        run("T-form 64b tile=1024 N=1 (shallow level)",
            lambda *a: variant_t_rowmajor(*a, n=1),
            (Xb64, g, h, ni1, 64, 128, 1024, jnp.bfloat16))
        run("T-form 255b tile=1024 ROW-MAJOR prologue", variant_t_rowmajor,
            (jax.device_put(Xb), g, h, ni, 255, 256, 1024, jnp.bfloat16))


if __name__ == "__main__":
    if os.environ.get("SWEEP10C"):
        Xb64 = jax.device_put((Xb % 64))
        for t in (1024, 1536, 2048):
            run(f"AB row-major 64b tile={t}", variant_t_rowmajor,
                (Xb64, g, h, ni, 64, 128, t, jnp.bfloat16), iters=15,
                reps=8)
