"""On-chip sweep of Pallas histogram kernel variants (perf scratchpad).

Run on the real TPU: python experiments/hist_sweep.py
Shapes = the headline bench shape (1M x 28 feat x 255 bins x 32 nodes).

Variants:
  v0   current library kernel (concat of per-feature one-hot slabs)
  v1   fused one-hot: broadcast-compare [T,F,Bp] -> reshape (no concat copies)
  v2   2-D grid (row tiles x feature groups): smaller OH per step -> larger
       tile_r -> larger K per matmul, fewer grid steps
  v3   v2 + weighted node one-hot A built in-kernel (saves ~256 MB/build of
       HBM traffic for the A operand)
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from ddt_tpu.ops.hist_pallas import _bins_pad, build_histograms_pallas
from ddt_tpu.utils.device import device_sync

R, F, B, N = 1_000_000, 28, 255, 32
ITERS = 10


# ---------------------------------------------------------------- v1: fused
def _kernel_v1(xb_ref, a_ref, out_ref, *, n_feat, bins_pad):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                                        # [T, F] int32
    t = x.shape[0]
    iota3 = jax.lax.broadcasted_iota(jnp.int32, (t, n_feat, bins_pad), 2)
    oh = (x[:, :, None] == iota3).astype(jnp.bfloat16).reshape(
        t, n_feat * bins_pad)
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins", "tile_r"))
def hist_v1(Xb, g, h, node_index, n_nodes, n_bins, tile_r):
    R_, F_ = Xb.shape
    bins_pad = _bins_pad(n_bins)
    active = node_index >= 0
    idx = jnp.where(active, node_index, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    node_oh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)
    A = jnp.concatenate(
        [node_oh * gz[:, None], node_oh * hz[:, None]], axis=1
    ).astype(jnp.bfloat16)
    Xi = Xb.astype(jnp.int32)
    n_tiles = -(-R_ // tile_r)
    pad = n_tiles * tile_r - R_
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A = jnp.pad(A, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_kernel_v1, n_feat=F_, bins_pad=bins_pad),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_r, F_), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * n_nodes), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, F_ * bins_pad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, F_ * bins_pad),
                                       jnp.float32),
    )(Xi, A)
    out = out.reshape(2, n_nodes, F_, bins_pad)[..., :n_bins]
    return out.transpose(1, 2, 3, 0)


# ------------------------------------------------------------- v2: 2-D grid
def _kernel_v2(xb_ref, a_ref, out_ref, *, fg, bins_pad):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                                        # [T, fg] int32
    t = x.shape[0]
    iota3 = jax.lax.broadcasted_iota(jnp.int32, (t, fg, bins_pad), 2)
    oh = (x[:, :, None] == iota3).astype(jnp.bfloat16).reshape(
        t, fg * bins_pad)
    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "n_bins", "tile_r", "fg"))
def hist_v2(Xb, g, h, node_index, n_nodes, n_bins, tile_r, fg):
    R_, F_ = Xb.shape
    assert F_ % fg == 0
    bins_pad = _bins_pad(n_bins)
    active = node_index >= 0
    idx = jnp.where(active, node_index, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    node_oh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)
    A = jnp.concatenate(
        [node_oh * gz[:, None], node_oh * hz[:, None]], axis=1
    ).astype(jnp.bfloat16)
    Xi = Xb.astype(jnp.int32)
    n_tiles = -(-R_ // tile_r)
    pad = n_tiles * tile_r - R_
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A = jnp.pad(A, ((0, pad), (0, 0)))
    n_fg = F_ // fg
    out = pl.pallas_call(
        functools.partial(_kernel_v2, fg=fg, bins_pad=bins_pad),
        grid=(n_tiles, n_fg),
        in_specs=[
            pl.BlockSpec((tile_r, fg), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2 * n_nodes), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, fg * bins_pad),
                               lambda i, j: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, F_ * bins_pad),
                                       jnp.float32),
    )(Xi, A)
    out = out.reshape(2, n_nodes, F_, bins_pad)[..., :n_bins]
    return out.transpose(1, 2, 3, 0)


# ----------------------------------------------- v3: v2 + in-kernel A build
def _kernel_v3(xb_ref, ni_ref, gh_ref, out_ref, *, fg, bins_pad, n_nodes):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                                        # [T, fg] int32
    t = x.shape[0]
    ni = ni_ref[:]                                       # [T, 1] int32
    gh = gh_ref[:]                                       # [T, 2] f32
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (t, n_nodes), 1)
    m = (node_iota == ni).astype(jnp.float32)            # [T, N]
    A = jnp.concatenate(
        [m * gh[:, 0:1], m * gh[:, 1:2]], axis=1
    ).astype(jnp.bfloat16)                               # [T, 2N]
    iota3 = jax.lax.broadcasted_iota(jnp.int32, (t, fg, bins_pad), 2)
    oh = (x[:, :, None] == iota3).astype(jnp.bfloat16).reshape(
        t, fg * bins_pad)
    out_ref[:] += jax.lax.dot_general(
        A, oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "n_bins", "tile_r", "fg"))
def hist_v3(Xb, g, h, node_index, n_nodes, n_bins, tile_r, fg):
    R_, F_ = Xb.shape
    assert F_ % fg == 0
    bins_pad = _bins_pad(n_bins)
    active = node_index >= 0
    ni = jnp.where(active, node_index, -1).astype(jnp.int32)[:, None]
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)
    gh = jnp.stack([gz, hz], axis=1).astype(jnp.float32)  # [R, 2]
    Xi = Xb.astype(jnp.int32)
    n_tiles = -(-R_ // tile_r)
    pad = n_tiles * tile_r - R_
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        ni = jnp.pad(ni, ((0, pad), (0, 0)), constant_values=-1)
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    n_fg = F_ // fg
    out = pl.pallas_call(
        functools.partial(_kernel_v3, fg=fg, bins_pad=bins_pad,
                          n_nodes=n_nodes),
        grid=(n_tiles, n_fg),
        in_specs=[
            pl.BlockSpec((tile_r, fg), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 2), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, fg * bins_pad),
                               lambda i, j: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, F_ * bins_pad),
                                       jnp.float32),
    )(Xi, ni, gh)
    out = out.reshape(2, n_nodes, F_, bins_pad)[..., :n_bins]
    return out.transpose(1, 2, 3, 0)


def bench(fn, name, ref=None):
    try:
        out = fn()
        s = device_sync(out)
    except Exception as e:  # noqa: BLE001
        print(f"{name:34s} FAILED: {type(e).__name__}: {str(e)[:140]}")
        return
    if ref is not None:
        ok = bool(jnp.allclose(out, ref, rtol=2e-2, atol=2e-2))
        if not ok:
            print(f"{name:34s} WRONG RESULT (sum={s:.3f})")
            return
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn()
    device_sync(out)
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:34s} {dt*1e3:8.2f} ms  {R/dt/1e6:7.1f} Mrows/s")


def main():
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) + 0.5).astype(np.float32))
    ni = jnp.asarray(rng.integers(0, N, size=R).astype(np.int32))

    ref = build_histograms_pallas(Xb, g, h, ni, N, B, tile_r=512)
    device_sync(ref)

    for tr in (256, 512, 768):
        bench(lambda tr=tr: build_histograms_pallas(
            Xb, g, h, ni, N, B, tile_r=tr), f"v0 concat      tile_r={tr}", ref)
    for tr in (256, 512, 768):
        bench(lambda tr=tr: hist_v1(Xb, g, h, ni, N, B, tr),
              f"v1 fused       tile_r={tr}", ref)
    for tr, fg in ((512, 7), (1024, 7), (2048, 7), (4096, 7),
                   (1024, 14), (2048, 14), (2048, 4), (4096, 4)):
        bench(lambda tr=tr, fg=fg: hist_v2(Xb, g, h, ni, N, B, tr, fg),
              f"v2 2Dgrid      tile_r={tr} fg={fg}", ref)
    for tr, fg in ((1024, 7), (2048, 7), (4096, 7), (2048, 14), (4096, 4),
                   (8192, 4), (8192, 2)):
        bench(lambda tr=tr, fg=fg: hist_v3(Xb, g, h, ni, N, B, tr, fg),
              f"v3 inkernel-A  tile_r={tr} fg={fg}", ref)


if __name__ == "__main__":
    main()
