#!/usr/bin/env bash
# Fetch the UCI Covertype dataset (BASELINE config 2: 7-class, depth-8,
# 500 trees). 581k rows, 54 features, label (1..7) in the LAST column —
# the csv loader normalizes 1-based classes to 0-based automatically.
#
# UNTESTED IN CI: no network in the build environment (docs/REAL_DATA.md).
set -euo pipefail

OUT_DIR="${1:-data}"
URL="https://archive.ics.uci.edu/ml/machine-learning-databases/covtype/covtype.data.gz"

mkdir -p "$OUT_DIR"
if [ -f "$OUT_DIR/covtype.data.gz" ]; then
    echo "already present: $OUT_DIR/covtype.data.gz"
    exit 0
fi
echo "fetching Covertype (~11 MB) -> $OUT_DIR/covtype.data.gz"
curl -fL --retry 3 -o "$OUT_DIR/covtype.data.gz.part" "$URL"
mv "$OUT_DIR/covtype.data.gz.part" "$OUT_DIR/covtype.data.gz"
echo "done. Covertype config run:"
echo "  python -m ddt_tpu.cli train --backend=tpu --data=$OUT_DIR/covtype.data.gz \\"
echo "      --label-col=last --loss=softmax --trees=500 --depth=8 --bins=255"
