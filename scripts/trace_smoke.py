#!/usr/bin/env python
"""Tier-1-safe flight-recorder smoke: train 2 rounds on a 2-partition
CPU mesh with a run log, fabricate a second host's log (clock skewed),
merge the two, export a Perfetto trace, and assert it parses with
partition lanes present.

tests/test_flight_recorder.py exercises each stage with real asserts;
this script is the one-command end-to-end witness
(docs/OBSERVABILITY.md). Exit 0 iff the whole pipeline holds.
"""

import copy
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    import numpy as np

    from ddt_tpu import api
    from ddt_tpu.telemetry import merge, perfetto, report
    from ddt_tpu.telemetry.events import RunLog

    rng = np.random.default_rng(0)
    Xb = rng.integers(0, 31, size=(2048, 7), dtype=np.uint8)
    y = (Xb[:, 0] > 15).astype(np.float32)

    with tempfile.TemporaryDirectory(prefix="ddt_trace_smoke_") as td:
        p0 = os.path.join(td, "host0.jsonl")
        with RunLog(p0) as rl:
            api.train(Xb, y, binned=True, n_trees=2, max_depth=3,
                      n_bins=31, backend="tpu", n_partitions=2,
                      run_log=rl)
        ev0 = report.read_events(p0)
        if not any(e["event"] == "partition_phases" for e in ev0):
            print("trace smoke: mesh run emitted no partition_phases",
                  file=sys.stderr)
            return 1

        # Fabricated host 1: same run, clock 3 s ahead — the merge must
        # estimate the offset away and interleave the rounds.
        p1 = os.path.join(td, "host1.jsonl")
        with open(p1, "w", encoding="utf-8") as f:
            for e in ev0:
                e2 = copy.deepcopy(e)
                e2["t"] += 3.0
                e2["host"] = 1
                f.write(json.dumps(e2) + "\n")

        merged = merge.merge_paths([p0, p1])
        if len(merged) != 2 * len(ev0):
            print("trace smoke: merge lost events", file=sys.stderr)
            return 1

        out = os.path.join(td, "trace.json")
        n = perfetto.write_trace(merged, out)
        with open(out, encoding="utf-8") as f:
            trace = json.load(f)              # asserts it parses
        recs = trace["traceEvents"]
        lanes = {r["tid"] for r in recs
                 if r["ph"] == "X" and r["name"].startswith("ddt:")}
        pids = {r["pid"] for r in recs}
        ok = (len(recs) == n and trace["displayTimeUnit"] == "ms"
              and lanes and pids == {0, 1}
              and all(r["dur"] >= 0 for r in recs if r["ph"] == "X"))
        if not ok:
            print(f"trace smoke: malformed trace (lanes={lanes}, "
                  f"pids={pids})", file=sys.stderr)
            return 1
        print(json.dumps({
            "smoke": "trace", "ok": True, "events": len(merged),
            "trace_events": n, "partition_lanes": sorted(lanes),
            "hosts": sorted(pids),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
