#!/usr/bin/env python
"""Tier-1-safe telemetry smoke: train 2 rounds on synthetic data with a
run log in a tmpdir, then render it with the report subcommand.

`make report` runs this; tests/test_telemetry.py runs main() in-process.
Exit 0 iff the round trip holds: the log is schema-valid, the report
renders, and the core events (manifest, rounds, counters, run_end) are
present with a nonzero jit-recompile counter.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ddt_tpu.cli import main as cli_main
    from ddt_tpu.telemetry import report

    with tempfile.TemporaryDirectory(prefix="ddt_smoke_") as td:
        log = os.path.join(td, "run.jsonl")
        model = os.path.join(td, "ens.npz")
        rc = cli_main([
            "train", "--backend=tpu", "--dataset=higgs", "--rows=3001",
            "--trees=2", "--depth=3", "--bins=31", "--valid-frac=0.2",
            f"--run-log={log}", f"--out={model}",
        ])
        if rc != 0:
            print(f"telemetry smoke: train exited {rc}", file=sys.stderr)
            return 1

        events = report.read_events(log)          # validates every record
        got = {e["event"] for e in events}
        need = {"run_manifest", "round", "counters", "run_end"}
        if not need <= got:
            print(f"telemetry smoke: missing events {need - got}",
                  file=sys.stderr)
            return 1
        summary = report.summarize(events)
        if not summary["counters"].get("jit_compiles"):
            print("telemetry smoke: jit_compiles counter is zero",
                  file=sys.stderr)
            return 1
        rc = cli_main(["report", "--log", log])
        if rc != 0:
            print(f"telemetry smoke: report exited {rc}", file=sys.stderr)
            return 1
        print(json.dumps({"smoke": "telemetry", "ok": True,
                          "events": sorted(got),
                          "rounds": summary["n_round_records"],
                          "jit_compiles":
                              summary["counters"]["jit_compiles"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
