"""Registry smoke (`make registry-smoke`): compile once, serve anywhere
— the ISSUE 9 acceptance witness, end to end on CPU (docs/REGISTRY.md).

Flow, across two REAL processes:

1. (this process) a tiny model trains with a run log, saves with its
   embedded manifest, and is pushed through the real CLI
   (`registry push`) — the artifact event lands in the same run log;
2. offline reference scores for a fixed request set are computed with
   in-process `api.predict`;
3. a COLD python process (fresh interpreter, empty jax caches) restores
   the artifact through the zero-retrace loader, publishes it in a
   ServeEngine, and serves every bucket shape plus an oversize request:
   - every score BIT-matches the exporting process's reference,
   - the jit_compiles counter moves ZERO during serving (all compiles
     happened at load/warmup — the counter delta is emitted into the
     run log as the witness the acceptance criteria name),
   - the restore mode is aot-* (the witness is not vacuous);
4. (back here) `cli report` renders the run log: the registry section
   shows the push + load cross-referenced to THIS run's run_id, and
   the serve_latency window carries the artifact digest.

Exit 0 = all hold.
"""

import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MAX_BATCH = 16
REQUEST_SIZES = (1, 2, 3, 8, MAX_BATCH, 3 * MAX_BATCH + 5)


def cold_serve(root: str, ref: str, io_path: str, run_log: str) -> int:
    """The cold-process half: restore -> publish -> serve -> witness.
    Runs in a FRESH interpreter (no training ever happened here; the
    only route to a scoring program is the artifact's AOT blobs)."""
    import numpy as np

    from ddt_tpu.config import TrainConfig
    from ddt_tpu.registry.loader import load_servable
    from ddt_tpu.serve.engine import ServeEngine
    from ddt_tpu.telemetry import counters as tc
    from ddt_tpu.telemetry.events import RunLog

    tc.install_jax_listener()
    with np.load(io_path) as z:
        X = np.asarray(z["X"])
        want = np.asarray(z["want"])
    rl = RunLog(run_log)
    report = load_servable(root, ref, quantize=False, run_log=rl)
    assert report.mode == "aot-f32", (
        f"restore fell back to {report.mode}; the zero-retrace witness "
        "would be vacuous")
    before_publish = tc.snapshot()["jit_compiles"]
    cfg = TrainConfig(backend="tpu",
                      loss=report.model.ens.loss)
    engine = ServeEngine(report.model, cfg, max_wait_ms=2.0,
                        max_batch=MAX_BATCH, run_log=rl)
    warm_compiles = tc.snapshot()["jit_compiles"]
    serving_start = tc.snapshot()
    got = []
    try:
        for n in REQUEST_SIZES:
            got.append(np.asarray(engine.predict(X[:n])))
        # The counters event IS the run-log witness: jit_compiles over
        # the serving window, exactly zero when every bucket shape was
        # pre-traced at export and compiled once at load.
        delta = tc.delta(serving_start)
        rl.emit("counters", **delta,
                device_peak_bytes=tc.device_peak_bytes(),
                host_peak_rss_bytes=tc.host_peak_rss_bytes())
        engine.emit_latency(reset=True)
    finally:
        engine.close()
    off = 0
    for n, g in zip(REQUEST_SIZES, got):
        w = want[off:off + n]
        assert np.array_equal(w, g), (
            f"cold-process scores diverge from the exporting process at "
            f"request size {n}")
        off += n
    out = {
        "ok": True,
        "digest": report.digest,
        "mode": report.mode,
        "compiles_at_load": warm_compiles,
        "compiles_serving": delta["jit_compiles"],
        "requests": len(REQUEST_SIZES),     # engine counts requests,
        "rows": int(sum(REQUEST_SIZES)),    # not rows
    }
    assert warm_compiles > before_publish or warm_compiles > 0, \
        "compile counter never moved — the witness is not counting"
    assert delta["jit_compiles"] == 0, (
        f"{delta['jit_compiles']} jit compiles DURING serving — the "
        "zero-retrace contract broke")
    print(json.dumps(out), flush=True)
    return 0


def main() -> int:
    import numpy as np

    from ddt_tpu import api
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data import datasets
    from ddt_tpu.telemetry import report as tele_report

    out = {"cmd": "registry_smoke"}
    with tempfile.TemporaryDirectory() as td:
        run_log = os.path.join(td, "run.jsonl")
        model = os.path.join(td, "model.npz")
        root = os.path.join(td, "registry")
        io_path = os.path.join(td, "io.npz")

        # 1. train (with a run log: the manifest's run_id is the
        # provenance key everything downstream joins on) + save.
        X, y = datasets.synthetic_binary(3000, seed=11)
        res = api.train(X, y, n_trees=6, max_depth=3, n_bins=31,
                        backend="tpu", log_every=10**9, run_log=run_log)
        assert res.run_id, "training with a run log must derive a run_id"
        res.save(model)

        # 2. push through the REAL CLI, artifact event into the same log.
        proc = subprocess.run(
            [sys.executable, "-m", "ddt_tpu.cli", "registry",
             "--registry", root, "push", "--model", model,
             "--name", "smoke", "--max-batch", str(MAX_BATCH),
             "--run-log", run_log],
            cwd=REPO, capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        push = json.loads(proc.stdout.strip().splitlines()[-1])
        assert push["version"] == 1
        out["digest"] = push["digest"]

        # 3. offline reference scores for the cold process to bit-match.
        cfg = TrainConfig(backend="tpu", n_bins=31)
        rows = np.concatenate([X[:n] for n in REQUEST_SIZES])
        want = api.predict(res.ensemble, rows, mapper=res.mapper, cfg=cfg)
        np.savez(io_path, X=X[:max(REQUEST_SIZES)], want=want)

        # 4. the cold process: fresh interpreter, registry-only restore.
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cold", root,
             "smoke@1", io_path, run_log],
            cwd=REPO, capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        cold = json.loads(proc.stdout.strip().splitlines()[-1])
        assert cold["ok"] and cold["compiles_serving"] == 0
        assert cold["digest"] == push["digest"]
        out.update({k: cold[k] for k in
                    ("mode", "compiles_at_load", "compiles_serving",
                     "requests")})

        # 5. the run log tells the whole story through `cli report`.
        events = tele_report.read_events(run_log)
        summary = tele_report.summarize(events)
        reg = summary["registry"]
        assert reg and reg["pushes"] == 1 and reg["loads"] == 1
        push_ev = next(e for e in reg["events"] if e["action"] == "push")
        assert push_ev["same_run"], (
            "the pushed artifact's run_id did not join back to this "
            "run's manifest")
        assert reg["digests"] == [push["digest"]]
        sl = summary["serving"]
        assert sl and sl["requests"] == cold["requests"]
        witness = [e for e in events
                   if e["event"] == "counters"][-1]["jit_compiles"]
        assert witness == 0, witness
        rendered = tele_report.render(summary)
        assert "registry:" in rendered and push["digest"] in rendered
        out["report_lines"] = len(rendered.splitlines())

    out["ok"] = True
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--cold":
        sys.exit(cold_serve(*sys.argv[2:6]))
    sys.exit(main())
