"""Billion-row-shape smoke (ISSUE 11, ROADMAP item 2): host-sharded
streamed training end to end at a scaled-down out-of-core config.

The production claim: a 1B-row x 1k-feature dataset trains through the
host-sharded streamed path with FLAT per-host memory — each host reads
only its own chunk sub-shards (data.chunks.HostShardedChunks), the
device array assembles from per-process blocks
(TPUDevice.upload_row_shards), and nothing ever holds the dataset. This
smoke witnesses the same pipeline at CPU scale, with the flatness
stated the only way RSS can state it honestly (the test_stream_scale
methodology): peak memory must track the CHUNK size, not the DATASET
size. Two fresh worker processes train at the SAME chunk size with the
dataset grown 6x; the peak-RSS-over-baseline deltas — read from each
run log's `host_peak_rss_bytes` counter, the telemetry witness — must
not move by anywhere near the dataset growth. The parent then
materializes the SMALL dataset once, trains the in-memory comparator
on a 2-partition mesh, and asserts streamed == in-memory split
agreement (structure bitwise at this fixed seed — the partition-count
invariance contract; leaves float-close per the documented
chunked-accumulation seam).

Run: JAX_PLATFORMS=cpu python scripts/bigdata_smoke.py   (make
bigdata-smoke). Scale knobs for the real shape: --rows 1000000000
--features 1024 --chunks 512 --shards-per-chunk <hosts> on a pod, one
process per host.
"""

import argparse
import json
import os
import resource
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BINS, DEPTH, TREES = 31, 4, 2
# The RSS workers run SINGLE-device (test_stream_scale's
# methodology): on CPU, mesh device arenas scale with in-flight
# buffers and would drown the held-data signature in jitter. The
# host-sharded source + grouped sub-shard reads are exercised
# identically; the 2-partition MESH correctness runs in the
# parent's split-agreement phase (and throughout tier-1).
PARTITIONS = 2


def _rss_bytes() -> int:
    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(ru) if sys.platform == "darwin" else int(ru) * 1024


def _worker(args) -> int:
    """One fresh-process training run: write shards O(chunk), train the
    host-sharded streamed path, report the run log's RSS counter."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data import chunks as chunks_mod
    from ddt_tpu.streaming import fit_streaming
    from ddt_tpu.telemetry.events import RunLog

    jax.devices()                     # platform init lands in the baseline
    rss_baseline = _rss_bytes()

    shard_dir = os.path.join(args.work_dir, "shards")
    n_files = args.chunks * args.shards_per_chunk
    chunk_rows = chunks_mod.shard_stress_chunks(
        shard_dir, args.rows, n_files, n_features=args.features, seed=5,
        n_bins=BINS)
    rss_sharded = _rss_bytes()

    cfg = TrainConfig(n_trees=TREES, max_depth=DEPTH, n_bins=BINS,
                      backend="tpu", seed=5)
    be = get_backend(cfg)
    src = chunks_mod.host_sharded_chunks(
        shard_dir, shards_per_chunk=args.shards_per_chunk)
    rl = RunLog()
    # Device cache OFF: on this CPU platform the "device" is host RAM,
    # so a cached run would legitimately hold the dataset and mask
    # exactly the flatness this smoke exists to witness.
    ens = fit_streaming(src, src.n_chunks, cfg, backend=be,
                        device_chunk_cache=False, run_log=rl)
    counters = rl.events("counters")
    assert counters, "run log carries no counters event"
    peak = counters[-1]["host_peak_rss_bytes"]
    assert peak is not None, "host_peak_rss_bytes unavailable"
    if args.save_model:
        ens.save(args.save_model)
    print(json.dumps({
        "rows": args.rows, "chunks": args.chunks,
        "chunk_mb": chunk_rows * args.shards_per_chunk
        * args.features / 1e6,
        "dataset_binned_mb": args.rows * args.features / 1e6,
        "rss_baseline_mb": round(rss_baseline / 1e6, 1),
        "rss_sharded_mb": round(rss_sharded / 1e6, 1),
        "host_peak_rss_mb": round(peak / 1e6, 1),
        "delta_mb": round((peak - rss_baseline) / 1e6, 1),
    }))
    return 0


def _run_worker(rows, chunks, base_args, work_dir, save_model=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)          # worker pins cpu itself
    # Single-device workers (see PARTITIONS note above): an inherited
    # multi-device conftest XLA_FLAGS would add ~100 MB of per-device
    # arena jitter to exactly the number this smoke asserts on.
    env.pop("XLA_FLAGS", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           f"--rows={rows}", f"--chunks={chunks}",
           f"--features={base_args.features}",
           f"--shards-per-chunk={base_args.shards_per_chunk}",
           f"--work-dir={work_dir}"]
    if save_model:
        cmd.append(f"--save-model={save_model}")
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=1800, env=env)
    assert out.returncode == 0, out.stdout + "\n" + out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=120_000,
                    help="SMALL-arm rows (the big arm grows this 6x at "
                         "fixed chunk size)")
    ap.add_argument("--features", type=int, default=256)
    ap.add_argument("--chunks", type=int, default=4,
                    help="logical streaming chunks (small arm)")
    ap.add_argument("--shards-per-chunk", type=int, default=2,
                    help="sub-shards per logical chunk (= hosts at scale)")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--save-model", default=None)
    args = ap.parse_args()
    if args.worker:
        return _worker(args)

    work = tempfile.mkdtemp(prefix="bigdata_smoke_")
    model = os.path.join(work, "streamed.npz")
    small = _run_worker(args.rows, args.chunks, args,
                        os.path.join(work, "small"), save_model=model)
    big = _run_worker(args.rows * 6, args.chunks * 6, args,
                      os.path.join(work, "big"))

    # FLATNESS: 6x the dataset at fixed chunk size must not move the
    # peak by anywhere near the dataset growth (~154 MB binned here if
    # any path held it; measured growth ~40 MB of allocator high-water).
    # 120 MB of headroom absorbs queue-depth/arena jitter under CPU
    # contention while staying under the held-data signature — the
    # test_stream_scale calibration.
    d_small = small["host_peak_rss_mb"] - small["rss_baseline_mb"]
    d_big = big["host_peak_rss_mb"] - big["rss_baseline_mb"]
    grew = d_big - d_small
    dataset_growth = (big["dataset_binned_mb"]
                      - small["dataset_binned_mb"])
    assert dataset_growth > 140, "arms too small to witness flatness"
    assert grew < 120, (small, big)

    # Split agreement: materialize the SMALL dataset once, train the
    # identical config in-memory, compare against the worker's saved
    # streamed ensemble.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data import chunks as chunks_mod
    from ddt_tpu.driver import Driver
    from ddt_tpu.models.tree import TreeEnsemble

    shard_dir = os.path.join(work, "small", "shards")
    src = chunks_mod.directory_chunks(shard_dir)
    parts = [src(c) for c in range(src.n_chunks)]
    Xb = np.concatenate([p[0] for p in parts])
    y = np.concatenate([p[1] for p in parts])
    del parts
    cfg = TrainConfig(n_trees=TREES, max_depth=DEPTH, n_bins=BINS,
                      backend="tpu", n_partitions=PARTITIONS, seed=5)
    ens_mem = Driver(get_backend(cfg), cfg, log_every=10 ** 9).fit(Xb, y)
    ens_streamed = TreeEnsemble.load(model)
    for k in ("feature", "threshold_bin", "is_leaf"):
        np.testing.assert_array_equal(
            getattr(ens_mem, k), getattr(ens_streamed, k), err_msg=k)
    np.testing.assert_allclose(ens_mem.leaf_value,
                               ens_streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)
    print(json.dumps({
        "small": small, "big": big,
        "rss_growth_mb": round(grew, 1),
        "dataset_growth_mb": round(dataset_growth, 1),
        "splits_compared": int(
            (~ens_mem.is_leaf & (ens_mem.feature >= 0)).sum()),
        "split_agreement": 1.0,
        "ok": True,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
