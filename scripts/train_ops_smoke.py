#!/usr/bin/env python
"""Training operations plane smoke (ISSUE 20, docs/OBSERVABILITY.md
"The training operations plane"): a REAL `cli train --status-port`
subprocess is scraped twice mid-run over a live socket, witnessing

1. the statusd boot line and a strictly advancing round counter across
   the two scrapes (live progress, not a post-hoc summary);
2. the /metrics exposition round-tripping through the shared parser
   (telemetry/exposition.py), with the train-plane series present;
3. `report progress` rendering the run's heartbeats from its run log;
4. the zero-overhead contract, measured on a clean (unscraped) pair:
   --status-port enabled vs disabled, same config, must land within
   1.05x of each other (compile time excluded via each run log's own
   counters — compile noise would otherwise dwarf the signal).

`make train-ops-smoke` runs this. Exit 0 iff all four hold.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS, TREES = 4001, 14


def _train_args(out, log=None, status_port=None):
    args = [sys.executable, "-m", "ddt_tpu.cli", "train",
            "--backend=tpu", "--dataset=higgs", f"--rows={ROWS}",
            f"--trees={TREES}", "--depth=3", "--bins=31",
            "--fused-block-rounds=1", "--checkpoint-every=4",
            f"--out={out}"]
    if log is not None:
        args.append(f"--run-log={log}")
    if status_port is not None:
        args.append(f"--status-port={status_port}")
    return args


def _scrape(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode("utf-8")


def _run_train(args, env, tag):
    """Run one train subprocess, retrying the environment's pre-existing
    ~10% teardown segfault (SIGSEGV in the import machinery during the
    final save_model, AFTER training and the run log complete — happens
    on plain `cli train` with no smoke harness involved at all). A
    retry is loud; a persistent failure still fails the smoke."""
    for attempt in range(3):
        r = subprocess.run(args, stdout=subprocess.DEVNULL,
                           stderr=subprocess.PIPE, text=True, env=env,
                           timeout=600)
        if r.returncode == 0:
            return True
        print(f"train-ops smoke: {tag} train attempt {attempt + 1} "
              f"died rc={r.returncode} (known infra flake if -11); "
              f"retrying", file=sys.stderr)
    print(f"train-ops smoke: {tag} train failed 3 times:\n"
          f"{r.stderr[-2000:]}", file=sys.stderr)
    return False


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ, PYTHONUNBUFFERED="1")
    from ddt_tpu.cli import main as cli_main
    from ddt_tpu.telemetry import report
    from ddt_tpu.telemetry.diffing import COUNTER_DIRECTIONS
    from ddt_tpu.telemetry.exposition import parse_exposition

    # The new counters must be registered for diffing before anything
    # else is worth measuring — an unregistered counter silently
    # vanishes from `report diff`.
    for name in ("train_rounds", "train_heartbeats"):
        if name not in COUNTER_DIRECTIONS:
            print(f"train-ops smoke: {name} missing from "
                  "COUNTER_DIRECTIONS", file=sys.stderr)
            return 1

    with tempfile.TemporaryDirectory(prefix="ddt_ops_smoke_") as td:

        # ---- enabled run: live subprocess, scraped mid-run ---------- #
        # Retried like _run_train: the environment's teardown segfault
        # can also kill this child at exit, after the scrapes and the
        # run log have already succeeded.
        log = rounds_seen = health = wall_on = None
        for attempt in range(3):
            log = os.path.join(td, f"run_{attempt}.jsonl")
            t0 = time.perf_counter()
            proc = subprocess.Popen(
                _train_args(os.path.join(td, "on.npz"), log=log,
                            status_port=0),
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env)
            boot = json.loads(proc.stdout.readline())
            port = boot["statusd"]["port"]
            print(json.dumps({"statusd_boot": boot["statusd"]}))

            rounds_seen = []
            deadline = time.time() + 300
            while time.time() < deadline and proc.poll() is None:
                try:
                    series = parse_exposition(_scrape(port, "/metrics"))
                    rnd = series.get("ddt_train_round", {}).get(())
                    if rnd and (not rounds_seen
                                or rnd > rounds_seen[-1]):
                        rounds_seen.append(rnd)
                    if len(rounds_seen) >= 2 and rnd < TREES:
                        break  # two live MID-RUN scrapes, advancing
                except OSError:
                    pass
                time.sleep(0.05)
            if len(rounds_seen) < 2:
                print(f"train-ops smoke: never saw two advancing "
                      f"mid-run scrapes (saw {rounds_seen})",
                      file=sys.stderr)
                proc.kill()
                return 1
            health = json.loads(_scrape(port, "/healthz"))
            if health.get("round", 0) < rounds_seen[0]:
                print("train-ops smoke: /healthz disagrees with "
                      "/metrics", file=sys.stderr)
                proc.kill()
                return 1
            proc.stdout.read()
            rc = proc.wait(timeout=300)
            wall_on = time.perf_counter() - t0
            if rc == 0:
                break
            print(f"train-ops smoke: scraped train attempt "
                  f"{attempt + 1} died rc={rc} (known infra flake if "
                  f"-11); retrying", file=sys.stderr)
        else:
            print("train-ops smoke: scraped train failed 3 times",
                  file=sys.stderr)
            return 1
        print(json.dumps({"mid_run_rounds_seen": rounds_seen,
                          "healthz_round": health["round"]}))

        # ---- the log side: heartbeats + report progress ------------- #
        events = report.read_events(log)
        hb = [e for e in events if e["event"] == "train_heartbeat"]
        if not hb:
            print("train-ops smoke: no train_heartbeat events",
                  file=sys.stderr)
            return 1
        sm_on = report.summarize(events)
        if cli_main(["report", f"--log={log}", "progress"]) != 0:
            print("train-ops smoke: report progress failed",
                  file=sys.stderr)
            return 1

        # ---- measured overhead bound -------------------------------- #
        # The scraped run above is the LIVENESS witness, not the timing
        # baseline — on a small box the harness's own polling loop
        # contends with the child for CPU. Overhead is measured on a
        # clean pair: --status-port enabled (daemon bound, hooks armed,
        # nobody scraping) vs disabled, both with a run log, comparing
        # each log's own in-process wallclock minus its own measured
        # compile seconds (compile time dominates a tiny CPU run and
        # varies run to run; leaving it in would drown the signal).
        # Best-of-3 per side, alternating, because a 1-CPU box's
        # scheduler adds ±20% run-to-run noise — the MIN is the run the
        # OS interfered with least, which is the honest estimate of
        # each configuration's intrinsic cost.
        timings = {"on": [], "off": []}
        for rep in range(3):
            for tag, port in (("on", 0), ("off", None)):
                tlog = os.path.join(td, f"timed_{tag}_{rep}.jsonl")
                if not _run_train(
                        _train_args(
                            os.path.join(td, f"timed_{tag}_{rep}.npz"),
                            log=tlog, status_port=port),
                        env, f"timed {tag} rep {rep}"):
                    return 1
                sm = report.summarize(report.read_events(tlog))
                compile_s = (sm["counters"].get("jit_compile_seconds")
                             or 0.0)
                timings[tag].append(
                    max(0.1, (sm["wallclock_s"] or 0.0) - compile_s))

        ratio = min(timings["on"]) / min(timings["off"])
        print(json.dumps({
            "overhead": {"train_s_on": round(min(timings["on"]), 3),
                         "train_s_off": round(min(timings["off"]), 3),
                         "samples_on": [round(t, 3)
                                        for t in timings["on"]],
                         "samples_off": [round(t, 3)
                                         for t in timings["off"]],
                         "scraped_run_wall_s": round(wall_on, 3),
                         "ratio_compile_adjusted": round(ratio, 4)}}))
        if ratio > 1.05:
            print(f"train-ops smoke: enabled/disabled overhead "
                  f"{ratio:.3f}x exceeds 1.05x", file=sys.stderr)
            return 1

        print(json.dumps({"smoke": "train-ops", "ok": True,
                          "heartbeats": len(hb),
                          "scrapes": len(rounds_seen)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
