"""Serve smoke (`make serve-smoke`): the serving tier end to end on CPU
(docs/SERVING.md).

One process, five assertions:

1. a tiny model trains, saves, and comes up behind the HTTP front end
   (ephemeral port) with every bucket shape pre-traced;
2. 100 CONCURRENT single-row HTTP requests (plus a few multi-row ones)
   all succeed and BIT-match the offline `api.predict` answer for
   whichever model version each was served by;
3. a hot swap to a second model fires MID-FLIGHT: zero failed requests,
   and every response is attributable to exactly the old or the new
   model (the response carries the serving token) — never a mix;
4. the admission batcher actually coalesced (width > 1 across the
   storm — the deterministic >= 8 witness lives in tests/test_serve.py
   behind a barrier; under real HTTP concurrency width depends on the
   box, so the smoke asserts coalescing happened, not a number);
5. the `serve_latency` SLO event lands in the run log and renders
   through `cli report`'s serving section;
6. (ISSUE 12 arm) an int4-quantized engine behind the SAME front end:
   a storm of `binned=raw` octet-stream requests (the zero-copy wire
   path) interleaved with sequential express-lane singles — every
   response BIT-matches the offline answer of the tier that actually
   served it (predict_impl='lut4', verified from /healthz), raw and
   JSON bodies agree bitwise, the express counter moved, and the
   malformed-width raw body 400s loudly;
7. (ISSUE 15 FLEET arm) three registry-pushed models of MIXED tiers
   (f32 / int8 / int4) behind ONE fleet engine with max_resident=2:
   a concurrent storm across all three (path + header routing,
   binned=raw included) with LRU evictions + zero-downtime reloads
   forced MID-STORM — zero failures, every response bit-identical to
   the offline `api.predict` answer OF THE TIER/ARTIFACT that served
   it, `/healthz` witnesses evictions>=1 and reloads>=1, a
   steady-state window over the resident models records 0 jit
   compiles, the run log's per-model serve_latency windows render
   through `report fleet`, and a saturated single-model A/B holds the
   fleet p99 within 1.5x of the plain single-engine baseline on the
   same run;
8. (ISSUE 17 metrics arm) the live operations plane under load: a
   MID-STORM `GET /metrics` scrape whose every process-counter series
   sits between counter snapshots taken immediately before and after
   the scrape (counter-for-counter, race-safe bounds — the read-only
   exposition never lags or invents a counter), one STORMED request
   pinning a client `X-DDT-Trace-Id` that round-trips through the
   response headers (with a full five-stage timing breakdown) and the
   `/debug/requests` ring, and the tracing-overhead A/B: saturated
   p99 with request traces ON (the default) within 1.1x of
   `--no-request-traces` (min-of-3 measured windows per side);
9. (ISSUE 19 drift arm) the drift observatory end to end: a registry
   fleet of a drift-tracked champion (+ a shadow challenger) and an
   un-shifted control model, stormed with covariate-shifted binned
   traffic — the `/metrics` drift series MOVE between scrapes
   (absent under MIN_ROWS, present and alerting after the shifted
   storm), exactly the shifted model fires the latched `drift` event
   and the `report drift` breach row while the control stays quiet,
   the challenger scores the champion's own traffic off the response
   path, and the drift+shadow overhead A/B holds saturated p99
   within 1.1x of the same fleet with drift off (interleaved
   min-of-3 windows per side).

Exit 0 = all hold.
"""

import json
import os
import sys
import tempfile
import threading
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from ddt_tpu import api  # noqa: E402
from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.data import datasets  # noqa: E402
from ddt_tpu.serve.engine import ServeEngine  # noqa: E402
from ddt_tpu.serve.http import serve_forever  # noqa: E402
from ddt_tpu.telemetry import report as tele_report  # noqa: E402


def _post(port: int, path: str, payload: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


def _post_raw(port: int, body: bytes) -> dict:
    """POST /predict?binned=raw with the uint8 row block AS the body
    (the zero-copy wire path)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict?binned=raw", data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def main() -> int:
    X, y = datasets.synthetic_binary(4000, seed=3)
    kw = dict(n_trees=6, max_depth=3, n_bins=31, backend="tpu",
              log_every=10**9)
    res_a = api.train(X, y, **kw)
    # A genuinely different model version (seed alone changes nothing
    # without bagging): halving the learning rate moves every leaf.
    res_b = api.train(X, y, learning_rate=0.05, **kw)
    cfg = TrainConfig(backend="tpu", n_bins=31)
    want = {}   # serving token -> offline reference scores
    out = {"cmd": "serve_smoke"}

    with tempfile.TemporaryDirectory() as td:
        model_b = os.path.join(td, "model_b.npz")
        res_b.save(model_b)
        run_log = os.path.join(td, "serve.jsonl")

        bundle_a = api.ModelBundle(ensemble=res_a.ensemble,
                                   mapper=res_a.mapper)
        engine = ServeEngine(bundle_a, cfg, max_wait_ms=2.0,
                             max_batch=64, run_log=run_log)
        for res in (res_a, res_b):
            tok = res.ensemble.compile().token
            want[tok] = np.asarray(api.predict(
                res.ensemble, X, mapper=res.mapper, cfg=cfg))

        ready = threading.Event()
        th = threading.Thread(
            target=serve_forever, args=(engine,),
            kwargs=dict(port=0, ready_event=ready), daemon=True)
        th.start()
        assert ready.wait(60), "server never came up"
        port = engine.http_port      # published before ready fires

        health = _get(port, "/healthz")
        assert health["ok"] and health["model_token"] == \
            res_a.ensemble.compile().token
        out["buckets"] = health["buckets"]

        # --- the storm: 100 concurrent single-row requests, a hot swap
        # injected from a parallel thread mid-flight, plus batch rows.
        n = 100
        errs = []
        served = [None] * n
        barrier = threading.Barrier(n + 1)

        def worker(i):
            barrier.wait()
            try:
                r = _post(port, "/predict",
                          {"rows": [X[i].tolist()]})
                served[i] = (r["model"], r["scores"][0])
            except Exception as e:       # noqa: BLE001 — smoke verdict
                errs.append((i, repr(e)))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()

        def swapper():
            barrier.wait()
            _post(port, "/swap", {"model": model_b})

        sw = threading.Thread(target=swapper)
        sw.start()
        for t in threads:
            t.join(60)
        sw.join(60)
        assert not errs, f"failed requests during hot swap: {errs[:5]}"

        # Every response matches the offline answer of the model that
        # served it — old or new, never a mix.
        seen_tokens = set()
        for i, (tok, score) in enumerate(served):
            assert tok in want, f"response {i} served by unknown {tok}"
            seen_tokens.add(tok)
            np.testing.assert_allclose(score, want[tok][i], rtol=1e-5,
                                       atol=1e-6)
        out["hot_swap_zero_failures"] = True
        out["tokens_seen"] = len(seen_tokens)

        # Post-swap requests must score with model B.
        r = _post(port, "/predict", {"rows": X[:5].tolist()})
        tok_b = res_b.ensemble.compile().token
        assert r["model"] == tok_b
        np.testing.assert_allclose(r["scores"], want[tok_b][:5],
                                   rtol=1e-5, atol=1e-6)

        stats = _get(port, "/stats?emit=1")
        assert stats["requests"] > 0
        out["coalesce_max"] = stats["coalesce_max"]
        assert stats["coalesce_max"] > 1, (
            "no coalescing under a 100-way concurrent storm: "
            f"{stats}")
        _post(port, "/shutdown", {})
        th.join(30)

        # --- the run log: serve_latency landed, report renders it.
        events = tele_report.read_events(run_log)
        sl = [e for e in events if e["event"] == "serve_latency"]
        assert sl, "no serve_latency event in the run log"
        summary = tele_report.summarize(events)
        assert summary["serving"]["requests"] >= n
        rendered = tele_report.render(summary)
        assert "serving:" in rendered and "latency:" in rendered
        out["serve_latency_events"] = len(sl)
        out["p99_ms"] = sl[-1]["p99_ms"]

    # --- ISSUE 12 arm: int4 tier + binned=raw wire path + express lane.
    # A 15-bin model so the int4 thresholds ride the nibble pack.
    X4, y4 = datasets.synthetic_binary(3000, seed=9)
    res4 = api.train(X4, y4, n_trees=8, max_depth=3, n_bins=15,
                     backend="tpu", log_every=10**9)
    cfg4 = TrainConfig(backend="tpu", n_bins=15, predict_impl="lut4")
    # Offline reference THROUGH THE SAME TIER: responses must bit-match
    # the tier that serves them, not merely sit near f32.
    ref4 = np.asarray(api.predict(res4.ensemble, X4, mapper=res4.mapper,
                                  cfg=cfg4))
    Xb4 = res4.mapper.transform(X4)
    engine4 = ServeEngine(
        api.ModelBundle(ensemble=res4.ensemble, mapper=res4.mapper),
        cfg4, max_wait_ms=2.0, max_batch=64, quantize="int4")
    ready4 = threading.Event()
    th4 = threading.Thread(
        target=serve_forever, args=(engine4,),
        kwargs=dict(port=0, ready_event=ready4), daemon=True)
    th4.start()
    assert ready4.wait(60), "int4 server never came up"
    port4 = engine4.http_port

    h4 = _get(port4, "/healthz")
    assert h4["quantized"] and h4["quantize_tier"] == "int4"
    assert h4["predict_impl"] == "lut4", (
        f"int4 engine silently fell back: serving {h4['predict_impl']}")
    out["int4_predict_impl"] = h4["predict_impl"]
    out["int4_err_bound"] = h4["lut_max_abs_err"]

    # Express singles FIRST (sequential -> empty queue -> the lane).
    for i in range(6):
        r = _post_raw(port4, Xb4[i:i + 1].tobytes())
        np.testing.assert_array_equal(
            np.asarray(r["scores"], np.float32),
            ref4[i:i + 1].astype(np.float32))

    # Then the raw-wire storm: concurrent multi-row raw bodies.
    n4, errs4 = 40, []

    def raw_worker(i):
        try:
            lo = 7 * i
            r = _post_raw(port4, Xb4[lo:lo + 7].tobytes())
            np.testing.assert_array_equal(
                np.asarray(r["scores"], np.float32),
                ref4[lo:lo + 7].astype(np.float32))
        except Exception as e:       # noqa: BLE001 — smoke verdict
            errs4.append((i, repr(e)))

    threads4 = [threading.Thread(target=raw_worker, args=(i,))
                for i in range(n4)]
    for t in threads4:
        t.start()
    for t in threads4:
        t.join(60)
    assert not errs4, f"raw-wire storm failures: {errs4[:5]}"

    # Raw and JSON bodies agree BITWISE on the same rows.
    r_raw = _post_raw(port4, Xb4[:5].tobytes())
    r_json = _post(port4, "/predict", {"rows": X4[:5].tolist()})
    np.testing.assert_array_equal(np.asarray(r_raw["scores"]),
                                  np.asarray(r_json["scores"]))

    # Malformed width: loud 400, never a silent reshape.
    try:
        _post_raw(port4, Xb4[:1].tobytes()[:-1])
        raise AssertionError("truncated raw body was accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 400, e.code

    stats4 = _get(port4, "/healthz")
    assert stats4["express"] >= 6, stats4      # the lane carried singles
    out["int4_raw_storm"] = n4
    out["int4_express_hits"] = stats4["express"]
    _post(port4, "/shutdown", {})
    th4.join(30)

    # --- ISSUE 15 FLEET arm: mixed-tier registry fleet, LRU eviction +
    # reload mid-storm, per-model SLO windows, saturated p99 A/B.
    import concurrent.futures

    from ddt_tpu.registry.loader import push_servable
    from ddt_tpu.serve.control import FleetSpec, build_fleet
    from ddt_tpu.telemetry import counters as tele_counters

    tele_counters.install_jax_listener()
    with tempfile.TemporaryDirectory() as td:
        reg = os.path.join(td, "registry")
        fleet_log = os.path.join(td, "fleet.jsonl")
        # three artifacts, three tiers (max_batch=32 keeps export quick)
        push_servable(reg, api.ModelBundle(ensemble=res_a.ensemble,
                                           mapper=res_a.mapper),
                      name="alpha", max_batch=32, quantize=False)
        push_servable(reg, api.ModelBundle(ensemble=res_b.ensemble,
                                           mapper=res_b.mapper),
                      name="beta", max_batch=32, quantize="int8")
        push_servable(reg, api.ModelBundle(ensemble=res4.ensemble,
                                           mapper=res4.mapper),
                      name="gamma", max_batch=32, quantize="int4")
        # offline references THROUGH THE TIER each artifact carries
        ref_fleet = {
            "alpha": want[res_a.ensemble.compile().token],
            "beta": np.asarray(api.predict(
                res_b.ensemble, X, mapper=res_b.mapper,
                cfg=TrainConfig(backend="tpu", n_bins=31,
                                predict_impl="lut"))),
            "gamma": ref4,
        }
        rows_for = {"alpha": X, "beta": X, "gamma": X4}
        engine_f = build_fleet(
            [FleetSpec(name="alpha", ref="alpha@latest", max_batch=32),
             FleetSpec(name="beta", ref="beta@latest", max_batch=32),
             FleetSpec(name="gamma", ref="gamma@latest", max_batch=32)],
            registry=reg, backend="tpu", max_wait_ms=2.0,
            max_resident=2, run_log=fleet_log)
        ready_f = threading.Event()
        th_f = threading.Thread(
            target=serve_forever, args=(engine_f,),
            kwargs=dict(port=0, ready_event=ready_f), daemon=True)
        th_f.start()
        assert ready_f.wait(60), "fleet server never came up"
        pf = engine_f.http_port

        h = _get(pf, "/healthz")
        assert h["fleet"] and set(h["models"]) == {"alpha", "beta",
                                                   "gamma"}
        assert h["resident"] == 2, h     # budget respected at boot

        # THE STORM: concurrent traffic across all three models —
        # gamma starts cold, so its first requests force an LRU
        # eviction + zero-downtime reload MID-STORM. Routing mixes the
        # URL-path and header forms; gamma additionally rides the
        # zero-copy binned=raw wire path.
        Xb_gamma = res4.mapper.transform(X4)
        errs_f = []

        def fleet_worker(i):
            name = ("alpha", "beta", "gamma")[i % 3]
            lo = 2 * i
            try:
                if name == "gamma":
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{pf}/models/gamma/predict"
                        "?binned=raw",
                        data=Xb_gamma[lo:lo + 2].tobytes(),
                        headers={"Content-Type":
                                 "application/octet-stream"},
                        method="POST")
                elif i % 2:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{pf}/models/{name}/predict",
                        data=json.dumps(
                            {"rows":
                             rows_for[name][lo:lo + 2].tolist()}
                        ).encode(),
                        headers={"Content-Type": "application/json"},
                        method="POST")
                else:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{pf}/predict",
                        data=json.dumps(
                            {"rows":
                             rows_for[name][lo:lo + 2].tolist()}
                        ).encode(),
                        headers={"Content-Type": "application/json",
                                 "X-DDT-Model": name},
                        method="POST")
                with urllib.request.urlopen(req, timeout=60) as r:
                    scores = json.loads(r.read())["scores"]
                np.testing.assert_array_equal(
                    np.asarray(scores, np.float32),
                    ref_fleet[name][lo:lo + 2].astype(np.float32))
            except Exception as e:       # noqa: BLE001 — smoke verdict
                errs_f.append((i, name, repr(e)))

        with concurrent.futures.ThreadPoolExecutor(24) as pool:
            list(pool.map(fleet_worker, range(36)))
        assert not errs_f, f"fleet storm failures: {errs_f[:5]}"

        # eviction + reload witnessed (gamma's cold load overflowed the
        # budget; the dispatcher settled it back; evicted models were
        # re-requested and reloaded — all mid-storm, zero failures)
        h = _get(pf, "/healthz")
        assert h["evictions"] >= 1, h
        # every model answers post-storm; at least one reloads to do so
        for name in ("alpha", "beta", "gamma"):
            r = _post(pf, f"/models/{name}/predict",
                      {"rows": rows_for[name][:2].tolist()})
            np.testing.assert_array_equal(
                np.asarray(r["scores"], np.float32),
                ref_fleet[name][:2].astype(np.float32))
        h = _get(pf, "/healthz")
        assert h["reloads"] >= 1, h
        out["fleet_evictions"] = h["evictions"]
        out["fleet_reloads"] = h["reloads"]

        # steady state on the RESIDENT pair: zero jit compiles across
        # a fresh storm (the zero-retrace dispatch-path witness)
        resident = [n for n, m in h["models"].items() if m["resident"]]
        assert len(resident) == 2, h
        for name in resident:            # warm the buckets in use
            _post(pf, f"/models/{name}/predict",
                  {"rows": rows_for[name][:2].tolist()})
        c0 = tele_counters.snapshot()
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            list(pool.map(
                lambda i: _post(
                    pf,
                    f"/models/{resident[i % 2]}/predict",
                    {"rows":
                     rows_for[resident[i % 2]][:2].tolist()}),
                range(24)))
        steady = tele_counters.delta(c0)["jit_compiles"]
        assert steady == 0, \
            f"{steady} jit compiles during steady-state fleet serving"
        out["fleet_steady_state_jit_compiles"] = steady

        _post(pf, "/shutdown", {})
        th_f.join(30)

        # per-model SLO windows land and the fleet rollup renders
        events = tele_report.read_events(fleet_log)
        names = {e.get("model_name") for e in events
                 if e["event"] == "serve_latency"}
        assert {"alpha", "beta", "gamma"} <= names, names
        summary = tele_report.summarize(events)
        assert set(summary["fleet"]["models"]) == {"alpha", "beta",
                                                   "gamma"}
        assert summary["fleet"]["evictions"] >= 1
        rollup = tele_report.render_fleet(summary)
        assert "fleet:" in rollup and "alpha" in rollup
        out["fleet_report_models"] = sorted(summary["fleet"]["models"])

    # --- saturated single-model A/B: fleet p99 within 1.5x of the
    # plain single-engine baseline, same process, same load pattern.
    def _saturate(submit):
        def worker(i):
            submit(X[i % 64:i % 64 + 1])

        with concurrent.futures.ThreadPoolExecutor(16) as pool:
            list(pool.map(worker, range(192)))

    bundle_ab = api.ModelBundle(ensemble=res_a.ensemble,
                                mapper=res_a.mapper)
    single = ServeEngine(bundle_ab, cfg, max_wait_ms=2.0, max_batch=64)
    _saturate(lambda rows: single.predict(rows, timeout=60.0))
    single.stats.window_summary(reset=True)      # measured window
    _saturate(lambda rows: single.predict(rows, timeout=60.0))
    p99_single = single.stats.window_summary()["p99_ms"]
    single.close()
    with tempfile.TemporaryDirectory() as td_ab:
        model_a = os.path.join(td_ab, "a.npz")
        res_a.save(model_a)
        fleet1 = build_fleet([FleetSpec(name="solo", ref=model_a,
                                        max_batch=64)],
                             backend="tpu", max_wait_ms=2.0)
        _saturate(lambda rows: fleet1.predict(rows, model="solo",
                                              timeout=60.0))
        fleet1.window_summaries(reset=True)      # measured window
        _saturate(lambda rows: fleet1.predict(rows, model="solo",
                                              timeout=60.0))
        p99_fleet = fleet1.window_summaries()["solo"]["p99_ms"]
        fleet1.close()
    out["p99_single_ms"] = p99_single
    out["p99_fleet_ms"] = p99_fleet
    assert p99_fleet <= 1.5 * max(p99_single, 1.0), (
        f"fleet saturated p99 {p99_fleet:.2f} ms vs single-engine "
        f"{p99_single:.2f} ms (> 1.5x)")

    # --- ISSUE 17 metrics arm: mid-storm /metrics scrape, trace id
    # round-trip on a stormed request, tracing-overhead A/B.
    from ddt_tpu.serve.metrics import parse_exposition

    engine_m = ServeEngine(bundle_ab, cfg, max_wait_ms=2.0,
                           max_batch=64)
    ready_m = threading.Event()
    th_m = threading.Thread(
        target=serve_forever, args=(engine_m,),
        kwargs=dict(port=0, ready_event=ready_m), daemon=True)
    th_m.start()
    assert ready_m.wait(60), "metrics-arm server never came up"
    pm = engine_m.http_port

    pinned = {}
    errs_m = []

    def metrics_worker(i):
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{pm}/predict",
                data=json.dumps({"rows": [X[i % 64].tolist()]}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            if i == 37:      # ONE stormed request pins the trace id
                req.add_header("X-DDT-Trace-Id", "smoke-pin-37")
            with urllib.request.urlopen(req, timeout=60) as r:
                json.loads(r.read())
                if i == 37:
                    pinned["id"] = r.headers["X-DDT-Trace-Id"]
                    pinned["timing"] = r.headers["X-DDT-Timing"]
        except Exception as e:       # noqa: BLE001 — smoke verdict
            errs_m.append((i, repr(e)))

    with concurrent.futures.ThreadPoolExecutor(16) as pool:
        futs = [pool.submit(metrics_worker, i) for i in range(96)]
        # MID-STORM scrape. Race-safe counter-for-counter bound: the
        # scrape happened between two snapshots of the same process
        # counters, so every numeric counter's scraped value must sit
        # inside [before, after].
        c_before = tele_counters.snapshot()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{pm}/metrics", timeout=60) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            scraped = r.read().decode()
        c_after = tele_counters.snapshot()
        for f in futs:
            f.result(60)
    assert not errs_m, f"metrics-arm storm failures: {errs_m[:5]}"

    series = parse_exposition(scraped)
    checked = 0
    for key, lo in c_before.items():
        if isinstance(lo, bool) or not isinstance(lo, (int, float)):
            continue
        got = series[f"ddt_{key}_total"][()]
        hi = c_after[key]
        assert lo <= got <= hi, (
            f"/metrics counter ddt_{key}_total={got} outside the "
            f"mid-storm snapshot bounds [{lo}, {hi}]")
        checked += 1
    assert checked >= 10, f"only {checked} counters exposed"
    out["metrics_counters_checked"] = checked

    # The pinned trace id round-trips with a full timing breakdown...
    assert pinned.get("id") == "smoke-pin-37", pinned
    stages = {p.split("=")[0] for p in pinned["timing"].split(",")}
    assert stages == {"handler", "queue", "gate", "device", "wake",
                      "total"}, pinned
    # ...and is attributable in the debug ring.
    dbg = _get(pm, "/debug/requests")
    assert any(t["trace_id"] == "smoke-pin-37"
               for t in dbg["models"]["default"]), (
        "pinned trace id missing from /debug/requests ring")
    out["trace_round_trip"] = pinned["timing"]
    _post(pm, "/shutdown", {})
    th_m.join(30)

    # Tracing-overhead A/B: request traces on (default) vs off
    # (`serve --no-request-traces`), saturated p99. Rounds INTERLEAVE
    # between the two engines and each side keeps its min-of-3, so
    # CPU-box scheduler drift hits both sides equally instead of
    # penalising whichever happened to measure first.
    traced = ServeEngine(bundle_ab, cfg, max_wait_ms=2.0, max_batch=64)
    untraced = ServeEngine(bundle_ab, cfg, max_wait_ms=2.0,
                           max_batch=64, request_traces=False)
    sides = (("traced", traced), ("untraced", untraced))
    for _, eng in sides:                         # warm both sides
        _saturate(lambda rows: eng.predict(rows, timeout=60.0))
        eng.stats.window_summary(reset=True)
    best = {}
    for _ in range(3):
        for name, eng in sides:
            _saturate(lambda rows: eng.predict(rows, timeout=60.0))
            p = eng.stats.window_summary(reset=True)["p99_ms"]
            best[name] = min(p, best.get(name, p))
    traced.close()
    untraced.close()
    p99_traced, p99_untraced = best["traced"], best["untraced"]
    out["p99_traced_ms"] = p99_traced
    out["p99_untraced_ms"] = p99_untraced
    assert p99_traced <= 1.1 * max(p99_untraced, 1.0), (
        f"request tracing costs too much at saturation: p99 "
        f"{p99_traced:.2f} ms traced vs {p99_untraced:.2f} ms with "
        f"--no-request-traces (> 1.1x)")

    # --- ISSUE 19 drift arm: registry fleet, covariate-shifted storm,
    # moving /metrics series, latched drift event + report breach row,
    # shadow challenger, and the drift+shadow overhead A/B.
    from ddt_tpu.serve import drift as serve_drift

    shifted = X + 5.0 * np.abs(X).max(axis=0)    # off every bin edge
    with tempfile.TemporaryDirectory() as td:
        reg = os.path.join(td, "registry")
        drift_log = os.path.join(td, "drift.jsonl")
        push_servable(reg, api.ModelBundle(ensemble=res_a.ensemble,
                                           mapper=res_a.mapper),
                      name="shifty", max_batch=64, quantize=False)
        push_servable(reg, api.ModelBundle(ensemble=res_b.ensemble,
                                           mapper=res_b.mapper),
                      name="steady", max_batch=64, quantize=False)
        engine_d = build_fleet(
            [FleetSpec(name="shifty", ref="shifty@latest", max_batch=64),
             FleetSpec(name="steady", ref="steady@latest", max_batch=64),
             FleetSpec(name="shade", ref="steady@latest", max_batch=64,
                       shadow_of="shifty")],
            registry=reg, backend="tpu", max_wait_ms=2.0,
            run_log=drift_log)
        ready_d = threading.Event()
        th_d = threading.Thread(
            target=serve_forever, args=(engine_d,),
            kwargs=dict(port=0, ready_event=ready_d), daemon=True)
        th_d.start()
        assert ready_d.wait(60), "drift-arm server never came up"
        pd = engine_d.http_port

        def storm(name, rows, total, width=100):
            errs_d = []

            def w(i):
                lo = (i * width) % len(rows)
                try:
                    _post(pd, f"/models/{name}/predict",
                          {"rows": rows[lo:lo + width].tolist()})
                except Exception as e:   # noqa: BLE001 — smoke verdict
                    errs_d.append((i, repr(e)))

            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                list(pool.map(w, range(total // width)))
            assert not errs_d, f"drift-arm storm failures: {errs_d[:5]}"

        def drift_series(name):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{pd}/metrics", timeout=60) as r:
                parsed = parse_exposition(r.read().decode())
            key = frozenset({("model", name)})
            return {s: v[key] for s, v in parsed.items()
                    if s.startswith("ddt_drift_") and key in v}

        # Scrape 1: under MIN_ROWS the divergence gauges are ABSENT
        # (omit-don't-lie), only the bookkeeping series render.
        storm("shifty", shifted, serve_drift.MIN_ROWS // 2)
        s1 = drift_series("shifty")
        assert "ddt_drift_psi_max" not in s1, s1
        assert s1["ddt_drift_alerting"] == 0.0, s1
        # Scrape 2 after the full shifted storm: the series MOVED —
        # divergence appears, the alert latched, the counter bumped.
        storm("shifty", shifted, 2 * serve_drift.MIN_ROWS)
        storm("steady", X, 2 * serve_drift.MIN_ROWS)    # control
        s2 = drift_series("shifty")
        assert s2["ddt_drift_psi_max"] >= serve_drift.PSI_ALERT, s2
        assert s2["ddt_drift_alerting"] == 1.0, s2
        assert s2["ddt_drift_model_alerts_total"] == 1.0, s2
        assert s2["ddt_drift_window_rows"] > s1["ddt_drift_window_rows"]
        s_ctl = drift_series("steady")
        assert s_ctl["ddt_drift_alerting"] == 0.0, s_ctl
        assert s_ctl["ddt_drift_model_alerts_total"] == 0.0, s_ctl
        out["drift_psi_max"] = s2["ddt_drift_psi_max"]

        # /healthz + /debug/drift agree; the challenger scored the
        # champion's own traffic off the response path.
        h = _get(pd, "/healthz")
        assert h["models"]["shifty"]["drift_alerting"] is True
        assert h["models"]["steady"]["drift_alerting"] is False
        dbg = _get(pd, "/debug/drift")
        assert dbg["models"]["shifty"]["state"]["alerting"] is True
        assert dbg["models"]["shifty"]["per_feature"][0]["psi"] >= \
            serve_drift.PSI_ALERT
        sh = h["models"]["shifty"]["shadow"]
        assert sh["model"] == "shade" and sh["rows"] > 0, sh
        out["shadow_rows"] = sh["rows"]

        _post(pd, "/shutdown", {})
        th_d.join(30)

        # Run log: EXACTLY the shifted model fired the latched event;
        # report drift renders its breach row, the control stays quiet.
        events = tele_report.read_events(drift_log)
        drift_ev = [e for e in events if e["event"] == "drift"]
        assert [e["model_name"] for e in drift_ev] == ["shifty"], \
            drift_ev
        assert drift_ev[0]["psi_max"] >= serve_drift.PSI_ALERT
        summary = tele_report.summarize(events)
        dr = summary["drift"]["models"]
        assert dr["shifty"]["alerts"] == 1 and dr["shifty"]["alerting"]
        assert dr["steady"]["alerts"] == 0 and not dr["steady"]["alerting"]
        row = tele_report.render_drift(summary)
        assert "shifty" in row and "ALERTING" in row and "shade" in row
        out["drift_events"] = len(drift_ev)

    # Drift+shadow overhead A/B: the same artifact served with the
    # observatory fully on (tracker + resident challenger) vs drift
    # explicitly off — interleaved min-of-3 saturated windows, same
    # discipline as the tracing A/B above.
    with tempfile.TemporaryDirectory() as td_ab:
        model_a = os.path.join(td_ab, "a.npz")
        model_b = os.path.join(td_ab, "b.npz")
        res_a.save(model_a)
        res_b.save(model_b)
        fleet_on = build_fleet(
            [FleetSpec(name="solo", ref=model_a, max_batch=64),
             FleetSpec(name="shade", ref=model_b, max_batch=64,
                       shadow_of="solo")],
            backend="tpu", max_wait_ms=2.0)
        fleet_off = build_fleet(
            [FleetSpec(name="solo", ref=model_a, max_batch=64,
                       drift=False)],
            backend="tpu", max_wait_ms=2.0)
        sides_d = (("drift_on", fleet_on), ("drift_off", fleet_off))
        for _, eng in sides_d:                       # warm both sides
            _saturate(lambda rows: eng.predict(rows, model="solo",
                                               timeout=60.0))
            eng.window_summaries(reset=True)
        best_d = {}
        for _ in range(3):
            for name, eng in sides_d:
                _saturate(lambda rows: eng.predict(rows, model="solo",
                                                   timeout=60.0))
                p = eng.window_summaries(reset=True)["solo"]["p99_ms"]
                best_d[name] = min(p, best_d.get(name, p))
        fleet_on.close()
        fleet_off.close()
    p99_on, p99_off = best_d["drift_on"], best_d["drift_off"]
    out["p99_drift_on_ms"] = p99_on
    out["p99_drift_off_ms"] = p99_off
    assert p99_on <= 1.1 * max(p99_off, 1.0), (
        f"drift+shadow cost too much at saturation: p99 {p99_on:.2f} "
        f"ms with the observatory on vs {p99_off:.2f} ms drift-off "
        "(> 1.1x)")

    out["ok"] = True
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
