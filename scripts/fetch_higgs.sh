#!/usr/bin/env bash
# Fetch the UCI HIGGS dataset (BASELINE config 1: Higgs-1M binary clf).
# 11M rows, 28 features, label in the FIRST column (the repo's csv
# loader's `--label-col auto` convention). ~2.6 GB gzipped.
#
# UNTESTED IN CI: the build environment has no network access
# (docs/REAL_DATA.md) — run on a networked machine, then train with:
#   python -m ddt_tpu.cli train --data data/HIGGS.csv.gz --rows 1000000 ...
set -euo pipefail

OUT_DIR="${1:-data}"
URL="https://archive.ics.uci.edu/ml/machine-learning-databases/00280/HIGGS.csv.gz"

mkdir -p "$OUT_DIR"
if [ -f "$OUT_DIR/HIGGS.csv.gz" ]; then
    echo "already present: $OUT_DIR/HIGGS.csv.gz"
    exit 0
fi
echo "fetching HIGGS (~2.6 GB) -> $OUT_DIR/HIGGS.csv.gz"
curl -fL --retry 3 -o "$OUT_DIR/HIGGS.csv.gz.part" "$URL"
mv "$OUT_DIR/HIGGS.csv.gz.part" "$OUT_DIR/HIGGS.csv.gz"
echo "done. First Higgs-1M training run:"
echo "  python -m ddt_tpu.cli train --backend=tpu --data=$OUT_DIR/HIGGS.csv.gz \\"
echo "      --trees=100 --depth=6 --bins=255 --valid-frac=0.2 --metric=auc"
