"""Chaos smoke (`make chaos-smoke`): a small CPU run under a multi-fault
plan asserting BIT-EXACT recovery (docs/ROBUSTNESS.md).

Four arms, all on the CPU platform (the first three in one process):

1. **Torn checkpoint write** — a streamed training run dies (injected
   crash between the checkpoint pair's two os.replace calls, leaving
   ensemble.npz one save ahead of cursor.json); the restarted run
   detects the torn pair via the cursor digest, falls back to the last
   good checkpoint, and finishes.
2. **Stream-read IOError** — the restarted run ALSO suffers injected
   chunk-read faults, absorbed by the retry/backoff seam.
3. **Injected straggler** — a 2-partition in-memory run with a run log
   gets one lane's observed times inflated; the watchdog must detect it
   (fault events in the log) while the trained model stays untouched.
4. **Serving process kill/restart** (ISSUE 15) — a real `cli serve`
   subprocess is SIGKILLed mid-storm and restarted on the same port;
   every concurrent client recovers by retrying, all requests
   eventually succeed, and every response matches the offline answer.

The verdict for every arm is the same: the final ensemble is
bit-identical to an undisturbed run, and the run log tells the whole
fault story (injected / retry / checkpoint_fallback /
checkpoint_resume / straggler_detected events). Exit 0 = all hold.

The streamed arms (1-2) run with --grad-dtype int8 ARMED (ISSUE 14):
quantized-gradient stochastic rounding is a pure function of (seed,
tree, global row), so a chunk-read retry re-quantizes the identical
bits and a torn-checkpoint resume replays the identical integer
histograms — bit-identical recovery must hold UNDER quantization, not
just beside it. Arm 3 keeps the f32 straggler coverage.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from ddt_tpu import api  # noqa: E402
from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.robustness import faultplan  # noqa: E402
from ddt_tpu.streaming import fit_streaming  # noqa: E402
from ddt_tpu.telemetry.events import RunLog  # noqa: E402


def _dataset(rows=4000, features=7, n_bins=29, seed=11):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, n_bins, size=(rows, features), dtype=np.uint8)
    y = (Xb[:, 0] + rng.integers(0, 6, size=rows) > 18).astype(np.float32)
    return Xb, y


def _chunk_fn(Xb, y, n_chunks):
    bounds = np.linspace(0, len(y), n_chunks + 1).astype(np.int64)

    def f(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

    return f


def _assert_same(a, b, label):
    for field in ("feature", "threshold_bin", "is_leaf", "leaf_value",
                  "split_gain"):
        np.testing.assert_array_equal(getattr(a, field), getattr(b, field),
                                      err_msg=f"{label}: {field} differs")


def main() -> int:
    n_chunks = 4
    Xb, y = _dataset()
    # --grad-dtype int8 armed (ISSUE 14): the streamed chaos arms must
    # recover bit-exactly THROUGH the quantized-gradient path.
    cfg = TrainConfig(n_trees=8, max_depth=3, n_bins=29, backend="tpu",
                      seed=3, grad_dtype="int8")
    chunk_fn = _chunk_fn(Xb, y, n_chunks)
    out = {"cmd": "chaos_smoke"}

    with tempfile.TemporaryDirectory() as td:
        # Undisturbed reference run (own checkpoint dir, never faulted).
        ens_clean = fit_streaming(
            chunk_fn, n_chunks, cfg, checkpoint_dir=os.path.join(td, "ck0"),
            checkpoint_every=2)

        # Arm 1: torn checkpoint write at round 4 — training dies with
        # the simulated crash AFTER ensemble.npz landed but BEFORE
        # cursor.json, exactly the pair-atomicity gap.
        ck = os.path.join(td, "ck1")
        torn = {"faults": [{"site": "ckpt.save.between", "round": 4}]}
        died = False
        prev = faultplan.activate(faultplan.load_plan(torn))
        try:
            fit_streaming(chunk_fn, n_chunks, cfg, checkpoint_dir=ck,
                          checkpoint_every=2)
        except faultplan.InjectedCrash:
            died = True
        finally:
            faultplan.deactivate(prev)
        assert died, "torn-checkpoint injection never fired"
        out["torn_ckpt_crashed"] = True

        # Arm 2: restart from the torn directory UNDER stream-read
        # faults, with a run log. The retry seam absorbs the IOErrors;
        # resume must fall back past the torn pair and finish.
        rl = RunLog()          # ring-only: assertions read events directly
        chaos = {"faults": [
            {"site": "stream.chunk_read", "chunk": 1, "times": 1},
            {"site": "stream.chunk_read", "chunk": 2, "times": 1},
        ]}
        prev = faultplan.activate(faultplan.load_plan(chaos))
        try:
            ens_chaos = fit_streaming(chunk_fn, n_chunks, cfg,
                                      checkpoint_dir=ck,
                                      checkpoint_every=2, run_log=rl)
        finally:
            faultplan.deactivate(prev)
        _assert_same(ens_clean, ens_chaos, "torn-ckpt + stream-read")
        kinds = [e["kind"] for e in rl.events("fault")]
        for want in ("checkpoint_corrupt", "checkpoint_fallback",
                     "checkpoint_resume", "injected", "retry"):
            assert want in kinds, f"missing fault kind {want!r}: {kinds}"
        out["recovered_bit_exact"] = True
        out["fault_kinds"] = sorted(set(kinds))

    # Arm 3: injected straggler on a 2-partition in-memory run — the
    # watchdog must DETECT (events, at the default threshold: the
    # watchdog's skew excludes the candidate lane from the median, so
    # 2.0 is reachable even on two lanes), the model must not move.
    cfg2 = TrainConfig(n_trees=6, max_depth=3, n_bins=29, backend="tpu",
                       n_partitions=2, seed=3)
    res_ref = api.train(Xb, y, cfg2, binned=True)
    rl2 = RunLog()
    strag = {"faults": [{"site": "straggler", "device": 1,
                         "delay_ms": 600000.0, "rounds": [1, 6],
                         "times": 6}]}
    prev = faultplan.activate(faultplan.load_plan(strag))
    try:
        res_strag = api.train(Xb, y, cfg2, binned=True, run_log=rl2)
    finally:
        faultplan.deactivate(prev)
    _assert_same(res_ref.ensemble, res_strag.ensemble, "straggler")
    kinds2 = [e["kind"] for e in rl2.events("fault")]
    assert "straggler_detected" in kinds2, kinds2
    out["straggler_detected"] = True

    # Arm 4 (ISSUE 15): kill/restart the SERVING process mid-storm —
    # the `cli serve` process is SIGKILLed while concurrent clients
    # are in flight, restarted on the same port, and every client
    # RECOVERS by retrying: all requests eventually succeed and every
    # response bit-matches the offline answer (the serving tier's
    # process-death story, complementing the training arms above).
    serve_chaos(out, res_ref, Xb, cfg2)

    out["ok"] = True
    print(json.dumps(out))
    return 0


def serve_chaos(out: dict, res, Xb, cfg) -> None:
    import socket
    import subprocess
    import threading
    import time
    import urllib.request

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ref = np.asarray(api.predict(res.ensemble, Xb[:64], cfg=cfg,
                                 binned=True))
    with tempfile.TemporaryDirectory() as td:
        model = os.path.join(td, "serve_chaos.npz")
        res.save(model)
        # a port that is free NOW and reusable after the SIGKILL
        # (HTTPServer sets allow_reuse_address)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        def spawn():
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            proc = subprocess.Popen(
                [sys.executable, "-m", "ddt_tpu.cli", "serve",
                 "--model", model, "--backend", "tpu",
                 "--port", str(port), "--max-wait-ms", "2"],
                cwd=repo, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            deadline = time.time() + 180
            while time.time() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"serve process exited rc={proc.returncode}")
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=2)
                    return proc
                except OSError:
                    time.sleep(0.25)
            raise RuntimeError("serve process never came up")

        proc = spawn()
        n_clients, per_client = 8, 6
        done = [0]
        done_lock = threading.Lock()
        errs = []

        def client(ci):
            for k in range(per_client):
                lo = (ci * per_client + k) % 48
                body = json.dumps({
                    "rows": Xb[lo:lo + 2].tolist(),
                    "binned": True}).encode()
                deadline = time.time() + 150
                while True:           # the RECOVERY loop: retry until
                    try:              # a (possibly new) process answers
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{port}/predict",
                            data=body,
                            headers={"Content-Type": "application/json"},
                            method="POST")
                        with urllib.request.urlopen(req, timeout=10) as r:
                            scores = json.loads(r.read())["scores"]
                        np.testing.assert_allclose(
                            np.asarray(scores, np.float32),
                            ref[lo:lo + 2].astype(np.float32),
                            rtol=1e-5, atol=1e-6)
                        with done_lock:
                            done[0] += 1
                        break
                    except AssertionError:
                        raise
                    except Exception as e:  # noqa: BLE001 — retried
                        if time.time() > deadline:
                            errs.append((ci, k, repr(e)))
                            return
                        time.sleep(0.3)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(n_clients)]
        for t in threads:
            t.start()
        # let the storm make progress, then KILL the server dead
        deadline = time.time() + 120
        while time.time() < deadline:
            with done_lock:
                if done[0] >= 8:
                    break
            time.sleep(0.05)
        proc.kill()
        proc.wait(30)
        out["serve_killed_after"] = done[0]
        # restart on the SAME port: in-flight and queued client
        # requests fail at the socket and RETRY into the new process
        proc = spawn()
        for t in threads:
            t.join(300)
        proc.kill()
        proc.wait(30)
        assert not errs, f"clients failed to recover: {errs[:5]}"
        assert done[0] == n_clients * per_client, done[0]
        out["serve_restart_recovered"] = done[0]


if __name__ == "__main__":
    sys.exit(main())
