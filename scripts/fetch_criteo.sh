#!/usr/bin/env bash
# Fetch the Criteo Display Advertising (Kaggle DAC) dataset — the
# tractable stand-in for BASELINE config 3 (Criteo-1TB CTR, sparse
# categoricals, 4-partition allreduce). ~4.3 GB tarball; train.txt is
# 45M rows: label, 13 integer features, 26 categorical (hex) features,
# tab-separated. Prep to npz shards with scripts/prep_criteo.py, then
# train with --stream-dir.
#
# The full Criteo 1TB click logs (config 3 at scale) are served per-day:
#   https://labs.criteo.com/2013/12/download-terabyte-click-logs/
# — same prep script, one day file at a time.
#
# UNTESTED IN CI: no network in the build environment (docs/REAL_DATA.md).
set -euo pipefail

OUT_DIR="${1:-data}"
URL="https://go.criteo.net/criteo-research-kaggle-display-advertising-challenge-dataset.tar.gz"

mkdir -p "$OUT_DIR"
if [ -f "$OUT_DIR/criteo/train.txt" ]; then
    echo "already present: $OUT_DIR/criteo/train.txt"
    exit 0
fi
echo "fetching Criteo DAC (~4.3 GB) -> $OUT_DIR/criteo/"
mkdir -p "$OUT_DIR/criteo"
curl -fL --retry 3 -o "$OUT_DIR/criteo/dac.tar.gz.part" "$URL"
mv "$OUT_DIR/criteo/dac.tar.gz.part" "$OUT_DIR/criteo/dac.tar.gz"
tar -xzf "$OUT_DIR/criteo/dac.tar.gz" -C "$OUT_DIR/criteo"
echo "done. Prep + streamed training:"
echo "  python scripts/prep_criteo.py $OUT_DIR/criteo/train.txt $OUT_DIR/criteo_shards"
echo "  python -m ddt_tpu.cli train --backend=tpu --stream-dir=$OUT_DIR/criteo_shards \\"
echo "      --trees=100 --depth=6 --bins=255 --partitions=4"
