"""lint_smoke: end-to-end drive of ddtlint's flow-aware passes.

Builds a throwaway mini-repo (real serve/batcher.py + backends/tpu.py +
parallel/mesh.py copies, and — since ddtlint v3 — the real contract
anchors config.py / backends/__init__.py / utils/checkpoint.py /
telemetry/{events,counters,diffing}.py) with every ISSUE-13 and
ISSUE-16 hazard seeded — lock-order inversion, unguarded cross-role
write, blocking-under-gate, acquire without try/finally, hand-built
PartitionSpec, literal axis name, uncovered layout-rule operand, stale
atomic-publish annotation, uncovered jit-traced cfg read, contract-less
config field, stale fingerprint exclude, reason-less trace-inert
annotation, typo'd event kind, undeclared event extra, direction-less
counter, required-field growth under a pinned schema version — then
runs the REAL CLI (`python -m tools.ddtlint --format json`) against it
and asserts each hazard is detected with the expected rule id at the
expected location. This is the tier the fixture unit tests cannot
cover: the walker, project-context resolution (mesh axes + rule table
from the copied mesh.py, contract anchors from the copied catalogs),
the JSON output contract, and the exit code, all through the subprocess
boundary `make lint` itself uses.

Usage: python scripts/lint_smoke.py      (also: make lint-smoke)
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MARKER = "# SMOKE-HAZARD:"

BATCHER_APPENDIX = f"""
    def _smoke_path_a(self):
        with self._cv:
            with self._gate:  {MARKER} lock-order
                pass

    def _smoke_path_b(self):
        with self._gate:
            with self._cv:  {MARKER} lock-order
                pass

    def retune(self, ms):
        self.max_wait_s = ms / 1e3  {MARKER} cross-role-state

    def grab_unsafe(self):
        self._gate.acquire()  {MARKER} lock-release
        self._q.clear()
        self._gate.release()
"""

BLOCKING_TARGET = ("                with self._gate:\n"
                   "                    self._dispatch(batch, depth)")
BLOCKING_MUTANT = (
    "                with self._gate:\n"
    f"                    time.sleep(0.001)  {MARKER} blocking-under-lock\n"
    "                    self._dispatch(batch, depth)")

TPU_APPENDIX = f"""

def _smoke_handbuilt(mesh):
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None))  {MARKER} handbuilt-partition-spec


SMOKE_ROW_AXIS = "rows"  {MARKER} axis-name-literal


def _smoke_coverage(lay):
    return lay.spec("operand_no_rule_matches")  {MARKER} layout-rule-coverage
"""

STALE_PUBLISH_MODULE = f"""\
class SmokeStale:
    def f(self):
        x = 1  # ddtlint: atomic-publish   {MARKER} suppression-hygiene
        return x
"""

# --- ddtlint v3 (ISSUE 16) seeds -------------------------------------- #
# config.py: one field in NO contract (the checkpoint copy below pops it
# out of the fingerprint), one reason-less trace-inert annotation.
CONFIG_ANCHOR = "    straggler_skew_threshold: float = 2.0"
CONFIG_APPENDIX_FIELDS = (
    f"    smoke_orphan_knob: int = 0  {MARKER} config-field-orphan\n"
    "    smoke_quiet_knob: int = 1  # ddtlint: trace-inert  "
    f"{MARKER} suppression-hygiene\n")

# backends/__init__.py: a jit-traced read of a field the cache key does
# not cover (n_trees is deliberately trace-inert at its DECLARATION, but
# an actual read inside a trace is exactly the PR 14 hazard).
BACKENDS_APPENDIX = f"""

def _smoke_make(cfg):
    import jax

    def _grow(x):
        return x * cfg.n_trees  {MARKER} jit-cache-key-coverage
    return jax.jit(_grow)
"""

# utils/checkpoint.py: a stale exclude entry naming no current field,
# plus the pop that orphans smoke_orphan_knob.
CHECKPOINT_TARGET = 'for k in ("n_trees",'
CHECKPOINT_MUTANT = (
    f'for k in ("zz_smoke_renamed",  {MARKER} fingerprint-field-coverage\n'
    '              "smoke_orphan_knob", "n_trees",')

# telemetry/events.py: required-set growth under the pinned schema
# version, a typo'd kind, and an undeclared extra.
EVENTS_TARGET = '    "round": {"round", "ms_per_round"},'
EVENTS_MUTANT = ('    "round": {"round", "ms_per_round", "smoke_now"},  '
                 f'{MARKER} event-schema-additivity')
EVENTS_APPENDIX = f"""

def _smoke_emits(log):
    log.emit("runmanifest", trainer="x")  {MARKER} undeclared-event-kind
    log.emit("run_end", completed_rounds=1, wallclock_s=1.0,
             smoke_vibes=3)  {MARKER} undeclared-event-extra
"""

# telemetry/counters.py: a published counter with no
# COUNTER_DIRECTIONS entry (the copied diffing.py is the real table).
COUNTERS_TARGET = "_c = {"
COUNTERS_MUTANT = ("_c = {\n"
                   f'    "smoke_counter": 0,  {MARKER} '
                   "counter-direction-missing")


def _expected(src: str, path: str) -> set:
    out = set()
    for i, line in enumerate(src.splitlines(), start=1):
        if MARKER in line:
            rule = line.split(MARKER, 1)[1].strip()
            out.add((rule, path, i))
    return out


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="ddtlint_smoke_")
    try:
        expected: set = set()

        def plant(rel: str, src: str) -> None:
            dst = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            with open(dst, "w", encoding="utf-8") as f:
                f.write(src)
            expected.update(_expected(src, rel))

        with open(os.path.join(REPO, "ddt_tpu/serve/batcher.py"),
                  encoding="utf-8") as f:
            batcher = f.read()
        assert BLOCKING_TARGET in batcher, \
            "batcher.py dispatch shape moved; update lint_smoke.py"
        plant("ddt_tpu/serve/batcher.py",
              batcher.replace(BLOCKING_TARGET, BLOCKING_MUTANT)
              + BATCHER_APPENDIX)
        with open(os.path.join(REPO, "ddt_tpu/backends/tpu.py"),
                  encoding="utf-8") as f:
            plant("ddt_tpu/backends/tpu.py", f.read() + TPU_APPENDIX)
        plant("ddt_tpu/serve/stale_smoke.py", STALE_PUBLISH_MODULE)

        # ddtlint v3: the config-flow + telemetry contract hazards ride
        # copies of the REAL anchor files so the analyzers resolve the
        # same contracts the gate does.
        def _read(rel: str) -> str:
            with open(os.path.join(REPO, rel), encoding="utf-8") as f:
                return f.read()

        config = _read("ddt_tpu/config.py")
        i = config.index(CONFIG_ANCHOR)
        eol = config.index("\n", i)
        plant("ddt_tpu/config.py",
              config[:eol + 1] + CONFIG_APPENDIX_FIELDS + config[eol + 1:])
        plant("ddt_tpu/backends/__init__.py",
              _read("ddt_tpu/backends/__init__.py") + BACKENDS_APPENDIX)
        ckpt = _read("ddt_tpu/utils/checkpoint.py")
        assert CHECKPOINT_TARGET in ckpt, \
            "checkpoint.py exclude-list shape moved; update lint_smoke.py"
        plant("ddt_tpu/utils/checkpoint.py",
              ckpt.replace(CHECKPOINT_TARGET, CHECKPOINT_MUTANT))
        events = _read("ddt_tpu/telemetry/events.py")
        assert EVENTS_TARGET in events, \
            "events.py round entry shape moved; update lint_smoke.py"
        plant("ddt_tpu/telemetry/events.py",
              events.replace(EVENTS_TARGET, EVENTS_MUTANT)
              + EVENTS_APPENDIX)
        counters = _read("ddt_tpu/telemetry/counters.py")
        assert COUNTERS_TARGET in counters, \
            "counters.py registry shape moved; update lint_smoke.py"
        plant("ddt_tpu/telemetry/counters.py",
              counters.replace(COUNTERS_TARGET, COUNTERS_MUTANT, 1))
        plant("ddt_tpu/telemetry/diffing.py",
              _read("ddt_tpu/telemetry/diffing.py"))
        # Project context: axis names + the SpecLayout rule table come
        # from the scanned tree's own mesh.py, exactly like the gate.
        shutil.copytree(os.path.join(REPO, "ddt_tpu/parallel"),
                        os.path.join(tmp, "ddt_tpu/parallel"))

        env = dict(os.environ, PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ddtlint", "ddt_tpu/",
             "--no-baseline", "--format", "json"],
            cwd=tmp, env=env, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1, (
            f"seeded hazards must fail the gate (rc=1), got "
            f"{proc.returncode}: {proc.stderr}")
        out = json.loads(proc.stdout)
        got = {(f["rule"], f["path"], f["line"]) for f in out["findings"]}

        missing = expected - got
        assert not missing, f"hazards NOT detected: {sorted(missing)}"
        # Every seeded rule fired where seeded; the JSON contract holds.
        assert out["summary"]["new"] == len(out["findings"])
        rules = sorted({r for r, _p, _l in expected})
        print(f"lint_smoke: {len(expected)} seeded hazards all detected "
              f"({', '.join(rules)}); json contract + exit code OK")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
