#!/usr/bin/env python
"""Tier-1-safe training-kernel smoke (make kernel-smoke): 2 boosting
rounds through the FUSED path with the VMEM-streaming Pallas histogram
kernel (interpret mode on CPU) and the sibling-subtraction trick forced
on, checked three ways:

1. fused-path parity — the granular per-tree Driver path must reproduce
   the fused multi-round path's trees (structure bitwise, leaf values to
   FMA tolerance) under the identical config;
2. telemetry spans — the compiled grow program must carry the round-6
   named scopes (ddt:fused_round, ddt:hist:subtract, and the kernel's
   ddt:hist:{stream,flush}) so Perfetto captures stay attributable;
3. run-log round trip — the telemetry run renders through `report` with
   the expected phases present;
4. quantized arm (ISSUE 14) — the interpret-mode Pallas kernel on int8
   gradients must match the segment path BITWISE (integer accumulation
   commutes), and a 2-round --grad-dtype int8 fused train must produce
   a valid run log whose manifest carries grad_dtype and whose counters
   carry the quantized g/h stream bytes.

Exit 0 iff all four hold. tests/test_hist_fused.py runs main()
in-process (the telemetry/trace/profile smoke pattern).
"""

import functools
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ddt_tpu import api
    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data.datasets import synthetic_binary
    from ddt_tpu.data.quantizer import quantize
    from ddt_tpu.driver import Driver
    from ddt_tpu.ops import grow as grow_ops
    from ddt_tpu.telemetry import report

    X, y = synthetic_binary(1200, n_features=5, seed=19)
    Xb, _ = quantize(X, n_bins=31, seed=19)
    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=31, backend="tpu",
                      hist_impl="pallas", hist_subtraction="on")

    with tempfile.TemporaryDirectory(prefix="ddt_kernel_smoke_") as td:
        log = os.path.join(td, "run.jsonl")
        fused = api.train(Xb, y, cfg, binned=True, log_every=10**9,
                          run_log=log).ensemble
        gran = Driver(get_backend(cfg), cfg, log_every=10**9,
                      profile=True).fit(Xb, y)
        for field in ("feature", "threshold_bin", "is_leaf"):
            if not np.array_equal(getattr(fused, field),
                                  getattr(gran, field)):
                print(f"kernel smoke: fused/granular {field} diverged",
                      file=sys.stderr)
                return 1
        if not np.allclose(fused.leaf_value, gran.leaf_value,
                           rtol=1e-5, atol=1e-6):
            print("kernel smoke: fused/granular leaf values diverged",
                  file=sys.stderr)
            return 1

        # Compiled-program span check on a tiny twin of the grow program.
        rng = np.random.default_rng(0)
        Xs = jnp.asarray(rng.integers(0, 31, size=(300, 5),
                                      dtype=np.uint8))
        gs = jnp.asarray(rng.standard_normal(300).astype(np.float32))
        hs = jnp.asarray((rng.random(300) * 0.2 + 0.01).astype(np.float32))
        txt = jax.jit(functools.partial(
            grow_ops.grow_tree, max_depth=2, n_bins=31, reg_lambda=1.0,
            min_child_weight=1e-3, min_split_gain=0.0,
            hist_impl="pallas", hist_subtraction=True,
        )).lower(Xs, gs, hs).compile().as_text()
        spans = ["ddt:fused_round", "ddt:hist:subtract", "ddt:hist:stream",
                 "ddt:hist:flush", "ddt:gain", "ddt:route"]
        missing = [s for s in spans if s not in txt]
        if missing:
            print(f"kernel smoke: spans missing from the compiled grow "
                  f"program: {missing}", file=sys.stderr)
            return 1

        events = report.read_events(log)      # validates every record
        got = {e["event"] for e in events}
        need = {"run_manifest", "round", "counters", "run_end"}
        if not need <= got:
            print(f"kernel smoke: missing events {need - got}",
                  file=sys.stderr)
            return 1
        phases = {p["phase"] for e in events if e["event"] == "phase_timings"
                  for p in e["phases"]}
        if not {"grow_block", "fetch_tree"} <= phases:
            print(f"kernel smoke: fused phases missing from the run log "
                  f"(got {sorted(phases)})", file=sys.stderr)
            return 1

        # Quantized arm (ISSUE 14): interpret-mode int8 kernel parity —
        # pallas == segment BITWISE on integer gradients — plus a
        # 2-round int8 train's run-log smoke.
        from ddt_tpu.ops import histogram as hist_ops
        from ddt_tpu.ops.hist_pallas import build_histograms_pallas

        qg = jnp.asarray(rng.integers(-127, 128, size=300, dtype=np.int8))
        qh = jnp.asarray(rng.integers(0, 128, size=300, dtype=np.int8))
        ni = jnp.asarray(rng.integers(-1, 4, size=300).astype(np.int32))
        pal = build_histograms_pallas(Xs, qg, qh, ni, 4, 31, interpret=True)
        seg = hist_ops.build_histograms_segment(Xs, qg, qh, ni, 4, 31)
        if pal.dtype != jnp.int32 or not bool((pal == seg).all()):
            print("kernel smoke: quantized pallas/segment parity broke "
                  f"(dtype {pal.dtype})", file=sys.stderr)
            return 1
        qlog = os.path.join(td, "run_q.jsonl")
        cfg_q = cfg.replace(grad_dtype="int8")
        api.train(Xb, y, cfg_q, binned=True, log_every=10**9, run_log=qlog)
        qevents = report.read_events(qlog)
        man = next(e for e in qevents if e["event"] == "run_manifest")
        if man.get("grad_dtype") != "int8":
            print("kernel smoke: run manifest lost grad_dtype",
                  file=sys.stderr)
            return 1
        cnt = next(e for e in qevents if e["event"] == "counters")
        if cnt.get("grad_quant_rounds", 0) < 1 or \
                cnt.get("grad_stream_bytes_est", 0) <= 0:
            print("kernel smoke: quantized counters missing from the run "
                  f"log (got {cnt})", file=sys.stderr)
            return 1
        print(json.dumps({"smoke": "kernel", "ok": True,
                          "spans": spans, "phases": sorted(phases),
                          "quant": {"pallas_bitwise": True,
                                    "grad_dtype": man["grad_dtype"]}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
