#!/usr/bin/env python
"""Prep a Criteo click-log file into streamable npz shards.

Input: the tab-separated Criteo format — label, 13 integer features
(empty = missing), 26 categorical hex ids (empty = missing) — either the
Kaggle DAC train.txt or one day_N file of the 1TB click logs.

Output: <out_dir>/chunk_NNNNN.npz shards (arrays X float32 [rows, 39],
y) consumable by `python -m ddt_tpu.cli train --stream-dir=<out_dir>`.
Integer features pass through as floats (missing -> NaN: train with
--missing=learn, or 0 by default policy); categorical ids are
STATELESS hash-binned (data.categorical.hash_bin_categoricals) so the
prep is one O(chunk)-memory pass — the frequency encoder would need a
global counting pass, wrong trade at 1TB. Hash bins are already in
[0, cat_bins): declare them identity-binned categorical columns via
--cat-splits=onehot semantics by training with a config file setting
cat_features to columns 13..38.

UNTESTED IN CI: the build environment has no network and no real Criteo
file (docs/REAL_DATA.md); the format parsing below follows the published
Criteo layout.

Usage: prep_criteo.py <train.txt[.gz]> <out_dir> [--chunk-rows N]
       [--cat-bins N] [--max-rows N]
"""

import argparse
import gzip
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddt_tpu.data.categorical import hash_bin_categoricals  # noqa: E402

N_INT, N_CAT = 13, 26


def _open(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path, "r")


def _parse_lines(lines, cat_bins):
    """(X [rows, 39] float32, y) for one batch of raw lines."""
    rows = len(lines)
    Xi = np.full((rows, N_INT), np.nan, np.float32)
    Xc = np.zeros((rows, N_CAT), np.int64)
    y = np.zeros(rows, np.int64)
    for r, ln in enumerate(lines):
        parts = ln.rstrip("\n").split("\t")
        if len(parts) != 1 + N_INT + N_CAT:
            raise ValueError(
                f"expected {1 + N_INT + N_CAT} tab-separated fields, got "
                f"{len(parts)}: {ln[:80]!r}")
        y[r] = int(parts[0])
        for j in range(N_INT):
            v = parts[1 + j]
            if v:
                Xi[r, j] = float(v)
        for j in range(N_CAT):
            v = parts[1 + N_INT + j]
            Xc[r, j] = int(v, 16) if v else -1
    Xcb = hash_bin_categoricals(Xc, n_bins=cat_bins).astype(np.float32)
    return np.concatenate([Xi, Xcb], axis=1), y


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src")
    ap.add_argument("out_dir")
    ap.add_argument("--chunk-rows", type=int, default=2_000_000)
    ap.add_argument("--cat-bins", type=int, default=255)
    ap.add_argument("--max-rows", type=int, default=None)
    args = ap.parse_args()

    os.makedirs(args.out_dir, exist_ok=True)
    c = total = 0
    buf: list[str] = []
    with _open(args.src) as f:
        for ln in f:
            buf.append(ln)
            total += 1
            if len(buf) == args.chunk_rows:
                X, y = _parse_lines(buf, args.cat_bins)
                np.savez(os.path.join(args.out_dir, f"chunk_{c:05d}.npz"),
                         X=X, y=y)
                print(f"chunk_{c:05d}: {len(y)} rows "
                      f"(ctr={y.mean():.4f})")
                c += 1
                buf = []
            if args.max_rows and total >= args.max_rows:
                break
    if buf:
        X, y = _parse_lines(buf, args.cat_bins)
        np.savez(os.path.join(args.out_dir, f"chunk_{c:05d}.npz"),
                 X=X, y=y)
        print(f"chunk_{c:05d}: {len(y)} rows (ctr={y.mean():.4f})")
        c += 1
    print(f"wrote {c} shards, {total} rows -> {args.out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
