#!/usr/bin/env python
"""Tier-1-safe xprof capture-window smoke (`make profile-smoke`): train 2
rounds on the CPU backend-interpreted XLA platform with a run log AND a
programmatic capture window over rounds 1:2, then assert

- the window actually started and stopped (a trace directory exists
  under <dir>/run_<run_id> and holds profiler output),
- the run manifest carries the cross-reference fields the flight
  recorder joins on (`xprof_dir` pointing at that directory,
  `xprof_rounds` = the requested window, `run_id` embedded in the path),
- the log still renders through `report` (the window must not perturb
  the telemetry stream).

tests/test_observatory.py runs this in-process; this script is the
one-command end-to-end witness (docs/OBSERVABILITY.md). Exit 0 iff the
whole pipeline holds.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from ddt_tpu import api
    from ddt_tpu.telemetry import report
    from ddt_tpu.telemetry.events import RunLog
    from ddt_tpu.telemetry.profiler import CaptureWindow

    rng = np.random.default_rng(0)
    Xb = rng.integers(0, 23, size=(1024, 5), dtype=np.uint8)
    y = (Xb[:, 0] > 11).astype(np.float32)

    with tempfile.TemporaryDirectory(prefix="ddt_profile_smoke_") as td:
        log_path = os.path.join(td, "run.jsonl")
        xprof_root = os.path.join(td, "xprof")
        window = CaptureWindow(xprof_root, "1:2")
        with RunLog(log_path) as rl:
            api.train(Xb, y, binned=True, n_trees=2, max_depth=3,
                      n_bins=23, backend="tpu", run_log=rl,
                      profiler_window=window)

        events = report.read_events(log_path)
        manifest = next(e for e in events if e["event"] == "run_manifest")
        run_id = manifest.get("run_id")
        fails = []
        if not run_id:
            fails.append("manifest carries no run_id")
        if manifest.get("xprof_rounds") != [1, 2]:
            fails.append(f"manifest xprof_rounds = "
                         f"{manifest.get('xprof_rounds')!r}, wanted [1, 2]")
        xdir = manifest.get("xprof_dir")
        if not xdir or os.path.basename(xdir) != f"run_{run_id}":
            fails.append(f"manifest xprof_dir {xdir!r} does not embed "
                         f"run_{run_id}")
        if xdir != window.trace_dir:
            fails.append("manifest xprof_dir disagrees with the window")
        trace_files = []
        if xdir and os.path.isdir(xdir):
            for dirpath, _dirs, fns in os.walk(xdir):
                trace_files.extend(os.path.join(dirpath, f) for f in fns)
        if not trace_files:
            fails.append(f"no profiler output under {xdir!r}")
        if window.active:
            fails.append("capture window still open after fit")
        # The window must not perturb the stream: summary still renders.
        summary = report.summarize(events)
        if summary["completed_rounds"] != 2:
            fails.append(f"completed_rounds = "
                         f"{summary['completed_rounds']}, wanted 2")
        if fails:
            for f in fails:
                print(f"profile smoke: {f}", file=sys.stderr)
            return 1
        print(json.dumps({
            "smoke": "profile", "ok": True, "run_id": run_id,
            "xprof_dir": os.path.basename(xdir),
            "trace_files": len(trace_files),
            "rounds": manifest["xprof_rounds"],
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
