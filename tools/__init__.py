# Repo-local developer tooling (not shipped with the ddt_tpu package).
