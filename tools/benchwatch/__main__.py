"""CLI: `python -m tools.benchwatch [artifacts...]` — exit 1 on a bench
regression, 0 on a clean bill, 2 when there is nothing to check.

Default (no arguments): glob BENCH_r*.json + MULTICHIP_r*.json in the
repo root, treat the newest of each kind as the current run and the
rest as history — the `make benchwatch` mode. `--current` points at a
fresh `python bench.py` output file instead (then every globbed
artifact is history).
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.benchwatch import (
    MIN_HISTORY, collect_default_paths, run)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.benchwatch",
        description="bench-artifact regression sentinel (median/MAD band "
                    "per metric; one-sided, adverse direction only)")
    ap.add_argument("paths", nargs="*",
                    help="artifact files (default: BENCH_r*.json + "
                         "MULTICHIP_r*.json in the cwd)")
    ap.add_argument("--current", default=None,
                    help="treat THIS file as the current run (all "
                         "positional/globbed artifacts become history)")
    ap.add_argument("--min-history", type=int, default=MIN_HISTORY,
                    help="minimum history samples before a metric is "
                         f"banded (default {MIN_HISTORY}; fewer = skipped)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON object")
    args = ap.parse_args(argv)

    paths = args.paths or collect_default_paths()
    if not paths and args.current is None:
        print("benchwatch: no artifacts found (BENCH_r*.json / "
              "MULTICHIP_r*.json)", file=sys.stderr)
        return 2

    report = run(paths, current_path=args.current,
                 min_history=args.min_history)
    if args.json:
        print(json.dumps(report))
    else:
        if report.get("error"):
            print(f"benchwatch: ERROR {report['error']}")
        for p in report.get("excluded_injected", []):
            print(f"benchwatch: excluded {p} (injected-fault chaos run; "
                  "not performance history)")
        b = report.get("bench")
        if b and b.get("skipped_injected"):
            print(f"benchwatch: {b['skipped_injected']}")
            b = None
        if b:
            print(f"benchwatch: {b['current_path']} vs {b['n_history']} "
                  f"history artifact(s): {len(b['checked'])} in band, "
                  f"{len(b['skipped'])} skipped (thin history), "
                  f"{len(b['regressions'])} regression(s)")
            for r in b["regressions"]:
                want = ">=" if r["direction"] == "higher" else "<="
                bound = (r["median"] - r["tolerance"]
                         if r["direction"] == "higher"
                         else r["median"] + r["tolerance"])
                print(f"  REGRESSION {r['metric']}: {r['current']} "
                      f"(band {want} {round(bound, 4)}; median "
                      f"{r['median']} ± {r['tolerance']} over "
                      f"{r['n_history']} runs)")
        for m in report["multichip"]:
            state = "FAIL" if m["regressions"] else "ok"
            print(f"benchwatch: multichip {m['path']}: {state}")
            for r in m["regressions"]:
                print(f"  REGRESSION {r['metric']}: {r['current']} "
                      f"(expected {r['expected']})")
        print(f"benchwatch: {'OK' if report['ok'] else 'REGRESSION'}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
