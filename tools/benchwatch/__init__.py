"""benchwatch — the bench-artifact regression sentinel (`make benchwatch`).

Five BENCH_r*.json / MULTICHIP_r*.json artifacts accumulated with zero
consumers; this tool is the consumer. It ingests the artifact history
plus a "current" run, computes a robust per-metric band (median ± the
larger of K·MAD and a relative floor), and exits nonzero when the
current run sits ADVERSELY outside the band — a one-sided check, so a
pleasantly fast run never fails the gate.

Why median/MAD with a relative floor instead of mean/σ or MAD alone:
the remote-tunnel throughput drifts in ±20% bands run to run
(docs/PERF.md drift analysis), so (a) the mean is polluted by band
outliers a median shrugs off, and (b) with ~5 samples that happen to
land in one band the raw MAD collapses toward zero and would flag
ordinary band-hopping — the REL_FLOOR (default 20% of the median)
keeps the gate wider than the known noise while a real 30% regression
still trips it. Metrics with fewer than MIN_HISTORY samples are
reported as skipped, never guessed at.

Artifact shapes accepted (load_artifact):
- driver-harness wrappers: {"n": .., "rc": .., "tail": .., "parsed":
  {metrics...}} — BENCH_r*.json;
- raw bench.py output: the metrics dict itself (has "metric"/"value");
- multichip dryrun records: {"n_devices", "rc", "ok", "skipped",
  "tail"} — checked as pass/fail facts (ok must be true, rc 0), not
  banded.

Metric directions are EXPLICIT (METRICS below): an unknown numeric
field is skipped, never auto-classified — silently banding a field
whose good direction we guessed wrong would invert the gate. Ordering:
artifacts sort by the harness round number (the wrapper's `n` field,
falling back to the rNN in the filename; a raw bench.py output has
neither and sorts first — point the gate at it with --current, which
is the intended mode for a fresh run). The run_id/git_rev stamps
bench.py writes are identity/provenance — a flagged excursion names
the rev it appeared at — not the sort key.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re

#: metric -> direction whose LOSS is a regression.
#: "higher": smaller-than-band current value fails; "lower": larger fails.
METRICS: dict[str, str] = {
    "value": "higher",                               # hist Mrows/s/chip
    "vs_baseline": "higher",
    "hist_one_dispatch_mrows_per_sec": "higher",
    "hist_one_dispatch_mrows_per_sec_min": "higher",
    "value_64bin_optin": "higher",
    "ab_ratio_64bin": "higher",
    # hist_fused_roofline_hbm_util is context-only (NOT banded) for the
    # same reason as hist_roofline_hbm_util below: lowering the fused
    # round's bytes-accessed is the design direction, so a drop is an
    # improvement and a "higher" band would invert the gate.
    "hist_fused_mrows_per_sec": "higher",
    "hist_fused_ab_ratio": "higher",
    "hist_fused_roofline_flops_util": "higher",
    # Split-comms A/B (ISSUE 10): losing the reduce-scatter wallclock
    # edge, the scattered arm's throughput, or the deterministic payload
    # reduction are all regressions.
    "hist_comms_ab_ratio": "higher",
    "hist_comms_rs_mrows_per_sec": "higher",
    "hist_comms_payload_ratio": "higher",
    # 2D-mesh A/B (ISSUE 11): losing the (rows x features) layout's
    # wallclock edge at the wide shape, the 2D arm's throughput, or the
    # deterministic second-axis payload reduction are all regressions.
    "hist_2d_ab_ratio": "higher",
    "hist_2d_mrows_per_sec": "higher",
    "hist_2d_payload_ratio": "higher",
    # Quantized-gradient A/B (ISSUE 14): paired f32/int8 wallclock
    # ratio, the quantized arm's throughput, and the deterministic g/h
    # HBM-stream byte ratio — all better when higher.
    "hist_quant_ab_ratio": "higher",
    "hist_quant_mrows_per_sec": "higher",
    "hist_quant_payload_ratio": "higher",
    "e2e_train_s": "lower",
    "e2e_ms_per_tree": "lower",
    "e2e_implied_hist_mrows": "higher",
    "predict_mrows_per_sec": "higher",
    "predict_total_s": "lower",
    "predict_compute_mrows_per_sec": "higher",
    "predict_pallas_mrows_per_sec": "higher",
    "predict_onehot_mrows_per_sec": "higher",
    "predict_pallas_ab_ratio": "higher",
    # Roofline utilization stamps (cost observatory): achieved/peak
    # fractions from XLA's cost model at the measured wallclock — losing
    # utilization is a regression even when absolute throughput drift
    # hides it inside the tunnel bands. hist_roofline_hbm_util is
    # deliberately NOT banded since bench schema v2: the VMEM-streaming
    # histogram kernel LOWERS bytes-accessed by design (the hist verdict
    # flipping hbm -> compute is the kernel campaign's goal), so a drop
    # against pre-rewrite history is the fix landing, not a regression;
    # flops_util stays the banded hist signal.
    "hist_roofline_flops_util": "higher",
    "predict_roofline_flops_util": "higher",
    "predict_roofline_hbm_util": "higher",
    "split_agreement": "higher",
    "auc_delta": "lower",
    # Serving tier (ISSUE 8): LATENCY IS LOWER-IS-BETTER — the first
    # metrics in this table whose regression direction is a rise in
    # milliseconds, stamped from bench_serve_latency's headline QPS
    # point. serve_cold_over_p99 (the acceptance ratio) and the
    # coalesce width band higher: losing either means the admission
    # batcher degenerated even if absolute latency drift hides it.
    # serve_cold_predict_ms is context only (NOT banded): it measures
    # first-call compile cost, which jax version bumps legitimately
    # move in either direction.
    "serve_p50_ms": "lower",
    "serve_p99_ms": "lower",
    "serve_p999_ms": "lower",
    "serve_cold_over_p99": "higher",
    "serve_coalesce_mean": "higher",
    "serve_coalesce_max": "higher",
    # Quantized LUT arm (chip artifacts): throughput and the paired
    # ratio band higher; the witnessed max-abs-error bands LOWER — a
    # quantizer change that widens real error past its documented bound
    # already asserts in-bench, but a creeping (still-in-bound) rise is
    # exactly what a band catches.
    "predict_lut_mrows_per_sec": "higher",
    "predict_lut_ab_ratio": "higher",
    "predict_lut_max_abs_err": "lower",
    # int4 bit-packed tier + express lane (ISSUE 12): same sign
    # conventions — tier throughput/paired-ratio band higher, the
    # witnessed error bands lower, and the express lane's single-row
    # latencies band lower next to the other serve_* milliseconds.
    # express_gain (coalesced-over-express at an empty queue) bands
    # higher: losing it means the lane stopped bypassing the admission
    # window even if absolute latency drift hides it.
    "predict_lut4_mrows_per_sec": "higher",
    "predict_lut4_ab_ratio": "higher",
    "predict_lut4_max_abs_err": "lower",
    "serve_express_empty_p99_ms": "lower",
    "serve_express_saturated_p99_ms": "lower",
    "serve_coalesced_saturated_p99_ms": "lower",
    "serve_express_gain": "higher",
}

#: metric -> minimum bench_schema whose artifacts are comparable. When a
#: metric's MEANING changes (not just its value), bench.py bumps
#: BENCH_SCHEMA and the entry here keeps older artifacts out of that
#: metric's band — banding a redefined quantity against pre-redefinition
#: history would flag the redefinition itself as a regression (and hide
#: real ones behind the semantic shift). Metrics absent here band across
#: every schema. v2: e2e_implied_hist_mrows counts EFFECTIVE levels
#: (1 + (depth-1)/2) when the sibling-subtraction trick is active.
METRIC_MIN_SCHEMA: dict[str, int] = {
    "e2e_implied_hist_mrows": 2,
}

MAD_K = 3.0          # band half-width in MADs...
REL_FLOOR = 0.20     # ...but never narrower than 20% of |median|
MIN_HISTORY = 3      # metrics with fewer samples are skipped, not banded

DEFAULT_GLOBS = ("BENCH_r*.json", "MULTICHIP_r*.json")


def load_artifact(path: str) -> dict:
    """Parse one artifact file into {"path", "kind", "order", "metrics",
    "facts"}. kind: "bench" | "multichip" | "unknown". `order` is the
    history sort key (run_id-stamped artifacts keep their harness round
    as primary order; the stamp makes the identity robust, the round the
    sequence). `facts` are pass/fail booleans (multichip ok/rc)."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    rec = raw.get("parsed", raw) if isinstance(raw, dict) else {}
    if not isinstance(rec, dict):
        rec = {}
    kind = "unknown"
    facts = {}
    if "metric" in rec or "value" in rec:
        kind = "bench"
    elif "n_devices" in raw or "ok" in raw:
        kind = "multichip"
        facts = {"ok": bool(raw.get("ok", False)),
                 "rc": int(raw.get("rc", 1)),
                 "skipped": bool(raw.get("skipped", False))}
    metrics = {k: float(v) for k, v in rec.items()
               if k in METRICS and isinstance(v, (int, float))
               and not isinstance(v, bool)}
    order = raw.get("n") if isinstance(raw, dict) else None
    if order is None:
        m = re.search(r"r(\d+)", os.path.basename(path))
        order = int(m.group(1)) if m else 0
    schema = rec.get("bench_schema")
    # Chaos-run exclusion (docs/ROBUSTNESS.md): bench.py stamps
    # injected_faults when a fault-injection plan was active, and an
    # attached run log's injected `fault` events count too — numbers
    # measured under injected faults are recovery tests, not
    # performance history, and banding against them would widen (or
    # poison) every band.
    injected = bool(rec.get("injected_faults")) or (
        isinstance(raw, dict) and bool(raw.get("injected_faults")))
    if not injected:
        run_events = rec.get("run_log_events") or (
            raw.get("run_log_events") if isinstance(raw, dict) else None)
        if isinstance(run_events, list):
            injected = any(
                isinstance(e, dict) and e.get("event") == "fault"
                and e.get("kind") == "injected" for e in run_events)
    return {"path": path, "kind": kind, "order": int(order),
            "metrics": metrics, "facts": facts,
            "schema": int(schema) if isinstance(schema, int) else 1,
            "injected_faults": injected,
            "run_id": rec.get("run_id"), "git_rev": rec.get("git_rev")}


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def robust_band(vals: list[float]) -> tuple[float, float]:
    """(median, tolerance): tolerance = max(MAD_K * MAD,
    REL_FLOOR * |median|) — the adverse deviation the gate accepts."""
    med = _median(vals)
    mad = _median([abs(v - med) for v in vals])
    return med, max(MAD_K * mad, REL_FLOOR * abs(med))


def check(history: list[dict], current: dict,
          min_history: int = MIN_HISTORY) -> dict:
    """Band every shared metric of `current` (a load_artifact record of
    kind "bench") against `history` (same-kind records). Returns
    {"regressions": [...], "checked": [...], "skipped": [...]} —
    regressions carry metric, direction, current, median, tolerance."""
    regressions, checked, skipped = [], [], []
    for name, cur in sorted(current["metrics"].items()):
        min_schema = METRIC_MIN_SCHEMA.get(name, 0)
        vals = [h["metrics"][name] for h in history
                if name in h["metrics"]
                and h.get("schema", 1) >= min_schema]
        if len(vals) < min_history:
            skipped.append({"metric": name, "history": len(vals)})
            continue
        med, tol = robust_band(vals)
        direction = METRICS[name]
        delta = cur - med
        adverse = -delta if direction == "higher" else delta
        rec = {"metric": name, "direction": direction,
               "current": cur, "median": round(med, 4),
               "tolerance": round(tol, 4), "n_history": len(vals)}
        if adverse > tol:
            regressions.append(rec)
        else:
            checked.append(rec)
    return {"regressions": regressions, "checked": checked,
            "skipped": skipped}


def check_facts(current: dict) -> list[dict]:
    """Pass/fail facts of a multichip record: a current artifact that
    FAILED (ok false / rc nonzero) is a regression regardless of
    history; a skipped run (no devices) is not."""
    f = current.get("facts") or {}
    if not f or f.get("skipped"):
        return []
    fails = []
    if not f.get("ok", False):
        fails.append({"metric": "multichip.ok", "current": False,
                      "expected": True, "path": current["path"]})
    if f.get("rc", 1) != 0:
        fails.append({"metric": "multichip.rc", "current": f.get("rc"),
                      "expected": 0, "path": current["path"]})
    return fails


def run(paths: list[str], current_path: str | None = None,
        min_history: int = MIN_HISTORY) -> dict:
    """The sentinel over a set of artifact files. Without
    `current_path`, the newest artifact of each kind (by `order`) is the
    current run and the rest are its history — `make benchwatch`'s
    zero-argument mode. Returns the full report dict; "ok" is the exit
    verdict."""
    arts = [load_artifact(p) for p in paths]
    report: dict = {"ok": True, "bench": None, "multichip": [],
                    "files": len(arts)}
    cur_art = None
    if current_path is not None:
        cur_art = load_artifact(current_path)
        report["current"] = current_path
        if cur_art["kind"] == "unknown":
            # A current run the loader cannot classify must FAIL, not
            # silently fall back to re-banding the newest history file
            # as if it were the run under test.
            report["ok"] = False
            report["error"] = (
                f"--current {current_path}: unrecognized artifact shape "
                "(no bench metrics, no multichip facts) — schema drift "
                "or a torn write; nothing was checked")
            return report
    # Injected-fault artifacts (chaos runs) never enter bench history,
    # and a chaos artifact under test is excluded rather than banded —
    # its numbers measure recovery, not performance.
    excluded = [a["path"] for a in arts
                if a["kind"] == "bench" and a.get("injected_faults")]
    if excluded:
        report["excluded_injected"] = excluded
    bench = sorted((a for a in arts if a["kind"] == "bench"
                    and not a.get("injected_faults")),
                   key=lambda a: a["order"])
    if cur_art is not None and cur_art["kind"] == "bench":
        if cur_art.get("injected_faults"):
            report["excluded_injected"] = (
                report.get("excluded_injected", []) + [cur_art["path"]])
            report["bench"] = {
                "skipped_injected": "current artifact carries "
                                    "injected-fault events; not banded"}
            current = None
            history = bench
        else:
            history, current = bench, cur_art
    elif bench:
        history, current = bench[:-1], bench[-1]
    else:
        history = current = None
    if current is not None:
        res = check(history, current, min_history=min_history)
        res["current_path"] = current["path"]
        res["n_history"] = len(history)
        report["bench"] = res
        if res["regressions"]:
            report["ok"] = False
    multichip = [a for a in arts if a["kind"] == "multichip"]
    if cur_art is not None and cur_art["kind"] == "multichip":
        multichip = [cur_art]
    elif multichip:
        multichip = [sorted(multichip, key=lambda a: a["order"])[-1]]
    for a in multichip:
        fails = check_facts(a)
        report["multichip"].append(
            {"path": a["path"], "regressions": fails})
        if fails:
            report["ok"] = False
    return report


def collect_default_paths(root: str = ".") -> list[str]:
    out: list[str] = []
    for g in DEFAULT_GLOBS:
        out.extend(sorted(_glob.glob(os.path.join(root, g))))
    return out
