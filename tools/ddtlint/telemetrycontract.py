"""Mechanized telemetry-schema contract (ddtlint v3, ISSUE 16).

The run log's schema is enforced at emit time only for REQUIRED fields
(telemetry/events.validate_event); extras — the additive growth
mechanism every version bump note leans on — were convention. This pass
reads the catalogs statically out of the parsed trees and turns the
convention into lint findings:

* `undeclared-event-kind` — an `.emit("<kind>", ...)` with a literal
  kind not in EVENT_FIELDS (today a runtime ValueError on the first
  emit — this moves it to lint time), and a fault kind (the literal in
  `emit_fault("<kind>", ...)` or `.emit("fault", kind="<kind>", ...)`)
  not in the FAULT_KINDS catalog — a typo'd kind is a fault event every
  report query silently misses.
* `undeclared-event-extra` — a literal keyword at an emit site that is
  neither a required field nor a declared extra (EVENT_EXTRAS, fnmatch
  globs like "valid_*" allowed): undeclared extras are schema drift no
  reader knows to look for. The counter registry cross-check rides the
  same rule: every counter the run log publishes (the `_c` dict plus
  the epilogue's peak-memory keys) must be declared on the `counters`
  event.
* `counter-direction-missing` — every published counter must have a
  COUNTER_DIRECTIONS entry ("lower"/"higher"/"neutral"): `report diff`
  can only flag an adverse move when it knows which direction adverse
  IS, and an unregistered counter was silently un-banded (the satellite
  runtime fix marks those `direction=?` — this rule makes the state
  unreachable).
* `event-schema-additivity` — a required field ADDED to an existing
  kind under an unchanged schema version breaks every reader of old
  logs (they lack the field and read-side validation rejects them);
  the pinned v5 snapshot below is the comparison base. New kinds and
  new extras are additive and free; a version bump retires the pin.

Emit sites with non-literal kinds or `**kwargs` payloads are skipped —
missed findings over false positives, the ratchet's standing bias.
Variable-kind fault emits are covered at the catalog end instead: the
kind string must exist SOMEWHERE in FAULT_KINDS for report to group it.

`python -m tools.ddtlint --explain-telemetry` dumps the derived
contract; docs/OBSERVABILITY.md embeds it between
`ddtlint:telemetry-contract` markers and tests/test_lint.py keeps the
two in sync (the SERVING.md thread-model pattern from PR 13).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from fnmatch import fnmatchcase

from tools.ddtlint import callgraph
from tools.ddtlint.base import Checker
from tools.ddtlint.findings import Finding

SCOPE = (r"^ddt_tpu/",)

RULE_KIND = "undeclared-event-kind"
RULE_EXTRA = "undeclared-event-extra"
RULE_DIRECTION = "counter-direction-missing"
RULE_ADDITIVITY = "event-schema-additivity"

RULES = (RULE_KIND, RULE_EXTRA, RULE_DIRECTION, RULE_ADDITIVITY)

VALID_DIRECTIONS = ("higher", "lower", "neutral")

#: The schema-v5 required-field sets, PINNED at the version this rule
#: shipped under. Additivity is checked against this snapshot: growing a
#: kind's required set without bumping SCHEMA_VERSION is the finding.
#: When SCHEMA_VERSION moves past 5 the pin retires (the rule skips) and
#: the snapshot should be re-pinned at the new version in the same PR.
PINNED_SCHEMA_VERSION = 5
PINNED_REQUIRED = {
    "run_manifest": frozenset({"trainer", "backend", "loss", "n_trees",
                               "max_depth", "rows", "features"}),
    "round": frozenset({"round", "ms_per_round"}),
    "phase_timings": frozenset({"phases"}),
    "partition_phases": frozenset({"round", "partitions"}),
    "partition_skew": frozenset({"phases"}),
    "early_stop": frozenset({"round", "best_round", "best_score",
                             "metric"}),
    "fault": frozenset({"kind"}),
    "counters": frozenset({"jit_compiles", "h2d_bytes", "d2h_bytes",
                           "collective_bytes_est"}),
    "cost_analysis": frozenset({"op", "flops", "bytes_accessed"}),
    "artifact": frozenset({"action", "digest"}),
    "serve_latency": frozenset({"requests", "p50_ms", "p99_ms"}),
    # ISSUE 17 (serve-side operations plane): new kind, additive under
    # v5 — pinned at birth so its required set cannot silently grow.
    "serve_trace": frozenset({"traces"}),
    # ISSUE 19 (drift observatory): new kind, additive under v5 —
    # pinned at birth like serve_trace.
    "drift": frozenset({"psi_max"}),
    # ISSUE 20 (training operations plane): new kind, additive under
    # v5 — pinned at birth like serve_trace/drift.
    "train_heartbeat": frozenset({"round"}),
    "run_end": frozenset({"completed_rounds", "wallclock_s"}),
}


def in_scope(path: str) -> bool:
    return any(re.search(p, path) for p in SCOPE)


@dataclass
class TelemetryModel:
    """Statically-read catalogs + computed findings."""

    events_path: "str | None" = None
    events_line: int = 0                      # EVENT_FIELDS assign line
    schema_version: "int | None" = None
    required: dict = field(default_factory=dict)   # kind -> frozenset
    kind_lines: dict = field(default_factory=dict)  # kind -> line
    extras: "dict | None" = None              # kind -> tuple of patterns
    fault_kinds: "tuple | None" = None
    fault_line: int = 0
    #: counter -> (path, line): the `_c` registry keys plus the run-log
    #: epilogue's subscript-added keys (the peak-memory pair).
    counter_lines: dict = field(default_factory=dict)
    directions: "dict | None" = None          # counter -> direction str
    directions_site: "tuple | None" = None    # (path, line)
    findings: list = field(default_factory=list)    # Finding (no line_text)


def _emit(m: TelemetryModel, rule: str, path: str, node,
          message: str) -> None:
    m.findings.append(Finding(
        rule=rule, path=path, line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1, message=message))


def _str_elts(node: ast.AST) -> "list | None":
    """Tuple/List/Set of string constants -> their (value, node) pairs;
    `set()` / `()` count as empty; None when the shape doesn't match."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append((e.value, e))
        return out
    if isinstance(node, ast.Call):
        d = callgraph.dotted(node.func)
        if d == "set" and not node.args:
            return []
    return None


def _assign_targets(node: ast.AST) -> list:
    if isinstance(node, ast.Assign):
        return [t for t in node.targets if isinstance(t, ast.Name)]
    if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
        return [node.target]
    return []


# --------------------------------------------------------------------- #
# anchor extraction
# --------------------------------------------------------------------- #
def _read_anchors(m: TelemetryModel, trees: dict) -> None:
    for path, tree in sorted(trees.items()):
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            names = {t.id for t in _assign_targets(node)}
            v = node.value
            if v is None:
                continue
            if "EVENT_FIELDS" in names and isinstance(v, ast.Dict) \
                    and m.events_path is None:
                m.events_path, m.events_line = path, node.lineno
                for k, val in zip(v.keys, v.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        continue
                    elts = _str_elts(val)
                    if elts is None:
                        continue
                    m.required[k.value] = frozenset(s for s, _ in elts)
                    m.kind_lines[k.value] = k.lineno
            elif "EVENT_EXTRAS" in names and isinstance(v, ast.Dict) \
                    and m.extras is None:
                extras: dict = {}
                for k, val in zip(v.keys, v.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        continue
                    elts = _str_elts(val)
                    if elts is not None:
                        extras[k.value] = tuple(s for s, _ in elts)
                m.extras = extras
            elif "FAULT_KINDS" in names and m.fault_kinds is None:
                elts = _str_elts(v)
                if elts:
                    m.fault_kinds = tuple(s for s, _ in elts)
                    m.fault_line = node.lineno
            elif "SCHEMA_VERSION" in names and m.schema_version is None \
                    and isinstance(v, ast.Constant) \
                    and isinstance(v.value, int):
                m.schema_version = v.value
            elif "_c" in names and isinstance(v, ast.Dict) \
                    and not m.counter_lines:
                for k in v.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        m.counter_lines[k.value] = (path, k.lineno)
            elif "COUNTER_DIRECTIONS" in names and isinstance(v, ast.Dict) \
                    and m.directions is None:
                m.directions = {}
                m.directions_site = (path, node.lineno)
                for k, val in zip(v.keys, v.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str) \
                            and isinstance(val, ast.Constant):
                        m.directions[k.value] = val.value


def _epilogue_counter_keys(m: TelemetryModel, trees: dict) -> None:
    """Keys subscript-assigned into a dict that is then splatted into an
    `.emit("counters", **d)` call — the finish_run_log peak-memory pair.
    They publish exactly like `_c` keys, so the direction + declaration
    rules must see them."""
    for path, tree in sorted(trees.items()):
        if tree is None:
            continue
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            splat_vars = set()
            for n in ast.walk(fn):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "emit" and n.args \
                        and isinstance(n.args[0], ast.Constant) \
                        and n.args[0].value == "counters":
                    for k in n.keywords:
                        if k.arg is None and isinstance(k.value, ast.Name):
                            splat_vars.add(k.value.id)
            if not splat_vars:
                continue
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in splat_vars \
                                and isinstance(t.slice, ast.Constant) \
                                and isinstance(t.slice.value, str):
                            m.counter_lines.setdefault(
                                t.slice.value, (path, t.lineno))


# --------------------------------------------------------------------- #
# emit-site checks
# --------------------------------------------------------------------- #
def _allowed(m: TelemetryModel, kind: str, name: str) -> bool:
    if name in m.required.get(kind, ()):
        return True
    return any(fnmatchcase(name, pat)
               for pat in (m.extras or {}).get(kind, ()))


def _check_kwargs(m: TelemetryModel, path: str, call: ast.Call,
                  kind: str, skip: "set | None" = None) -> None:
    if m.extras is None:
        return                     # extras catalog unresolved: no guessing
    for k in call.keywords:
        if k.arg is None or (skip and k.arg in skip):
            continue
        if kind == "fault" and k.arg == "kind":
            if isinstance(k.value, ast.Constant) \
                    and isinstance(k.value.value, str) \
                    and m.fault_kinds is not None \
                    and k.value.value not in m.fault_kinds:
                _emit(m, RULE_KIND, path, k.value, (
                    f"fault kind {k.value.value!r} is not in the "
                    f"FAULT_KINDS catalog ({m.events_path}:"
                    f"{m.fault_line}) — report's fault table silently "
                    "drops kinds it cannot group; declare it "
                    "(docs/ANALYSIS.md undeclared-event-kind)"))
            continue
        if not _allowed(m, kind, k.arg):
            _emit(m, RULE_EXTRA, path, k.value, (
                f"`{k.arg}=` is neither a required field nor a declared "
                f"extra of the {kind!r} event — undeclared extras are "
                "schema drift no reader knows to look for; declare it "
                f"in EVENT_EXTRAS ({m.events_path}) "
                "(docs/ANALYSIS.md undeclared-event-extra)"))


def _check_emits(m: TelemetryModel, trees: dict) -> None:
    if not m.required:
        return
    for path, tree in sorted(trees.items()):
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "emit":
                if not (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                kind = node.args[0].value
                if kind not in m.required:
                    _emit(m, RULE_KIND, path, node.args[0], (
                        f"event kind {kind!r} is not declared in "
                        f"EVENT_FIELDS ({m.events_path}:{m.events_line}) "
                        "— today this is a ValueError on the first emit; "
                        "declare the kind (with its required fields) or "
                        "fix the typo "
                        "(docs/ANALYSIS.md undeclared-event-kind)"))
                    continue
                _check_kwargs(m, path, node, kind)
            else:
                d = callgraph.dotted(f)
                if d is None or d.split(".")[-1] != "emit_fault":
                    continue
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and m.fault_kinds is not None \
                        and node.args[0].value not in m.fault_kinds:
                    _emit(m, RULE_KIND, path, node.args[0], (
                        f"fault kind {node.args[0].value!r} is not in "
                        f"the FAULT_KINDS catalog ({m.events_path}:"
                        f"{m.fault_line}) — report's fault table "
                        "silently drops kinds it cannot group; declare "
                        "it (docs/ANALYSIS.md undeclared-event-kind)"))
                _check_kwargs(m, path, node, "fault")


# --------------------------------------------------------------------- #
# catalog-level checks
# --------------------------------------------------------------------- #
def _check_counters(m: TelemetryModel) -> None:
    if not m.counter_lines:
        return
    if m.required and m.extras is not None and "counters" in m.required:
        for key, (path, line) in sorted(m.counter_lines.items()):
            if not _allowed(m, "counters", key):
                _emit(m, RULE_EXTRA, path, _Pos(line), (
                    f"counter {key!r} is published on the `counters` "
                    "event but not declared there (required or "
                    f"EVENT_EXTRAS, {m.events_path}) — a counter no "
                    "reader knows to look for "
                    "(docs/ANALYSIS.md undeclared-event-extra)"))
    if m.directions is None:
        return
    dp, dl = m.directions_site
    for key, (path, line) in sorted(m.counter_lines.items()):
        direction = m.directions.get(key)
        if direction is None:
            _emit(m, RULE_DIRECTION, path, _Pos(line), (
                f"counter {key!r} has no COUNTER_DIRECTIONS entry "
                f"({dp}:{dl}) — `report diff` cannot band a counter "
                "whose adverse direction it does not know and renders "
                "it direction=?; declare \"lower\", \"higher\", or "
                "\"neutral\" (never flagged) "
                "(docs/ANALYSIS.md counter-direction-missing)"))
        elif direction not in VALID_DIRECTIONS:
            _emit(m, RULE_DIRECTION, path, _Pos(line), (
                f"counter {key!r} declares direction {direction!r} — "
                f"COUNTER_DIRECTIONS values must be one of "
                f"{'/'.join(VALID_DIRECTIONS)} ({dp}:{dl}) "
                "(docs/ANALYSIS.md counter-direction-missing)"))


def _check_additivity(m: TelemetryModel) -> None:
    if m.schema_version != PINNED_SCHEMA_VERSION or not m.required:
        return
    for kind in sorted(m.required):
        pinned = PINNED_REQUIRED.get(kind)
        if pinned is None:
            continue                    # new kinds are additive and free
        grown = sorted(m.required[kind] - pinned)
        if grown:
            _emit(m, RULE_ADDITIVITY, m.events_path,
                  _Pos(m.kind_lines.get(kind, m.events_line)), (
                      f"required field(s) {', '.join(grown)} added to "
                      f"existing event kind {kind!r} under schema "
                      f"v{PINNED_SCHEMA_VERSION} — old logs lack the "
                      "field and read-side validation now rejects them; "
                      "make it an EVENT_EXTRAS entry (additive) or bump "
                      "SCHEMA_VERSION and re-pin the snapshot in "
                      "tools/ddtlint/telemetrycontract.py "
                      "(docs/ANALYSIS.md event-schema-additivity)"))


class _Pos:
    def __init__(self, lineno: int, col_offset: int = 0):
        self.lineno = lineno
        self.col_offset = col_offset


# --------------------------------------------------------------------- #
# model construction
# --------------------------------------------------------------------- #
def build(trees: dict) -> TelemetryModel:
    """{relpath: parsed ast.Module} -> the package-wide telemetry model
    with findings computed. All catalog anchors are found by NAME
    (EVENT_FIELDS, EVENT_EXTRAS, FAULT_KINDS, SCHEMA_VERSION, _c,
    COUNTER_DIRECTIONS) so fixture files can embed a self-contained
    mini-catalog; unresolved anchors make their rules skip, not guess."""
    m = TelemetryModel()
    _read_anchors(m, trees)
    _epilogue_counter_keys(m, trees)
    _check_emits(m, trees)
    _check_counters(m)
    _check_additivity(m)
    return m


# --------------------------------------------------------------------- #
# the checker (runner wiring)
# --------------------------------------------------------------------- #
class TelemetryContractChecker(Checker):
    """Emits this file's slice of the package-wide telemetry model's
    findings (runner builds ONE model over the default scope so emit
    sites check against the real catalogs; fixture tests get a
    single-file model built on demand)."""

    rule = RULE_KIND
    rules = RULES
    path_scope = SCOPE

    def run(self):
        m = self.ctx.telemetry_model
        if m is None:
            m = build({self.ctx.path: self.ctx.tree})
        for f in m.findings:
            if f.path != self.ctx.path:
                continue
            self.findings.append(Finding(
                rule=f.rule, path=f.path, line=f.line, col=f.col,
                message=f.message,
                line_text=self.ctx.line_text(f.line)))
        return self.findings


# --------------------------------------------------------------------- #
# --explain-telemetry
# --------------------------------------------------------------------- #
def explain(m: TelemetryModel) -> str:
    """Byte-stable dump of the derived contract — docs/OBSERVABILITY.md
    embeds it between `ddtlint:telemetry-contract` markers and
    tests/test_lint.py keeps the two in sync."""
    out = ["telemetry contract (tools/ddtlint --explain-telemetry)"]
    out.append(f"schema: v{m.schema_version}")
    out.append("events (required | extras):")
    for kind in sorted(m.required):
        req = ", ".join(sorted(m.required[kind]))
        ext = ", ".join(sorted((m.extras or {}).get(kind, ()))) or "-"
        out.append(f"  {kind}: {req} | {ext}")
    out.append("fault kinds:")
    for k in sorted(m.fault_kinds or ()):
        out.append(f"  {k}")
    out.append("counter directions:")
    for k in sorted(m.counter_lines):
        out.append(f"  {k}: {(m.directions or {}).get(k, '?')}")
    return "\n".join(out) + "\n"


CHECKERS = [TelemetryContractChecker]
