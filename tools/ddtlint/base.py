"""Checker base + per-file context — shared by checkers.py and the
flow-aware pass modules (shardspec.py, threadmodel.py), which subclass
`Checker` without importing the whole rule catalogue (no import cycle).
"""

from __future__ import annotations

import ast
import re

from tools.ddtlint.findings import Finding


class CheckContext:
    """Per-file inputs plus the project-level facts checkers share."""

    def __init__(self, path: str, source: str, tree: ast.AST,
                 mesh_axes: set[str] | None = None,
                 reachable: set[str] | None = None,
                 layout_rules: "list[str] | None" = None,
                 thread_model=None, config_model=None,
                 telemetry_model=None):
        self.path = path                      # repo-relative, fwd slashes
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.mesh_axes = mesh_axes if mesh_axes is not None else set()
        self.reachable = reachable if reachable is not None else set()
        #: SpecLayout.rules() regexes (shardspec.layout_rule_patterns);
        #: None = table unresolved, coverage rule skips.
        self.layout_rules = layout_rules
        #: package-wide threadmodel.ThreadModel for the serve tier; None
        #: = build a single-file model on demand (fixture tests).
        self.thread_model = thread_model
        #: package-wide configflow.ConfigModel / telemetrycontract.
        #: TelemetryModel; None = single-file on demand (fixture tests).
        self.config_model = config_model
        self.telemetry_model = telemetry_model

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class Checker(ast.NodeVisitor):
    rule = "base"
    #: multi-rule checkers (the threadmodel pass) list every rule id
    #: they can emit; None = just `rule`. Used by --rules selection.
    rules: tuple[str, ...] | None = None
    #: relpath regexes this rule runs on (None = every scanned .py file)
    path_scope: tuple[str, ...] | None = None

    def __init__(self, ctx: CheckContext):
        self.ctx = ctx
        self.findings: list[Finding] = []

    @classmethod
    def rule_set(cls) -> set[str]:
        return set(cls.rules) if cls.rules is not None else {cls.rule}

    @classmethod
    def applies_to(cls, relpath: str) -> bool:
        if cls.path_scope is None:
            return True
        return any(re.search(p, relpath) for p in cls.path_scope)

    def report(self, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        self.findings.append(Finding(
            rule=self.rule, path=self.ctx.path, line=line,
            col=getattr(node, "col_offset", 0) + 1, message=message,
            line_text=self.ctx.line_text(line),
        ))

    def run(self) -> list[Finding]:
        self.visit(self.ctx.tree)
        return self.findings
