"""CLI: `python -m tools.ddtlint [paths...]` — exit 0 iff no new findings.

See docs/ANALYSIS.md for the rule catalogue and the baseline workflow.
"""

from __future__ import annotations

import argparse
import sys

from tools.ddtlint import checkers, runner

ALL_RULES = sorted(
    [c.rule for c in checkers.AST_CHECKERS] + [checkers.SUPPRESSION_RULE])


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.ddtlint",
        description="project-native static analysis for JAX/TPU hazards")
    ap.add_argument("paths", nargs="*", default=["ddt_tpu/", "tests/"],
                    help="files/dirs to lint (default: ddt_tpu/ tests/)")
    ap.add_argument("--baseline", default=runner.DEFAULT_BASELINE,
                    help=f"ratchet file (default {runner.DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the ratchet")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(r)
        return 0

    rules = None
    if args.rules:
        rules = set(args.rules.split(","))
        unknown = rules - set(ALL_RULES)
        if unknown:
            print(f"ddtlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    findings = runner.lint_paths(args.paths or ["ddt_tpu/", "tests/"],
                                 rules=rules)

    if args.write_baseline:
        runner.save_baseline(args.baseline, findings)
        print(f"ddtlint: wrote {len(findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    baseline = {} if args.no_baseline else runner.load_baseline(args.baseline)
    new, known, stale = runner.split_vs_baseline(findings, baseline)

    if not args.quiet:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"ddtlint: stale baseline entry (fixed? ratchet it out "
                  f"with --write-baseline): {e['path']} [{e['rule']}] "
                  f"{e.get('line_text', '')}")
    print(f"ddtlint: {len(findings)} finding(s): {len(new)} new, "
          f"{len(known)} baselined, {len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'}")
    # stale entries fail too (matching tests/test_lint.py's gate): a fixed
    # finding must be ratcheted out so the baseline only ever shrinks.
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
