"""CLI: `python -m tools.ddtlint [paths...]` — exit 0 iff no new findings.

See docs/ANALYSIS.md for the rule catalogue and the baseline workflow.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.ddtlint import checkers, runner, telemetrycontract, threadmodel

ALL_RULES = sorted(
    {r for c in checkers.AST_CHECKERS for r in c.rule_set()}
    | {checkers.SUPPRESSION_RULE})


def _json_payload(findings, new, known, stale) -> dict:
    """Stable machine-readable output (--format json): findings sorted
    by position (assign_fingerprints already did), keys fixed — the
    contract scripts/lint_smoke.py and CI consumers parse."""
    def enc(f):
        return {"rule": f.rule, "path": f.path, "line": f.line,
                "col": f.col, "message": f.message,
                "line_text": f.line_text.strip(),
                "fingerprint": f.fingerprint}

    return {
        "findings": [enc(f) for f in findings],
        "new": [enc(f) for f in new],
        "stale_baseline": stale,
        "summary": {"total": len(findings), "new": len(new),
                    "baselined": len(known), "stale": len(stale)},
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.ddtlint",
        description="project-native static analysis for JAX/TPU hazards")
    ap.add_argument("paths", nargs="*", default=["ddt_tpu/", "tests/"],
                    help="files/dirs to lint (default: ddt_tpu/ tests/)")
    ap.add_argument("--baseline", default=runner.DEFAULT_BASELINE,
                    help=f"ratchet file (default {runner.DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the ratchet")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs the git merge-base "
                         "(falls back to a full scan without git); stale "
                         "baseline entries are only checked for scanned "
                         "files")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="output format (json: one stable object on "
                         "stdout — the scripts/lint_smoke.py contract)")
    ap.add_argument("--explain-threads", action="store_true",
                    help="dump the serve tier's inferred threading model "
                         "(roles, locks, publish points, lock-order "
                         "edges) instead of linting — reviewers diff "
                         "this across serve PRs (docs/SERVING.md)")
    ap.add_argument("--explain-telemetry", action="store_true",
                    help="dump the derived telemetry contract (event "
                         "kinds, extras, fault kinds, counter "
                         "directions) instead of linting — "
                         "docs/OBSERVABILITY.md embeds this block and "
                         "the doc-sync test keeps the two aligned")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(r)
        return 0

    root = os.getcwd()

    if args.explain_threads:
        files = runner._walk_py(args.paths or ["ddt_tpu/"], root)
        trees, sources = {}, {}
        for rel in files:
            if not threadmodel.in_scope(rel):
                continue
            with open(os.path.join(root, rel), encoding="utf-8",
                      errors="replace") as f:
                sources[rel] = f.read()
            trees[rel] = runner._parse(sources[rel])
        model = threadmodel.build(trees, sources)
        print(threadmodel.explain(model), end="")
        return 0

    if args.explain_telemetry:
        files = runner._walk_py(args.paths or ["ddt_tpu/"], root)
        trees = {}
        for rel in files:
            if not telemetrycontract.in_scope(rel) \
                    or not rel.endswith(".py"):
                continue
            with open(os.path.join(root, rel), encoding="utf-8",
                      errors="replace") as f:
                trees[rel] = runner._parse(f.read())
        model = telemetrycontract.build(trees)
        print(telemetrycontract.explain(model), end="")
        return 0

    rules = None
    if args.rules:
        rules = set(args.rules.split(","))
        unknown = rules - set(ALL_RULES)
        if unknown:
            print(f"ddtlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    if args.changed_only and args.write_baseline:
        # A partial-scope scan must never REWRITE the ratchet: the
        # baseline would be truncated to just the changed files'
        # findings, destroying every unscanned file's curated entry.
        print("ddtlint: --write-baseline requires a full scan; drop "
              "--changed-only", file=sys.stderr)
        return 2

    only_files = None
    if args.changed_only:
        only_files = runner.changed_files(root)
        if only_files is None and args.format == "text":
            print("ddtlint: --changed-only: no git merge-base available; "
                  "falling back to a full scan", file=sys.stderr)

    findings = runner.lint_paths(args.paths or ["ddt_tpu/", "tests/"],
                                 rules=rules, only_files=only_files)

    if args.write_baseline:
        runner.save_baseline(args.baseline, findings)
        print(f"ddtlint: wrote {len(findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    baseline = {} if args.no_baseline else runner.load_baseline(args.baseline)
    scanned = None
    if only_files is not None:
        scanned = {f for f in runner._walk_py(
            args.paths or ["ddt_tpu/", "tests/"], root) if f in only_files}
    new, known, stale = runner.split_vs_baseline(findings, baseline,
                                                 scanned=scanned)

    if args.format == "json":
        print(json.dumps(_json_payload(findings, new, known, stale),
                         indent=1, sort_keys=False))
        return 1 if (new or stale) else 0

    if not args.quiet:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"ddtlint: stale baseline entry (fixed? ratchet it out "
                  f"with --write-baseline): {e['path']} [{e['rule']}] "
                  f"{e.get('line_text', '')}")
    print(f"ddtlint: {len(findings)} finding(s): {len(new)} new, "
          f"{len(known)} baselined, {len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'}")
    # stale entries fail too (matching tests/test_lint.py's gate): a fixed
    # finding must be ratcheted out so the baseline only ever shrinks.
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
