"""Config-flow contract analysis (ddtlint v3, ISSUE 16).

The backend-by-flag contract means one TrainConfig must deterministically
select one traced program — yet PR 14 found the exact failure mode
reachable: `_JIT_FIELDS` missed `grad_dtype`, so a cached f32 backend
silently served a quantized config. This pass mechanizes the audit that
found it, so the NEXT trace-shaping field cannot drift out of the
contracts:

* `jit-cache-key-coverage` — every `cfg.<field>` read reachable inside a
  jit trace (callgraph.py's roots + closure, over ddt_tpu/backends/,
  ddt_tpu/ops/, ddt_tpu/streaming.py) must be covered by the backend
  cache key: the `_JIT_FIELDS` tuple plus the explicit trailing terms
  `_cache_key` itself reads (seed under bagging/quantization). An
  uncovered read means a cached instance compiled under a DIFFERENT
  value of that field can be silently reused — the PR 14 bug, as a lint
  finding at the read site citing the tuple it should join.
* `fingerprint-field-coverage` — the checkpoint resume gate
  (`utils/checkpoint._cfg_fingerprint`) must place every TrainConfig
  field in exactly one of {fingerprinted, excluded-with-reason}: an
  exclude-list entry naming no current field is stale (a renamed field
  silently rejoined the fingerprint — or never left it), and a
  non-asdict fingerprint that enumerates fields must enumerate all of
  them.
* `config-field-orphan` — (a) a TrainConfig field covered by NO
  contract (not in the cache key, excluded from the fingerprint, and
  not annotated trace-inert at its declaration) is invisible to every
  mechanism that keys on config identity; (b) a `derive_run_id(...)`
  call site must cover every field (`**dataclasses.asdict(cfg)` or an
  explicit full enumeration) — the run id is the cross-host merge key
  and "no field may be left out" is its documented contract.

The one escape hatch is `# ddtlint: trace-inert — <why>` (the reason is
REQUIRED): on a read line it asserts the read never shapes the traced
program (e.g. a host-side branch outside the trace the callgraph
over-approximates into it); on a config.py declaration line it asserts
the field deliberately belongs to no contract. Annotations that
suppress nothing (the line has no uncovered read / the field already
has a contract) are flagged under the existing suppression-hygiene rule
— an annotation that outlives its hazard exempts whatever lands on the
line next.

Every contract input is read STATICALLY out of the parsed trees by
anchor name (class TrainConfig, the `_JIT_FIELDS` tuple, the
`_cache_key` / `_cfg_fingerprint` defs), so fixture files can embed a
self-contained mini-contract; when an anchor cannot be found the rules
that need it skip rather than guess (the shardspec precedent).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from tools.ddtlint import callgraph
from tools.ddtlint.base import Checker
from tools.ddtlint.findings import Finding

#: files the checker emits on (the contract spans the whole package).
SCOPE = (r"^ddt_tpu/",)
#: files whose jit-reachable cfg reads the cache-key rule audits — the
#: tracing roots (backends), the traced bodies (ops), and the streaming
#: driver whose helpers feed scan/fori bodies.
TRACE_SCOPE = (r"^ddt_tpu/backends/", r"^ddt_tpu/ops/",
               r"^ddt_tpu/streaming\.py$")

RULE_CACHE_KEY = "jit-cache-key-coverage"
RULE_FINGERPRINT = "fingerprint-field-coverage"
RULE_ORPHAN = "config-field-orphan"
#: stale / reason-less trace-inert annotations report under the existing
#: suppression-hygiene rule (an annotation is a suppression).
RULE_STALE = "suppression-hygiene"

RULES = (RULE_CACHE_KEY, RULE_FINGERPRINT, RULE_ORPHAN, RULE_STALE)

#: `# ddtlint: trace-inert — <why>`; the reason group is None when
#: missing (itself a suppression-hygiene finding — an unexplained
#: exemption is unreviewable).
TRACE_INERT_RE = re.compile(
    r"#\s*ddtlint:\s*trace-inert(?:\s*(?:—|–|--|-)\s*(\S.*))?")


def in_scope(path: str) -> bool:
    return any(re.search(p, path) for p in SCOPE)


def in_trace_scope(path: str) -> bool:
    return any(re.search(p, path) for p in TRACE_SCOPE)


def _recv_is_cfg(node: ast.Attribute) -> bool:
    """True for `cfg.x` / `self.cfg.x` / `be.cfg.x` — any receiver chain
    whose last segment is the `cfg` idiom the codebase uses for the
    frozen TrainConfig."""
    d = callgraph.dotted(node.value)
    return d is not None and d.split(".")[-1] == "cfg"


def _cfg_reads(fn: ast.AST) -> set[str]:
    """Field names read off a cfg receiver anywhere inside `fn`."""
    return {n.attr for n in ast.walk(fn)
            if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load)
            and _recv_is_cfg(n)}


@dataclass
class ConfigModel:
    """Statically-read contract state + computed findings."""

    fields: dict = field(default_factory=dict)       # name -> (path, line)
    config_path: "str | None" = None
    jit_fields: set = field(default_factory=set)
    jit_site: "tuple | None" = None                  # (path, line)
    cache_reads: set = field(default_factory=set)    # _cache_key return-expr reads
    fp_path: "str | None" = None
    fp_line: int = 0
    fp_asdict: bool = False
    fp_excluded: dict = field(default_factory=dict)  # name -> line (fp_path)
    fp_reads: set = field(default_factory=set)       # explicit enumeration
    #: path -> {line: reason-or-None} trace-inert annotations
    annotations: dict = field(default_factory=dict)
    used: set = field(default_factory=set)           # (path, line) that suppressed
    traced_reads: list = field(default_factory=list)  # (path, node, fieldname)
    runid_calls: list = field(default_factory=list)   # (path, Call)
    findings: list = field(default_factory=list)      # Finding (no line_text)

    @property
    def covered(self) -> set:
        """Fields the backend cache key accounts for."""
        return self.jit_fields | self.cache_reads

    @property
    def fingerprinted(self) -> set:
        if self.fp_asdict:
            return set(self.fields) - set(self.fp_excluded)
        return set(self.fp_reads)

    @property
    def resolved(self) -> bool:
        """All three anchors found — the orphan audit and annotation
        staleness are only decidable with the full contract picture."""
        return bool(self.fields) and self.jit_site is not None \
            and self.fp_path is not None


def _emit(m: ConfigModel, rule: str, path: str, node, message: str) -> None:
    m.findings.append(Finding(
        rule=rule, path=path, line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1, message=message))


class _Line:
    """Position shim for findings anchored to a source LINE (annotation
    hygiene) rather than an AST node."""

    def __init__(self, lineno: int, col_offset: int = 0):
        self.lineno = lineno
        self.col_offset = col_offset


# --------------------------------------------------------------------- #
# anchor extraction
# --------------------------------------------------------------------- #
def _read_fingerprint(m: ConfigModel, path: str, fn: ast.AST) -> None:
    m.fp_path, m.fp_line = path, fn.lineno
    m.fp_reads = _cfg_reads(fn)
    pop_loop_vars: dict[str, list] = {}
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            d = callgraph.dotted(n.func)
            last = d.split(".")[-1] if d else None
            if last == "asdict":
                m.fp_asdict = True
            elif last == "pop" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                m.fp_excluded[n.args[0].value] = n.args[0].lineno
            elif last == "pop" and n.args \
                    and isinstance(n.args[0], ast.Name):
                pop_loop_vars.setdefault(n.args[0].id, [])
    # `for k in ("a", "b", ...): d.pop(k, ...)` — the exclude-list idiom
    for n in ast.walk(fn):
        if isinstance(n, ast.For) and isinstance(n.target, ast.Name) \
                and n.target.id in pop_loop_vars \
                and isinstance(n.iter, (ast.Tuple, ast.List)):
            for e in n.iter.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    m.fp_excluded[e.value] = e.lineno


def _read_anchors(m: ConfigModel, trees: dict) -> None:
    for path, tree in sorted(trees.items()):
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) \
                    and node.name == "TrainConfig" and not m.fields:
                m.config_path = path
                for ch in ast.iter_child_nodes(node):
                    if isinstance(ch, ast.AnnAssign) \
                            and isinstance(ch.target, ast.Name):
                        m.fields[ch.target.id] = (path, ch.lineno)
            elif isinstance(node, ast.Assign) and m.jit_site is None \
                    and any(isinstance(t, ast.Name) and t.id == "_JIT_FIELDS"
                            for t in node.targets) \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                vals = {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
                if vals:
                    m.jit_fields = vals
                    m.jit_site = (path, node.lineno)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == "_cache_key":
                    # Only reads in the RETURN expression join the key.
                    # A read that merely gates another term (seed_live's
                    # `cfg.grad_dtype != "f32"` test) does not make the
                    # key distinguish values of that field — treating it
                    # as covered would have hidden the PR 14 bug.
                    for st in ast.walk(node):
                        if isinstance(st, ast.Return) \
                                and st.value is not None:
                            m.cache_reads |= _cfg_reads(st.value)
                elif node.name == "_cfg_fingerprint" and m.fp_path is None:
                    _read_fingerprint(m, path, node)
            elif isinstance(node, ast.Call):
                d = callgraph.dotted(node.func)
                if d is not None and d.split(".")[-1] == "derive_run_id":
                    m.runid_calls.append((path, node))


def _set_annotations(m: ConfigModel, sources: dict) -> None:
    for path, src in sorted(sources.items()):
        per: dict = {}
        for i, line in enumerate(src.splitlines(), start=1):
            hit = TRACE_INERT_RE.search(line)
            if hit:
                per[i] = hit.group(1)
        if per:
            m.annotations[path] = per


class _TracedReadVisitor(ast.NodeVisitor):
    """cfg-field reads + their enclosing function qualname, matching
    callgraph._Collector's qualname convention (class names included) so
    the reachability sets line up."""

    def __init__(self, m: ConfigModel, path: str, reachable: set):
        self.m = m
        self.path = path
        self.reachable = reachable
        self.stack: list[str] = []
        self.fn_stack: list[str] = []

    def _visit_func(self, node):
        qual = ".".join(self.stack + [node.name])
        self.stack.append(node.name)
        self.fn_stack.append(qual)
        self.generic_visit(node)
        self.fn_stack.pop()
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.ctx, ast.Load) and node.attr in self.m.fields \
                and _recv_is_cfg(node) and self.fn_stack \
                and self.fn_stack[-1] in self.reachable:
            self.m.traced_reads.append((self.path, node, node.attr))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# model construction + findings
# --------------------------------------------------------------------- #
def build(trees: dict, sources: "dict | None" = None,
          reachable: "dict | None" = None) -> ConfigModel:
    """{relpath: parsed ast.Module} -> the package-wide config-flow model
    with findings computed. `sources` (same keys) resolves trace-inert
    annotation lines; `reachable` reuses the runner's callgraph result
    ({relpath: jit-reachable qualnames}) — computed here when absent
    (fixture tests), from the SAME trees (no re-parse)."""
    m = ConfigModel()
    sources = sources or {}
    _set_annotations(m, sources)
    _read_anchors(m, trees)

    if m.fields:
        if reachable is None:
            reachable = callgraph.build(
                {p: sources.get(p, "") for p in trees}, trees=trees)
        for path, tree in sorted(trees.items()):
            if tree is None or not in_trace_scope(path):
                continue
            _TracedReadVisitor(m, path, reachable.get(path, set())).visit(tree)

    _find_cache_key(m)
    _find_fingerprint(m)
    _find_orphans(m)
    _find_runid(m)
    _find_annotation_hygiene(m)
    return m


def _annotated(m: ConfigModel, path: str, line: int) -> bool:
    return line in m.annotations.get(path, {})


def _find_cache_key(m: ConfigModel) -> None:
    if not m.fields or m.jit_site is None:
        return
    jp, jl = m.jit_site
    for path, node, fname in m.traced_reads:
        if fname in m.covered:
            continue
        if _annotated(m, path, node.lineno):
            m.used.add((path, node.lineno))
            continue
        _emit(m, RULE_CACHE_KEY, path, node, (
            f"`cfg.{fname}` is read inside a jit-traced region but is "
            "not part of the backend cache key — a cached backend "
            f"compiled under a different {fname} would be silently "
            f"reused (the PR 14 grad_dtype bug); add {fname!r} to "
            f"_JIT_FIELDS ({jp}:{jl}) or, if the read provably never "
            "shapes the trace, annotate it "
            "`# ddtlint: trace-inert — <why>` "
            "(docs/ANALYSIS.md jit-cache-key-coverage)"))


def _find_fingerprint(m: ConfigModel) -> None:
    if not m.fields or m.fp_path is None:
        return
    cpath = m.config_path or "ddt_tpu/config.py"
    for name, line in sorted(m.fp_excluded.items()):
        if name not in m.fields:
            _emit(m, RULE_FINGERPRINT, m.fp_path, _Line(line), (
                f"fingerprint exclude entry {name!r} names no current "
                "TrainConfig field — a renamed or removed field left a "
                "stale exclusion behind, and the field that replaced it "
                "is being fingerprinted (or excluded) by accident; "
                f"update the exclude list to match {cpath} "
                "(docs/ANALYSIS.md fingerprint-field-coverage)"))
    if not m.fp_asdict:
        missing = sorted(set(m.fields) - m.fp_reads - set(m.fp_excluded))
        if missing:
            _emit(m, RULE_FINGERPRINT, m.fp_path, _Line(m.fp_line), (
                "_cfg_fingerprint enumerates fields explicitly but "
                f"omits {', '.join(missing)} — every TrainConfig field "
                "must be fingerprinted or excluded-with-reason, or a "
                "checkpoint resumes under a silently different config; "
                "use dataclasses.asdict(cfg) + an exclude list "
                "(docs/ANALYSIS.md fingerprint-field-coverage)"))


def _find_orphans(m: ConfigModel) -> None:
    if not m.resolved:
        return
    jp, jl = m.jit_site
    fingerprinted = m.fingerprinted
    for name, (cpath, cline) in sorted(m.fields.items()):
        if name in m.covered or name in fingerprinted:
            continue
        if _annotated(m, cpath, cline):
            m.used.add((cpath, cline))
            continue
        _emit(m, RULE_ORPHAN, cpath, _Line(cline), (
            f"TrainConfig field {name!r} belongs to NO config contract: "
            f"not in the backend cache key (_JIT_FIELDS, {jp}:{jl}) and "
            f"excluded from the checkpoint fingerprint ({m.fp_path}:"
            f"{m.fp_line}) — no mechanism that keys on config identity "
            "can see it change; wire it into a contract or annotate the "
            "declaration `# ddtlint: trace-inert — <why>` "
            "(docs/ANALYSIS.md config-field-orphan)"))


def _find_runid(m: ConfigModel) -> None:
    """derive_run_id call sites must cover every TrainConfig field —
    `**dataclasses.asdict(cfg)` (the idiom) always does; an explicit
    kwarg enumeration is checked field-by-field; an opaque `**other` is
    statically unresolvable and skipped (missed findings over false
    positives)."""
    if not m.fields:
        return
    for path, call in m.runid_calls:
        starred = [k for k in call.keywords if k.arg is None]
        if starred:
            if any(isinstance(k.value, ast.Call)
                   and (d := callgraph.dotted(k.value.func)) is not None
                   and d.split(".")[-1] == "asdict" for k in starred):
                continue                      # full coverage by construction
            continue                          # opaque **kwargs: unresolvable
        explicit = {k.arg for k in call.keywords if k.arg}
        missing = sorted(set(m.fields) - explicit)
        if missing:
            shown = ", ".join(missing[:4]) + \
                (f", ... ({len(missing)} total)" if len(missing) > 4 else "")
            _emit(m, RULE_ORPHAN, path, call, (
                f"derive_run_id call leaves out TrainConfig field(s) "
                f"{shown} — the run id is the cross-host merge key and "
                "two configs differing in ANY field must derive "
                "different ids; pass `**dataclasses.asdict(cfg)` "
                "(docs/ANALYSIS.md config-field-orphan)"))


def _find_annotation_hygiene(m: ConfigModel) -> None:
    """Reason-less annotations always flag; annotations that suppressed
    nothing flag only when the full contract picture resolved (a partial
    model cannot tell stale from load-bearing)."""
    for path, per in sorted(m.annotations.items()):
        for line, reason in sorted(per.items()):
            if reason is None:
                _emit(m, RULE_STALE, path, _Line(line), (
                    "`# ddtlint: trace-inert` annotation without a "
                    "reason — the grammar is `# ddtlint: trace-inert — "
                    "<why>`; an unexplained exemption is unreviewable "
                    "(docs/ANALYSIS.md config-field-orphan)"))
            elif m.resolved and (path, line) not in m.used:
                _emit(m, RULE_STALE, path, _Line(line), (
                    "stale `# ddtlint: trace-inert` annotation — this "
                    "line has no uncovered traced cfg read and declares "
                    "no contract-less field, so the annotation exempts "
                    "nothing today and would silently exempt whatever "
                    "lands here next; delete it "
                    "(docs/ANALYSIS.md config-field-orphan)"))


# --------------------------------------------------------------------- #
# the checker (runner wiring)
# --------------------------------------------------------------------- #
class ConfigFlowChecker(Checker):
    """Emits this file's slice of the package-wide config-flow model's
    findings (runner builds ONE model over the default scope so the
    contract anchors, the traced reads, and the declarations resolve
    across files; fixture tests get a single-file model built on demand
    — fixtures embed their own mini-contract anchors)."""

    rule = RULE_CACHE_KEY
    rules = RULES
    path_scope = SCOPE

    def run(self):
        m = self.ctx.config_model
        if m is None:
            m = build({self.ctx.path: self.ctx.tree},
                      {self.ctx.path: self.ctx.source})
        for f in m.findings:
            if f.path != self.ctx.path:
                continue
            self.findings.append(Finding(
                rule=f.rule, path=f.path, line=f.line, col=f.col,
                message=f.message,
                line_text=self.ctx.line_text(f.line)))
        return self.findings


CHECKERS = [ConfigFlowChecker]
