"""Cross-module jit-reachability for the traced-branch checker.

The codebase's tracing roots live in ddt_tpu/backends/ (``jax.jit(grow)``,
``@jax.jit`` methods) while the traced bodies live in ddt_tpu/ops/ — so a
module-local analysis would mark nothing in ops/ as traced.  This builds a
small project-wide call graph instead:

* **roots** — functions decorated with ``jit``/``pjit`` (directly, via
  ``@partial(jax.jit, ...)``), wrapped as ``jax.jit(f)`` call sites, or
  passed by name into JAX tracing combinators (``lax.fori_loop``,
  ``lax.scan``, ``shard_map``, ``vmap``, ...), whose bodies are always
  traced regardless of an enclosing jit.
* **edges** — ``Name(...)`` calls resolved through lexical scopes to
  module-level or nested functions, and ``alias.attr(...)`` calls resolved
  through ``import``/``from-import`` aliases to functions in other scanned
  modules.
* **closure** — BFS from the roots; every function lexically nested inside
  a reachable function is itself reachable (inner ``def``s of a traced
  function trace with it).

Deliberately unsound where Python makes static resolution impossible
(``self.method`` dispatch, functions passed through containers): missed
edges mean missed findings, never false positives — the right bias for a
ratcheting lint gate.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

JIT_NAMES = {"jit", "pjit"}
# Combinators whose function-valued arguments are traced unconditionally.
# pallas_call is one of them: the kernel body is traced (by Mosaic or the
# interpreter), so Pallas kernels are jit-reachability roots — the
# traced-branch rule covers them and the pallas-interpret rule can anchor
# on their call sites.
TRACING_COMBINATORS = {
    "fori_loop", "while_loop", "scan", "cond", "switch",
    "vmap", "pmap", "shard_map", "checkpoint", "remat", "custom_vjp",
    "grad", "value_and_grad", "pallas_call",
}


def dotted(node: ast.AST) -> str | None:
    """`jax.lax.psum` Attribute/Name chain -> "jax.lax.psum", else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.AST) -> str | None:
    """`self.X` -> "X", else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def walk_skip_defs(node: ast.AST):
    """`node` and its descendants, excluding nested function/lambda
    bodies — code inside a nested def does not execute where it is
    defined, so lock state and call events must not leak across the
    boundary. The root is always yielded and always expanded (callers
    pass function nodes as roots on purpose)."""
    yield node
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


# --------------------------------------------------------------------- #
# intra-procedural lock-state tracking (the threadmodel pass, ISSUE 13)
# --------------------------------------------------------------------- #
class LockTracker:
    """Walk ONE function body tracking which `self.<lock>` locks are held
    at each program point.

    Tracked acquisition forms: `with self.lock:` blocks (exact extent,
    multi-item `with` acquires left-to-right), and `self.lock.acquire()`
    ... `self.lock.release()` call pairs (held from the acquire's
    statement to the matching release at the same or an outer statement
    level, else to the end of the function — a sound over-approximation
    matching the try/finally idiom the lock-release rule enforces).
    Non-blocking try-acquires (`acquire(blocking=False)`) still mark the
    lock held on the fallthrough path, but the acquisition event carries
    `blocking=False` so the lock-order pass can exempt them — a trylock
    cannot participate in a deadlock cycle.

    Collected (all with the held-set at that point):
    - `calls`: every Call node (lock-method calls excluded),
    - `accesses`: every `self.<attr>` Load/Store,
    - `acquisitions`: (lock, held_before, blocking, node) per acquire,
    - `acquire_calls`: the explicit `.acquire()` call sites,
    - `finally_releases`: locks `.release()`d inside a `finally:` block.

    Deliberately approximate where Python makes path-sensitivity
    expensive (an acquire in an `if` test marks the lock held for the
    body AND the fallthrough); the bias is over-holding, which for the
    rules built on top means findings fire, never silently pass.
    """

    def __init__(self, lock_attrs: set):
        self.lock_attrs = set(lock_attrs)
        self.calls: list = []            # (Call node, frozenset held)
        self.accesses: list = []         # (attr, "load"|"store", node, held)
        self.acquisitions: list = []     # (lock, held_before, blocking, node)
        self.acquire_calls: list = []    # (lock, Call node)
        self.finally_releases: set = set()

    def run(self, fn: ast.AST) -> "LockTracker":
        self._body(list(fn.body), frozenset())
        return self

    # -- helpers -------------------------------------------------------- #
    def lock_call(self, call: ast.Call):
        """self.X.acquire/release -> ("X", "acquire"/"release"), else
        None (X must be a known lock attribute)."""
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in ("acquire", "release"):
            attr = self_attr(f.value)
            if attr in self.lock_attrs:
                return attr, f.attr
        return None

    @staticmethod
    def _nonblocking(call: ast.Call) -> bool:
        for k in call.keywords:
            if k.arg == "blocking" and isinstance(k.value, ast.Constant) \
                    and k.value.value is False:
                return True
        return (call.args and isinstance(call.args[0], ast.Constant)
                and call.args[0].value is False)

    def _scan(self, node: ast.AST, held: frozenset) -> None:
        """Record calls + self-attr accesses inside `node` (nested defs
        excluded), with `held` active."""
        for n in walk_skip_defs(node):
            if isinstance(n, ast.Call):
                lk = self.lock_call(n)
                if lk is not None:
                    lock, what = lk
                    if what == "acquire":
                        self.acquisitions.append(
                            (lock, held, not self._nonblocking(n), n))
                        self.acquire_calls.append((lock, n))
                    continue
                self.calls.append((n, held))
            elif isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) and n.value.id == "self":
                if isinstance(n.ctx, ast.Store):
                    self.accesses.append((n.attr, "store", n, held))
                elif isinstance(n.ctx, ast.Load):
                    self.accesses.append((n.attr, "load", n, held))

    def _effects(self, stmt: ast.AST) -> tuple:
        """Locks (acquired, released) anywhere inside `stmt` — the net
        state change this statement propagates to its successors."""
        acq, rel = set(), set()
        for n in walk_skip_defs(stmt):
            if isinstance(n, ast.Call):
                lk = self.lock_call(n)
                if lk is not None:
                    (acq if lk[1] == "acquire" else rel).add(lk[0])
        return acq, rel

    # -- the walker ----------------------------------------------------- #
    def _body(self, body: list, held: frozenset) -> None:
        cur = set(held)
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = set(cur)
                for item in stmt.items:
                    attr = self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        self.acquisitions.append(
                            (attr, frozenset(inner), True,
                             item.context_expr))
                        inner.add(attr)
                    else:
                        self._scan(item.context_expr, frozenset(inner))
                self._body(stmt.body, frozenset(inner))
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan(stmt.test, frozenset(cur))
                acq, rel = self._effects(stmt.test)
                branch = (cur | acq) - rel
                self._body(stmt.body, frozenset(branch))
                self._body(stmt.orelse, frozenset(branch))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan(stmt.iter, frozenset(cur))
                self._body(stmt.body, frozenset(cur))
                self._body(stmt.orelse, frozenset(cur))
            elif isinstance(stmt, ast.Try):
                self._body(stmt.body, frozenset(cur))
                for h in stmt.handlers:
                    self._body(h.body, frozenset(cur))
                self._body(stmt.orelse, frozenset(cur))
                self._body(stmt.finalbody, frozenset(cur))
                for n in stmt.finalbody:
                    for c in walk_skip_defs(n):
                        if isinstance(c, ast.Call):
                            lk = self.lock_call(c)
                            if lk is not None and lk[1] == "release":
                                self.finally_releases.add(lk[0])
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                pass                       # separate execution context
            else:
                self._scan(stmt, frozenset(cur))
            # Fall-through state. A release inside a COMPOUND statement
            # executes only on some paths (an early-return branch, an
            # except arm), so it must NOT clear the lock for the code
            # after the statement — only straight-line releases (simple
            # statements) and try/FINALLY releases (run on every path)
            # subtract. Acquires always propagate. This is the
            # documented over-holding bias: branchy releases can only
            # ADD findings, never hide one.
            acq, rel = self._effects(stmt)
            if isinstance(stmt, ast.Try):
                fin_rel = set()
                for fs in stmt.finalbody:
                    for c in walk_skip_defs(fs):
                        if isinstance(c, ast.Call):
                            lk = self.lock_call(c)
                            if lk is not None and lk[1] == "release":
                                fin_rel.add(lk[0])
                cur = (cur | acq) - fin_rel
            elif isinstance(stmt, (ast.If, ast.While, ast.For,
                                   ast.AsyncFor, ast.With, ast.AsyncWith)):
                cur = cur | acq
            else:
                cur = (cur | acq) - rel


def _resolves_to_jit(expr: ast.AST) -> bool:
    """Does a decorator/callee expression denote jit/pjit?  Covers ``jit``,
    ``jax.jit``, ``@partial(jax.jit, ...)`` and ``@jax.jit(...)`` forms."""
    d = dotted(expr)
    if d is not None and d.split(".")[-1] in JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        f = dotted(expr.func)
        if f is not None and f.split(".")[-1] in JIT_NAMES:
            return True
        if f is not None and f.split(".")[-1] == "partial":
            return any(_resolves_to_jit(a) for a in expr.args)
    return False


@dataclass
class FuncInfo:
    qualname: str
    node: ast.AST
    parent: str | None              # enclosing function qualname ("" = module)
    calls_local: set = field(default_factory=set)    # Name callees
    calls_ext: set = field(default_factory=set)      # (alias_or_mod, attr)


@dataclass
class ModuleInfo:
    path: str                       # repo-relative
    modname: str                    # "ddt_tpu.ops.grow"
    funcs: dict = field(default_factory=dict)        # qualname -> FuncInfo
    scopes: dict = field(default_factory=dict)       # scope -> {name: qual}
    imports: dict = field(default_factory=dict)      # alias -> dotted module
    symbols: dict = field(default_factory=dict)      # alias -> (mod, name)
    roots: set = field(default_factory=set)          # qualnames
    _wrap_sites: list = field(default_factory=list)  # (scope, func_name)
    #: `name = functools.partial(fn, ...)` bindings: (scope, name) -> the
    #: partial's function-valued Name args. Wrap sites referencing such a
    #: name root the underlying functions (the predict_pallas idiom:
    #: kernel = partial(_traverse_kernel, ...); pl.pallas_call(kernel,...)).
    _partial_aliases: dict = field(default_factory=dict)


class _Collector(ast.NodeVisitor):
    """One pass per module: functions, scopes, imports, roots, call edges."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.stack: list[str] = []        # qualname parts (incl. class names)
        self.fn_stack: list[str] = []     # enclosing FUNCTION qualnames
        mod.scopes[""] = {}

    # -- scope helpers -------------------------------------------------- #
    def _scope(self) -> str:
        return ".".join(self.stack)

    def _cur_fn(self) -> FuncInfo | None:
        return self.mod.funcs.get(self.fn_stack[-1]) if self.fn_stack else None

    # -- imports -------------------------------------------------------- #
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.mod.imports[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0])
            if a.asname:
                self.mod.imports[a.asname] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom):
        base = node.module or ""
        if node.level:                        # relative: resolve vs package
            pkg = self.mod.modname.split(".")
            pkg = pkg[: len(pkg) - node.level]
            base = ".".join(pkg + ([node.module] if node.module else []))
        for a in node.names:
            alias = a.asname or a.name
            # `from ddt_tpu.ops import histogram` may bind a MODULE or a
            # symbol; record both readings — resolution prefers whichever
            # matches a scanned module.
            self.mod.imports[alias] = f"{base}.{a.name}" if base else a.name
            self.mod.symbols[alias] = (base, a.name)

    # -- functions ------------------------------------------------------ #
    def _visit_func(self, node):
        qual = ".".join(self.stack + [node.name])
        parent = self.fn_stack[-1] if self.fn_stack else ""
        fi = FuncInfo(qual, node, parent)
        self.mod.funcs[qual] = fi
        self.mod.scopes.setdefault(self._scope(), {})[node.name] = qual
        if any(_resolves_to_jit(d) for d in node.decorator_list):
            self.mod.roots.add(qual)
        self.stack.append(node.name)
        self.fn_stack.append(qual)
        self.mod.scopes.setdefault(self._scope(), {})
        for child in node.body:
            self.visit(child)
        self.fn_stack.pop()
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef):
        self.stack.append(node.name)
        self.mod.scopes.setdefault(self._scope(), {})
        for child in node.body:
            self.visit(child)
        self.stack.pop()

    # -- assignments ---------------------------------------------------- #
    def visit_Assign(self, node: ast.Assign):
        v = node.value
        if isinstance(v, ast.Call):
            f = dotted(v.func)
            if f is not None and f.split(".")[-1] == "partial":
                names = [a.id for a in list(v.args)
                         + [k.value for k in v.keywords]
                         if isinstance(a, ast.Name)]
                if names:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.mod._partial_aliases[
                                (self._scope(), t.id)] = names
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------- #
    def visit_Call(self, node: ast.Call):
        callee = dotted(node.func)
        last = callee.split(".")[-1] if callee else None
        # jax.jit(f) wrap sites and lax.fori_loop(..., body, ...) style
        # combinators make their function-valued Name args roots.
        if _resolves_to_jit(node.func) or last in TRACING_COMBINATORS:
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Name):
                    self.mod._wrap_sites.append((self._scope(), a.id))
                elif isinstance(a, ast.Call):
                    # functools.partial(kernel, ...) — the idiomatic way
                    # static parameters reach Pallas kernels (and scan/
                    # fori bodies): the partial's function-valued args
                    # are traced exactly like bare names.
                    f = dotted(a.func)
                    if f is not None and f.split(".")[-1] == "partial":
                        for pa in list(a.args) + [k.value
                                                  for k in a.keywords]:
                            if isinstance(pa, ast.Name):
                                self.mod._wrap_sites.append(
                                    (self._scope(), pa.id))
        fn = self._cur_fn()
        if fn is not None and callee is not None:
            parts = callee.split(".")
            if len(parts) == 1:
                fn.calls_local.add((self._scope(), parts[0]))
            else:
                fn.calls_ext.add((parts[0], parts[-1]))
        self.generic_visit(node)


def _resolve_scoped(mod: ModuleInfo, scope: str, name: str) -> str | None:
    """Find function `name` looking outward from `scope` (lexical)."""
    parts = scope.split(".") if scope else []
    for i in range(len(parts), -1, -1):
        s = ".".join(parts[:i])
        qual = mod.scopes.get(s, {}).get(name)
        if qual is not None:
            return qual
    return None


def build(sources: dict[str, str],
          trees: "dict[str, ast.AST | None] | None" = None
          ) -> dict[str, set[str]]:
    """{relpath: source} -> {relpath: set of jit-reachable func qualnames}.

    `trees` reuses ASTs the caller already parsed (the runner's
    single-parse cache). Files that fail to parse contribute nothing
    (the runner reports syntax errors separately)."""
    mods: dict[str, ModuleInfo] = {}          # modname -> info
    by_path: dict[str, ModuleInfo] = {}
    for path, src in sources.items():
        modname = path[:-3].replace("/", ".") if path.endswith(".py") else path
        if modname.endswith(".__init__"):
            modname = modname[: -len(".__init__")]
        mi = ModuleInfo(path=path, modname=modname)
        tree = trees.get(path) if trees is not None else None
        if tree is None:
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
        _Collector(mi).visit(tree)

        def alias_targets(scope: str, name: str) -> list[str]:
            """partial-alias expansion, looking outward from `scope`."""
            parts = scope.split(".") if scope else []
            for i in range(len(parts), -1, -1):
                s = ".".join(parts[:i])
                if (s, name) in mi._partial_aliases:
                    return mi._partial_aliases[(s, name)]
            return []

        for scope, name in mi._wrap_sites:
            qual = _resolve_scoped(mi, scope, name)
            if qual is not None:
                mi.roots.add(qual)
                continue
            for fn_name in alias_targets(scope, name):
                qual = _resolve_scoped(mi, scope, fn_name)
                if qual is not None:
                    mi.roots.add(qual)
        mods[modname] = mi
        by_path[path] = mi

    def ext_target(mi: ModuleInfo, base: str, attr: str):
        """alias.attr(...) -> (module, funcqual) in another scanned module."""
        target_mod = mi.imports.get(base)
        if target_mod in mods and attr in mods[target_mod].funcs:
            return mods[target_mod], attr
        # `from pkg import sub as base` where pkg.sub is a scanned module
        if base in mi.symbols:
            b, n = mi.symbols[base]
            cand = f"{b}.{n}" if b else n
            if cand in mods and attr in mods[cand].funcs:
                return mods[cand], attr
        return None

    def symbol_target(mi: ModuleInfo, name: str):
        """`from mod import f` call f(...) -> (module, funcqual)."""
        if name in mi.symbols:
            b, n = mi.symbols[name]
            if b in mods and n in mods[b].funcs:
                return mods[b], n
        return None

    # BFS over (module, qualname)
    work = [(mi, q) for mi in mods.values() for q in mi.roots]
    reach: set[tuple[str, str]] = set()
    while work:
        mi, qual = work.pop()
        if (mi.modname, qual) in reach:
            continue
        reach.add((mi.modname, qual))
        fi = mi.funcs.get(qual)
        if fi is None:
            continue
        # lexically nested defs trace with their parent
        prefix = qual + "."
        for q2 in mi.funcs:
            if q2.startswith(prefix):
                work.append((mi, q2))
        for scope, name in fi.calls_local:
            q2 = _resolve_scoped(mi, scope, name)
            if q2 is not None:
                work.append((mi, q2))
            else:
                t = symbol_target(mi, name)
                if t is not None:
                    work.append(t)
        for base, attr in fi.calls_ext:
            t = ext_target(mi, base, attr)
            if t is not None:
                work.append(t)

    out: dict[str, set[str]] = {}
    for path, mi in by_path.items():
        out[path] = {q for (m, q) in reach if m == mi.modname}
    return out
