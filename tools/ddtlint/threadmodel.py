"""Thread-role + lock-discipline analysis for the serve tier (ddtlint v2).

The serving tier is the one place in this codebase where concurrent
mutable state is load-bearing: HTTP handler threads submit into the
admission queue, a dedicated dispatcher thread drains it, the express
lane (ISSUE 12) runs the SAME dispatch path synchronously on handler
threads, and hot swap publishes a new model from whatever thread called
/swap. ROADMAP item 3 (multi-model tenancy, weighted dispatcher, LRU
eviction) multiplies that surface — so this pass mechanizes the review
that used to guard it, BEFORE the tenancy work lands.

The analysis is structural — no annotations beyond the one documented
escape hatch (`# ddtlint: atomic-publish`):

* **Thread roles.** "dispatcher" is the closure of every
  `threading.Thread(target=...)` target; "handler" is the closure of
  every public method / module function (HTTP handler threads, the
  express-lane caller thread, the swap path, tests). Call edges resolve
  `self.m()`, `self.attr.m()` through constructor-derived attribute
  types (`self.stats = ServeStats()`), bare module-function calls, and
  INJECTED CALLABLES (`MicroBatcher(self._dispatch, ...)` binds the
  batcher's stored `self._dispatch` to `ServeEngine._dispatch`, so the
  engine's dispatch body correctly carries BOTH roles: dispatcher via
  the batcher loop, handler via the express lane).
* **Lock state.** `callgraph.LockTracker` walks each method tracking
  which `threading.Lock`/`Condition` attributes are held at every call
  and every `self.<attr>` access (`with lock:` exact; acquire/release
  pairs over-approximated toward "held", so findings fire rather than
  silently pass; `acquire(blocking=False)` try-locks are held but
  exempt from the deadlock graph — a trylock cannot deadlock).

Rules (docs/ANALYSIS.md has the full catalogue):

* `lock-order` — a cycle in the lock-acquisition graph (lock B taken
  while A is held, directly or through resolved calls, and somewhere
  else A while B): the classic inversion deadlock, which no CPU test
  hits until the exact interleaving does.
* `cross-role-state` — an attribute written on one role and read on
  another with neither a common guarding lock on every access nor a
  `# ddtlint: atomic-publish` annotation on the write (the documented
  single-assignment publish idiom: one reference store, readers
  tolerate old-or-new-never-a-mix).
* `blocking-under-lock` — the serve-blocking-io predicate (time.sleep,
  open, np.load/json.load, .read_text/.read_bytes) upgraded from file
  scope to LOCK scope: a blocking call made while a lock or the
  dispatch gate is held stalls every thread that contends on it, not
  just the caller.
* `lock-release` — `.acquire()` without a dominating try/finally
  `.release()` of the same lock, or with call-bearing statements
  between the acquire and the try (a raise there leaks the lock
  forever; every future contender deadlocks).
* stale `# ddtlint: atomic-publish` annotations (lines that no longer
  store an attribute) report under `suppression-hygiene` — an
  annotation that outlives its publish is a suppression with nothing
  to suppress.

`python -m tools.ddtlint --explain-threads` dumps the inferred model
(roles, locks, publish points, lock-order edges) so reviewers of serve
PRs can diff it; docs/SERVING.md embeds the stable part and
tests/test_lint.py keeps the two in sync.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from tools.ddtlint import callgraph
from tools.ddtlint.base import Checker
from tools.ddtlint.findings import Finding

#: files the pass runs on (relpath regexes). statusd (ISSUE 20) is the
#: training tier's one concurrent-mutable-state surface — the trainer
#: thread and HTTP handler threads share its TrainStatus — so it lives
#: under the same analysis as the serve tier.
SCOPE = (r"^ddt_tpu/serve/", r"^ddt_tpu/robustness/watchdog\.py$",
         r"^ddt_tpu/telemetry/statusd\.py$")

RULE_LOCK_ORDER = "lock-order"
RULE_CROSS_ROLE = "cross-role-state"
RULE_BLOCKING = "blocking-under-lock"
RULE_RELEASE = "lock-release"
#: stale atomic-publish annotations report under the existing
#: suppression-hygiene rule (an annotation is a suppression).
RULE_STALE_PUBLISH = "suppression-hygiene"

RULES = (RULE_LOCK_ORDER, RULE_CROSS_ROLE, RULE_BLOCKING, RULE_RELEASE,
         RULE_STALE_PUBLISH)

#: the serve-blocking-io predicate, reused at lock scope.
BLOCKING_CALLS = {"time.sleep", "open", "np.load", "numpy.load",
                  "json.load"}
BLOCKING_READ_ATTRS = {"read_text", "read_bytes"}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
ATOMIC_PUBLISH_RE = re.compile(r"#\s*ddtlint:\s*atomic-publish")


def in_scope(path: str) -> bool:
    return any(re.search(p, path) for p in SCOPE)


def _blocking_label(call: ast.Call) -> str | None:
    """Dotted label when `call` matches the blocking predicate."""
    d = callgraph.dotted(call.func)
    if d in BLOCKING_CALLS:
        return d
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in BLOCKING_READ_ATTRS:
        return f".{call.func.attr}"
    return None


@dataclass
class Method:
    path: str
    cls: str                    # "" for module-level functions
    name: str
    node: ast.AST
    roles: set = field(default_factory=set)
    tracker: "callgraph.LockTracker | None" = None
    edges: list = field(default_factory=list)   # (key, held, Call node)
    # transitive facts (fixpoint below)
    order_acquires: set = field(default_factory=set)   # blocking (cls, lock)
    blocking: "tuple | None" = None      # (label, line) of a reachable
    #                                      blocking call, None when clean

    @property
    def key(self) -> tuple:
        return (self.path, self.cls, self.name)

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class ClassModel:
    path: str
    name: str
    node: ast.AST
    methods: dict = field(default_factory=dict)      # name -> Method
    locks: dict = field(default_factory=dict)        # attr -> ctor kind
    attr_types: dict = field(default_factory=dict)   # attr -> class name
    injected: dict = field(default_factory=dict)     # attr -> __init__ param


@dataclass
class ThreadModel:
    classes: dict = field(default_factory=dict)      # name -> ClassModel
    functions: dict = field(default_factory=dict)    # (path, name) -> Method
    methods: dict = field(default_factory=dict)      # key -> Method
    thread_roots: list = field(default_factory=list)  # Method keys
    #: (cls, attr) -> (path, cls, meth) the injected callable binds to
    bindings: dict = field(default_factory=dict)
    #: lock-order digraph: (from_lock, to_lock) -> representative site
    #: (path, node);  locks are (class, attr) pairs.
    order_edges: dict = field(default_factory=dict)
    #: attributes declared atomic-publish: (cls, attr) -> [write lines]
    published: dict = field(default_factory=dict)
    #: attributes guarded by a common lock: (cls, attr) -> lock attr
    guarded: dict = field(default_factory=dict)
    #: {path: set of `# ddtlint: atomic-publish` line numbers}
    annotated: dict = field(default_factory=dict)
    findings: list = field(default_factory=list)     # Finding (no line_text)


# --------------------------------------------------------------------- #
# model construction
# --------------------------------------------------------------------- #
def build(trees: dict, sources: dict | None = None) -> ThreadModel:
    """{relpath: parsed ast.Module} for the serve-scope files -> the
    package-wide thread model with findings computed. `sources` (same
    keys) resolves `# ddtlint: atomic-publish` annotation lines — the
    cross-role exemption; without it no line is annotated."""
    m = ThreadModel()
    set_annotations(m, sources or {})

    # pass A: classes, methods, module functions, locks, attr seeds ---- #
    for path, tree in sorted(trees.items()):
        if tree is None:
            continue
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.ClassDef):
                cm = ClassModel(path=path, name=node.name, node=node)
                for ch in ast.iter_child_nodes(node):
                    if isinstance(ch, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        meth = Method(path, node.name, ch.name, ch)
                        cm.methods[ch.name] = meth
                        m.methods[meth.key] = meth
                # __init__ seeds: locks, attr types, injected callables
                init = cm.methods.get("__init__")
                if init is not None:
                    params = [a.arg for a in init.node.args.args[1:]]
                    for st in ast.walk(init.node):
                        if not isinstance(st, ast.Assign):
                            continue
                        for t in st.targets:
                            attr = callgraph.self_attr(t)
                            if attr is None:
                                continue
                            v = st.value
                            if isinstance(v, ast.Call):
                                d = callgraph.dotted(v.func)
                                last = d.split(".")[-1] if d else None
                                if last in _LOCK_CTORS:
                                    cm.locks[attr] = last
                                elif last is not None:
                                    cm.attr_types[attr] = last
                            elif isinstance(v, ast.Name) \
                                    and v.id in params:
                                cm.injected[attr] = v.id
                # classes may collide across files only by accident;
                # first (path-sorted) wins, deterministically.
                m.classes.setdefault(node.name, cm)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                meth = Method(path, "", node.name, node)
                m.functions[(path, node.name)] = meth
                m.methods[meth.key] = meth

    # attr types only count when they name a modelled class
    for cm in m.classes.values():
        cm.attr_types = {a: t for a, t in cm.attr_types.items()
                         if t in m.classes}

    # pass B: thread targets + injected-callable bindings -------------- #
    for meth in m.methods.values():
        cls = m.classes.get(meth.cls)
        for n in callgraph.walk_skip_defs(meth.node):
            if not isinstance(n, ast.Call):
                continue
            d = callgraph.dotted(n.func)
            last = d.split(".")[-1] if d else None
            if last == "Thread":
                for k in n.keywords:
                    if k.arg != "target":
                        continue
                    attr = callgraph.self_attr(k.value)
                    if attr is not None and cls is not None \
                            and attr in cls.methods:
                        m.thread_roots.append(cls.methods[attr].key)
                    elif isinstance(k.value, ast.Name):
                        f = m.functions.get((meth.path, k.value.id))
                        if f is not None:
                            m.thread_roots.append(f.key)
            elif last in m.classes and cls is not None:
                callee = m.classes[last]
                init = callee.methods.get("__init__")
                if init is None:
                    continue
                params = [a.arg for a in init.node.args.args[1:]]
                bound: dict = {}
                for i, a in enumerate(n.args):
                    if i < len(params):
                        bound[params[i]] = a
                for k in n.keywords:
                    if k.arg is not None:
                        bound[k.arg] = k.value
                for attr, pname in callee.injected.items():
                    v = bound.get(pname)
                    tgt = callgraph.self_attr(v) if v is not None else None
                    if tgt is not None and tgt in cls.methods:
                        m.bindings[(callee.name, attr)] = \
                            cls.methods[tgt].key

    # pass C: lock tracking + call-edge resolution --------------------- #
    for meth in m.methods.values():
        cls = m.classes.get(meth.cls)
        lock_attrs = set(cls.locks) if cls is not None else set()
        meth.tracker = callgraph.LockTracker(lock_attrs).run(meth.node)
        for call, held in meth.tracker.calls:
            key = _resolve_call(m, meth, cls, call)
            if key is not None:
                meth.edges.append((key, held, call))

    # pass D: roles ----------------------------------------------------- #
    _flood(m, "dispatcher", m.thread_roots)
    handler_seeds = [meth.key for meth in m.methods.values()
                     if not meth.name.startswith("_")]
    _flood(m, "handler", handler_seeds)

    # pass E: transitive acquire/blocking facts (fixpoint) ------------- #
    for meth in m.methods.values():
        for lock, _held, blocking, _n in meth.tracker.acquisitions:
            if blocking:
                meth.order_acquires.add((meth.cls, lock))
        for call, _held in meth.tracker.calls:
            lbl = _blocking_label(call)
            if lbl is not None and meth.blocking is None:
                meth.blocking = (lbl, getattr(call, "lineno", 0))
    for _ in range(len(m.methods) + 1):
        changed = False
        for meth in m.methods.values():
            for key, _held, _call in meth.edges:
                callee = m.methods.get(key)
                if callee is None:
                    continue
                if not callee.order_acquires <= meth.order_acquires:
                    meth.order_acquires |= callee.order_acquires
                    changed = True
                if meth.blocking is None and callee.blocking is not None:
                    meth.blocking = (f"{callee.qual} -> "
                                     f"{callee.blocking[0]}",
                                     callee.blocking[1])
                    changed = True
        if not changed:
            break

    # pass F: lock-order digraph ---------------------------------------- #
    for meth in m.methods.values():
        for lock, held, blocking, node in meth.tracker.acquisitions:
            if not blocking:
                continue                      # trylocks cannot deadlock
            for h in held:
                m.order_edges.setdefault(
                    ((meth.cls, h), (meth.cls, lock)), (meth.path, node))
        for key, held, call in meth.edges:
            callee = m.methods.get(key)
            if callee is None or not held:
                continue
            for h in held:
                for tgt in callee.order_acquires:
                    if tgt == (meth.cls, h):
                        continue
                    m.order_edges.setdefault(
                        ((meth.cls, h), tgt), (meth.path, call))

    _find_lock_order(m)
    _find_cross_role(m)
    _find_blocking(m)
    _find_release(m)
    return m


def _resolve_call(m: ThreadModel, meth: Method, cls, call: ast.Call):
    """Call node -> callee Method key, where statically resolvable."""
    f = call.func
    # self.m(...) / self.attr(...) on an injected callable
    attr = callgraph.self_attr(f)
    if attr is not None and cls is not None:
        if attr in cls.methods:
            return cls.methods[attr].key
        if (cls.name, attr) in m.bindings:
            return m.bindings[(cls.name, attr)]
        return None
    # self.obj.m(...) through a constructor-derived attribute type
    if isinstance(f, ast.Attribute):
        owner = callgraph.self_attr(f.value)
        if owner is not None and cls is not None:
            t = cls.attr_types.get(owner)
            if t is not None and f.attr in m.classes[t].methods:
                return m.classes[t].methods[f.attr].key
        return None
    # bare module-function call (same file first, then any scanned file)
    if isinstance(f, ast.Name):
        hit = m.functions.get((meth.path, f.id))
        if hit is not None:
            return hit.key
        for (_p, name), fn in sorted(m.functions.items()):
            if name == f.id:
                return fn.key
    return None


def _flood(m: ThreadModel, role: str, seeds: list) -> None:
    work, seen = list(seeds), set()
    while work:
        key = work.pop()
        if key in seen:
            continue
        seen.add(key)
        meth = m.methods.get(key)
        if meth is None:
            continue
        meth.roles.add(role)
        for key2, _held, _call in meth.edges:
            work.append(key2)


# --------------------------------------------------------------------- #
# findings
# --------------------------------------------------------------------- #
def _emit(m: ThreadModel, rule: str, path: str, node, message: str) -> None:
    m.findings.append(Finding(
        rule=rule, path=path, line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1, message=message))


def _lock_name(lock: tuple) -> str:
    cls, attr = lock
    return f"{cls}.{attr}" if cls else attr


def _find_lock_order(m: ThreadModel) -> None:
    """Cycles in the lock-acquisition digraph, reported once per cycle
    at each participating edge's site (so every involved file shows the
    finding)."""
    graph: dict = {}
    for (a, b) in m.order_edges:
        graph.setdefault(a, set()).add(b)

    def reachable(src, dst) -> bool:
        work, seen = [src], set()
        while work:
            n = work.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            work.extend(graph.get(n, ()))
        return False

    for (a, b), (path, node) in sorted(
            m.order_edges.items(),
            key=lambda kv: (kv[1][0], getattr(kv[1][1], "lineno", 0))):
        if a != b and reachable(b, a):
            _emit(m, RULE_LOCK_ORDER, path, node, (
                f"lock-order inversion: {_lock_name(b)} is acquired here "
                f"while {_lock_name(a)} is held, and elsewhere "
                f"{_lock_name(a)} is acquired under {_lock_name(b)} — two "
                "threads taking the locks in opposite orders deadlock; "
                "pick one global order (docs/ANALYSIS.md lock-order)"))


def _find_cross_role(m: ThreadModel) -> None:
    """Attributes written on one role and read on another, with neither
    a common guarding lock on every access nor an atomic-publish
    annotation on every write."""
    for cname, cm in sorted(m.classes.items()):
        per_attr: dict = {}
        for meth in cm.methods.values():
            if not meth.roles and meth.name != "__init__":
                continue                   # never reached: no thread runs it
            for attr, kind, node, held in meth.tracker.accesses:
                if attr in cm.locks:
                    continue
                per_attr.setdefault(attr, []).append(
                    (kind, meth, node, held))
        for attr, accs in sorted(per_attr.items()):
            writes = [a for a in accs
                      if a[0] == "store" and a[1].name != "__init__"]
            if not writes:
                continue                   # init-published, then read-only
            outside = [a for a in accs if a[1].name != "__init__"]
            roles = set()
            for _k, meth, _n, _h in outside:
                roles |= meth.roles
            if len(roles) < 2:
                continue                   # single-role: no concurrency
            common = None
            for _k, _meth, _n, held in outside:
                common = set(held) if common is None else common & set(held)
            if common:
                m.guarded[(cname, attr)] = sorted(common)[0]
                continue
            # atomic-publish annotation on EVERY write line exempts
            ann = m.annotated.get(cm.path, set())
            if all(getattr(n, "lineno", 0) in ann
                   for _k, _meth, n, _h in writes):
                m.published[(cname, attr)] = sorted(
                    getattr(n, "lineno", 0) for _k, _meth, n, _h in writes)
                continue
            wroles = sorted({r for _k, meth, _n, _h in writes
                             for r in meth.roles})
            rroles = sorted(roles)
            for _k, meth, node, _h in writes:
                if getattr(node, "lineno", 0) in ann:
                    continue
                _emit(m, RULE_CROSS_ROLE, cm.path, node, (
                    f"`{cname}.{attr}` is written here on role(s) "
                    f"{'/'.join(wroles) or 'unreached'} and accessed on "
                    f"role(s) {'/'.join(rroles)} with no common guarding "
                    "lock — hold one lock on every access, or make this "
                    "a single-assignment atomic publish and annotate the "
                    "store with `# ddtlint: atomic-publish` "
                    "(docs/ANALYSIS.md cross-role-state)"))


def set_annotations(m: ThreadModel, sources: dict) -> None:
    """Record which lines of each source carry the atomic-publish
    annotation (the cross-role exemption); runs before findings are
    computed."""
    ann: dict = {}
    for path, src in sources.items():
        lines = set()
        for i, line in enumerate(src.splitlines(), start=1):
            if ATOMIC_PUBLISH_RE.search(line):
                lines.add(i)
        if lines:
            ann[path] = lines
    m.annotated = ann


def _find_blocking(m: ThreadModel) -> None:
    for meth in m.methods.values():
        for call, held in meth.tracker.calls:
            if not held:
                continue
            locks = "/".join(sorted(f"{meth.cls}.{h}" if meth.cls else h
                                    for h in held))
            lbl = _blocking_label(call)
            if lbl is not None:
                _emit(m, RULE_BLOCKING, meth.path, call, (
                    f"`{lbl}(...)` while {locks} is held — every thread "
                    "contending on the lock (the dispatch gate included) "
                    "inherits the block's wall time; release first, or "
                    "park on a Condition/Event timeout "
                    "(docs/ANALYSIS.md blocking-under-lock)"))
                continue
            key = _resolve_call(m, meth, m.classes.get(meth.cls), call)
            callee = m.methods.get(key) if key is not None else None
            if callee is not None and callee.blocking is not None:
                _emit(m, RULE_BLOCKING, meth.path, call, (
                    f"call to `{callee.qual}` while {locks} is held "
                    f"reaches blocking I/O ({callee.blocking[0]}, line "
                    f"{callee.blocking[1]}) — the lock serialises every "
                    "contender behind it (docs/ANALYSIS.md "
                    "blocking-under-lock)"))


def _find_release(m: ThreadModel) -> None:
    for meth in m.methods.values():
        tr = meth.tracker
        if not tr.acquire_calls:
            continue
        stmts = [s for s in callgraph.walk_skip_defs(meth.node)
                 if isinstance(s, ast.stmt)]
        # first try whose finally releases each lock
        for lock, call in tr.acquire_calls:
            if lock not in tr.finally_releases:
                _emit(m, RULE_RELEASE, meth.path, call, (
                    f"`{lock}.acquire()` with no dominating try/finally "
                    f"`{lock}.release()` in `{meth.qual}` — any raise on "
                    "the held path leaks the lock and deadlocks every "
                    "future contender; use `with`, or release in a "
                    "finally (docs/ANALYSIS.md lock-release)"))
                continue
            guard_line = None
            for s in stmts:
                if isinstance(s, ast.Try) and any(
                        isinstance(c, ast.Call)
                        and tr.lock_call(c) == (lock, "release")
                        for fs in s.finalbody
                        for c in callgraph.walk_skip_defs(fs)):
                    if s.lineno > call.lineno and (
                            guard_line is None or s.lineno < guard_line):
                        guard_line = s.lineno
            if guard_line is None:
                continue                  # acquire inside the try: fine
            risky = [
                s for s in stmts
                if call.lineno < s.lineno < guard_line
                and not any(c is call
                            for c in ast.walk(s))
                and any(isinstance(c, ast.Call)
                        and tr.lock_call(c) is None
                        for c in callgraph.walk_skip_defs(s))
            ]
            if risky:
                first = min(risky, key=lambda s: s.lineno)
                _emit(m, RULE_RELEASE, meth.path, call, (
                    f"`{lock}.acquire()` in `{meth.qual}` is guarded by a "
                    f"try/finally only from line {guard_line}, but line "
                    f"{first.lineno} between them makes a call that can "
                    "raise and leak the lock — enter the try immediately "
                    "after the acquire (docs/ANALYSIS.md lock-release)"))


def stale_annotations(path: str, tree: ast.AST, source: str) -> list:
    """`# ddtlint: atomic-publish` lines that no longer store an
    attribute — a stale publish declaration hides nothing today and
    will silently exempt whatever lands on that line tomorrow."""
    out: list = []
    store_lines = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Store):
            store_lines.add(getattr(n, "lineno", 0))
    for i, line in enumerate(source.splitlines(), start=1):
        if ATOMIC_PUBLISH_RE.search(line) and i not in store_lines:
            out.append(Finding(
                rule=RULE_STALE_PUBLISH, path=path, line=i,
                col=line.index("#") + 1,
                message=(
                    "stale `# ddtlint: atomic-publish` annotation — this "
                    "line no longer stores an attribute, so the "
                    "declaration exempts nothing today and would "
                    "silently exempt whatever publish lands here next; "
                    "delete it or move it to the store it describes")))
    return out


# --------------------------------------------------------------------- #
# the checker (runner wiring)
# --------------------------------------------------------------------- #
class ThreadModelChecker(Checker):
    """Emits this file's slice of the package-wide thread model's
    findings (runner builds ONE model over every scanned serve-scope
    file so cross-file edges — the injected dispatch callable — resolve;
    fixture tests get a single-file model built on demand)."""

    rule = RULE_LOCK_ORDER
    rules = RULES
    path_scope = SCOPE

    def run(self):
        m = self.ctx.thread_model
        if m is None:
            m = build({self.ctx.path: self.ctx.tree},
                      {self.ctx.path: self.ctx.source})
        for f in m.findings:
            if f.path != self.ctx.path:
                continue
            self.findings.append(Finding(
                rule=f.rule, path=f.path, line=f.line, col=f.col,
                message=f.message,
                line_text=self.ctx.line_text(f.line)))
        for f in stale_annotations(self.ctx.path, self.ctx.tree,
                                   self.ctx.source):
            f.line_text = self.ctx.line_text(f.line)
            self.findings.append(f)
        return self.findings


# --------------------------------------------------------------------- #
# --explain-threads
# --------------------------------------------------------------------- #
def explain(m: ThreadModel, details: bool = True) -> str:
    """Human-readable dump of the inferred model. The `details=False`
    form omits line numbers and is byte-stable across unrelated edits —
    docs/SERVING.md embeds it and tests keep the two in sync."""
    out = ["inferred threading model (tools/ddtlint --explain-threads)"]
    by_role: dict = {}
    for meth in m.methods.values():
        for r in sorted(meth.roles) or ["(unreached)"]:
            by_role.setdefault(r, []).append(meth.qual)
    out.append("roles:")
    for role in sorted(r for r in by_role if r != "(unreached)"):
        names = ", ".join(sorted(set(by_role[role])))
        out.append(f"  {role}: {names}")
    out.append("locks:")
    for cname, cm in sorted(m.classes.items()):
        for attr, kind in sorted(cm.locks.items()):
            out.append(f"  {cname}.{attr}: threading.{kind}")
    out.append("atomic-publish attrs:")
    for (cname, attr) in sorted(m.published):
        out.append(f"  {cname}.{attr}")
    out.append("lock-guarded attrs:")
    for (cname, attr), lock in sorted(m.guarded.items()):
        out.append(f"  {cname}.{attr} <- {cname}.{lock}")
    out.append("lock-order edges:")
    for (a, b), (path, node) in sorted(m.order_edges.items()):
        loc = f"  [{path}:{getattr(node, 'lineno', 0)}]" if details else ""
        out.append(f"  {_lock_name(a)} -> {_lock_name(b)}{loc}")
    return "\n".join(out) + "\n"
