"""Mechanized TSan suppression audit (ddt_tpu/native/tsan.supp AUDIT tag).

tsan.supp carries two PROCESS-WIDE suppressions (`race:_contig_to_contig`,
`race:array_dealloc`) for the join-edge false-positive class: after an
OpenMP region ends, NumPy copies/frees buffers the workers just wrote, the
join ordering lives inside uninstrumented libgomp, and the only visible
frames are NumPy's.  Being process-wide, they would ALSO hide a real
kernel-returns-before-worker-finishes race, whose report looks identical.
The prescribed audit — rerun the soak with those entries dropped and check
every survivor still has the join-edge *shape* — used to be prose a
reviewer had to remember; this module executes it:

    python -m tools.ddtlint.tsan_audit --run          # full soak (or:
                                                      #   make tsan-audit)
    python -m tools.ddtlint.tsan_audit --classify F   # classify a report
                                                      #   log (pure, fast)

Join-edge shape (all must hold, per report):
  * it is a `data race` report (not use-after-free / leak / ...);
  * no visible frame is a ddt_ kernel symbol;
  * every racing-stack frame is NumPy/libc memory machinery;
  * at least one side is `[failed to restore the stack]` (the worker
    whose stack died with the OpenMP team);
  * total surviving reports stay under a small ceiling.
Anything else is a FINDING and the audit exits 1.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile

SUPP_PATH = "ddt_tpu/native/tsan.supp"
NATIVE_DIR = "ddt_tpu/native"
#: suppression patterns scoped to our kernels stay active during the audit
_SCOPED_PREFIX = "ddt_"
#: more survivors than this is "count small and stable" violated
MAX_REPORTS = 64

# Frames legitimate in a join-edge report's RACING stacks: NumPy's copy /
# dealloc machinery plus the allocator+interceptor glue around it.
_NUMPY_FRAME_RE = re.compile(
    r"(memmove|memcpy|_contig_to_contig|array_dealloc|PyArray|PyDataMem|"
    r"numpy|npy_|__interceptor_|operator delete|\bfree\b|\bmalloc\b|"
    r"\b_?Py[A-Z_])",   # CPython frames under the NumPy call are expected
    re.IGNORECASE)
# Frames legitimate in the trailing "Thread T<n> ... created by" section
# (team bring-up is libgomp/pthread by construction).
_SPAWN_FRAME_RE = re.compile(
    r"(pthread_create|gomp|GOMP|omp_|clone|start_thread|__kmp)",
    re.IGNORECASE)
_FRAME_RE = re.compile(r"^\s+#\d+\s+(\S+)")
_REPORT_START = re.compile(r"WARNING: ThreadSanitizer: (.+?) \(pid=\d+\)")
_THREAD_SECTION = re.compile(r"Thread T\d+ .*created by")
_FAILED_STACK = "[failed to restore the stack]"


def split_reports(text: str) -> list[str]:
    """Cut a TSan log into individual report blocks."""
    blocks, cur = [], None
    for line in text.splitlines():
        if _REPORT_START.search(line):
            if cur:
                blocks.append("\n".join(cur))
            cur = [line]
        elif cur is not None:
            if line.strip().startswith("=================="):
                blocks.append("\n".join(cur))
                cur = None
            else:
                cur.append(line)
    if cur:
        blocks.append("\n".join(cur))
    return blocks


def classify_report(block: str) -> dict:
    """One report block -> {kind: 'join-edge'|'finding', reasons: [...]}."""
    reasons: list[str] = []
    m = _REPORT_START.search(block)
    what = m.group(1) if m else "unknown"
    if what != "data race":
        reasons.append(f"report type {what!r}, not a data race")

    in_spawn = False
    for line in block.splitlines():
        if _THREAD_SECTION.search(line):
            in_spawn = True
            continue
        fm = _FRAME_RE.match(line)
        if not fm:
            continue
        frame = fm.group(1)
        if frame.startswith(_SCOPED_PREFIX) or "ddt_" in frame:
            reasons.append(f"ddt_ kernel frame visible: {frame}")
        elif in_spawn:
            if not (_SPAWN_FRAME_RE.search(line)
                    or _NUMPY_FRAME_RE.search(line)):
                reasons.append(f"unexpected thread-creation frame: {frame}")
        elif not _NUMPY_FRAME_RE.search(line):
            reasons.append(f"non-NumPy racing frame: {frame}")

    if _FAILED_STACK not in block:
        reasons.append("no '[failed to restore the stack]' side — both "
                       "stacks restored, which the join-edge class never "
                       "shows")
    return {"kind": "finding" if reasons else "join-edge",
            "what": what, "reasons": reasons,
            "head": block.splitlines()[0].strip() if block else ""}


def classify_log(text: str, max_reports: int = MAX_REPORTS) -> dict:
    """Full log -> summary dict; 'ok' False iff any report breaks the
    expected join-edge shape (or there are implausibly many)."""
    blocks = split_reports(text)
    classified = [classify_report(b) for b in blocks]
    findings = [c for c in classified if c["kind"] == "finding"]
    if len(blocks) > max_reports:
        findings.append({
            "kind": "finding", "what": "report-count",
            "reasons": [f"{len(blocks)} surviving reports > {max_reports} "
                        "ceiling — join-edge survivors are few and stable"],
            "head": ""})
    return {"ok": not findings, "total_reports": len(blocks),
            "join_edge": sum(1 for c in classified
                             if c["kind"] == "join-edge"),
            "findings": findings}


# --------------------------------------------------------------------- #
# orchestration (--run)
# --------------------------------------------------------------------- #
def write_audit_supp(src_path: str, dst_path: str) -> int:
    """Copy tsan.supp with every process-wide suppression commented out
    (scoped ddt_ entries stay active).  Returns how many were dropped.
    Entry classification is shared with the suppression-hygiene lint rule
    (checkers.is_process_wide_suppression) so the audited configuration
    always matches what the gate enforces."""
    from tools.ddtlint.checkers import is_process_wide_suppression

    dropped = 0
    out_lines = []
    with open(src_path, encoding="utf-8") as f:
        for line in f:
            s = line.strip()
            if s and not s.startswith("#") and ":" in s \
                    and is_process_wide_suppression(s):
                out_lines.append(f"# [tsan-audit dropped] {line}")
                dropped += 1
            else:
                out_lines.append(line)
    with open(dst_path, "w", encoding="utf-8") as f:
        f.writelines(out_lines)
    return dropped


def _libtsan() -> str | None:
    gcc = shutil.which("gcc")
    if gcc is None:
        return None
    out = subprocess.run([gcc, "-print-file-name=libtsan.so"],
                         capture_output=True, text=True).stdout.strip()
    return out if out and os.path.sep in out and os.path.exists(out) \
        else None


def run_audit(root: str = ".", max_reports: int = MAX_REPORTS,
              pytest_args: tuple = ("tests/test_native.py", "-q")) -> int:
    root = os.path.abspath(root)
    supp = os.path.join(root, SUPP_PATH)
    if not os.path.exists(supp):
        print(f"tsan-audit: {SUPP_PATH} not found under {root}",
              file=sys.stderr)
        return 2
    libtsan = _libtsan()
    if libtsan is None:
        print("tsan-audit: libtsan.so not available from gcc on this host "
              "— cannot run the soak (the classifier still works: "
              "--classify <log>)", file=sys.stderr)
        return 3

    mk = subprocess.run(["make", "-C", os.path.join(root, NATIVE_DIR),
                         "-s", "tsan"], capture_output=True, text=True)
    if mk.returncode != 0:
        print(f"tsan-audit: TSan build failed:\n{mk.stderr}",
              file=sys.stderr)
        return 2

    with tempfile.TemporaryDirectory(prefix="tsan_audit_") as tmp:
        audit_supp = os.path.join(tmp, "tsan_audit.supp")
        dropped = write_audit_supp(supp, audit_supp)
        log_stem = os.path.join(tmp, "tsan-report")
        env = dict(os.environ)
        env.update({
            "TSAN_OPTIONS": (f"suppressions={audit_supp} "
                             f"log_path={log_stem} exitcode=0"),
            "LD_PRELOAD": libtsan,
            "DDT_NATIVE_LIB": "libddthist_tsan.so",
            "OMP_NUM_THREADS": "4",
            "JAX_PLATFORMS": "cpu",
        })
        print(f"tsan-audit: soak with {dropped} process-wide "
              f"suppression(s) dropped ...")
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", *pytest_args],
            cwd=root, env=env, capture_output=True, text=True)
        text = ""
        for path in sorted(glob.glob(log_stem + "*")):
            with open(path, encoding="utf-8", errors="replace") as f:
                text += f.read() + "\n"
        # TSan also writes to stderr when log_path misbehaves; include it.
        if "WARNING: ThreadSanitizer" in proc.stderr:
            text += proc.stderr
        summary = classify_log(text, max_reports=max_reports)
        summary["pytest_exit"] = proc.returncode
        summary["suppressions_dropped"] = dropped
        print(json.dumps(summary, indent=2))
        if proc.returncode != 0:
            print("tsan-audit: FAIL — the behavioral net itself failed "
                  "under TSan (pytest nonzero); see output above",
                  file=sys.stderr)
            print(proc.stdout[-4000:], file=sys.stderr)
            return 1
        if not summary["ok"]:
            print("tsan-audit: FAIL — surviving report(s) break the "
                  "join-edge shape; treat as a real race finding",
                  file=sys.stderr)
            return 1
        print(f"tsan-audit: OK — {summary['total_reports']} surviving "
              "report(s), all join-edge shaped")
        return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.ddtlint.tsan_audit",
        description="mechanized tsan.supp process-wide suppression audit")
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--run", action="store_true",
                   help="build the TSan lib, rerun the soak with "
                        "process-wide suppressions dropped, classify")
    g.add_argument("--classify", metavar="LOG",
                   help="classify an existing TSan report log")
    ap.add_argument("--root", default=".", help="repo root (default: cwd)")
    ap.add_argument("--max-reports", type=int, default=MAX_REPORTS)
    args = ap.parse_args(argv)

    if args.classify:
        with open(args.classify, encoding="utf-8", errors="replace") as f:
            summary = classify_log(f.read(), max_reports=args.max_reports)
        print(json.dumps(summary, indent=2))
        return 0 if summary["ok"] else 1
    return run_audit(args.root, max_reports=args.max_reports)


if __name__ == "__main__":
    sys.exit(main())
