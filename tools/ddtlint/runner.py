"""File walking, project context, pragma + baseline handling for ddtlint."""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess

from tools.ddtlint import (callgraph, checkers, configflow, shardspec,
                           telemetrycontract, threadmodel)
from tools.ddtlint.base import CheckContext
from tools.ddtlint.findings import Finding, assign_fingerprints

DEFAULT_BASELINE = "tools/ddtlint/baseline.json"
#: the gate's default scan scope — also the floor for cross-file
#: ANALYSIS inputs on narrowed runs (see lint_paths).
DEFAULT_SCOPE = ["ddt_tpu/", "tests/"]
MESH_FILE = "ddt_tpu/parallel/mesh.py"
#: directories holding deliberate violations (checker fixtures) — skipped
#: by the walker; tests exercise them through run_on_source directly.
SKIP_DIRS = {"lint_fixtures", "__pycache__", ".git"}

_PRAGMA_RE = re.compile(r"ddtlint:\s*disable=([\w,-]+)")


def _parse(source: str) -> "ast.AST | None":
    try:
        return ast.parse(source)
    except SyntaxError:
        return None


# --------------------------------------------------------------------- #
# project context
# --------------------------------------------------------------------- #
def _mesh_tree(root: str, tree: "ast.AST | None" = None) -> "ast.AST | None":
    """Parsed parallel/mesh.py — reuses a tree the caller already parsed
    (the lint run's shared-AST cache) or reads from disk."""
    if tree is not None:
        return tree
    path = os.path.join(root, MESH_FILE)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return _parse(f.read())


def mesh_axis_names(root: str, tree: "ast.AST | None" = None) -> set[str]:
    """Axis names any mesh in parallel/mesh.py can define: module-level
    `*_AXIS = "..."` constants plus string literals in the axis-name
    tuples handed to make_mesh."""
    tree = _mesh_tree(root, tree)
    if tree is None:
        return set()
    axes: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id.endswith("_AXIS")
                   for t in node.targets) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                axes.add(node.value.value)
        elif isinstance(node, ast.Call):
            d = callgraph.dotted(node.func)
            if d is not None and d.split(".")[-1] == "make_mesh":
                cands = list(node.args[1:2]) + [
                    k.value for k in node.keywords
                    if k.arg in ("axis_names", None)]
                for c in cands:
                    if isinstance(c, (ast.Tuple, ast.List)):
                        for e in c.elts:
                            if isinstance(e, ast.Constant) \
                                    and isinstance(e.value, str):
                                axes.add(e.value)
    return axes


def layout_rule_patterns(root: str,
                         tree: "ast.AST | None" = None
                         ) -> "list[str] | None":
    """SpecLayout.rules() regexes out of parallel/mesh.py — the
    layout-rule-coverage oracle (shardspec.layout_rule_patterns)."""
    return shardspec.layout_rule_patterns(_mesh_tree(root, tree))


def _walk_py(paths: list[str], root: str) -> list[str]:
    """Expand files/dirs into sorted repo-relative .py (and .supp) paths."""
    out: set[str] = set()
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.add(os.path.relpath(full, root).replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
            for fn in filenames:
                if fn.endswith((".py", ".supp")):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.add(rel.replace(os.sep, "/"))
    return sorted(out)


def changed_files(root: str) -> "set[str] | None":
    """Repo-relative paths changed vs `git merge-base HEAD <default>` —
    the --changed-only scope: committed changes since the branch point,
    plus working-tree modifications and untracked files. None when git
    (or a merge base) is unavailable, in which case the caller falls
    back to the full scan — degrading to MORE coverage, never less."""
    def _git(*args) -> "str | None":
        try:
            p = subprocess.run(["git", *args], cwd=root,
                               capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return p.stdout if p.returncode == 0 else None

    base = None
    for ref in ("origin/main", "origin/master", "main", "master"):
        out = _git("merge-base", "HEAD", ref)
        if out:
            base = out.strip()
            break
    if base is None:
        return None
    out: set[str] = set()
    # ONE diff of base vs the WORKTREE (no HEAD operand): covers
    # committed-since-base, STAGED, and unstaged edits in one pass — a
    # base..HEAD + worktree pair misses staged-but-uncommitted files
    # (worktree == index there), exactly the state a pre-commit lint
    # runs in.
    for args in (("diff", "--name-only", base),
                 ("ls-files", "--others", "--exclude-standard")):
        text = _git(*args)
        if text is None:
            return None
        out.update(ln.strip() for ln in text.splitlines() if ln.strip())
    return out


# --------------------------------------------------------------------- #
# linting
# --------------------------------------------------------------------- #
def _apply_pragmas(findings: list[Finding],
                   sources: dict[str, str]) -> list[Finding]:
    """Drop findings whose source line carries
    `# ddtlint: disable=<rule>[,rule...]` (or disable=all)."""
    kept = []
    line_cache: dict[str, list[str]] = {}
    for f in findings:
        lines = line_cache.setdefault(f.path,
                                      sources.get(f.path, "").splitlines())
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        m = _PRAGMA_RE.search(text)
        if m and (f.rule in m.group(1).split(",") or m.group(1) == "all"):
            continue
        kept.append(f)
    return kept


def run_on_source(path: str, source: str, mesh_axes: set[str] | None = None,
                  reachable: set[str] | None = None,
                  rules: set[str] | None = None,
                  tree: "ast.AST | None" = None,
                  layout_rules: "list[str] | None" = None,
                  thread_model=None, config_model=None,
                  telemetry_model=None) -> list[Finding]:
    """Lint one in-memory python source. For .supp content use
    checkers.check_suppressions directly. `tree` reuses an AST the
    caller already parsed (lint_paths parses each file exactly once and
    shares it across every checker AND the call-graph/thread-model
    builders — the single-parse contract tests/test_lint.py times)."""
    if tree is None:
        tree = _parse(source)
    if tree is None:
        try:
            ast.parse(source)
        except SyntaxError as e:
            return [Finding(rule="syntax-error", path=path,
                            line=e.lineno or 1, col=(e.offset or 0) + 1,
                            message=f"does not parse: {e.msg}")]
    if reachable is None:
        reachable = callgraph.build({path: source},
                                    trees={path: tree}).get(path, set())
    out: list[Finding] = []
    for cls in checkers.AST_CHECKERS:
        if rules is not None and not (cls.rule_set() & rules):
            continue
        if not cls.applies_to(path):
            continue
        ctx = CheckContext(path, source, tree, mesh_axes, reachable,
                           layout_rules=layout_rules,
                           thread_model=thread_model,
                           config_model=config_model,
                           telemetry_model=telemetry_model)
        out.extend(cls(ctx).run())
    if rules is not None:
        # Multi-rule checkers emit their whole catalogue; keep only the
        # selection (--rules contract).
        out = [f for f in out if f.rule in rules]
    return _apply_pragmas(out, {path: source})


def lint_paths(paths: list[str], root: str | None = None,
               rules: set[str] | None = None,
               only_files: "set[str] | None" = None) -> list[Finding]:
    """Lint files/directories; returns fingerprinted findings sorted by
    position.  `root` defaults to the repo root (cwd).  `only_files`
    (repo-relative) restricts which files REPORT findings — the
    --changed-only scope. The cross-file analysis inputs (the jit
    call graph, the serve thread model) are always built from the FULL
    walk: a thread model missing batcher.py would silently strip
    ServeEngine._dispatch of its dispatcher role and wave through a
    cross-role hazard an engine-only edit introduced — restricting
    emission, never analysis, is what keeps --changed-only "more
    coverage, never less"."""
    root = os.path.abspath(root or os.getcwd())
    requested = _walk_py(paths, root)
    emit_files = requested if only_files is None \
        else [f for f in requested if f in only_files]
    # Analysis inputs always cover the DEFAULT scope (plus anything the
    # caller explicitly named outside it): `ddtlint engine.py` must
    # still see batcher.py's thread roots and the backends' jit roots,
    # or a narrowed run reports false-clean — the same failure mode
    # only_files guards against.
    files = sorted(set(requested) | set(_walk_py(DEFAULT_SCOPE, root)))
    sources: dict[str, str] = {}
    for rel in files:
        with open(os.path.join(root, rel), encoding="utf-8",
                  errors="replace") as f:
            sources[rel] = f.read()

    # Parse ONCE per file; every consumer below shares the tree.
    py_sources = {p: s for p, s in sources.items() if p.endswith(".py")}
    trees = {p: _parse(s) for p, s in py_sources.items()}
    reach = callgraph.build(py_sources, trees=trees)
    mesh_t = _mesh_tree(root, trees.get(MESH_FILE))
    axes = mesh_axis_names(root, mesh_t)
    layout_rules = shardspec.layout_rule_patterns(mesh_t)
    # ONE serve-tier thread model over every scanned in-scope file, so
    # cross-file edges (the injected dispatch callable) resolve.
    tm_files = {p for p in py_sources
                if threadmodel.in_scope(p) and trees.get(p) is not None}
    tmodel = threadmodel.build(
        {p: trees[p] for p in tm_files},
        {p: py_sources[p] for p in tm_files}) if tm_files else None
    # ONE config-flow model and ONE telemetry model, both over every
    # scanned in-scope file (contract anchors + reads span the package)
    # and both reusing the shared trees — and, for configflow, the
    # already-built call graph (the single-parse contract).
    cf_files = {p for p in py_sources
                if configflow.in_scope(p) and trees.get(p) is not None}
    cmodel = configflow.build(
        {p: trees[p] for p in cf_files},
        {p: py_sources[p] for p in cf_files},
        reachable=reach) if cf_files else None
    tc_files = {p for p in py_sources
                if telemetrycontract.in_scope(p) and trees.get(p) is not None}
    tele_model = telemetrycontract.build(
        {p: trees[p] for p in tc_files}) if tc_files else None

    findings: list[Finding] = []
    for rel in emit_files:
        src = sources[rel]
        if rel.endswith(".supp"):
            if rules is None or checkers.SUPPRESSION_RULE in rules:
                findings.extend(checkers.check_suppressions(rel, src))
        else:
            findings.extend(run_on_source(
                rel, src, mesh_axes=axes, reachable=reach.get(rel, set()),
                rules=rules, tree=trees.get(rel),
                layout_rules=layout_rules, thread_model=tmodel,
                config_model=cmodel, telemetry_model=tele_model))
    return assign_fingerprints(findings)


# --------------------------------------------------------------------- #
# baseline
# --------------------------------------------------------------------- #
def load_baseline(path: str) -> dict[str, dict]:
    """{fingerprint: entry}; tolerant of a missing file (empty ratchet)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save_baseline(path: str, findings: list[Finding]) -> None:
    data = {
        "comment": (
            "ddtlint ratchet baseline — known findings the gate tolerates. "
            "Regenerate with `python -m tools.ddtlint ddt_tpu/ tests/ "
            "--write-baseline` AFTER confirming every new entry is a "
            "deliberate, documented exception (docs/ANALYSIS.md); the goal "
            "is for this list to only ever shrink."),
        "findings": [
            {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
             "line": f.line, "line_text": f.line_text.strip(),
             "message": f.message}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")


def split_vs_baseline(findings: list[Finding], baseline: dict[str, dict],
                      scanned: "set[str] | None" = None
                      ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """(new, known, stale_baseline_entries).  `scanned` restricts the
    stale check to baseline entries whose file was actually linted — a
    --changed-only run must not declare every untouched file's entry
    stale."""
    fps = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    known = [f for f in findings if f.fingerprint in baseline]
    stale = [e for fp, e in baseline.items()
             if fp not in fps
             and (scanned is None or e.get("path") in scanned)]
    return new, known, stale
