"""File walking, project context, pragma + baseline handling for ddtlint."""

from __future__ import annotations

import ast
import json
import os
import re

from tools.ddtlint import callgraph, checkers
from tools.ddtlint.findings import Finding, assign_fingerprints

DEFAULT_BASELINE = "tools/ddtlint/baseline.json"
MESH_FILE = "ddt_tpu/parallel/mesh.py"
#: directories holding deliberate violations (checker fixtures) — skipped
#: by the walker; tests exercise them through run_on_source directly.
SKIP_DIRS = {"lint_fixtures", "__pycache__", ".git"}

_PRAGMA_RE = re.compile(r"ddtlint:\s*disable=([\w,-]+)")


# --------------------------------------------------------------------- #
# project context
# --------------------------------------------------------------------- #
def mesh_axis_names(root: str) -> set[str]:
    """Axis names any mesh in parallel/mesh.py can define: module-level
    `*_AXIS = "..."` constants plus string literals in the axis-name
    tuples handed to make_mesh."""
    path = os.path.join(root, MESH_FILE)
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read())
        except SyntaxError:
            return set()
    axes: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id.endswith("_AXIS")
                   for t in node.targets) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                axes.add(node.value.value)
        elif isinstance(node, ast.Call):
            d = callgraph.dotted(node.func)
            if d is not None and d.split(".")[-1] == "make_mesh":
                cands = list(node.args[1:2]) + [
                    k.value for k in node.keywords
                    if k.arg in ("axis_names", None)]
                for c in cands:
                    if isinstance(c, (ast.Tuple, ast.List)):
                        for e in c.elts:
                            if isinstance(e, ast.Constant) \
                                    and isinstance(e.value, str):
                                axes.add(e.value)
    return axes


def _walk_py(paths: list[str], root: str) -> list[str]:
    """Expand files/dirs into sorted repo-relative .py (and .supp) paths."""
    out: set[str] = set()
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.add(os.path.relpath(full, root).replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
            for fn in filenames:
                if fn.endswith((".py", ".supp")):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.add(rel.replace(os.sep, "/"))
    return sorted(out)


# --------------------------------------------------------------------- #
# linting
# --------------------------------------------------------------------- #
def _apply_pragmas(findings: list[Finding],
                   sources: dict[str, str]) -> list[Finding]:
    """Drop findings whose source line carries
    `# ddtlint: disable=<rule>[,rule...]` (or disable=all)."""
    kept = []
    line_cache: dict[str, list[str]] = {}
    for f in findings:
        lines = line_cache.setdefault(f.path,
                                      sources.get(f.path, "").splitlines())
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        m = _PRAGMA_RE.search(text)
        if m and (f.rule in m.group(1).split(",") or m.group(1) == "all"):
            continue
        kept.append(f)
    return kept


def run_on_source(path: str, source: str, mesh_axes: set[str] | None = None,
                  reachable: set[str] | None = None,
                  rules: set[str] | None = None) -> list[Finding]:
    """Lint one in-memory python source. For .supp content use
    checkers.check_suppressions directly."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="syntax-error", path=path,
                        line=e.lineno or 1, col=(e.offset or 0) + 1,
                        message=f"does not parse: {e.msg}")]
    if reachable is None:
        reachable = callgraph.build({path: source}).get(path, set())
    out: list[Finding] = []
    for cls in checkers.AST_CHECKERS:
        if rules is not None and cls.rule not in rules:
            continue
        if not cls.applies_to(path):
            continue
        ctx = checkers.CheckContext(path, source, tree, mesh_axes, reachable)
        out.extend(cls(ctx).run())
    return _apply_pragmas(out, {path: source})


def lint_paths(paths: list[str], root: str | None = None,
               rules: set[str] | None = None) -> list[Finding]:
    """Lint files/directories; returns fingerprinted findings sorted by
    position.  `root` defaults to the repo root (cwd)."""
    root = os.path.abspath(root or os.getcwd())
    files = _walk_py(paths, root)
    sources: dict[str, str] = {}
    for rel in files:
        with open(os.path.join(root, rel), encoding="utf-8",
                  errors="replace") as f:
            sources[rel] = f.read()

    py_sources = {p: s for p, s in sources.items() if p.endswith(".py")}
    reach = callgraph.build(py_sources)
    axes = mesh_axis_names(root)

    findings: list[Finding] = []
    for rel, src in sources.items():
        if rel.endswith(".supp"):
            if rules is None or checkers.SUPPRESSION_RULE in rules:
                findings.extend(checkers.check_suppressions(rel, src))
        else:
            findings.extend(run_on_source(
                rel, src, mesh_axes=axes, reachable=reach.get(rel, set()),
                rules=rules))
    return assign_fingerprints(findings)


# --------------------------------------------------------------------- #
# baseline
# --------------------------------------------------------------------- #
def load_baseline(path: str) -> dict[str, dict]:
    """{fingerprint: entry}; tolerant of a missing file (empty ratchet)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save_baseline(path: str, findings: list[Finding]) -> None:
    data = {
        "comment": (
            "ddtlint ratchet baseline — known findings the gate tolerates. "
            "Regenerate with `python -m tools.ddtlint ddt_tpu/ tests/ "
            "--write-baseline` AFTER confirming every new entry is a "
            "deliberate, documented exception (docs/ANALYSIS.md); the goal "
            "is for this list to only ever shrink."),
        "findings": [
            {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
             "line": f.line, "line_text": f.line_text.strip(),
             "message": f.message}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")


def split_vs_baseline(findings: list[Finding], baseline: dict[str, dict]
                      ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """(new, known, stale_baseline_entries)."""
    fps = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    known = [f for f in findings if f.fingerprint in baseline]
    stale = [e for fp, e in baseline.items() if fp not in fps]
    return new, known, stale
