"""The ddtlint rules — one small, individually-testable visitor per hazard.

Every checker is deliberately biased toward *no false negatives on the
fixture shapes, no false positives on idiomatic repo code*: anything it
cannot resolve statically it skips, and the pytest gate's ratchet baseline
(tools/ddtlint/baseline.json) absorbs the residue.  docs/ANALYSIS.md
documents each rule's rationale, scope, and escape hatches.
"""

from __future__ import annotations

import ast
import re

from tools.ddtlint import (callgraph, configflow, shardspec,
                           telemetrycontract, threadmodel)
from tools.ddtlint.base import Checker, CheckContext  # noqa: F401 — the
# base moved to tools/ddtlint/base.py so the flow-aware pass modules can
# subclass it without an import cycle; re-exported here for callers.
from tools.ddtlint.findings import Finding

# Attribute-chain roots that produce traced arrays when called.
_TRACED_ROOTS = ("jnp.", "jax.", "lax.")
# jax/jnp callables that return HOST values (python bools/strings/ints),
# not traced arrays — assignments from these must not taint.
_HOST_FUNCS = {
    "default_backend", "devices", "local_devices", "device_count",
    "local_device_count", "process_index", "process_count",
    "issubdtype", "result_type", "promote_types", "dtype", "shape",
    "ndim", "iinfo", "finfo", "axis_size", "Precision",
}


def _is_traced_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = callgraph.dotted(node.func)
    if d is None or not (d + ".").startswith(_TRACED_ROOTS):
        return False
    return d.split(".")[-1] not in _HOST_FUNCS \
        and not callgraph._resolves_to_jit(node.func)


# --------------------------------------------------------------------- #
# 1. traced-branch
# --------------------------------------------------------------------- #
class TracedBranchChecker(Checker):
    """Python `if`/`while` (and ternaries) on traced values inside functions
    reachable from a jit/pjit root — a TracerBoolConversionError on device,
    invisible to eager CPU tests.  Taint: locals assigned from jnp./jax.
    calls, propagated through expressions; parameters are NOT tainted
    (static-argument branches are the dominant legitimate pattern in ops/).
    `x is None` / isinstance() tests are static Python and exempt."""

    rule = "traced-branch"
    path_scope = (r"^ddt_tpu/ops/", r"^ddt_tpu/backends/")

    def run(self) -> list[Finding]:
        for qual in sorted(self.ctx.reachable):
            fn = self._find_func(qual)
            if fn is not None:
                self._check_fn(qual, fn)
        return self.findings

    def _find_func(self, qual: str):
        parts = qual.split(".")
        node: ast.AST = self.ctx.tree
        for name in parts:
            found = None
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)) and child.name == name:
                    found = child
                    break
            if found is None:
                return None
            node = found
        return node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) else None

    @classmethod
    def _walk_own(cls, fn: ast.AST):
        """Descendants of `fn` excluding nested function bodies — nested
        defs are reachable in their own right (callgraph closure), so
        checking them here would double-report."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _check_fn(self, qual: str, fn: ast.AST) -> None:
        tainted = self._taint(fn)
        for node in self._walk_own(fn):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                test = node.test
                if self._static_test(test):
                    continue
                if self._traced_expr(test, tainted):
                    kind = {ast.If: "if", ast.While: "while",
                            ast.IfExp: "conditional expression"}[type(node)]
                    self.report(node, (
                        f"Python {kind} on a traced value in jit-reachable "
                        f"'{qual}' — use jnp.where / lax.cond / "
                        "lax.while_loop (traces as data, not control flow)"))

    @staticmethod
    def _static_test(test: ast.AST) -> bool:
        """Tests that stay in Python even on traced operands."""
        while isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            test = test.operand
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return True
        if isinstance(test, ast.Call):
            d = callgraph.dotted(test.func)
            if d in ("isinstance", "hasattr", "callable", "len"):
                return True
            # host-returning jax/jnp predicates stay python bools even on
            # traced operands (jnp.issubdtype(x.dtype, ...), etc.)
            if d is not None and d.split(".")[-1] in _HOST_FUNCS:
                return True
        return False

    @classmethod
    def _taint(cls, fn: ast.AST) -> set[str]:
        tainted: set[str] = set()

        def expr_traced(e: ast.AST) -> bool:
            for n in ast.walk(e):
                if _is_traced_call(n):
                    return True
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
            return False

        def add_target(t: ast.AST):
            if isinstance(t, ast.Name):
                tainted.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    add_target(e)

        # _walk_own, not ast.walk: nested defs are separate scopes checked
        # in their own right — a jnp-assigned name INSIDE a nested def must
        # not taint the same name in the enclosing function.
        for _ in range(8):                    # fixpoint; converges fast
            n0 = len(tainted)
            for node in cls._walk_own(fn):
                if isinstance(node, ast.Assign) and expr_traced(node.value):
                    for t in node.targets:
                        add_target(t)
                elif isinstance(node, ast.AugAssign) \
                        and expr_traced(node.value):
                    add_target(node.target)
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None \
                        and expr_traced(node.value):
                    add_target(node.target)
            if len(tainted) == n0:
                break
        return tainted

    def _traced_expr(self, e: ast.AST, tainted: set[str]) -> bool:
        for n in ast.walk(e):
            if _is_traced_call(n):
                return True
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
        return False


# --------------------------------------------------------------------- #
# 2. host-sync
# --------------------------------------------------------------------- #
class HostSyncChecker(Checker):
    """`.item()`, `float()`, `int()`, `np.asarray()` on arrays inside the
    grow/stream/scoring loops: each one is a blocking device->host fetch
    that serialises the dispatch pipeline through the tunnel.  Scoped to
    the hot-loop files; loop bodies (for/while/comprehensions) only."""

    rule = "host-sync"
    path_scope = (r"^ddt_tpu/ops/grow\.py$", r"^ddt_tpu/ops/stream\.py$",
                  r"^ddt_tpu/backends/tpu\.py$")
    _LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
              ast.DictComp, ast.GeneratorExp)

    def run(self) -> list[Finding]:
        for loop in ast.walk(self.ctx.tree):
            if isinstance(loop, self._LOOPS):
                self._check_loop(loop)
        # dedupe: nested loops visit the same node twice
        seen, out = set(), []
        for f in self.findings:
            k = (f.line, f.col, f.message)
            if k not in seen:
                seen.add(k)
                out.append(f)
        self.findings = out
        return self.findings

    def _check_loop(self, loop: ast.AST) -> None:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            d = callgraph.dotted(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                self.report(node, "`.item()` in a loop body forces a "
                                  "blocking device->host sync per iteration")
            elif d in ("float", "int") and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant):
                self.report(node, (
                    f"`{d}()` on an array in a loop body blocks on the "
                    "device — hoist the sync out of the loop or keep the "
                    "value on device"))
            elif d in ("np.asarray", "np.array", "numpy.asarray",
                       "numpy.array"):
                self.report(node, (
                    f"`{d}()` in a loop body copies device memory to host "
                    "per iteration — batch the fetch outside the loop"))


# --------------------------------------------------------------------- #
# 3. dtype-drift
# --------------------------------------------------------------------- #
class DtypeDriftChecker(Checker):
    """Array constructors without an explicit dtype in ops/: the default
    (f32 vs x64-mode f64, plus weak-type promotion) differs between the
    CPU and TPU backends and between jax configs, so accumulator dtypes
    must be spelled out.  Also flags bare float literals flowing into
    histogram builders/accumulators, where a weakly-typed Python float
    silently upcasts a bf16/f32 accumulation."""

    rule = "dtype-drift"
    path_scope = (r"^ddt_tpu/ops/",)
    # ctor -> index of the positional dtype parameter
    _CTORS = {"jnp.zeros": 1, "jnp.ones": 1, "jnp.array": 1, "jnp.empty": 1}
    _HIST_RE = re.compile(r"(hist|acc)", re.IGNORECASE)

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        if d in self._CTORS:
            pos = self._CTORS[d]
            has_dtype = len(node.args) > pos or any(
                k.arg == "dtype" for k in node.keywords)
            if not has_dtype:
                self.report(node, (
                    f"`{d}(...)` without an explicit dtype — the default "
                    "drifts between backends/x64 mode; pass dtype= "
                    "(positionally or by keyword)"))
        if d is not None and "histogram" in d.split(".")[-1].lower():
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                float):
                    self.report(arg, (
                        "bare float literal passed into a histogram "
                        "builder — wrap in jnp.float32(...) to pin the "
                        "accumulator dtype"))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if isinstance(node.target, ast.Name) \
                and self._HIST_RE.search(node.target.id) \
                and self._bare_float(node.value):
            self.report(node, (
                f"bare float literal accumulated into `{node.target.id}` — "
                "weak-type promotion can upcast the histogram dtype; wrap "
                "in jnp.float32(...)"))
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp):
        pairs = ((node.left, node.right), (node.right, node.left))
        for name_side, lit_side in pairs:
            if isinstance(name_side, ast.Name) \
                    and self._HIST_RE.search(name_side.id) \
                    and isinstance(lit_side, ast.Constant) \
                    and isinstance(lit_side.value, float):
                self.report(node, (
                    f"bare float literal combined with `{name_side.id}` — "
                    "weak-type promotion can upcast the histogram dtype; "
                    "wrap in jnp.float32(...)"))
                break
        self.generic_visit(node)

    @staticmethod
    def _bare_float(e: ast.AST) -> bool:
        return isinstance(e, ast.Constant) and isinstance(e.value, float)


# --------------------------------------------------------------------- #
# 4. collective-consistency
# --------------------------------------------------------------------- #
class CollectiveAxisChecker(Checker):
    """String axis names in collectives must exist on a mesh defined in
    parallel/mesh.py — a typo'd axis traces fine on one device and dies
    (or worse, silently no-ops the reduction) under shard_map on the pod.
    Variable axis arguments are skipped (plumbed from the mesh at runtime,
    which is exactly the safe pattern)."""

    rule = "collective-consistency"
    path_scope = (r"^ddt_tpu/",)
    # collective -> positional index of the axis-name argument
    _AXIS_POS = {
        "psum": 1, "psum_scatter": 1, "pmin": 1, "pmax": 1, "pmean": 1,
        "all_gather": 1, "all_to_all": 1, "ppermute": 1,
        "axis_index": 0, "axis_size": 0,
    }

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        last = d.split(".")[-1] if d else None
        if last in self._AXIS_POS and d != last:   # require lax./jax.lax.
            axis = None
            for k in node.keywords:
                if k.arg in ("axis_name", "axis_names"):
                    axis = k.value
            pos = self._AXIS_POS[last]
            if axis is None and len(node.args) > pos:
                axis = node.args[pos]
            for name in self._literal_axes(axis):
                if name not in self.ctx.mesh_axes:
                    known = ", ".join(sorted(self.ctx.mesh_axes)) or "(none)"
                    self.report(node, (
                        f"`{last}` over axis {name!r} which no mesh in "
                        f"parallel/mesh.py defines (known axes: {known}) — "
                        "mismatched collective axis names deadlock or "
                        "mis-reduce under shard_map"))
        self.generic_visit(node)

    @staticmethod
    def _literal_axes(axis: ast.AST | None) -> list[str]:
        if axis is None:
            return []
        if isinstance(axis, ast.Constant) and isinstance(axis.value, str):
            return [axis.value]
        if isinstance(axis, (ast.Tuple, ast.List)):
            return [e.value for e in axis.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
        return []


# --------------------------------------------------------------------- #
# 5. broad-except
# --------------------------------------------------------------------- #
class BroadExceptChecker(Checker):
    """`except Exception` / bare `except` swallow real faults (the
    conftest thread-pin finding: a ctypes TypeError became nondeterministic
    bit-identity flakes).  Handlers that re-raise are exempt — translating
    an exception type is the legitimate use of a broad catch."""

    rule = "broad-except"
    path_scope = None                         # everywhere scanned

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        broad = False
        if node.type is None:
            broad = True
        else:
            names = []
            if isinstance(node.type, ast.Tuple):
                names = [callgraph.dotted(e) for e in node.type.elts]
            else:
                names = [callgraph.dotted(node.type)]
            broad = any(n in ("Exception", "BaseException") for n in names)
        if broad and not any(isinstance(n, ast.Raise)
                             for n in ast.walk(node)):
            what = "bare `except:`" if node.type is None \
                else "`except Exception`"
            self.report(node, (
                f"{what} without re-raise swallows unexpected faults — "
                "narrow to the exception types the fallback is designed "
                "for (e.g. `except (ImportError, OSError)`)"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# 6. no-print
# --------------------------------------------------------------------- #
class NoPrintChecker(Checker):
    """Bare `print(...)` in ddt_tpu/ LIBRARY code: invisible to logging
    config, unparseable by log shippers, and — since the telemetry PR —
    redundant with the structured event stream every trainer can emit.
    The CLI (ddt_tpu/cli.py) is exempt (stdout JSON lines ARE its
    interface), as are tools/ and tests/ (outside the scanned scope /
    path_scope). Only the BUILTIN name counts: methods named print and
    callables passed in as parameters are fine."""

    rule = "no-print"
    # Negative lookahead: everything under ddt_tpu/ except the CLI.
    path_scope = (r"^ddt_tpu/(?!cli\.py$)",)

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            self.report(node, (
                "bare `print(...)` in ddt_tpu library code — emit a "
                "telemetry event (ddt_tpu.telemetry.RunLog.emit) or use "
                "the module logger; stdout belongs to the CLI"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# 7. pallas-interpret
# --------------------------------------------------------------------- #
class PallasInterpretChecker(Checker):
    """`pl.pallas_call` sites must carry a LIVE `interpret=` operand — a
    variable the dispatcher resolves (the hist_pallas/predict_pallas
    idiom: `interpret=None` auto-selects the Pallas interpreter off-TPU).
    A call site with no interpret kwarg, or a hard `interpret=False`,
    has no interpret-mode fallback path: the kernel cannot run on the
    CPU tier-1 suite, so its logic ships untested and every later edit
    is verified only on a real chip.  Pallas kernels are jit-reachability
    roots (callgraph.TRACING_COMBINATORS includes pallas_call, bare or
    partial()-wrapped), so the traced-branch rule already covers the
    kernel BODY; this rule covers its DISPATCH."""

    rule = "pallas-interpret"
    path_scope = (r"^ddt_tpu/",)

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        if d is not None and d.split(".")[-1] == "pallas_call":
            interp = None
            has_kwarg = False
            for k in node.keywords:
                if k.arg == "interpret":
                    has_kwarg = True
                    interp = k.value
            if not has_kwarg:
                self.report(node, (
                    "`pallas_call` without an `interpret=` operand — the "
                    "kernel has no interpret-mode fallback path and "
                    "cannot run on the CPU test suite; thread an "
                    "`interpret` parameter through the dispatcher "
                    "(None = auto-select off-TPU, the hist_pallas "
                    "pattern)"))
            elif isinstance(interp, ast.Constant) \
                    and interp.value in (False, None):
                self.report(node, (
                    f"`pallas_call` hard-codes interpret="
                    f"{interp.value!r} — the interpreter fallback is "
                    "unreachable; pass a dispatcher-resolved variable "
                    "(None = auto-select off-TPU, the hist_pallas "
                    "pattern)"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# 7b. pallas-vmem-guard
# --------------------------------------------------------------------- #
class PallasVmemGuardChecker(Checker):
    """Every `pl.pallas_call` dispatch site must sit behind a VMEM-fits
    predicate — a call whose name matches `*fits*` / `*chunks_for*`
    (the hist_pallas.pallas_fits / feature_chunks_for /
    predict_pallas.predict_pallas_fits idiom) — in the dispatching
    function itself or in a module-local (transitive) caller. A Pallas
    kernel pins its whole working set in VMEM: an unguarded dispatch at
    a shape past the ~16 MB/core budget dies as a Mosaic allocation
    failure (or a silent multi-minute pathological compile) ON THE CHIP
    ONLY — the CPU interpret-mode tests never see it, so the guard is
    the one thing standing between a new config knob and a fleet crash.
    Dispatch units are module-level functions, class METHODS, and
    module-scope code (no pallas_call site can hide by where it sits);
    cross-module dispatchers don't count: the module that owns the
    kernel must own (or call) its own budget predicate, so the guard and
    the kernel's VMEM layout can never drift apart in separate files."""

    rule = "pallas-vmem-guard"
    path_scope = (r"^ddt_tpu/",)
    _GUARD_RE = re.compile(r"fits|chunks_for")

    def _units(self):
        """(qualname, node) dispatch units: module-level functions,
        CLASS METHODS (qualified `Class.method` so same-named methods in
        different classes keep distinct guard status), and a `<module>`
        pseudo-unit for module-scope statements — no pallas_call site
        can hide from the scan by where it sits. Nested defs stay part
        of their enclosing unit (they dispatch under its entry point).
        Call EDGES still resolve on the bare last name (`self.m()` and
        `obj.m()` are indistinguishable statically), conservatively
        linking every same-named unit."""
        defs = (ast.FunctionDef, ast.AsyncFunctionDef)
        for node in ast.iter_child_nodes(self.ctx.tree):
            if isinstance(node, defs):
                yield node.name, node
            elif isinstance(node, ast.ClassDef):
                for m in ast.iter_child_nodes(node):
                    if isinstance(m, defs):
                        yield f"{node.name}.{m.name}", m
        # Module scope: everything outside the units above.
        mod = ast.Module(
            body=[n for n in self.ctx.tree.body
                  if not isinstance(n, defs + (ast.ClassDef,))],
            type_ignores=[])
        yield "<module>", mod

    def run(self) -> list[Finding]:
        calls: dict[str, set[str]] = {}       # qual -> called last-names
        guarded: set[str] = set()             # quals with a fits call
        dispatches: dict[str, list[ast.AST]] = {}
        by_bare: dict[str, list[str]] = {}    # bare name -> quals
        for qual, fn in self._units():
            by_bare.setdefault(qual.split(".")[-1], []).append(qual)
            called: set[str] = set()
            for n in ast.walk(fn):               # incl. nested defs: they
                if not isinstance(n, ast.Call):  # dispatch under the
                    continue                     # enclosing entry point
                d = callgraph.dotted(n.func)
                if d is None:
                    continue
                last = d.split(".")[-1]
                called.add(last)
                if last == "pallas_call":
                    dispatches.setdefault(qual, []).append(n)
                if self._GUARD_RE.search(last):
                    guarded.add(qual)
            calls[qual] = called

        # Reverse reachability: the dispatching unit plus every
        # module-local transitive caller (a called bare name links every
        # unit carrying it).
        callers: dict[str, set[str]] = {q: set() for q in calls}
        for src, called in calls.items():
            for c in called:
                for target in by_bare.get(c, ()):
                    callers[target].add(src)

        for qual, sites in dispatches.items():
            seen, stack = {qual}, [qual]
            ok = False
            while stack and not ok:
                cur = stack.pop()
                if cur in guarded:
                    ok = True
                    break
                for up in callers.get(cur, ()):
                    if up not in seen:
                        seen.add(up)
                        stack.append(up)
            if ok:
                continue
            for site in sites:
                self.report(site, (
                    f"`pallas_call` in '{qual}' has no VMEM-fits guard on "
                    "its module-local dispatch chain — gate the dispatch "
                    "behind a budget predicate (the hist_pallas."
                    "pallas_fits / feature_chunks_for pattern) so "
                    "over-budget shapes fail at the cause instead of as "
                    "an on-chip Mosaic VMEM allocation failure"))
        return self.findings


# --------------------------------------------------------------------- #
# 8. named-scope
# --------------------------------------------------------------------- #
class NamedScopeChecker(Checker):
    """Every jit-reachable op ENTRY POINT in ddt_tpu/ops/ — a public
    top-level function that lowers device work (contains jnp./jax./lax.
    array calls) — must open a `ddt:`-prefixed scope
    (telemetry.annotations.traced_scope, or jax.named_scope with a
    literal "ddt:..." name) somewhere in its body, so XLA op metadata —
    and therefore Perfetto/trace-export timelines — stays attributable
    to the pipeline stage that emitted it (docs/OBSERVABILITY.md
    "Phase timing and Perfetto alignment"). Host-only helpers (shape
    math, impl resolvers) contain no traced calls and are exempt;
    private helpers and nested defs trace under their caller's scope."""

    rule = "named-scope"
    path_scope = (r"^ddt_tpu/ops/",)

    def run(self) -> list[Finding]:
        for node in ast.iter_child_nodes(self.ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name.startswith("_"):
                continue
            if node.name not in self.ctx.reachable:
                continue                      # never traced: no HLO to name
            if not self._does_device_work(node):
                continue                      # host-only helper
            if self._opens_ddt_scope(node):
                continue
            self.report(node, (
                f"jit-reachable op entry point '{node.name}' opens no "
                "`ddt:` named scope — wrap its device work in "
                "telemetry.annotations.traced_scope(...) so traces stay "
                "attributable (docs/OBSERVABILITY.md)"))
        return self.findings

    @staticmethod
    def _does_device_work(fn: ast.AST) -> bool:
        return any(_is_traced_call(n) for n in ast.walk(fn))

    @staticmethod
    def _opens_ddt_scope(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            d = callgraph.dotted(n.func)
            if d is None:
                continue
            last = d.split(".")[-1]
            # Both telemetry.annotations spellings add the ddt: prefix
            # themselves: traced_scope (with-block) / op_scope (decorator).
            if last in ("traced_scope", "op_scope"):
                return True
            if last == "named_scope" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str) \
                    and n.args[0].value.startswith("ddt:"):
                return True
        return False


# --------------------------------------------------------------------- #
# 9. atomic-artifact-write
# --------------------------------------------------------------------- #
class AtomicArtifactWriteChecker(Checker):
    """Persistent artifacts (checkpoints, model files, chunk caches)
    must be written tmp-then-`os.replace` — a direct
    `np.savez(final, ...)` / `open(final, "w")` killed mid-write leaves
    a TORN artifact at the canonical name, which a later resume/load
    then chokes on (the checkpoint-hardening bug class,
    docs/ROBUSTNESS.md). Scoped to the artifact-owning modules
    (utils/checkpoint.py, api.py, models/, data/chunks.py, and — since
    the model registry (ISSUE 9) — ddt_tpu/registry/, whose manifests
    and name indexes are exactly the small-JSON-beside-big-npz pair the
    checkpoint hardening story is about); a write is compliant when its
    path expression is tmp-like — a name/attribute/literal containing
    "tmp", or anything tempfile-derived — because the
    tmp-name-then-replace dance is exactly the pattern the rule exists
    to enforce. Read modes and append modes are exempt (appends are
    logs, not artifact overwrites; the run log's crash story is
    line-granularity by design). ddt_tpu/export/ stays OUT of scope by
    design: its writers only ever target a registry STAGING directory,
    which publishes wholesale via one atomic os.rename
    (registry/store.py) — the directory is the tmp sibling."""

    rule = "atomic-artifact-write"
    path_scope = (r"^ddt_tpu/utils/checkpoint\.py$", r"^ddt_tpu/api\.py$",
                  r"^ddt_tpu/models/", r"^ddt_tpu/data/chunks\.py$",
                  r"^ddt_tpu/registry/")
    _WRITERS = {"np.save", "np.savez", "np.savez_compressed",
                "numpy.save", "numpy.savez", "numpy.savez_compressed"}

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        if d in self._WRITERS and node.args \
                and not self._tmp_like(node.args[0]):
            self.report(node, (
                f"`{d}(...)` writes a persistent artifact directly to its "
                "final path — a kill mid-write leaves a torn file there; "
                "write to a tmp-suffixed sibling and `os.replace` it "
                "(docs/ROBUSTNESS.md atomic-artifact-write)"))
        elif d == "open" and node.args:
            mode = self._mode(node)
            if mode is not None and ("w" in mode or "x" in mode) \
                    and not self._tmp_like(node.args[0]):
                self.report(node, (
                    f"`open(..., {mode!r})` truncates a persistent "
                    "artifact in place — a kill mid-write leaves a torn "
                    "file at the final path; write a tmp-suffixed sibling "
                    "and `os.replace` it (docs/ROBUSTNESS.md "
                    "atomic-artifact-write)"))
        self.generic_visit(node)

    @staticmethod
    def _mode(node: ast.Call) -> str | None:
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            return node.args[1].value
        for k in node.keywords:
            if k.arg == "mode" and isinstance(k.value, ast.Constant) \
                    and isinstance(k.value.value, str):
                return k.value.value
        return None

    @staticmethod
    def _tmp_like(e: ast.AST) -> bool:
        for n in ast.walk(e):
            if isinstance(n, ast.Name) and "tmp" in n.id.lower():
                return True
            if isinstance(n, ast.Attribute) and "tmp" in n.attr.lower():
                return True
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and "tmp" in n.value.lower():
                return True
            if isinstance(n, ast.Call):
                d = callgraph.dotted(n.func)
                if d is not None and (
                        d.startswith("tempfile.")
                        or "temp" in d.split(".")[-1].lower()):
                    return True
        return False


# --------------------------------------------------------------------- #
# 10. raw-phase-timing
# --------------------------------------------------------------------- #
class RawPhaseTimingChecker(Checker):
    """Raw host clocks (`time.time()` / `time.perf_counter()` /
    `time.monotonic()`, and their _ns twins) in the device-op layer
    (ddt_tpu/ops/, ddt_tpu/backends/): a host timestamp around device
    work measures DISPATCH, not the device — XLA enqueues asynchronously,
    so the number silently reports queue depth and looks plausible in a
    log.  Phase timing belongs at the trainer layer through
    PhaseTimer/phase_ctx (utils/profiling.py + telemetry/annotations.py,
    which pair the wallclock with the required sync discipline and emit
    it into the run log); device-side attribution belongs to the named
    `ddt:` scopes + the cost observatory (telemetry/costmodel.py), not a
    clock.  The trainer loops (driver/streaming — PhaseTimer's
    consumers), the timing subsystem itself, the shard-readiness probe
    (parallel/mesh.py), bench harnesses, cli, and tests are all outside
    the scope: their clocks ARE the instrument.  time.sleep and the time
    module's non-clock helpers are not flagged."""

    rule = "raw-phase-timing"
    path_scope = (r"^ddt_tpu/ops/", r"^ddt_tpu/backends/")
    _CLOCKS = {"time.time", "time.perf_counter", "time.monotonic",
               "time.perf_counter_ns", "time.monotonic_ns",
               "time.process_time", "time.process_time_ns"}

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        if d in self._CLOCKS:
            self.report(node, (
                f"`{d}()` in the device-op layer times DISPATCH, not the "
                "device (XLA enqueues asynchronously) — time phases at "
                "the trainer layer via PhaseTimer/phase_ctx "
                "(telemetry/annotations.py), or attribute device work "
                "with `ddt:` scopes + the cost observatory "
                "(docs/OBSERVABILITY.md)"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# 11. serve-blocking-io
# --------------------------------------------------------------------- #
class ServeBlockingIOChecker(Checker):
    """Blocking host I/O in the serving tier's HOT-LOOP modules
    (ddt_tpu/serve/batcher.py + engine.py): the admission batcher's
    dispatcher thread is shared by EVERY in-flight request — one
    `time.sleep` poll or synchronous file read there adds its wall time
    to the whole queue's tail latency, invisibly (the p999 the SLO
    counters exist to expose). Since the express lane (ISSUE 12) the
    stakes are doubled: the SAME dispatch path (`ServeEngine._dispatch`
    and everything it reaches) also runs synchronously on HTTP handler
    threads for empty-queue single-row requests, so a blocking call
    there is both the whole queue's tail tax AND the express path's
    whole latency budget — the lane exists to score in ~dispatch time,
    and one file read erases it. Flagged: `time.sleep` (park on a
    Condition/Event with a timeout instead — the batcher's admission
    window does exactly that), `open(...)` in any mode, `np.load` /
    `json.load`, and Path `.read_text`/`.read_bytes` (model files load
    in the cli/http layer and arrive as ready ModelBundles —
    docs/SERVING.md "Hot swap"). The transport layer (serve/http.py)
    and everything outside ddt_tpu/serve/ are out of scope: their
    blocking is the caller's thread, not the dispatch path's."""

    rule = "serve-blocking-io"
    path_scope = (r"^ddt_tpu/serve/batcher\.py$",
                  r"^ddt_tpu/serve/engine\.py$")
    _BLOCKING_CALLS = {"time.sleep", "open", "np.load", "numpy.load",
                       "json.load"}
    _READ_ATTRS = {"read_text", "read_bytes"}

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        if d in self._BLOCKING_CALLS:
            self.report(node, (
                f"`{d}(...)` in a serving hot-loop module blocks the "
                "shared dispatch path — it taxes every in-flight "
                "request's tail latency on the dispatcher thread AND "
                "is the express lane's whole latency budget on the "
                "handler thread — park on a Condition/Event timeout, "
                "or move the I/O to the cli/http layer "
                "(docs/SERVING.md; ddtlint serve-blocking-io)"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in self._READ_ATTRS:
            self.report(node, (
                f"`.{node.func.attr}()` in a serving hot-loop module is "
                "a synchronous file read on the shared dispatcher "
                "thread — load artifacts in the cli/http layer and hand "
                "the engine ready objects (docs/SERVING.md; ddtlint "
                "serve-blocking-io)"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# 12. one-home-collective
# --------------------------------------------------------------------- #
class OneHomeCollectiveChecker(Checker):
    """Raw `jax.lax` collectives outside parallel/comms.py: every
    cross-device byte the trainer moves must funnel through the one-home
    comms module (psum/pmax/pmin/all_gather/reduce_scatter wrappers with
    version-portable fallbacks, compression, `ddt:comms:*` scopes) — a
    raw psum elsewhere silently bypasses split_comms/hist_comms_dtype
    AND desynchronizes the `hist_allreduce_bytes` payload model from the
    wire it claims to estimate. comms.py itself is the sanctioned home;
    `axis_index`/`axis_size` are topology reads, not traffic, and stay
    legal everywhere (collective-consistency still checks their axis
    names)."""

    rule = "one-home-collective"
    path_scope = (r"^ddt_tpu/(?!parallel/comms\.py$)",)
    _COLLECTIVES = {
        "psum", "psum_scatter", "pmin", "pmax", "pmean",
        "all_gather", "all_to_all", "ppermute", "pshuffle",
    }

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        last = d.split(".")[-1] if d else None
        # Require the lax./jax.lax. spelling (like collective-consistency):
        # comms.psum(...) and locally-defined helpers named psum are the
        # sanctioned indirections, not raw collectives.
        if last in self._COLLECTIVES and d != last \
                and d.split(".")[-2] in ("lax",):
            self.report(node, (
                f"raw `{d}(...)` outside parallel/comms.py — route the "
                "collective through the one-home comms module so "
                "split_comms/hist_comms_dtype apply and the "
                "hist_allreduce_bytes payload model stays true to the "
                "wire (docs/ANALYSIS.md one-home-collective)"))
        self.generic_visit(node)


AST_CHECKERS = [
    TracedBranchChecker,
    HostSyncChecker,
    DtypeDriftChecker,
    CollectiveAxisChecker,
    BroadExceptChecker,
    NoPrintChecker,
    PallasInterpretChecker,
    PallasVmemGuardChecker,
    NamedScopeChecker,
    AtomicArtifactWriteChecker,
    RawPhaseTimingChecker,
    ServeBlockingIOChecker,
    OneHomeCollectiveChecker,
    # ddtlint v2 flow-aware passes (ISSUE 13): the sharding-spec
    # contract and the serve-tier thread/lock-discipline analysis.
    *shardspec.CHECKERS,
    threadmodel.ThreadModelChecker,
    # ddtlint v3 contract passes (ISSUE 16): config-flow cache-key /
    # fingerprint coverage and the mechanized telemetry schema.
    *configflow.CHECKERS,
    *telemetrycontract.CHECKERS,
]


# --------------------------------------------------------------------- #
# 6. suppression-hygiene  (not AST — .supp files)
# --------------------------------------------------------------------- #
SUPPRESSION_RULE = "suppression-hygiene"
#: suppression patterns scoped to our own kernels are self-justifying
_SCOPED_PREFIX = "ddt_"


def is_process_wide_suppression(line: str) -> bool:
    """Is a sanitizer-suppression entry (`race:PATTERN`, ...) process-wide,
    i.e. NOT scoped to one of our own kernel symbols?  Single source of
    truth shared with tsan_audit.write_audit_supp — the hygiene rule and
    the mechanized audit must classify entries identically, or the audited
    configuration stops matching what the gate enforces."""
    _, _, pattern = line.strip().partition(":")
    return not pattern.startswith(_SCOPED_PREFIX)


def check_suppressions(path: str, text: str) -> list[Finding]:
    """Sanitizer suppression hygiene: every PROCESS-WIDE entry (pattern not
    scoped to a ddt_ kernel symbol) must carry a structured `# AUDIT:` tag
    in its preceding comment block, naming how the suppression is
    re-verified (`make tsan-audit` reruns the soak without these entries
    and shape-checks the survivors).  Consecutive suppression lines share
    the comment block above them."""
    findings: list[Finding] = []
    block: list[str] = []                  # current comment block
    prev_was_comment = False
    for i, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line:
            prev_was_comment = False
            continue
        if line.startswith("#"):
            if not prev_was_comment:
                block = []
            block.append(line)
            prev_was_comment = True
            continue
        prev_was_comment = False
        if ":" not in line:
            continue
        if not is_process_wide_suppression(line):
            continue
        if not any("AUDIT:" in c for c in block):
            findings.append(Finding(
                rule=SUPPRESSION_RULE, path=path, line=i, col=1,
                message=(
                    f"process-wide suppression `{line}` lacks a structured "
                    "`# AUDIT:` tag in its comment block — unscoped "
                    "frame-matches can hide real races (e.g. a kernel "
                    "returning before its workers finish); tag it with the "
                    "re-verification procedure (`make tsan-audit`)"),
                line_text=line,
            ))
    return findings
