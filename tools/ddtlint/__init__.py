"""ddtlint — project-native static analysis for JAX/TPU correctness hazards.

The Driver/DeviceBackend split puts the tree-growth loop behind jitted XLA
programs, which makes whole classes of bugs invisible to CPU-only tests
until they hit real hardware: silent host<->device syncs in the hot loop,
Python branching on traced values, dtype drift between backends, collective
axis names that don't exist on any mesh.  ddtlint mechanizes those reviews
as small AST checkers with a checked-in ratchet baseline (docs/ANALYSIS.md).

Usage:
    python -m tools.ddtlint ddt_tpu/ tests/            # gate (exit 1 on new)
    python -m tools.ddtlint --write-baseline ...       # regenerate baseline
    python -m tools.ddtlint --list-rules

The pytest gate lives in tests/test_lint.py (tier-1, marker-free).
"""

from tools.ddtlint.findings import Finding, fingerprint
from tools.ddtlint.runner import lint_paths, load_baseline, run_on_source

__all__ = [
    "Finding",
    "fingerprint",
    "lint_paths",
    "load_baseline",
    "run_on_source",
]
