"""ddtlint — project-native static analysis for JAX/TPU correctness hazards.

The Driver/DeviceBackend split puts the tree-growth loop behind jitted XLA
programs, which makes whole classes of bugs invisible to CPU-only tests
until they hit real hardware: silent host<->device syncs in the hot loop,
Python branching on traced values, dtype drift between backends, collective
axis names that don't exist on any mesh.  ddtlint mechanizes those reviews
as small AST checkers with a checked-in ratchet baseline (docs/ANALYSIS.md).

Since v2 (ISSUE 13) two FLOW-AWARE passes join the per-file visitors:
threadmodel.py (serve-tier thread roles + lock discipline — lock-order
cycles, cross-role unguarded state, blocking-under-lock, leaked
acquires, `--explain-threads`) and shardspec.py (the mechanized
SpecLayout contract — hand-built PartitionSpecs, literal mesh axis
names, layout-rule-table coverage).

Usage:
    python -m tools.ddtlint ddt_tpu/ tests/            # gate (exit 1 on new)
    python -m tools.ddtlint --write-baseline ...       # regenerate baseline
    python -m tools.ddtlint --list-rules
    python -m tools.ddtlint --changed-only             # vs git merge-base
    python -m tools.ddtlint --format json              # stable CI output
    python -m tools.ddtlint --explain-threads          # serve thread model

The pytest gate lives in tests/test_lint.py (tier-1, marker-free).
"""

from tools.ddtlint.findings import Finding, fingerprint
from tools.ddtlint.runner import lint_paths, load_baseline, run_on_source

__all__ = [
    "Finding",
    "fingerprint",
    "lint_paths",
    "load_baseline",
    "run_on_source",
]
