"""Finding record + stable fingerprints for the ratchet baseline.

A fingerprint must survive unrelated edits (line insertions above the
finding) but change when the flagged code itself changes — so it hashes
(rule, path, stripped source line, occurrence index among identical
lines) rather than the line number.  The occurrence index keeps two
textually identical violations in one file distinct so fixing one of
them cannot silently absolve the other.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative, forward slashes
    line: int            # 1-based
    col: int
    message: str
    line_text: str = ""  # stripped source of the flagged line
    fingerprint: str = field(default="", compare=False)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}"


def fingerprint(rule: str, path: str, line_text: str, occurrence: int) -> str:
    key = f"{rule}|{path}|{line_text.strip()}|{occurrence}"
    return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]


def assign_fingerprints(findings: list[Finding]) -> list[Finding]:
    """Fill in fingerprints, numbering identical (rule, path, line_text)
    triples by order of appearance.  Sorts by (path, line, col, rule) first
    so occurrence indices are deterministic."""
    findings = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    seen: dict[tuple, int] = {}
    for f in findings:
        key = (f.rule, f.path, f.line_text.strip())
        n = seen.get(key, 0)
        seen[key] = n + 1
        f.fingerprint = fingerprint(f.rule, f.path, f.line_text, n)
    return findings
