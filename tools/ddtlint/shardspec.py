"""Mechanized sharding-spec contract (ddtlint v2, ISSUE 13).

PR 11 made partition correctness DECLARATIVE: `parallel/mesh.SpecLayout`
is the one rule table mapping operand names to PartitionSpecs, and every
`shard_map` in the backend resolves its in/out specs through it by name.
But that was a convention — nothing stopped a hand-built `P("rows")`, a
raw axis-name literal, or an operand name the rule table doesn't know
from compiling fine and silently de-sharding (or replicating) an
operand. Until now the only enforcement was dynamic: the collective-
inventory contract in tests/test_distributed.py and the trace-time raise
inside `match_partition_rules`. This pass moves the contract to lint
time:

* `handbuilt-partition-spec` — direct `PartitionSpec(...)`/`P(...)`
  construction in `ddt_tpu/backends/`: specs there must resolve through
  `backend.layout` (SpecLayout) by operand name, so the mesh's axis
  story lives in ONE rule table and a new axis is a table edit, not a
  hunt through shard_map call sites.
* `axis-name-literal` — a mesh axis name ("rows"/"hosts"/"features" —
  whatever parallel/mesh.py defines) spelled as a string literal
  anywhere outside parallel/mesh.py, in an axis-bearing position: an
  `axis_name=` keyword, a positional argument to a collective /
  topology helper, an axis-named assignment target, or a
  PartitionSpec argument. Axis names must be THREADED from the mesh
  module as parameters — a literal compiles on every mesh that happens
  to define it and silently de-shards on one that doesn't. This is
  also the collective-parameterization contract for parallel/comms.py
  itself: its wrappers take `axis_name` arguments, never literals.
* `layout-rule-coverage` — operand names passed to
  `layout.spec("name")` / `layout.specs(...)` are checked against the
  regex rule table statically read out of `SpecLayout.rules()`: an
  unmatched name is a lint finding at the call site, not a trace-time
  `ValueError` on the first distributed run.

Scope notes: tests/ spell axes and specs freely (they construct
adversarial meshes on purpose) and parallel/mesh.py IS the home of the
names — both stay out of scope.
"""

from __future__ import annotations

import ast
import re

from tools.ddtlint import callgraph
from tools.ddtlint.base import Checker

RULE_HANDBUILT = "handbuilt-partition-spec"
RULE_AXIS_LITERAL = "axis-name-literal"
RULE_COVERAGE = "layout-rule-coverage"

RULES = (RULE_HANDBUILT, RULE_AXIS_LITERAL, RULE_COVERAGE)

#: functions whose positional string args are mesh axis names — the
#: comms wrappers, the raw lax collectives (one-home-collective already
#: bans those outside comms.py; the literal ban applies in BOTH homes),
#: and the topology readers.
_AXIS_FUNCS = {
    "psum", "psum_scatter", "pmin", "pmax", "pmean", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "reduce_scatter",
    "hist_reduce", "combine_shard_winners", "axis_index", "axis_size",
    "static_axis_size", "flat_axis_index",
}
_AXIS_KWARGS = {"axis_name", "axis_names", "feature_axis_name"}


def layout_rule_patterns(tree: ast.AST | None) -> "list[str] | None":
    """Statically read the [(regex, spec)] rule table out of
    SpecLayout.rules() in a parsed parallel/mesh.py — the
    layout-rule-coverage oracle. None when the table cannot be found
    (the rule then skips rather than guessing)."""
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "SpecLayout":
            for fn in ast.iter_child_nodes(node):
                if isinstance(fn, ast.FunctionDef) and fn.name == "rules":
                    pats = []
                    for n in ast.walk(fn):
                        if isinstance(n, ast.Tuple) and n.elts \
                                and isinstance(n.elts[0], ast.Constant) \
                                and isinstance(n.elts[0].value, str):
                            pats.append(n.elts[0].value)
                    return pats or None
    return None


class HandbuiltPartitionSpecChecker(Checker):
    """Direct PartitionSpec construction in the backend layer — the
    declarative layout's one bypass. `backend.layout` (SpecLayout,
    parallel/mesh.py) must be the only producer of specs there: a
    hand-built `P(...)` compiles fine and silently de-shards (or
    replicates — a 10x memory bug) the operand the rule table would
    have placed correctly, and nothing dynamic catches it until a pod
    run reads the wrong bytes."""

    rule = RULE_HANDBUILT
    path_scope = (r"^ddt_tpu/backends/",)

    def run(self):
        # Every name the module binds to PartitionSpec: import aliases
        # (`from jax.sharding import PartitionSpec as P`) and assigned
        # aliases of ANY name (`Spec = jax.sharding.PartitionSpec`,
        # chained `Q = Spec`), to a fixpoint — the rule exists to catch
        # bypasses, so a renamed alias must not be one.
        aliases = {"PartitionSpec"}
        for _ in range(8):
            n0 = len(aliases)
            for n in ast.walk(self.ctx.tree):
                if isinstance(n, ast.ImportFrom):
                    for a in n.names:
                        if a.name == "PartitionSpec" and a.asname:
                            aliases.add(a.asname)
                elif isinstance(n, ast.Assign) \
                        and isinstance(n.value, (ast.Attribute, ast.Name)):
                    d = callgraph.dotted(n.value)
                    if d is not None and d.split(".")[-1] in aliases:
                        aliases.update(t.id for t in n.targets
                                       if isinstance(t, ast.Name))
            if len(aliases) == n0:
                break
        for n in ast.walk(self.ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            d = callgraph.dotted(n.func)
            if d is not None and d.split(".")[-1] in aliases:
                self.report(n, (
                    f"hand-built `{d}(...)` in the backend layer — "
                    "resolve the spec through backend.layout "
                    "(SpecLayout, parallel/mesh.py) by operand name so "
                    "the mesh's axis story stays in the one rule table "
                    "(docs/ANALYSIS.md handbuilt-partition-spec)"))
        return self.findings


class AxisNameLiteralChecker(Checker):
    """Mesh axis names as string literals outside parallel/mesh.py, in
    axis-bearing positions (see module doc). The safe pattern is the
    one the codebase already uses everywhere else: import the
    `*_AXIS` constant or thread the name as a parameter."""

    rule = RULE_AXIS_LITERAL
    path_scope = (r"^ddt_tpu/(?!parallel/mesh\.py$)",)

    def _literal_axes(self, node: ast.AST | None):
        if node is None:
            return
        if isinstance(node, ast.Constant) and node.value in self.ctx.mesh_axes:
            yield node
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                if isinstance(e, ast.Constant) \
                        and e.value in self.ctx.mesh_axes:
                    yield e

    def _flag(self, node: ast.AST, where: str) -> None:
        self.report(node, (
            f"mesh axis name {node.value!r} as a literal {where} outside "
            "parallel/mesh.py — import the *_AXIS constant or thread the "
            "axis name as a parameter; a literal compiles on any mesh "
            "that happens to define it and silently de-shards on one "
            "that doesn't (docs/ANALYSIS.md axis-name-literal)"))

    def visit_Assign(self, node: ast.Assign):
        targets = node.targets
        if any(self._axis_named(t) for t in targets):
            for lit in self._literal_axes(node.value):
                self._flag(lit, "bound to an axis-named variable")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if self._axis_named(node.target) and node.value is not None:
            for lit in self._literal_axes(node.value):
                self._flag(lit, "bound to an axis-named variable")
        self.generic_visit(node)

    @staticmethod
    def _axis_named(t: ast.AST) -> bool:
        name = t.id if isinstance(t, ast.Name) else (
            t.attr if isinstance(t, ast.Attribute) else "")
        return re.search(r"axis|axes", name.lower()) is not None

    def visit_Call(self, node: ast.Call):
        d = callgraph.dotted(node.func)
        last = d.split(".")[-1] if d else None
        for k in node.keywords:
            if k.arg in _AXIS_KWARGS:
                for lit in self._literal_axes(k.value):
                    self._flag(lit, f"as `{k.arg}=`")
        if last in _AXIS_FUNCS:
            for a in node.args:
                for lit in self._literal_axes(a):
                    self._flag(lit, f"passed to `{last}`")
        if last in ("P", "PartitionSpec"):
            for a in node.args:
                for lit in self._literal_axes(a):
                    self._flag(lit, "inside a PartitionSpec")
        self.generic_visit(node)


class LayoutRuleCoverageChecker(Checker):
    """Operand names handed to `layout.spec(...)`/`layout.specs(...)`
    must match a rule in SpecLayout.rules() — checked here against the
    statically-read rule table, so an unknown name is a lint finding at
    the call site instead of `match_partition_rules`' ValueError on the
    first distributed trace. Receivers named `lay`/`layout` count (the
    backend idiom: `lay = self.layout`); other objects with spec()
    methods are someone else's API."""

    rule = RULE_COVERAGE
    path_scope = (r"^ddt_tpu/(?!parallel/mesh\.py$)",)

    def visit_Call(self, node: ast.Call):
        rules = self.ctx.layout_rules
        if rules and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("spec", "specs"):
            recv = callgraph.dotted(node.func.value)
            if recv is not None and recv.split(".")[-1] in ("lay", "layout"):
                names = []
                for a in node.args:
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str):
                        names.append((a, a.value))
                    elif isinstance(a, ast.Starred) and isinstance(
                            a.value, (ast.List, ast.Tuple)):
                        names.extend(
                            (e, e.value) for e in a.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str))
                for lit, name in names:
                    if not any(re.search(p, name) for p in rules):
                        self.report(lit, (
                            f"operand name {name!r} matches no rule in "
                            "SpecLayout.rules() (parallel/mesh.py) — "
                            "match_partition_rules would raise at trace "
                            "time on the first distributed run; add the "
                            "operand to the rule table "
                            "(docs/ANALYSIS.md layout-rule-coverage)"))
        self.generic_visit(node)


CHECKERS = [HandbuiltPartitionSpecChecker, AxisNameLiteralChecker,
            LayoutRuleCoverageChecker]
