# Repo-root convenience targets. The real build logic lives in
# ddt_tpu/native/Makefile (C++ kernels + sanitizer builds); these wrap the
# day-to-day workflows so they are one short command from the repo root.

PY ?= python

# Static analysis gate (docs/ANALYSIS.md): exit 1 on any finding not in
# the ratchet baseline. Same check tier-1 runs via tests/test_lint.py.
lint:
	$(PY) -m tools.ddtlint ddt_tpu/ tests/

# Regenerate the ratchet baseline. Only after confirming every new entry
# is a deliberate, documented exception — the baseline should only shrink.
lint-baseline:
	$(PY) -m tools.ddtlint ddt_tpu/ tests/ --write-baseline

# ddtlint v2 smoke (docs/ANALYSIS.md): seed every ISSUE-13 hazard
# (lock inversion, cross-role write, blocking-under-gate, leaked
# acquire, hand-built spec, literal axis, uncovered layout operand,
# stale annotation) into copies of the REAL serve/backends modules and
# drive the CLI end-to-end (--format json), asserting each fires.
lint-smoke:
	$(PY) scripts/lint_smoke.py

# Mechanized TSan suppression audit (ddt_tpu/native/Makefile tsan-audit):
# soak with process-wide suppressions dropped, shape-check the survivors.
tsan-audit:
	$(PY) -m tools.ddtlint.tsan_audit --run

# Tier-1 test suite (CPU backend; the ROADMAP.md verify command).
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# Telemetry smoke (docs/OBSERVABILITY.md): train 2 rounds on synthetic
# data with a run log in a tmpdir, then render it via `cli report` —
# the round trip the tier-1 suite also asserts (tests/test_telemetry.py).
report:
	JAX_PLATFORMS=cpu $(PY) scripts/telemetry_smoke.py

# Flight-recorder smoke (docs/OBSERVABILITY.md): 2-round 2-partition CPU
# mesh train -> per-host log merge -> Perfetto trace export -> parse.
trace-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/trace_smoke.py

# xprof capture-window smoke (docs/OBSERVABILITY.md): 2-round CPU train
# with a programmatic jax.profiler window over rounds 1:2; asserts the
# trace lands and the manifest carries the run-id cross-reference.
profile-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/profile_smoke.py

# Training-kernel smoke (docs/PERF.md "Training kernel"): 2 fused rounds
# through the VMEM-streaming Pallas histogram (interpret mode) with
# sibling subtraction on; asserts fused/granular parity and the
# ddt:fused_round / ddt:hist:{stream,flush,subtract} spans.
kernel-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/kernel_smoke.py

# Chaos smoke (docs/ROBUSTNESS.md): small CPU run under a multi-fault
# plan — torn checkpoint write (digest-detected, history fallback),
# injected stream-read IOErrors (retry seam), injected straggler
# (watchdog detection) — asserting the recovered ensemble is
# BIT-IDENTICAL to an undisturbed run and the run log tells the story.
# Arm 4 (ISSUE 15): a real `cli serve` subprocess SIGKILLed mid-storm
# and restarted on the same port, with every client recovering.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/chaos_smoke.py

# Serving-tier smoke (docs/SERVING.md): tiny model behind the HTTP
# front end on CPU — 100 concurrent requests with a mid-flight hot
# swap (zero failures, old-or-new responses only), admission
# coalescing witnessed, serve_latency SLO event lands in the run log
# and renders through `cli report`. Fleet arm (ISSUE 15): 3 registry
# models of mixed tiers behind one engine, LRU eviction + reload
# mid-storm, 0 steady-state jit compiles, `report fleet` rollup, and
# saturated single-model p99 within 1.5x of the plain engine.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/serve_smoke.py

# Billion-row-shape smoke (docs/PERF.md "2D sharding"): host-sharded
# streamed training at a scaled-down out-of-core config — each "host"
# reads only its own chunk sub-shards, flat per-host peak RSS asserted
# against the run log's host_peak_rss_bytes counter, and streamed ==
# in-memory split agreement checked.
bigdata-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/bigdata_smoke.py

# Registry smoke (docs/REGISTRY.md): train -> CLI push -> COLD-process
# restore through the zero-retrace AOT loader -> serve -> bit-match vs
# the exporting process, with the jit_compiles counter witnessing zero
# compiles during serving; the run log's registry section renders the
# push/load provenance via `cli report`.
registry-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/registry_smoke.py

# Training-ops-plane smoke (docs/OBSERVABILITY.md "The training
# operations plane"): a real `cli train --status-port` subprocess is
# scraped twice MID-RUN over a live socket (strictly advancing round
# counter, /metrics round-tripped through telemetry/exposition.py),
# `report progress` renders its heartbeats, and the enabled/disabled
# overhead is measured and bounded at 1.05x.
train-ops-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/train_ops_smoke.py

# Bench regression sentinel (docs/OBSERVABILITY.md): band every metric
# of the newest BENCH_r*/MULTICHIP_r* artifact against the history
# (median ± max(3*MAD, 20%)); exit 1 on an adverse excursion. Point a
# fresh run at it with `python -m tools.benchwatch --current out.json`.
benchwatch:
	$(PY) -m tools.benchwatch

native:
	$(MAKE) -C ddt_tpu/native

.PHONY: lint lint-baseline lint-smoke tsan-audit test report trace-smoke \
	profile-smoke kernel-smoke chaos-smoke serve-smoke registry-smoke \
	bigdata-smoke train-ops-smoke benchwatch native
