"""LightGBM model.txt interop round-trips (round-2 verdict item 8):
export -> re-parse with the repo's own loader -> identical predictions.
LightGBM itself is not installed here; the format is validated
structurally and semantically via the independent parser."""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.data import datasets
from ddt_tpu.models.tree import TreeEnsemble


def _train(loss="logloss", **kw):
    if loss == "softmax":
        X, y = datasets.synthetic_multiclass(1500, n_features=8,
                                             n_classes=3, seed=11)
        kw.setdefault("n_classes", 3)
    elif loss == "mse":
        X, y = datasets.synthetic_regression(1500, seed=11)
    else:
        X, y = datasets.synthetic_binary(1500, n_features=8, seed=11)
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31, loss=loss,
                    backend="cpu", log_every=10**9, **kw)
    return res, X


@pytest.mark.parametrize("loss", ["logloss", "mse", "softmax"])
def test_roundtrip_predictions(loss):
    res, X = _train(loss)
    txt = res.ensemble.to_lightgbm_text()
    assert txt.startswith("tree\nversion=v3")
    assert "end of trees" in txt
    back = TreeEnsemble.from_lightgbm_text(txt)
    assert back.loss == loss
    assert back.n_features == res.ensemble.n_features
    want = res.ensemble.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    # base-score fold + shrinkage pre-multiplication reorder float adds:
    # ULP-level, not structural.
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_roundtrip_missing_default_directions():
    """NaN routing survives: decision_type carries the NaN missing type
    and the learned default-left bit."""
    X, y = datasets.synthetic_binary(2000, n_features=6, seed=3)
    X = X.copy()
    X[::7, 2] = np.nan
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31,
                    backend="cpu", missing_policy="learn",
                    log_every=10**9)
    txt = res.ensemble.to_lightgbm_text()
    back = TreeEnsemble.from_lightgbm_text(txt)
    want = res.ensemble.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert back.default_left is not None


def test_export_validates():
    from ddt_tpu.models.tree import empty_ensemble

    bare = empty_ensemble(2, 3, 5, 0.1, 0.0, "logloss")
    with pytest.raises(ValueError, match="raw-value thresholds"):
        bare.to_lightgbm_text()


def _train_categorical(seed=0, **kw):
    """A model with real one-vs-rest cat splits (criteo-shaped data)."""
    from ddt_tpu.data.categorical import fit_categorical_encoder
    from ddt_tpu.data.datasets import synthetic_ctr

    Xn, Xc, y = synthetic_ctr(4000, seed=seed)
    enc = fit_categorical_encoder(Xc, n_bins=63)
    X = np.concatenate([Xn, enc.transform(Xc).astype(np.float32)], axis=1)
    cat = tuple(range(Xn.shape[1], X.shape[1]))
    res = api.train(X, y, n_trees=5, max_depth=4, n_bins=63,
                    backend="cpu", cat_features=cat, log_every=10**9, **kw)
    return res, X, cat


def test_roundtrip_categorical():
    """Cat one-vs-rest splits export as LightGBM categorical nodes
    (single-bit cat_threshold bitsets) and parse back to identical
    predictions — the Criteo-config model family is no longer excluded
    from the tree-diff validation path (round-3 verdict item 6)."""
    res, X, cat = _train_categorical()
    ens = res.ensemble
    assert ens.has_cat_splits
    txt = ens.to_lightgbm_text()
    blocks = [b for b in txt.split("Tree=") if "num_cat" in b]
    n_cat_total = sum(
        int(b.split("num_cat=")[1].splitlines()[0]) for b in blocks)
    assert n_cat_total > 0, "model grew no cat splits; test data too easy"
    assert "cat_boundaries=" in txt and "cat_threshold=" in txt

    back = TreeEnsemble.from_lightgbm_text(txt)
    assert back.cat_features is not None
    assert set(back.cat_features) <= set(cat)
    want = ens.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def _lgbm_oracle_raw(txt: str, X: np.ndarray) -> np.ndarray:
    """Independent NumPy evaluator of LightGBM model.txt semantics (slow
    per-row walk, no shared code with models/lightgbm_io.py): numerical
    `v <= thr goes left`, categorical `int(v) in bitset goes left`, NaN
    follows decision_type's default-left bit. Leaf values are final
    contributions; returns the raw margin sum per row."""
    lines = txt.splitlines()
    blocks, cur = [], None
    for ln in lines:
        if ln.startswith("Tree="):
            cur = {}
            blocks.append(cur)
        elif cur is not None and "=" in ln and ln.strip():
            k, _, v = ln.partition("=")
            cur[k] = v
        elif cur is not None and not ln.strip():
            cur = None

    out = np.zeros(X.shape[0], np.float64)
    for blk in blocks:
        lv = [float(v) for v in blk["leaf_value"].split()]
        if int(blk["num_leaves"]) == 1:
            out += lv[0]
            continue
        sf = [int(v) for v in blk["split_feature"].split()]
        th = [float(v) for v in blk["threshold"].split()]
        dt = [int(float(v)) for v in blk["decision_type"].split()]
        lc = [int(v) for v in blk["left_child"].split()]
        rc = [int(v) for v in blk["right_child"].split()]
        cb = ct = None
        if int(blk.get("num_cat", "0")) != 0:
            cb = [int(v) for v in blk["cat_boundaries"].split()]
            ct = [int(v) for v in blk["cat_threshold"].split()]
        for r in range(X.shape[0]):
            ref = 0
            while ref >= 0:
                v = X[r, sf[ref]]
                if np.isnan(v):
                    left = bool(dt[ref] & 2)
                elif dt[ref] & 1:          # categorical bitset
                    ci = int(th[ref])
                    words = ct[cb[ci]:cb[ci + 1]]
                    k = int(v)
                    left = (k // 32 < len(words)
                            and bool(words[k // 32] >> (k % 32) & 1))
                else:
                    left = v <= th[ref]
                ref = lc[ref] if left else rc[ref]
            out[r] += lv[~ref]
    return out


def test_multibit_categorical_import():
    """Externally-trained LightGBM models with MULTI-category bitsets
    (round-4 verdict item 5) import via one-vs-rest chain expansion and
    score identically to an independent LightGBM-semantics oracle —
    including bitsets spanning two uint32 words, NaN rows, and an empty
    bitset whose decision_type demands NaN-default-LEFT (the one case an
    empty bitset cannot collapse: no category matches but NaN rows still
    exit left — caught by review, sentinel link in bits_of)."""
    # Hand-built model: f0 numeric, f1 categorical with 40 categories.
    # Tree 0's root sends categories {1, 5, 33, 38} left (2-word bitset);
    # its left child is numeric, right child a 1-bit cat node. Tree 1
    # has an EMPTY bitset at the root with decision_type=11
    # (categorical | default-left | NaN missing): real values all go
    # right, NaN goes LEFT.
    def words(cats):
        w = [0, 0]
        for c in cats:
            w[c // 32] |= 1 << (c % 32)
        return w

    w0 = words([1, 5, 33, 38])
    txt = "\n".join([
        "tree", "version=v3", "num_class=1", "num_tree_per_iteration=1",
        "label_index=0", "max_feature_idx=1",
        "objective=binary sigmoid:1", "feature_names=Column_0 Column_1",
        "feature_infos=[-inf:inf] [-inf:inf]", "",
        "Tree=0", "num_leaves=4", "num_cat=2",
        "split_feature=1 0 1",
        "split_gain=9 4 2",
        "threshold=0 0.35 1",
        "decision_type=1 0 1",
        "left_child=1 -1 -3",
        "right_child=2 -2 -4",
        "leaf_value=0.5 -0.25 0.125 -0.75",
        "leaf_weight=0 0 0 0", "leaf_count=0 0 0 0",
        "internal_value=0 0 0", "internal_weight=0 0 0",
        "internal_count=0 0 0",
        f"cat_boundaries=0 2 3",
        f"cat_threshold={w0[0]} {w0[1]} {1 << 7}",
        "is_linear=0", "shrinkage=1", "",
        "Tree=1", "num_leaves=2", "num_cat=1",
        "split_feature=1",
        "split_gain=1",
        "threshold=0",
        "decision_type=11",
        "left_child=-1",
        "right_child=-2",
        "leaf_value=100.0 0.0625",
        "leaf_weight=0 0", "leaf_count=0 0",
        "internal_value=0", "internal_weight=0", "internal_count=0",
        "cat_boundaries=0 1",
        "cat_threshold=0",
        "is_linear=0", "shrinkage=1", "",
        "end of trees", "", "pandas_categorical:null", "",
    ])
    back = TreeEnsemble.from_lightgbm_text(txt)
    assert back.cat_features is not None and 1 in set(back.cat_features)
    # 4-bit chain under a depth-1 subtree: expanded depth 4+1 = 5
    assert back.max_depth == 5
    rng = np.random.default_rng(7)
    X = np.stack([
        rng.random(400).astype(np.float32),
        rng.integers(0, 40, size=400).astype(np.float32),
    ], axis=1)
    X[::11, 1] = np.nan            # NaN in the categorical column
    X[::13, 0] = np.nan            # NaN in the numeric column
    want = _lgbm_oracle_raw(txt, X)
    got = back.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    # The empty-bitset default-left tree: real rows all went right
    # (0.0625), NaN-in-f1 rows exited LEFT into the 100 leaf.
    nan_f1 = np.isnan(X[:, 1])
    assert got[~nan_f1].max() < 50
    assert (got[nan_f1] > 50).all()

    # Gain-sum importances count each original split once, not once per
    # chain link or subtree copy: f0 keeps gain 4 (one copy counted),
    # f1 keeps 9 + 2 + 1 (first links, incl. the sentinel link standing
    # in for the empty-bitset NaN split) -> normalized [4/16, 12/16].
    imp = back.feature_importances("gain")
    np.testing.assert_allclose(imp, [4 / 16, 12 / 16], rtol=1e-6)


def test_multibit_roundtrip_of_doctored_export():
    """A doctored two-extra-bit bitset on a REAL exported model parses
    (no longer rejected) and scores per LightGBM semantics."""
    res, X, cat = _train_categorical()
    txt = res.ensemble.to_lightgbm_text()
    lines = txt.splitlines()
    for i, ln in enumerate(lines):
        if ln.startswith("cat_threshold="):
            words = ln.split("=")[1].split()
            words[0] = str(int(words[0]) | (1 << 31) | 1)
            lines[i] = "cat_threshold=" + " ".join(words)
            break
    doctored = "\n".join(lines)
    back = TreeEnsemble.from_lightgbm_text(doctored)
    want = _lgbm_oracle_raw(doctored, X)
    got = back.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_malformed_cat_node_rejected():
    """Categorical decision_type with num_cat=0 (foreign/corrupt input)
    fails with a precise ValueError, not a NoneType subscript."""
    txt = "\n".join([
        "tree", "version=v3", "num_class=1", "num_tree_per_iteration=1",
        "label_index=0", "max_feature_idx=0",
        "objective=binary sigmoid:1", "feature_names=Column_0",
        "feature_infos=[-inf:inf]", "",
        "Tree=0", "num_leaves=2", "num_cat=0",
        "split_feature=0", "split_gain=1", "threshold=0",
        "decision_type=1", "left_child=-1", "right_child=-2",
        "leaf_value=1 0", "leaf_weight=0 0", "leaf_count=0 0",
        "internal_value=0", "internal_weight=0", "internal_count=0",
        "is_linear=0", "shrinkage=1", "",
        "end of trees", "", "pandas_categorical:null", "",
    ])
    with pytest.raises(ValueError, match="num_cat=0"):
        TreeEnsemble.from_lightgbm_text(txt)


def test_cat_missing_export_warns():
    """Exporting cat splits together with learned NaN directions warns
    about the cross-tool NaN-routing difference (round-4 advisor)."""
    from ddt_tpu.models.tree import empty_ensemble

    ens = empty_ensemble(1, 2, 3, 0.1, 0.0, "logloss",
                         missing_bin=True, n_bins=31, cat_features=(1,))
    ens.feature[0, 0] = 1
    ens.threshold_bin[0, 0] = 2
    ens.threshold_raw[0, 0] = 2.0
    ens.is_leaf[0, 1:3] = True
    ens.has_raw_thresholds = True
    with pytest.warns(UserWarning, match="NaN"):
        ens.to_lightgbm_text()


def test_categorical_bitset_validation():
    """Mixed cat/ordinal feature use is unrepresentable and must fail
    loudly, not silently misroute."""
    res, X, cat = _train_categorical()
    txt = res.ensemble.to_lightgbm_text()

    # Doctor a cat node's feature to collide with an ordinal feature.
    lines = txt.splitlines()
    for i, ln in enumerate(lines):
        if ln.startswith("decision_type="):
            dts = [int(v) for v in ln.split("=")[1].split()]
            if not any(d & 1 for d in dts):
                continue
            cat_pos = next(j for j, d in enumerate(dts) if d & 1)
            ord_pos = next((j for j, d in enumerate(dts) if not d & 1), None)
            if ord_pos is None:
                continue
            sf_line = i - 3          # split_feature precedes decision_type
            assert lines[sf_line].startswith("split_feature=")
            sfs = lines[sf_line].split("=")[1].split()
            sfs[cat_pos] = sfs[ord_pos]
            lines[sf_line] = "split_feature=" + " ".join(sfs)
            break
    with pytest.raises(ValueError, match="both categorical and numerical"):
        TreeEnsemble.from_lightgbm_text("\n".join(lines))


def test_header_fields_and_leaf_encoding():
    res, _ = _train()
    txt = res.ensemble.to_lightgbm_text(
        feature_names=[f"f{i}" for i in range(8)])
    lines = dict(
        ln.partition("=")[::2] for ln in txt.splitlines() if "=" in ln)
    assert lines["num_class"] == "1"
    assert lines["objective"] == "binary sigmoid:1"
    assert lines["max_feature_idx"] == "7"
    assert "feature_names=f0 f1 f2 f3 f4 f5 f6 f7" in txt
    # leaf references are negative (~leaf_idx), internals non-negative
    lc = [int(v) for v in lines["left_child"].split()]
    rc = [int(v) for v in lines["right_child"].split()]
    n_leaves = int(lines["num_leaves"])
    refs = lc + rc
    assert sum(1 for r in refs if r < 0) == n_leaves
    assert all(-n_leaves <= r < n_leaves - 1 for r in refs)
