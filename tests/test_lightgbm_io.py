"""LightGBM model.txt interop round-trips (round-2 verdict item 8):
export -> re-parse with the repo's own loader -> identical predictions.
LightGBM itself is not installed here; the format is validated
structurally and semantically via the independent parser."""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.data import datasets
from ddt_tpu.models.tree import TreeEnsemble


def _train(loss="logloss", **kw):
    if loss == "softmax":
        X, y = datasets.synthetic_multiclass(1500, n_features=8,
                                             n_classes=3, seed=11)
        kw.setdefault("n_classes", 3)
    elif loss == "mse":
        X, y = datasets.synthetic_regression(1500, seed=11)
    else:
        X, y = datasets.synthetic_binary(1500, n_features=8, seed=11)
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31, loss=loss,
                    backend="cpu", log_every=10**9, **kw)
    return res, X


@pytest.mark.parametrize("loss", ["logloss", "mse", "softmax"])
def test_roundtrip_predictions(loss):
    res, X = _train(loss)
    txt = res.ensemble.to_lightgbm_text()
    assert txt.startswith("tree\nversion=v3")
    assert "end of trees" in txt
    back = TreeEnsemble.from_lightgbm_text(txt)
    assert back.loss == loss
    assert back.n_features == res.ensemble.n_features
    want = res.ensemble.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    # base-score fold + shrinkage pre-multiplication reorder float adds:
    # ULP-level, not structural.
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_roundtrip_missing_default_directions():
    """NaN routing survives: decision_type carries the NaN missing type
    and the learned default-left bit."""
    X, y = datasets.synthetic_binary(2000, n_features=6, seed=3)
    X = X.copy()
    X[::7, 2] = np.nan
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31,
                    backend="cpu", missing_policy="learn",
                    log_every=10**9)
    txt = res.ensemble.to_lightgbm_text()
    back = TreeEnsemble.from_lightgbm_text(txt)
    want = res.ensemble.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert back.default_left is not None


def test_export_validates():
    from ddt_tpu.models.tree import empty_ensemble

    bare = empty_ensemble(2, 3, 5, 0.1, 0.0, "logloss")
    with pytest.raises(ValueError, match="raw-value thresholds"):
        bare.to_lightgbm_text()


def _train_categorical(seed=0, **kw):
    """A model with real one-vs-rest cat splits (criteo-shaped data)."""
    from ddt_tpu.data.categorical import fit_categorical_encoder
    from ddt_tpu.data.datasets import synthetic_ctr

    Xn, Xc, y = synthetic_ctr(4000, seed=seed)
    enc = fit_categorical_encoder(Xc, n_bins=63)
    X = np.concatenate([Xn, enc.transform(Xc).astype(np.float32)], axis=1)
    cat = tuple(range(Xn.shape[1], X.shape[1]))
    res = api.train(X, y, n_trees=5, max_depth=4, n_bins=63,
                    backend="cpu", cat_features=cat, log_every=10**9, **kw)
    return res, X, cat


def test_roundtrip_categorical():
    """Cat one-vs-rest splits export as LightGBM categorical nodes
    (single-bit cat_threshold bitsets) and parse back to identical
    predictions — the Criteo-config model family is no longer excluded
    from the tree-diff validation path (round-3 verdict item 6)."""
    res, X, cat = _train_categorical()
    ens = res.ensemble
    assert ens.has_cat_splits
    txt = ens.to_lightgbm_text()
    blocks = [b for b in txt.split("Tree=") if "num_cat" in b]
    n_cat_total = sum(
        int(b.split("num_cat=")[1].splitlines()[0]) for b in blocks)
    assert n_cat_total > 0, "model grew no cat splits; test data too easy"
    assert "cat_boundaries=" in txt and "cat_threshold=" in txt

    back = TreeEnsemble.from_lightgbm_text(txt)
    assert back.cat_features is not None
    assert set(back.cat_features) <= set(cat)
    want = ens.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_categorical_bitset_validation():
    """Multi-bit bitsets (real LightGBM cat splits) and mixed cat/ordinal
    feature use are unrepresentable and must fail loudly, not silently
    misroute."""
    res, X, cat = _train_categorical()
    txt = res.ensemble.to_lightgbm_text()

    # Doctor one bitset to carry two categories.
    lines = txt.splitlines()
    for i, ln in enumerate(lines):
        if ln.startswith("cat_threshold="):
            words = ln.split("=")[1].split()
            words[0] = str(int(words[0]) | (1 << 31) | 1)
            lines[i] = "cat_threshold=" + " ".join(words)
            break
    with pytest.raises(ValueError, match="set bits"):
        TreeEnsemble.from_lightgbm_text("\n".join(lines))

    # Doctor a cat node's feature to collide with an ordinal feature.
    lines = txt.splitlines()
    for i, ln in enumerate(lines):
        if ln.startswith("decision_type="):
            dts = [int(v) for v in ln.split("=")[1].split()]
            if not any(d & 1 for d in dts):
                continue
            cat_pos = next(j for j, d in enumerate(dts) if d & 1)
            ord_pos = next((j for j, d in enumerate(dts) if not d & 1), None)
            if ord_pos is None:
                continue
            sf_line = i - 3          # split_feature precedes decision_type
            assert lines[sf_line].startswith("split_feature=")
            sfs = lines[sf_line].split("=")[1].split()
            sfs[cat_pos] = sfs[ord_pos]
            lines[sf_line] = "split_feature=" + " ".join(sfs)
            break
    with pytest.raises(ValueError, match="both categorical and numerical"):
        TreeEnsemble.from_lightgbm_text("\n".join(lines))


def test_header_fields_and_leaf_encoding():
    res, _ = _train()
    txt = res.ensemble.to_lightgbm_text(
        feature_names=[f"f{i}" for i in range(8)])
    lines = dict(
        ln.partition("=")[::2] for ln in txt.splitlines() if "=" in ln)
    assert lines["num_class"] == "1"
    assert lines["objective"] == "binary sigmoid:1"
    assert lines["max_feature_idx"] == "7"
    assert "feature_names=f0 f1 f2 f3 f4 f5 f6 f7" in txt
    # leaf references are negative (~leaf_idx), internals non-negative
    lc = [int(v) for v in lines["left_child"].split()]
    rc = [int(v) for v in lines["right_child"].split()]
    n_leaves = int(lines["num_leaves"])
    refs = lc + rc
    assert sum(1 for r in refs if r < 0) == n_leaves
    assert all(-n_leaves <= r < n_leaves - 1 for r in refs)
