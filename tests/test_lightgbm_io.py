"""LightGBM model.txt interop round-trips (round-2 verdict item 8):
export -> re-parse with the repo's own loader -> identical predictions.
LightGBM itself is not installed here; the format is validated
structurally and semantically via the independent parser."""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.data import datasets
from ddt_tpu.models.tree import TreeEnsemble


def _train(loss="logloss", **kw):
    if loss == "softmax":
        X, y = datasets.synthetic_multiclass(1500, n_features=8,
                                             n_classes=3, seed=11)
        kw.setdefault("n_classes", 3)
    elif loss == "mse":
        X, y = datasets.synthetic_regression(1500, seed=11)
    else:
        X, y = datasets.synthetic_binary(1500, n_features=8, seed=11)
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31, loss=loss,
                    backend="cpu", log_every=10**9, **kw)
    return res, X


@pytest.mark.parametrize("loss", ["logloss", "mse", "softmax"])
def test_roundtrip_predictions(loss):
    res, X = _train(loss)
    txt = res.ensemble.to_lightgbm_text()
    assert txt.startswith("tree\nversion=v3")
    assert "end of trees" in txt
    back = TreeEnsemble.from_lightgbm_text(txt)
    assert back.loss == loss
    assert back.n_features == res.ensemble.n_features
    want = res.ensemble.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    # base-score fold + shrinkage pre-multiplication reorder float adds:
    # ULP-level, not structural.
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_roundtrip_missing_default_directions():
    """NaN routing survives: decision_type carries the NaN missing type
    and the learned default-left bit."""
    X, y = datasets.synthetic_binary(2000, n_features=6, seed=3)
    X = X.copy()
    X[::7, 2] = np.nan
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31,
                    backend="cpu", missing_policy="learn",
                    log_every=10**9)
    txt = res.ensemble.to_lightgbm_text()
    back = TreeEnsemble.from_lightgbm_text(txt)
    want = res.ensemble.predict_raw(X, binned=False)
    got = back.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert back.default_left is not None


def test_export_validates():
    from ddt_tpu.models.tree import empty_ensemble

    bare = empty_ensemble(2, 3, 5, 0.1, 0.0, "logloss")
    with pytest.raises(ValueError, match="raw-value thresholds"):
        bare.to_lightgbm_text()

    res, _ = _train()
    ens = res.ensemble
    ens.cat_features = np.array([1], np.int32)
    with pytest.raises(ValueError, match="categorical"):
        ens.to_lightgbm_text()


def test_header_fields_and_leaf_encoding():
    res, _ = _train()
    txt = res.ensemble.to_lightgbm_text(
        feature_names=[f"f{i}" for i in range(8)])
    lines = dict(
        ln.partition("=")[::2] for ln in txt.splitlines() if "=" in ln)
    assert lines["num_class"] == "1"
    assert lines["objective"] == "binary sigmoid:1"
    assert lines["max_feature_idx"] == "7"
    assert "feature_names=f0 f1 f2 f3 f4 f5 f6 f7" in txt
    # leaf references are negative (~leaf_idx), internals non-negative
    lc = [int(v) for v in lines["left_child"].split()]
    rc = [int(v) for v in lines["right_child"].split()]
    n_leaves = int(lines["num_leaves"])
    refs = lc + rc
    assert sum(1 for r in refs if r < 0) == n_leaves
    assert all(-n_leaves <= r < n_leaves - 1 for r in refs)
