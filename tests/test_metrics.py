"""Metrics + eval_set/early-stopping tests (SURVEY.md §4 "Algorithm-level"
and §5 observability). sklearn is the external oracle for metric values and
for whole-trainer quality (HistGradientBoosting — the same histogram-GBDT
family as the reference)."""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import synthetic_binary, synthetic_multiclass
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.utils import metrics


def test_auc_matches_sklearn():
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, size=500)
    # include ties: coarse-quantized scores
    s = np.round(rng.standard_normal(500) + y, 1)
    assert metrics.auc(y, s) == pytest.approx(roc_auc_score(y, s), abs=1e-12)


def test_logloss_matches_sklearn_binary_and_multi():
    from sklearn.metrics import log_loss

    rng = np.random.default_rng(1)
    y = rng.integers(0, 2, size=300)
    s = rng.standard_normal(300)
    p = 1 / (1 + np.exp(-s))
    assert metrics.logloss(y, s) == pytest.approx(
        log_loss(y, p), rel=1e-6)

    y3 = rng.integers(0, 3, size=300)
    s3 = rng.standard_normal((300, 3))
    e = np.exp(s3 - s3.max(1, keepdims=True))
    p3 = e / e.sum(1, keepdims=True)
    assert metrics.logloss(y3, s3) == pytest.approx(
        log_loss(y3, p3, labels=[0, 1, 2]), rel=1e-6)


def test_accuracy_rmse():
    y = np.array([0, 1, 1, 0])
    s = np.array([-1.0, 2.0, -0.5, -2.0])
    assert metrics.accuracy(y, s) == pytest.approx(0.75)
    assert metrics.rmse(np.zeros(2), np.array([3.0, 4.0])) == pytest.approx(
        np.sqrt(12.5))


def test_default_metric_known_losses_and_error_contract():
    assert metrics.default_metric("logloss") == "logloss"
    assert metrics.default_metric("softmax") == "logloss"
    assert metrics.default_metric("mse") == "rmse"
    # Unknown losses raise ValueError naming the known ones — the same
    # contract as evaluate() (was a bare KeyError before the telemetry PR).
    with pytest.raises(ValueError, match="no default metric.*huber"):
        metrics.default_metric("huber")
    with pytest.raises(ValueError, match="logloss"):
        metrics.default_metric("huber")


def _split(X, y, frac=0.2, seed=0):
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(y))
    k = int(len(y) * frac)
    va, tr = idx[:k], idx[k:]
    return X[tr], y[tr], X[va], y[va]


def test_eval_set_history_and_final_score():
    X, y = synthetic_binary(4000, n_features=10, seed=3)
    Xt, yt, Xv, yv = _split(X, y)
    res = api.train(
        Xt, yt, n_trees=20, max_depth=4, n_bins=63, backend="cpu",
        eval_set=(Xv, yv), eval_metric="auc", log_every=5,
    )
    aucs = [r["valid_auc"] for r in res.history if "valid_auc" in r]
    assert len(aucs) >= 3
    # trained-model AUC must beat chance comfortably and match the last
    # recorded incremental value (incremental scoring == full rescoring)
    raw = res.ensemble.predict_raw(res.mapper.transform(Xv), binned=True)
    assert metrics.auc(yv, raw) == pytest.approx(aucs[-1], abs=1e-6)
    assert aucs[-1] > 0.8
    assert res.best_round is not None


def test_early_stopping_truncates_to_best_round():
    # tiny noisy data + many trees => validation metric degrades, stop early
    rng = np.random.default_rng(5)
    X = rng.standard_normal((300, 5)).astype(np.float32)
    y = (rng.random(300) < 0.5).astype(np.int64)   # pure noise labels
    Xt, yt, Xv, yv = _split(X, y, frac=0.3, seed=1)
    res = api.train(
        Xt, yt, n_trees=100, max_depth=3, n_bins=31, backend="cpu",
        eval_set=(Xv, yv), eval_metric="logloss", early_stopping_rounds=5,
        log_every=10 ** 9,
    )
    assert res.ensemble.n_trees < 100
    assert res.ensemble.n_trees == res.best_round + 1


def test_early_stopping_multiclass_counts_trees_per_class():
    X, y = synthetic_multiclass(1200, n_features=8, n_classes=3, seed=7)
    Xt, yt, Xv, yv = _split(X, y)
    res = api.train(
        Xt, yt, n_trees=30, max_depth=3, n_bins=31, backend="cpu",
        loss="softmax", n_classes=3,
        eval_set=(Xv, yv), early_stopping_rounds=4, log_every=10 ** 9,
    )
    assert res.ensemble.n_trees % 3 == 0
    raw = res.ensemble.predict_raw(res.mapper.transform(Xv), binned=True)
    assert raw.shape == (len(yv), 3)
    assert metrics.accuracy(yv, raw) > 0.5


def test_quality_parity_vs_sklearn_hist_gbdt():
    """Whole-trainer check vs sklearn's HistGradientBoostingClassifier with
    matched capacity (same family: histogram GBDT, 255 bins)."""
    from sklearn.ensemble import HistGradientBoostingClassifier
    from sklearn.metrics import roc_auc_score

    X, y = synthetic_binary(6000, n_features=12, seed=11)
    Xt, yt, Xv, yv = _split(X, y)

    res = api.train(
        Xt, yt, n_trees=60, max_depth=6, n_bins=255, learning_rate=0.2,
        backend="cpu", log_every=10 ** 9,
    )
    ours = metrics.auc(
        yv, res.ensemble.predict_raw(res.mapper.transform(Xv), binned=True))

    sk = HistGradientBoostingClassifier(
        max_iter=60, max_depth=6, max_bins=255, learning_rate=0.2,
        early_stopping=False, min_samples_leaf=1, l2_regularization=1.0,
    ).fit(Xt, yt)
    theirs = roc_auc_score(yv, sk.decision_function(Xv))

    assert ours > 0.85
    assert ours >= theirs - 0.02   # within 2 AUC points of sklearn


def test_early_stop_with_checkpoint_dir_resumes_cleanly(tmp_path):
    """Early stop must write a cursor matching the truncated ensemble, so a
    follow-up train with higher n_trees resumes without shape errors."""
    rng = np.random.default_rng(9)
    X = rng.standard_normal((300, 5)).astype(np.float32)
    y = (rng.random(300) < 0.5).astype(np.int64)   # noise => early stop
    Xt, yt, Xv, yv = _split(X, y, frac=0.3, seed=2)
    d = str(tmp_path / "ck")
    res = api.train(
        Xt, yt, n_trees=50, max_depth=3, n_bins=31, backend="cpu",
        eval_set=(Xv, yv), early_stopping_rounds=3, log_every=10 ** 9,
        checkpoint_dir=d, checkpoint_every=10 ** 9, seed=4,
    )
    kept = res.ensemble.n_trees
    assert kept < 50
    # resume-and-continue (no early stopping this time) picks up at `kept`
    res2 = api.train(
        Xt, yt, n_trees=kept + 2, max_depth=3, n_bins=31, backend="cpu",
        log_every=10 ** 9, checkpoint_dir=d, seed=4,
    )
    assert res2.ensemble.n_trees == kept + 2
    np.testing.assert_array_equal(
        res2.ensemble.feature[:kept], res.ensemble.feature)


def test_eval_set_binned_path():
    X, y = synthetic_binary(2000, n_features=6, seed=2)
    Xb, _ = quantize(X, n_bins=31)
    Xt, yt, Xv, yv = Xb[:1600], y[:1600], Xb[1600:], y[1600:]
    cfg = TrainConfig(n_trees=10, max_depth=3, n_bins=31, backend="cpu")
    res = api.train(Xt, yt, cfg, binned=True, eval_set=(Xv, yv),
                    log_every=10 ** 9)
    assert res.best_score is not None


def test_driver_profile_phase_breakdown():
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data.datasets import synthetic_binary
    from ddt_tpu.data.quantizer import quantize
    from ddt_tpu.driver import Driver

    X, y = synthetic_binary(2000, n_features=6, seed=0)
    Xb, _ = quantize(X, n_bins=31)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=31, backend="cpu")
    d = Driver(CPUDevice(cfg), cfg, log_every=10 ** 9, profile=True)
    d.fit(Xb, y)
    rep = {r["phase"]: r for r in d.timer.report()}
    assert {"grad", "grow", "apply_delta", "fetch_tree"} <= set(rep)
    assert all(r["calls"] == 3 for r in rep.values())


# ---------------------------------------------------------------------- #
# device-side eval_set scoring (round-1 verdict Weak #5): TPUDevice keeps
# validation predictions resident on device, applies packed tree handles
# there, and computes f32 metric twins on device (auc stays on host).
# ---------------------------------------------------------------------- #

def test_device_metric_twins_match_host():
    import jax.numpy as jnp

    from ddt_tpu.utils.metrics import device_metric

    rng = np.random.default_rng(0)
    y = (rng.random(500) < 0.4).astype(np.float32)
    s = rng.standard_normal(500).astype(np.float32)
    valid = np.ones(600, bool); valid[500:] = False
    sp = np.concatenate([s, rng.standard_normal(100).astype(np.float32)])
    yp = np.concatenate([y, np.ones(100, np.float32)])
    for name in ("logloss", "rmse", "accuracy"):
        want = metrics.evaluate(name, y, s)
        got = float(device_metric(name)(
            jnp.asarray(yp), jnp.asarray(sp), jnp.asarray(valid)))
        np.testing.assert_allclose(got, want, rtol=2e-6, err_msg=name)
    # multiclass twins
    ym = rng.integers(0, 3, 500).astype(np.int32)
    sm = rng.standard_normal((500, 3)).astype(np.float32)
    vm = np.ones(500, bool)
    for name in ("logloss", "accuracy"):
        want = metrics.evaluate(name, ym, sm)
        got = float(device_metric(name)(
            jnp.asarray(ym), jnp.asarray(sm), jnp.asarray(vm)))
        np.testing.assert_allclose(got, want, rtol=2e-6, err_msg=name)
    assert device_metric("auc", n_classes=3) is None   # softmax: host-only


def test_device_auc_parity_adversarial():
    """The binned-rank device auc (round-4 verdict item 3) matches the
    f64 host auc within the documented ~1/DEVICE_AUC_BINS tolerance on
    adversarial score distributions: heavy exact ties, near-constant
    scores (span normalisation must spread them), mixed magnitudes, and
    pad rows. Exact ties bin identically, so tie-heavy cases are EXACT;
    the only error source is distinct scores sharing a bin."""
    import jax.numpy as jnp

    from ddt_tpu.utils.metrics import device_metric

    fn = device_metric("auc")
    rng = np.random.default_rng(5)
    R = 20_000
    y = (rng.random(R) < 0.35).astype(np.float32)
    cases = {
        "normal": rng.standard_normal(R).astype(np.float32),
        # GBDT-shaped: few distinct leaf-sum values -> heavy exact ties
        "quantized": rng.choice(
            np.float32(rng.standard_normal(37)), size=R),
        # near-constant: scores within 1e-5 of each other around 3.0
        "near_constant": np.float32(3.0)
        + np.float32(1e-5) * rng.random(R).astype(np.float32),
        # separated + informative (auc ~0.9)
        "informative": (y * 2.0 + rng.standard_normal(R)).astype(
            np.float32),
        # binary scores only (one bin boundary): everything ties
        "two_valued": rng.choice(np.float32([0.25, -1.5]), size=R),
    }
    for name, s in cases.items():
        want = metrics.auc(y, s)
        # padded: 500 pad rows with wild scores/labels must not count
        sp = np.concatenate([s, np.float32(1e9) * np.ones(500, np.float32)])
        yp = np.concatenate([y, np.ones(500, np.float32)])
        valid = np.zeros(R + 500, bool)
        valid[:R] = True
        got = float(fn(jnp.asarray(yp), jnp.asarray(sp),
                       jnp.asarray(valid)))
        assert abs(got - want) <= 5e-5, (name, got, want)

    # all-equal scores: exactly 0.5 (span-zero branch)
    const = np.full(R, 7.25, np.float32)
    got = float(fn(jnp.asarray(y), jnp.asarray(const),
                   jnp.asarray(np.ones(R, bool))))
    assert got == 0.5
    # single-class validation data: NaN (the Driver's guard raises on it)
    got = float(fn(jnp.asarray(np.ones(R, np.float32)),
                   jnp.asarray(cases["normal"]),
                   jnp.asarray(np.ones(R, bool))))
    assert np.isnan(got)


def test_twinless_metric_gather_fallback_pod_mesh(monkeypatch):
    """eval_round's metric=None branch — fetch a REPLICATED raw-score
    copy for host evaluation — is the generic fallback for metrics
    without a device twin. No shipped metric is twin-less anymore
    (round 5 gave auc one), so this test keeps the branch exercised on
    the multi-host-addressability-sensitive pod mesh by forcing the
    twin registry empty: histories must still match the CPU host-eval
    path."""
    import ddt_tpu.utils.metrics as M

    monkeypatch.setattr(M, "device_metric",
                        lambda name, n_classes=1: None)
    X, y = synthetic_binary(3000, n_features=8, seed=3)
    kw = dict(n_trees=6, max_depth=3, n_bins=31, log_every=1,
              eval_set=(X[2400:], y[2400:]), eval_metric="logloss")
    rt = api.train(X[:2400], y[:2400], backend="tpu",
                   host_partitions=2, n_partitions=2, **kw)
    monkeypatch.undo()
    rc = api.train(X[:2400], y[:2400], backend="cpu", **kw)
    hc = [r["valid_logloss"] for r in rc.history if "valid_logloss" in r]
    ht = [r["valid_logloss"] for r in rt.history if "valid_logloss" in r]
    assert len(ht) == 6
    np.testing.assert_allclose(hc, ht, rtol=2e-5)


def test_softmax_auc_rejected_at_fit():
    """auc is binary; with softmax raw scores the host rank formulation
    crashes deep inside ravel — both trainers fail at the cause
    instead (round 5; previously this crashed far from the API)."""
    from ddt_tpu.streaming import fit_streaming

    X, y = synthetic_multiclass(600, n_features=6, n_classes=3, seed=1)
    with pytest.raises(ValueError, match="binary"):
        api.train(X[:500], y[:500], loss="softmax", n_classes=3,
                  n_trees=2, max_depth=2, n_bins=31, backend="cpu",
                  eval_set=(X[500:], y[500:]), eval_metric="auc",
                  log_every=10**9)
    Xb, _ = quantize(X, n_bins=31)
    cfg = TrainConfig(n_trees=2, max_depth=2, n_bins=31, loss="softmax",
                      n_classes=3, backend="cpu")

    def cf(c):
        return Xb[c * 300:(c + 1) * 300], y[c * 300:(c + 1) * 300]

    with pytest.raises(ValueError, match="binary"):
        fit_streaming(cf, 2, cfg, valid_chunk_fn=cf, n_valid_chunks=1,
                      eval_metric="auc")


def test_fused_auc_early_stopping_matches_granular():
    """auc eval + early stopping now rides the fused dispatch path
    (grow_rounds_eval with the binned-rank device twin, round-4 verdict
    item 3): the fused run must record the same per-round auc series and
    pick the same best_round as the granular device path (profile=True
    forces per-round dispatch; both score with the identical compiled
    twin)."""
    X, y = synthetic_binary(4000, n_features=10, seed=3)
    Xt, yt, Xv, yv = _split(X, y)
    kw = dict(n_trees=30, max_depth=4, n_bins=63, backend="tpu",
              log_every=10**9, eval_set=(Xv, yv), eval_metric="auc",
              early_stopping_rounds=3)
    fused = api.train(Xt, yt, **kw)
    granular = api.train(Xt, yt, profile=True, **kw)
    assert fused.best_round is not None
    assert fused.best_round == granular.best_round
    hf = [r["valid_auc"] for r in fused.history if "valid_auc" in r]
    hg = [r["valid_auc"] for r in granular.history if "valid_auc" in r]
    np.testing.assert_array_equal(hf, hg)
    np.testing.assert_array_equal(fused.ensemble.feature,
                                  granular.ensemble.feature)


def test_device_auc_sharded_matches_single():
    """psum/pmin/pmax-distributed device auc over an 8-way row shard
    equals the single-device evaluation bitwise (same bin histograms,
    same summation)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ddt_tpu.utils.metrics import device_metric

    fn = device_metric("auc")
    rng = np.random.default_rng(11)
    R = 16_384
    y = (rng.random(R) < 0.4).astype(np.float32)
    s = rng.standard_normal(R).astype(np.float32)
    v = np.ones(R, bool)
    single = float(fn(jnp.asarray(y), jnp.asarray(s), jnp.asarray(v)))

    mesh = jax.make_mesh((8,), ("rows",))

    def allreduce(x, op="sum"):
        return {"sum": jax.lax.psum, "min": jax.lax.pmin,
                "max": jax.lax.pmax}[op](x, "rows")

    from ddt_tpu.parallel import mesh as mesh_lib

    sharded_fn = jax.jit(mesh_lib.shard_map(
        lambda y_, s_, v_: fn(y_, s_, v_, allreduce),
        mesh=mesh, in_specs=(P("rows"), P("rows"), P("rows")),
        out_specs=P()))
    sharded = float(sharded_fn(jnp.asarray(y), jnp.asarray(s),
                               jnp.asarray(v)))
    assert sharded == single


def test_device_eval_matches_host_eval_history():
    """TPU (device-resident eval, pipelined tree fetch) and CPU (host
    incremental traversal) must record the same per-round validation
    scores and pick the same best round — for the host-metric path (auc)
    AND a device-metric path (logloss)."""
    X, y = synthetic_binary(4000, n_features=10, seed=3)
    Xt, yt, Xv, yv = _split(X, y)
    for metric in ("auc", "logloss"):
        kw = dict(n_trees=15, max_depth=4, n_bins=63, log_every=5,
                  eval_set=(Xv, yv), eval_metric=metric)
        rc = api.train(Xt, yt, backend="cpu", **kw)
        rt = api.train(Xt, yt, backend="tpu", **kw)
        hc = [r[f"valid_{metric}"] for r in rc.history
              if f"valid_{metric}" in r]
        ht = [r[f"valid_{metric}"] for r in rt.history
              if f"valid_{metric}" in r]
        assert len(ht) >= 3
        np.testing.assert_allclose(hc, ht, rtol=2e-5)
        assert rc.best_round == rt.best_round


def test_device_eval_sharded_matches_single():
    """Row-sharded validation scoring (psum'd device metric) equals the
    single-device path."""
    X, y = synthetic_binary(4000, n_features=10, seed=3)
    Xt, yt, Xv, yv = _split(X, y)
    kw = dict(n_trees=10, max_depth=4, n_bins=63, log_every=2,
              eval_set=(Xv, yv), eval_metric="logloss")
    r1 = api.train(Xt, yt, backend="tpu", **kw)
    r2 = api.train(Xt, yt, backend="tpu", n_partitions=2, **kw)
    h1 = [r["valid_logloss"] for r in r1.history if "valid_logloss" in r]
    h2 = [r["valid_logloss"] for r in r2.history if "valid_logloss" in r]
    np.testing.assert_allclose(h1, h2, rtol=2e-5)


def test_device_eval_early_stopping_multiclass():
    """Early stopping through the device-eval path truncates cleanly with
    the tree-fetch pipeline active (the pending fetch must flush before
    truncation)."""
    X, y = synthetic_multiclass(1500, n_features=8, n_classes=3, seed=7)
    Xt, yt, Xv, yv = _split(X, y)
    res = api.train(
        Xt, yt, backend="tpu", loss="softmax", n_classes=3,
        n_trees=25, max_depth=3, n_bins=31,
        eval_set=(Xv, yv), early_stopping_rounds=4, log_every=10 ** 9,
    )
    assert res.ensemble.n_trees % 3 == 0
    assert res.ensemble.n_trees == (res.best_round + 1) * 3
    # every stored tree is real (the pipeline flushed): no all-zero slots
    assert (res.ensemble.is_leaf.sum(axis=1) > 0).all()


def test_device_eval_missing_values_match_oracle():
    """NaN rows follow learned default directions inside the device eval
    traversal: the recorded score equals rescoring the truncated ensemble
    with the (missing-aware) host oracle."""
    rng = np.random.default_rng(0)
    X, y = synthetic_binary(3000, n_features=8, seed=5)
    X[rng.random(X.shape) < 0.1] = np.nan
    res = api.train(
        X[:2400], y[:2400], backend="tpu", missing_policy="learn",
        n_trees=8, max_depth=4, n_bins=63,
        eval_set=(X[2400:], y[2400:]), eval_metric="logloss", log_every=1,
    )
    last = res.history[-1]
    part = res.ensemble.truncate(last["round"])
    want = metrics.evaluate(
        "logloss", y[2400:],
        part.predict_raw(res.mapper.transform(X[2400:]), binned=True))
    np.testing.assert_allclose(last["valid_logloss"], want, rtol=2e-5)


def test_device_eval_pod_mesh_matches_single():
    """Device eval over a (hosts, rows) pod mesh: the host-metric path
    (auc) resolves a replicated gather — the row-sharded state itself is
    not addressable-fetchable on real multi-host meshes — and the device
    metric psums over both axes."""
    X, y = synthetic_binary(4000, n_features=10, seed=3)
    Xt, yt, Xv, yv = _split(X, y)
    for metric in ("auc", "logloss"):
        kw = dict(n_trees=8, max_depth=4, n_bins=63, log_every=2,
                  eval_set=(Xv, yv), eval_metric=metric)
        r1 = api.train(Xt, yt, backend="tpu", **kw)
        rp = api.train(Xt, yt, backend="tpu", host_partitions=2,
                       n_partitions=2, **kw)
        h1 = [r[f"valid_{metric}"] for r in r1.history
              if f"valid_{metric}" in r]
        hp = [r[f"valid_{metric}"] for r in rp.history
              if f"valid_{metric}" in r]
        np.testing.assert_allclose(h1, hp, rtol=2e-5)


def test_fused_eval_matches_host_and_granular():
    """Without early stopping, eval rides INSIDE the fused scan
    (grow_rounds_eval): histories must equal the host path's, per-round
    records included, on single and sharded meshes and multiclass."""
    X, y = synthetic_binary(4000, n_features=10, seed=3)
    Xt, yt, Xv, yv = _split(X, y)
    kw = dict(n_trees=12, max_depth=4, n_bins=63, log_every=5,
              eval_set=(Xv, yv), eval_metric="logloss")
    rc = api.train(Xt, yt, backend="cpu", **kw)
    rt = api.train(Xt, yt, backend="tpu", **kw)   # fused in-scan eval
    hc = [r["valid_logloss"] for r in rc.history if "valid_logloss" in r]
    ht = [r["valid_logloss"] for r in rt.history if "valid_logloss" in r]
    assert len(ht) == 12                          # recorded every round
    np.testing.assert_allclose(hc, ht, rtol=2e-5)
    assert rc.best_round == rt.best_round
    r2 = api.train(Xt, yt, backend="tpu", n_partitions=2, **kw)
    h2 = [r["valid_logloss"] for r in r2.history if "valid_logloss" in r]
    np.testing.assert_allclose(ht, h2, rtol=2e-5)

    Xm, ym = synthetic_multiclass(1500, n_features=8, n_classes=3, seed=7)
    km = dict(loss="softmax", n_classes=3, n_trees=8, max_depth=3,
              n_bins=31, eval_set=(Xm[1200:], ym[1200:]),
              eval_metric="accuracy", log_every=10**9)
    rm = api.train(Xm[:1200], ym[:1200], backend="tpu", **km)
    rh = api.train(Xm[:1200], ym[:1200], backend="cpu", **km)
    assert rm.best_round == rh.best_round
    np.testing.assert_allclose(rm.best_score, rh.best_score, rtol=1e-6)


def test_fused_early_stopping_matches_granular():
    """Early stopping now rides the fused block path (round-3): the
    stopping rule replays over the in-scan scores vector, so the model,
    best round, and truncation are identical to the granular path — at
    one dispatch per block instead of per round."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data import datasets
    from ddt_tpu.data.quantizer import quantize
    from ddt_tpu.driver import Driver

    X, y = datasets.synthetic_binary(3072, n_features=8, seed=17)
    Xb, _ = quantize(X, n_bins=31, seed=17)
    Xt, yt, Xv, yv = Xb[:2304], y[:2304], Xb[2304:], y[2304:]
    cfg = TrainConfig(n_trees=30, max_depth=4, n_bins=31, backend="tpu",
                      learning_rate=0.9, min_split_gain=1e-3)

    be = get_backend(cfg)
    calls = {"fused": 0}
    orig = be.grow_rounds_eval

    def spy(*a, **k):
        calls["fused"] += 1
        return orig(*a, **k)

    be.grow_rounds_eval = spy
    try:
        drv = Driver(be, cfg, log_every=10**9)
        fused = drv.fit(Xt, yt, eval_set=(Xv, yv), eval_metric="logloss",
                        early_stopping_rounds=3)
    finally:
        be.grow_rounds_eval = orig
    assert calls["fused"] >= 1              # the fused path actually ran
    assert fused.n_trees < 30               # and it stopped early
    fused_best = drv.best_round

    # Granular comparator: CPUDevice has no grow_rounds — same rule,
    # per-round host scoring.
    cfg_c = cfg.replace(backend="cpu")
    drv_c = Driver(get_backend(cfg_c), cfg_c, log_every=10**9)
    gran = drv_c.fit(Xt, yt, eval_set=(Xv, yv), eval_metric="logloss",
                     early_stopping_rounds=3)
    assert gran.n_trees == fused.n_trees
    assert drv_c.best_round == fused_best
    np.testing.assert_array_equal(gran.feature, fused.feature)
    np.testing.assert_array_equal(gran.threshold_bin, fused.threshold_bin)
    np.testing.assert_allclose(gran.leaf_value, fused.leaf_value,
                               rtol=2e-4, atol=2e-5)
