"""Worker process for the 2-process jax.distributed bring-up test.

Run as:  python tests/mp_worker.py <coordinator> <num_processes> \
             <process_id> <devices_per_process> <out.npz> <stream_dir> \
             [host_partitions]
(host_partitions defaults to 2; the single-process comparator passes it
explicitly so its mesh shape matches the multi-process run's.)

num_processes == 1 skips initialize_multihost (the single-process
comparator: same mesh shape, same program, one controller). Each process
trains the identical small config over a (hosts=nproc*? , rows) pod mesh
built from the GLOBAL device list and saves its fetched ensemble — the
parent test asserts all outputs are bit-identical (SURVEY.md §5
"Distributed communication backend": jax.distributed.initialize is the
v5e-64 pod bring-up; this exercises the exact entry path with local CPU
processes, coordinator bootstrap and gloo collectives included).

NOT imported by pytest (no test_ prefix); a standalone entry so the JAX
platform/device-count environment can be set before first device use.
"""

import os
import sys


def main() -> int:
    coord, nproc, pid, dev_per_proc, out, stream_dir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
        sys.argv[5], sys.argv[6],
    )
    host_partitions = int(sys.argv[7]) if len(sys.argv) > 7 else 2
    # sitecustomize may have imported jax already with another platform
    # bound; the config.update below overrides it. XLA_FLAGS is read when
    # the CPU client is instantiated, which is AFTER this line.
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={dev_per_proc}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ddt_tpu.parallel.mesh import initialize_multihost

    if nproc > 1:
        initialize_multihost(coordinator_address=coord, num_processes=nproc,
                             process_id=pid)
        # Idempotence: a repeat call with identical args must be a no-op
        # (preemptible-restart loops re-run the whole entry point) ...
        initialize_multihost(coordinator_address=coord, num_processes=nproc,
                             process_id=pid)
        # ... and different args must be LOUD, not silently ignored.
        try:
            initialize_multihost(coordinator_address=coord,
                                 num_processes=nproc + 1, process_id=pid)
        except RuntimeError:
            pass
        else:
            raise AssertionError(
                "re-init with different args should have raised")
        assert jax.process_count() == nproc, jax.process_count()
        assert jax.process_index() == pid, jax.process_index()
    n_global = nproc * dev_per_proc
    assert len(jax.devices()) == n_global, jax.devices()
    assert len(jax.local_devices()) == dev_per_proc

    import numpy as np

    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data import datasets
    from ddt_tpu.data.quantizer import quantize
    from ddt_tpu.driver import Driver

    # Same deterministic data in every process (the multi-controller SPMD
    # convention: every host runs the identical program on the identical
    # host inputs; shards are cut by the sharding's index map).
    X, y = datasets.synthetic_binary(2048, n_features=10, seed=31)
    Xb, _ = quantize(X, n_bins=31, seed=31)
    cfg = TrainConfig(
        n_trees=3, max_depth=3, n_bins=31, backend="tpu",
        host_partitions=host_partitions,
        n_partitions=n_global // host_partitions,
    )
    be = get_backend(cfg)
    assert be.mesh.devices.size == n_global
    ens = Driver(be, cfg, log_every=10**9).fit(Xb, y)

    # Eval-set training on the pod mesh. Binary auc rides the fused path
    # through the binned-rank device twin since round 5 (one psum'd
    # scalar per round — no row-sized fetch); eval_round's
    # replicated-gather branch remains only as the backend-surface
    # fallback for metrics without a device twin (none of the shipped
    # valid metric/loss combinations hits it anymore).
    k = 512
    ens2 = Driver(be, cfg, log_every=10**9).fit(
        Xb[k:], y[k:], eval_set=(Xb[:k], y[:k]), eval_metric="auc")

    # Streamed training over on-disk shards on the SAME multi-process
    # mesh (round-3 verdict item 4): fit_streaming's device path does
    # per-chunk jax.device_put placement every (chunk, level) step —
    # exactly where process-local addressability bugs live. Every process
    # writes identical shards to its own dir (multi-controller SPMD: same
    # host inputs everywhere), then streams them.
    from ddt_tpu.data import chunks as chunks_mod
    from ddt_tpu.streaming import fit_streaming

    chunks_mod.shard_arrays(Xb, y, stream_dir, n_chunks=4)
    src = chunks_mod.directory_chunks(stream_dir)
    assert src.binned
    # BAGGED streaming (round 5): the counter-based keep bits derive
    # from global row ids computed per (process, shard) via axis_index —
    # cross-process identity of the masks is exactly what this layer
    # can break and the virtual mesh cannot witness.
    cfg_bag = cfg.replace(subsample=0.8, seed=7)
    ens3 = fit_streaming(src, src.n_chunks, cfg_bag,
                         backend=get_backend(cfg_bag))

    np.savez(
        out,
        feature=ens.feature, threshold_bin=ens.threshold_bin,
        is_leaf=ens.is_leaf, leaf_value=ens.leaf_value,
        g_feature=ens2.feature, g_threshold_bin=ens2.threshold_bin,
        g_is_leaf=ens2.is_leaf, g_leaf_value=ens2.leaf_value,
        s_feature=ens3.feature, s_threshold_bin=ens3.threshold_bin,
        s_is_leaf=ens3.is_leaf, s_leaf_value=ens3.leaf_value,
        process_index=np.int64(jax.process_index()),
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
