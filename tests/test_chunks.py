"""File-backed out-of-core chunk sources (round-2 verdict item 4).

data/chunks.py: npz shard directories behind the streaming ChunkFn
protocol, the shard writers, the uint8 binned cache — and the CLI's
--stream-dir path, which must train bit-identically to the in-memory
--stream-chunks path on the same chunk boundaries.
"""

import json

import numpy as np
import pytest

from ddt_tpu.cli import main
from ddt_tpu.data import chunks as chunks_mod
from ddt_tpu.data import datasets


def _run(capsys, argv):
    rc = main(argv)
    assert rc == 0
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_shard_roundtrip(tmp_path):
    X, y = datasets.synthetic_binary(1003, n_features=7, seed=3)
    d = str(tmp_path / "shards")
    paths = chunks_mod.shard_arrays(X, y, d, n_chunks=4)
    assert len(paths) == 4
    src = chunks_mod.directory_chunks(d)
    assert src.n_chunks == 4
    assert src.n_features == 7
    assert not src.binned
    Xr = np.concatenate([src(c)[0] for c in range(4)])
    yr = np.concatenate([src(c)[1] for c in range(4)])
    np.testing.assert_array_equal(X, Xr)         # every row, in order
    np.testing.assert_array_equal(y, yr)
    np.testing.assert_array_equal(src.labels(2), src(2)[1])


def test_shard_arrays_validates(tmp_path):
    X, y = datasets.synthetic_binary(10, n_features=5, seed=1)
    with pytest.raises(ValueError, match="exactly one"):
        chunks_mod.shard_arrays(X, y, str(tmp_path), n_chunks=2,
                                chunk_rows=5)
    with pytest.raises(ValueError, match="exceeds"):
        chunks_mod.shard_arrays(X, y, str(tmp_path), n_chunks=11)
    with pytest.raises(ValueError, match="no chunk_"):
        chunks_mod.directory_chunks(str(tmp_path / "empty"))


def test_binned_cache_clears_stale_shards(tmp_path):
    """Re-using a cache dir for a run with fewer chunks must not leave
    the prior run's extra shards behind (the returned source would
    report the stale count and serve the old run's data)."""
    from ddt_tpu.data.quantizer import fit_bin_mapper

    X, y = datasets.synthetic_binary(400, n_features=6, seed=7)
    mapper = fit_bin_mapper(X, n_bins=15)
    cache = str(tmp_path / "cache")

    def raw4(c):
        return X[c * 100:(c + 1) * 100], y[c * 100:(c + 1) * 100]

    src = chunks_mod.write_binned_cache(raw4, 4, mapper, cache)
    assert src.n_chunks == 4

    def raw2(c):
        return X[c * 200:(c + 1) * 200], y[c * 200:(c + 1) * 200]

    src = chunks_mod.write_binned_cache(raw2, 2, mapper, cache)
    assert src.n_chunks == 2
    assert sum(len(src.labels(c)) for c in range(2)) == 400

    # In-place re-bin: the raw source reads from cache_dir itself; the
    # purge must not delete shards before they are read.
    raw_dir = str(tmp_path / "raw")
    chunks_mod.shard_arrays(X, y, raw_dir, n_chunks=3)
    raw_src = chunks_mod.directory_chunks(raw_dir)
    src = chunks_mod.write_binned_cache(raw_src, 3, mapper, raw_dir)
    assert src.n_chunks == 3
    assert src.binned
    assert sum(len(src.labels(c)) for c in range(3)) == 400

    # shard_arrays over a reused out_dir purges stale indices too, but
    # leaves non-canonical names that merely match the glob alone.
    foreign = tmp_path / "raw" / "chunk_backup.npz"
    np.savez(foreign, junk=np.zeros(1))
    chunks_mod.shard_arrays(X, y, raw_dir, n_chunks=2)
    assert foreign.exists()
    assert not (tmp_path / "raw" / "chunk_00002.npz").exists()
    # ...and the reader shares the purge's definition of a chunk: the
    # foreign file is not served as a shard.
    assert chunks_mod.directory_chunks(raw_dir).n_chunks == 2


def test_shard_file_chunk_rows(tmp_path):
    X, y = datasets.synthetic_binary(900, n_features=5, seed=4)
    src_npz = str(tmp_path / "data.npz")
    np.savez(src_npz, X=X, y=y)
    d = str(tmp_path / "shards")
    paths = chunks_mod.shard_file(src_npz, d, chunk_rows=400)
    assert len(paths) == 3        # ceil(900/400)
    src = chunks_mod.directory_chunks(d)
    assert sum(len(src.labels(c)) for c in range(3)) == 900


def test_cli_stream_dir_matches_stream_chunks(tmp_path, capsys):
    """--stream-dir (O(chunk) disk path) == --stream-chunks (loaded
    dataset) when the shard boundaries match: same reservoir mapper fit,
    same chunk histograms, bit-identical trees."""
    from ddt_tpu.models.tree import TreeEnsemble

    X, y = datasets.synthetic_binary(3000, n_features=8, seed=0)
    d = str(tmp_path / "shards")
    # linspace bounds — identical to the CLI's in-memory chunk cut
    chunks_mod.shard_arrays(X, y, d, n_chunks=3)
    src_npz = str(tmp_path / "data.npz")
    np.savez(src_npz, X=X, y=y)

    m_mem = str(tmp_path / "mem.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", f"--data={src_npz}", "--trees=3",
        "--depth=3", "--bins=31", "--stream-chunks=3", f"--out={m_mem}",
    ])
    m_dir = str(tmp_path / "dir.npz")
    rec2 = _run(capsys, [
        "train", "--backend=cpu", "--trees=3", "--depth=3", "--bins=31",
        f"--stream-dir={d}", f"--out={m_dir}",
    ])
    assert rec2["rows"] == 3000 and rec2["streamed_chunks"] == 3
    e1 = TreeEnsemble.load(m_mem)
    e2 = TreeEnsemble.load(m_dir)
    np.testing.assert_array_equal(e1.feature, e2.feature)
    np.testing.assert_array_equal(e1.threshold_bin, e2.threshold_bin)
    np.testing.assert_array_equal(e1.leaf_value, e2.leaf_value)
    assert rec["rows"] == 3000


def test_cli_stream_dir_validation_and_cache_modes(tmp_path, capsys):
    """--stream-dir + --valid-frac holds out shards; explicit
    --stream-cache-dir persists the uint8 cache; '' disables caching and
    trains identically (re-binning reads)."""
    from ddt_tpu.models.tree import TreeEnsemble

    X, y = datasets.synthetic_binary(3000, n_features=8, seed=2)
    d = str(tmp_path / "shards")
    chunks_mod.shard_arrays(X, y, d, n_chunks=4)

    cache = str(tmp_path / "cache")
    m1 = str(tmp_path / "m1.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--trees=6", "--depth=3", "--bins=31",
        f"--stream-dir={d}", "--valid-frac=0.25", "--metric=auc",
        "--early-stop=4", f"--stream-cache-dir={cache}", f"--out={m1}",
    ])
    assert rec["streamed_chunks"] == 3          # 1 of 4 shards held out
    assert rec["rows"] == 2250
    assert "best_score" in rec
    cached = chunks_mod.directory_chunks(str(tmp_path / "cache" / "train"))
    assert cached.binned and cached.n_chunks == 3

    m2 = str(tmp_path / "m2.npz")
    _run(capsys, [
        "train", "--backend=cpu", "--trees=6", "--depth=3", "--bins=31",
        f"--stream-dir={d}", "--valid-frac=0.25", "--metric=auc",
        "--early-stop=4", "--stream-cache-dir=", f"--out={m2}",
    ])
    e1 = TreeEnsemble.load(m1)
    e2 = TreeEnsemble.load(m2)
    np.testing.assert_array_equal(e1.feature, e2.feature)
    np.testing.assert_array_equal(e1.leaf_value, e2.leaf_value)


def test_cli_stream_dir_prebinned(tmp_path, capsys):
    """uint8 shards are consumed as-is (no mapper in the artifact)."""
    from ddt_tpu import api

    Xb, y = datasets.stress_binned_chunk(0, 1200, n_features=16, seed=7)
    d = str(tmp_path / "binned")
    chunks_mod.shard_arrays(Xb, y, d, n_chunks=3)
    src = chunks_mod.directory_chunks(d)
    assert src.binned

    m = str(tmp_path / "m.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--trees=2", "--depth=3", "--bins=255",
        f"--stream-dir={d}", f"--out={m}",
    ])
    assert rec["trees"] == 2
    b = api.load_model(m)
    assert b.mapper is None
    p = b.ensemble.predict(Xb, binned=True)
    assert p[y == 1].mean() > p[y == 0].mean()


def test_cli_predict_stream_dir(tmp_path, capsys):
    """Out-of-core batch scoring (BASELINE config 4 beyond RAM): per-shard
    score files, equal to in-memory prediction on the concatenation."""
    from ddt_tpu import api

    X, y = datasets.synthetic_binary(2500, n_features=8, seed=6)
    d = str(tmp_path / "shards")
    chunks_mod.shard_arrays(X, y, d, n_chunks=3)

    m = str(tmp_path / "m.npz")
    _run(capsys, [
        "train", "--backend=cpu", "--trees=4", "--depth=3", "--bins=31",
        f"--stream-dir={d}", f"--out={m}",
    ])
    sdir = str(tmp_path / "scores")
    rec = _run(capsys, [
        "predict", "--backend=cpu", f"--model={m}",
        f"--stream-dir={d}", f"--out={sdir}",
    ])
    assert rec["rows"] == 2500 and rec["streamed_chunks"] == 3
    got = np.concatenate(
        [np.load(f"{sdir}/scores_{c:05d}.npy") for c in range(3)])
    b = api.load_model(m)
    want = api.predict(b.ensemble, X, mapper=b.mapper)
    # CLI routes through the CPU backend (native traversal); the oracle
    # comparison is ULP-level, not bitwise.
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # pre-binned shards score too (binned model path)
    Xb, yb = datasets.stress_binned_chunk(0, 900, n_features=16, seed=8)
    db = str(tmp_path / "binned")
    chunks_mod.shard_arrays(Xb, yb, db, n_chunks=2)
    mb = str(tmp_path / "mb.npz")
    _run(capsys, ["train", "--backend=cpu", "--trees=2", "--depth=3",
                  "--bins=255", f"--stream-dir={db}", f"--out={mb}"])
    rec = _run(capsys, ["predict", "--backend=cpu", f"--model={mb}",
                        f"--stream-dir={db}",
                        f"--out={tmp_path / 'sb'}"])
    assert rec["rows"] == 900


def test_cli_predict_stream_dir_guards(tmp_path, capsys):
    """Encoder-carrying models refuse raw shards (silent garbage
    otherwise); width mismatches on binned shards fail loudly."""
    from ddt_tpu.cli import main as cli_main

    # criteo-style in-memory model carries an encoder
    m = str(tmp_path / "cm.npz")
    _run(capsys, ["train", "--backend=cpu", "--dataset=criteo",
                  "--rows=1200", "--trees=2", "--depth=3", "--bins=31",
                  f"--out={m}"])
    X, y = datasets.synthetic_binary(600, n_features=8, seed=1)
    d = str(tmp_path / "raw")
    chunks_mod.shard_arrays(X, y, d, n_chunks=2)
    with pytest.raises(SystemExit, match="categorical encoder"):
        cli_main(["predict", "--backend=cpu", f"--model={m}",
                  f"--stream-dir={d}", f"--out={tmp_path / 's'}"])

    # binned shards with the wrong width vs a binned-trained model
    Xb, yb = datasets.stress_binned_chunk(0, 800, n_features=16, seed=2)
    db = str(tmp_path / "b16")
    chunks_mod.shard_arrays(Xb, yb, db, n_chunks=2)
    mb = str(tmp_path / "mb.npz")
    _run(capsys, ["train", "--backend=cpu", "--trees=2", "--depth=2",
                  "--bins=255", f"--stream-dir={db}", f"--out={mb}"])
    Xw, yw = datasets.stress_binned_chunk(0, 800, n_features=24, seed=2)
    dw = str(tmp_path / "b24")
    chunks_mod.shard_arrays(Xw, yw, dw, n_chunks=2)
    with pytest.raises(SystemExit, match="24 features"):
        cli_main(["predict", "--backend=cpu", f"--model={mb}",
                  f"--stream-dir={dw}", f"--out={tmp_path / 's2'}"])
