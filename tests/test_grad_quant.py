"""Quantized-gradient training (ISSUE 14, cfg.grad_dtype): the int8/
int16 g/h pipeline's contracts.

What the suite pins (docs/PERF.md "Quantized gradients"):

- the jax/np quantizer TWINS are bit-identical (64-bit row bases
  included), on-grid values quantize exactly, zeros stay zero, |q| is
  bounded by qmax, and the draw is a pure function of its key;
- the three histogram impls (pallas interpret / matmul / segment) are
  bitwise IDENTICAL on integer gradients, sibling subtraction is exact
  in the integer domain (fused and streamed assembly), and cross-shard
  merges are order-independent;
- quantized trees are STRUCTURE-IDENTICAL to f32 on exact-grid models
  across n_classes {1, 3} x missing x categorical x (Pr, Pf) meshes,
  and split agreement on random-value models meets the Higgs-shape
  acceptance bar;
- streamed == in-memory STRUCTURE is fully bitwise under quantization
  (the f32 path's chunked-summation bf16-tie seam does not exist;
  leaf values keep the usual device-vs-host 1-ULP arithmetic seam);
- grad_quant_error_bound holds end-to-end (witnessed, not hoped);
- stochastic rounding replays identically under injected chaos retries
  and across checkpoint resume;
- the refuse-loudly config validation and the effective-bytes counters
  (per-level wire >= 2x for levels >= 1, g/h stream 4x/2x) hold —
  witnessed in-process from run-log counters, not just computed.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddt_tpu import api, streaming
from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import synthetic_binary, synthetic_multiclass
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver
from ddt_tpu.ops import grad as grad_ops
from ddt_tpu.ops import histogram as hist_ops
from ddt_tpu.ops.grow import resolve_hist_subtraction
from ddt_tpu.ops.hist_pallas import build_histograms_pallas
from ddt_tpu.telemetry import counters as tele_counters


def _binary(rows=3000, features=8, bins=63, seed=3):
    X, y = synthetic_binary(rows, n_features=features, seed=seed)
    Xb, _ = quantize(X, n_bins=bins, seed=seed)
    return Xb, y


def _struct_equal(a, b):
    return (np.array_equal(a.feature, b.feature)
            and np.array_equal(a.threshold_bin, b.threshold_bin)
            and np.array_equal(a.is_leaf, b.is_leaf))


# --------------------------------------------------------------------- #
# quantizer unit contracts
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("grad_dtype", ["int8", "int16"])
def test_quantize_twins_bit_identical(grad_dtype):
    rng = np.random.default_rng(0)
    g = rng.standard_normal(2000).astype(np.float32)
    h = (rng.random(2000) * 0.25).astype(np.float32)
    qg, qh, gs, hs = grad_ops.quantize_gradients(
        jnp.asarray(g), jnp.asarray(h), grad_dtype=grad_dtype,
        tree_id=jnp.int32(5), seed=11, local_offset=jnp.int32(0))
    qg2, qh2, gs2, hs2 = grad_ops.quantize_gradients_np(
        g, h, grad_dtype=grad_dtype, tree_id=5, seed=11, row_start=0)
    assert float(gs) == float(gs2) and float(hs) == float(hs2)
    assert np.array_equal(np.asarray(qg), qg2)
    assert np.array_equal(np.asarray(qh), qh2)
    qmax = grad_ops.GRAD_QMAX[grad_dtype]
    assert np.abs(qg2.astype(np.int64)).max() <= qmax
    # Determinism: the identical key reproduces the identical bits.
    qg3, _, _, _ = grad_ops.quantize_gradients_np(
        g, h, grad_dtype=grad_dtype, tree_id=5, seed=11, row_start=0)
    assert np.array_equal(qg2, qg3)
    # A different tree id moves the rounding bits (off-grid values).
    qg4, _, _, _ = grad_ops.quantize_gradients_np(
        g, h, grad_dtype=grad_dtype, tree_id=6, seed=11, row_start=0)
    assert not np.array_equal(qg2, qg4)


def test_quantize_64bit_row_base_twins():
    """The streaming trainers key rows above 2^32 via (hi, lo) pairs —
    the jax carry path must match the np uint64 path bitwise."""
    rng = np.random.default_rng(1)
    g = rng.standard_normal(500).astype(np.float32)
    h = (rng.random(500) + 0.1).astype(np.float32)
    base = (1 << 33) + 0xFFFFFF00          # forces a lo-word carry
    qg_np, qh_np, gs, hs = grad_ops.quantize_gradients_np(
        g, h, grad_dtype="int8", tree_id=2, seed=9, row_start=base)
    qg_j, qh_j = grad_ops.quantize_with_scales(
        jnp.asarray(g), jnp.asarray(h), jnp.float32(gs), jnp.float32(hs),
        grad_dtype="int8", tree_id=jnp.int32(2), seed=9,
        local_offset=jnp.int32(0),
        row_start_lo=jnp.uint32(base & 0xFFFFFFFF),
        row_start_hi=jnp.uint32(base >> 32))
    assert np.array_equal(np.asarray(qg_j), qg_np)
    assert np.array_equal(np.asarray(qh_j), qh_np)


def test_quantize_exact_grid_and_zeros():
    """On-grid values quantize exactly (u < 1 strictly), zeros stay
    exactly zero (masked/pad rows must contribute nothing), and the
    power-of-two scale makes dequantization exact."""
    scale = np.float32(2.0 ** -6)
    g = (np.arange(-127, 128).astype(np.float32)) * scale
    h = np.abs(g) + scale
    qg, qh, gs, hs = grad_ops.quantize_gradients_np(
        g, h, grad_dtype="int8", tree_id=0, seed=0)
    assert np.array_equal(qg.astype(np.float32) * gs, g)
    z = np.zeros(64, np.float32)
    qz, qzh, zs, _ = grad_ops.quantize_gradients_np(
        z, z, grad_dtype="int16", tree_id=3, seed=1)
    assert not qz.any() and not qzh.any() and zs == np.float32(1.0)


def test_quant_scale_sum_cap_engages():
    """When the mass term dominates, the scale coarsens so the global
    sum of |q| stays under the int32 headroom — overflow-free merges by
    construction, not by runtime checks."""
    max_abs, sum_abs = 1.0, float(2 ** 34)
    s = grad_ops.quant_scale_np(max_abs, sum_abs, "int16")
    assert s >= np.float32(sum_abs / grad_ops.GRAD_SUM_CAP)
    assert sum_abs / float(s) <= grad_ops.GRAD_SUM_CAP
    # And it matches the traced twin bit-for-bit.
    sj = grad_ops.quant_scale(jnp.float32(max_abs), jnp.float32(sum_abs),
                              "int16")
    assert float(sj) == float(s)


# --------------------------------------------------------------------- #
# integer histogram kernels
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("grad_dtype,bins", [("int8", 31), ("int8", 255),
                                             ("int16", 64)])
def test_integer_hist_impls_bitwise_identical(grad_dtype, bins):
    rng = np.random.default_rng(2)
    R, F, N = 2500, 5, 4
    npdt = np.int8 if grad_dtype == "int8" else np.int16
    Xb = jnp.asarray(rng.integers(0, bins, size=(R, F), dtype=np.uint8))
    qmax = grad_ops.GRAD_QMAX[grad_dtype]
    qg = jnp.asarray(rng.integers(-qmax, qmax + 1, size=R).astype(npdt))
    qh = jnp.asarray(rng.integers(0, qmax + 1, size=R).astype(npdt))
    ni = jnp.asarray(rng.integers(-1, N, size=R).astype(np.int32))
    seg = hist_ops.build_histograms_segment(Xb, qg, qh, ni, N, bins)
    mm = hist_ops.build_histograms_matmul(Xb, qg, qh, ni, N, bins,
                                          row_chunk=600)
    pal = build_histograms_pallas(Xb, qg, qh, ni, N, bins, interpret=True)
    assert seg.dtype == mm.dtype == pal.dtype == jnp.int32
    assert bool((seg == mm).all()) and bool((seg == pal).all())
    # Chunked == monolithic: integer adds commute exactly.
    mm1 = hist_ops.build_histograms_matmul(Xb, qg, qh, ni, N, bins,
                                           row_chunk=10 ** 6)
    assert bool((mm == mm1).all())


def test_integer_sibling_subtraction_bitwise_device():
    """level_histograms' integer path: right = parent - left recovered
    bitwise vs a direct full build — the f32-ULP caveat is gone."""
    import functools

    from ddt_tpu.ops import grow as grow_ops

    rng = np.random.default_rng(4)
    R, F, bins = 3000, 6, 31
    Xb = jnp.asarray(rng.integers(0, bins, size=(R, F), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) * 0.25 + 0.01).astype(np.float32))
    kw = dict(max_depth=4, n_bins=bins, reg_lambda=1.0,
              min_child_weight=1e-3, min_split_gain=0.0,
              grad_dtype="int8", quant_seed=7)
    t_on = jax.jit(functools.partial(
        grow_ops.grow_tree, hist_subtraction=True, **kw))(Xb, g, h)
    t_off = jax.jit(functools.partial(
        grow_ops.grow_tree, hist_subtraction=False, **kw))(Xb, g, h)
    # Integer subtraction is exact, so the WHOLE tree — leaf values
    # included — must be bitwise invariant to the trick.
    assert _struct_equal(t_on, t_off)
    assert np.array_equal(np.asarray(t_on.leaf_value),
                          np.asarray(t_off.leaf_value))


def test_streamed_subtraction_assembly_integer_exact():
    from ddt_tpu.streaming import _assemble_subtracted_level

    rng = np.random.default_rng(5)
    parent = rng.integers(-1000, 1000, size=(2, 3, 8, 2)).astype(np.int32)
    left = rng.integers(-500, 500, size=(2, 3, 8, 2)).astype(np.int32)
    is_leaf = np.zeros(15, bool)
    is_leaf[2] = True                       # parent slot 2 froze
    out = _assemble_subtracted_level(parent, left, is_leaf, 2)
    assert out.dtype == np.int32
    assert np.array_equal(out[0::2], left)
    assert np.array_equal(out[1], parent[0] - left[0])
    assert not out[3].any()                 # frozen parent's right child


def test_resolve_hist_subtraction_integer_on_everywhere():
    assert resolve_hist_subtraction("auto", platform="cpu",
                                    integer_hists=True) is True
    assert resolve_hist_subtraction("off", platform="cpu",
                                    integer_hists=True) is False
    assert resolve_hist_subtraction("auto", platform="cpu") is False


# --------------------------------------------------------------------- #
# structure identity / agreement
# --------------------------------------------------------------------- #

def _exact_grid_gh(rng, R, grad_dtype):
    """Crafted per-row g/h whose quantization AND dequantization are
    EXACT (the ops/grad module docstring's recipe): integer values with
    the channel max PINNED to qmax — the scale is then exactly 1.0 —
    and total integer mass under 2^24, so the single int32 -> f32
    dequantize cast of any node total is exact too (past 2^24 the one
    dequantize rounds once — inside the bound, but not grid-exact —
    docs/PERF.md 'Quantized gradients')."""
    qmax = grad_ops.GRAD_QMAX[grad_dtype]
    g = rng.integers(-64, 65, size=R).astype(np.float32)
    h = rng.integers(1, 65, size=R).astype(np.float32)
    g[0] = qmax          # pins gscale = qmax/qmax = 1.0 exactly
    h[0] = qmax
    return g, h


@pytest.mark.parametrize("grad_dtype", ["int8", "int16"])
@pytest.mark.parametrize("mesh,variant", [
    # Every mesh on the plain variant; the missing/categorical routing
    # variants on the single-device and full-2D corners (routing is
    # layout-independent by the mesh suite's own contracts — repeating
    # every cross term would only re-buy compile time).
    ((1, 1), "plain"), ((2, 1), "plain"), ((2, 2), "plain"),
    ((1, 4), "plain"),
    ((1, 1), "missing"), ((2, 2), "missing"),
    ((1, 1), "categorical"), ((2, 2), "categorical"),
])
def test_exact_grid_structure_identity_meshes(grad_dtype, mesh, variant):
    """Quantized trees == f32 trees on exact-grid gradients at every
    (Pr, Pf), with missing-bin and categorical routing in the mix — the
    acceptance criterion's core. Crafted on-grid g/h isolate the
    quantization step (real losses rarely land on the grid; the
    end-to-end exact-grid constructions are below)."""
    rng = np.random.default_rng(8)
    # R kept under the 2^24-mass exactness condition for int16's finer
    # grid (see _exact_grid_gh).
    R, F, bins = 1000, 6, 31
    Xb = rng.integers(0, bins, size=(R, F), dtype=np.uint8)
    g, h = _exact_grid_gh(rng, R, grad_dtype)
    pr, pf = mesh
    kw = dict(n_trees=1, max_depth=3, n_bins=bins, backend="tpu",
              n_partitions=pr, feature_partitions=pf)
    if variant == "missing":
        kw["missing_policy"] = "learn"
        Xb = Xb.copy()
        Xb[rng.random(R) < 0.1] = bins - 1   # NaN-bin rows
    elif variant == "categorical":
        kw["cat_features"] = (1, 4)
    trees = {}
    for dt in ("f32", grad_dtype):
        cfg = TrainConfig(grad_dtype=dt, **kw)
        be = get_backend(cfg)
        data = be.upload(Xb)
        gd = be._put_rows(g)
        hd = be._put_rows(h)
        handle, _ = be.grow_tree(data, gd, hd, tree_id=0)
        trees[dt] = be.fetch_tree(handle)
    for field in ("feature", "threshold_bin", "is_leaf", "default_left"):
        assert np.array_equal(trees["f32"][field],
                              trees[grad_dtype][field]), (field, mesh)
    np.testing.assert_allclose(trees["f32"]["leaf_value"],
                               trees[grad_dtype]["leaf_value"],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("loss,n_classes", [("mse", 2), ("logloss", 2),
                                            ("softmax", 3)])
def test_exact_grid_end_to_end_first_round(loss, n_classes):
    """End-to-end exact-grid constructions through the REAL loss: mse on
    y in {-1, +1} with mean 0 gives g in {-/+1}, h = 1; balanced logloss
    gives g in {-/+0.5}, h = 0.25 — all exact powers of two on the
    snapped grid, so round 1's quantized tree must equal f32's exactly.
    Softmax gradients are never on-grid (p = 1/3...), so that arm pins
    the AGREEMENT contract instead of identity."""
    rng = np.random.default_rng(12)
    R, F, bins = 3000, 8, 63
    Xb = rng.integers(0, bins, size=(R, F), dtype=np.uint8)
    if loss == "mse":
        y = np.tile([-1.0, 1.0], R // 2).astype(np.float32)
    elif loss == "logloss":
        y = np.tile([0.0, 1.0], R // 2).astype(np.float32)
    else:
        y = rng.integers(0, n_classes, size=R).astype(np.int32)
    cfg = TrainConfig(n_trees=1, max_depth=4, n_bins=bins, backend="tpu",
                      loss=loss, n_classes=n_classes)
    ens_f = api.train(Xb, y, cfg, binned=True).ensemble
    ens_q = api.train(Xb, y, cfg.replace(grad_dtype="int8"),
                      binned=True).ensemble
    if loss == "softmax":
        agree = np.mean(ens_f.feature == ens_q.feature)
        assert agree >= 0.95, agree
    else:
        assert _struct_equal(ens_f, ens_q)
        np.testing.assert_allclose(ens_f.leaf_value, ens_q.leaf_value,
                                   rtol=1e-5, atol=1e-6)


def test_split_agreement_higgs_shape():
    """The acceptance bar: int8 split agreement >= 0.985 vs f32 at the
    Higgs-shape bench config (28 features, 255 bins, depth 6) over REAL
    logloss gradients.

    Protocol (docs/PERF.md "Quantized gradients"): PER-ROUND — each
    round's quantized tree is grown from the SAME f32 boosting state as
    its f32 twin, and agreement is the fraction of node slots whose
    feature choice matches. This isolates the quantizer's per-decision
    flip rate; a compounded two-trajectory comparison conflates it with
    model divergence (one early near-tie flip relabels a whole subtree
    and every later round — both models remain valid GBDTs). Rows are a
    tier-1-sized slice of the 1M bench shape; measured agreement holds
    comfortably above the floor across slice sizes (1.0 at 100k, 0.994
    at 400k — docs/PERF.md 'Quantized gradients'); the tier-1 run uses
    the 100k slice."""
    X, y = synthetic_binary(100_000, n_features=28, seed=42)
    Xb, _ = quantize(X, n_bins=255, seed=42)
    cfg_f = TrainConfig(n_trees=3, max_depth=6, n_bins=255, backend="tpu")
    be_f = get_backend(cfg_f)
    be_q = get_backend(cfg_f.replace(grad_dtype="int8"))
    data_f = be_f.upload(Xb)
    data_q = be_q.upload(Xb)
    yh = be_f.upload_labels(y.astype(np.float32))
    pred = be_f.init_pred(yh, float(np.log(y.mean() / (1 - y.mean()))))
    same = tot = 0
    for rnd in range(cfg_f.n_trees):
        g, h = be_f.grad_hess(pred, yh)
        hf, delta = be_f.grow_tree(data_f, g, h, tree_id=rnd)
        hq, _ = be_q.grow_tree(data_q, g, h, tree_id=rnd)
        tf = be_f.fetch_tree(hf)
        tq = be_q.fetch_tree(hq)
        same += int((tf["feature"] == tq["feature"]).sum())
        tot += tf["feature"].size
        pred = be_f.apply_delta(pred, delta, 0)
    agree = same / tot
    assert agree >= 0.985, f"int8 split agreement {agree:.4f} < 0.985"


def test_fused_equals_granular_quantized():
    Xb, y = _binary()
    for dt in ("int8", "int16"):
        cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=63,
                          backend="tpu", grad_dtype=dt,
                          subsample=0.8, colsample_bytree=0.9)
        fused = api.train(Xb, y, cfg, binned=True).ensemble
        gran = Driver(get_backend(cfg), cfg, log_every=10 ** 9,
                      profile=True).fit(Xb, y)
        assert _struct_equal(fused, gran), dt
        np.testing.assert_allclose(fused.leaf_value, gran.leaf_value,
                                   rtol=1e-5, atol=1e-6)


def test_quantized_with_inscan_eval_and_early_stop():
    """The fused-rounds eval composition: quantized rounds thread their
    round ids through the same scan lane as eval + colsample — the
    in-scan validation scoring and early stopping must work unchanged
    (and match the f32 arm's plumbing, not its scores)."""
    Xb, y = _binary(rows=2400, seed=31)
    cfg = TrainConfig(n_trees=6, max_depth=3, n_bins=63, backend="tpu",
                      grad_dtype="int8", colsample_bytree=0.9)
    res = api.train(Xb[:2000], y[:2000], cfg, binned=True,
                    eval_set=(Xb[2000:], y[2000:]),
                    eval_metric="logloss", early_stopping_rounds=3)
    assert res.ensemble.n_trees >= 1
    assert any("valid_logloss" in h for h in res.history)


def test_mesh_structure_identity_full_train():
    """Whole quantized TRAINS are structure-identical across mesh
    layouts — the integer merge is order-independent, so (Pr, Pf)
    cannot perturb anything."""
    Xb, y = _binary()
    base = TrainConfig(n_trees=2, max_depth=3, n_bins=63, backend="tpu",
                       grad_dtype="int8")
    single = api.train(Xb, y, base, binned=True).ensemble
    for pr, pf in [(2, 2), (1, 4)]:
        m = api.train(Xb, y,
                      base.replace(n_partitions=pr, feature_partitions=pf),
                      binned=True).ensemble
        assert _struct_equal(single, m), (pr, pf)


# --------------------------------------------------------------------- #
# streamed == in-memory, chaos, resume
# --------------------------------------------------------------------- #

def _chunk_fn(Xb, y, n_chunks):
    bounds = np.linspace(0, len(y), n_chunks + 1).astype(np.int64)

    def f(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

    return f


@pytest.mark.parametrize("grad_dtype", ["int8", "int16"])
def test_streamed_equals_in_memory_bitwise(grad_dtype):
    """Under quantization streamed == in-memory STRUCTURE is fully
    BITWISE — integer chunk merges commute and the rounding is keyed by
    global row id, so the f32 path's documented bf16-tie seam (chunked
    summation order flipping near-tie splits) does not exist here. Leaf
    VALUES share the f32 suite's device-vs-host arithmetic seam (the
    final -G/(H+lambda) runs fused on device in-memory, numpy on host
    streamed): 1-ULP tolerance, same as test_streaming."""
    Xb, y = _binary(rows=4000, seed=7)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=63, backend="tpu",
                      grad_dtype=grad_dtype, subsample=0.85)
    mem = api.train(Xb, y, cfg, binned=True).ensemble
    st = streaming.fit_streaming(_chunk_fn(Xb, y, 5), 5, cfg,
                                 backend=get_backend(cfg))
    assert _struct_equal(mem, st)
    np.testing.assert_allclose(mem.leaf_value, st.leaf_value,
                               rtol=1e-5, atol=1e-6)


def test_streamed_softmax_quantized():
    X, y = synthetic_multiclass(3000, n_features=6, n_classes=3, seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=31, backend="tpu",
                      loss="softmax", n_classes=3, grad_dtype="int8")
    mem = api.train(Xb, y, cfg, binned=True).ensemble
    st = streaming.fit_streaming(_chunk_fn(Xb, y, 4), 4, cfg,
                                 backend=get_backend(cfg))
    assert _struct_equal(mem, st)
    np.testing.assert_allclose(mem.leaf_value, st.leaf_value,
                               rtol=1e-5, atol=1e-6)


def test_host_streaming_loop_refuses_quantized():
    Xb, y = _binary(rows=1000)
    cfg = TrainConfig(n_trees=1, max_depth=2, n_bins=63, backend="cpu",
                      grad_dtype="int8")
    with pytest.raises(NotImplementedError, match="grad_dtype"):
        # Config construction succeeds; the CPU backend (and the host
        # loop) refuse. Build the backend indirectly via fit_streaming.
        streaming.fit_streaming(_chunk_fn(Xb, y, 2), 2, cfg)


def test_chaos_retry_replays_identical_bits():
    """Stochastic-rounding determinism under an injected retry: a
    chunk-read fault forces a re-read + re-quantize mid-train; the
    ensemble must be bit-identical to an undisturbed run (the rounding
    is a pure function of (seed, tree, row), never of the attempt)."""
    from ddt_tpu.robustness import faultplan

    Xb, y = _binary(rows=2400, seed=13)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=63, backend="tpu",
                      grad_dtype="int8", seed=13)
    clean = streaming.fit_streaming(_chunk_fn(Xb, y, 4), 4, cfg,
                                    backend=get_backend(cfg))
    plan = faultplan.load_plan({"faults": [
        {"site": "stream.chunk_read", "chunk": 1, "times": 2},
        {"site": "stream.chunk_read", "chunk": 3, "times": 1},
    ]})
    prev = faultplan.activate(plan)
    try:
        chaos = streaming.fit_streaming(_chunk_fn(Xb, y, 4), 4, cfg,
                                        backend=get_backend(cfg))
    finally:
        faultplan.deactivate(prev)
    assert _struct_equal(clean, chaos)
    assert np.array_equal(clean.leaf_value, chaos.leaf_value)


def test_checkpoint_resume_bit_identical_quantized(tmp_path):
    from ddt_tpu.robustness import faultplan

    Xb, y = _binary(rows=2400, seed=17)
    cfg = TrainConfig(n_trees=6, max_depth=3, n_bins=63, backend="tpu",
                      grad_dtype="int8", seed=17)
    ck = str(tmp_path / "ck")
    clean = streaming.fit_streaming(
        _chunk_fn(Xb, y, 3), 3, cfg, backend=get_backend(cfg),
        checkpoint_dir=str(tmp_path / "ck0"), checkpoint_every=2)
    plan = faultplan.load_plan({"faults": [
        {"site": "ckpt.save.between", "round": 4}]})
    prev = faultplan.activate(plan)
    died = False
    try:
        streaming.fit_streaming(_chunk_fn(Xb, y, 3), 3, cfg,
                                backend=get_backend(cfg),
                                checkpoint_dir=ck, checkpoint_every=2)
    except faultplan.InjectedCrash:
        died = True
    finally:
        faultplan.deactivate(prev)
    assert died
    resumed = streaming.fit_streaming(_chunk_fn(Xb, y, 3), 3, cfg,
                                      backend=get_backend(cfg),
                                      checkpoint_dir=ck,
                                      checkpoint_every=2)
    assert _struct_equal(clean, resumed)
    assert np.array_equal(clean.leaf_value, resumed.leaf_value)


# --------------------------------------------------------------------- #
# error bound
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("grad_dtype", ["int8", "int16"])
def test_error_bound_held_end_to_end(grad_dtype):
    """Every dequantized histogram entry (and node total) lands within
    grad_quant_error_bound of the exact f32 value — computed, then
    WITNESSED against real kernels."""
    rng = np.random.default_rng(21)
    R, F, bins, N = 4000, 6, 31, 4
    Xb = rng.integers(0, bins, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = (rng.random(R) * 0.25).astype(np.float32)
    ni = rng.integers(0, N, size=R).astype(np.int32)
    qg, qh, gs, hs = grad_ops.quantize_gradients_np(
        g, h, grad_dtype=grad_dtype, tree_id=0, seed=3)
    hq = np.asarray(hist_ops.build_histograms_segment(
        jnp.asarray(Xb), jnp.asarray(qg), jnp.asarray(qh),
        jnp.asarray(ni), N, bins))
    hf = np.zeros((N, F, bins, 2), np.float64)
    for f in range(F):
        np.add.at(hf[:, f, :, 0], (ni, Xb[:, f]), g)
        np.add.at(hf[:, f, :, 1], (ni, Xb[:, f]), h)
    bg = grad_ops.grad_quant_error_bound(
        grad_dtype, np.abs(g).max(), np.abs(g).sum(), R)
    bh = grad_ops.grad_quant_error_bound(
        grad_dtype, np.abs(h).max(), np.abs(h).sum(), R)
    dg = np.abs(hq[..., 0].astype(np.float64) * gs - hf[..., 0]).max()
    dh = np.abs(hq[..., 1].astype(np.float64) * hs - hf[..., 1]).max()
    assert dg <= bg and dh <= bh, (dg, bg, dh, bh)
    # int16's grid is finer: its realized error must undercut int8's
    # bound by a wide margin.
    if grad_dtype == "int16":
        b8 = grad_ops.grad_quant_error_bound(
            "int8", np.abs(g).max(), np.abs(g).sum(), R)
        assert bg < b8


# --------------------------------------------------------------------- #
# refuse-loudly config validation + comms backstop
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("grad_dtype", ["int8", "int16"])
@pytest.mark.parametrize("comms_dtype", ["bf16", "int32_fixed"])
def test_config_refuses_double_quantization(grad_dtype, comms_dtype):
    # Both orderings: whichever knob the user reaches for second, the
    # constructor names the hazard.
    with pytest.raises(ValueError, match="double-quantize"):
        TrainConfig(grad_dtype=grad_dtype, hist_comms_dtype=comms_dtype)
    with pytest.raises(ValueError, match="double-quantize"):
        TrainConfig(hist_comms_dtype=comms_dtype, grad_dtype=grad_dtype)
    # Either knob alone is fine.
    TrainConfig(grad_dtype=grad_dtype)
    TrainConfig(hist_comms_dtype=comms_dtype)
    with pytest.raises(ValueError, match="grad_dtype"):
        TrainConfig(grad_dtype="int4")


def test_hist_reduce_refuses_compressed_integer_partials():
    from ddt_tpu.parallel import comms

    hq = jnp.ones((2, 4, 8, 2), jnp.int32)
    with pytest.raises(ValueError, match="(?i)double-quantize"):
        comms.hist_reduce(hq, None, comms_dtype="bf16")
    # f32 comms on integer partials is the exact identity single-shard.
    out = comms.hist_reduce(hq, None, comms_dtype="f32")
    assert out.dtype == jnp.int32


def test_cpu_backend_refuses_quantized():
    with pytest.raises(NotImplementedError, match="grad_dtype"):
        get_backend(TrainConfig(backend="cpu", grad_dtype="int8"),
                    use_cache=False)


def test_backend_cache_key_separates_grad_dtype():
    cfg_f = TrainConfig(backend="tpu", n_bins=31)
    cfg_q = cfg_f.replace(grad_dtype="int8")
    assert get_backend(cfg_f) is not get_backend(cfg_q)
    # seed is trace-relevant under quantization (the rounding key).
    assert get_backend(cfg_q) is not get_backend(cfg_q.replace(seed=1))


# --------------------------------------------------------------------- #
# effective-bytes counters: computed model + in-process witness
# --------------------------------------------------------------------- #

def test_per_level_wire_bytes_at_least_2x():
    """The acceptance criterion's wire half: under int8 every level >= 1
    moves >= 2x fewer bytes than the f32 baseline (exact subtraction is
    unconditional on the integer path), per level — whole-tree the
    ratio asymptotes to 2 from below (depth 0 has no parent;
    docs/PERF.md). The g/h HBM stream halves at least 2x (int16) / 4x
    (int8) at every level."""
    for dt, stream_floor in (("int8", 4.0), ("int16", 2.0)):
        sub = resolve_hist_subtraction("auto", platform="cpu",
                                       integer_hists=True)
        lv_f = tele_counters.hist_allreduce_bytes_by_level(
            6, 28, 255, partitions=2,
            subtraction=resolve_hist_subtraction("auto", platform="cpu"))
        lv_q = tele_counters.hist_allreduce_bytes_by_level(
            6, 28, 255, partitions=2, subtraction=sub, grad_dtype=dt)
        assert all(f / q >= 2.0 for f, q in zip(lv_f[1:], lv_q[1:]))
        assert lv_f[0] == lv_q[0]          # depth 0 has no parent
        gf = tele_counters.grad_stream_bytes(10 ** 6, 6, "f32")
        gq = tele_counters.grad_stream_bytes(10 ** 6, 6, dt)
        assert gf / gq >= stream_floor
    with pytest.raises(ValueError, match="double-quantiz"):
        tele_counters.hist_allreduce_bytes(6, 28, 255, grad_dtype="int8",
                                           comms_dtype="bf16")


def test_effective_bytes_witnessed_in_process(tmp_path):
    """The counters are WITNESSED from real run logs, not just computed:
    an f32 and an int8 2-partition train of the same shape record
    collective + grad-stream counters whose ratios meet the bars."""
    Xb, y = _binary(rows=2400, seed=23)
    logs = {}
    for dt in ("f32", "int8"):
        cfg = TrainConfig(n_trees=2, max_depth=4, n_bins=63,
                          backend="tpu", n_partitions=2, grad_dtype=dt)
        path = str(tmp_path / f"run_{dt}.jsonl")
        api.train(Xb, y, cfg, binned=True, log_every=10 ** 9,
                  run_log=path)
        with open(path) as f:
            events = [json.loads(ln) for ln in f]
        logs[dt] = next(e for e in events if e["event"] == "counters")
        man = next(e for e in events if e["event"] == "run_manifest")
        if dt == "int8":
            assert man.get("grad_dtype") == "int8"
        else:
            assert "grad_dtype" not in man
    gf = logs["f32"]["grad_stream_bytes_est"]
    gq = logs["int8"]["grad_stream_bytes_est"]
    assert gf > 0 and gq > 0 and gf / gq >= 4.0
    cf = logs["f32"]["collective_bytes_est"]
    cq = logs["int8"]["collective_bytes_est"]
    # Whole-tree wire: subtraction-on integer vs subtraction-off f32
    # (CPU platform) — 63/32 entries at depth 6, ~1.9x; the >= 2x
    # PER-LEVEL criterion is the model test above.
    assert cf > cq and cf / cq >= 1.8, (cf, cq)
    assert logs["int8"]["grad_quant_rounds"] == 2
    assert logs["f32"]["grad_quant_rounds"] == 0


# --------------------------------------------------------------------- #
# bench + CLI surfaces
# --------------------------------------------------------------------- #

def test_bench_hist_quant_ab_smoke():
    from ddt_tpu.bench import bench_hist_quant_ab, run_bench

    out = bench_hist_quant_ab(rows=2000, features=4, bins=31, depth=2,
                              iters=1, reps=2)
    assert out["kernel"] == "hist_quant_ab"
    assert out["payload_ratio"] == 4.0
    assert out["ratio_f32_over_quant"] > 0
    out16 = run_bench(kernel="hist_quant", rows=1500, features=4,
                      bins=31, depth=2, iters=1, seed=1,
                      grad_dtype="int16")
    assert out16["grad_dtype"] == "int16" and out16["payload_ratio"] == 2.0


def test_cli_grad_dtype_flag(tmp_path):
    from ddt_tpu import cli

    Xb, y = _binary(rows=800, seed=29)
    data = str(tmp_path / "d.npz")
    np.savez(data, X=Xb.astype(np.float32), y=y)
    out = str(tmp_path / "m.npz")
    rc = cli.main(["train", "--data", data, "--trees", "1", "--depth",
                   "2", "--bins", "31", "--backend", "tpu",
                   "--grad-dtype", "int8", "--out", out,
                   "--valid-frac", "0"])
    assert rc in (0, None)
    assert os.path.exists(out)
