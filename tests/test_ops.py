"""Kernel-level parity tests: JAX ops vs the NumPy oracle (SURVEY.md §4
"Unit (kernel-level)"). Runs on 8 virtual CPU devices (conftest.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddt_tpu.config import TrainConfig
from ddt_tpu.ops import grad as jgrad
from ddt_tpu.ops import grow as jgrow
from ddt_tpu.ops import histogram as jhist
from ddt_tpu.ops import predict as jpred
from ddt_tpu.ops import split as jsplit
from ddt_tpu.reference import numpy_trainer as oracle
from ddt_tpu.data.datasets import synthetic_binary
from ddt_tpu.data.quantizer import quantize


def _rand_case(R=500, F=7, B=32, n_nodes=4, seed=0, frozen_frac=0.2):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32) + 0.1
    node_index = rng.integers(0, n_nodes, size=R).astype(np.int32)
    node_index[rng.random(R) < frozen_frac] = -1
    return Xb, g, h, node_index


# --------------------------------------------------------------------------- #
# histogram
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("impl", ["segment", "matmul"])
@pytest.mark.parametrize("seed", [0, 1])
def test_histogram_matches_oracle(impl, seed):
    Xb, g, h, node_index = _rand_case(seed=seed)
    want = oracle.build_histograms(Xb, g, h, node_index, 4, 32)
    got = np.asarray(
        jhist.build_histograms(
            jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(node_index), 4, 32,
            impl=impl, input_dtype=jnp.float32,
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_histogram_matmul_chunked_equals_unchunked():
    Xb, g, h, node_index = _rand_case(R=1000)
    a = jhist.build_histograms_matmul(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(node_index), 4, 32,
        row_chunk=128, input_dtype=jnp.float32,
    )
    b = jhist.build_histograms_matmul(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(node_index), 4, 32,
        row_chunk=4096, input_dtype=jnp.float32,
    )
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-4)


def test_histogram_mass_conservation():
    """Property: per-node sums over (bin) equal per-node sums of g/h, for
    every feature (each feature's histogram redistributes the same rows)."""
    Xb, g, h, node_index = _rand_case(R=300, F=3, B=16, n_nodes=3)
    hist = np.asarray(
        jhist.build_histograms(
            jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(node_index), 3, 16, impl="segment",
        )
    )
    for n in range(3):
        m = node_index == n
        for f in range(3):
            np.testing.assert_allclose(
                hist[n, f, :, 0].sum(), g[m].sum(), rtol=1e-4, atol=1e-4
            )
            np.testing.assert_allclose(
                hist[n, f, :, 1].sum(), h[m].sum(), rtol=1e-4, atol=1e-4
            )


# --------------------------------------------------------------------------- #
# split gain
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("reg_lambda,mcw", [(1.0, 1e-3), (0.0, 0.5)])
def test_best_splits_matches_oracle(reg_lambda, mcw):
    Xb, g, h, node_index = _rand_case(B=16, n_nodes=4)
    hist = oracle.build_histograms(Xb, g, h, node_index, 4, 16)
    want_gain, want_f, want_b, _ = oracle.best_splits(hist, reg_lambda, mcw)
    got_gain, got_f, got_b, _ = jsplit.best_splits(
        jnp.asarray(hist), reg_lambda, mcw
    )
    np.testing.assert_allclose(np.asarray(got_gain), want_gain, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(got_f), want_f)
    np.testing.assert_array_equal(np.asarray(got_b), want_b)


# --------------------------------------------------------------------------- #
# gradients
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("loss", ["logloss", "mse", "softmax"])
def test_grad_hess_matches_oracle(loss):
    rng = np.random.default_rng(0)
    R, C = 200, 4
    if loss == "softmax":
        pred = rng.standard_normal((R, C)).astype(np.float32)
        y = rng.integers(0, C, R).astype(np.int32)
    else:
        pred = rng.standard_normal(R).astype(np.float32)
        y = (rng.random(R) > 0.5).astype(np.float32)
    wg, wh = oracle.grad_hess(pred, y, loss)
    gg, gh = jgrad.grad_hess(jnp.asarray(pred), jnp.asarray(y), loss)
    np.testing.assert_allclose(np.asarray(gg), wg, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gh), wh, rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------------- #
# whole-tree growth
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("hist_impl", ["segment", "matmul"])
def test_grow_tree_matches_oracle(hist_impl):
    X, y = synthetic_binary(800, n_features=6, seed=3)
    Xb, _ = quantize(X, n_bins=32)
    cfg = TrainConfig(n_trees=1, max_depth=4, n_bins=32, backend="cpu")
    pred = np.full(800, 0.1, np.float32)
    g, h = oracle.grad_hess(pred, y.astype(np.float32), "logloss")
    want = oracle.grow_tree(Xb, g, h, cfg)

    got = jgrow.grow_tree(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        max_depth=4, n_bins=32, reg_lambda=cfg.reg_lambda,
        min_child_weight=cfg.min_child_weight,
        min_split_gain=cfg.min_split_gain,
        hist_impl=hist_impl, input_dtype=jnp.float32,
    )
    np.testing.assert_array_equal(np.asarray(got.feature), want["feature"])
    np.testing.assert_array_equal(
        np.asarray(got.threshold_bin), want["threshold_bin"]
    )
    np.testing.assert_array_equal(np.asarray(got.is_leaf), want["is_leaf"])
    np.testing.assert_allclose(
        np.asarray(got.leaf_value), want["leaf_value"], rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(got.leaf_of_row), want["leaf_of_row"]
    )


# --------------------------------------------------------------------------- #
# predict
# --------------------------------------------------------------------------- #

def _train_tiny_ensemble():
    X, y = synthetic_binary(600, n_features=5, seed=7)
    Xb, mapper = quantize(X, n_bins=32)
    cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=32, backend="cpu")
    ens = oracle.fit(Xb, y, cfg, mapper=mapper)
    return ens, Xb, X


@pytest.mark.parametrize("tree_chunk", [2, 64])
def test_predict_matches_oracle(tree_chunk):
    ens, Xb, X = _train_tiny_ensemble()
    want = ens.predict_raw(Xb, binned=True)
    got = jpred.predict_raw(
        jnp.asarray(ens.feature), jnp.asarray(ens.threshold_bin),
        jnp.asarray(ens.is_leaf), jnp.asarray(ens.leaf_value),
        jnp.asarray(Xb.astype(np.int32)),
        max_depth=ens.max_depth, learning_rate=ens.learning_rate,
        base=ens.base_score, n_classes=1, tree_chunk=tree_chunk,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_predict_raw_thresholds_match_binned():
    """Raw-value traversal (threshold_raw) agrees with binned traversal."""
    ens, Xb, X = _train_tiny_ensemble()
    want = ens.predict_raw(Xb, binned=True)
    got = jpred.predict_raw(
        jnp.asarray(ens.feature), jnp.asarray(ens.threshold_raw),
        jnp.asarray(ens.is_leaf), jnp.asarray(ens.leaf_value),
        jnp.asarray(X.astype(np.float32)),
        max_depth=ens.max_depth, learning_rate=ens.learning_rate,
        base=ens.base_score, n_classes=1,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_predict_softmax_interleave():
    X, y = synthetic_binary(400, n_features=5, seed=11)
    y = (y + (X[:, 0] > 0)).astype(np.int32)  # 3-ish classes
    Xb, _ = quantize(X, n_bins=32)
    cfg = TrainConfig(
        n_trees=3, max_depth=3, n_bins=32, loss="softmax", n_classes=3,
        backend="cpu",
    )
    ens = oracle.fit(Xb, y, cfg)
    want = ens.predict_raw(Xb, binned=True)          # [R, 3]
    got = jpred.predict_raw(
        jnp.asarray(ens.feature), jnp.asarray(ens.threshold_bin),
        jnp.asarray(ens.is_leaf), jnp.asarray(ens.leaf_value),
        jnp.asarray(Xb.astype(np.int32)),
        max_depth=ens.max_depth, learning_rate=ens.learning_rate,
        base=ens.base_score, n_classes=3, tree_chunk=4,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)
