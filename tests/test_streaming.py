"""Streaming trainer == in-memory trainer on identical data (SURVEY.md §7 M6).

The streaming path recomputes node assignment and gradients statelessly per
chunk; its per-level histogram is the chunk-sum of the in-memory histogram,
entering the same bf16-rounded split selection — so trees must come out
identical (leaf values to float-sum tolerance).
"""

import numpy as np
import pytest

from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver
from ddt_tpu.streaming import fit_streaming


def _chunked(Xb, y, chunk_rows):
    def chunk_fn(c):
        s = c * chunk_rows
        return Xb[s:s + chunk_rows], y[s:s + chunk_rows]
    return chunk_fn, Xb.shape[0] // chunk_rows


@pytest.mark.parametrize("backend_flag,cache", [
    ("cpu", True),
    ("cpu", False),      # stateless rescoring path
    ("tpu", True),       # device histogram kernel per chunk
])
def test_streaming_matches_inmemory(backend_flag, cache):
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=21)
    Xb, _ = quantize(X, n_bins=31, seed=21)
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=31,
                      backend=backend_flag)

    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)

    chunk_fn, n_chunks = _chunked(Xb, y, 512)
    assert n_chunks == 8
    streamed = fit_streaming(chunk_fn, n_chunks, cfg, cache_preds=cache)

    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin, streamed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, streamed.is_leaf)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_streaming_stress_generator_runs():
    """The 10B-row config's generator, miniaturised: streamed chunks of
    already-binned uint8 with 1024 features."""
    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=255, backend="cpu")

    def chunk_fn(c):
        return datasets.stress_binned_chunk(c, chunk_rows=256,
                                            n_features=64, seed=9)

    ens = fit_streaming(chunk_fn, 4, cfg)
    assert ens.n_trees == 2
    Xb, y = datasets.stress_binned_chunk(0, 256, n_features=64, seed=9)
    p = ens.predict(Xb, binned=True)
    # The stress labels are a deterministic function of two bins — the tree
    # must separate classes on its own training chunk.
    assert p[y == 1].mean() > p[y == 0].mean()


@pytest.mark.parametrize("cache", [True, False])
def test_streaming_softmax_host_matches_inmemory(cache):
    """Round-2 verdict item 7b: the host path streams softmax too (one
    tree per class per round from round-start preds), closing the
    backend-parity hole that used to raise NotImplementedError."""
    X, y = datasets.synthetic_multiclass(2048, n_features=8, n_classes=3,
                                         seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=31, backend="cpu",
                      loss="softmax", n_classes=3)
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    chunk_fn, n_chunks = _chunked(Xb, y, 512)
    streamed = fit_streaming(chunk_fn, n_chunks, cfg, cache_preds=cache)
    assert streamed.n_trees == 9          # rounds x classes
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin, streamed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, streamed.is_leaf)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("backend_flag", ["cpu", "tpu"])
def test_streaming_sampling_matches_inmemory(backend_flag):
    """Bagging + colsample STREAM since round 5 (stateless counter-based
    row masks + the Driver's host-drawn colsample masks, ops/sampling):
    the streamed run must grow the in-memory Driver's exact trees — on
    the host loop (cpu) and the device stream ops (tpu), where the keep
    mask is recomputed ON DEVICE per chunk from the chunk's global row
    offset."""
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=21)
    Xb, _ = quantize(X, n_bins=31, seed=21)
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=31,
                      backend=backend_flag, subsample=0.7,
                      colsample_bytree=0.6, seed=11)

    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)

    chunk_fn, n_chunks = _chunked(Xb, y, 512)
    streamed = fit_streaming(chunk_fn, n_chunks, cfg)

    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  streamed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, streamed.is_leaf)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_streaming_sampling_softmax_device_partitioned():
    """Sampling x softmax x row shards x streaming, all at once: the
    sharded device stream (per-class colsample masks at split selection,
    shard-offset-derived bagging bits) equals the in-memory run."""
    X, y = datasets.synthetic_multiclass(3072, n_features=8, n_classes=3,
                                         seed=9)
    Xb, _ = quantize(X, n_bins=31, seed=9)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=31, backend="tpu",
                      loss="softmax", n_classes=3, subsample=0.8,
                      colsample_bytree=0.7, seed=4, n_partitions=2)
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    chunk_fn, n_chunks = _chunked(Xb, y, 768)
    streamed = fit_streaming(chunk_fn, n_chunks, cfg)
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  streamed.threshold_bin)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_streaming_empty_chunk_rejected():
    cfg = TrainConfig(n_trees=2, max_depth=2, backend="cpu")
    with pytest.raises(ValueError, match="empty"):
        fit_streaming(
            lambda c: (np.zeros((0, 3), np.uint8), np.zeros(0)), 2, cfg)


def test_early_stop_nan_metric_raises():
    """Round-2 verdict weak #3: a NaN metric from round 1 must fail with
    the cause, not a TypeError from best_round arithmetic."""
    X, y = datasets.synthetic_binary(512, n_features=6, seed=3)
    Xb, _ = quantize(X, n_bins=15, seed=3)
    yv = np.full(128, np.nan)     # NaN labels => NaN rmse every round
    cfg = TrainConfig(n_trees=5, max_depth=2, n_bins=15, backend="cpu",
                      loss="mse")
    drv = Driver(get_backend(cfg), cfg, log_every=10**9)
    with pytest.raises(ValueError, match="NaN since round 1"):
        drv.fit(Xb, y.astype(np.float32), eval_set=(Xb[:128], yv),
                eval_metric="rmse", early_stopping_rounds=2)


def test_streaming_device_partitioned_matches_inmemory():
    """VERDICT r1 item 5: device streaming composed with row partitions —
    each chunk row-sharded over the mesh, the per-chunk histogram psum'd —
    must still be bit-identical to the in-memory single-device run."""
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=23)
    Xb, _ = quantize(X, n_bins=31, seed=23)
    cfg1 = TrainConfig(n_trees=4, max_depth=4, n_bins=31, backend="tpu")
    full = Driver(get_backend(cfg1), cfg1, log_every=10**9).fit(Xb, y)

    cfg2 = cfg1.replace(n_partitions=2)
    chunk_fn, n_chunks = _chunked(Xb, y, 512)
    streamed = fit_streaming(chunk_fn, n_chunks, cfg2)
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin, streamed.threshold_bin)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)

    # ... and over a (hosts, rows) pod mesh (DCN axis).
    cfg3 = cfg1.replace(host_partitions=2, n_partitions=2)
    streamed_pod = fit_streaming(chunk_fn, n_chunks, cfg3)
    np.testing.assert_array_equal(full.feature, streamed_pod.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  streamed_pod.threshold_bin)

    # ... and the device chunk cache composes with the pod mesh: cached
    # handles are MESH-SHARDED arrays held across passes (forced on via
    # an explicit budget — the CPU-platform default is off), still
    # bit-identical.
    streamed_pod_cached = fit_streaming(chunk_fn, n_chunks, cfg3,
                                        device_chunk_cache=1 << 30)
    np.testing.assert_array_equal(streamed_pod.feature,
                                  streamed_pod_cached.feature)
    np.testing.assert_array_equal(streamed_pod.threshold_bin,
                                  streamed_pod_cached.threshold_bin)
    np.testing.assert_array_equal(streamed_pod.leaf_value,
                                  streamed_pod_cached.leaf_value)


def test_streaming_device_early_leaves_match_inmemory():
    """Deep-narrow config (3 bins, depth 6): most rows freeze at early
    leaves — the device pred-update must keep them at their leaf (sticky
    frozen flag), not resume descending through garbage splits. Multiple
    trees so a wrong pred update would change later trees."""
    X, y = datasets.synthetic_binary(2048, n_features=6, seed=9)
    Xb, _ = quantize(X, n_bins=3, seed=9)
    cfg = TrainConfig(n_trees=5, max_depth=6, n_bins=3, backend="tpu")
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    chunk_fn, n_chunks = _chunked(Xb, y, 512)
    streamed = fit_streaming(chunk_fn, n_chunks, cfg)
    assert full.is_leaf[:, : 2 ** 6 - 1].any()   # early leaves exist
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin, streamed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, streamed.is_leaf)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_streaming_device_softmax_matches_inmemory():
    """VERDICT r1 item 5: softmax streaming (one tree per class per round,
    per-class device passes) == in-memory softmax training."""
    X, y = datasets.synthetic_multiclass(2048, n_features=8, n_classes=3,
                                         seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=31, backend="tpu",
                      loss="softmax", n_classes=3)
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    chunk_fn, n_chunks = _chunked(Xb, y, 512)
    streamed = fit_streaming(chunk_fn, n_chunks, cfg)
    assert streamed.n_trees == 9          # rounds x classes
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin, streamed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, streamed.is_leaf)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("backend_flag", ["cpu", "tpu"])
def test_streaming_missing_matches_inmemory(backend_flag):
    """missing_policy='learn' through the streamed paths: NaN rows occupy
    the reserved bin and follow learned default directions in the per-chunk
    traversal — trees bit-identical to the in-memory Driver, and the
    returned ensemble carries the missing_bin metadata."""
    rng = np.random.default_rng(3)
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=21)
    X[rng.random(X.shape) < 0.15] = np.nan
    from ddt_tpu.data.quantizer import fit_bin_mapper

    m = fit_bin_mapper(X, n_bins=31, missing_policy="learn")
    Xb = m.transform(X)
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=31,
                      backend=backend_flag, missing_policy="learn")

    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)

    chunk_fn, n_chunks = _chunked(Xb, y, 512)
    streamed = fit_streaming(chunk_fn, n_chunks, cfg)

    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin, streamed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, streamed.is_leaf)
    np.testing.assert_array_equal(full.default_left, streamed.default_left)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)
    assert streamed.missing_bin
    # a learned default direction was actually exercised
    assert streamed.default_left[~streamed.is_leaf].any()


@pytest.mark.parametrize("backend_flag", ["cpu", "tpu"])
def test_streaming_ragged_chunks_match_inmemory(backend_flag):
    """Unequal chunk sizes (each size compiles its own program) grow trees
    bit-identical to in-memory training — the CLI's array_split chunking
    relies on this."""
    X, y = datasets.synthetic_binary(4000, n_features=8, seed=9)
    Xb, _ = quantize(X, n_bins=31, seed=9)
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31,
                      backend=backend_flag)
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)

    bounds = [0, 1337, 2674, 4000]          # 1337/1337/1326 rows

    def chunk_fn(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

    streamed = fit_streaming(chunk_fn, 3, cfg)
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  streamed.threshold_bin)


@pytest.mark.parametrize("backend_flag,loss", [
    ("cpu", "logloss"),
    ("tpu", "logloss"),
    ("tpu", "softmax"),     # C>1: cursor counts rounds, slots rounds*C
])
def test_streaming_checkpoint_resume_bit_exact(tmp_path, backend_flag,
                                               loss):
    """Streamed training checkpoints per round and resumes BIT-exactly:
    an interrupted-then-resumed run equals an uninterrupted one (the
    resident boosting state is reconstituted by per-round rescoring of
    the restored partial ensemble)."""
    if loss == "softmax":
        X, y = datasets.synthetic_multiclass(2048, n_features=8,
                                             n_classes=3, seed=5)
        extra = dict(loss="softmax", n_classes=3)
    else:
        X, y = datasets.synthetic_binary(2048, n_features=8, seed=5)
        extra = {}
    Xb, _ = quantize(X, n_bins=31, seed=5)
    cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=31,
                      backend=backend_flag, **extra)
    chunk_fn, n_chunks = _chunked(Xb, y, 512)

    plain = fit_streaming(chunk_fn, n_chunks, cfg)

    # "Interrupt" after round 2: train a 2-round run into the checkpoint
    # dir, then resume to 5 from its artifacts.
    ck = str(tmp_path / "ck")
    fit_streaming(chunk_fn, n_chunks, cfg.replace(n_trees=2),
                  checkpoint_dir=ck, checkpoint_every=1)
    resumed = fit_streaming(chunk_fn, n_chunks, cfg,
                            checkpoint_dir=ck, checkpoint_every=2)

    np.testing.assert_array_equal(plain.feature, resumed.feature)
    np.testing.assert_array_equal(plain.threshold_bin,
                                  resumed.threshold_bin)
    np.testing.assert_array_equal(plain.is_leaf, resumed.is_leaf)
    np.testing.assert_array_equal(plain.leaf_value, resumed.leaf_value)


# --------------------------------------------------------------------- #
# Streaming validation + early stopping (round-2 verdict item 3)
# --------------------------------------------------------------------- #

def _chunked_all(Xb, y, n_chunks):
    """Chunking that covers EVERY row (linspace bounds, ragged tail ok) —
    _chunked drops the tail when len isn't a multiple of the chunk size."""
    bounds = np.linspace(0, len(y), n_chunks + 1).astype(np.int64)

    def chunk_fn(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]
    return chunk_fn, n_chunks


def _val_split(Xb, y, frac=0.25, seed=7):
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(y))
    k = int(len(y) * frac)
    va, tr = idx[:k], idx[k:]
    return Xb[tr], y[tr], Xb[va], y[va]


@pytest.mark.parametrize("backend_flag", ["cpu", "tpu"])
def test_streaming_validation_history_matches_driver(backend_flag):
    """Per-round streamed validation scores equal the in-memory Driver's
    valid_<metric> series on the same split (host-f64 metric both sides;
    the cpu pair is bit-identical, the device pair FMA-close)."""
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=13)
    Xb, _ = quantize(X, n_bins=31, seed=13)
    Xt, yt, Xv, yv = _val_split(Xb, y)
    cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=31,
                      backend=backend_flag)

    drv = Driver(get_backend(TrainConfig(n_trees=5, max_depth=3, n_bins=31,
                                         backend="cpu")),
                 cfg, log_every=10**9)
    drv.fit(Xt, yt, eval_set=(Xv, yv), eval_metric="auc")
    want = [r["valid_auc"] for r in drv.history]

    chunk_fn, n_chunks = _chunked_all(Xt, yt, 6)
    vfn, n_valid = _chunked_all(Xv, yv, 2)
    history = []
    streamed = fit_streaming(chunk_fn, n_chunks, cfg,
                             valid_chunk_fn=vfn, n_valid_chunks=n_valid,
                             eval_metric="auc", history=history)
    got = [r["valid_auc"] for r in history]
    assert len(got) == 5
    assert streamed.n_trees == 5            # no early stop requested
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_streaming_early_stop_truncates_like_driver():
    """Early stopping under streaming stops at the same round and returns
    the same truncated ensemble as Driver.fit on the same data."""
    X, y = datasets.synthetic_binary(3072, n_features=8, seed=17)
    Xb, _ = quantize(X, n_bins=31, seed=17)
    Xt, yt, Xv, yv = _val_split(Xb, y)
    # Aggressive lr so validation logloss degrades within a few rounds.
    # min_split_gain floors the decisions above the f32 noise floor (the
    # determinism domain documented in ops/split.py) — lr=0.9 pushes late
    # trees into signal-free territory where noise-sign splits otherwise
    # legitimately differ between chunk-summed and whole-data histograms.
    cfg = TrainConfig(n_trees=30, max_depth=4, n_bins=31, backend="cpu",
                      learning_rate=0.9, min_split_gain=1e-3)

    drv = Driver(get_backend(cfg), cfg, log_every=10**9)
    full = drv.fit(Xt, yt, eval_set=(Xv, yv), eval_metric="logloss",
                   early_stopping_rounds=3)
    assert full.n_trees < 30                # it actually stopped

    chunk_fn, n_chunks = _chunked_all(Xt, yt, 4)
    vfn, n_valid = _chunked_all(Xv, yv, 2)
    history = []
    streamed = fit_streaming(chunk_fn, n_chunks, cfg,
                             valid_chunk_fn=vfn, n_valid_chunks=n_valid,
                             eval_metric="logloss",
                             early_stopping_rounds=3, history=history)
    assert streamed.n_trees == full.n_trees
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  streamed.threshold_bin)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_streaming_device_early_stop_matches_host_streaming():
    """Device-resident val-pred early stopping (tpu) picks the same round
    as the host streaming path."""
    X, y = datasets.synthetic_binary(3072, n_features=8, seed=17)
    Xb, _ = quantize(X, n_bins=31, seed=17)
    Xt, yt, Xv, yv = _val_split(Xb, y)
    cfg_h = TrainConfig(n_trees=30, max_depth=4, n_bins=31, backend="cpu",
                        learning_rate=0.9, min_split_gain=1e-3)
    chunk_fn, n_chunks = _chunked_all(Xt, yt, 4)
    vfn, n_valid = _chunked_all(Xv, yv, 2)
    host = fit_streaming(chunk_fn, n_chunks, cfg_h,
                         valid_chunk_fn=vfn, n_valid_chunks=n_valid,
                         eval_metric="logloss", early_stopping_rounds=3)
    dev = fit_streaming(chunk_fn, n_chunks, cfg_h.replace(backend="tpu"),
                        valid_chunk_fn=vfn, n_valid_chunks=n_valid,
                        eval_metric="logloss", early_stopping_rounds=3)
    assert host.n_trees == dev.n_trees
    np.testing.assert_array_equal(host.feature, dev.feature)


def test_streaming_early_stop_requires_validation():
    cfg = TrainConfig(n_trees=2, max_depth=2, backend="cpu")
    with pytest.raises(ValueError, match="valid_chunk_fn"):
        fit_streaming(lambda c: (np.zeros((4, 3), np.uint8), np.zeros(4)),
                      1, cfg, early_stopping_rounds=2)


def test_streaming_device_folded_pass_count():
    """Round-2 verdict item 6: the pred-update pass is folded into the
    next round's depth-0 pass (stream_round_start) — a T-round depth-D
    binary run reads each chunk exactly T*(D+1) times (D hist passes + 1
    leaf pass), with NO separate update passes; and the folded run stays
    bit-identical to in-memory training."""
    X, y = datasets.synthetic_binary(2048, n_features=8, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    calls = {"n": 0}

    def chunk_fn(c):
        calls["n"] += 1
        return Xb[c * 512:(c + 1) * 512], y[c * 512:(c + 1) * 512]

    chunk_fn.labels = lambda c: y[c * 512:(c + 1) * 512]   # pass 0 reads
    chunk_fn.n_features = 8                                # shape probe
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31, backend="tpu")
    streamed = fit_streaming(chunk_fn, 4, cfg, device_chunk_cache=False)
    assert calls["n"] == 4 * 3 * (4 + 1)      # chunks * rounds * (D+1)

    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin, streamed.threshold_bin)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)

    # Device chunk cache (round 4): the SAME run with the cache forced
    # on (explicit byte budget — on this CPU platform the True default
    # degrades to off, see below) reads each chunk from the host exactly
    # once — every later pass serves the device-resident buffer — and
    # the results are identical buffers-in, so identical out.
    calls["n"] = 0
    cached = fit_streaming(chunk_fn, 4, cfg, device_chunk_cache=1 << 30)
    assert calls["n"] == 4                          # one read per chunk
    np.testing.assert_array_equal(streamed.feature, cached.feature)
    np.testing.assert_array_equal(streamed.threshold_bin,
                                  cached.threshold_bin)
    np.testing.assert_array_equal(streamed.leaf_value, cached.leaf_value)

    # The True default on a CPU-platform run must NOT cache (the
    # "device" is host RAM — pinning the dataset would break the
    # O(chunk) host contract): read count matches the uncached run.
    calls["n"] = 0
    fit_streaming(chunk_fn, 4, cfg, device_chunk_cache=True)
    assert calls["n"] == 4 * 3 * (4 + 1)


def test_streaming_device_cache_budget():
    """A byte budget smaller than the dataset caches only the chunks that
    fit; the rest re-upload per pass. Results are unchanged."""
    X, y = datasets.synthetic_binary(2048, n_features=8, seed=9)
    Xb, _ = quantize(X, n_bins=31, seed=9)
    calls = {"n": 0}

    def chunk_fn(c):
        calls["n"] += 1
        return Xb[c * 512:(c + 1) * 512], y[c * 512:(c + 1) * 512]

    chunk_fn.labels = lambda c: y[c * 512:(c + 1) * 512]
    chunk_fn.n_features = 8
    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=31, backend="tpu")
    # Explicit budget = 2 chunks' bytes: chunks 0-1 cached, 2-3 re-read
    # per pass (an int budget is honored even on the CPU platform).
    budget = 2 * 512 * 8
    part = fit_streaming(chunk_fn, 4, cfg, device_chunk_cache=budget)
    passes = 2 * (3 + 1)                            # rounds * (D+1)
    assert calls["n"] == 2 + 2 * passes
    full = fit_streaming(chunk_fn, 4, cfg, device_chunk_cache=False)
    np.testing.assert_array_equal(part.feature, full.feature)
    np.testing.assert_array_equal(part.leaf_value, full.leaf_value)
