"""Drift observatory + champion/challenger shadow mode (ISSUE 19).

Covers the pipeline end to end: divergence math, the time-sliced
rolling window with latched alerts, the training-reference capture and
its npz round trip (including pre-drift artifacts loading with drift
OFF, never an error), the fleet wiring (health/metrics/debug/event
surfaces), shadow-mode bit-identity and misconfig rejection over HTTP,
/metrics read-only semantics with drift enabled, and the `report
drift` rollup with graceful degradation over pre-drift logs.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import feature_bincounts
from ddt_tpu.serve import drift as serve_drift
from ddt_tpu.serve.control import (FleetConfigError, FleetSpec,
                                   build_fleet)
from ddt_tpu.serve.drift import DriftTracker, divergence
from ddt_tpu.serve.metrics import parse_exposition, render_metrics
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry import report as tele_report
from ddt_tpu.telemetry.events import validate_event


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """Two models over the same bin space (champion + challenger) and a
    reference-less 'pre-drift era' artifact, shared module-wide."""
    X, y = datasets.synthetic_binary(3000, seed=11)
    kw = dict(n_trees=5, max_depth=3, n_bins=31, backend="tpu",
              log_every=10**9)
    champ = api.train(X, y, **kw)
    chall = api.train(X, y, learning_rate=0.05, **kw)
    td = tmp_path_factory.mktemp("drift_models")
    p_champ = str(td / "champ.npz")
    p_chall = str(td / "chall.npz")
    champ.save(p_champ)
    chall.save(p_chall)
    # A pre-drift artifact: same model, reference stripped before save —
    # byte-level what every artifact looked like before ISSUE 19.
    saved_ref = champ.mapper.ref_counts
    champ.mapper.ref_counts = None
    p_legacy = str(td / "legacy.npz")
    champ.save(p_legacy)
    champ.mapper.ref_counts = saved_ref
    cfg = TrainConfig(backend="tpu", n_bins=31)
    ref_scores = np.asarray(api.predict(
        champ.ensemble, X, mapper=champ.mapper, cfg=cfg))
    return dict(X=X, y=y, champ=champ, chall=chall, cfg=cfg,
                paths=dict(champ=p_champ, chall=p_chall,
                           legacy=p_legacy),
                ref_scores=ref_scores)


# --------------------------------------------------------------------- #
# divergence math
# --------------------------------------------------------------------- #
def test_divergence_identical_histograms_score_zero():
    rng = np.random.default_rng(0)
    ref = rng.integers(1, 100, size=(4, 8)).astype(np.int64)
    psi, js = divergence(ref, ref * 3)      # same shape, scaled counts
    assert psi.shape == js.shape == (4,)
    np.testing.assert_allclose(psi, 0.0, atol=1e-9)
    np.testing.assert_allclose(js, 0.0, atol=1e-9)


def test_divergence_disjoint_histograms_saturate():
    ref = np.zeros((1, 8), np.int64)
    win = np.zeros((1, 8), np.int64)
    ref[0, :4] = 100
    win[0, 4:] = 100
    psi, js = divergence(ref, win)
    assert psi[0] > 1.0                      # far past any threshold
    assert 0.99 < js[0] <= 1.0 + 1e-9        # JS base 2 is bounded [0,1]
    # JS is symmetric; PSI is too (its summand is symmetric in p,q)
    psi2, js2 = divergence(win, ref)
    np.testing.assert_allclose(js, js2, atol=1e-12)
    np.testing.assert_allclose(psi, psi2, atol=1e-12)


def test_divergence_matches_feature_bincounts_shapes():
    rng = np.random.default_rng(1)
    Xb = rng.integers(0, 16, size=(500, 6)).astype(np.uint8)
    counts = feature_bincounts(Xb, 16)
    assert counts.shape == (6, 16)
    assert counts.sum() == 500 * 6
    psi, js = divergence(counts, counts)
    np.testing.assert_allclose(psi, 0.0, atol=1e-9)


# --------------------------------------------------------------------- #
# DriftTracker: windowing, latched alerts, omit-don't-lie
# --------------------------------------------------------------------- #
def _batches(rng, lo, hi, rows, n_f):
    return rng.integers(lo, hi, size=(rows, n_f)).astype(np.uint8)


def test_tracker_below_min_rows_reports_none():
    rng = np.random.default_rng(2)
    ref = feature_bincounts(_batches(rng, 0, 8, 2000, 3), 16)
    trk = DriftTracker(ref, min_rows=256)
    assert trk.observe(0.0, _batches(rng, 0, 8, 100, 3)) is None
    st = trk.state(0.0)
    assert st["window_rows"] == 100
    assert st["psi_max"] is None and st["js_max"] is None
    assert trk.per_feature(0.0) is None
    assert not trk.has_pending()


def test_tracker_latched_alert_fires_once_and_rearms():
    rng = np.random.default_rng(3)
    ref = feature_bincounts(_batches(rng, 0, 8, 4000, 3), 16)
    trk = DriftTracker(ref, window_s=10.0, min_rows=64)
    # in-distribution traffic: scored, quiet
    assert trk.observe(0.0, _batches(rng, 0, 8, 300, 3)) is None
    st = trk.state(0.0)
    assert st["psi_max"] is not None and not st["alerting"]
    # shifted traffic (bins 8..16 the reference never saw): ONE latched
    # alert no matter how many shifted batches follow
    alert = trk.observe(1.0, _batches(rng, 8, 16, 600, 3))
    assert alert is not None and alert["psi_max"] >= trk.threshold
    assert alert["alerts"] == 1 and "feature" in alert
    for _ in range(5):
        assert trk.observe(1.5, _batches(rng, 8, 16, 200, 3)) is None
    assert trk.state(1.5)["alerting"] is True
    assert trk.state(1.5)["alerts"] == 1
    # the payload waits for a handler flush
    assert trk.has_pending()
    pend = trk.take_pending()
    assert len(pend) == 1 and pend[0] == alert
    assert not trk.has_pending() and trk.take_pending() == []
    # window expiry empties the ring -> scores vanish, alert re-arms
    st = trk.state(100.0)
    assert st["window_rows"] == 0 and st["psi_max"] is None
    assert st["alerting"] is False          # cooled below threshold
    alert2 = trk.observe(101.0, _batches(rng, 8, 16, 300, 3))
    assert alert2 is not None and alert2["alerts"] == 2


def test_tracker_ring_rotation_drops_only_expired_slices():
    rng = np.random.default_rng(4)
    ref = feature_bincounts(_batches(rng, 0, 8, 4000, 2), 16)
    trk = DriftTracker(ref, window_s=16.0, min_rows=1)  # 1 s per slice
    trk.observe(0.0, _batches(rng, 0, 8, 100, 2))
    trk.observe(8.0, _batches(rng, 0, 8, 50, 2))
    assert trk.state(8.0)["window_rows"] == 150
    # advance past the first slice's expiry but not the second's
    assert trk.state(17.0)["window_rows"] == 50
    assert trk.state(40.0)["window_rows"] == 0


def test_tracker_per_feature_attribution_sorts_worst_first():
    rng = np.random.default_rng(5)
    ref = feature_bincounts(_batches(rng, 0, 8, 4000, 3), 16)
    trk = DriftTracker(ref, min_rows=1)
    # shift ONLY feature 2
    Xb = _batches(rng, 0, 8, 500, 3)
    Xb[:, 2] = rng.integers(10, 16, size=500)
    trk.observe(0.0, Xb)
    pf = trk.per_feature(0.0)
    assert [r["feature"] for r in pf][0] == 2
    assert pf[0]["psi"] >= pf[-1]["psi"]
    assert trk.state(0.0)["feature"] == 2


def test_tracker_rejects_malformed_reference():
    with pytest.raises(ValueError, match="n_features"):
        DriftTracker(np.zeros(8, np.int64))


# --------------------------------------------------------------------- #
# reference capture + artifact round trip
# --------------------------------------------------------------------- #
def test_train_captures_reference_and_npz_round_trips(trained, tmp_path):
    mapper = trained["champ"].mapper
    ref = mapper.ref_counts
    assert ref is not None and ref.dtype == np.int64
    assert ref.shape == (mapper.n_features, mapper.n_bins)
    assert ref.sum() == 3000 * mapper.n_features   # every cell counted
    bundle = api.load_model(trained["paths"]["champ"])
    np.testing.assert_array_equal(bundle.mapper.ref_counts, ref)


def test_pre_drift_artifact_loads_with_drift_off(trained):
    """A reference-less artifact is the pre-ISSUE-19 on-disk format:
    it must load cleanly and serve with drift tracking silently OFF."""
    bundle = api.load_model(trained["paths"]["legacy"])
    assert bundle.mapper.ref_counts is None
    eng = build_fleet([FleetSpec(name="old", ref=trained["paths"]
                                 ["legacy"])], backend="tpu")
    try:
        eng.predict(trained["X"][:4], model="old", timeout=60.0)
        h = eng.health()["models"]["old"]
        assert "drift_psi_max" not in h        # schema-additive absence
        assert eng.metrics_snapshot()["models"]["old"]["drift"] is None
        dbg = eng.debug_drift()["models"]["old"]
        assert dbg["reference"] is False and "state" not in dbg
    finally:
        eng.close()


def test_drift_required_on_referenceless_artifact_is_config_error(
        trained):
    with pytest.raises(FleetConfigError, match="reference"):
        build_fleet([FleetSpec(name="old", ref=trained["paths"]
                               ["legacy"], drift=True)], backend="tpu")


def test_drift_false_disables_despite_reference(trained):
    eng = build_fleet([FleetSpec(name="m", ref=trained["paths"]["champ"],
                                 drift=False)], backend="tpu")
    try:
        eng.predict(trained["X"][:4], model="m", timeout=60.0)
        assert "drift_psi_max" not in eng.health()["models"]["m"]
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# fleet end to end: event, health, /metrics, debug, report
# --------------------------------------------------------------------- #
def test_fleet_drift_surfaces_end_to_end(trained, tmp_path):
    """Shifted traffic on a reference-carrying model lights up every
    surface — run-log event, healthz, metrics exposition (with a
    parse_exposition round trip), /debug/drift, report drift — while
    an un-shifted control model on the same fleet stays quiet."""
    path = str(tmp_path / "drift.jsonl")
    eng = build_fleet(
        [FleetSpec(name="hot", ref=trained["paths"]["champ"]),
         FleetSpec(name="cool", ref=trained["paths"]["chall"])],
        backend="tpu", max_wait_ms=5.0, run_log=path)
    X = trained["X"]
    try:
        # control: in-distribution traffic only
        for i in range(0, 600, 100):
            eng.predict(X[i:i + 100], model="cool", timeout=60.0)
        # target: enough shifted rows to clear MIN_ROWS and latch
        shifted = X + 5.0 * np.abs(X).max(axis=0)
        for i in range(0, 600, 100):
            eng.predict(shifted[i:i + 100], model="hot", timeout=60.0)

        h = eng.health()["models"]
        assert h["hot"]["drift_alerting"] is True
        assert h["hot"]["drift_alerts"] == 1
        assert h["hot"]["drift_psi_max"] >= serve_drift.PSI_ALERT
        assert h["hot"]["drift_window_rows"] >= serve_drift.MIN_ROWS
        assert h["cool"]["drift_alerting"] is False
        assert h["cool"]["drift_alerts"] == 0

        # exposition + round trip
        text = render_metrics(tele_counters.snapshot(),
                              eng.metrics_snapshot())
        parsed = parse_exposition(text)

        def series(name, model):
            return parsed[name][frozenset({("model", model)})]

        assert series("ddt_drift_alerting", "hot") == 1.0
        assert series("ddt_drift_alerting", "cool") == 0.0
        assert series("ddt_drift_model_alerts_total", "hot") == 1.0
        assert series("ddt_drift_psi_max", "hot") >= serve_drift.PSI_ALERT
        assert series("ddt_drift_js_max", "hot") <= 1.0
        for name in ("hot", "cool"):
            assert series("ddt_drift_psi_threshold", name) \
                == serve_drift.PSI_ALERT

        # per-feature attribution
        dbg = eng.debug_drift()["models"]["hot"]
        assert dbg["reference"] is True
        assert dbg["state"]["alerting"] is True
        assert dbg["per_feature"][0]["psi"] >= dbg["per_feature"][-1]["psi"]

        # windows carry the drift extras and validate against the schema
        emitted = eng.emit_latency(reset=True)
        assert emitted["hot"]["drift_alerting"] is True
        assert emitted["cool"]["drift_alerting"] is False
        for s in emitted.values():
            validate_event({"event": "serve_latency", "schema": 5,
                            "t": 0.0, "seq": 0, **s})
    finally:
        eng.close()

    events = tele_report.read_events(path)
    drifts = [e for e in events if e["event"] == "drift"]
    assert len(drifts) == 1 and drifts[0]["model_name"] == "hot"
    assert drifts[0]["psi_max"] >= serve_drift.PSI_ALERT
    for e in drifts:
        validate_event(e)
    # the counter moved, and its direction is registered lower-is-better
    assert tele_counters.snapshot()["drift_alerts"] >= 1
    from ddt_tpu.telemetry.diffing import COUNTER_DIRECTIONS
    assert COUNTER_DIRECTIONS["drift_alerts"] == "lower"

    summary = tele_report.summarize(events)
    dr = summary["drift"]
    assert dr["models"]["hot"]["alerts"] == 1
    assert dr["models"]["hot"]["alerting"] is True
    assert dr["models"]["cool"]["alerts"] == 0
    rendered = tele_report.render_drift(summary)
    assert "hot" in rendered and "ALERTING" in rendered
    assert "drift:" in tele_report.render(summary)


# --------------------------------------------------------------------- #
# shadow mode
# --------------------------------------------------------------------- #
def _shadow_fleet(trained, **kw):
    return build_fleet(
        [FleetSpec(name="champ", ref=trained["paths"]["champ"]),
         FleetSpec(name="chall", ref=trained["paths"]["chall"],
                   shadow_of="champ")],
        backend="tpu", max_wait_ms=5.0, **kw)


def test_shadow_champion_responses_bit_identical_to_shadow_off(trained):
    """THE acceptance pin: attaching a challenger changes nothing about
    what the champion's clients see — scores are bit-identical to a
    shadow-less fleet on the same traffic."""
    X = trained["X"]
    eng_off = build_fleet(
        [FleetSpec(name="champ", ref=trained["paths"]["champ"])],
        backend="tpu", max_wait_ms=5.0)
    try:
        base = [np.asarray(eng_off.predict(X[i:i + 64], model="champ",
                                           timeout=60.0))
                for i in range(0, 512, 64)]
    finally:
        eng_off.close()
    eng_on = _shadow_fleet(trained)
    try:
        shadowed = [np.asarray(eng_on.predict(X[i:i + 64], model="champ",
                                              timeout=60.0))
                    for i in range(0, 512, 64)]
    finally:
        eng_on.close()
    for a, b in zip(base, shadowed):
        np.testing.assert_array_equal(a, b)     # bit-identical


def test_shadow_scores_champion_traffic_off_response_path(trained):
    eng = _shadow_fleet(trained)
    X = trained["X"]
    try:
        eng.n_features_for("chall")             # force-resident
        for i in range(0, 512, 64):
            eng.predict(X[i:i + 64], model="champ", timeout=60.0)
        # the scorer thread drains asynchronously — poll, don't race
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            s = eng.health()["models"]["champ"]["shadow"]
            if s["rows"] >= 64 and s["mean_abs_diff"] is not None:
                break
            time.sleep(0.05)
        assert s["model"] == "chall" and s["champion"] == "champ"
        assert s["rows"] >= 64
        # different learning rates -> genuinely different predictions
        assert s["mean_abs_diff"] > 0
        assert s["ms_p50"] is not None and s["errors"] == 0
        assert eng.health()["models"]["chall"]["shadow_of"] == "champ"
        # metrics exposition carries the {model,shadow} series
        parsed = parse_exposition(render_metrics(
            tele_counters.snapshot(), eng.metrics_snapshot()))
        labels = frozenset({("model", "champ"), ("shadow", "chall")})
        assert parsed["ddt_shadow_scored_rows_total"][labels] >= 64
        assert parsed["ddt_shadow_mean_abs_diff"][labels] > 0
        # windows carry the shadow extras
        w = eng.emit_latency(reset=True)["champ"]
        assert w["shadow_model"] == "chall" and w["shadow_rows"] >= 64
        validate_event({"event": "serve_latency", "schema": 5,
                        "t": 0.0, "seq": 0, **w})
    finally:
        eng.close()


def test_shadow_skips_not_loads_an_evicted_challenger(trained):
    """The scorer must never do file I/O: a non-resident challenger
    means skipped batches, not a load from the shadow thread."""
    eng = _shadow_fleet(trained, preload=False)
    X = trained["X"]
    try:
        eng.n_features_for("champ")             # champion only
        for i in range(0, 256, 64):
            eng.predict(X[i:i + 64], model="champ", timeout=60.0)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            s = eng.health()["models"]["champ"]["shadow"]
            if s["skipped"] >= 1:
                break
            time.sleep(0.05)
        assert s["skipped"] >= 1 and s["rows"] == 0
        assert s["mean_abs_diff"] is None       # omit, don't lie
        assert eng.health()["models"]["chall"]["resident"] is False
    finally:
        eng.close()


def test_shadow_drop_on_full_never_blocks():
    """Unit-level: a stuffed queue drops (counted) instead of growing
    or blocking the caller."""
    class _Slot:
        model = None
    sc = serve_drift.ShadowScorer("c", "m", _Slot(), time.monotonic)
    try:
        with sc._cv:                            # freeze the drain
            for i in range(serve_drift.ShadowScorer.QUEUE_CAP + 3):
                if len(sc._q) >= sc.QUEUE_CAP:
                    sc._dropped += 1
                else:
                    sc._q.append((np.zeros((1, 2), np.uint8), [0.0]))
            assert sc._dropped == 3
            assert len(sc._q) == sc.QUEUE_CAP
    finally:
        sc.close()
    assert sc.summary()["dropped"] == 3


def test_shadow_topology_validation(trained):
    p = trained["paths"]
    # dangling champion
    with pytest.raises(FleetConfigError, match="shadow_of"):
        build_fleet([FleetSpec(name="a", ref=p["champ"],
                               shadow_of="ghost")], backend="tpu")
    # chains refused
    with pytest.raises(FleetConfigError, match="chain|shadow"):
        build_fleet([FleetSpec(name="a", ref=p["champ"]),
                     FleetSpec(name="b", ref=p["chall"], shadow_of="a"),
                     FleetSpec(name="c", ref=p["chall"], shadow_of="b")],
                    backend="tpu")
    # one challenger per champion
    with pytest.raises(FleetConfigError, match="challenger"):
        build_fleet([FleetSpec(name="a", ref=p["champ"]),
                     FleetSpec(name="b", ref=p["chall"], shadow_of="a"),
                     FleetSpec(name="c", ref=p["chall"], shadow_of="a")],
                    backend="tpu")


def test_remove_shadowed_champion_refused_until_shadow_goes(trained):
    eng = _shadow_fleet(trained)
    try:
        with pytest.raises(ValueError, match="shadow"):
            eng.remove_model("champ")
        eng.remove_model("chall")               # detaches cleanly
        assert "shadow" not in eng.health()["models"]["champ"]
        eng.remove_model("champ")               # now removable
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# HTTP: structured errors + read-only /metrics with drift live
# --------------------------------------------------------------------- #
def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _get_raw(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.read().decode()


@pytest.fixture()
def served_drift_fleet(trained):
    from ddt_tpu.serve.http import serve_forever

    eng = build_fleet(
        [FleetSpec(name="champ", ref=trained["paths"]["champ"]),
         FleetSpec(name="chall", ref=trained["paths"]["chall"],
                   shadow_of="champ")],
        backend="tpu", max_wait_ms=5.0)
    ready = threading.Event()
    th = threading.Thread(target=serve_forever, args=(eng,),
                          kwargs=dict(port=0, ready_event=ready),
                          daemon=True)
    th.start()
    assert ready.wait(60)
    yield eng, eng.http_port
    try:
        _post(eng.http_port, "/shutdown", {})
    except OSError:
        pass
    th.join(30)


def test_http_drift_misconfig_is_structured_400_never_500(
        served_drift_fleet, trained):
    eng, port = served_drift_fleet
    # drift=true on a reference-less artifact
    try:
        _post(port, "/models", {"action": "add", "name": "old",
                                "ref": trained["paths"]["legacy"],
                                "drift": True})
        raise AssertionError("reference-less drift=true accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        body = json.loads(e.read())
        assert "error" in body and "reference" in body["error"]
    # second challenger on an already-shadowed champion
    try:
        _post(port, "/models", {"action": "add", "name": "c2",
                                "ref": trained["paths"]["chall"],
                                "shadow_of": "champ"})
        raise AssertionError("second challenger accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "error" in json.loads(e.read())
    # dangling shadow_of
    try:
        _post(port, "/models", {"action": "add", "name": "c3",
                                "ref": trained["paths"]["chall"],
                                "shadow_of": "ghost"})
        raise AssertionError("dangling shadow_of accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    # the fleet is intact after every rejection
    assert set(json.loads(_get_raw(port, "/healthz"))["models"]) \
        == {"champ", "chall"}


def test_http_metrics_read_only_with_drift_enabled(served_drift_fleet,
                                                   trained):
    """Extends the ISSUE-17 pin to the drift era: scrapes never rotate
    the drift window, reset a tracker, or steal from the emit window."""
    eng, port = served_drift_fleet
    X = trained["X"]
    shifted = X + 5.0 * np.abs(X).max(axis=0)
    for i in range(0, 600, 100):
        _post(port, "/models/champ/predict",
              {"rows": shifted[i:i + 100].tolist()})
    a = _get_raw(port, "/metrics")
    dbg = json.loads(_get_raw(port, "/debug/drift"))
    assert dbg["fleet"] is True
    assert dbg["models"]["champ"]["state"]["alerting"] is True
    b = _get_raw(port, "/metrics")

    def drift_series(text):
        return {k: v for k, v in parse_exposition(text).items()
                if k.startswith("ddt_drift_")}

    # scrape-idempotent on the drift series: the scrapes (and the
    # /debug/drift read between them) rotated no window, reset no
    # tracker (shadow series are excluded — the scorer thread drains
    # its queue asynchronously between reads by design)
    da, db = drift_series(a), drift_series(b)
    assert da == db
    assert frozenset({("model", "champ")}) in da["ddt_drift_alerting"]
    # the emit window still owns all the traffic after two scrapes
    emitted = json.loads(_get_raw(port, "/models/champ/stats?emit=1"))
    assert emitted["requests"] == 6


# --------------------------------------------------------------------- #
# report: rollup + graceful degradation over pre-drift logs
# --------------------------------------------------------------------- #
def test_report_drift_degrades_gracefully_on_pre_drift_logs(tmp_path):
    """A v5-era log with no drift signal summarizes exactly as before
    (drift section absent) and `report drift` fails loudly — while the
    full report renders unchanged."""
    path = str(tmp_path / "old.jsonl")
    from ddt_tpu.telemetry.events import RunLog
    with RunLog(path) as rl:
        rl.emit("run_manifest", trainer="driver", backend="cpu",
                loss="logloss", n_trees=2, max_depth=3, rows=10,
                features=4)
        rl.emit("serve_latency", requests=10, p50_ms=1.0, p99_ms=2.0,
                p999_ms=3.0, max_ms=3.0, batches=2, coalesce_mean=5.0,
                coalesce_max=8, queue_depth_max=1, window_s=1.0,
                model_name="old")
        rl.emit("run_end", completed_rounds=0, wallclock_s=0.1)
    summary = tele_report.summarize(tele_report.read_events(path))
    assert summary.get("drift") is None
    with pytest.raises(ValueError, match="drift"):
        tele_report.render_drift(summary)
    rendered = tele_report.render(summary)
    assert "drift:" not in rendered
    assert "run:" in rendered                 # the full report is intact


def test_report_drift_rollup_joins_events_and_windows(tmp_path):
    path = str(tmp_path / "drift.jsonl")
    from ddt_tpu.telemetry.events import RunLog
    with RunLog(path) as rl:
        rl.emit("run_manifest", trainer="driver", backend="cpu",
                loss="logloss", n_trees=2, max_depth=3, rows=10,
                features=4)
        rl.emit("serve_latency", requests=600, p50_ms=1.0, p99_ms=2.0,
                p999_ms=3.0, max_ms=3.0, batches=6, coalesce_mean=100.0,
                coalesce_max=100, queue_depth_max=1, window_s=1.0,
                model_name="hot", drift_psi_max=0.9, drift_js_max=0.5,
                drift_alerting=True, shadow_model="ch",
                shadow_rows=512, shadow_mean_abs_diff=0.012,
                shadow_ms_p50=0.4)
        rl.emit("drift", model_name="hot", psi_max=0.9, js_max=0.5,
                psi_mean=0.4, feature=3, window_rows=600,
                window_s=300.0, threshold=0.25, alerts=1)
        rl.emit("run_end", completed_rounds=0, wallclock_s=0.1)
    summary = tele_report.summarize(tele_report.read_events(path))
    rec = summary["drift"]["models"]["hot"]
    assert rec["alerts"] == 1 and rec["worst_feature"] == 3
    assert rec["worst_psi_max"] == 0.9 and rec["threshold"] == 0.25
    assert rec["shadow"]["model"] == "ch"
    assert rec["shadow"]["mean_abs_diff"] == 0.012
    rendered = tele_report.render_drift(summary)
    assert "hot" in rendered and "ch" in rendered
    # --json path: the rollup is a plain JSON object
    json.dumps(summary["drift"])
