"""Distributed-training invariants on the 8-virtual-device CPU mesh.

SURVEY.md §4 "Distributed without a cluster": N-partition training (histogram
psum over the mesh axis) must produce the SAME trees as 1-partition training —
the allreduce is additively exact up to float ordering, and split selection is
bf16-tie-break deterministic (ops/split.py), so distribution must not change
results. This replaces the reference's multi-FPGA tests; the real-chip
multi-host path compiles the identical program (driver dryrun_multichip).
"""

import jax
import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver


def _fit(n_partitions, Xb, y, **kw):
    cfg = TrainConfig(
        n_trees=4, max_depth=4, n_bins=31, backend="tpu",
        n_partitions=n_partitions, **kw,
    )
    be = get_backend(cfg)
    return Driver(be, cfg, log_every=10**9).fit(Xb, y)


@pytest.mark.parametrize("n_partitions", [2, 4, 8])
def test_partitioned_equals_single(n_partitions):
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=11)
    Xb, _ = quantize(X, n_bins=31, seed=11)
    e1 = _fit(1, Xb, y)
    eN = _fit(n_partitions, Xb, y)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eN.is_leaf)
    np.testing.assert_allclose(e1.leaf_value, eN.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_partitioned_rows_not_divisible():
    """Row padding: R not a multiple of the partition count."""
    X, y = datasets.synthetic_binary(4001, n_features=8, seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    e1 = _fit(1, Xb, y)
    e8 = _fit(8, Xb, y)
    np.testing.assert_array_equal(e1.feature, e8.feature)
    np.testing.assert_array_equal(e1.threshold_bin, e8.threshold_bin)


@pytest.mark.parametrize("np_,fp", [(1, 2), (1, 4), (2, 2), (4, 2)])
def test_feature_parallel_equals_single(np_, fp):
    """2-D mesh (rows x features): column-sharded histogramming + gathered
    split argmax + psum row routing must grow identical trees (SURVEY.md §2
    'Parallelism strategies': the optional features axis)."""
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=21)
    Xb, _ = quantize(X, n_bins=31, seed=21)
    e1 = _fit(1, Xb, y)
    eN = _fit(np_, Xb, y, feature_partitions=fp)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eN.is_leaf)
    np.testing.assert_allclose(e1.leaf_value, eN.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_feature_parallel_pads_nondivisible_columns():
    """F=9 over 4 feature shards: padded all-zero columns are never chosen."""
    X, y = datasets.synthetic_binary(2048, n_features=9, seed=23)
    Xb, _ = quantize(X, n_bins=31, seed=23)
    e1 = _fit(1, Xb, y)
    eN = _fit(2, Xb, y, feature_partitions=4)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    assert e1.feature.max() < 9
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)


def test_feature_parallel_softmax():
    X, y = datasets.synthetic_multiclass(2000, n_features=12, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    e1 = _fit(1, Xb, y, loss="softmax", n_classes=7)
    eN = _fit(2, Xb, y, loss="softmax", n_classes=7, feature_partitions=2)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)


def test_partitioned_softmax():
    X, y = datasets.synthetic_multiclass(2000, n_features=12, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    e1 = _fit(1, Xb, y, loss="softmax", n_classes=7)
    e4 = _fit(4, Xb, y, loss="softmax", n_classes=7)
    np.testing.assert_array_equal(e1.feature, e4.feature)
    np.testing.assert_array_equal(e1.threshold_bin, e4.threshold_bin)


@pytest.mark.parametrize("hp,np_,fp", [(2, 4, 1), (2, 2, 2), (4, 2, 1),
                                       (8, 1, 1)])
def test_pod_mesh_equals_single(hp, np_, fp):
    """The DCN story (SURVEY.md §5 'Distributed communication backend',
    BASELINE config 5): a (hosts, rows[, features]) pod mesh — psum over
    BOTH row axes — grows bit-identical trees to a single chip."""
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=31)
    Xb, _ = quantize(X, n_bins=31, seed=31)
    e1 = _fit(1, Xb, y)
    eP = _fit(np_, Xb, y, host_partitions=hp, feature_partitions=fp)
    np.testing.assert_array_equal(e1.feature, eP.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eP.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eP.is_leaf)
    np.testing.assert_allclose(e1.leaf_value, eP.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_pod_mesh_from_make_pod_mesh():
    """TPUDevice consumes an externally built parallel.mesh.make_pod_mesh
    (the multi-host entry path: jax.distributed.initialize + make_pod_mesh
    + TPUDevice(cfg, mesh=...))."""
    from ddt_tpu.backends.tpu import TPUDevice
    from ddt_tpu.parallel.mesh import make_pod_mesh

    mesh = make_pod_mesh(n_hosts=2, devices_per_host=4)
    assert mesh.axis_names == ("hosts", "rows")
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=31, backend="tpu")
    be = TPUDevice(cfg, mesh=mesh)
    assert be.host_partitions == 2 and be.n_partitions == 4
    assert be.row_shards == 8

    X, y = datasets.synthetic_binary(4096, n_features=10, seed=31)
    Xb, _ = quantize(X, n_bins=31, seed=31)
    e1 = _fit(1, Xb, y)
    eP = Driver(be, cfg, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(e1.feature, eP.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eP.threshold_bin)


def test_pod_mesh_softmax_and_nondivisible_rows():
    X, y = datasets.synthetic_multiclass(2003, n_features=12, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    e1 = _fit(1, Xb, y, loss="softmax", n_classes=7)
    eP = _fit(2, Xb, y, loss="softmax", n_classes=7, host_partitions=2)
    np.testing.assert_array_equal(e1.feature, eP.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eP.threshold_bin)


def test_pod_predict_raw():
    """Row-sharded inference over the (hosts, rows) mesh."""
    X, y = datasets.synthetic_binary(3000, n_features=10, seed=2)
    Xb, _ = quantize(X, n_bins=31, seed=2)
    res = api.train(Xb, y, binned=True, n_trees=6, max_depth=4, n_bins=31,
                    backend="cpu", log_every=10**9)
    cfg = TrainConfig(backend="tpu", host_partitions=2, n_partitions=4,
                      n_bins=31)
    be = get_backend(cfg)
    got = be.predict_raw(res.ensemble, Xb)
    want = res.ensemble.predict_raw(Xb, binned=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_distributed_histogram_is_global():
    """The granular L4 kernel includes the cross-partition allreduce: the
    sharded histogram equals the single-device histogram of all rows."""
    from ddt_tpu.reference import numpy_trainer as ref

    rng = np.random.default_rng(7)
    R, F, B, N = 4096, 5, 16, 4
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(-1, N, size=R).astype(np.int32)

    cfg = TrainConfig(backend="tpu", n_bins=B, n_partitions=8)
    be = get_backend(cfg)
    data = be.upload(Xb)
    got = np.asarray(be.build_histograms(data, g, h, ni, N))
    want = ref.build_histograms(Xb, g, h, ni, N, B)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_mesh_uses_requested_devices():
    cfg = TrainConfig(backend="tpu", n_partitions=8)
    be = get_backend(cfg)
    assert be.distributed
    assert be.mesh.devices.size == 8
    assert be.mesh.axis_names == ("rows", "features")
    assert be.mesh.shape == {"rows": 8, "features": 1}
    with pytest.raises(ValueError, match="devices"):
        get_backend(TrainConfig(backend="tpu", n_partitions=16))


def test_predict_raw_distributed():
    """Row-sharded batch inference equals NumPy oracle scoring."""
    X, y = datasets.synthetic_binary(3000, n_features=10, seed=2)
    Xb, mapper = quantize(X, n_bins=31, seed=2)
    res = api.train(Xb, y, binned=True, n_trees=6, max_depth=4, n_bins=31,
                    backend="cpu", log_every=10**9)
    cfg = TrainConfig(backend="tpu", n_partitions=8, n_bins=31)
    be = get_backend(cfg)
    got = be.predict_raw(res.ensemble, Xb)
    want = res.ensemble.predict_raw(Xb, binned=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


# --------------------------------------------------------------------------- #
# Compiled-collective contract (round-4 verdict item 2; comms inventory
# updated for ISSUE 10's reduce-scatter split finding). The pod-scale
# extrapolation rests on the property that the ONLY cross-device traffic in
# tree growth is (a) the histogram collective over the row axes — a psum
# under split_comms=allreduce, a reduce-scatter (at most histogram-sized)
# under the reduce_scatter default, (b) the tiny per-level split-winner
# all_gather — over the feature axis on column-sharded meshes, over the ROW
# axes under reduce-scatter split finding (never both in one program),
# (c) node-aggregate / loss psums over the row axes, and (d) the [R_loc]
# winning-column-value psum over the feature axis (ops/grow.py routing).
# Bit-identity tests cannot catch an accidental row-sized all_gather — on a
# one-host virtual mesh it is merely slow, not wrong — so these tests pin
# the compiled program's collective inventory itself: they FAIL if any new
# collective kind appears, if any gather grows beyond split-winner size, or
# if a row-sized operand rides a row-axis collective.
# --------------------------------------------------------------------------- #

import re  # noqa: E402

_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "all-to-all",
                     "collective-permute", "collective-broadcast",
                     "reduce-scatter")
_COLL_RE = re.compile(
    r"=\s+(?P<res>\(.*?\)|\S+)\s+(?P<kind>%s)(?:-start)?\("
    % "|".join(_COLLECTIVE_KINDS))
_SHAPE_RE = re.compile(r"\[([0-9,]*)\]")
_GROUP_RE = re.compile(r"replica_groups=\{(\{[0-9,{}]*\})\}")
# XLA's compact iota form: replica_groups=[G,S]<=[d0,d1,...]T(p0,p1,...)
# meaning arange(prod(d)).reshape(d).transpose(p).reshape(G, S).
_IOTA_GROUP_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")


def _parse_groups(ln):
    """frozenset of sorted device-id tuples from either replica_groups
    syntax, or None if the line carries neither."""
    gm = _GROUP_RE.search(ln)
    if gm is not None:
        return frozenset(
            tuple(sorted(int(x) for x in grp.split(",")))
            for grp in re.findall(r"\{([0-9,]+)\}", gm.group(1))
        ) or None
    im = _IOTA_GROUP_RE.search(ln)
    if im is not None:
        g, s = int(im.group(1)), int(im.group(2))
        dims = [int(x) for x in im.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if im.group(4):
            ids = ids.transpose([int(x) for x in im.group(4).split(",")])
        return frozenset(
            tuple(sorted(int(x) for x in row))
            for row in ids.reshape(g, s)
        )
    return None


def _collective_inventory(hlo_text):
    """[(kind, [shape tuples], frozenset of device-id groups)] from compiled
    HLO. Parsing is strict: a collective line whose replica_groups cannot be
    read fails the test rather than being skipped."""
    out = []
    for ln in hlo_text.splitlines():
        m = _COLL_RE.search(ln)
        if m is None or "get-tuple-element" in ln:
            continue
        shapes = [
            tuple(int(d) for d in s.split(",") if d)
            for s in _SHAPE_RE.findall(m.group("res"))
        ]
        groups = _parse_groups(ln)
        assert groups, f"unparseable replica_groups in: {ln.strip()}"
        out.append((m.group("kind"), shapes, groups))
    return out


def _mesh_groups(be):
    """(row_axis_groups, feature_axis_groups, all_axis_groups) as
    frozensets of sorted device-id tuples, derived from the backend's
    own mesh layout. all_axis_groups is the single whole-mesh group the
    2D winner combine gathers over (rows x features in one pass)."""
    ids = np.vectorize(lambda d: d.id)(be.mesh.devices)
    f = be.feature_partitions
    flat = ids.reshape(-1, f)
    feature_groups = frozenset(tuple(sorted(row)) for row in flat)
    row_groups = frozenset(tuple(sorted(flat[:, i])) for i in range(f))
    all_groups = frozenset({tuple(sorted(ids.flat))})
    return row_groups, feature_groups, all_groups


def _numel(shape):
    n = 1
    for d in shape:
        n *= d
    return n


def _assert_collective_contract(hlo_text, be, *, r_loc, f_loc, n_bins,
                                max_depth):
    row_groups, feature_groups, all_groups = _mesh_groups(be)
    n_level = 1 << max_depth
    # Any operand this big is "row-sized" — between the largest legitimate
    # row-axis payload (one level's histograms) and the smallest per-shard
    # row count the test uses. f_loc is the per-FEATURE-SHARD column
    # count, so a feature-column-sized operand on the wrong axis trips
    # the same caps (both row-sized and feature-column-sized operands
    # are forbidden outside the patterns below).
    hist_cap = n_level * f_loc * n_bins * 2
    assert hist_cap < r_loc, "test shapes must separate hist from row size"
    inv = _collective_inventory(hlo_text)
    assert inv, "distributed program lowered with no collectives at all"
    rs = getattr(be, "split_comms", "allreduce") == "reduce_scatter"
    fp = be.feature_partitions
    for kind, shapes, groups in inv:
        desc = f"{kind} {shapes} groups={sorted(groups)}"
        assert kind in ("all-reduce", "all-gather", "reduce-scatter"), \
            f"forbidden collective kind: {desc}"
        allowed = {row_groups, feature_groups}
        if rs and fp > 1:
            allowed.add(all_groups)    # the 2D winner combine
        assert groups in allowed, \
            f"collective over unexpected device groups: {desc}"
        if kind == "reduce-scatter":
            # Only the histogram slab scatter over the row axes, only
            # when reduce-scatter split finding is resolved on; the
            # (scattered) result is at most slab-sized. On the 2D mesh
            # the scatter stays WITHIN each feature slab — row groups,
            # never the whole mesh.
            assert rs, f"reduce-scatter without split_comms=rs: {desc}"
            assert groups == row_groups, \
                f"reduce-scatter outside the row axes: {desc}"
            for s in shapes:
                assert r_loc not in s and _numel(s) <= hist_cap, \
                    f"oversized reduce-scatter operand: {desc}"
        elif kind == "all-gather":
            # Only the per-level split-winner gather (gain/feat/bin/dir
            # tuples): over the feature axis on column-sharded meshes,
            # over the ROW axes under reduce-scatter split finding, and
            # over BOTH axes at once on the 2D rs mesh (every shard
            # owns a distinct global column slab — one combine) —
            # [n_shards, n_level] at most in every form.
            if rs and fp > 1:
                assert groups == all_groups, \
                    f"2D winner gather outside the full mesh: {desc}"
                cap = be.row_shards * fp * n_level
            elif rs:
                assert groups == row_groups, \
                    f"all-gather outside the row axes under rs: {desc}"
                cap = be.row_shards * n_level
            else:
                assert groups == feature_groups != row_groups, \
                    f"all-gather outside the feature axis: {desc}"
                cap = fp * n_level
            for s in shapes:
                assert _numel(s) <= cap, \
                    f"all-gather operand beyond split-winner size: {desc}"
        elif groups == feature_groups and feature_groups != row_groups:
            # Feature-axis psum: the [R_loc] winning-column routing value
            # (exactly one shard owns each winning column) or smaller
            # node-level aggregates. Anything bigger would be a new
            # feature-axis traffic pattern — review scaling before allowing.
            for s in shapes:
                assert s == (r_loc,) or _numel(s) <= hist_cap, \
                    f"unexpected feature-axis all-reduce operand: {desc}"
        else:
            # Row/host-axis psum: histograms + node/loss aggregates only.
            # A row-sized operand here is exactly the pod-scaling bug this
            # test exists to catch.
            for s in shapes:
                assert r_loc not in s and _numel(s) <= hist_cap, \
                    f"row-sized operand on a row-axis collective: {desc}"


_MESH_CASES = [
    dict(n_partitions=8),
    dict(host_partitions=2, n_partitions=4),
    dict(host_partitions=2, n_partitions=2, feature_partitions=2),
    # The declarative 2D (rows x features) mesh (ISSUE 11): auto
    # resolves reduce_scatter COMPOSED with the feature axis — slab
    # scatter over row groups, ONE winner gather over the whole mesh.
    dict(mesh_shape=(4, 2)),
]

_MESH_IDS = ["rows8", "hosts2rows4", "hosts2rows2feat2", "mesh4x2"]


@pytest.mark.parametrize("mesh_kw", _MESH_CASES, ids=_MESH_IDS)
def test_grow_collective_inventory(mesh_kw):
    """The granular whole-tree grow program's compiled collectives match
    the contract for every supported mesh shape."""
    R, F, B, D = 32768, 8, 15, 4
    X, y = datasets.synthetic_binary(R, n_features=F, seed=31)
    Xb, _ = quantize(X, n_bins=B, seed=31)
    cfg = TrainConfig(n_trees=2, max_depth=D, n_bins=B, backend="tpu",
                      **mesh_kw)
    be = get_backend(cfg)
    data = be.upload(Xb)
    rng = np.random.default_rng(0)
    g = be._put_rows(rng.standard_normal(R).astype(np.float32))
    h = be._put_rows(rng.random(R).astype(np.float32))
    txt = be._grow_fn.lower(data, g, h).compile().as_text()
    r_shards = be.host_partitions * be.n_partitions
    _assert_collective_contract(
        txt, be, r_loc=R // r_shards, f_loc=F // be.feature_partitions,
        n_bins=B, max_depth=D)


@pytest.mark.parametrize("mesh_kw", _MESH_CASES, ids=_MESH_IDS)
def test_fused_rounds_collective_inventory(mesh_kw):
    """The fused multi-round scan (the production training path) compiles
    to the same collective inventory — the scan must not introduce any new
    cross-device traffic (e.g. a resharding gather of the prediction
    buffer between rounds)."""
    R, F, B, D = 32768, 8, 15, 4
    X, y = datasets.synthetic_binary(R, n_features=F, seed=33)
    Xb, _ = quantize(X, n_bins=B, seed=33)
    cfg = TrainConfig(n_trees=2, max_depth=D, n_bins=B, backend="tpu",
                      **mesh_kw)
    be = get_backend(cfg)
    data = be.upload(Xb)
    yl = be.upload_labels(y.astype(np.float32))
    pred = be.init_pred(yl, 0.0)
    fn = be._rounds_fns.get(2)
    if fn is None:
        fn = be._build_rounds_fn(2)
        be._rounds_fns[2] = fn
    txt = fn.lower(data, pred, yl.y, yl.valid).compile().as_text()
    r_shards = be.host_partitions * be.n_partitions
    _assert_collective_contract(
        txt, be, r_loc=R // r_shards, f_loc=F // be.feature_partitions,
        n_bins=B, max_depth=D)
