"""Distributed-training invariants on the 8-virtual-device CPU mesh.

SURVEY.md §4 "Distributed without a cluster": N-partition training (histogram
psum over the mesh axis) must produce the SAME trees as 1-partition training —
the allreduce is additively exact up to float ordering, and split selection is
bf16-tie-break deterministic (ops/split.py), so distribution must not change
results. This replaces the reference's multi-FPGA tests; the real-chip
multi-host path compiles the identical program (driver dryrun_multichip).
"""

import jax
import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver


def _fit(n_partitions, Xb, y, **kw):
    cfg = TrainConfig(
        n_trees=4, max_depth=4, n_bins=31, backend="tpu",
        n_partitions=n_partitions, **kw,
    )
    be = get_backend(cfg)
    return Driver(be, cfg, log_every=10**9).fit(Xb, y)


@pytest.mark.parametrize("n_partitions", [2, 4, 8])
def test_partitioned_equals_single(n_partitions):
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=11)
    Xb, _ = quantize(X, n_bins=31, seed=11)
    e1 = _fit(1, Xb, y)
    eN = _fit(n_partitions, Xb, y)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eN.is_leaf)
    np.testing.assert_allclose(e1.leaf_value, eN.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_partitioned_rows_not_divisible():
    """Row padding: R not a multiple of the partition count."""
    X, y = datasets.synthetic_binary(4001, n_features=8, seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    e1 = _fit(1, Xb, y)
    e8 = _fit(8, Xb, y)
    np.testing.assert_array_equal(e1.feature, e8.feature)
    np.testing.assert_array_equal(e1.threshold_bin, e8.threshold_bin)


@pytest.mark.parametrize("np_,fp", [(1, 2), (1, 4), (2, 2), (4, 2)])
def test_feature_parallel_equals_single(np_, fp):
    """2-D mesh (rows x features): column-sharded histogramming + gathered
    split argmax + psum row routing must grow identical trees (SURVEY.md §2
    'Parallelism strategies': the optional features axis)."""
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=21)
    Xb, _ = quantize(X, n_bins=31, seed=21)
    e1 = _fit(1, Xb, y)
    eN = _fit(np_, Xb, y, feature_partitions=fp)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eN.is_leaf)
    np.testing.assert_allclose(e1.leaf_value, eN.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_feature_parallel_pads_nondivisible_columns():
    """F=9 over 4 feature shards: padded all-zero columns are never chosen."""
    X, y = datasets.synthetic_binary(2048, n_features=9, seed=23)
    Xb, _ = quantize(X, n_bins=31, seed=23)
    e1 = _fit(1, Xb, y)
    eN = _fit(2, Xb, y, feature_partitions=4)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    assert e1.feature.max() < 9
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)


def test_feature_parallel_softmax():
    X, y = datasets.synthetic_multiclass(2000, n_features=12, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    e1 = _fit(1, Xb, y, loss="softmax", n_classes=7)
    eN = _fit(2, Xb, y, loss="softmax", n_classes=7, feature_partitions=2)
    np.testing.assert_array_equal(e1.feature, eN.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)


def test_partitioned_softmax():
    X, y = datasets.synthetic_multiclass(2000, n_features=12, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    e1 = _fit(1, Xb, y, loss="softmax", n_classes=7)
    e4 = _fit(4, Xb, y, loss="softmax", n_classes=7)
    np.testing.assert_array_equal(e1.feature, e4.feature)
    np.testing.assert_array_equal(e1.threshold_bin, e4.threshold_bin)


@pytest.mark.parametrize("hp,np_,fp", [(2, 4, 1), (2, 2, 2), (4, 2, 1),
                                       (8, 1, 1)])
def test_pod_mesh_equals_single(hp, np_, fp):
    """The DCN story (SURVEY.md §5 'Distributed communication backend',
    BASELINE config 5): a (hosts, rows[, features]) pod mesh — psum over
    BOTH row axes — grows bit-identical trees to a single chip."""
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=31)
    Xb, _ = quantize(X, n_bins=31, seed=31)
    e1 = _fit(1, Xb, y)
    eP = _fit(np_, Xb, y, host_partitions=hp, feature_partitions=fp)
    np.testing.assert_array_equal(e1.feature, eP.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eP.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eP.is_leaf)
    np.testing.assert_allclose(e1.leaf_value, eP.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_pod_mesh_from_make_pod_mesh():
    """TPUDevice consumes an externally built parallel.mesh.make_pod_mesh
    (the multi-host entry path: jax.distributed.initialize + make_pod_mesh
    + TPUDevice(cfg, mesh=...))."""
    from ddt_tpu.backends.tpu import TPUDevice
    from ddt_tpu.parallel.mesh import make_pod_mesh

    mesh = make_pod_mesh(n_hosts=2, devices_per_host=4)
    assert mesh.axis_names == ("hosts", "rows")
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=31, backend="tpu")
    be = TPUDevice(cfg, mesh=mesh)
    assert be.host_partitions == 2 and be.n_partitions == 4
    assert be.row_shards == 8

    X, y = datasets.synthetic_binary(4096, n_features=10, seed=31)
    Xb, _ = quantize(X, n_bins=31, seed=31)
    e1 = _fit(1, Xb, y)
    eP = Driver(be, cfg, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(e1.feature, eP.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eP.threshold_bin)


def test_pod_mesh_softmax_and_nondivisible_rows():
    X, y = datasets.synthetic_multiclass(2003, n_features=12, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    e1 = _fit(1, Xb, y, loss="softmax", n_classes=7)
    eP = _fit(2, Xb, y, loss="softmax", n_classes=7, host_partitions=2)
    np.testing.assert_array_equal(e1.feature, eP.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eP.threshold_bin)


def test_pod_predict_raw():
    """Row-sharded inference over the (hosts, rows) mesh."""
    X, y = datasets.synthetic_binary(3000, n_features=10, seed=2)
    Xb, _ = quantize(X, n_bins=31, seed=2)
    res = api.train(Xb, y, binned=True, n_trees=6, max_depth=4, n_bins=31,
                    backend="cpu", log_every=10**9)
    cfg = TrainConfig(backend="tpu", host_partitions=2, n_partitions=4,
                      n_bins=31)
    be = get_backend(cfg)
    got = be.predict_raw(res.ensemble, Xb)
    want = res.ensemble.predict_raw(Xb, binned=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_distributed_histogram_is_global():
    """The granular L4 kernel includes the cross-partition allreduce: the
    sharded histogram equals the single-device histogram of all rows."""
    from ddt_tpu.reference import numpy_trainer as ref

    rng = np.random.default_rng(7)
    R, F, B, N = 4096, 5, 16, 4
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(-1, N, size=R).astype(np.int32)

    cfg = TrainConfig(backend="tpu", n_bins=B, n_partitions=8)
    be = get_backend(cfg)
    data = be.upload(Xb)
    got = np.asarray(be.build_histograms(data, g, h, ni, N))
    want = ref.build_histograms(Xb, g, h, ni, N, B)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_mesh_uses_requested_devices():
    cfg = TrainConfig(backend="tpu", n_partitions=8)
    be = get_backend(cfg)
    assert be.distributed
    assert be.mesh.devices.size == 8
    assert be.mesh.axis_names == ("rows", "features")
    assert be.mesh.shape == {"rows": 8, "features": 1}
    with pytest.raises(ValueError, match="devices"):
        get_backend(TrainConfig(backend="tpu", n_partitions=16))


def test_predict_raw_distributed():
    """Row-sharded batch inference equals NumPy oracle scoring."""
    X, y = datasets.synthetic_binary(3000, n_features=10, seed=2)
    Xb, mapper = quantize(X, n_bins=31, seed=2)
    res = api.train(Xb, y, binned=True, n_trees=6, max_depth=4, n_bins=31,
                    backend="cpu", log_every=10**9)
    cfg = TrainConfig(backend="tpu", n_partitions=8, n_bins=31)
    be = get_backend(cfg)
    got = be.predict_raw(res.ensemble, Xb)
    want = res.ensemble.predict_raw(Xb, binned=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
