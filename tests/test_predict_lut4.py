"""int4 bit-packed TreeLUT tier (ops/predict_lut.py "int4 TIER"): the
pack/unpack round trip, the exactness contract, the extended error
bound, and the fallback ladder — pinned.

Exactness framing (the module doc spells it out): descent and the
per-leaf dequantize are exact at int4 width, but f32 SUMMATION ORDER
across trees belongs to XLA's fusion choices, the same slack every
kernel-parity contract in this repo carries (test_hist_fused pins its
bitwise claims on integer-valued inputs for exactly this reason). So:

1. BITWISE parity vs the f32 one-hot reference is pinned on EXACT-GRID
   models — leaf values on a power-of-two grid with the per-tree scale
   forced to exactly 1/8, where every product and partial sum is exact
   in f32 and summation order cannot matter. Swept across n_classes
   {1, 3} x missing x categorical x ragged trees/tiles x BOTH
   threshold regimes (nibble-packed <= 15-bin models and the lossless
   int8 form).
2. ERROR BOUND end to end on random-valued models: |lut4 - f32| <=
   QuantizedTables.max_abs_err (computed for the int4 rounding step)
   plus f32-accumulation slack only — and the dequantized reference
   sits within pure accumulation slack (1e-5 absolute), witnessing
   that the ONLY real error source is the documented rounding step.
3. PACK ROUND TRIP: unpacking PackedTables' nibble arrays host-side
   reproduces thr/leaf_q bit-for-bit (two's-complement low nibbles,
   threshold sentinel semantics included).
4. DISPATCH: cfg.predict_impl="lut4" routes the backend through the
   packed tables within the bound; the ladder degrades lut4 -> lut ->
   f32 when the guards refuse, and `resolved_predict_impl` reports the
   rung that actually serves (the telemetry-stamp satellite).

All kernels run in Pallas interpret mode on the CPU suite.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import empty_ensemble
from ddt_tpu.ops import predict as predict_ops
from ddt_tpu.ops import predict_lut


def _rand_ens(seed=0, trees=12, depth=3, features=7, bins=31,
              loss="logloss", n_classes=2, missing=False, cat=(),
              exact_grid=False):
    rng = np.random.default_rng(seed)
    n_nodes = 2 ** (depth + 1) - 1
    ens = empty_ensemble(
        trees, depth, features, 0.125 if exact_grid else 0.1,
        0.25, loss, n_classes=n_classes,
        missing_bin=missing, n_bins=bins, cat_features=tuple(cat))
    ens.feature[:] = rng.integers(0, features, size=(trees, n_nodes))
    ens.threshold_bin[:] = rng.integers(
        0, bins - (2 if missing else 1), size=(trees, n_nodes))
    ens.is_leaf[:] = rng.random((trees, n_nodes)) < 0.25
    if exact_grid:
        # Power-of-two grid: integer leaf_q in [-7, 7] at scale exactly
        # 1/8 — the left spine stays internal and the leftmost bottom
        # node pins each tree's max|bot_val| to 7/8, so
        # scale = max/7 = 0.125 exactly and quantization is LOSSLESS
        # (max_abs_err == 0; asserted where used).
        q = rng.integers(-7, 8, size=(trees, n_nodes)).astype(np.float32)
        ens.leaf_value[:] = q / 8.0
        ens.is_leaf[:, [(1 << d) - 1 for d in range(depth)]] = False
        ens.leaf_value[:, (1 << depth) - 1] = 7 / 8.0
    else:
        ens.leaf_value[:] = rng.standard_normal(
            (trees, n_nodes)).astype(np.float32)
    if missing:
        ens.default_left[:] = rng.random((trees, n_nodes)) < 0.5
    return ens


def _rows(ens, rows=50, bins=31, missing=False, seed=1):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins - (1 if missing else 0),
                      size=(rows, ens.n_features)).astype(np.uint8)
    if missing:
        mask = rng.random(Xb.shape) < 0.2
        Xb[mask] = bins - 1
    return Xb


def _f32_reference(ce, Xb, tables=None):
    """Jitted one-hot scores on the original (tables=None) or
    dequantized int4 tables — jitted like the production dispatch."""
    if tables is None:
        eff_feat, eff_thr = ce.eff_feat, ce.eff_thr
        bot_val, cls_oh = ce.bot_val, ce.cls_oh
        dl, cn = ce.eff_dl, ce.eff_cat
    else:
        eff_thr, bot_val = tables.dequantized()
        eff_feat, cls_oh = tables.eff_feat, tables.cls_oh
        dl, cn = tables.eff_dl, tables.eff_cat
    kw = {}
    if dl is not None:
        kw["eff_dl"] = jnp.asarray(dl)
    if cn is not None:
        kw["eff_cat"] = jnp.asarray(cn)
    fn = jax.jit(functools.partial(
        predict_ops.predict_raw_effective,
        max_depth=ce.max_depth, learning_rate=ce.learning_rate,
        base=ce.base_score, n_classes=ce.n_classes_out,
        tree_chunk=ce.tree_chunk,
        missing_bin_value=ce.missing_bin_value, use_pallas=False, **kw))
    return np.asarray(fn(jnp.asarray(eff_feat), jnp.asarray(eff_thr),
                         jnp.asarray(bot_val), jnp.asarray(cls_oh),
                         jnp.asarray(Xb)))


def _lut4_scores(packed, Xb, tile_r=None):
    fn = jax.jit(lambda X: predict_lut.predict_effective_lut4(
        packed, X, tile_r=tile_r))
    return np.asarray(fn(jnp.asarray(Xb)))


# bins 13 -> thresholds fit a nibble (thr_packed=True), bins 31 -> the
# lossless int8 threshold form; both regimes ride every sweep.
VARIANTS = [
    pytest.param(dict(), 13, id="binary-thrpacked"),
    pytest.param(dict(), 31, id="binary-thr8"),
    pytest.param(dict(loss="softmax", n_classes=3, trees=12), 13,
                 id="softmax3-thrpacked"),
    pytest.param(dict(missing=True), 13, id="missing-thrpacked"),
    pytest.param(dict(missing=True), 31, id="missing-thr8"),
    pytest.param(dict(cat=(1, 4)), 13, id="categorical-thrpacked"),
    pytest.param(dict(cat=(1, 4)), 31, id="categorical-thr8"),
    pytest.param(dict(loss="softmax", n_classes=3, cat=(0, 2), trees=9),
                 31, id="softmax3-cat-ragged"),
    pytest.param(dict(trees=13, depth=4), 13, id="ragged-deep"),
]


@pytest.mark.parametrize("variant,bins", VARIANTS)
def test_lut4_bitexact_on_exact_grid(variant, bins):
    """Property 1: on order-free exact-grid models the int4 tier equals
    the f32 one-hot path BITWISE — descent, threshold nibble decode,
    sign extension, and the scale multiply all exact; the ragged tile
    (tile_r=16 on 50 rows) rides along."""
    missing = variant.get("missing", False)
    ens = _rand_ens(bins=bins, exact_grid=True, **variant)
    Xb = _rows(ens, bins=bins, missing=missing)
    ce = ens.compile(tree_chunk=8)
    tables = ce.quantize(leaf_dtype="int4")
    packed = tables.pack_int4()
    assert packed.thr_packed == (bins <= 15)
    assert tables.max_abs_err == 0.0        # the grid is lossless
    got = _lut4_scores(packed, Xb, tile_r=16)
    np.testing.assert_array_equal(got, _f32_reference(ce, Xb))
    # ... and therefore also bitwise vs the dequantized reference.
    np.testing.assert_array_equal(got,
                                  _f32_reference(ce, Xb, tables=tables))


@pytest.mark.parametrize("variant,bins", VARIANTS)
def test_lut4_error_bound_end_to_end(variant, bins):
    """Property 2: random-valued models hold the computed int4 bound
    vs true f32, and sit within pure f32-accumulation slack of the
    dequantized reference (the rounding step is the only real error)."""
    missing = variant.get("missing", False)
    ens = _rand_ens(bins=bins, **variant)
    Xb = _rows(ens, bins=bins, missing=missing)
    ce = ens.compile(tree_chunk=8)
    tables = ce.quantize(leaf_dtype="int4")
    packed = tables.pack_int4()
    got = _lut4_scores(packed, Xb)
    want = _f32_reference(ce, Xb)
    err = float(np.abs(got - want).max())
    assert err <= tables.max_abs_err * (1 + 1e-5) + 1e-6, \
        (err, tables.max_abs_err)
    # int4 genuinely rounds at these random leaf values.
    assert tables.max_abs_err > 0
    deq_ref = _f32_reference(ce, Xb, tables=tables)
    assert float(np.abs(got - deq_ref).max()) <= 1e-5
    # The int4 grid is coarser than int8's: its bound must dominate.
    assert tables.max_abs_err >= ce.quantize(
        leaf_dtype="int8").max_abs_err


def test_pack_round_trip_bit_exact():
    """Property 3: unpacking the nibble arrays host-side reproduces the
    logical tables bit-for-bit — thresholds (values <= 14 verbatim, the
    15 sentinel for every clipped +BIG) and two's-complement leaves."""
    ens = _rand_ens(bins=13)
    ce = ens.compile(tree_chunk=8)
    t = ce.quantize(leaf_dtype="int4")
    p = t.pack_int4()
    assert p.thr_packed
    tc = t.tree_chunk
    n_tc = t.n_trees_padded // tc
    n_int = (1 << t.max_depth) - 1
    n_leaves = 1 << t.max_depth
    h_n, h_l = (n_int + 1) // 2, (n_leaves + 1) // 2

    def unpack_node_major(packed, half, width):
        """[n_tc, half*tc] bytes -> [Tpad, width] nibbles (node-major
        inverse: low nibbles = blocks [0, half), high = [half, 2*half))."""
        out = np.zeros((t.n_trees_padded, 2 * half), np.int64)
        for c in range(n_tc):
            b = packed[c].astype(np.int64)
            for j in range(half):
                out[c * tc:(c + 1) * tc, j] = b[j * tc:(j + 1) * tc] & 15
                out[c * tc:(c + 1) * tc, half + j] = \
                    (b[j * tc:(j + 1) * tc] >> 4) & 15
        return out[:, :width]

    thr_nib = unpack_node_major(p.ops[1], h_n, n_int)
    thr_raw = t.thr_i8[:, :n_int].astype(np.int64) + 128
    want_nib = np.where(thr_raw >= 255, 15, thr_raw)
    np.testing.assert_array_equal(thr_nib, want_nib)

    leaf_nib = unpack_node_major(p.ops[2], h_l, n_leaves)
    leaf = np.where(leaf_nib >= 8, leaf_nib - 16, leaf_nib)
    np.testing.assert_array_equal(leaf, t.leaf_q.astype(np.int64))
    np.testing.assert_array_equal(
        p.ops[3].reshape(-1), t.leaf_scale)


def test_thr_pack_condition_is_value_based():
    """A 31-bin model whose thresholds all happen to be <= 14 still
    packs (the condition is the VALUES, not n_bins); one threshold at
    15 unpacks (15 is the sentinel, not a value)."""
    ens = _rand_ens(bins=31)
    ens.threshold_bin[:] = ens.threshold_bin % 15      # <= 14
    t = ens.compile(tree_chunk=8).quantize(leaf_dtype="int4")
    assert t.pack_int4().thr_packed
    ens.threshold_bin[0, 0] = 15
    ens.is_leaf[0, 0] = False
    t2 = ens.compile(tree_chunk=8).quantize(leaf_dtype="int4")
    assert not t2.pack_int4().thr_packed


def test_thr_pack_refuses_categorical_sentinel_collision():
    """A categorical node's comparison is EQUALITY, so it gets no
    always-left 255 exemption: a cat split whose bin id would clip into
    the sentinel must refuse the pack (packed, 'bin == 255 goes left'
    would decode to 256 and flip into always-right — review finding)."""
    ens = _rand_ens(bins=31, cat=(1,))
    ens.threshold_bin[:] = ens.threshold_bin % 15
    # A real (non-leaf) categorical node on feature 1 with bin id 255.
    ens.feature[0, 0] = 1
    ens.is_leaf[0, 0] = False
    ens.threshold_bin[0, 0] = 255
    t = ens.compile(tree_chunk=8).quantize(leaf_dtype="int4")
    assert not t.pack_int4().thr_packed
    # The SAME 255 on a numeric node is fine (">" semantics: 255 and
    # the 256 sentinel are both always-left for uint8 bins).
    ens2 = _rand_ens(bins=31, cat=(1,))
    ens2.threshold_bin[:] = ens2.threshold_bin % 15
    ens2.feature[0, 0] = 0                 # numeric feature
    ens2.is_leaf[0, 0] = False
    ens2.threshold_bin[0, 0] = 255
    t2 = ens2.compile(tree_chunk=8).quantize(leaf_dtype="int4")
    assert t2.pack_int4().thr_packed


def test_pack_refuses_non_int4_tables():
    ens = _rand_ens()
    with pytest.raises(ValueError, match="int4"):
        ens.compile(tree_chunk=8).quantize().pack_int4()


def test_fits_guard_refuses_monster_shapes():
    """predict_lut4_fits is the vmem-guard: a shape whose trace/VMEM
    budget explodes must return False, and a forced COMPILED dispatch
    at it must raise at the cause (interpret mode stays callable)."""
    assert predict_lut.predict_lut4_fits(64, 64, 3, 7, 1)
    assert predict_lut.predict_lut4_fits(64, 64, 3, 7, 1,
                                         thr_packed=True)
    assert not predict_lut.predict_lut4_fits(131072, 64, 10, 4096, 1)
    ens = _rand_ens()
    packed = ens.compile(tree_chunk=8).quantize(
        leaf_dtype="int4").pack_int4()
    with pytest.raises(ValueError, match="VMEM"):
        predict_lut.predict_effective_lut4(
            packed, _rows(ens), tile_r=10**6, interpret=False)


def test_backend_lut4_dispatch_and_fallback_ladder(monkeypatch):
    """Property 4: predict_impl='lut4' serves the packed tables within
    the bound; the guard ladder degrades lut4 -> lut -> f32 and
    `resolved_predict_impl` reports the serving rung each time."""
    from ddt_tpu.backends import get_backend

    ens = _rand_ens(trees=8, bins=13)
    Xb = _rows(ens, rows=33, bins=13)
    ce = ens.compile()
    be_f32 = get_backend(TrainConfig(backend="tpu", n_bins=13),
                         use_cache=False)
    be_l4 = get_backend(TrainConfig(backend="tpu", n_bins=13,
                                    predict_impl="lut4"),
                        use_cache=False)
    want = be_f32.predict_raw(ens, Xb)
    got = be_l4.predict_raw(ens, Xb)
    bound = ce.quantize(leaf_dtype="int4").max_abs_err
    assert float(np.abs(got - want).max()) <= bound * (1 + 1e-5) + 1e-6
    assert be_l4.resolved_predict_impl(ce.token) == "lut4"
    assert be_f32.resolved_predict_impl(ce.token) == "f32"

    # int4 guard refuses -> the int8 tier serves...
    monkeypatch.setattr(predict_lut, "predict_lut4_fits",
                        lambda *a, **k: False)
    be_l8 = get_backend(TrainConfig(backend="tpu", n_bins=13,
                                    predict_impl="lut4"),
                        use_cache=False)
    got8 = be_l8.predict_raw(ens, Xb)
    assert be_l8.resolved_predict_impl(ce.token) == "lut"
    bound8 = ce.quantize().max_abs_err
    assert float(np.abs(got8 - want).max()) <= bound8 * (1 + 1e-5) + 1e-6

    # ...and with both quantized guards refusing, f32 serves exactly.
    monkeypatch.setattr(predict_lut, "predict_lut_fits",
                        lambda *a, **k: False)
    be_ff = get_backend(TrainConfig(backend="tpu", n_bins=13,
                                    predict_impl="lut4"),
                        use_cache=False)
    np.testing.assert_array_equal(be_ff.predict_raw(ens, Xb), want)
    assert be_ff.resolved_predict_impl(ce.token) == "f32"


def test_lut4_quantize_memoized_and_seedable():
    ens = _rand_ens()
    ce = ens.compile(tree_chunk=8)
    t1 = ce.quantize(leaf_dtype="int4")
    assert ce.quantize(leaf_dtype="int4") is t1
    ce2 = ens.compile(tree_chunk=8)
    ce2.seed_quantized(t1)
    assert ce2.quantize(leaf_dtype="int4") is t1


def test_lut4_empty_batch():
    ens = _rand_ens()
    packed = ens.compile(tree_chunk=8).quantize(
        leaf_dtype="int4").pack_int4()
    out = predict_lut.predict_effective_lut4(
        packed, np.zeros((0, ens.n_features), np.uint8))
    assert np.asarray(out).shape == (0,)


def test_lut4_tables_npz_round_trip_token_pinned():
    """The int4 tables survive the aot npz round trip verbatim (the
    registry's carried-representation contract): every array bitwise,
    the scalars exact, and re-packing the restored tables yields
    byte-identical device operands."""
    from ddt_tpu.export import aot

    ens = _rand_ens(bins=13, missing=False, cat=(2,))
    t = ens.compile(tree_chunk=8).quantize(leaf_dtype="int4")
    back = aot.tables_from_arrays(aot.tables_to_arrays(t))
    assert back.token == t.token and back.leaf_dtype == "int4"
    assert back.max_abs_err == t.max_abs_err
    np.testing.assert_array_equal(back.leaf_q, t.leaf_q)
    np.testing.assert_array_equal(back.leaf_scale, t.leaf_scale)
    np.testing.assert_array_equal(back.thr_i8, t.thr_i8)
    p0, p1 = t.pack_int4(), back.pack_int4()
    assert p0.thr_packed == p1.thr_packed
    for a, b in zip(p0.ops, p1.ops):
        np.testing.assert_array_equal(a, b)
