"""Config-5 memory witness at this box's capacity (round-3 verdict
item 3): "O(chunk) by construction" meets multi-GB-class data. The
defining property of O(chunk) is that peak memory tracks the CHUNK
size, not the dataset size — so the test trains TWICE at the same
500k-row chunk size, with the dataset quadrupled (2.5M -> 10M rows; 80
-> 320 MB binned, 320 MB -> 1.28 GB as the float32 matrix the
in-memory path would hold), each in a FRESH subprocess (RSS high-water
marks are process-wide), and asserts the peak-RSS growth is flat. On
this CPU platform the "device" is host RAM, so a path that held the
dataset device-side would show up too (it would add ~+240 MB binned /
+960 MB float between the runs); the device chunk cache is explicitly
OFF in the worker for the same reason.

The full-size measured run (20M x 64 on the real chip, throughput +
peak RSS) lives in experiments/stream_scale.py with results in
docs/PERF.md.
"""

import json
import os
import subprocess
import sys

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "stream_rss_worker.py")

FEATURES, BINS, CHUNK_ROWS = 32, 31, 500_000


def _measure(rows, n_chunks, work_dir):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)          # worker pins cpu itself
    # The pytest session exports an 8-virtual-device XLA_FLAGS
    # (conftest.py) which the worker would inherit: 8 device arenas +
    # thread pools add ~100 MB of RSS *and* most of its run-to-run
    # jitter — measured swings up to 208 MB on the diff-of-diffs this
    # test asserts at 120. The streaming run under measurement is
    # single-device; measure it that way.
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, _WORKER, str(rows), str(FEATURES),
         str(n_chunks), str(BINS), str(work_dir)],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    assert out.returncode == 0, out.stdout + "\n" + out.stderr
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["rc"] == 0 and rec["n_chunks"] == n_chunks
    return rec


def test_stream_dir_memory_is_o_chunk(tmp_path):
    small = _measure(5 * CHUNK_ROWS, 5, tmp_path / "small")
    big = _measure(20 * CHUNK_ROWS, 20, tmp_path / "big")

    # The shard writer holds one generated chunk + npz buffers — flat in
    # dataset size by construction, bounded in chunk size.
    for rec in (small, big):
        shard_delta = rec["rss_sharded_mb"] - rec["rss_baseline_mb"]
        assert shard_delta < 8 * rec["chunk_mb"], rec

    # Training: peak RSS grows with the chunk (per-chunk buffers, XLA
    # intermediates sized [chunk_rows, ...], async-dispatch queue depth)
    # plus small per-dataset state (the cached per-chunk preds: rows x
    # 4 B = 10 -> 40 MB, labels). Quadrupling the dataset at fixed chunk
    # size must NOT move the peak by anywhere near the dataset growth
    # (+240 MB binned / +960 MB float if a path held it); 120 MB of
    # headroom absorbs queue-depth jitter under CPU contention while
    # staying half the smallest held-data signature.
    d_small = small["rss_trained_mb"] - small["rss_baseline_mb"]
    d_big = big["rss_trained_mb"] - big["rss_baseline_mb"]
    assert d_big - d_small < 120, (small, big)
