"""Fleet serving (ddt_tpu/serve/fleet.py + control.py, ISSUE 15):
multi-model tenancy, weighted deficit-round-robin dispatch, LRU
eviction with zero-downtime reload, and the control plane.

Everything runs in-process against the engines (plus one live-socket
HTTP sweep); the CPU 'tpu' backend (XLA CPU) scores for real.
Timing-sensitive behavior is deterministic: fairness uses the
autostart=False backlog seam + the on_dispatch order log, eviction
tests drive the LRU clock with explicit request order, and every
response is checked against the offline `api.predict` answer OF THE
MODEL THAT SERVED IT — structure, never wall-clock.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.serve.batcher import ShuttingDown
from ddt_tpu.serve.control import (FleetConfigError, FleetSpec,
                                   build_fleet, coerce_spec,
                                   load_fleet_config, parse_models_arg,
                                   resolve_specs, validate_specs)
from ddt_tpu.serve.engine import ServeEngine
from ddt_tpu.serve.fleet import (FleetEngine, ModelUnavailableError,
                                 SloBurnTracker, UnknownModelError)
from ddt_tpu.serve.metrics import parse_exposition, render_metrics
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry import report as tele_report
from ddt_tpu.telemetry.events import RunLog, validate_event


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """Three small models (one per fleet member), saved artifacts, and
    offline reference scores — shared module-wide (training is the slow
    part)."""
    X, y = datasets.synthetic_binary(3000, seed=5)
    kw = dict(n_trees=5, max_depth=3, n_bins=31, backend="tpu",
              log_every=10**9)
    results = {
        "a": api.train(X, y, **kw),
        "b": api.train(X, y, learning_rate=0.05, **kw),
        "c": api.train(X, y, learning_rate=0.2, **kw),
    }
    cfg = TrainConfig(backend="tpu", n_bins=31)
    td = tmp_path_factory.mktemp("fleet_models")
    paths, ref = {}, {}
    for name, res in results.items():
        p = str(td / f"{name}.npz")
        res.save(p)
        paths[name] = p
        ref[name] = np.asarray(api.predict(
            res.ensemble, X, mapper=res.mapper, cfg=cfg))
    return dict(X=X, results=results, cfg=cfg, paths=paths, ref=ref)


def _specs(trained, names=("a", "b"), **overrides):
    return [FleetSpec(name=n, ref=trained["paths"][n],
                      **overrides.get(n, {})) for n in names]


def _fleet(trained, names=("a", "b"), *, overrides=None, **kw):
    kw.setdefault("backend", "tpu")
    kw.setdefault("max_wait_ms", 25.0)
    return build_fleet(_specs(trained, names, **(overrides or {})), **kw)


# --------------------------------------------------------------------- #
# config parsing (the --models / --fleet-config surfaces)
# --------------------------------------------------------------------- #
def test_parse_models_arg_full_grammar():
    specs = parse_models_arg(
        "a@prod,b@canary:weight=3,c@v2:tier=int4:max_batch=64:name=tiny")
    assert [(s.name, s.ref, s.weight, s.tier, s.max_batch)
            for s in specs] == [
        ("a", "a@prod", 1.0, None, 256),
        ("b", "b@canary", 3.0, None, 256),
        ("tiny", "c@v2", 1.0, "int4", 64),
    ]


@pytest.mark.parametrize("bad, msg", [
    ("a@prod,,b@x", "empty"),
    ("a@prod:weight", "key=value"),
    ("a@prod:bogus=1", "unknown fleet entry key"),
    ("a@prod:tier=int2", "unknown quantization tier"),
    ("a@prod:weight=0", "weight must be > 0"),
    ("a@prod:weight=nope", "could not convert"),
    ("a@prod:max_batch=0", "max_batch must be >= 1"),
])
def test_parse_models_arg_loud_errors(bad, msg):
    with pytest.raises(FleetConfigError, match=msg):
        parse_models_arg(bad)


def test_fleet_config_file_round_trip(tmp_path):
    p = tmp_path / "fleet.json"
    p.write_text(json.dumps({"models": [
        {"name": "a", "ref": "a@prod", "weight": 2},
        {"model": "b@canary", "tier": "int8"},
    ]}))
    specs = validate_specs(load_fleet_config(str(p)))
    assert [(s.name, s.weight, s.tier) for s in specs] == [
        ("a", 2.0, None), ("b", 1.0, "int8")]
    # bare-list form parses identically
    p2 = tmp_path / "bare.json"
    p2.write_text(json.dumps([{"ref": "a@prod"}]))
    assert load_fleet_config(str(p2))[0].name == "a"


@pytest.mark.parametrize("doc, msg", [
    ({"modelz": []}, "unknown top-level key"),
    ({"models": []}, "non-empty list"),
    ({"models": ["x"]}, "must be an object"),
    ({"models": [{"name": "a"}]}, "needs a 'ref'"),
], ids=["topkey", "empty", "scalar-entry", "no-ref"])
def test_fleet_config_file_loud_errors(tmp_path, doc, msg):
    p = tmp_path / "fleet.json"
    p.write_text(json.dumps(doc))
    with pytest.raises(FleetConfigError, match=msg):
        load_fleet_config(str(p))


def test_duplicate_names_and_unknown_refs_refused(trained, tmp_path):
    with pytest.raises(FleetConfigError, match="duplicate model name"):
        validate_specs([FleetSpec(name="a", ref="a@1"),
                        FleetSpec(name="a", ref="a@2")])
    # unknown ref, no registry: boot-time loud failure
    with pytest.raises(FleetConfigError, match="not a file"):
        resolve_specs([FleetSpec(name="x", ref="ghost@prod")], None)
    # unknown ref against a real (empty) registry: RegistryError text
    with pytest.raises(FleetConfigError, match="x"):
        resolve_specs([FleetSpec(name="x", ref="ghost@prod")],
                      str(tmp_path / "reg"))
    # file refs resolve without a registry
    assert resolve_specs(
        [FleetSpec(name="a", ref=trained["paths"]["a"])], None) == {
        "a": "file"}


def test_default_name_from_path_and_ref(trained):
    s = coerce_spec({"ref": trained["paths"]["a"]}, "t")
    assert s.name == "a"
    assert coerce_spec({"ref": "modelx@prod"}, "t").name == "modelx"


def test_raw_flag_string_spellings_parse_strictly():
    """bool('false') is True — the string surfaces (--models raw=...,
    POST /models JSON strings) must parse the flag strictly, so
    raw=false can actually turn it OFF."""
    assert parse_models_arg("m@1:raw=true")[0].raw is True
    assert parse_models_arg("m@1:raw=false")[0].raw is False
    assert parse_models_arg("m@1:raw=0")[0].raw is False
    assert coerce_spec({"ref": "m@1", "raw": True}, "t").raw is True
    with pytest.raises(FleetConfigError, match="must be a boolean"):
        parse_models_arg("m@1:raw=bogus")


# --------------------------------------------------------------------- #
# routing + per-model bit-match
# --------------------------------------------------------------------- #
def test_routes_by_name_and_bit_matches_each_model(trained):
    eng = _fleet(trained, ("a", "b", "c"))
    try:
        X, ref = trained["X"], trained["ref"]
        for name in ("a", "b", "c"):
            got = eng.predict(X[:9], model=name, timeout=60.0)
            np.testing.assert_allclose(got, ref[name][:9],
                                       rtol=1e-6, atol=1e-7)
        # multi-model fleet: an unrouted request is a loud, addressed
        # refusal (the structured-404 surface), never a silent default
        with pytest.raises(UnknownModelError) as ei:
            eng.predict(X[:1])
        assert ei.value.known == ["a", "b", "c"]
        with pytest.raises(UnknownModelError):
            eng.predict(X[:1], model="nope")
    finally:
        eng.close()


def test_single_model_fleet_routes_implicitly(trained):
    eng = _fleet(trained, ("a",))
    try:
        assert eng.default_model == "a"
        got = eng.predict(trained["X"][:4], timeout=60.0)
        np.testing.assert_allclose(got, trained["ref"]["a"][:4],
                                   rtol=1e-6, atol=1e-7)
        # the raw wire path's width lookup resolves the same default
        # (an unrouted binned=raw body on a one-model fleet must not
        # 404 while the identical JSON request succeeds)
        assert eng.n_features_for() == trained["X"].shape[1]
    finally:
        eng.close()


def test_remove_racing_submit_is_a_loud_404_not_a_hang(trained):
    """A remove_model landing between a request's residency check and
    its enqueue must surface as UnknownModelError — enqueueing into the
    orphaned slot would hang the waiter forever (the dispatcher's
    rotation no longer lists it). Injected deterministically at the
    exact seam via the residency hook."""
    eng = _fleet(trained, ("a", "b"))
    try:
        orig = eng._ensure_resident
        fired = {"done": False}

        def racy(slot):
            orig(slot)
            if slot.name == "b" and not fired["done"]:
                fired["done"] = True
                eng.remove_model("b")

        eng._ensure_resident = racy
        with pytest.raises(UnknownModelError):
            eng.predict(trained["X"][:2], model="b", timeout=10.0)
        # the untouched model keeps serving
        np.testing.assert_allclose(
            eng.predict(trained["X"][:2], model="a", timeout=60.0),
            trained["ref"]["a"][:2], rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_concurrent_multi_model_storm_bit_matches(trained):
    """Concurrent requests across all three models: every response
    matches the offline answer of the model that served it — per-model
    queues never cross-contaminate."""
    eng = _fleet(trained, ("a", "b", "c"))
    try:
        X, ref = trained["X"], trained["ref"]
        names = ["a", "b", "c"]
        n = 30
        errs, got = [], [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            name = names[i % 3]
            barrier.wait()
            try:
                got[i] = (name, eng.predict(X[i:i + 2], model=name,
                                            timeout=60.0))
            except Exception as e:  # ddtlint: disable=broad-except
                errs.append((i, repr(e)))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errs, errs[:5]
        for i, (name, scores) in enumerate(got):
            np.testing.assert_allclose(scores, ref[name][i:i + 2],
                                       rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# LRU eviction + zero-downtime reload
# --------------------------------------------------------------------- #
def test_lru_eviction_demotes_coldest_and_reloads_on_request(trained):
    eng = _fleet(trained, ("a", "b", "c"), max_resident=2)
    try:
        X, ref = trained["X"], trained["ref"]
        h = eng.health()
        # preload respects the budget: only the first two are resident
        assert [h["models"][n]["resident"] for n in ("a", "b", "c")] \
            == [True, True, False]
        # touch a (so b is coldest), then request c: b must be evicted
        eng.predict(X[:1], model="a", timeout=60.0)
        np.testing.assert_allclose(
            eng.predict(X[:3], model="c", timeout=60.0), ref["c"][:3],
            rtol=1e-6, atol=1e-7)
        h = eng.health()
        assert h["models"]["b"]["resident"] is False
        assert h["models"]["a"]["resident"] is True
        assert h["models"]["b"]["evictions"] == 1
        # an evicted model still serves — reloaded on request, answers
        # bit-identical to its artifact
        np.testing.assert_allclose(
            eng.predict(X[:5], model="b", timeout=60.0), ref["b"][:5],
            rtol=1e-6, atol=1e-7)
        assert eng.health()["models"]["b"]["reloads"] == 1
    finally:
        eng.close()


def test_eviction_reload_under_concurrent_traffic(trained):
    """The acceptance storm: concurrent traffic across 3 models with a
    max_resident=2 budget forces evictions+reloads MID-STORM; zero
    failed requests, every response bit-matches the artifact that
    served it, and the lifecycle counters/events tell the story."""
    log = RunLog()
    c0 = tele_counters.snapshot()
    eng = _fleet(trained, ("a", "b", "c"), max_resident=2, run_log=log)
    try:
        X, ref = trained["X"], trained["ref"]
        names = ["a", "b", "c"]
        n = 36
        errs, got = [], [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            name = names[i % 3]
            barrier.wait()
            try:
                got[i] = (name, eng.predict(X[i:i + 1], model=name,
                                            timeout=120.0))
            except Exception as e:  # ddtlint: disable=broad-except
                errs.append((i, repr(e)))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errs, f"eviction storm failed requests: {errs[:5]}"
        for i, (name, scores) in enumerate(got):
            np.testing.assert_allclose(scores, ref[name][i:i + 1],
                                       rtol=1e-6, atol=1e-7)
        # The dispatcher settles the over-budget fleet once queues
        # drain: evictions observed, residency back inside the budget.
        deadline = 30
        while eng.health()["resident"] > 2 and deadline:
            import time as _time

            _time.sleep(0.05)
            deadline -= 1
        h = eng.health()
        assert h["resident"] <= 2, h
        assert h["evictions"] >= 1, h
        # at least one model is now cold — requesting every model again
        # reloads it zero-downtime, bit-identical to its artifact
        for name in names:
            np.testing.assert_allclose(
                eng.predict(X[:2], model=name, timeout=120.0),
                ref[name][:2], rtol=1e-6, atol=1e-7)
        h = eng.health()
        assert h["reloads"] >= 1, h
        d = tele_counters.delta(c0)
        assert d["fleet_evictions"] >= 1 and d["fleet_reloads"] >= 1
        kinds = [e.get("kind") for e in log.events("fault")]
        assert "fleet_eviction" in kinds and "fleet_reload" in kinds
        ev = next(e for e in log.events("fault")
                  if e.get("kind") == "fleet_eviction")
        assert ev["model_name"] in names
    finally:
        eng.close()


def test_zero_jit_compiles_during_steady_state(trained):
    """With every model resident and warmed, a storm across the fleet
    compiles NOTHING: dispatches ride the pre-traced bucket shapes
    (the zero-retrace steady-state witness)."""
    tele_counters.install_jax_listener()
    eng = _fleet(trained, ("a", "b"))
    try:
        X = trained["X"]
        for name in ("a", "b"):        # warm every bucket in use
            eng.predict(X[:1], model=name, timeout=60.0)
            eng.predict(X[:8], model=name, timeout=60.0)
        c0 = tele_counters.snapshot()
        for i in range(10):
            eng.predict(X[i:i + 1], model="a", timeout=60.0)
            eng.predict(X[i:i + 8], model="b", timeout=60.0)
        assert tele_counters.delta(c0)["jit_compiles"] == 0
    finally:
        eng.close()


def test_reload_failure_is_a_structured_unavailable(trained):
    """A model whose reload fails surfaces ModelUnavailableError (the
    HTTP 503) — and recovers when the loader does."""
    from ddt_tpu.serve.control import make_loader

    loader = make_loader(None, "tpu")
    broken = {"on": False}

    def flaky(spec):
        if broken["on"]:
            raise OSError("artifact store unreachable")
        return loader(spec)

    eng = FleetEngine(_specs(trained, ("a", "b")), flaky,
                      max_wait_ms=25.0, max_resident=1)
    try:
        X = trained["X"]
        eng.predict(X[:1], model="a", timeout=60.0)   # a resident
        broken["on"] = True
        with pytest.raises(ModelUnavailableError, match="unreachable"):
            eng.predict(X[:1], model="b", timeout=60.0)
        assert eng.health()["models"]["b"]["load_error"]
        broken["on"] = False
        np.testing.assert_allclose(
            eng.predict(X[:2], model="b", timeout=60.0),
            trained["ref"]["b"][:2], rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# weighted deficit-round-robin fairness
# --------------------------------------------------------------------- #
def test_weighted_dispatch_fairness_under_saturation(trained):
    """Deterministic saturation: both queues pre-filled while the
    dispatcher is stopped, then drained. While both models have
    backlog, the weight-3 model receives ~3x the rows of the weight-1
    model (deficit round robin, quantum = weight x max_batch)."""
    specs = [FleetSpec(name="a", ref=trained["paths"]["a"], weight=1.0,
                       max_batch=8),
             FleetSpec(name="b", ref=trained["paths"]["b"], weight=3.0,
                       max_batch=8)]
    from ddt_tpu.serve.control import make_loader

    order = []
    eng = FleetEngine(specs, make_loader(None, "tpu"),
                      max_wait_ms=1.0, express_lane=False,
                      on_dispatch=lambda name, rows:
                      order.append((name, rows)),
                      autostart=False)
    try:
        X = trained["X"]
        per_model = 48                      # 48 x 8-row requests each
        reqs = []
        for i in range(per_model):
            reqs.append(eng.predict_async(X[:8], model="a"))
            reqs.append(eng.predict_async(X[:8], model="b"))
        eng.start()
        for r in reqs:
            r.result(120.0)
        total = per_model * 8
        # fairness window: up to the point the first model drains
        seen = {"a": 0, "b": 0}
        for name, rows in order:
            seen[name] += rows
            if seen[name] >= total:
                break
        ratio = seen["b"] / max(1, seen["a"])
        assert 2.0 <= ratio <= 4.5, (seen, order[:20])
    finally:
        eng.close()


def test_equal_weights_drain_evenly(trained):
    specs = [FleetSpec(name="a", ref=trained["paths"]["a"], max_batch=8),
             FleetSpec(name="b", ref=trained["paths"]["b"], max_batch=8)]
    from ddt_tpu.serve.control import make_loader

    order = []
    eng = FleetEngine(specs, make_loader(None, "tpu"),
                      max_wait_ms=1.0, express_lane=False,
                      on_dispatch=lambda name, rows:
                      order.append((name, rows)),
                      autostart=False)
    try:
        X = trained["X"]
        reqs = [eng.predict_async(X[:8], model=n)
                for _ in range(32) for n in ("a", "b")]
        eng.start()
        for r in reqs:
            r.result(120.0)
        seen = {"a": 0, "b": 0}
        for name, rows in order:
            seen[name] += rows
            if seen[name] >= 32 * 8:
                break
        ratio = seen["b"] / max(1, seen["a"])
        assert 0.5 <= ratio <= 2.0, (seen, order[:20])
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# express lane (per model)
# --------------------------------------------------------------------- #
def test_express_lane_carries_idle_singles_per_model(trained):
    eng = _fleet(trained, ("a", "b"))
    try:
        X, ref = trained["X"], trained["ref"]
        for i in range(5):
            got = eng.predict(X[i:i + 1], model="a", timeout=60.0)
            np.testing.assert_allclose(got, ref["a"][i:i + 1],
                                       rtol=1e-6, atol=1e-7)
        # sequential singles at an empty queue ride the lane
        assert eng.window_summaries()["a"]["express"] >= 4
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# control plane: add / remove / retag
# --------------------------------------------------------------------- #
def test_add_remove_model_without_restart(trained):
    eng = _fleet(trained, ("a",))
    try:
        X, ref = trained["X"], trained["ref"]
        out = eng.add_model(FleetSpec(name="b",
                                      ref=trained["paths"]["b"]))
        assert out["resident"] is True
        np.testing.assert_allclose(
            eng.predict(X[:3], model="b", timeout=60.0), ref["b"][:3],
            rtol=1e-6, atol=1e-7)
        with pytest.raises(ValueError, match="already in the fleet"):
            eng.add_model(FleetSpec(name="b", ref=trained["paths"]["a"]))
        eng.remove_model("b")
        with pytest.raises(UnknownModelError):
            eng.predict(X[:1], model="b")
        with pytest.raises(UnknownModelError):
            eng.remove_model("b")
        # a is untouched throughout
        np.testing.assert_allclose(
            eng.predict(X[:2], model="a", timeout=60.0), ref["a"][:2],
            rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_add_model_failed_load_rolls_back(trained):
    """A failed add (bad ref through POST /models — no boot-time
    resolution there) must not leave a permanently broken member: the
    slot rolls back out and the corrected retry succeeds instead of
    'already in the fleet'."""
    from ddt_tpu.serve.control import make_loader

    loader = make_loader(None, "tpu")
    broken = {"on": False}

    def flaky(spec):
        if broken["on"]:
            raise OSError("artifact store unreachable")
        return loader(spec)

    eng = FleetEngine(_specs(trained, ("a",)), flaky, max_wait_ms=25.0)
    try:
        eng.predict(trained["X"][:1], model="a", timeout=60.0)
        broken["on"] = True
        with pytest.raises(ModelUnavailableError):
            eng.add_model(FleetSpec(name="b",
                                    ref=trained["paths"]["b"]))
        assert "b" not in eng.health()["models"]
        with pytest.raises(UnknownModelError):
            eng.predict(trained["X"][:1], model="b")
        # corrected retry under the SAME name succeeds
        broken["on"] = False
        out = eng.add_model(FleetSpec(name="b",
                                      ref=trained["paths"]["b"]))
        assert out["resident"] is True
        np.testing.assert_allclose(
            eng.predict(trained["X"][:2], model="b", timeout=60.0),
            trained["ref"]["b"][:2], rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_fleet_report_tolerates_fault_only_models(trained):
    """A model that was evicted before it ever emitted a window enters
    the rollup through its lifecycle faults alone — the report renders
    its quantiles as absent instead of crashing the whole command."""
    log = RunLog()
    eng = _fleet(trained, ("a", "b"), run_log=log)
    eng.predict(trained["X"][:2], model="a", timeout=60.0)
    eng.close()
    events = [dict(e) for e in log.ring]
    # synthesize the fault-only member (deterministic; the live
    # equivalent is preload->evict with zero traffic)
    events.append({"event": "fault", "schema": 5, "t": 0.0, "seq": 999,
                   "kind": "fleet_eviction", "model_name": "ghost",
                   "evictions": 1, "reloads": 0})
    summary = tele_report.summarize(events)
    assert "ghost" in summary["fleet"]["models"]
    rendered = tele_report.render_fleet(summary)
    assert "ghost" in rendered
    assert "fleet:" in tele_report.render(summary)   # full report too


def test_retag_hot_swaps_one_model_old_or_new_never_a_mix(trained):
    """Retag mid-flight: every concurrent response for the retagged
    model bit-matches EITHER the old or the new artifact (per-model
    hot-swap atomicity), and the other model is untouched."""
    log = RunLog()
    eng = _fleet(trained, ("a", "b"), run_log=log)
    try:
        X, ref = trained["X"], trained["ref"]
        n = 20
        errs, got = [], [None] * n
        barrier = threading.Barrier(n + 1)

        def worker(i):
            barrier.wait()
            try:
                got[i] = eng.predict(X[i:i + 1], model="a",
                                     timeout=60.0)[0]
            except Exception as e:  # ddtlint: disable=broad-except
                errs.append((i, repr(e)))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()

        def swapper():
            barrier.wait()
            eng.retag("a", FleetSpec(name="a",
                                     ref=trained["paths"]["c"]))

        sw = threading.Thread(target=swapper)
        sw.start()
        for t in threads:
            t.join(60)
        sw.join(60)
        assert not errs, errs[:5]
        for i, s in enumerate(got):
            old, new = ref["a"][i], ref["c"][i]
            assert (abs(s - old) < 1e-5) or (abs(s - new) < 1e-5), \
                (i, s, old, new)
        # post-retag requests score with the new artifact
        np.testing.assert_allclose(
            eng.predict(X[:4], model="a", timeout=60.0), ref["c"][:4],
            rtol=1e-6, atol=1e-7)
        swaps = [e for e in log.events("fault")
                 if e.get("kind") == "hot_swap"]
        assert swaps and swaps[-1]["model_name"] == "a"
        # b never moved
        np.testing.assert_allclose(
            eng.predict(X[:2], model="b", timeout=60.0), ref["b"][:2],
            rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_cli_fleet_rejects_single_model_flags():
    """--quantized/--raw/--max-batch are single-model knobs: the fleet
    CLI refuses them loudly instead of silently serving every model at
    its default tier (fleets spell them per entry)."""
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "ddt_tpu.cli", "serve",
         "--models", "a@prod", "--quantized", "int4"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 1
    assert "per entry" in r.stderr, r.stderr


def test_close_refuses_new_work(trained):
    eng = _fleet(trained, ("a",))
    eng.close()
    with pytest.raises(ShuttingDown):
        eng.predict(trained["X"][:1], model="a")


# --------------------------------------------------------------------- #
# telemetry: per-model serve_latency + report fleet rollup
# --------------------------------------------------------------------- #
def test_per_model_serve_latency_events_and_fleet_report(trained,
                                                         tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    eng = _fleet(trained, ("a", "b", "c"), max_resident=2,
                 run_log=path)
    X = trained["X"]
    for name in ("a", "b", "c"):
        eng.predict(X[:4], model=name, timeout=60.0)
    emitted = eng.emit_latency(reset=True)
    assert set(emitted) == {"a", "b", "c"}
    for name, s in emitted.items():
        assert s["model_name"] == name and s["requests"] >= 1
        validate_event({"event": "serve_latency", "schema": 5, "t": 0.0,
                        "seq": 0, **s})
    eng.close()

    events = tele_report.read_events(path)
    names = {e["model_name"] for e in events
             if e["event"] == "serve_latency"}
    assert names == {"a", "b", "c"}
    summary = tele_report.summarize(events)
    fl = summary["fleet"]
    assert set(fl["models"]) == {"a", "b", "c"}
    assert fl["evictions"] >= 1 and fl["reloads"] >= 0
    for m in fl["models"].values():
        assert m["requests"] >= 1 and m["p99_ms"] is not None
    rendered = tele_report.render_fleet(summary)
    assert "fleet:" in rendered and "a" in rendered
    # the full report embeds the same rollup
    assert "fleet:" in tele_report.render(summary)


def test_single_model_logs_have_no_fleet_section(trained, tmp_path):
    """Back-compat: a single-model serve log (no model_name dimension)
    summarizes with fleet=None and render_fleet refuses loudly."""
    path = str(tmp_path / "single.jsonl")
    eng = ServeEngine(api.ModelBundle(
        ensemble=trained["results"]["a"].ensemble,
        mapper=trained["results"]["a"].mapper),
        trained["cfg"], max_wait_ms=25.0, max_batch=32, run_log=path)
    eng.predict(trained["X"][:4], timeout=60.0)
    eng.close()
    summary = tele_report.summarize(tele_report.read_events(path))
    assert summary["fleet"] is None
    with pytest.raises(ValueError, match="no fleet"):
        tele_report.render_fleet(summary)


def test_single_engine_model_name_dimension(trained):
    """The ISSUE 15 satellite on the SINGLE-model engine: model_name=
    stamps serve_latency windows, hot_swap events, and /healthz."""
    log = RunLog()
    eng = ServeEngine(api.ModelBundle(
        ensemble=trained["results"]["a"].ensemble,
        mapper=trained["results"]["a"].mapper),
        trained["cfg"], max_wait_ms=25.0, max_batch=32, run_log=log,
        model_name="prod")
    try:
        eng.predict(trained["X"][:2], timeout=60.0)
        assert eng.health()["model_name"] == "prod"
        s = eng.emit_latency(reset=True)
        assert s["model_name"] == "prod"
        eng.swap(api.ModelBundle(
            ensemble=trained["results"]["b"].ensemble,
            mapper=trained["results"]["b"].mapper))
        hs = [e for e in log.events("fault")
              if e.get("kind") == "hot_swap"]
        assert hs and hs[-1]["model_name"] == "prod"
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# HTTP front end: routing, control plane, structured errors
# --------------------------------------------------------------------- #
def _post(port, path, payload, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture()
def served_fleet(trained):
    from ddt_tpu.serve.http import serve_forever

    eng = _fleet(trained, ("a", "b"))
    ready = threading.Event()
    th = threading.Thread(target=serve_forever, args=(eng,),
                          kwargs=dict(port=0, ready_event=ready),
                          daemon=True)
    th.start()
    assert ready.wait(60)
    yield eng, eng.http_port
    try:
        _post(eng.http_port, "/shutdown", {})
    except OSError:
        pass
    th.join(30)


def test_http_fleet_routing_and_control_plane(served_fleet, trained):
    eng, port = served_fleet
    X, ref = trained["X"], trained["ref"]
    Xb = trained["results"]["a"].mapper.transform(X)

    # path routing
    r = _post(port, "/models/a/predict", {"rows": X[:3].tolist()})
    np.testing.assert_allclose(r["scores"], ref["a"][:3],
                               rtol=1e-5, atol=1e-6)
    # header routing
    r = _post(port, "/predict", {"rows": X[:3].tolist()},
              headers={"X-DDT-Model": "b"})
    np.testing.assert_allclose(r["scores"], ref["b"][:3],
                               rtol=1e-5, atol=1e-6)
    # binned=raw on the path route (the zero-copy wire path, per model)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/models/a/predict?binned=raw",
        data=Xb[:2].tobytes(),
        headers={"Content-Type": "application/octet-stream"},
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        raw = json.loads(resp.read())
    np.testing.assert_allclose(raw["scores"], ref["a"][:2],
                               rtol=1e-5, atol=1e-6)

    # structured 404: unknown model carries the addressed body
    try:
        _post(port, "/models/ghost/predict", {"rows": X[:1].tolist()})
        raise AssertionError("unknown model accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        body = json.loads(e.read())
        assert body["model"] == "ghost" and body["models"] == ["a", "b"]
    # structured 404: unrouted request on a multi-model fleet
    try:
        _post(port, "/predict", {"rows": X[:1].tolist()})
        raise AssertionError("unrouted request accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert json.loads(e.read())["models"] == ["a", "b"]

    # GET /models + /stats per model
    models = _get(port, "/models")["models"]
    assert set(models) == {"a", "b"}
    assert models["a"]["resident"] is True
    st = _get(port, "/models/a/stats")
    assert st["model_name"] == "a" and st["requests"] >= 1
    # per-model emit resets ONLY that model's window
    _get(port, "/models/a/stats?emit=1")
    stb = _get(port, "/models/b/stats")
    assert stb["requests"] >= 1, "emit on a must not reset b's window"
    # unknown model stats: the same structured 404 as /predict, never
    # healthy-looking zeros
    try:
        _get(port, "/models/ghost/stats")
        raise AssertionError("unknown model stats served")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert json.loads(e.read())["model"] == "ghost"

    # control plane: add, predict, retag, remove
    out = _post(port, "/models", {
        "action": "add", "name": "c", "ref": trained["paths"]["c"]})
    assert out["resident"] is True
    r = _post(port, "/models/c/predict", {"rows": X[:2].tolist()})
    np.testing.assert_allclose(r["scores"], ref["c"][:2],
                               rtol=1e-5, atol=1e-6)
    out = _post(port, "/models", {
        "action": "retag", "name": "c", "ref": trained["paths"]["b"]})
    assert out["old"] != out["new"]
    r = _post(port, "/models/c/predict", {"rows": X[:2].tolist()})
    np.testing.assert_allclose(r["scores"], ref["b"][:2],
                               rtol=1e-5, atol=1e-6)
    _post(port, "/models", {"action": "remove", "name": "c"})
    try:
        _post(port, "/models/c/predict", {"rows": X[:1].tolist()})
        raise AssertionError("removed model still served")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    # /swap is the single-model surface
    try:
        _post(port, "/swap", {"model": trained["paths"]["a"]})
        raise AssertionError("/swap accepted on a fleet")
    except urllib.error.HTTPError as e:
        assert e.code == 400

    # healthz rolls the fleet up
    h = _get(port, "/healthz")
    assert h["fleet"] is True and set(h["models"]) == {"a", "b"}


def test_http_single_model_rejects_fleet_routing(trained):
    """The bugfix satellite on a SINGLE-model server: a request routed
    to a named model is a structured 404 (today it would have been a
    bare 500/404 with no addressed body)."""
    from ddt_tpu.serve.http import serve_forever

    eng = ServeEngine(api.ModelBundle(
        ensemble=trained["results"]["a"].ensemble,
        mapper=trained["results"]["a"].mapper),
        trained["cfg"], max_wait_ms=25.0, max_batch=32)
    ready = threading.Event()
    th = threading.Thread(target=serve_forever, args=(eng,),
                          kwargs=dict(port=0, ready_event=ready),
                          daemon=True)
    th.start()
    assert ready.wait(60)
    port = eng.http_port
    try:
        for path, headers in (
                ("/models/x/predict", {}),
                ("/predict", {"X-DDT-Model": "x"})):
            try:
                _post(port, path,
                      {"rows": trained["X"][:1].tolist()}, headers)
                raise AssertionError("fleet route accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 404
                assert json.loads(e.read())["model"] == "x"
    finally:
        _post(port, "/shutdown", {})
        th.join(30)


def test_http_reload_failure_is_503(trained):
    from ddt_tpu.serve.control import make_loader
    from ddt_tpu.serve.http import serve_forever

    loader = make_loader(None, "tpu")
    broken = {"on": False}

    def flaky(spec):
        if broken["on"]:
            raise OSError("store down")
        return loader(spec)

    eng = FleetEngine(_specs(trained, ("a", "b")), flaky,
                      max_wait_ms=25.0, max_resident=1)
    ready = threading.Event()
    th = threading.Thread(target=serve_forever, args=(eng,),
                          kwargs=dict(port=0, ready_event=ready),
                          daemon=True)
    th.start()
    assert ready.wait(60)
    port = eng.http_port
    try:
        X = trained["X"]
        _post(port, "/models/a/predict", {"rows": X[:1].tolist()})
        broken["on"] = True
        try:
            _post(port, "/models/b/predict", {"rows": X[:1].tolist()})
            raise AssertionError("reload failure served")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            body = json.loads(e.read())
            assert body["model"] == "b" and "store down" in body["reason"]
        broken["on"] = False
        r = _post(port, "/models/b/predict", {"rows": X[:2].tolist()})
        np.testing.assert_allclose(r["scores"], trained["ref"]["b"][:2],
                                   rtol=1e-5, atol=1e-6)
    finally:
        _post(port, "/shutdown", {})
        th.join(30)


# --------------------------------------------------------------------- #
# thread-model lint: zero findings on the fleet locks
# --------------------------------------------------------------------- #
def test_thread_model_clean_on_fleet_tier():
    """ddtlint's serve-tier thread/lock analysis over the WHOLE serve
    package (fleet.py + control.py included): zero findings, the fleet
    loop carries the dispatcher role, and the shared dispatch body
    carries both roles (the ISSUE 15 guardrail landing as promised)."""
    import ast

    from tools.ddtlint import threadmodel

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trees, sources = {}, {}
    for rel in ("ddt_tpu/serve/__init__.py", "ddt_tpu/serve/batcher.py",
                "ddt_tpu/serve/engine.py", "ddt_tpu/serve/fleet.py",
                "ddt_tpu/serve/control.py", "ddt_tpu/serve/http.py",
                "ddt_tpu/serve/metrics.py",
                "ddt_tpu/robustness/watchdog.py"):
        with open(os.path.join(repo, rel), encoding="utf-8") as f:
            sources[rel] = f.read()
        trees[rel] = ast.parse(sources[rel])
    m = threadmodel.build(trees, sources)
    assert m.findings == [], [f.render() for f in m.findings]
    loop = m.methods[("ddt_tpu/serve/fleet.py", "FleetEngine", "_loop")]
    assert "dispatcher" in loop.roles
    disp = m.methods[("ddt_tpu/serve/engine.py", "", "dispatch_batch")]
    assert disp.roles == {"dispatcher", "handler"}
    # the fleet's cross-role state is Condition-guarded
    assert ("FleetEngine", "_closed") in m.guarded


# --------------------------------------------------------------------- #
# ISSUE 17: SLO objectives, burn-rate breaches, traces, /metrics
# --------------------------------------------------------------------- #
def test_slo_spec_grammar_and_loud_rejection():
    """slo_p99_ms rides every config surface (--models grammar, JSON
    entries) and junk is a boot-time refusal, never a silently ignored
    objective."""
    specs = parse_models_arg("a@prod:slo_p99_ms=5.0,b@canary")
    assert specs[0].slo_p99_ms == 5.0
    assert specs[1].slo_p99_ms is None          # opt-in, never implied
    assert coerce_spec({"ref": "m@1", "slo_p99_ms": "2.5"},
                       "t").slo_p99_ms == 2.5
    with pytest.raises(FleetConfigError, match="positive number"):
        parse_models_arg("a@prod:slo_p99_ms=fast")
    with pytest.raises(FleetConfigError, match="must be > 0"):
        parse_models_arg("a@prod:slo_p99_ms=-1")
    with pytest.raises(FleetConfigError, match="must be > 0"):
        FleetSpec(name="a", ref="a@1", slo_p99_ms=0.0)


def test_slo_burn_tracker_latching_and_rearm():
    """The tracker unit-tested on a fake clock: burn rates need
    MIN_REQUESTS before they are trusted, a breach is a LATCHED
    transition (continuous burning is ONE event), and the latch re-arms
    only after the fast window cools below burn 1.0."""
    trk = SloBurnTracker(10.0)
    # under MIN_REQUESTS: every window abstains, nothing fires
    assert trk.record(0.0, [20.0] * 5) is None
    assert trk.burn_rates(0.0) == {"30s": None, "300s": None}
    assert not trk.has_pending()
    # the 20th all-violating sample: both windows qualify at burn 100
    b = trk.record(1.0, [20.0] * 15)
    assert b is not None and trk.breaches == 1
    assert b == {"burn_rate": 100.0, "objective_ms": 10.0,
                 "window_s": 30.0, "requests": 20}
    assert trk.has_pending()
    # latched: continued burning is the SAME breach, not a new page
    assert trk.record(2.0, [20.0] * 10) is None
    assert trk.breaches == 1
    # the bad batches age out of the 30s window; clean traffic cools
    # the fast burn to 0 -> the latch re-arms
    assert trk.record(40.0, [1.0] * 50) is None
    assert trk.burn_rates(40.0)["30s"] == 0.0
    # a second storm is a NEW breach
    assert trk.record(41.0, [20.0] * 50) is not None
    assert trk.breaches == 2
    pending = trk.take_pending()
    assert len(pending) == 2 and not trk.has_pending()


def test_fleet_slo_breach_counter_fault_and_trace_flush(trained):
    """Live end-to-end breach: a member with an impossible objective
    latches exactly ONE breach under sustained violation — the process
    counter bumps, the slo_breach fault validates with its burn-rate
    payload, the breach drags the trace ring out as a serve_trace
    event, and every surface (healthz, metrics snapshot, exposition)
    tells the same story. The un-SLO'd member stays schema-clean."""
    log = RunLog()
    c0 = tele_counters.snapshot()
    eng = _fleet(trained, ("a", "b"),
                 overrides={"a": {"slo_p99_ms": 0.0001}}, run_log=log)
    try:
        X = trained["X"]
        for i in range(SloBurnTracker.MIN_REQUESTS + 5):
            eng.predict(X[i:i + 1], model="a", timeout=60.0)
        h = eng.health()                  # handler touchpoint sweeps
        assert tele_counters.delta(c0)["slo_breaches"] == 1
        ha = h["models"]["a"]
        assert ha["slo_p99_ms"] == 0.0001
        assert ha["slo_breaches"] == 1
        assert ha["slo_burn_rate"]["30s"] >= SloBurnTracker.BREACH_BURN
        assert not any(k.startswith("slo") for k in h["models"]["b"])
        faults = [e for e in log.events("fault")
                  if e.get("kind") == "slo_breach"]
        assert len(faults) == 1, "latched breach must be ONE event"
        f = faults[0]
        assert f["model_name"] == "a" and f["objective_ms"] == 0.0001
        assert f["burn_rate"] >= SloBurnTracker.BREACH_BURN
        assert f["requests"] >= SloBurnTracker.MIN_REQUESTS
        assert f["window_s"] == SloBurnTracker.WINDOWS_S[0]
        validate_event(dict(f))
        flushed = [e for e in log.events("serve_trace")
                   if e.get("reason") == "slo_breach"]
        assert flushed and flushed[-1]["model_name"] == "a"
        assert flushed[-1]["count"] == len(flushed[-1]["traces"]) >= 1
        validate_event(dict(flushed[-1]))
        snap = eng.metrics_snapshot()
        assert snap["models"]["a"]["slo"]["breaches"] == 1
        assert snap["models"]["b"]["slo"] is None
        series = parse_exposition(
            render_metrics(tele_counters.snapshot(), snap))
        ka = frozenset({("model", "a")})
        assert series["ddt_serve_slo_breaches_total"][ka] == 1.0
        assert series["ddt_serve_slo_objective_ms"][ka] == 0.0001
        kw = frozenset({("model", "a"), ("window", "30s")})
        assert series["ddt_serve_slo_burn_rate"][kw] >= 2.0
        assert frozenset({("model", "b")}) not in \
            series["ddt_serve_slo_breaches_total"]
    finally:
        eng.close()


def test_fleet_healthz_backlog_and_resident_fields(trained):
    """The ISSUE 17 /healthz additions on a fleet WITHOUT SLOs:
    backlog_rows + resident_models appear, slo_* keys do not —
    schema-additive in both directions."""
    eng = _fleet(trained, ("a", "b"))
    try:
        eng.predict(trained["X"][:2], model="a", timeout=60.0)
        h = eng.health()
        assert h["resident_models"] == h["resident"] == 2
        assert h["backlog_rows"] == 0         # idle: queues drained
        for m in h["models"].values():
            assert not any(k.startswith("slo") for k in m)
    finally:
        eng.close()


def test_report_slo_mixed_era_and_fault_only_models():
    """`report slo` over a mixed pre-SLO / SLO-era log: pre-SLO models
    never enter the table, absent objectives and quantiles render `-`,
    and a model that breached before ever emitting a window enters
    through its fault alone."""
    base = {"event": "serve_latency", "schema": 5, "t": 1.0, "seq": 1,
            "requests": 50, "p50_ms": 1.0, "p99_ms": 4.0}
    events = [
        dict(base, model_name="old"),                     # pre-SLO era
        dict(base, seq=2, model_name="new", slo_p99_ms=5.0),
        dict(base, seq=3, model_name="new", p99_ms=9.0),  # older window
        {"event": "fault", "schema": 5, "t": 2.0, "seq": 4,
         "kind": "slo_breach", "model_name": "ghost",
         "burn_rate": 3.25, "objective_ms": 2.0, "window_s": 30.0,
         "requests": 40},
    ]
    summary = tele_report.summarize(events)
    slo = summary["slo"]
    assert set(slo["models"]) == {"new", "ghost"}
    assert slo["breaches"] == 1
    g = slo["models"]["ghost"]
    assert g["objective_ms"] == 2.0 and g["p99_ms"] is None
    assert g["breaches"] == 1 and g["max_burn_rate"] == 3.25
    n = slo["models"]["new"]
    assert n["objective_ms"] == 5.0 and n["windows"] == 2
    assert n["worst_p99_ms"] == 9.0 and n["breaches"] == 0
    rendered = tele_report.render_slo(summary)
    assert "slo: 2 model(s), 1 breach(es)" in rendered
    ghost_row = next(ln for ln in rendered.splitlines() if "ghost" in ln)
    assert "-" in ghost_row        # absent quantiles render, not crash
    assert "slo:" in tele_report.render(summary)
    # a purely pre-SLO log summarizes with NO slo section and the
    # dedicated renderer refuses loudly rather than printing zeros
    pre = tele_report.summarize([dict(base, model_name="old")])
    assert pre["slo"] is None
    with pytest.raises(ValueError, match="no SLO data"):
        tele_report.render_slo(pre)
    assert "slo:" not in tele_report.render(pre)


def test_http_fleet_trace_metrics_and_healthz(served_fleet, trained):
    """The live-socket sweep of the ISSUE 17 surfaces on a fleet:
    client trace ids round-trip with a timing breakdown header,
    /metrics exposes the per-model histogram + residency gauges, the
    debug ring holds the pinned id, and /healthz carries the fleet-wide
    backlog/residency rollup."""
    eng, port = served_fleet
    X = trained["X"]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/models/a/predict",
        data=json.dumps({"rows": X[:1].tolist()}).encode(),
        headers={"Content-Type": "application/json",
                 "X-DDT-Trace-Id": "fleet-pin-42"},
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        json.loads(r.read())
        assert r.headers["X-DDT-Trace-Id"] == "fleet-pin-42"
        timing = r.headers["X-DDT-Timing"]
    parts = dict(p.split("=") for p in timing.split(","))
    assert set(parts) == {"handler", "queue", "gate", "device", "wake",
                          "total"}
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    series = parse_exposition(text)
    ka = frozenset({("model", "a")})
    assert series["ddt_serve_latency_ms_count"][ka] >= 1
    assert series["ddt_serve_resident_models"][()] == 2
    assert series["ddt_serve_backlog_rows"][ka] == 0
    dbg = _get(port, "/debug/requests")
    assert any(t["trace_id"] == "fleet-pin-42"
               for t in dbg["models"]["a"])
    h = _get(port, "/healthz")
    assert h["resident_models"] == 2 and h["backlog_rows"] == 0
