"""Training operations plane (ISSUE 20): the live read-only status
daemon (ddt_tpu/telemetry/statusd.py), the shared Prometheus exposition
dialect it reuses (telemetry/exposition.py), the schema-additive
train_heartbeat event, the zero-overhead-when-disabled contract, and
`report progress` over a log whose run died mid-round. CPU platform,
tier-1."""

import json
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry import report
from ddt_tpu.telemetry.events import (
    EVENT_FIELDS, SCHEMA_VERSION, RunLog, emit_train_heartbeat,
    validate_event)
from ddt_tpu.telemetry.exposition import (
    EXPOSITION_CONTENT_TYPE, parse_exposition, render_counters)
from ddt_tpu.telemetry.statusd import TrainStatus, start_statusd


def _binary(rows, features=7, bins=29, seed=0):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    y = (Xb[:, 0] > bins // 2).astype(np.float32)
    return Xb, y


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5)


# --------------------------------------------------------------------- #
# the daemon: live socket sweep
# --------------------------------------------------------------------- #
def test_statusd_live_socket_sweep():
    """All three endpoints answer over a real socket: /healthz carries
    the progress snapshot (round i/N, rolling pace, ETA, checkpoint
    age, counters, memory watermarks), /metrics parses through the
    shared exposition parser with the train-plane series present, and
    /debug/rounds mirrors the round-record ring. Unknown routes 404
    with the route list."""
    st = TrainStatus()
    st.begin_run(run_id="deadbeef", total_rounds=10, rows=1000)
    st.round_end(0, 20.0, {"round": 1, "ms_per_round": 20.0})
    st.round_end(1, 10.0, {"round": 2, "ms_per_round": 10.0})
    st.checkpoint_saved(2)
    d = start_statusd(st, port=0)
    try:
        assert d.port > 0                      # bound before start() ran
        h = json.load(_get(d.port, "/healthz"))
        assert h["run_id"] == "deadbeef"
        assert h["phase"] == "train"
        assert (h["round"], h["total_rounds"], h["rows"]) == (2, 10, 1000)
        assert h["ms_per_round"] == pytest.approx(15.0)
        assert h["rows_per_s"] == pytest.approx(1000 / 0.015, rel=1e-3)
        assert h["eta_s"] == pytest.approx(8 * 0.015, rel=1e-3)
        assert h["last_checkpoint_round"] == 2
        assert h["checkpoint_age_s"] >= 0
        assert h["counters"]["fault_retries"] >= 0
        assert "host_peak_rss_bytes" in h and "device_peak_bytes" in h

        resp = _get(d.port, "/metrics")
        assert resp.headers["Content-Type"] == EXPOSITION_CONTENT_TYPE
        series = parse_exposition(resp.read().decode("utf-8"))
        # Every process counter under the shared ddt_<name>_total
        # naming, plus the train-plane gauges and the paper-facing
        # hist-allreduce alias.
        assert "ddt_train_rounds_total" in series
        assert "ddt_hist_allreduce_bytes_total" in series
        assert series["ddt_train_round"][()] == 2.0
        assert series["ddt_train_total_rounds"][()] == 10.0
        assert series["ddt_train_rows_per_s"][()] > 0
        assert "ddt_train_checkpoint_age_seconds" in series
        assert "ddt_host_peak_rss_bytes" in series

        rr = json.load(_get(d.port, "/debug/rounds"))
        assert rr["n"] == 2
        assert [r["round"] for r in rr["rounds"]] == [1, 2]

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(d.port, "/nope")
        assert ei.value.code == 404
        assert "/healthz" in json.loads(ei.value.read())["routes"]
    finally:
        d.close()


def test_statusd_scrape_is_strictly_read_only():
    """The /metrics contract: scraping mutates NOTHING. Consecutive
    scrapes with no trainer activity are identical (modulo the host-RSS
    watermark, which the probe itself may legitimately raise), the
    rolling window and ring are untouched, and the process counter
    snapshot is unchanged — the /stats?emit=1 contrast."""
    st = TrainStatus()
    st.begin_run(run_id="r", total_rounds=4, rows=100)
    st.round_end(0, 5.0, {"round": 1, "ms_per_round": 5.0})
    d = start_statusd(st, port=0)
    try:
        before = tele_counters.snapshot()
        a = _get(d.port, "/metrics").read()
        b = _get(d.port, "/metrics").read()

        def stable(body):
            return [ln for ln in body.decode().splitlines()
                    if not ln.startswith("ddt_host_peak_rss_bytes")]

        assert stable(a) == stable(b)          # scrape #1 changed nothing
        assert tele_counters.snapshot() == before
        # The trainer-side state is untouched too: window still holds
        # exactly one sample, ring exactly one record.
        assert len(st._round_ms) == 1
        assert len(st._ring) == 1
        # /healthz and /debug/rounds are just as inert.
        json.load(_get(d.port, "/healthz"))
        json.load(_get(d.port, "/debug/rounds"))
        assert tele_counters.snapshot() == before
    finally:
        d.close()


def test_statusd_counters_monotone_across_scrapes():
    """Round progress between scrapes is visible and monotone in BOTH
    exposed forms: the ddt_train_rounds_total counter and the
    ddt_train_round gauge never move backwards."""
    st = TrainStatus()
    st.begin_run(run_id="r", total_rounds=100, rows=10)
    d = start_statusd(st, port=0)
    try:
        seen_counter, seen_gauge = [], []
        for i in range(3):
            st.round_end(i, 1.0)
            tele_counters.record_train_round()
            series = parse_exposition(
                _get(d.port, "/metrics").read().decode())
            seen_counter.append(series["ddt_train_rounds_total"][()])
            seen_gauge.append(series["ddt_train_round"][()])
        assert seen_counter == sorted(seen_counter)
        assert seen_counter[-1] >= seen_counter[0] + 2
        assert seen_gauge == [1.0, 2.0, 3.0]
    finally:
        d.close()


# --------------------------------------------------------------------- #
# shared exposition dialect (the serve/metrics.py factoring)
# --------------------------------------------------------------------- #
def test_exposition_factored_not_forked():
    """serve/metrics.py re-exports the ONE dialect from
    telemetry/exposition.py — identity, not a copy — and the factored
    writer still renders the exact bytes the serve tier always did."""
    from ddt_tpu.serve import metrics as serve_metrics

    assert serve_metrics.render_counters is render_counters
    assert serve_metrics.parse_exposition is parse_exposition
    # Byte-level regression of the counter block format.
    assert render_counters({"x_total_bytes": 3}) == [
        "# TYPE ddt_x_total_bytes_total counter",
        "ddt_x_total_bytes_total 3",
    ]
    text = "\n".join(render_counters({"a": 1, "b": 2.5})) + "\n"
    parsed = parse_exposition(text)
    assert parsed["ddt_a_total"][()] == 1.0
    assert parsed["ddt_b_total"][()] == 2.5


def test_new_counters_registered_everywhere():
    """A counter is only real once all three registries agree: the live
    counter dict, the counters-event schema extras, and the diff tool's
    direction table (an unregistered counter silently vanishes from
    diffs — the failure this test exists to catch)."""
    from ddt_tpu.telemetry.diffing import COUNTER_DIRECTIONS
    from ddt_tpu.telemetry.events import EVENT_EXTRAS

    snap = tele_counters.snapshot()
    for name in ("train_rounds", "train_heartbeats"):
        assert name in snap
        assert name in EVENT_EXTRAS["counters"]
        assert name in COUNTER_DIRECTIONS


# --------------------------------------------------------------------- #
# train_heartbeat: schema-additive, pinned at birth
# --------------------------------------------------------------------- #
def test_train_heartbeat_schema_additive(tmp_path):
    """The new event rides schema v5 WITHOUT a version bump (additive
    growth contract): required fields pinned at birth in the lint
    contract, extras validated, and a v5 reader round-trips it."""
    from tools.ddtlint.telemetrycontract import PINNED_REQUIRED

    assert SCHEMA_VERSION == 5                  # additive, no bump
    assert EVENT_FIELDS["train_heartbeat"] == {"round"}
    assert PINNED_REQUIRED["train_heartbeat"] == frozenset({"round"})
    path = str(tmp_path / "run.jsonl")
    with RunLog(path) as rl:
        emit_train_heartbeat(rl, rnd=5, total_rounds=12,
                             checkpoint_round=6, ms_per_round=37.5,
                             rows_per_s=12000.0)
    (ev,) = report.read_events(path)
    validate_event(ev)
    assert ev["event"] == "train_heartbeat" and ev["schema"] == 5
    assert ev["round"] == 6                     # 1-based on the wire
    assert ev["checkpoint_round"] == 6
    assert ev["ms_per_round"] == 37.5


def test_driver_emits_heartbeats_at_checkpoint_cadence(tmp_path):
    """An in-memory train with checkpointing writes heartbeats at the
    cadence, monotone in round, stamping the checkpoint round the
    fused/granular loops actually saved."""
    Xb, y = _binary(1201)
    path = str(tmp_path / "run.jsonl")
    ckpt = str(tmp_path / "ckpt")
    with RunLog(path) as rl:
        api.train(Xb, y, binned=True, n_trees=4, max_depth=3, n_bins=29,
                  backend="tpu", run_log=rl, checkpoint_dir=ckpt,
                  checkpoint_every=2)
    hb = [e for e in report.read_events(path)
          if e["event"] == "train_heartbeat"]
    assert hb, "no heartbeats in a checkpointed run"
    rounds = [e["round"] for e in hb]
    assert rounds == sorted(rounds)
    assert rounds[-1] == 4
    assert any(e.get("checkpoint_round") for e in hb)
    assert all(e.get("total_rounds") == 4 for e in hb)


def test_statusd_tracks_a_real_training_run(tmp_path):
    """api.train(status=...) drives the aggregate end to end: run
    identity stamped, every round in the window, checkpoint recorded,
    phase 'done' at the epilogue."""
    Xb, y = _binary(1201)
    st = TrainStatus()
    api.train(Xb, y, binned=True, n_trees=4, max_depth=3, n_bins=29,
              backend="tpu", status=st,
              checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    h = st.healthz()
    assert h["round"] == 4 and h["total_rounds"] == 4
    assert h["phase"] == "done"
    assert h["run_id"]
    assert h["last_checkpoint_round"] is not None
    assert len(st.rounds_ring()) == 4


# --------------------------------------------------------------------- #
# zero overhead when disabled
# --------------------------------------------------------------------- #
def test_no_status_port_means_no_statusd_import_or_state(tmp_path):
    """The disabled-telemetry contract extended to the daemon: a train
    WITHOUT --status-port never imports the statusd module (it is
    lazily imported behind the flag) and the Driver's hook slot stays
    None — asserted, not assumed."""
    import inspect

    from ddt_tpu.cli import main as cli_main
    from ddt_tpu.driver import Driver

    assert inspect.signature(api.train).parameters["status"].default \
        is None
    assert inspect.signature(Driver.__init__).parameters["status"] \
        .default is None
    saved = sys.modules.pop("ddt_tpu.telemetry.statusd", None)
    try:
        rc = cli_main([
            "train", "--backend=tpu", "--dataset=higgs", "--rows=601",
            "--trees=2", "--depth=3",
            f"--out={tmp_path / 'm.npz'}"])
        assert rc == 0
        assert "ddt_tpu.telemetry.statusd" not in sys.modules
    finally:
        if saved is not None:
            sys.modules["ddt_tpu.telemetry.statusd"] = saved


# --------------------------------------------------------------------- #
# report progress: the mid-run-death question
# --------------------------------------------------------------------- #
def _dead_run_log(path, drift=False):
    """A run log whose process died mid-round: manifest, five rounds,
    heartbeats at the 2-cadence, NO run_end, and a torn final line."""
    with RunLog(str(path)) as rl:
        rl.emit("run_manifest", trainer="driver", backend="tpu",
                loss="logloss", n_trees=10, max_depth=3, rows=999,
                features=7)
        for r in range(5):
            rl.emit("round", round=r + 1, ms_per_round=100.0,
                    train_loss=0.6)
            if (r + 1) % 2 == 0:
                emit_train_heartbeat(rl, rnd=r, total_rounds=10,
                                     checkpoint_round=r + 1,
                                     ms_per_round=100.0,
                                     rows_per_s=9990.0)
        if drift:
            rl.emit("drift", psi_max=0.5, alerts=1)
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"event": "round", "schema": 5, "t": 1.0, "seq')


def test_report_progress_over_mid_round_death(tmp_path, capsys):
    """`report progress` places a dead run from its surviving
    heartbeats: round reached (max over heartbeats AND intact round
    records), last checkpoint, DIED MID-RUN state — through the torn
    final line the tolerant reader drops."""
    path = tmp_path / "dead.jsonl"
    _dead_run_log(path)
    summary = report.summarize(report.read_events(str(path)))
    pg = summary["progress"]
    assert pg["heartbeats"] == 2
    assert pg["last_round"] == 5               # round record beats hb 4
    assert pg["total_rounds"] == 10
    assert pg["last_checkpoint_round"] == 4
    assert pg["completed"] is False
    text = report.render_progress(summary)
    assert "DIED MID-RUN" in text
    assert "round 5/10" in text

    from ddt_tpu.cli import main as cli_main

    assert cli_main(["report", f"--log={path}", "progress"]) == 0
    assert "DIED MID-RUN" in capsys.readouterr().out


def test_report_progress_fails_loudly_without_heartbeats(tmp_path):
    """A log with no heartbeat data must refuse with a loud, specific
    error — at the renderer (ValueError) and at the CLI (SystemExit),
    never a silent empty table."""
    path = tmp_path / "old.jsonl"
    with RunLog(str(path)) as rl:
        rl.emit("run_manifest", trainer="driver", backend="tpu",
                loss="logloss", n_trees=2, max_depth=3, rows=10,
                features=4)
        rl.emit("round", round=1, ms_per_round=1.0, train_loss=0.5)
        rl.emit("run_end", completed_rounds=1, wallclock_s=0.1)
    summary = report.summarize(report.read_events(str(path)))
    assert summary["progress"] is None         # pre-ISSUE-20 logs: as-is
    with pytest.raises(ValueError, match="no training heartbeat"):
        report.render_progress(summary)

    from ddt_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="report: .*heartbeat"):
        cli_main(["report", f"--log={path}", "progress"])


def test_trace_renders_heartbeats_and_never_drops_kinds(tmp_path):
    """Perfetto export (the satellite): train_heartbeat lands on the
    rounds lane as an instant, and kinds with no dedicated mapping
    (e.g. drift) land on the catch-all 'events' lane instead of
    silently vanishing."""
    from ddt_tpu.telemetry import perfetto

    path = tmp_path / "dead.jsonl"
    _dead_run_log(path, drift=True)
    trace = perfetto.to_trace_events(report.read_events(str(path)))
    recs = trace["traceEvents"]
    hb = [r for r in recs if r["name"] == "train_heartbeat"]
    assert hb and all(r["tid"] == 0 and r["ph"] == "i" for r in hb)
    dr = [r for r in recs if r["name"] == "drift"]
    assert dr and dr[0]["tid"] == perfetto._MISC_TID
    lanes = {(r["pid"], r["tid"]): r["args"]["name"] for r in recs
             if r["name"] == "thread_name"}
    assert lanes[(0, perfetto._MISC_TID)] == "events"
