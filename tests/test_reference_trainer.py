"""M0 oracle tests (SURVEY.md §4: kernel-level + algorithm-level checks)."""

import numpy as np

from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import (
    synthetic_binary,
    synthetic_multiclass,
    synthetic_regression,
)
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.reference import numpy_trainer as ref


def auc(y, score):
    order = np.argsort(score)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(score) + 1)
    pos = y == 1
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_histogram_mass_conservation():
    # Property: per-node histogram sums == per-node grad/hess sums.
    rng = np.random.default_rng(0)
    R, F, B, N = 1000, 4, 16, 4
    Xb = rng.integers(0, B, (R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    node_index = rng.integers(-1, N, R).astype(np.int32)
    hist = ref.build_histograms(Xb, g, h, node_index, N, B)
    assert hist.shape == (N, F, B, 2)
    for n in range(N):
        mask = node_index == n
        for f in range(F):
            np.testing.assert_allclose(
                hist[n, f, :, 0].sum(), g[mask].sum(), rtol=1e-4, atol=1e-4
            )
            np.testing.assert_allclose(
                hist[n, f, :, 1].sum(), h[mask].sum(), rtol=1e-4, atol=1e-4
            )
    # Bin placement: brute-force check one (node, feature)
    for b in range(B):
        mask = (node_index == 1) & (Xb[:, 2] == b)
        np.testing.assert_allclose(
            hist[1, 2, b, 0], g[mask].sum(), rtol=1e-4, atol=1e-4
        )


def test_split_gain_hand_computed():
    # One node, one feature, 3 bins with known grad/hess sums.
    lam = 1.0
    hist = np.zeros((1, 1, 3, 2), np.float32)
    hist[0, 0, :, 0] = [-4.0, 1.0, 3.0]   # G per bin
    hist[0, 0, :, 1] = [2.0, 1.0, 2.0]    # H per bin
    gains, feats, bins, _ = ref.best_splits(hist, lam, min_child_weight=0.0)
    # Candidate splits: after bin0: GL=-4,HL=2 | GR=4,HR=3
    #                   after bin1: GL=-3,HL=3 | GR=3,HR=2
    parent = 0.0  # G=0 => G^2/(H+l) = 0
    g0 = 0.5 * (16 / 3 + 16 / 4 - parent)
    g1 = 0.5 * (9 / 4 + 9 / 3 - parent)
    assert g0 > g1
    # Returned gain is bf16-rounded (deterministic selection, see
    # ops/split.py) — compare at bf16 resolution.
    np.testing.assert_allclose(gains[0], g0, rtol=1 / 128)
    assert feats[0] == 0 and bins[0] == 0


def test_split_gain_respects_min_child_weight():
    hist = np.zeros((1, 1, 3, 2), np.float32)
    hist[0, 0, :, 0] = [-4.0, 1.0, 3.0]
    hist[0, 0, :, 1] = [0.5, 1.0, 2.0]
    gains, _, bins, _ = ref.best_splits(hist, 1.0, min_child_weight=1.0)
    assert bins[0] == 1  # split after bin0 invalid (HL=0.5 < 1.0)


def test_last_bin_never_chosen():
    hist = np.ones((1, 2, 4, 2), np.float32)
    _, _, bins, _ = ref.best_splits(hist, 1.0, 0.0)
    assert bins[0] < 3


def test_binary_training_learns():
    X, y = synthetic_binary(4000, seed=0)
    Xb, mapper = quantize(X, n_bins=63)
    cfg = TrainConfig(n_trees=20, max_depth=4, n_bins=63, backend="cpu",
                      learning_rate=0.3)
    ens = ref.fit(Xb, y, cfg, mapper)
    p = ens.predict(Xb, binned=True)
    assert auc(y, p) > 0.85
    # Raw-value prediction path agrees with binned path.
    p_raw = ens.predict(X, binned=False)
    np.testing.assert_allclose(p, p_raw, atol=1e-5)


def test_training_reduces_loss_monotonically_early():
    X, y = synthetic_binary(2000, seed=1)
    Xb, _ = quantize(X, n_bins=31)
    losses = []
    for t in (1, 5, 15):
        cfg = TrainConfig(n_trees=t, max_depth=3, n_bins=31, backend="cpu",
                          learning_rate=0.3)
        ens = ref.fit(Xb, y, cfg)
        p = np.clip(ens.predict(Xb, binned=True), 1e-7, 1 - 1e-7)
        losses.append(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
    assert losses[0] > losses[1] > losses[2]


def test_regression_mse():
    X, y = synthetic_regression(3000, seed=2)
    Xb, _ = quantize(X, n_bins=63)
    cfg = TrainConfig(n_trees=30, max_depth=4, n_bins=63, loss="mse",
                      backend="cpu", learning_rate=0.2)
    ens = ref.fit(Xb, y, cfg)
    pred = ens.predict(Xb, binned=True)
    mse = np.mean((pred - y) ** 2)
    base = np.var(y)
    assert mse < 0.35 * base


def test_multiclass_softmax():
    X, y = synthetic_multiclass(3000, n_features=20, n_classes=5, seed=3)
    Xb, _ = quantize(X, n_bins=63)
    cfg = TrainConfig(n_trees=10, max_depth=4, n_bins=63, loss="softmax",
                      n_classes=5, backend="cpu", learning_rate=0.3)
    ens = ref.fit(Xb, y, cfg)
    p = ens.predict(Xb, binned=True)
    assert p.shape == (3000, 5)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)
    acc = np.mean(np.argmax(p, axis=1) == y)
    assert acc > 0.8


def test_deterministic():
    X, y = synthetic_binary(1000, seed=4)
    Xb, _ = quantize(X, n_bins=31)
    cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=31, backend="cpu")
    e1 = ref.fit(Xb, y, cfg)
    e2 = ref.fit(Xb, y, cfg)
    assert np.array_equal(e1.feature, e2.feature)
    assert np.array_equal(e1.leaf_value, e2.leaf_value)


def test_ensemble_save_load(tmp_path):
    X, y = synthetic_binary(500, seed=5)
    Xb, mapper = quantize(X, n_bins=31)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=31, backend="cpu")
    ens = ref.fit(Xb, y, cfg, mapper)
    path = str(tmp_path / "ens.npz")
    ens.save(path)
    ens2 = ens.load(path)
    np.testing.assert_array_equal(ens.feature, ens2.feature)
    np.testing.assert_allclose(
        ens.predict(X), ens2.predict(X), atol=0
    )
