"""Native C++ kernel parity vs the NumPy oracle (exact — same f32 op order).

The native kernels are the honest CPU-reference baseline for the bench
(BASELINE.md); skipped cleanly when the toolchain can't build them.
"""

import numpy as np
import pytest

native = pytest.importorskip(
    "ddt_tpu.native", reason="native kernels unavailable (no toolchain?)"
)

from ddt_tpu.reference import numpy_trainer as ref  # noqa: E402


@pytest.mark.parametrize("R,F,B,N", [
    (1000, 6, 31, 1),
    (2048, 4, 255, 8),
    (777, 3, 16, 32),     # odd row count
])
def test_native_histogram_exact(R, F, B, N):
    rng = np.random.default_rng(1)
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(-1, N, size=R).astype(np.int32)
    want = ref.build_histograms(Xb, g, h, ni, N, B)
    got = native.histogram_native(Xb, g, h, ni, N, B)
    # Same accumulation order (row-major) → bit-exact.
    np.testing.assert_array_equal(want, got)


def test_native_traverse_matches_ensemble():
    from ddt_tpu.models.tree import empty_ensemble

    rng = np.random.default_rng(2)
    R, F, B, depth, T = 3000, 8, 63, 5, 12
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    ens = empty_ensemble(T, depth, F, 0.1, 0.0, "logloss")
    N = ens.feature.shape[1]
    ens.feature[:] = rng.integers(0, F, size=(T, N))
    ens.threshold_bin[:] = rng.integers(0, B - 1, size=(T, N))
    # Random early leaves + all-leaf last level.
    ens.is_leaf[:] = rng.random((T, N)) < 0.15
    ens.is_leaf[:, (1 << depth) - 1:] = True
    want = ens._traverse_np(Xb, binned=True)
    got = native.traverse_native(
        Xb, ens.feature, ens.threshold_bin, ens.is_leaf, depth
    )
    np.testing.assert_array_equal(want, got)


def test_cpu_backend_uses_native():
    """CPUDevice should pick the native kernel up automatically."""
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.config import TrainConfig

    be = CPUDevice(TrainConfig(backend="cpu", n_bins=31))
    assert be._native is not None
    rng = np.random.default_rng(3)
    Xb = rng.integers(0, 31, size=(500, 4), dtype=np.uint8)
    g = rng.standard_normal(500).astype(np.float32)
    h = rng.random(500).astype(np.float32)
    ni = rng.integers(0, 4, size=500).astype(np.int32)
    got = be.build_histograms(be.upload(Xb), g, h, ni, 4)
    want = ref.build_histograms(Xb, g, h, ni, 4, 31)
    np.testing.assert_array_equal(want, got)
