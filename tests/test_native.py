"""Native C++ kernel parity vs the NumPy oracle (exact — same f32 op order).

The native kernels are the honest CPU-reference baseline for the bench
(BASELINE.md); skipped cleanly when the toolchain can't build them.
"""

import numpy as np
import pytest

try:
    from ddt_tpu import native
except (ImportError, OSError) as _e:
    # ImportError: no toolchain. OSError: ctypes.CDLL on a corrupt/
    # wrong-arch lib or a DDT_NATIVE_LIB sanitizer build without its
    # runtime preloaded — skip, don't error. Other exception types are
    # real binding bugs and must propagate (round-5 advisor finding).
    pytest.skip(f"native kernels unavailable: {_e}",
                allow_module_level=True)

from ddt_tpu.config import TrainConfig  # noqa: E402
from ddt_tpu.reference import numpy_trainer as ref  # noqa: E402


# Bit-exactness vs the row-order NumPy oracle holds only on the serial
# kernel path; tests/conftest.py pins the whole suite to one OpenMP
# thread (rationale there). Multi-thread behavior is covered explicitly
# by test_native_multithread_allclose_deterministic below.


@pytest.mark.parametrize("R,F,B,N", [
    (1000, 6, 31, 1),
    (2048, 4, 255, 8),
    (777, 3, 16, 32),     # odd row count
])
def test_native_histogram_exact(R, F, B, N):
    rng = np.random.default_rng(1)
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(-1, N, size=R).astype(np.int32)
    want = ref.build_histograms(Xb, g, h, ni, N, B)
    got = native.histogram_native(Xb, g, h, ni, N, B)
    # Same accumulation order (row-major) → bit-exact.
    np.testing.assert_array_equal(want, got)


def test_native_traverse_matches_ensemble():
    from ddt_tpu.models.tree import empty_ensemble

    rng = np.random.default_rng(2)
    R, F, B, depth, T = 3000, 8, 63, 5, 12
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    ens = empty_ensemble(T, depth, F, 0.1, 0.0, "logloss")
    N = ens.feature.shape[1]
    ens.feature[:] = rng.integers(0, F, size=(T, N))
    ens.threshold_bin[:] = rng.integers(0, B - 1, size=(T, N))
    # Random early leaves + all-leaf last level.
    ens.is_leaf[:] = rng.random((T, N)) < 0.15
    ens.is_leaf[:, (1 << depth) - 1:] = True
    want = ens._traverse_np(Xb, binned=True)
    got = native.traverse_native(
        Xb, ens.feature, ens.threshold_bin, ens.is_leaf, depth
    )
    np.testing.assert_array_equal(want, got)


def test_cpu_backend_uses_native():
    """CPUDevice should pick the native kernels up automatically."""
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.config import TrainConfig

    be = CPUDevice(TrainConfig(backend="cpu", n_bins=31))
    assert be._native is not None
    assert be._native_split is not None
    assert be._native_traverse is not None


@pytest.mark.parametrize("reg_lambda,mcw,seed", [
    (1.0, 1e-3, 0),
    (0.0, 0.0, 1),      # NaN-masking path (0/0 gains)
    (5.0, 2.0, 2),      # min_child_weight pruning
])
def test_native_split_gain_exact(reg_lambda, mcw, seed):
    rng = np.random.default_rng(seed)
    N, F, B = 8, 5, 31
    hist = rng.standard_normal((N, F, B, 2)).astype(np.float32)
    hist[..., 1] = np.abs(hist[..., 1])          # hessians >= 0
    hist[2] = 0.0                                # empty node (no valid split)
    # Duplicate a feature to force exact bf16 ties → first-index tie-break.
    hist[:, 3] = hist[:, 1]
    want = ref.best_splits(hist, reg_lambda, mcw)[:3]
    got = native.split_gain_native(hist, reg_lambda, mcw)
    for w, g_ in zip(want, got):
        np.testing.assert_array_equal(w, g_)


def test_native_trainer_identical_to_numpy_trainer():
    """Full CPU training with native kernels == pure-NumPy oracle training,
    tree for tree (the bit-parity contract that makes the native path a
    legitimate drop-in)."""
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data.datasets import synthetic_binary
    from ddt_tpu.data.quantizer import quantize
    from ddt_tpu.driver import Driver

    X, y = synthetic_binary(3000, n_features=8, seed=13)
    Xb, _ = quantize(X, n_bins=63, seed=13)
    cfg = TrainConfig(n_trees=6, max_depth=4, n_bins=63, backend="cpu")
    e_native = Driver(
        CPUDevice(cfg, use_native=True), cfg, log_every=10**9).fit(Xb, y)
    e_numpy = Driver(
        CPUDevice(cfg, use_native=False), cfg, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(e_native.feature, e_numpy.feature)
    np.testing.assert_array_equal(e_native.threshold_bin,
                                  e_numpy.threshold_bin)
    np.testing.assert_array_equal(e_native.is_leaf, e_numpy.is_leaf)
    np.testing.assert_array_equal(e_native.leaf_value, e_numpy.leaf_value)


def test_native_predict_matches_numpy_predict():
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data.datasets import synthetic_multiclass
    from ddt_tpu.data.quantizer import quantize
    from ddt_tpu.driver import Driver

    X, y = synthetic_multiclass(1500, n_features=6, n_classes=3, seed=4)
    Xb, _ = quantize(X, n_bins=31, seed=4)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=31, backend="cpu",
                      loss="softmax", n_classes=3)
    be = CPUDevice(cfg, use_native=True)
    ens = Driver(be, cfg, log_every=10**9).fit(Xb, y)
    np.testing.assert_allclose(
        be.predict_raw(ens, Xb), ens.predict_raw(Xb, binned=True),
        rtol=1e-6, atol=1e-6)


def test_cpu_backend_histogram_exact():
    """be.build_histograms through the backend (not the raw kernel) is
    bit-exact vs the NumPy oracle."""
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.config import TrainConfig

    be = CPUDevice(TrainConfig(backend="cpu", n_bins=31), use_native=True)
    rng = np.random.default_rng(3)
    Xb = rng.integers(0, 31, size=(500, 4), dtype=np.uint8)
    g = rng.standard_normal(500).astype(np.float32)
    h = rng.random(500).astype(np.float32)
    ni = rng.integers(0, 4, size=500).astype(np.int32)
    got = be.build_histograms(be.upload(Xb), g, h, ni, 4)
    want = ref.build_histograms(Xb, g, h, ni, 4, 31)
    np.testing.assert_array_equal(want, got)


def test_split_gain_full_matches_oracle_fuzz():
    """ddt_split_gain_full == reference.best_splits EXACTLY across the
    full contract grid: feature masks, missing_bin direction scoring,
    categorical one-vs-rest, zero/nonzero reg_lambda and
    min_child_weight (bf16 argmax tie-breaks included)."""
    native = pytest.importorskip("ddt_tpu.native")

    rng = np.random.default_rng(0)
    for trial in range(50):
        n = int(rng.integers(1, 5))
        F = int(rng.integers(2, 7))
        B = int(rng.integers(3, 20))
        hist = rng.standard_normal((n, F, B, 2)).astype(np.float32)
        hist[..., 1] = np.abs(hist[..., 1])
        lam = float(rng.choice([0.0, 0.5, 1.0]))
        mcw = float(rng.choice([0.0, 1e-3, 0.7]))
        fm = rng.random(F) < 0.7 if rng.random() < 0.5 else None
        if fm is not None and not fm.any():
            fm[0] = True
        missing = bool(rng.random() < 0.5)
        cm = (rng.random(F) < 0.4) if rng.random() < 0.5 else None
        want = ref.best_splits(hist, lam, mcw, fm, missing_bin=missing,
                               cat_mask=cm)
        got = native.split_gain_full_native(hist, lam, mcw, fm, missing, cm)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(
                np.asarray(w, np.float64), np.asarray(g, np.float64),
                err_msg=f"trial {trial} lam={lam} mcw={mcw} "
                        f"missing={missing}")


def test_native_traverse_cat_routing_matches_numpy():
    """v3 traversal's one-vs-rest routing == TreeEnsemble's NumPy scorer
    on a trained categorical model (the native predict path no longer
    gates cat models off)."""
    pytest.importorskip("ddt_tpu.native")
    from ddt_tpu import api
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.data.categorical import fit_categorical_encoder
    from ddt_tpu.data.datasets import synthetic_ctr
    from ddt_tpu.data.quantizer import fit_bin_mapper

    Xn, Xc, y = synthetic_ctr(2000, seed=0)
    enc = fit_categorical_encoder(Xc, n_bins=63)
    X = np.concatenate([Xn, enc.transform(Xc).astype(np.float32)], axis=1)
    cat = tuple(range(Xn.shape[1], X.shape[1]))
    m = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    res = api.train(X, y, mapper=m, cat_features=cat, n_trees=5,
                    max_depth=4, n_bins=63, backend="cpu",
                    log_every=10**9)
    Xb = m.transform(X)
    be = CPUDevice(TrainConfig(backend="cpu", n_bins=63,
                               cat_features=cat), use_native=True)
    assert be._native_traverse is not None
    want = res.ensemble.predict_raw(Xb, binned=True)
    got = be.predict_raw(res.ensemble, Xb)
    np.testing.assert_array_equal(want, got)
    used = res.ensemble.feature[(~res.ensemble.is_leaf)
                                & (res.ensemble.feature >= 0)]
    assert np.isin(used, cat).any()


def test_cpu_backend_uses_native_full_split_missing_colsample():
    """The native full-contract SplitGain drives CPU training for
    missing+colsample configs (no silent NumPy fallback), growing trees
    identical to a native-disabled run. (Cat composes with
    missing_policy='zero' only — covered separately below.)"""
    pytest.importorskip("ddt_tpu.native")
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.driver import Driver

    rng = np.random.default_rng(5)
    X = rng.standard_normal((3000, 8)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (X[:, 0] > 0.2).astype(np.int64)
    y[np.isnan(X[:, 0])] = rng.integers(0, 2, np.isnan(X[:, 0]).sum())
    from ddt_tpu.data.quantizer import fit_bin_mapper

    m = fit_bin_mapper(X, n_bins=31, missing_policy="learn")
    Xb = m.transform(X)
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=31, backend="cpu",
                      missing_policy="learn", colsample_bytree=0.75)
    be_n = CPUDevice(cfg, use_native=True)
    assert be_n._native_split_full is not None
    be_0 = CPUDevice(cfg, use_native=False)
    e_n = Driver(be_n, cfg, log_every=10**9).fit(Xb, y)
    e_0 = Driver(be_0, cfg, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(e_n.feature, e_0.feature)
    np.testing.assert_array_equal(e_n.threshold_bin, e_0.threshold_bin)
    np.testing.assert_array_equal(e_n.default_left, e_0.default_left)
    np.testing.assert_allclose(e_n.leaf_value, e_0.leaf_value, rtol=1e-6)


def test_cpu_backend_uses_native_full_split_cat_training():
    """Driver-level categorical training through the native full-contract
    SplitGain equals a native-disabled run (cat wiring of the
    split_full path through grow_tree)."""
    pytest.importorskip("ddt_tpu.native")
    from ddt_tpu.backends.cpu import CPUDevice
    from ddt_tpu.data.categorical import fit_categorical_encoder
    from ddt_tpu.data.datasets import synthetic_ctr
    from ddt_tpu.data.quantizer import fit_bin_mapper
    from ddt_tpu.driver import Driver

    Xn, Xc, y = synthetic_ctr(2500, seed=2)
    enc = fit_categorical_encoder(Xc, n_bins=63)
    X = np.concatenate([Xn, enc.transform(Xc).astype(np.float32)], axis=1)
    cat = tuple(range(Xn.shape[1], X.shape[1]))
    m = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=63, backend="cpu",
                      cat_features=cat)
    be_n = CPUDevice(cfg, use_native=True)
    assert be_n._native_split_full is not None
    e_n = Driver(be_n, cfg, log_every=10**9).fit(Xb, y)
    e_0 = Driver(CPUDevice(cfg, use_native=False), cfg,
                 log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(e_n.feature, e_0.feature)
    np.testing.assert_array_equal(e_n.threshold_bin, e_0.threshold_bin)
    np.testing.assert_allclose(e_n.leaf_value, e_0.leaf_value, rtol=1e-6)
    used = e_n.feature[(~e_n.is_leaf) & (e_n.feature >= 0)]
    assert np.isin(used, cat).any()


def test_csv_parse_native_matches_loadtxt(tmp_path):
    """The native CSV parser (csv_loader.cpp) vs np.loadtxt on the exact
    subset load_file uses: comments, blank lines, headers skipped by
    physical count, \\r\\n endings, exponents, max_rows."""
    from ddt_tpu.native import csv_parse_native

    text = (
        "colA,colB,colC\n"            # header (skip_rows=1)
        "1.5,2,-3e2\r\n"
        "# a full-line comment\n"
        "\n"
        "4,5.25,6 # trailing comment\n"
        "-0.125,1e-3,+7\n"
    )
    p = tmp_path / "t.csv"
    p.write_text(text)
    want = np.loadtxt(str(p), delimiter=",", skiprows=1)
    got = csv_parse_native(text.encode(), skip_rows=1)
    np.testing.assert_array_equal(got, want)

    got2 = csv_parse_native(text.encode(), skip_rows=1, max_rows=2)
    np.testing.assert_array_equal(got2, want[:2])


def test_csv_parse_native_rejects_malformed():
    from ddt_tpu.native import csv_parse_native

    with pytest.raises(ValueError, match="line 2.*expected"):
        csv_parse_native(b"1,2,3\n4,5\n")
    with pytest.raises(ValueError, match="unparseable"):
        csv_parse_native(b"1,2\n3,x\n")
    with pytest.raises(ValueError, match="empty"):
        csv_parse_native(b"1,,3\n")
    assert csv_parse_native(b"").shape == (0, 0)


def test_load_file_csv_native_equals_fallback(tmp_path, monkeypatch):
    """load_file's CSV branch: native parse == np.loadtxt fallback."""
    from ddt_tpu.data import datasets as ds

    rng = np.random.default_rng(3)
    M = rng.standard_normal((200, 5)).round(4)
    M[:, 0] = rng.integers(0, 2, 200)
    p = tmp_path / "d.csv"
    np.savetxt(str(p), M, delimiter=",", fmt="%.6g")

    Xn, yn = ds.load_file(str(p))
    # Force the fallback by making the native import fail.
    import builtins
    real_import = builtins.__import__

    def block(name, *a, **k):
        if name == "ddt_tpu.native":
            raise ImportError("blocked for fallback test")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", block)
    Xf, yf = ds.load_file(str(p))
    np.testing.assert_array_equal(Xn, Xf)
    np.testing.assert_array_equal(yn, yf)


def test_native_multithread_allclose_deterministic():
    """The multi-thread kernel contract (and the TSan soak's parallel
    workout — native/Makefile): at a fixed team size >1 the histogram
    reduction is (a) deterministic run-to-run, (b) equal to the serial
    oracle up to float32 reassociation (~1e-6 relative), and (c) node/bin
    placement-exact (a race would corrupt placement or drop rows, moving
    sums far beyond reassociation noise). CSV parsing writes row-disjoint
    output, so it stays bit-exact at any team size."""
    rng = np.random.default_rng(7)
    R, F, B, N = 20_000, 8, 63, 16
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(-1, N, size=R).astype(np.int32)
    want = ref.build_histograms(Xb, g, h, ni, N, B)

    with native.omp_threads(4):
        a = native.histogram_native(Xb, g, h, ni, N, B)
        b = native.histogram_native(Xb, g, h, ni, N, B)
        np.testing.assert_array_equal(a, b)
        np.testing.assert_allclose(a, want, rtol=2e-5, atol=2e-5)

        M = rng.standard_normal((2_000, 6))
        text = "\n".join(",".join(f"{v:.6f}" for v in row) for row in M)
        got = native.csv_parse_native((text + "\n").encode())
        np.testing.assert_array_equal(got, np.round(M, 6))

        # split_gain + traversal parallelize over nodes/trees with
        # per-item serial scans and disjoint outputs: bit-exact at ANY
        # team size (no reassociation), so the oracle comparison is exact.
        hist = want + 0.0
        hist[..., 1] = np.abs(hist[..., 1])
        sw = ref.best_splits(hist, 1.0, 1e-3)[:3]
        sg = native.split_gain_native(hist, 1.0, 1e-3)
        for w_, g_ in zip(sw, sg):
            np.testing.assert_array_equal(w_, g_)

        from ddt_tpu.models.tree import empty_ensemble
        depth, T = 5, 12
        ens = empty_ensemble(T, depth, F, 0.1, 0.0, "logloss")
        NN = ens.feature.shape[1]
        ens.feature[:] = rng.integers(0, F, size=(T, NN))
        ens.threshold_bin[:] = rng.integers(0, B - 1, size=(T, NN))
        ens.is_leaf[:] = rng.random((T, NN)) < 0.15
        ens.is_leaf[:, (1 << depth) - 1:] = True
        np.testing.assert_array_equal(
            ens._traverse_np(Xb, binned=True),
            native.traverse_native(Xb, ens.feature, ens.threshold_bin,
                                   ens.is_leaf, depth))

        # Composed kernels under real interleaving (the shapes a single
        # kernel call can't produce): a full CPU Driver training at team
        # size 4 — histogram -> split_gain_full -> traversal per level,
        # every round. Gains here sit above the reassociation noise
        # floor, so tree STRUCTURE matches the serial run; leaf sums may
        # differ at float32 reassociation level only.
        from ddt_tpu.backends.cpu import CPUDevice
        from ddt_tpu.data.datasets import synthetic_binary
        from ddt_tpu.data.quantizer import quantize
        from ddt_tpu.driver import Driver

        X4, y4 = synthetic_binary(5000, n_features=8, seed=21)
        Xb4, _ = quantize(X4, n_bins=63, seed=21)
        cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=63, backend="cpu")
        e4 = Driver(CPUDevice(cfg, use_native=True), cfg,
                    log_every=10**9).fit(Xb4, y4)
    e1 = Driver(CPUDevice(cfg, use_native=True), cfg,
                log_every=10**9).fit(Xb4, y4)      # serial (suite pin)
    np.testing.assert_array_equal(e4.feature, e1.feature)
    np.testing.assert_array_equal(e4.threshold_bin, e1.threshold_bin)
    np.testing.assert_allclose(e4.leaf_value, e1.leaf_value,
                               rtol=1e-5, atol=1e-6)
