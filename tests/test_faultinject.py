"""Fault injection (SURVEY.md §5 "Failure detection/elastic recovery"):
SIGKILL a real training process mid-run, resume from its checkpoint, and
require the final ensemble to be IDENTICAL to an uninterrupted run —
training is deterministic given binned data, so recovery must be exact.

Runs the actual CLI in a subprocess (not an in-process simulation) on the
CPU backend with a synthetic dataset regenerated from the same seed."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(args, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        [sys.executable, "-m", "ddt_tpu.cli", *args],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, **kw,
    )


# Sized so the kill window is wide even with the native C++ kernels built:
# ~0.3 s/tree x 24 trees ≈ 7 s of training, first cursor at tree 2 — the
# 0.05 s poll + SIGKILL latency is orders of magnitude inside the remaining
# ~6 s (a 3000-row config finished before the kill landed on fast machines).
TRAIN_ARGS = [
    "train", "--backend=cpu", "--dataset=higgs", "--rows=50000",
    "--bins=63", "--trees=24", "--depth=5", "--seed=7",
    "--checkpoint-every=2",
]


def test_sigkill_mid_training_then_resume_is_exact(tmp_path):
    from ddt_tpu.models.tree import TreeEnsemble

    ck = str(tmp_path / "ck")
    out_a = str(tmp_path / "interrupted.npz")
    out_b = str(tmp_path / "clean.npz")

    # Start training, wait for the first checkpoint, SIGKILL the process.
    p = _cli(TRAIN_ARGS + ["--checkpoint-dir", ck, "--out", out_a])
    cursor = os.path.join(ck, "cursor.json")
    deadline = time.time() + 240
    while time.time() < deadline:
        if os.path.exists(cursor):
            break
        if p.poll() is not None:
            pytest.fail("training finished before a checkpoint appeared; "
                        "slow the config down")
        time.sleep(0.05)
    else:
        p.kill()
        pytest.fail("no checkpoint appeared in time")
    os.kill(p.pid, signal.SIGKILL)
    p.wait()
    assert not os.path.exists(out_a), "model should not exist after SIGKILL"

    # Resume from the checkpoint to completion.
    p2 = _cli(TRAIN_ARGS + ["--checkpoint-dir", ck, "--out", out_a])
    assert p2.wait(timeout=240) == 0

    # Uninterrupted run, fresh directory.
    p3 = _cli(TRAIN_ARGS + ["--checkpoint-dir", str(tmp_path / "ck2"),
                            "--out", out_b])
    assert p3.wait(timeout=240) == 0

    ea = TreeEnsemble.load(out_a)
    eb = TreeEnsemble.load(out_b)
    np.testing.assert_array_equal(ea.feature, eb.feature)
    np.testing.assert_array_equal(ea.threshold_bin, eb.threshold_bin)
    np.testing.assert_array_equal(ea.is_leaf, eb.is_leaf)
    # Resume rescoring replays fit's own per-round float32 accumulation
    # order (predict_raw_roundwise), so recovery is BIT-exact.
    np.testing.assert_array_equal(ea.leaf_value, eb.leaf_value)
    # Gains of pre-crash trees must survive the resume (round-1 verdict bug).
    np.testing.assert_array_equal(ea.split_gain, eb.split_gain)
    assert np.any(ea.split_gain > 0)
