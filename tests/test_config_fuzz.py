"""Randomized cross-backend config fuzz: CPU and TPU training must grow
IDENTICAL tree structure for any valid config (the repo-wide deterministic
split rule), and partitioned runs must equal single-device runs. One test,
wide net — dedicated suites cover each feature in depth; this catches
interaction regressions between them (loss x missing x cat x sampling x
partitions x bins x depth).
"""

import numpy as np
import pytest

from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.categorical import fit_categorical_encoder
from ddt_tpu.data.datasets import synthetic_binary, synthetic_multiclass
from ddt_tpu.data.quantizer import fit_bin_mapper
from ddt_tpu.driver import Driver
from tree_compare import assert_trees_match_mod_ties


def _random_case(rng):
    rows = int(rng.integers(300, 1500))
    n_num = int(rng.integers(3, 9))
    loss = rng.choice(["logloss", "mse", "softmax"])
    n_classes = int(rng.integers(3, 5)) if loss == "softmax" else 2
    missing = bool(rng.random() < 0.35)
    cat = bool(rng.random() < 0.35) and not missing   # config forbids both
    bins = int(rng.choice([7, 31, 63, 255]))

    X = rng.standard_normal((rows, n_num)).astype(np.float32)
    if loss == "softmax":
        _, y = synthetic_multiclass(rows, n_features=4,
                                    n_classes=n_classes,
                                    seed=int(rng.integers(99)))
        y = y[:rows]
    elif loss == "mse":
        y = (X[:, 0] * 1.5 + rng.standard_normal(rows) * 0.3).astype(
            np.float32)
    else:
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int64)
    cat_features: tuple = ()
    if cat:
        ids = rng.integers(0, 12, size=(rows, 2))
        enc = fit_categorical_encoder(ids, n_bins=bins)
        X = np.concatenate([X, enc.transform(ids).astype(np.float32)],
                           axis=1)
        cat_features = (n_num, n_num + 1)
        # categorical signal so cat splits actually get chosen sometimes
        y = np.asarray(y)
        if loss == "logloss":
            y = ((X[:, 0] > 0) | (ids[:, 0] == 3)).astype(np.int64)
    if missing:
        X[rng.random(X.shape) < 0.1] = np.nan

    # Cross-backend bit-identity holds when no node's split/no-split
    # DECISION sits at the f32 cancellation noise floor (ops/split.py
    # "Determinism boundary"): a signal-free node's best gain is ~1e-8
    # noise whose sign/magnitude varies with summation order, so
    # min_split_gain=0 puts the decision on a razor edge regardless of
    # reg_lambda; and reg_lambda=0 with min_child_weight=0 lets near-
    # empty children amplify the noise unboundedly. The fuzzer therefore
    # always carries a noise-floor min_split_gain, plus a hessian floor
    # when reg_lambda=0.
    lam = float(rng.choice([0.0, 1.0]))
    mcw = float(rng.choice([0.0, 1e-3, 0.5]))
    cfg = TrainConfig(
        n_trees=int(rng.integers(2, 5)),
        max_depth=int(rng.integers(2, 6)),
        n_bins=bins,
        loss=str(loss),
        n_classes=n_classes,
        learning_rate=float(rng.choice([0.1, 0.3])),
        reg_lambda=lam,
        min_split_gain=1e-3,
        min_child_weight=max(mcw, 1e-3) if lam == 0.0 else mcw,
        subsample=float(rng.choice([1.0, 0.8])),
        colsample_bytree=float(rng.choice([1.0, 0.7])),
        missing_policy="learn" if missing else "zero",
        cat_features=cat_features,
        seed=int(rng.integers(1000)),
    )
    m = fit_bin_mapper(X, n_bins=bins,
                       missing_policy=cfg.missing_policy,
                       cat_features=cat_features)
    return m.transform(X), np.asarray(y), cfg


@pytest.mark.parametrize("case_seed", range(15))
def test_random_config_backend_and_partition_identity(case_seed):
    rng = np.random.default_rng((97, case_seed))
    Xb, y, cfg = _random_case(rng)
    # ~1/3 of cases train weighted (round 3: weights ride the valid mask
    # through every path, so the whole identity matrix must hold with
    # them too).
    w = (rng.integers(1, 4, len(y)).astype(np.float64)
         if rng.random() < 0.35 else None)
    ens = {}
    for backend in ("cpu", "tpu"):
        c = cfg.replace(backend=backend)
        ens[backend] = Driver(get_backend(c), c, log_every=10**9).fit(
            Xb, y, sample_weight=w)
    np.testing.assert_array_equal(ens["cpu"].feature, ens["tpu"].feature)
    np.testing.assert_array_equal(ens["cpu"].threshold_bin,
                                  ens["tpu"].threshold_bin)
    np.testing.assert_array_equal(ens["cpu"].is_leaf, ens["tpu"].is_leaf)
    np.testing.assert_array_equal(ens["cpu"].default_left,
                                  ens["tpu"].default_left)
    np.testing.assert_allclose(ens["cpu"].leaf_value,
                               ens["tpu"].leaf_value,
                               rtol=2e-4, atol=2e-5)
    # a partitioned run on the mesh equals the single-device run
    parts = int(rng.choice([2, 4, 8]))
    cp = cfg.replace(backend="tpu", n_partitions=parts)
    ep = Driver(get_backend(cp), cp, log_every=10**9).fit(
        Xb, y, sample_weight=w)
    np.testing.assert_array_equal(ens["tpu"].feature, ep.feature)
    np.testing.assert_array_equal(ens["tpu"].threshold_bin,
                                  ep.threshold_bin)
    # and both backends score the result identically (tolerance)
    pc = get_backend(cfg.replace(backend="cpu")).predict_raw(
        ens["cpu"], Xb)
    pt = get_backend(cfg.replace(backend="tpu")).predict_raw(
        ens["cpu"], Xb)
    np.testing.assert_allclose(pc, pt, rtol=5e-4, atol=5e-5)


def test_lambda_zero_empty_nodes_have_finite_leaves():
    """reg_lambda=0 + empty intermediate nodes: the leaf value must be 0,
    not -0/0 = NaN (a predict-time row from DIFFERENT data can reach a
    node that was empty at training). Fuzz-discovered; guarded in
    ops/grow.py, the oracle, and streaming alike."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((120, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    m = fit_bin_mapper(X, n_bins=15)
    Xb = m.transform(X)
    cfg = TrainConfig(n_trees=2, max_depth=6, n_bins=15, reg_lambda=0.0,
                      min_child_weight=0.0)
    for backend in ("cpu", "tpu"):
        c = cfg.replace(backend=backend)
        ens = Driver(get_backend(c), c, log_every=10**9).fit(Xb, y)
        assert np.isfinite(ens.leaf_value).all(), backend
        # scoring previously-unseen data stays finite even through nodes
        # empty at training time
        X2 = rng.standard_normal((500, 4)).astype(np.float32) * 3
        p = ens.predict_raw(m.transform(X2), binned=True)
        assert np.isfinite(p).all(), backend


@pytest.mark.parametrize("trial", range(8))
def test_random_model_predict_paths_agree(trial):
    """Every scorer path — NumPy oracle, native C++ traversal, device
    traversal, api raw-with-mapper, float raw-threshold — agrees on a
    random model (missing/cat included). Exact where the path is exact;
    tight tolerance for bf16-assisted device descent and re-derived float
    thresholds."""
    from ddt_tpu import api
    from ddt_tpu.backends.cpu import CPUDevice

    rng = np.random.default_rng((31, trial))
    rows = int(rng.integers(200, 1200))
    F = int(rng.integers(3, 9))
    bins = int(rng.choice([7, 31, 63, 255]))
    loss = str(rng.choice(["logloss", "mse", "softmax"]))
    nc = int(rng.integers(3, 5)) if loss == "softmax" else 2
    missing = bool(rng.random() < 0.4)
    cat = bool(rng.random() < 0.4) and not missing
    X = rng.standard_normal((rows, F)).astype(np.float32)
    catf: tuple = ()
    if cat:
        ids = rng.integers(0, 10, size=(rows, 1))
        enc = fit_categorical_encoder(ids, n_bins=bins)
        X = np.concatenate([X, enc.transform(ids).astype(np.float32)], 1)
        catf = (F,)
    if missing:
        X[rng.random(X.shape) < 0.1] = np.nan
    y = (rng.integers(0, nc, rows) if loss == "softmax"
         else (np.nan_to_num(X[:, 0]) > 0).astype(np.int64)
         if loss == "logloss"
         else rng.standard_normal(rows).astype(np.float32))
    res = api.train(X, y, n_trees=int(rng.integers(2, 5)),
                    max_depth=int(rng.integers(2, 5)), n_bins=bins,
                    loss=loss, n_classes=nc, backend="cpu",
                    missing_policy="learn" if missing else "zero",
                    cat_features=catf, log_every=10**9)
    ens, m = res.ensemble, res.mapper
    Xb = m.transform(X)
    ref = ens.predict_raw(Xb, binned=True)
    exact = {
        "native": CPUDevice(TrainConfig(backend="cpu", n_bins=bins,
                                        cat_features=catf),
                            use_native=True).predict_raw(ens, Xb),
        "api_raw_mapper": api.predict(ens, X, mapper=m, raw=True),
    }
    for name, got in exact.items():
        np.testing.assert_allclose(ref, got, rtol=0, atol=0, err_msg=name)
    approx = {
        "device": get_backend(TrainConfig(backend="tpu", n_bins=bins,
                                          cat_features=catf)
                              ).predict_raw(ens, Xb),
        "raw_thresholds": ens.predict_raw(X, binned=False),
    }
    for name, got in approx.items():
        np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4,
                                   err_msg=name)




@pytest.mark.parametrize("case_seed", range(5))
def test_random_config_streaming_identity(case_seed):
    """Round-4 fuzz dimension: fit_streaming over RANDOM chunk boundaries
    and a RANDOM device-chunk-cache budget (0 .. whole dataset) must grow
    the in-memory Driver's exact trees for any valid config — the cache
    changes only when the H2D link is paid, never the math. Since round 5
    the fuzzed config space INCLUDES sampling (_random_case draws
    subsample/colsample freely): the stateless counter-based masks
    (ops/sampling) make bagged streaming equal bagged in-memory training
    bit-for-bit, chunk boundaries notwithstanding."""
    from ddt_tpu.streaming import fit_streaming

    rng = np.random.default_rng((113, case_seed))
    Xb, y, cfg = _random_case(rng)
    cfg = cfg.replace(backend="tpu")
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)

    rows = len(y)
    n_chunks = int(rng.integers(2, 6))
    bounds = np.linspace(0, rows, n_chunks + 1).astype(int)

    def chunk_fn(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

    chunk_fn.labels = lambda c: y[bounds[c]:bounds[c + 1]]
    chunk_fn.n_features = Xb.shape[1]
    budget = int(rng.integers(0, Xb.nbytes + 1))   # 0 = no caching
    streamed = fit_streaming(chunk_fn, n_chunks, cfg,
                             device_chunk_cache=budget)
    assert_trees_match_mod_ties(full, streamed, cfg.min_split_gain)
