"""Device-truth cost observatory (schema v3 — docs/OBSERVABILITY.md):
XLA cost-analysis capture + roofline verdicts, programmatic xprof
capture windows, and run-log diffing. CPU platform, tier-1.

Layers covered:
- cost_analysis event round trip through a REAL training run, and the
  report CLI's roofline table with bound-by verdicts for the hist, gain,
  and predict phases (the acceptance criterion, end to end);
- v1/v2 run logs still parse through report / merge / perfetto (the
  back-compat contract SCHEMA_VERSION bumps must keep);
- `report diff` flags a synthetic +30% gain-phase regression — with the
  right phase and counter named — and stays quiet on identical logs;
- the disabled path compiles/lowers nothing (extends the PR-2 zero-
  overhead guard; the run-side half lives in tests/test_telemetry.py);
- roofline verdict math on controlled synthetic inputs;
- the profiler capture window's parsing/block-capping and the
  profile-smoke script (`make profile-smoke`) in-process.
"""

import copy
import importlib.util
import json
import os

import numpy as np
import pytest

from ddt_tpu.telemetry import costmodel, diffing, perfetto, report
from ddt_tpu.telemetry import merge as tele_merge
from ddt_tpu.telemetry.events import RunLog
from ddt_tpu.telemetry.profiler import CaptureWindow, parse_rounds

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _binary(rows, features=7, bins=23, seed=0):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    y = (Xb[:, 0] > bins // 2).astype(np.float32)
    return Xb, y


# --------------------------------------------------------------------- #
# capture: a real run emits cost_analysis; the roofline joins it
# --------------------------------------------------------------------- #
def _streaming_cli_log(tmp_path, capsys) -> str:
    """One real 2-round streamed train through the CLI with a run log —
    the log every acceptance assertion below reads."""
    from ddt_tpu.cli import main

    log = str(tmp_path / "stream.jsonl")
    model = str(tmp_path / "ens.npz")
    rc = main([
        "train", "--backend=tpu", "--dataset=higgs", "--rows=900",
        "--trees=2", "--depth=3", "--bins=23", "--stream-chunks=2",
        "--valid-frac=0.25", f"--run-log={log}", f"--out={model}",
    ])
    assert rc == 0
    capsys.readouterr()
    return log


def test_cost_events_and_roofline_on_real_run(tmp_path, capsys):
    """Acceptance: a real 2-round run log carries cost_analysis events
    for the streamed device programs, and `report` renders a roofline
    row WITH a bound-by verdict for at least hist, gain, and predict."""
    from ddt_tpu.cli import main

    log = _streaming_cli_log(tmp_path, capsys)
    events = report.read_events(log)
    cost = [e for e in events if e["event"] == "cost_analysis"]
    assert cost, "telemetry run emitted no cost_analysis events"
    by_op = {e["op"]: e for e in cost}
    # The streamed device loop's programs registered their cost.
    assert "stream_hist" in by_op
    assert "stream_update" in by_op          # the predict-phase scorer
    for e in cost:
        assert e["calls"] >= 1
        assert e["flops"] >= 0 and e["bytes_accessed"] >= 0
        assert e["platform"] == "cpu"
        # memory_analysis landed (CPU XLA supports it on this jax).
        assert "signature" in e

    summary = report.summarize(events)
    roof = summary["roofline"]
    assert roof is not None
    rows = {r["phase"]: r for r in roof}
    verdicts = {"compute", "hbm", "recompile", "host"}
    for phase in ("hist", "gain", "predict"):
        assert phase in rows, (phase, sorted(rows))
        assert rows[phase]["verdict"] in verdicts
    # hist/predict carried device cost; gain is NumPy split selection by
    # design — no device program, so its verdict is host-side.
    assert rows["hist"]["gflops"] is not None
    assert rows["predict"]["gflops"] is not None
    assert rows["gain"]["verdict"] in ("host", "recompile")

    rc = main(["report", "--log", log])
    assert rc == 0
    text = capsys.readouterr().out
    assert "roofline (XLA cost model" in text
    for phase in ("hist", "gain", "predict"):
        # the phase's roofline row (not its phases-table row) carries
        # the "-> <verdict>" column
        assert any(ln.strip().startswith(phase) and "-> " in ln
                   for ln in text.splitlines()), (phase, text)
    assert "compiling)" in text              # compile-seconds satellite


def test_costed_wrapper_counts_calls_and_signatures():
    """CostedFn: one capture per (op, signature), a call count per
    signature, and full passthrough of the wrapped function."""
    import jax
    import jax.numpy as jnp

    calls = {"n": 0}

    @costmodel.costed("toy", phase="toyphase")
    @jax.jit
    def f(x):
        calls["n"] += 1              # traced: counts compiles, not calls
        return x * 2.0

    col = costmodel.activate()
    try:
        a = jnp.ones(8)
        b = jnp.ones(16)
        np.testing.assert_allclose(f(a), np.full(8, 2.0))
        f(a)
        f(b)
        evs = sorted(col.events(), key=lambda e: -e["calls"])
        assert [(e["op"], e["phase"], e["calls"]) for e in evs] == \
            [("toy", "toyphase", 2), ("toy", "toyphase", 1)]
        for e in evs:
            assert e["flops"] >= 0
            assert e["platform"] == "cpu"
    finally:
        costmodel.deactivate(col)
    # Wrapper passthrough: the underlying jit surface stays reachable.
    assert hasattr(f, "lower")


def test_analysis_compile_does_not_inflate_recompile_counters():
    """The capture's AOT analysis compile must not bill itself to the
    jit_compiles/jit_compile_seconds counters it exists to explain: one
    costed call = ONE counted compile, exactly like a telemetry-less
    run (a 2x-counters observatory would flag itself in report diff)."""
    import jax

    from ddt_tpu.telemetry import counters as tele_counters

    tele_counters.install_jax_listener()

    @costmodel.costed("toy3")
    @jax.jit
    def f(x):
        return x * 3.0

    col = costmodel.activate()
    try:
        c0 = tele_counters.snapshot()
        f(np.float32(2.0))               # fresh shape: capture + compile
        d = tele_counters.delta(c0)
        assert len(col.events()) == 1    # the capture DID run
        assert d["jit_compiles"] == 1, d
    finally:
        costmodel.deactivate(col)


def test_costmodel_analyze_sees_real_flops():
    import jax.numpy as jnp

    x = jnp.ones((64, 64), jnp.float32)
    rec = costmodel.analyze(lambda a: a @ a, x)
    assert rec.get("error") is None
    assert rec["flops"] > 64 * 64 * 64       # ~2*N^3 matmul flops
    assert rec["bytes_accessed"] > 0


def test_disabled_path_never_captures(monkeypatch):
    """No collector active -> a costed call must not lower, compile, or
    allocate capture state (the module-global read is the whole cost)."""
    import jax

    def _boom(*a, **k):
        raise AssertionError("capture ran while telemetry disabled")

    monkeypatch.setattr(costmodel, "_capture", _boom)

    @costmodel.costed("toy2")
    @jax.jit
    def f(x):
        return x + 1

    assert costmodel._active is None
    assert int(f(np.int32(1))) == 2          # plain call, no capture


def test_deactivate_only_removes_its_own_collector():
    c1 = costmodel.activate()
    c2 = costmodel.activate()                # replaces c1
    costmodel.deactivate(c1)                 # stale handle: no-op
    assert costmodel._active is c2
    costmodel.deactivate(c2)
    assert costmodel._active is None


# --------------------------------------------------------------------- #
# roofline verdict math (synthetic, controlled)
# --------------------------------------------------------------------- #
def _phase(name, ms, calls=1):
    return {"phase": name, "ms_total": ms,
            "ms_per_call": ms / calls, "calls": calls,
            "share": 1.0}


def _cost(phase, flops, byts, calls=1, platform="cpu"):
    return {"op": phase, "phase": phase, "flops": flops,
            "bytes_accessed": byts, "calls": calls, "platform": platform}


def test_roofline_verdicts():
    peaks = costmodel.PEAK_CEILINGS["cpu"]   # 150 GFLOP/s, 30 GB/s
    # 100 ms wall: 50% compute util, negligible bytes -> compute-bound.
    compute = _cost("a", 0.5 * peaks["gflops"] * 1e9 * 0.1, 1e3)
    # 100 ms wall: 50% HBM util, negligible flops -> hbm-bound.
    hbm = _cost("b", 1e3, 0.5 * peaks["gbs"] * 1e9 * 0.1)
    # device barely touched, low compile share -> host.
    idle = _cost("c", 1e3, 1e3)
    rows = costmodel.roofline_table(
        [_phase("a", 100.0), _phase("b", 100.0), _phase("c", 100.0)],
        [compute, hbm, idle],
        counters={"jit_compile_seconds": 0.0}, wallclock_s=10.0)
    verdict = {r["phase"]: r["verdict"] for r in rows}
    assert verdict == {"a": "compute", "b": "hbm", "c": "host"}
    util = {r["phase"]: r for r in rows}
    assert util["a"]["flops_util"] == pytest.approx(0.5, rel=1e-3)
    assert util["b"]["hbm_util"] == pytest.approx(0.5, rel=1e-3)


def test_roofline_recompile_verdict_and_growblock_fold():
    # Idle device + compile time over the wall-share threshold ->
    # recompile; grow_block's row folds in the fetch_tree barrier.
    rows = costmodel.roofline_table(
        [_phase("grow_block", 400.0), _phase("fetch_tree", 600.0)],
        [_cost("grow_block", 1e3, 1e3)],
        counters={"jit_compile_seconds": 3.0}, wallclock_s=10.0)
    assert len(rows) == 1                    # fetch_tree folded away
    assert rows[0]["phase"] == "grow_block"
    assert rows[0]["ms"] == pytest.approx(1000.0)
    assert rows[0]["verdict"] == "recompile"


def test_roofline_phase_without_cost_is_host():
    rows = costmodel.roofline_table(
        [_phase("gain", 50.0), _phase("hist", 100.0)],
        [_cost("hist", 1e9, 1e9)])
    by = {r["phase"]: r for r in rows}
    assert by["gain"]["verdict"] == "host"
    assert by["gain"]["gflops"] is None


# --------------------------------------------------------------------- #
# schema back-compat: v1/v2 logs through report / merge / perfetto
# --------------------------------------------------------------------- #
def _v1_log(tmp_path, name="v1.jsonl"):
    """A minimal schema-1 log exactly as the PR-2 writer shaped it."""
    recs = [
        {"event": "run_manifest", "schema": 1, "t": 100.0, "seq": 0,
         "trainer": "driver", "backend": "tpu", "loss": "logloss",
         "n_trees": 2, "max_depth": 3, "rows": 100, "features": 4},
        {"event": "round", "schema": 1, "t": 101.0, "seq": 1,
         "round": 1, "ms_per_round": 9.0, "train_loss": 0.6},
        {"event": "round", "schema": 1, "t": 102.0, "seq": 2,
         "round": 2, "ms_per_round": 8.0, "train_loss": 0.5},
        {"event": "phase_timings", "schema": 1, "t": 102.5, "seq": 3,
         "phases": [{"phase": "grow", "ms_total": 17.0,
                     "ms_per_call": 8.5, "calls": 2, "share": 1.0}]},
        {"event": "counters", "schema": 1, "t": 102.6, "seq": 4,
         "jit_compiles": 2, "h2d_bytes": 400, "d2h_bytes": 60,
         "collective_bytes_est": 0},
        {"event": "run_end", "schema": 1, "t": 102.7, "seq": 5,
         "completed_rounds": 2, "wallclock_s": 2.7},
    ]
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def _v2_log(tmp_path, host, name=None):
    """A schema-2 flight-recorder log (run_id/host manifest extras +
    partition events) — no v3 fields anywhere."""
    recs = [
        {"event": "run_manifest", "schema": 2, "t": 100.0 + host,
         "seq": 0, "trainer": "driver", "backend": "tpu",
         "loss": "logloss", "n_trees": 1, "max_depth": 3, "rows": 100,
         "features": 4, "run_id": "cafe01234567", "host": host},
        {"event": "partition_phases", "schema": 2, "t": 101.0 + host,
         "seq": 1, "round": 1, "rounds": 1, "partitions": [
             {"device": 0, "phases": {"grow": 5.0},
              "hist_allreduce_bytes": 64},
             {"device": 1, "phases": {"grow": 7.0},
              "hist_allreduce_bytes": 64}]},
        {"event": "partition_skew", "schema": 2, "t": 101.5 + host,
         "seq": 2, "phases": [
             {"phase": "grow", "ms_max": 7.0, "ms_median": 6.0,
              "skew": 1.167, "max_device": 1}], "n_partitions": 2},
        {"event": "run_end", "schema": 2, "t": 102.0 + host, "seq": 3,
         "completed_rounds": 1, "wallclock_s": 2.0},
    ]
    p = tmp_path / (name or f"v2_h{host}.jsonl")
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_v1_log_still_reads_summarizes_and_traces(tmp_path):
    p = _v1_log(tmp_path)
    events = report.read_events(p)           # validates every record
    summary = report.summarize(events)
    assert summary["completed_rounds"] == 2
    assert summary["roofline"] is None       # no v3 events -> no table
    assert summary["cost_events"] == []
    text = report.render(summary)
    assert "roofline" not in text            # renders exactly as before
    out = tmp_path / "v1_trace.json"
    n = perfetto.write_trace(events, str(out))
    trace = json.loads(out.read_text())
    assert len(trace["traceEvents"]) == n > 0


def test_v2_logs_still_merge_and_report(tmp_path):
    p0, p1 = _v2_log(tmp_path, 0), _v2_log(tmp_path, 1)
    merged = tele_merge.merge_paths([p0, p1])
    assert len(merged) == 8
    summary = report.summarize(merged)
    assert summary["hosts"] == [0, 1]
    assert summary["partition_skew"]         # cross-host recompute ran
    assert summary["roofline"] is None
    n = perfetto.write_trace(merged, str(tmp_path / "v2_trace.json"))
    assert n > 0


def test_v3_diff_reads_v1_logs_too(tmp_path):
    """The differ runs on pre-v3 logs (no cost events): phases and
    counters still align."""
    a = report.summarize(report.read_events(_v1_log(tmp_path, "a.jsonl")))
    b = report.summarize(report.read_events(_v1_log(tmp_path, "b.jsonl")))
    d = diffing.diff_summaries(a, b)
    assert d["flagged"] == []
    assert d["cost"] == []


# --------------------------------------------------------------------- #
# report diff
# --------------------------------------------------------------------- #
def _perturb_log(src_path: str, dst_path: str, gain_factor: float,
                 h2d_factor: float) -> None:
    """Clone a run log with the gain phase slowed by `gain_factor` and
    the H2D transfer counter inflated — the synthetic regression.
    (h2d_bytes rather than jit_compiles: the upload counter is nonzero
    on EVERY run, while a warm jit cache can legitimately leave the
    baseline's recompile count at 0 — and a zero baseline is exactly
    the case the differ declines to band.)"""
    with open(src_path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    out = []
    for line in lines:
        rec = json.loads(line)
        if rec["event"] == "phase_timings":
            rec = copy.deepcopy(rec)
            for p in rec["phases"]:
                if p["phase"] == "gain":
                    p["ms_total"] = round(p["ms_total"] * gain_factor, 3)
                    p["ms_per_call"] = round(
                        p["ms_per_call"] * gain_factor, 4)
        if rec["event"] == "counters":
            rec = dict(rec, h2d_bytes=int(rec["h2d_bytes"] * h2d_factor))
        out.append(json.dumps(rec))
    with open(dst_path, "w", encoding="utf-8") as f:
        f.write("\n".join(out) + "\n")


def test_report_diff_flags_synthetic_gain_regression(tmp_path, capsys):
    """Acceptance: diff attributes a synthetic +30%-past-band gain-phase
    regression to the right phase AND counter, and stays quiet on
    identical logs."""
    from ddt_tpu.cli import main

    log_a = _streaming_cli_log(tmp_path, capsys)
    log_b = str(tmp_path / "regressed.jsonl")
    # +30% on the gain phase (the ISSUE's synthetic regression) plus a
    # 4x transfer-bytes jump; the absolute floor is dropped because this
    # micro-run's real gain timings are sub-millisecond.
    _perturb_log(log_a, log_b, gain_factor=1.30001, h2d_factor=4.0)

    rc = main(["report", "diff", log_a, log_b, "--json",
               "--abs-floor-ms=0"])
    assert rc == 0
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert any(f.startswith("gain +") for f in d["flagged"]), d["flagged"]
    assert any(f.startswith("h2d_bytes ") for f in d["flagged"])
    gain = next(p for p in d["phases"] if p["phase"] == "gain")
    assert gain["flag"] == "slower"
    hist = next(p for p in d["phases"] if p["phase"] == "hist")
    assert hist["flag"] is None              # regression stays attributed

    # Identical logs: quiet, and --check exits 0.
    rc = main(["report", "diff", log_a, log_a, "--check"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "no adverse excursions" in text

    # --check turns a flagged diff into exit 1 (CI mode).
    rc = main(["report", "diff", log_a, log_b, "--check",
               "--abs-floor-ms=0"])
    capsys.readouterr()
    assert rc == 1


def test_diff_directionality_and_structure():
    """Unit checks on the band logic: favorable moves never flag, new /
    gone phases are marked, cache-hit counter flags on DECREASE."""
    a = {"phases": [_phase("hist", 1000.0), _phase("old", 100.0)],
         "counters": {"jit_compiles": 10,
                      "compiled_ensemble_cache_hits": 50},
         "cost_events": [_cost("hist", 1e9, 2e9)],
         "completed_rounds": 2, "wallclock_s": 2.0}
    b = {"phases": [_phase("hist", 500.0), _phase("new", 100.0)],
         "counters": {"jit_compiles": 11,
                      "compiled_ensemble_cache_hits": 0},
         "cost_events": [_cost("hist", 1e9, 2e9)],
         "completed_rounds": 2, "wallclock_s": 1.5}
    d = diffing.diff_summaries(a, b)
    by = {p["phase"]: p for p in d["phases"]}
    assert by["hist"]["flag"] is None        # 2x FASTER: never flagged
    assert by["old"]["flag"] == "gone"
    assert by["new"]["flag"] == "new"
    assert any("compiled_ensemble_cache_hits" in f for f in d["flagged"])
    # jit_compiles 10 -> 11 is inside the 20% band: not flagged.
    jc = next(c for c in d["counters"] if c["counter"] == "jit_compiles")
    assert jc["flag"] is None
    # hist cost identical: no bytes-bloat.
    assert all(c["flag"] is None for c in d["cost"])


def test_diff_unknown_and_neutral_counter_directions():
    """ISSUE 16 satellite: a counter missing from COUNTER_DIRECTIONS is
    reported with a loud direction=? marker (text AND --json record) but
    never flagged; a declared-"neutral" counter is banded in NO
    direction — a 10x workload-shape move stays quiet."""
    a = {"phases": [], "counters": {"mystery_counter": 10,
                                    "serve_requests": 10},
         "cost_events": [], "completed_rounds": 1, "wallclock_s": 1.0}
    b = {"phases": [], "counters": {"mystery_counter": 100,
                                    "serve_requests": 100},
         "cost_events": [], "completed_rounds": 1, "wallclock_s": 1.0}
    d = diffing.diff_summaries(a, b)
    by = {c["counter"]: c for c in d["counters"]}
    assert by["mystery_counter"]["direction"] == "?"
    assert by["mystery_counter"]["flag"] is None
    assert by["serve_requests"]["direction"] == "neutral"
    assert by["serve_requests"]["flag"] is None
    assert d["flagged"] == []
    text = diffing.render_diff(d)
    assert "mystery_counter" in text and "serve_requests" in text
    # exactly one marker: the unregistered counter, not the neutral one
    assert text.count("direction=?") == 1


# --------------------------------------------------------------------- #
# profiler capture window
# --------------------------------------------------------------------- #
def test_parse_rounds():
    assert parse_rounds("5:8") == (5, 8)
    assert parse_rounds("4") == (4, 4)
    with pytest.raises(ValueError, match="LO:HI"):
        parse_rounds("a:b")
    with pytest.raises(ValueError, match="empty or starts"):
        parse_rounds("8:5")
    with pytest.raises(ValueError, match="empty or starts"):
        parse_rounds("0:3")


def test_block_cap_aligns_blocks_to_window_edges(tmp_path):
    w = CaptureWindow(str(tmp_path), "5:8")
    # block [0, 10) must break at round 4 (0-based start edge lo-1=4).
    assert w.block_cap(0, 10) == 4
    # block [4, 10) must break at the stop edge hi=8.
    assert w.block_cap(4, 10) == 4
    # blocks fully inside or outside the window pass through.
    assert w.block_cap(4, 4) == 4
    assert w.block_cap(8, 10) == 10
    assert w.block_cap(0, 3) == 3


def test_capture_window_manifest_fields_and_close(tmp_path):
    w = CaptureWindow(str(tmp_path / "xp"), "1:2")
    w.bind("deadbeef0123")
    m = w.manifest_fields()
    assert m["xprof_rounds"] == [1, 2]
    assert os.path.basename(m["xprof_dir"]) == "run_deadbeef0123"
    # closing an unopened window is safe and terminal.
    w.close()
    assert not w.active
    w.round_start(0)                         # done: never restarts
    assert not w.active


def test_profile_smoke_script():
    """`make profile-smoke`, in-process (tier-1, non-slow): 2-round CPU
    capture-window train; asserts the manifest cross-reference fields
    and the written trace."""
    spec = importlib.util.spec_from_file_location(
        "profile_smoke", os.path.join(REPO, "scripts",
                                      "profile_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0
