"""Backend-parity + Driver/API tests (SURVEY.md §4 "Backend parity").

The DeviceBackend contract test: CPUDevice and TPUDevice produce identical
ensembles on fixed seeds, driven through the SAME Driver. Also covers the
registry flag, the FPGA stub, checkpoint/resume, and the api.train surface.
"""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.backends import FPGADevice, get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver


def _small_problem(rows=2000, loss="logloss", seed=0, bins=31):
    if loss == "softmax":
        X, y = datasets.synthetic_multiclass(rows, n_features=12, seed=seed)
    elif loss == "mse":
        X, y = datasets.synthetic_regression(rows, n_features=8, seed=seed)
    else:
        X, y = datasets.synthetic_binary(rows, n_features=10, seed=seed)
    Xb, mapper = quantize(X, n_bins=bins, seed=seed)
    return Xb, y, mapper


def _fit(backend_flag, Xb, y, **cfg_kw):
    cfg = TrainConfig(
        n_trees=5, max_depth=4, n_bins=31, backend=backend_flag, **cfg_kw
    )
    be = get_backend(cfg)
    return Driver(be, cfg, log_every=10**9).fit(Xb, y), cfg


@pytest.mark.parametrize("loss,extra", [
    ("logloss", {}),
    ("mse", {}),
    ("softmax", {"n_classes": 7}),
])
def test_backend_parity_cpu_vs_tpu(loss, extra):
    """The DeviceBackend contract: identical trees from both backends."""
    Xb, y, _ = _small_problem(loss=loss)
    ens_cpu, _ = _fit("cpu", Xb, y, loss=loss, **extra)
    ens_tpu, _ = _fit("tpu", Xb, y, loss=loss, **extra)

    np.testing.assert_array_equal(ens_cpu.feature, ens_tpu.feature)
    np.testing.assert_array_equal(ens_cpu.threshold_bin, ens_tpu.threshold_bin)
    np.testing.assert_array_equal(ens_cpu.is_leaf, ens_tpu.is_leaf)
    np.testing.assert_allclose(
        ens_cpu.leaf_value, ens_tpu.leaf_value, rtol=2e-4, atol=2e-5
    )


def test_backend_registry_flag():
    cfg = TrainConfig(backend="cpu")
    assert get_backend(cfg).name == "cpu"
    cfg = TrainConfig(backend="tpu")
    assert get_backend(cfg).name == "tpu"
    with pytest.raises(NotImplementedError, match="FPGA"):
        get_backend(TrainConfig(backend="fpga"))
    with pytest.raises(ValueError):
        TrainConfig(backend="cuda")


def test_granular_kernel_contract_via_backend():
    """build_histograms/best_splits through the L4 interface match the
    oracle — on both backends, including node_index -1 masking."""
    from ddt_tpu.reference import numpy_trainer as ref

    rng = np.random.default_rng(3)
    R, F, B, N = 512, 6, 16, 4
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(-1, N, size=R).astype(np.int32)

    want_h = ref.build_histograms(Xb, g, h, ni, N, B)
    want_s = ref.best_splits(want_h, 1.0, 1e-3)

    for flag in ("cpu", "tpu"):
        be = get_backend(TrainConfig(backend=flag, n_bins=B))
        data = be.upload(Xb)
        got_h = np.asarray(be.build_histograms(data, g, h, ni, N))
        np.testing.assert_allclose(got_h, want_h, rtol=1e-5, atol=1e-5)
        gains, feats, bins = be.best_splits(got_h)
        np.testing.assert_array_equal(np.asarray(feats), want_s[1])
        np.testing.assert_array_equal(np.asarray(bins), want_s[2])


def test_api_train_predict_roundtrip(tmp_path):
    X, y = datasets.synthetic_binary(3000, n_features=10, seed=1)
    res = api.train(X, y, n_trees=10, max_depth=4, n_bins=31,
                    backend="tpu", log_every=10**9)
    assert res.ensemble.n_trees == 10
    assert res.ensemble.has_raw_thresholds

    p_np = api.predict(res.ensemble, X, mapper=res.mapper)
    auc_inputs = p_np[y == 1].mean() - p_np[y == 0].mean()
    assert auc_inputs > 0.1  # learned something

    # device predict path agrees with the NumPy oracle scorer
    be = get_backend(TrainConfig(backend="tpu", n_bins=31))
    Xb = res.mapper.transform(X)
    p_dev = api.predict(res.ensemble, Xb, binned=True, backend=be)
    np.testing.assert_allclose(p_dev, p_np, rtol=2e-4, atol=2e-5)

    # save/load roundtrip
    path = str(tmp_path / "ens.npz")
    res.ensemble.save(path)
    from ddt_tpu.models.tree import TreeEnsemble

    loaded = TreeEnsemble.load(path)
    np.testing.assert_array_equal(loaded.feature, res.ensemble.feature)


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    """SURVEY.md §5 fault tolerance: train 10 trees straight vs 6 + resume 4;
    the ensembles must match."""
    Xb, y, _ = _small_problem(rows=1500)
    cfg = TrainConfig(n_trees=10, max_depth=4, n_bins=31, backend="tpu")

    be = get_backend(cfg)
    full = Driver(be, cfg, log_every=10**9).fit(Xb, y)

    ck = str(tmp_path / "ck")
    # Phase 1: "crash" after 6 rounds (simulated by only running 6).
    be1 = get_backend(cfg.replace(n_trees=6))
    Driver(be1, cfg.replace(n_trees=6), log_every=10**9,
           checkpoint_dir=ck, checkpoint_every=3).fit(Xb, y)
    # Phase 2: resume with the full config.
    be2 = get_backend(cfg)
    resumed = Driver(be2, cfg, log_every=10**9,
                     checkpoint_dir=ck, checkpoint_every=5).fit(Xb, y)

    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.threshold_bin, resumed.threshold_bin)
    np.testing.assert_allclose(full.leaf_value, resumed.leaf_value,
                               rtol=2e-4, atol=2e-5)
    # split_gain must survive resume too (round-1 verdict: trees trained
    # before the checkpoint silently lost their gains, corrupting
    # feature_importances("gain") after any resume).
    np.testing.assert_allclose(full.split_gain, resumed.split_gain,
                               rtol=2e-4, atol=2e-5)
    assert np.any(resumed.split_gain[:6] > 0)


def test_checkpoint_resume_with_sampling_matches_uninterrupted(tmp_path):
    """Round 5: bagging/colsample masks are STATELESS counter draws
    (ops/sampling) — there is no RNG stream to lose in a crash, so a
    resumed run recomputes the IDENTICAL masks for the rounds it
    continues (the fused path rebuilds them in-scan from first_round).
    6-then-resume-to-10 must equal straight-10, like the deterministic
    resume contract above."""
    Xb, y, _ = _small_problem(rows=1500)
    cfg = TrainConfig(n_trees=10, max_depth=4, n_bins=31, backend="tpu",
                      subsample=0.75, colsample_bytree=0.7, seed=11)

    be = get_backend(cfg)
    full = Driver(be, cfg, log_every=10**9).fit(Xb, y)

    ck = str(tmp_path / "ck")
    be1 = get_backend(cfg.replace(n_trees=6))
    Driver(be1, cfg.replace(n_trees=6), log_every=10**9,
           checkpoint_dir=ck, checkpoint_every=3).fit(Xb, y)
    be2 = get_backend(cfg)
    resumed = Driver(be2, cfg, log_every=10**9,
                     checkpoint_dir=ck, checkpoint_every=5).fit(Xb, y)

    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  resumed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, resumed.is_leaf)
    np.testing.assert_allclose(full.leaf_value, resumed.leaf_value,
                               rtol=2e-4, atol=2e-5)
    # Gains must survive a sampled resume too (the deterministic resume
    # test added this for a real round-1 regression; the fused masked
    # scan is a different writer and deserves the same tripwire).
    np.testing.assert_allclose(full.split_gain, resumed.split_gain,
                               rtol=2e-4, atol=2e-5)
    assert np.any(resumed.split_gain[:6] > 0)


def test_streaming_checkpoint_resume_with_sampling(tmp_path):
    """The streamed twin: a bagged streaming run interrupted at round 4
    resumes to the straight run's exact trees (per-chunk device masks
    re-derive from (seed, round, global row id) — nothing to replay)."""
    from ddt_tpu.streaming import fit_streaming

    Xb, y, _ = _small_problem(rows=2000)
    cfg = TrainConfig(n_trees=8, max_depth=3, n_bins=31, backend="tpu",
                      subsample=0.8, colsample_bytree=0.7, seed=5)

    def cf(c):
        return Xb[c * 500:(c + 1) * 500], y[c * 500:(c + 1) * 500]

    full = fit_streaming(cf, 4, cfg)
    ck = str(tmp_path / "ck")
    fit_streaming(cf, 4, cfg.replace(n_trees=4), checkpoint_dir=ck,
                  checkpoint_every=2)
    resumed = fit_streaming(cf, 4, cfg, checkpoint_dir=ck,
                            checkpoint_every=4)
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  resumed.threshold_bin)
    np.testing.assert_array_equal(full.leaf_value, resumed.leaf_value)


def test_checkpoint_config_mismatch_refuses(tmp_path):
    Xb, y, _ = _small_problem(rows=500)
    ck = str(tmp_path / "ck")
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=31, backend="cpu")
    Driver(get_backend(cfg), cfg, log_every=10**9,
           checkpoint_dir=ck, checkpoint_every=2).fit(Xb, y)
    bad = cfg.replace(max_depth=5)
    with pytest.raises(ValueError, match="incompatible"):
        Driver(get_backend(bad), bad, log_every=10**9,
               checkpoint_dir=ck).fit(Xb, y)


def test_driver_history_logging():
    Xb, y, _ = _small_problem(rows=800)
    cfg = TrainConfig(n_trees=6, max_depth=3, n_bins=31, backend="tpu")
    d = Driver(get_backend(cfg), cfg, log_every=2)
    d.fit(Xb, y)
    assert len(d.history) == 3
    assert d.history[-1]["round"] == 6
    losses = [r["train_loss"] for r in d.history]
    assert losses == sorted(losses, reverse=True)  # loss decreases


@pytest.mark.parametrize("depth,bins,loss", [
    (1, 2, "logloss"),      # stumps on binary bins
    (2, 3, "mse"),
    (8, 17, "logloss"),     # deep + few bins: most nodes become leaves
    (3, 256, "logloss"),    # full uint8 range
    (2, 63, "softmax"),
])
def test_backend_parity_edge_configs(depth, bins, loss):
    """CPU and TPU grow identical trees across uncommon shapes."""
    from ddt_tpu import api
    from ddt_tpu.data.datasets import synthetic_binary, synthetic_multiclass
    from ddt_tpu.data.quantizer import quantize

    if loss == "softmax":
        X, y = synthetic_multiclass(1200, n_features=5, n_classes=3, seed=7)
        extra = dict(loss="softmax", n_classes=3)
    else:
        X, y = synthetic_binary(1200, n_features=5, seed=7)
        if loss == "mse":
            y = y + 0.1 * np.random.default_rng(0).standard_normal(len(y))
        extra = dict(loss=loss)
    Xb, _ = quantize(X, n_bins=bins, seed=7)
    kw = dict(n_trees=3, max_depth=depth, n_bins=bins, seed=7, **extra)
    ec = api.train(Xb, y, TrainConfig(backend="cpu", **kw),
                   binned=True, log_every=10 ** 9).ensemble
    et = api.train(Xb, y, TrainConfig(backend="tpu", **kw),
                   binned=True, log_every=10 ** 9).ensemble
    np.testing.assert_array_equal(ec.feature, et.feature)
    np.testing.assert_array_equal(ec.threshold_bin, et.threshold_bin)
    np.testing.assert_array_equal(ec.is_leaf, et.is_leaf)
    np.testing.assert_allclose(ec.leaf_value, et.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_api_predict_accepts_model_bundle(tmp_path):
    """api.predict(load_model(path), X) scores with the training-time
    mapper automatically (the complete-artifact contract end to end)."""
    from ddt_tpu import api
    from ddt_tpu.data.datasets import synthetic_binary

    X, y = synthetic_binary(1500, n_features=6, seed=2)
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31,
                    backend="cpu", log_every=10**9)
    p = str(tmp_path / "m.npz")
    res.save(p)
    bundle = api.load_model(p)
    got = api.predict(bundle, X)
    want = api.predict(res.ensemble, X, mapper=res.mapper)
    np.testing.assert_array_equal(got, want)


def test_predict_backend_row_chunking_identity(monkeypatch):
    """The backend-level row-chunked scoring path (R > PREDICT_ROW_CHUNK;
    overlapped per-chunk D2H since round 5) equals the host oracle and
    the unchunked path exactly — including a non-multiple final chunk."""
    from ddt_tpu.backends.tpu import TPUDevice

    Xb, y, _ = _small_problem()
    cfg = TrainConfig(n_trees=6, max_depth=4, n_bins=31, backend="tpu")
    be = get_backend(cfg)
    ens = Driver(be, cfg, log_every=10**9).fit(Xb, y)
    want = be.predict_raw(ens, Xb)                   # single dispatch
    monkeypatch.setattr(TPUDevice, "PREDICT_ROW_CHUNK", 96)
    assert Xb.shape[0] % 96 != 0                     # ragged tail chunk
    got = be.predict_raw(ens, Xb)                    # chunked + async D2H
    np.testing.assert_array_equal(want, got)
    np.testing.assert_allclose(
        got, ens.predict_raw(Xb, binned=True), rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("block_rounds", [3, 4])
def test_fused_block_cap_multi_block_identity(block_rounds):
    """Long configs split into multiple fused dispatches
    (cfg.fused_block_rounds caps single-dispatch runtime — an
    unbounded 500-round block crashed the remote chip worker in round
    4). Block boundaries must not change results: a 10-round run forced
    through small blocks (both even and uneven final blocks) equals the
    single-block run and the CPU oracle exactly."""
    Xb, y, _ = _small_problem()

    def fit(backend, fused_block_rounds=100):
        cfg = TrainConfig(n_trees=10, max_depth=4, n_bins=31,
                          backend=backend,
                          fused_block_rounds=fused_block_rounds)
        return Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)

    one_block = fit("tpu")
    multi_block = fit("tpu", fused_block_rounds=block_rounds)
    cpu = fit("cpu")
    for k in ("feature", "threshold_bin", "is_leaf", "leaf_value",
              "split_gain", "default_left"):
        a, b = getattr(one_block, k), getattr(multi_block, k)
        if a is None or b is None:          # default_left on non-missing
            assert a is b, k                # models: None on BOTH sides
        else:
            np.testing.assert_array_equal(a, b, err_msg=k)
    np.testing.assert_array_equal(cpu.feature, multi_block.feature)
