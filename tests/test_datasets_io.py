"""Real-data file ingestion (BASELINE configs 1-3) + model-artifact
completeness (round-1 verdict items 2 and 4).

The loaders read the on-disk formats the reference datasets actually ship
in — UCI Higgs CSV.gz (label first), UCI Covertype CSV (label last, classes
1..7), libsvm sparse text, and our own .npz — so real data can be dropped
in the moment a file exists. The artifact tests pin the contract that
predict-time preprocessing comes from the TRAINING-time mapper/encoder
stored in the model file, never refit on scoring data.
"""

import gzip
import json
import subprocess
import sys

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.data import datasets


# ------------------------------------------------------------------ #
# load_file formats
# ------------------------------------------------------------------ #

def test_load_npz_roundtrip(tmp_path):
    X = np.random.default_rng(0).standard_normal((50, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    p = str(tmp_path / "d.npz")
    np.savez(p, X=X, y=y)
    X2, y2 = datasets.load_file(p)
    np.testing.assert_array_equal(X2, X)
    np.testing.assert_array_equal(y2, y)
    assert y2.dtype == np.int32


def test_load_npz_missing_keys_raises(tmp_path):
    p = str(tmp_path / "bad.npz")
    np.savez(p, a=np.zeros(3))
    with pytest.raises(ValueError, match="must contain arrays 'X' and 'y'"):
        datasets.load_file(p)


def test_load_csv_higgs_convention(tmp_path):
    """UCI Higgs: label is the FIRST column, features follow."""
    rng = np.random.default_rng(1)
    X = rng.standard_normal((30, 5)).astype(np.float32)
    y = rng.integers(0, 2, 30)
    p = str(tmp_path / "higgs.csv")
    M = np.column_stack([y.astype(np.float64), X])
    np.savetxt(p, M, delimiter=",")
    X2, y2 = datasets.load_file(p)
    np.testing.assert_allclose(X2, X, rtol=1e-6)
    np.testing.assert_array_equal(y2, y)


def test_load_csv_covertype_convention(tmp_path):
    """UCI Covertype: label is the LAST column, classes 1..7 -> 0..6."""
    rng = np.random.default_rng(2)
    X = rng.standard_normal((40, 6)).astype(np.float32) * 10 + 100
    y = rng.integers(1, 8, 40)  # 1-based classes
    p = str(tmp_path / "covtype.csv")
    np.savetxt(p, np.column_stack([X, y.astype(np.float64)]), delimiter=",")
    X2, y2 = datasets.load_file(p, label_col="last")
    np.testing.assert_allclose(X2, X, rtol=1e-6)
    np.testing.assert_array_equal(y2, y - 1)


def test_load_csv_gz_with_header_and_auto_label(tmp_path):
    rng = np.random.default_rng(3)
    X = rng.standard_normal((25, 3))
    y = rng.integers(0, 2, 25)
    p = str(tmp_path / "d.csv.gz")
    lines = ["label,f0,f1,f2"]
    for i in range(25):
        lines.append(",".join([str(y[i])] + [f"{v:.6f}" for v in X[i]]))
    with gzip.open(p, "wt") as f:
        f.write("\n".join(lines) + "\n")
    X2, y2 = datasets.load_file(p)  # auto: header skipped, label=first
    assert X2.shape == (25, 3)
    np.testing.assert_array_equal(y2, y)


def test_load_libsvm_sparse(tmp_path):
    p = str(tmp_path / "d.libsvm")
    with open(p, "w") as f:
        f.write("1 1:0.5 3:2.0\n")
        f.write("0 2:-1.0\n")
        f.write("# comment line\n")
        f.write("1 1:1.0 4:4.0  # trailing comment\n")
    X, y = datasets.load_file(p)
    assert X.shape == (3, 4)
    np.testing.assert_allclose(
        X, [[0.5, 0, 2.0, 0], [0, -1.0, 0, 0], [1.0, 0, 0, 4.0]]
    )
    np.testing.assert_array_equal(y, [1, 0, 1])


def test_load_libsvm_bad_line_raises(tmp_path):
    p = str(tmp_path / "bad.txt")
    with open(p, "w") as f:
        f.write("1 0:0.5\n")  # 0-based index: invalid
    with pytest.raises(ValueError, match="bad libsvm line"):
        datasets.load_file(p)


def test_load_libsvm_minus_one_plus_one_labels(tmp_path):
    """The dominant binary-libsvm convention {-1,+1} maps to {0,1}."""
    p = str(tmp_path / "d.libsvm")
    with open(p, "w") as f:
        f.write("-1 1:0.5\n+1 2:1.0\n-1 1:2.0\n")
    _, y = datasets.load_file(p)
    np.testing.assert_array_equal(y, [0, 1, 0])


def test_load_npz_labels_verbatim(tmp_path):
    """.npz is our own format: y passes through untouched — integer
    regression targets 1..k must NOT be shifted."""
    p = str(tmp_path / "d.npz")
    yc = np.arange(1, 41)   # counts 1..40
    np.savez(p, X=np.zeros((40, 2), np.float32), y=yc)
    _, y = datasets.load_file(p)
    np.testing.assert_array_equal(y, yc)


def test_load_csv_regression_labels_not_normalized(tmp_path):
    """normalize_labels=False keeps 1-based integer targets for mse."""
    rng = np.random.default_rng(4)
    X = rng.standard_normal((20, 3))
    yc = rng.integers(1, 6, 20)
    p = str(tmp_path / "r.csv")
    np.savetxt(p, np.column_stack([X, yc.astype(np.float64)]), delimiter=",")
    _, y = datasets.load_file(p, label_col="last", normalize_labels=False)
    np.testing.assert_array_equal(y, yc)


def test_cli_label_col_last_for_regression(tmp_path, capsys):
    """--label-col=last trains on the true last-column float target."""
    from ddt_tpu.cli import main

    X, yt = datasets.synthetic_regression(800, n_features=6, seed=9)
    p = str(tmp_path / "r.csv")
    np.savetxt(p, np.column_stack([X, yt.astype(np.float64)]), delimiter=",")
    model = str(tmp_path / "m.npz")
    rc = main(["train", "--backend=cpu", f"--data={p}", "--label-col=last",
               "--loss=mse", "--trees=3", "--depth=3", "--bins=31",
               f"--out={model}"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # Training on the true target beats the variance of y; a label grabbed
    # from a feature column would leave loss ~ var(feature col 0).
    assert rec["final_train_loss"] < np.var(yt) * 0.7


def test_cli_criteo_predict_refuses_missing_encoder(tmp_path, capsys):
    from ddt_tpu.cli import main

    X, y = datasets.synthetic_binary(500, n_features=8, seed=1)
    res = api.train(X, y, n_trees=2, max_depth=2, n_bins=31,
                    backend="cpu", log_every=10**9)
    model = str(tmp_path / "no_enc.npz")
    res.save(model)  # API save: no encoder stored
    with pytest.raises(SystemExit, match="categorical encoder"):
        main(["predict", "--backend=cpu", f"--model={model}",
              "--dataset=criteo", "--rows=100", "--bins=31"])


def test_load_libsvm_n_features_pins_width(tmp_path):
    """A sparse scoring file must not shrink X below the model's width."""
    p = str(tmp_path / "d.libsvm")
    with open(p, "w") as f:
        f.write("1 1:0.5 2:1.0\n0 1:2.0\n")   # max observed index = 2
    X, _ = datasets.load_file(p, n_features=5)
    assert X.shape == (2, 5)
    with pytest.raises(ValueError, match="exceeds n_features"):
        datasets.load_file(p, n_features=1)


def test_load_libsvm_dense_guardrail(tmp_path, monkeypatch):
    monkeypatch.setattr(datasets, "_LIBSVM_DENSE_MAX_ELEMS", 10)
    p = str(tmp_path / "d.libsvm")
    with open(p, "w") as f:
        f.write("1 20:0.5\n")   # 1 row x 20 cols > 10 elems
    with pytest.raises(ValueError, match="dense-only"):
        datasets.load_file(p)


def test_labels_not_shifted_when_class_zero_merely_absent(tmp_path):
    """An all-positive slice {1} or a non-contiguous set must pass through."""
    p = str(tmp_path / "d.libsvm")
    with open(p, "w") as f:
        f.write("1 1:0.5\n1 1:1.5\n")      # only label 1 present
    _, y = datasets.load_file(p)
    np.testing.assert_array_equal(y, [1, 1])

    p2 = str(tmp_path / "d2.libsvm")
    with open(p2, "w") as f:
        f.write("1 1:0.5\n3 1:1.5\n")      # {1,3}: not contiguous 1..k
    _, y2 = datasets.load_file(p2)
    np.testing.assert_array_equal(y2, [1, 3])


def test_cli_predict_rejects_wrong_width_file(tmp_path, capsys):
    from ddt_tpu.cli import main

    X, y = datasets.synthetic_binary(800, n_features=8, seed=0)
    ptrain = str(tmp_path / "t.npz")
    np.savez(ptrain, X=X, y=y)
    model = str(tmp_path / "m.npz")
    assert main(["train", "--backend=cpu", f"--data={ptrain}", "--trees=2",
                 "--depth=2", "--bins=31", f"--out={model}"]) == 0
    capsys.readouterr()
    pbad = str(tmp_path / "bad.npz")
    np.savez(pbad, X=X[:, :5], y=y)        # 5 cols vs model's 8
    with pytest.raises(ValueError, match="expected 8 feature columns"):
        main(["predict", "--backend=cpu", f"--model={model}",
              f"--data={pbad}"])


def test_load_csv_comment_before_header(tmp_path):
    """skiprows must count PHYSICAL lines: a comment/blank line before the
    header previously desynchronized the header skip and crashed loadtxt."""
    p = str(tmp_path / "c.csv")
    with open(p, "w") as f:
        f.write("# exported 2026-07-30\n")
        f.write("\n")
        f.write("label,f0,f1\n")
        f.write("1,0.5,0.2\n0,1.5,0.8\n")
    X, y = datasets.load_file(p)
    assert X.shape == (2, 2)
    np.testing.assert_array_equal(y, [1, 0])


def test_load_csv_auto_refuses_float_targets(tmp_path):
    """A float regression target defeats auto label detection; refusing
    beats silently training on feature column 0."""
    rng = np.random.default_rng(6)
    M = rng.standard_normal((20, 4))
    p = str(tmp_path / "r.csv")
    np.savetxt(p, M, delimiter=",")
    with pytest.raises(ValueError, match="label_col"):
        datasets.load_file(p)
    X, y = datasets.load_file(p, label_col="last")   # explicit works
    assert X.shape == (20, 3)


def test_load_file_max_rows(tmp_path):
    p = str(tmp_path / "d.npz")
    np.savez(p, X=np.zeros((100, 2), np.float32), y=np.zeros(100))
    X, y = datasets.load_file(p, max_rows=7)
    assert len(X) == 7 and len(y) == 7


def test_train_from_csv_end_to_end(tmp_path):
    """--data=file.csv trains end-to-end through the CLI (VERDICT item 4)."""
    from ddt_tpu.cli import main

    X, y = datasets.synthetic_binary(1500, n_features=8, seed=5)
    p = str(tmp_path / "higgs.csv")
    np.savetxt(p, np.column_stack([y.astype(np.float64), X]), delimiter=",")
    model = str(tmp_path / "m.npz")
    rc = main(["train", "--backend=cpu", f"--data={p}", "--trees=3",
               "--depth=3", "--bins=31", f"--out={model}"])
    assert rc == 0
    bundle = api.load_model(model)
    assert bundle.ensemble.n_trees == 3
    assert bundle.mapper is not None  # full artifact, not just trees


# ------------------------------------------------------------------ #
# Model artifact: mapper/encoder persistence (round-1 Weak #2)
# ------------------------------------------------------------------ #

def test_save_load_model_bundle_roundtrip(tmp_path):
    from ddt_tpu.data.categorical import fit_categorical_encoder

    X, y = datasets.synthetic_binary(1000, n_features=6, seed=0)
    res = api.train(X, y, n_trees=3, max_depth=3, n_bins=31,
                    backend="cpu", log_every=10**9)
    Xc = np.random.default_rng(0).integers(0, 50, size=(1000, 2))
    enc = fit_categorical_encoder(Xc, n_bins=31)
    p = str(tmp_path / "m.npz")
    api.save_model(p, res.ensemble, mapper=res.mapper, encoder=enc)

    b = api.load_model(p)
    np.testing.assert_array_equal(b.ensemble.feature, res.ensemble.feature)
    np.testing.assert_array_equal(b.mapper.edges, res.mapper.edges)
    assert b.mapper.n_bins == res.mapper.n_bins
    assert len(b.encoder.vocab_ids) == 2
    np.testing.assert_array_equal(b.encoder.transform(Xc), enc.transform(Xc))

    # Bare TreeEnsemble.load still reads the same file (extra keys ignored).
    from ddt_tpu.models.tree import TreeEnsemble

    ens = TreeEnsemble.load(p)
    np.testing.assert_array_equal(ens.feature, res.ensemble.feature)


def test_cli_predict_uses_training_mapper_on_shifted_data(tmp_path, capsys):
    """Score data whose distribution differs from training: bins must come
    from the TRAINING mapper in the artifact. (Round 1 refit the mapper on
    the scoring set — silently wrong thresholds.)"""
    from ddt_tpu.cli import main

    X, y = datasets.synthetic_binary(2000, n_features=8, seed=0)
    ptrain = str(tmp_path / "train.npz")
    np.savez(ptrain, X=X, y=y)
    model = str(tmp_path / "m.npz")
    rc = main(["train", "--backend=cpu", f"--data={ptrain}", "--trees=4",
               "--depth=3", "--bins=31", f"--out={model}"])
    assert rc == 0
    capsys.readouterr()

    # Non-monotone transform: quantile binning is monotone-invariant, so
    # only a genuinely different distribution SHAPE exposes a refit mapper.
    Xs = np.square(X[:500]).astype(np.float32)
    pshift = str(tmp_path / "shift.npz")
    np.savez(pshift, X=Xs, y=y[:500])
    sout = str(tmp_path / "scores.npy")
    rc = main(["predict", "--backend=cpu", f"--model={model}",
               f"--data={pshift}", f"--out={sout}"])
    assert rc == 0
    got = np.load(sout)

    bundle = api.load_model(model)
    from ddt_tpu.config import TrainConfig

    cfg = TrainConfig(backend="cpu", loss=bundle.ensemble.loss)
    want = api.predict(bundle.ensemble, Xs, mapper=bundle.mapper, cfg=cfg)
    np.testing.assert_array_equal(got, want)

    # The round-1 behavior (refit on scoring data) binned this set
    # differently — prove the test would have caught it.
    from ddt_tpu.data.quantizer import fit_bin_mapper

    refit = fit_bin_mapper(Xs, n_bins=31, seed=0)
    assert (refit.transform(Xs) != bundle.mapper.transform(Xs)).any()
