"""Instance weights (sample_weight): the standard GBDT trainer surface.

The anchor invariant: INTEGER weights are exactly equivalent to
duplicating rows — histograms are additive, the base score is the
weighted mean, and the loss is the weighted mean; g+g == 2*g exactly in
float (power-of-two scaling), so trees must come out identical (within
the same-platform determinism contract)."""

import numpy as np
import pytest

from ddt_tpu import api, DDTClassifier
from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver


def _dup_problem(seed=7, rows=2000):
    X, y = datasets.synthetic_binary(rows, n_features=8, seed=seed)
    Xb, _ = quantize(X, n_bins=31, seed=seed)
    rng = np.random.default_rng(seed)
    w = rng.integers(1, 4, rows)            # integer weights 1..3
    idx = np.repeat(np.arange(rows), w)     # duplicated dataset
    return Xb, y, w, Xb[idx], y[idx]


@pytest.mark.parametrize("backend_flag", ["cpu", "tpu"])
def test_integer_weights_equal_duplication(backend_flag):
    Xb, y, w, Xd, yd = _dup_problem()
    cfg = TrainConfig(n_trees=5, max_depth=4, n_bins=31,
                      backend=backend_flag)
    wtd = Driver(get_backend(cfg), cfg, log_every=10**9).fit(
        Xb, y, sample_weight=w.astype(np.float64))
    dup = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xd, yd)
    np.testing.assert_array_equal(wtd.feature, dup.feature)
    np.testing.assert_array_equal(wtd.threshold_bin, dup.threshold_bin)
    np.testing.assert_array_equal(wtd.is_leaf, dup.is_leaf)
    np.testing.assert_allclose(wtd.leaf_value, dup.leaf_value,
                               rtol=2e-4, atol=2e-5)
    assert abs(wtd.base_score - dup.base_score) < 1e-6


def test_weighted_backend_parity():
    """Weighted training grows identical trees on both backends (granular
    CPU vs fused TPU), like every other config."""
    Xb, y, w, _, _ = _dup_problem(seed=11)
    kw = dict(n_trees=5, max_depth=4, n_bins=31, binned=True,
              log_every=10**9)
    c = api.train(Xb, y, backend="cpu", sample_weight=w, **kw).ensemble
    t = api.train(Xb, y, backend="tpu", sample_weight=w, **kw).ensemble
    np.testing.assert_array_equal(c.feature, t.feature)
    np.testing.assert_array_equal(c.threshold_bin, t.threshold_bin)
    np.testing.assert_allclose(c.leaf_value, t.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_weights_change_the_model_and_validate():
    Xb, y, w, _, _ = _dup_problem(seed=3)
    kw = dict(n_trees=4, max_depth=3, n_bins=31, binned=True,
              backend="cpu", log_every=10**9)
    plain = api.train(Xb, y, **kw).ensemble
    wtd = api.train(Xb, y, sample_weight=w * 10.0, **kw).ensemble
    assert not np.array_equal(plain.leaf_value, wtd.leaf_value)

    with pytest.raises(ValueError, match="sample_weight must be"):
        api.train(Xb, y, sample_weight=w[:-1], **kw)
    with pytest.raises(ValueError, match="finite"):
        api.train(Xb, y, sample_weight=np.full(len(y), np.nan), **kw)
    with pytest.raises(ValueError, match="all zero"):
        api.train(Xb, y, sample_weight=np.zeros(len(y)), **kw)


def test_sklearn_sample_weight():
    X, y = datasets.synthetic_binary(1500, n_features=8, seed=5)
    w = np.where(y == 1, 5.0, 1.0)          # upweight the positive class
    clf = DDTClassifier(n_trees=10, max_depth=3, n_bins=31,
                        backend="cpu").fit(X, y, sample_weight=w)
    clfp = DDTClassifier(n_trees=10, max_depth=3, n_bins=31,
                         backend="cpu").fit(X, y)
    # Upweighting positives raises predicted probabilities on average.
    assert clf.predict_proba(X)[:, 1].mean() \
        > clfp.predict_proba(X)[:, 1].mean()


def test_weighted_softmax_and_mse():
    X, y = datasets.synthetic_multiclass(1500, n_features=8, n_classes=3,
                                         seed=9)
    Xb, _ = quantize(X, n_bins=31, seed=9)
    rng = np.random.default_rng(9)
    w = rng.integers(1, 3, len(y))
    idx = np.repeat(np.arange(len(y)), w)
    kw = dict(n_trees=3, max_depth=3, n_bins=31, binned=True,
              backend="cpu", loss="softmax", n_classes=3, log_every=10**9)
    wtd = api.train(Xb, y, sample_weight=w, **kw).ensemble
    dup = api.train(Xb[idx], y[idx], **kw).ensemble
    np.testing.assert_array_equal(wtd.feature, dup.feature)

    Xr, yr = datasets.synthetic_regression(1500, seed=4)
    Xrb, _ = quantize(Xr, n_bins=31, seed=4)
    wr = rng.integers(1, 3, len(yr))
    ir = np.repeat(np.arange(len(yr)), wr)
    kwr = dict(n_trees=3, max_depth=3, n_bins=31, binned=True,
               backend="cpu", loss="mse", log_every=10**9)
    wm = api.train(Xrb, yr, sample_weight=wr, **kwr).ensemble
    dm = api.train(Xrb[ir], yr[ir], **kwr).ensemble
    np.testing.assert_array_equal(wm.feature, dm.feature)
    assert abs(wm.base_score - dm.base_score) < 1e-5
