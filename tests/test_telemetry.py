"""Telemetry subsystem (ddt_tpu/telemetry, docs/OBSERVABILITY.md):
schema validation of every event type, the zero-overhead disabled path
(no device syncs, no file I/O — asserted, not assumed), run-log
round-trips through the report CLI, and the streaming trainer's phase
timing. CPU platform, tier-1."""

import importlib.util
import json
import os

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry import report
from ddt_tpu.telemetry.events import (
    EVENT_FIELDS, RunLog, validate_event)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _binary(rows, features=7, bins=29, seed=0):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    y = (Xb[:, 0] > bins // 2).astype(np.float32)
    return Xb, y


# --------------------------------------------------------------------- #
# schema
# --------------------------------------------------------------------- #
def test_runlog_emits_and_round_trips_every_event_type(tmp_path):
    """One emission per schema event type, written to JSONL and read back
    through the validating reader — EVENT_FIELDS is covered exhaustively,
    so a new event type without a test fails here."""
    path = str(tmp_path / "run.jsonl")
    payloads = {
        "run_manifest": dict(trainer="driver", backend="cpu",
                             loss="logloss", n_trees=2, max_depth=3,
                             rows=10, features=4),
        "round": dict(round=1, ms_per_round=1.5, train_loss=None,
                      valid_logloss=0.6),
        "phase_timings": dict(phases=[{"phase": "grow", "ms_total": 1.0,
                                       "ms_per_call": 0.5, "calls": 2,
                                       "share": 1.0}]),
        "early_stop": dict(round=2, best_round=1, best_score=0.59,
                           metric="logloss"),
        "fault": dict(kind="checkpoint_resume", round=1),
        "counters": dict(jit_compiles=1, jit_compile_seconds=0.25,
                         h2d_bytes=10, d2h_bytes=5,
                         collective_bytes_est=0, device_peak_bytes=None,
                         host_peak_rss_bytes=123456),
        # Schema v3 (device-truth cost observatory): XLA's cost model for
        # one op entry point at one signature.
        "cost_analysis": dict(op="hist", flops=2.5e9, bytes_accessed=1e9,
                              phase="hist", calls=12, platform="cpu",
                              arg_bytes=1000, output_bytes=200,
                              temp_bytes=50,
                              signature="([1000, 7]:uint8)"),
        "partition_phases": dict(
            round=1, rounds=1,
            partitions=[{"device": 0, "phases": {"grow": 1.5},
                         "hist_allreduce_bytes": 64},
                        {"device": 1, "phases": {"grow": 2.0},
                         "hist_allreduce_bytes": 64}]),
        "partition_skew": dict(
            phases=[{"phase": "grow", "ms_max": 2.0, "ms_median": 1.75,
                     "skew": 1.143, "max_device": 1}],
            n_partitions=2),
        # Schema v5 (AOT export + model registry): one artifact
        # lifecycle step (registry push / loader restore).
        "artifact": dict(action="push", digest="a1b2c3d4e5f60718",
                         name="higgs", version=3, kind="servable",
                         run_id="58226c4d64f0", mode=None),
        # Schema v4 (low-latency serving tier): one SLO window from
        # ServeEngine.emit_latency.
        "serve_latency": dict(requests=100, p50_ms=0.8, p99_ms=2.5,
                              p999_ms=4.0, max_ms=4.2, batches=13,
                              coalesce_mean=7.7, coalesce_max=16,
                              queue_depth_max=3, window_s=1.0,
                              model_token="cafe" * 10),
        # Schema v5-additive (ISSUE 17 operations plane): one flushed
        # request-trace ring (breakdown per trace_breakdown's shape).
        "serve_trace": dict(
            traces=[{"trace_id": "ab12cd34ef56-00000001", "rows": 1,
                     "express": True, "handler_ms": 0.012,
                     "queue_ms": 0.0, "gate_ms": 0.21,
                     "device_ms": 3.1, "wake_ms": 0.05,
                     "total_ms": 3.37}],
            count=1, model_name="higgs", model_token="cafe" * 10,
            reason="on_demand"),
        # Schema v5-additive (ISSUE 19 drift observatory): one latched
        # divergence-alert transition from serve.drift.DriftTracker.
        "drift": dict(psi_max=0.41, model_name="higgs", feature=3,
                      js_max=0.22, psi_mean=0.11, window_rows=512,
                      window_s=300.0, threshold=0.25, alerts=1),
        # Schema v5-additive (ISSUE 20 training operations plane): one
        # checkpoint-cadence progress heartbeat from the train loops.
        "train_heartbeat": dict(round=6, total_rounds=12,
                                checkpoint_round=6, ms_per_round=375.1,
                                rows_per_s=14776.0),
        "run_end": dict(completed_rounds=2, wallclock_s=0.1),
    }
    assert set(payloads) == set(EVENT_FIELDS)   # exhaustive by contract
    with RunLog(path) as rl:
        for ev, fields in payloads.items():
            rl.emit(ev, **fields)
        assert [r["event"] for r in rl.events()] == list(payloads)
    back = report.read_events(path)
    assert [r["event"] for r in back] == list(payloads)
    assert [r["seq"] for r in back] == list(range(len(payloads)))
    for r in back:
        validate_event(r)                       # idempotent on valid recs


def test_validate_event_rejects_malformed():
    ok = {"event": "round", "schema": 1, "t": 0.0, "seq": 0,
          "round": 1, "ms_per_round": 2.0}
    validate_event(ok)
    with pytest.raises(ValueError, match="unknown run-log event"):
        validate_event({**ok, "event": "nonsense"})
    bad = dict(ok)
    del bad["ms_per_round"]
    with pytest.raises(ValueError, match="missing required fields"):
        validate_event(bad)
    bad = dict(ok)
    del bad["seq"]
    with pytest.raises(ValueError, match="envelope"):
        validate_event(bad)
    with pytest.raises(ValueError, match="newer than this reader"):
        validate_event({**ok, "schema": 999})
    # Corrupt/hand-edited logs must surface as the reader's clean
    # ValueError, never a TypeError from the version comparison.
    with pytest.raises(ValueError, match="schema must be an integer"):
        validate_event({**ok, "schema": "1"})
    with pytest.raises(ValueError, match="must be an object"):
        validate_event(["not", "a", "dict"])


def test_runlog_rejects_bad_emit_at_the_producer():
    rl = RunLog()                               # ring-only
    with pytest.raises(ValueError):
        rl.emit("round")                        # missing required fields
    with pytest.raises(ValueError):
        rl.emit("no_such_event", x=1)
    assert rl.events() == []                    # nothing half-recorded


# --------------------------------------------------------------------- #
# driver integration
# --------------------------------------------------------------------- #
def test_driver_e2e_run_log_counters_and_eval_curve(tmp_path):
    """The acceptance round trip at API level: a TPU-backend (XLA-on-CPU)
    train with eval_set produces a schema-valid log holding per-phase
    timings, per-round eval metrics, and a NONZERO jit-recompile count
    (unique shapes force fresh compiles even in a shared process)."""
    Xb, y = _binary(2113)
    Xv, yv = _binary(431, seed=1)
    path = str(tmp_path / "run.jsonl")
    with RunLog(path) as rl:
        api.train(Xb, y, binned=True, n_trees=4, max_depth=3, n_bins=29,
                  backend="tpu", eval_set=(Xv, yv),
                  eval_metric="logloss", run_log=rl)
    events = report.read_events(path)
    by_type = {}
    for e in events:
        by_type.setdefault(e["event"], []).append(e)
    assert {"run_manifest", "round", "phase_timings", "counters",
            "run_end"} <= set(by_type)

    man = by_type["run_manifest"][0]
    assert (man["trainer"], man["backend"]) == ("driver", "tpu")
    assert (man["rows"], man["features"]) == (2113, 7)

    rounds = by_type["round"]
    assert [r["round"] for r in rounds] == [1, 2, 3, 4]
    assert all("valid_logloss" in r for r in rounds)   # metric EVERY round
    assert all(r["ms_per_round"] > 0 for r in rounds)

    c = by_type["counters"][-1]
    assert c["jit_compiles"] > 0                       # the silent killer
    assert c["h2d_bytes"] >= Xb.nbytes                 # data plane counted
    assert c["d2h_bytes"] > 0                          # tree fetches

    phases = by_type["phase_timings"][-1]["phases"]
    assert phases and {"phase", "ms_total", "ms_per_call", "calls",
                       "share"} <= set(phases[0])
    assert by_type["run_end"][-1]["completed_rounds"] == 4


def test_disabled_path_no_syncs_no_file_io(monkeypatch, tmp_path):
    """With telemetry off (run_log=None, profile=False) the hot loop must
    add ZERO device syncs — counted on the backend's sync callable — and
    perform no run-log file I/O, asserted by making any RunLog
    construction or emission explode."""
    from ddt_tpu.backends.tpu import TPUDevice
    from ddt_tpu.parallel import mesh as mesh_lib
    import ddt_tpu.telemetry.events as ev_mod

    def _boom(*a, **k):
        raise AssertionError("telemetry touched while disabled")

    monkeypatch.setattr(ev_mod.RunLog, "__init__", _boom)
    monkeypatch.setattr(ev_mod.RunLog, "emit", _boom)
    # Flight-recorder collectors (schema v2) are held to the same bar:
    # no shard probes while telemetry is off (the probe is a barrier).
    monkeypatch.setattr(mesh_lib, "shard_ready_times", _boom)
    # Cost observatory (schema v3), same bar: no collector install and —
    # the acceptance criterion — no compile()/re-lowering on the hot
    # path while telemetry is off (_capture is the only lowering site).
    from ddt_tpu.telemetry import costmodel

    monkeypatch.setattr(costmodel, "activate", _boom)
    monkeypatch.setattr(costmodel, "_capture", _boom)

    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=29, backend="tpu")
    be = TPUDevice(cfg)
    calls = {"sync": 0}
    real_sync = be.sync

    def counting_sync(x):
        calls["sync"] += 1
        return real_sync(x)

    monkeypatch.setattr(be, "sync", counting_sync)
    Xb, y = _binary(977)
    res = api.train(Xb, y, cfg, binned=True, backend=be)
    assert res.ensemble.n_trees == 3
    assert calls["sync"] == 0


def test_early_stop_event_and_driver_history_unchanged(tmp_path):
    """Granular CPU path: the early-stop decision lands in the log with
    best-round attribution, and Driver.history keeps its shape (the
    sklearn evals_result_ surface must not change under telemetry)."""
    Xb, y = _binary(1201, seed=2)
    rng = np.random.default_rng(3)
    Xv = rng.integers(0, 29, size=(301, 7), dtype=np.uint8)
    yv = rng.integers(0, 2, size=301).astype(np.float32)  # noise: stops
    rl = RunLog()                                         # ring-only
    res = api.train(Xb, y, binned=True, n_trees=40, max_depth=3,
                    n_bins=29, backend="cpu", eval_set=(Xv, yv),
                    early_stopping_rounds=2, run_log=rl)
    stops = rl.events("early_stop")
    assert len(stops) == 1
    es = stops[0]
    assert es["metric"] == "logloss"
    assert es["best_round"] == res.best_round + 1
    assert es["best_score"] == pytest.approx(res.best_score)
    assert res.ensemble.n_trees == res.best_round + 1
    # history round records match the run log's round events 1:1 here
    # (eval every round -> every round recorded in both).
    assert len(rl.events("round")) == len(res.history)


def test_checkpoint_resume_emits_fault_event(tmp_path):
    """Resume-from-checkpoint is the recovery story — the run log records
    it as a fault event carrying the resume round."""
    Xb, y = _binary(1301, seed=4)
    ck = str(tmp_path / "ck")
    api.train(Xb, y, binned=True, n_trees=2, max_depth=3, n_bins=29,
              backend="cpu", checkpoint_dir=ck)
    rl = RunLog()
    res = api.train(Xb, y, binned=True, n_trees=4, max_depth=3, n_bins=29,
                    backend="cpu", checkpoint_dir=ck, run_log=rl)
    faults = rl.events("fault")
    assert faults and faults[0]["kind"] == "checkpoint_resume"
    assert faults[0]["round"] == 2
    assert res.ensemble.n_trees == 4
    assert rl.events("run_end")[-1]["completed_rounds"] == 4


def test_owned_run_log_closed_when_fit_raises(tmp_path, monkeypatch):
    """A run log built from a PATH is Driver-owned: mid-run exceptions
    (here the NaN-eval guard) must still close the file handle — a
    long-lived process retrying failing fits must not leak fds. close()
    is observed directly (reading the file back would pass even with a
    leaked handle on POSIX)."""
    import ddt_tpu.telemetry.events as ev_mod

    closed = []
    real_close = ev_mod.RunLog.close

    def recording_close(self):
        closed.append(self.path)
        real_close(self)

    monkeypatch.setattr(ev_mod.RunLog, "close", recording_close)
    Xb, y = _binary(601, seed=7)
    Xv = np.zeros((50, 7), np.uint8)
    yv = np.zeros(50, np.float32)          # single-class: auc -> error
    log_path = str(tmp_path / "fail.jsonl")
    with pytest.raises(ValueError):
        api.train(Xb, y, binned=True, n_trees=5, max_depth=3, n_bins=29,
                  backend="cpu", eval_set=(Xv, yv), eval_metric="auc",
                  early_stopping_rounds=2, run_log=log_path)
    assert log_path in closed              # the ownership shim fired
    # The manifest got out before the failure: complete lines only.
    events = report.read_events(log_path)
    assert events[0]["event"] == "run_manifest"


# --------------------------------------------------------------------- #
# streaming integration
# --------------------------------------------------------------------- #
def test_streaming_host_run_log_and_phase_timer(tmp_path):
    from ddt_tpu.streaming import fit_streaming

    Xb, y = _binary(900, seed=5)
    bounds = [0, 300, 600, 900]

    def chunk_fn(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

    Xv, yv = _binary(200, seed=6)

    def valid_fn(c):
        return Xv, yv

    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=29, backend="cpu")
    rl = RunLog(str(tmp_path / "stream.jsonl"))
    history = []
    ens = fit_streaming(chunk_fn, 3, cfg, valid_chunk_fn=valid_fn,
                        n_valid_chunks=1, history=history, run_log=rl)
    rl.close()
    assert ens.n_trees == 3
    events = report.read_events(str(tmp_path / "stream.jsonl"))
    by_type = {}
    for e in events:
        by_type.setdefault(e["event"], []).append(e)
    man = by_type["run_manifest"][0]
    assert man["trainer"] == "streaming_host"
    assert man["n_chunks"] == 3
    rounds = by_type["round"]
    assert [r["round"] for r in rounds] == [1, 2, 3]
    assert all("valid_logloss" in r for r in rounds)
    # PhaseTimer wired into fit_streaming (satellite): the streamed hot
    # loop's phases appear in the embedded breakdown.
    phases = {p["phase"] for p in by_type["phase_timings"][-1]["phases"]}
    assert {"hist", "gain", "leaf", "eval"} <= phases
    assert by_type["run_end"][-1]["completed_rounds"] == 3
    # history (the _StreamEval surface) is unchanged by telemetry
    assert [h["round"] for h in history] == [1, 2, 3]


# --------------------------------------------------------------------- #
# report round trip (CLI) + smoke
# --------------------------------------------------------------------- #
def test_report_cli_round_trips_a_training_run(tmp_path, capsys):
    """The acceptance criterion end to end through the CLI: train with
    --run-log, then `report` renders it — phase timings, metric curve,
    and a nonzero recompile counter all present."""
    from ddt_tpu.cli import main

    log = str(tmp_path / "run.jsonl")
    model = str(tmp_path / "ens.npz")
    rc = main([
        "train", "--backend=tpu", "--dataset=higgs", "--rows=2357",
        "--trees=3", "--depth=3", "--bins=23", "--valid-frac=0.2",
        f"--run-log={log}", f"--out={model}",
    ])
    assert rc == 0
    train_out = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert train_out["run_log"] == log

    rc = main(["report", "--log", log, "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["counters"]["jit_compiles"] > 0
    assert summary["phases"]                     # per-phase timings
    assert summary["metric"] == "logloss"
    assert [c["round"] for c in summary["metric_curve"]] == [1, 2, 3]
    assert summary["completed_rounds"] == 3

    rc = main(["report", "--log", log])          # human rendering
    assert rc == 0
    text = capsys.readouterr().out
    assert "phases (host wallclock):" in text
    assert "jit_compiles=" in text
    assert "valid_logloss:" in text


def test_read_events_tolerates_torn_tail_keeps_records_pure(tmp_path):
    """A run killed mid-write tears only the FINAL line (append-only,
    line-buffered writes): the reader drops it, keeps everything above,
    and injects no out-of-schema marker keys into surviving records."""
    p = tmp_path / "torn.jsonl"
    with RunLog(str(p)) as rl:
        rl.emit("run_manifest", trainer="driver", backend="cpu",
                loss="logloss", n_trees=2, max_depth=3, rows=5, features=2)
        rl.emit("round", round=1, ms_per_round=1.0, train_loss=None)
    with open(p, "a", encoding="utf-8") as f:
        f.write('{"event": "round", "schema": 1, "t":')   # torn mid-write
    events = report.read_events(str(p))
    assert [e["event"] for e in events] == ["run_manifest", "round"]
    for e in events:
        validate_event(e)
        assert "truncated_tail" not in e
    report.summarize(events)                              # still renders


def test_report_cli_fails_loudly_on_garbage(tmp_path):
    from ddt_tpu.cli import main

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "nonsense", "schema": 1, "t": 0, "seq": 0}\n'
                   '{"event": "run_end"}\n')
    with pytest.raises(SystemExit, match="unknown run-log event"):
        main(["report", "--log", str(bad)])
    with pytest.raises(SystemExit, match="report:"):
        main(["report", "--log", str(tmp_path / "missing.jsonl")])


def test_telemetry_smoke_script():
    """`make report`'s smoke, run in-process: 2 rounds on synthetic data,
    run log in a tmpdir, report on it (tier-1-safe)."""
    spec = importlib.util.spec_from_file_location(
        "telemetry_smoke", os.path.join(REPO, "scripts",
                                        "telemetry_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


# --------------------------------------------------------------------- #
# counters unit behavior
# --------------------------------------------------------------------- #
def test_counter_snapshots_delta_and_estimate():
    c0 = tele_counters.snapshot()
    tele_counters.record_h2d(100)
    tele_counters.record_d2h(40)
    tele_counters.record_collective(7)
    d = tele_counters.delta(c0)
    assert (d["h2d_bytes"], d["d2h_bytes"], d["collective_bytes_est"]) \
        == (100, 40, 7)
    # depth-2, 3 features, 4 bins: levels 1+2 nodes of [F, bins, 2] f32
    # pairs + 4 leaf-aggregate pairs.
    assert tele_counters.hist_allreduce_bytes(2, 3, 4) \
        == (1 + 2) * 3 * 4 * 8 + 4 * 8
