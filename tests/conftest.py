"""Test harness config.

Distributed tests run single-process multi-device on CPU (SURVEY.md §4
"Distributed without a cluster"): 8 virtual XLA CPU devices via
--xla_force_host_platform_device_count.

CAVEAT: this image's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon already bound, so setting os.environ["JAX_PLATFORMS"] here
is too late — the value was frozen into jax.config at sitecustomize time. The
working override is jax.config.update("jax_platforms", ...). XLA_FLAGS, by
contrast, is only read when the CPU client is first instantiated, so mutating
the env before the first jax.devices() call still works.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
