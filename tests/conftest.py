"""Test harness config.

Distributed tests run single-process multi-device on CPU (SURVEY.md §4
"Distributed without a cluster"): 8 virtual XLA CPU devices via
--xla_force_host_platform_device_count. Must be set before jax imports.
"""

import os
import sys

# Force CPU: the ambient environment pins JAX_PLATFORMS=axon (the real TPU
# tunnel); the test suite needs the 8-virtual-device CPU mesh instead.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
