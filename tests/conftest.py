"""Test harness config.

Distributed tests run single-process multi-device on CPU (SURVEY.md §4
"Distributed without a cluster"): 8 virtual XLA CPU devices via
--xla_force_host_platform_device_count.

CAVEAT: this image's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon already bound, so setting os.environ["JAX_PLATFORMS"] here
is too late — the value was frozen into jax.config at sitecustomize time. The
working override is jax.config.update("jax_platforms", ...). XLA_FLAGS, by
contrast, is only read when the CPU client is first instantiated, so mutating
the env before the first jax.devices() call still works.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The suite's bit-identity contracts (native == NumPy oracle, CPU == TPU
# ensembles, N == 1 partitions, streamed == in-memory) assume the native
# kernels' SERIAL summation order: at OpenMP team sizes > 1 the histogram
# reduction reassociates float32 sums (~1e-6 — native/histogram.cpp), which
# can flip near-tie bf16 argmax splits in any module that trains through
# CPUDevice. Pin one thread for the whole suite regardless of the host's
# core count or OMP_NUM_THREADS; multi-thread kernel behavior has its own
# explicit coverage (test_native.py
# test_native_multithread_allclose_deterministic, which raises the team
# size inside its body and restores it).
# Import cost at collection: a fresh .so is one dlopen (~ms); after a
# .cpp edit this triggers the rebuild here instead of at first CPUDevice
# use — acceptable, the suite is normally run whole from the repo root.
# ImportError: no toolchain. OSError: ctypes.CDLL on a corrupt/wrong-arch/
# unresolvable library (e.g. a sanitizer build named via DDT_NATIVE_LIB
# without its runtime preloaded). Either way the suite still runs on the
# NumPy fallback kernels — which need no pin. Anything ELSE (say a
# TypeError in the ctypes setup) is a real binding bug: swallowing it here
# used to turn such bugs into nondeterministic bit-identity flakes with no
# visible cause (round-5 advisor finding), so it now propagates.
try:
    from ddt_tpu import native as _native

    _native.omp_set_threads(1)
except (ImportError, OSError) as _pin_err:
    import warnings

    warnings.warn(
        f"native thread-pin skipped ({type(_pin_err).__name__}: {_pin_err});"
        " suite runs on the NumPy fallback kernels",
        RuntimeWarning,
        stacklevel=1,
    )
