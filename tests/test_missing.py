"""Missing-value support (round-1 verdict item 7): reserved NaN bin +
learned default direction, through quantizer → split/grow kernels →
predict paths → C++ twins.

Design (cfg.missing_policy="learn"): the top bin (n_bins-1) holds NaN rows;
best_splits scores BOTH default directions per (feature, bin) and the
routing/predict paths send missing rows down the learned side. Direction
RIGHT occupies the first argmax block, so zero-missing nodes
deterministically report default_left=False — bit-compatible with the
"zero" policy's selection semantics on NaN-free data.
"""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import synthetic_binary
from ddt_tpu.data.quantizer import fit_bin_mapper, quantize
from ddt_tpu.driver import Driver
from ddt_tpu.reference import numpy_trainer as ref


def _nan_data(rows=4000, f=8, seed=3, frac=0.25, informative=True):
    """Binary task where MISSINGNESS itself carries label signal, so the
    learned direction must beat the NaN→bin0 policy."""
    rng = np.random.default_rng(seed)
    X, y = synthetic_binary(rows, n_features=f, seed=seed)
    miss = rng.random((rows, f)) < frac
    if informative:
        # Missingness correlated with the POSITIVE class on several
        # features: under the zero policy NaNs land in bin 0 next to the
        # lowest values (mostly negatives here), so the forced grouping is
        # actively wrong; the learned direction can route them with the
        # positives instead.
        for c in range(3):
            miss[:, c] = (rng.random(rows) < 0.3 * frac) | (
                (y == 1) & (rng.random(rows) < 3 * frac)
            )
    X = X.copy()
    X[miss] = np.nan
    return X, y


# ------------------------------------------------------------------ #
# quantizer
# ------------------------------------------------------------------ #

def test_mapper_reserves_top_bin():
    X, _ = _nan_data(800)
    m = fit_bin_mapper(X, n_bins=32, missing_policy="learn")
    assert m.missing_bin and m.n_value_bins == 31
    Xb = m.transform(X)
    assert (Xb[np.isnan(X)] == 31).all()
    assert (Xb[~np.isnan(X)] <= 30).all()

    # zero policy unchanged
    m0 = fit_bin_mapper(X, n_bins=32)
    assert not m0.missing_bin
    assert (m0.transform(X)[np.isnan(X)] == 0).all()


def test_mapper_missing_roundtrips_through_artifact(tmp_path):
    X, y = _nan_data(1000)
    res = api.train(X, y, n_trees=3, max_depth=3, n_bins=31, backend="cpu",
                    missing_policy="learn", log_every=10**9)
    p = str(tmp_path / "m.npz")
    res.save(p)
    b = api.load_model(p)
    assert b.mapper.missing_bin
    assert b.ensemble.missing_bin and b.ensemble.n_bins == 31
    np.testing.assert_array_equal(
        b.ensemble.default_left, res.ensemble.default_left)


# ------------------------------------------------------------------ #
# split kernel twins
# ------------------------------------------------------------------ #

def test_split_direction_learning_matches_oracle():
    """XLA best_splits == NumPy best_splits with missing_bin, including the
    direction bit, on random histograms."""
    from ddt_tpu.ops.split import best_splits as jx_best

    rng = np.random.default_rng(11)
    hist = rng.standard_normal((4, 5, 16, 2)).astype(np.float32)
    hist[..., 1] = np.abs(hist[..., 1])  # hessians positive
    want = ref.best_splits(hist, 1.0, 1e-3, missing_bin=True)
    got = jx_best(hist, 1.0, 1e-3, missing_bin=True)
    np.testing.assert_array_equal(np.asarray(got[1]), want[1])
    np.testing.assert_array_equal(np.asarray(got[2]), want[2])
    np.testing.assert_array_equal(np.asarray(got[3]), want[3])
    np.testing.assert_allclose(np.asarray(got[0]), want[0],
                               rtol=1e-2, atol=1e-2)  # bf16-rounded


def test_zero_missing_mass_defaults_right():
    """Nodes with no missing rows must report default_left=False (the RIGHT
    block wins exact ties), keeping behavior aligned with the zero policy."""
    rng = np.random.default_rng(5)
    hist = np.abs(rng.standard_normal((3, 4, 8, 2))).astype(np.float32)
    hist[:, :, -1, :] = 0.0              # zero mass in the NaN bin
    *_, dl = ref.best_splits(hist, 1.0, 1e-3, missing_bin=True)
    assert not dl.any()


# ------------------------------------------------------------------ #
# end-to-end: parity + quality
# ------------------------------------------------------------------ #

def _fit(backend, Xb, y, **kw):
    cfg = TrainConfig(n_trees=5, max_depth=4, n_bins=31, backend=backend,
                      missing_policy="learn", **kw)
    be = get_backend(cfg)
    return Driver(be, cfg, log_every=10**9).fit(Xb, y)


def test_backend_parity_with_nans():
    X, y = _nan_data()
    Xb, _ = quantize(X, n_bins=31, missing_policy="learn")
    ec = _fit("cpu", Xb, y)
    et = _fit("tpu", Xb, y)
    np.testing.assert_array_equal(ec.feature, et.feature)
    np.testing.assert_array_equal(ec.threshold_bin, et.threshold_bin)
    np.testing.assert_array_equal(ec.default_left, et.default_left)
    np.testing.assert_allclose(ec.leaf_value, et.leaf_value,
                               rtol=2e-4, atol=2e-5)
    assert ec.default_left.any()        # informative missingness was used


def test_partitioned_nan_training_identical():
    X, y = _nan_data(4096)
    Xb, _ = quantize(X, n_bins=31, missing_policy="learn")
    e1 = _fit("tpu", Xb, y)
    e8 = _fit("tpu", Xb, y, n_partitions=8)
    np.testing.assert_array_equal(e1.feature, e8.feature)
    np.testing.assert_array_equal(e1.default_left, e8.default_left)


def test_learned_direction_beats_zero_policy():
    """On data whose missingness is informative, the learned policy must
    improve held-out AUC over NaN→bin0. Coarse bins (n_bins=8) make the
    zero policy's weakness material: bin 0 then conflates NaN with the
    bottom ~1/7 of real values, which the reserved bin never does (at 255
    bins the contamination is ~0.4% of rows and the two policies nearly
    tie — that regime is covered by the parity tests, not this one)."""
    from ddt_tpu.utils.metrics import evaluate

    X, y = _nan_data(8000, seed=7)
    tr, va = slice(0, 6000), slice(6000, None)
    kw = dict(n_trees=25, max_depth=5, n_bins=8, backend="cpu",
              log_every=10**9)
    r_learn = api.train(X[tr], y[tr], missing_policy="learn", **kw)
    r_zero = api.train(X[tr], y[tr], missing_policy="zero", **kw)
    auc_learn = evaluate(
        "auc", y[va], api.predict(r_learn.ensemble, X[va],
                                  mapper=r_learn.mapper, raw=True))
    auc_zero = evaluate(
        "auc", y[va], api.predict(r_zero.ensemble, X[va],
                                  mapper=r_zero.mapper, raw=True))
    assert auc_learn > auc_zero + 0.005, (auc_learn, auc_zero)


# ------------------------------------------------------------------ #
# predict-path parity (NumPy oracle vs device vs native C++ vs raw)
# ------------------------------------------------------------------ #

def test_predict_paths_agree_with_nans():
    X, y = _nan_data(3000)
    res = api.train(X, y, n_trees=6, max_depth=4, n_bins=31, backend="cpu",
                    missing_policy="learn", log_every=10**9)
    ens, mapper = res.ensemble, res.mapper
    Xb = mapper.transform(X)

    want = ens.predict_raw(Xb, binned=True)          # NumPy oracle

    # Device (XLA comparison-matrix descent + per-level path)
    be_t = get_backend(TrainConfig(backend="tpu", n_bins=31,
                                   missing_policy="learn"))
    got_dev = be_t.predict_raw(ens, Xb)
    np.testing.assert_allclose(got_dev, want, rtol=2e-4, atol=2e-5)

    # Native C++ traversal twin
    be_c = get_backend(TrainConfig(backend="cpu", n_bins=31,
                                   missing_policy="learn"))
    if getattr(be_c, "_native_traverse", None) is not None:
        got_cpp = be_c.predict_raw(ens, Xb)
        np.testing.assert_allclose(got_cpp, want, rtol=1e-6, atol=1e-6)

    # Raw-value path (NaN detected directly, default direction honored)
    want_raw = ens.predict_raw(X, binned=False)
    np.testing.assert_allclose(want_raw, want, rtol=2e-4, atol=2e-4)


def test_device_raw_float_predict_with_nans():
    """ops/predict._descend raw path: NaN routed by direction on device."""
    import jax.numpy as jnp

    from ddt_tpu.ops.predict import predict_raw as dev_predict

    X, y = _nan_data(800, f=5)
    res = api.train(X, y, n_trees=4, max_depth=3, n_bins=31, backend="cpu",
                    missing_policy="learn", log_every=10**9)
    ens = res.ensemble
    got = np.asarray(dev_predict(
        jnp.asarray(ens.feature), jnp.asarray(ens.threshold_raw),
        jnp.asarray(ens.is_leaf), jnp.asarray(ens.leaf_value),
        jnp.asarray(X.astype(np.float32)),
        max_depth=ens.max_depth, learning_rate=ens.learning_rate,
        base=ens.base_score, n_classes=1,
        default_left=jnp.asarray(ens.default_left),
    ))
    want = ens.predict_raw(X, binned=False)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_checkpoint_resume_preserves_default_left(tmp_path):
    X, y = _nan_data(1500)
    Xb, _ = quantize(X, n_bins=31, missing_policy="learn")
    cfg = TrainConfig(n_trees=8, max_depth=4, n_bins=31, backend="tpu",
                      missing_policy="learn")
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    ck = str(tmp_path / "ck")
    Driver(get_backend(cfg.replace(n_trees=4)), cfg.replace(n_trees=4),
           log_every=10**9, checkpoint_dir=ck, checkpoint_every=2).fit(Xb, y)
    resumed = Driver(get_backend(cfg), cfg, log_every=10**9,
                     checkpoint_dir=ck).fit(Xb, y)
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.default_left, resumed.default_left)


def test_missing_policy_validation():
    with pytest.raises(ValueError, match="missing_policy"):
        TrainConfig(missing_policy="nan")
    with pytest.raises(ValueError, match="n_bins >= 3"):
        TrainConfig(missing_policy="learn", n_bins=2)
    # mapper fitted with the wrong policy is rejected at train time
    X, y = _nan_data(200)
    m = fit_bin_mapper(X, n_bins=31)   # zero-policy mapper
    with pytest.raises(ValueError, match="missing_policy"):
        api.train(X, y, n_trees=1, max_depth=2, n_bins=31, backend="cpu",
                  missing_policy="learn", mapper=m, log_every=10**9)
