"""Declarative 2D (rows x features) mesh + host-sharded ingest (ISSUE
11, ROADMAP item 2).

Three contracts on the 8-virtual-device CPU mesh:

- **Structure identity at any (Pr, Pf).** Reduce-scatter split finding
  now COMPOSES with a sharded feature axis — the scatter runs over the
  row axes within each feature slab and ONE winner combine gathers over
  both axes by global flattened candidate index — so trees must be
  structure-identical to single-device at every mesh shape, including
  ragged F, softmax, missing-bin, categorical, and engineered exact
  ties.
- **Ownership.** The host-sharded chunk source
  (data.chunks.HostShardedChunks) must never let a process read a
  feature sub-shard it does not own, and the streamed trainer over it
  must reproduce the plain streamed path bitwise at the same logical
  chunk bounds.
- **Payload.** The second-axis-aware hist_allreduce_bytes model must
  show per-level collective payload <= 1/(Pr*Pf) of the
  replicated-feature allreduce baseline plus the O(Pr*Pf*nodes) winner
  term — the ISSUE 11 acceptance criterion, witnessed in-process.
"""

import numpy as np
import pytest

from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig, load_config_file
from ddt_tpu.data import chunks as chunks_lib
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver
from ddt_tpu.parallel import mesh as mesh_lib


def _fit(Xb, y, **kw):
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31, backend="tpu",
                      **kw)
    be = get_backend(cfg)
    return Driver(be, cfg, log_every=10 ** 9).fit(Xb, y), be


def _assert_structure_equal(e1, eN):
    np.testing.assert_array_equal(e1.feature, eN.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eN.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eN.is_leaf)
    np.testing.assert_allclose(e1.leaf_value, eN.leaf_value,
                               rtol=2e-4, atol=2e-5)


MESH_SHAPES = [(1, 1), (2, 2), (4, 2), (1, 4)]


@pytest.mark.parametrize("mesh_shape", MESH_SHAPES,
                         ids=[f"{pr}x{pf}" for pr, pf in MESH_SHAPES])
def test_mesh2d_structure_identity(mesh_shape):
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=11)
    Xb, _ = quantize(X, n_bins=31, seed=11)
    e1, _ = _fit(Xb, y)
    eN, be = _fit(Xb, y, mesh_shape=mesh_shape)
    # The resolver composes: any mesh with a row wire scatters.
    pr, pf = mesh_shape
    want = "reduce_scatter" if pr > 1 else "allreduce"
    assert be.split_comms == want
    _assert_structure_equal(e1, eN)


@pytest.mark.parametrize("mesh_shape", [(2, 2), (2, 4), (1, 4)],
                         ids=["2x2", "2x4", "1x4"])
def test_mesh2d_ragged_features(mesh_shape):
    """F=9 does not divide Pf: upload pads all-zero columns, which must
    never win a split; reduce-scatter pads again over the row axes."""
    X, y = datasets.synthetic_binary(2048, n_features=9, seed=23)
    Xb, _ = quantize(X, n_bins=31, seed=23)
    e1, _ = _fit(Xb, y)
    eN, _ = _fit(Xb, y, mesh_shape=mesh_shape,
                 split_comms="reduce_scatter" if mesh_shape[0] > 1
                 else "auto")
    assert e1.feature.max() < 9
    _assert_structure_equal(e1, eN)


def test_mesh2d_softmax():
    X, y = datasets.synthetic_multiclass(1500, n_features=12, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    e1, _ = _fit(Xb, y, loss="softmax", n_classes=4)
    eN, be = _fit(Xb, y, loss="softmax", n_classes=4, mesh_shape=(2, 2))
    assert be.split_comms == "reduce_scatter"
    _assert_structure_equal(e1, eN)


def test_mesh2d_missing_bin():
    """missing_policy='learn': the direction-block tie-break (RIGHT
    before LEFT) must survive the two-axis winner combine."""
    X, y = datasets.synthetic_binary(3000, n_features=10, seed=7)
    X = X.copy()
    X[::7, 3] = np.nan
    X[::11, 6] = np.nan
    Xb, _ = quantize(X, n_bins=31, seed=7, missing_policy="learn")
    e1, _ = _fit(Xb, y, missing_policy="learn")
    eN, _ = _fit(Xb, y, missing_policy="learn", mesh_shape=(2, 2),
                 split_comms="reduce_scatter")
    _assert_structure_equal(e1, eN)
    np.testing.assert_array_equal(e1.default_left, eN.default_left)


def test_mesh2d_categorical_and_sampling():
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    kw = dict(cat_features=(1, 4), subsample=0.7, colsample_bytree=0.6)
    e1, _ = _fit(Xb, y, **kw)
    eN, _ = _fit(Xb, y, mesh_shape=(4, 2), **kw)
    _assert_structure_equal(e1, eN)


def test_mesh2d_duplicate_column_tie_break():
    """Engineered EXACT gain tie across feature shards: column 7 is a
    byte-for-byte copy of column 0, so their best candidates tie
    exactly. On the (2, 2) mesh the copies live on DIFFERENT feature
    shards and their slabs on different row shards — the combined
    winner must still be the single-device argmax's pick (the smallest
    global flattened candidate index: feature 0)."""
    X, y = datasets.synthetic_binary(2048, n_features=8, seed=13)
    Xb, _ = quantize(X, n_bins=31, seed=13)
    Xb = Xb.copy()
    Xb[:, 7] = Xb[:, 0]
    e1, _ = _fit(Xb, y)
    eN, _ = _fit(Xb, y, mesh_shape=(2, 2),
                 split_comms="reduce_scatter")
    _assert_structure_equal(e1, eN)
    # The tie itself must have been broken toward the lower global id
    # wherever the duplicated pair was the winner.
    split_feats = e1.feature[(~e1.is_leaf) & (e1.feature >= 0)]
    assert 7 not in split_feats


def test_mesh2d_fused_rounds_match_granular():
    """The fused multi-round scan on the 2D rs mesh grows bit-identical
    trees to the granular per-tree path (they share one grow_tree
    program; profile=True forces the granular loop)."""
    X, y = datasets.synthetic_binary(3000, n_features=10, seed=2)
    Xb, _ = quantize(X, n_bins=31, seed=2)
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=31, backend="tpu",
                      mesh_shape=(2, 2))
    be = get_backend(cfg)
    fused = Driver(be, cfg, log_every=10 ** 9).fit(Xb, y)
    granular = Driver(be, cfg, log_every=10 ** 9, profile=True).fit(Xb, y)
    # Structure bitwise; leaves to tolerance — the scan context can
    # contract the leaf one-hot matmul differently than the standalone
    # program (the documented FMA-contraction seam, driver.py).
    _assert_structure_equal(granular, fused)


# ------------------------------------------------------------------ #
# config + layout plumbing
# ------------------------------------------------------------------ #

def test_mesh_shape_config_normalizes_and_conflicts():
    cfg = TrainConfig(mesh_shape=(4, 2))
    assert cfg.n_partitions == 4 and cfg.feature_partitions == 2
    # canonicalized to None: both spellings are byte-identical configs
    # (equal run ids / cache keys), and .replace() on partition fields
    # never false-conflicts.
    assert cfg.mesh_shape is None
    assert cfg == TrainConfig(n_partitions=4, feature_partitions=2)
    assert cfg.replace(n_partitions=4) == cfg
    # agreeing explicit values are fine
    TrainConfig(mesh_shape=(4, 2), n_partitions=4, feature_partitions=2)
    with pytest.raises(ValueError, match="conflicts"):
        TrainConfig(mesh_shape=(4, 2), n_partitions=2)
    with pytest.raises(ValueError, match="conflicts"):
        TrainConfig(mesh_shape=(4, 2), feature_partitions=4)
    with pytest.raises(ValueError, match="mesh_shape"):
        TrainConfig(mesh_shape=(4,))
    with pytest.raises(ValueError, match="mesh_shape"):
        TrainConfig(mesh_shape=(0, 2))


def test_mesh_shape_config_file(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text('{"mesh_shape": [2, 2], "n_trees": 3}')
    d = load_config_file(str(p))
    assert d["mesh_shape"] == (2, 2)
    cfg = TrainConfig(**d)
    assert cfg.n_partitions == 2 and cfg.feature_partitions == 2


def test_cli_mesh_shape_parse():
    from ddt_tpu.cli import _parse_mesh_shape

    assert _parse_mesh_shape(None) is None
    assert _parse_mesh_shape("4,2") == (4, 2)
    assert _parse_mesh_shape(" 4 , 2 ") == (4, 2)
    with pytest.raises(SystemExit):
        _parse_mesh_shape("4")
    with pytest.raises(SystemExit):
        _parse_mesh_shape("a,b")


def test_spec_layout_rules():
    P = mesh_lib.P
    lay = mesh_lib.SpecLayout(row_axes="rows", feature_axis="features")
    assert lay.binned_data() == P("rows", "features")
    assert lay.row_vector() == P("rows")
    assert lay.level_hist_scattered() == P(None, "rows")
    assert lay.specs("data", "grad", "mask") == (
        P("rows", "features"), P("rows"), P())
    # pod form: tuple row axes
    pod = mesh_lib.SpecLayout(row_axes=("hosts", "rows"),
                              feature_axis=None)
    assert pod.binned_data() == P(("hosts", "rows"), None)
    assert pod.spec("pred") == P(("hosts", "rows"), None)
    assert pod.spec("pred1d") == P(("hosts", "rows"))
    # single-device layout degenerates to replicated
    solo = mesh_lib.SpecLayout(row_axes=None)
    assert solo.binned_data() == P()
    # unmatched names fail loudly
    with pytest.raises(ValueError, match="no partition rule"):
        lay.spec("mystery_operand")


def test_make_mesh_2d_shapes():
    m = mesh_lib.make_mesh_2d(4, 2)
    assert m.axis_names == ("rows", "features")
    assert m.shape == {"rows": 4, "features": 2}
    m3 = mesh_lib.make_mesh_2d(2, 2, n_hosts=2)
    assert m3.axis_names == ("hosts", "rows", "features")
    with pytest.raises(ValueError, match="devices"):
        mesh_lib.make_mesh_2d(16, 2)


# ------------------------------------------------------------------ #
# payload model (the acceptance criterion's witness)
# ------------------------------------------------------------------ #

def test_hist_allreduce_bytes_2d_payload_bound():
    """Per-level collective payload on the 2D rs mesh must be
    <= 1/(Pr*Pf) of the replicated-feature allreduce baseline plus the
    winner term — and the resolved backend config must feed exactly
    this model (collective_bytes_per_tree)."""
    from ddt_tpu.telemetry.counters import hist_allreduce_bytes

    D, F, B = 6, 1024, 255
    base = hist_allreduce_bytes(D, F, B, partitions=8, mode="allreduce")
    leaf_term = (1 << D) * 4 * 2
    for pr, pf in [(2, 2), (4, 2), (2, 4), (8, 4)]:
        got = hist_allreduce_bytes(D, F, B, partitions=pr,
                                   feature_partitions=pf,
                                   mode="reduce_scatter")
        winner = sum(pr * pf * (1 << d) * 4 * 4 for d in range(D))
        assert got - winner - leaf_term <= \
            (base - leaf_term) / (pr * pf) + pr * B * 8 * D, \
            (pr, pf, got, base)
    # back-compat: the pre-2D keyword surface is unchanged.
    assert hist_allreduce_bytes(D, F, B, partitions=8) == base
    assert hist_allreduce_bytes(
        D, F, B, partitions=8, mode="reduce_scatter") == \
        hist_allreduce_bytes(D, F, B, partitions=8,
                             mode="reduce_scatter", feature_partitions=1)


def test_backend_collective_bytes_uses_second_axis():
    cfg = TrainConfig(n_bins=31, max_depth=4, backend="tpu",
                      mesh_shape=(2, 2))
    be = get_backend(cfg)
    cfg1d = TrainConfig(n_bins=31, max_depth=4, backend="tpu",
                        n_partitions=4, split_comms="allreduce")
    be1d = get_backend(cfg1d)
    F = 1024
    got_2d = be.collective_bytes_per_tree(F)
    replicated = be1d.collective_bytes_per_tree(F)
    # <= 1/(Pr*Pf) of the replicated-feature baseline + winner/leaf
    # terms (the ISSUE 11 acceptance criterion).
    winner = sum(4 * (1 << d) * 4 * 4 for d in range(4))
    leaf = (1 << 4) * 4 * 2
    assert got_2d - winner - leaf <= (replicated - leaf) / 4


# ------------------------------------------------------------------ #
# bench arm smoke
# ------------------------------------------------------------------ #

def test_bench_hist_2d_smoke():
    from ddt_tpu.bench import bench_hist_2d

    out = bench_hist_2d(rows=20_000, features=64, bins=15, depth=3,
                        iters=1, reps=2)
    assert out["kernel"] == "hist_2d_ab"
    assert out["mesh_2d"][1] > 1
    assert out["ratio_1d_over_2d"] > 0
    # deterministic payload factor vs the replicated baseline: ~Pr*Pf
    # up to the winner term.
    assert out["payload_ratio"] > 0.75 * (
        out["mesh_2d"][0] * out["mesh_2d"][1])


# ------------------------------------------------------------------ #
# host-sharded ingest: ownership + bitwise streaming + repartition
# ------------------------------------------------------------------ #

def _shard_dir(tmp_path, Xb, y, n_files):
    d = str(tmp_path / f"shards{n_files}")
    chunks_lib.shard_arrays(Xb, y, d, n_chunks=n_files)
    return d


def test_host_sharded_ownership_contract(tmp_path):
    X, y = datasets.synthetic_binary(1024, n_features=6, seed=1)
    Xb, _ = quantize(X, n_bins=15, seed=1)
    d = _shard_dir(tmp_path, Xb, y, 8)
    v0 = chunks_lib.HostShardedChunks(d, 4, process_index=0,
                                      process_count=2)
    v1 = chunks_lib.HostShardedChunks(d, 4, process_index=1,
                                      process_count=2)
    assert v0.n_chunks == 2
    assert v0.owned_slots(0) == [0, 1] and v1.owned_slots(0) == [2, 3]
    # no host reads a sub-shard it doesn't own
    with pytest.raises(PermissionError, match="ownership"):
        v0.read_part(0, 2)
    with pytest.raises(PermissionError, match="ownership"):
        v1.read_part(1, 0)
    # full-chunk reads are forbidden on multi-process views
    with pytest.raises(PermissionError, match="full-chunk"):
        v1(0)
    # labels stay a global side channel (y members only)
    np.testing.assert_array_equal(
        np.concatenate([v0.labels(c) for c in range(2)]), y)
    # assignment rotation moves ownership wholesale, coverage preserved
    v0.rotate_assignment()
    assert v0.assignment == (1, 1, 0, 0)
    assert v0.owned_slots(0) == [2, 3]
    # validation: bad groupings fail loudly
    with pytest.raises(ValueError, match="group"):
        chunks_lib.HostShardedChunks(d, 3, process_index=0,
                                     process_count=1)
    with pytest.raises(ValueError, match="multiple"):
        chunks_lib.HostShardedChunks(d, 4, process_index=0,
                                     process_count=3)


def test_host_sharded_streamed_bitwise_vs_plain(tmp_path):
    """Host-sharded streamed training == plain directory streaming at
    the same logical chunk bounds, BITWISE — and == the in-memory
    Driver in structure."""
    from ddt_tpu.streaming import fit_streaming

    X, y = datasets.synthetic_binary(4096, n_features=10, seed=11)
    Xb, _ = quantize(X, n_bins=31, seed=11)
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31, backend="tpu",
                      n_partitions=2)
    be = get_backend(cfg)

    d8 = _shard_dir(tmp_path, Xb, y, 8)      # 2 logical x 4 sub-shards
    src = chunks_lib.host_sharded_chunks(d8, shards_per_chunk=4)
    e_hs = fit_streaming(src, src.n_chunks, cfg, backend=be)

    d2 = _shard_dir(tmp_path, Xb, y, 2)      # same logical bounds
    e_dir = fit_streaming(chunks_lib.directory_chunks(d2), 2, cfg,
                          backend=be)
    for k in ("feature", "threshold_bin", "is_leaf", "leaf_value",
              "split_gain"):
        np.testing.assert_array_equal(getattr(e_dir, k),
                                      getattr(e_hs, k), err_msg=k)

    e_mem, _ = _fit(Xb, y, n_partitions=2)
    _assert_structure_equal(e_mem, e_hs)


def test_watchdog_streamed_repartition_bit_exact(tmp_path):
    """Injected straggler on the streamed device loop: the watchdog's
    ACTION fires at checkpoint-cadence boundaries (mesh rotation +
    resident-state reshard + chunk-cache drop) and the ensemble is
    bit-identical to an undisturbed run."""
    from ddt_tpu.robustness import faultplan
    from ddt_tpu.streaming import fit_streaming
    from ddt_tpu.telemetry.events import RunLog

    X, y = datasets.synthetic_binary(2048, n_features=8, seed=4)
    Xb, _ = quantize(X, n_bins=29, seed=4)
    d = _shard_dir(tmp_path, Xb, y, 4)
    cfg = TrainConfig(n_trees=6, max_depth=3, n_bins=29, backend="tpu",
                      n_partitions=2, seed=4,
                      straggler_repartition=True)
    be = get_backend(cfg)

    def src():
        return chunks_lib.host_sharded_chunks(d, shards_per_chunk=2)

    ref = fit_streaming(src(), 2, cfg, backend=be)
    rl = RunLog()
    prev = faultplan.activate(faultplan.load_plan({"faults": [
        {"site": "straggler", "device": 1, "delay_ms": 600000.0,
         "rounds": [1, 6], "times": 6}]}))
    try:
        chaotic = fit_streaming(
            src(), 2, cfg, backend=be, run_log=rl,
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    finally:
        faultplan.deactivate(prev)
    for k in ("feature", "threshold_bin", "is_leaf", "leaf_value"):
        np.testing.assert_array_equal(getattr(ref, k),
                                      getattr(chaotic, k), err_msg=k)
    kinds = [e["kind"] for e in rl.events("fault")]
    assert "straggler_detected" in kinds
    assert "repartition" in kinds


def test_watchdog_repartition_2d_mesh_bit_exact(tmp_path):
    """The in-memory watchdog ACTION now covers the 2D mesh too:
    rotate_row_partitions rolls the ROW axis of the device grid
    (feature columns preserved), so an injected straggler on a
    (2, 2) mesh repartitions without perturbing the model."""
    from ddt_tpu import api
    from ddt_tpu.robustness import faultplan
    from ddt_tpu.telemetry.events import RunLog

    X, y = datasets.synthetic_binary(1600, n_features=8, seed=4)
    Xb, _ = quantize(X, n_bins=29, seed=4)
    cfg = TrainConfig(n_trees=6, max_depth=3, n_bins=29, backend="tpu",
                      mesh_shape=(2, 2), seed=4,
                      straggler_repartition=True)
    ref = api.train(Xb, y, cfg, binned=True)
    rl = RunLog()
    prev = faultplan.activate(faultplan.load_plan({"faults": [
        {"site": "straggler", "device": 1, "delay_ms": 600000.0,
         "rounds": [1, 6], "times": 6}]}))
    try:
        chaotic = api.train(Xb, y, cfg, binned=True, run_log=rl,
                            checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2)
    finally:
        faultplan.deactivate(prev)
    for k in ("feature", "threshold_bin", "is_leaf", "leaf_value"):
        np.testing.assert_array_equal(getattr(ref.ensemble, k),
                                      getattr(chaotic.ensemble, k),
                                      err_msg=k)
    kinds = [e["kind"] for e in rl.events("fault")]
    assert "straggler_detected" in kinds
    assert "repartition" in kinds


def test_upload_row_shards_matches_upload():
    """Single-process assembly: upload_row_shards(parts) is the same
    device layout and values as upload(concat(parts))."""
    cfg = TrainConfig(n_bins=15, backend="tpu", n_partitions=2)
    be = get_backend(cfg)
    rng = np.random.default_rng(0)
    parts = [rng.integers(0, 15, size=(500, 6), dtype=np.uint8)
             for _ in range(2)]
    a = be.upload_row_shards(parts, 1000)
    b = be.upload(np.concatenate(parts))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.sharding == b.sharding
