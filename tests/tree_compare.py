"""Tree-pair comparison helpers shared by the identity fuzz suites.

`assert_trees_match_mod_ties` is the tie-proving comparator the streamed
and cross-platform identity contracts route through; because a false
NEGATIVE here would silently void those contracts, the comparator has its
own adversarial suite (tests/test_tie_comparator.py) proving it rejects
real divergences — flipped splits at non-boundary gains, perturbed
leaves, split/leaf flips away from the min_split_gain floor, swapped
children, and root-cause floods.
"""

import numpy as np


def assert_trees_match_mod_ties(full, streamed, min_split_gain,
                                leaf_rtol=1e-3, leaf_atol=2e-5,
                                leaf_contrib_atol=1e-3,
                                cascade_gain_atol=2e-3,
                                cascade_leaf_scale=5.0,
                                max_root_causes=None):
    """Bitwise tree equality, except provable f32-order boundary ties.

    Streamed training accumulates per-chunk histogram partials on host;
    the in-memory path sums once on device. The summation TREES differ,
    so where a decision's competing quantities land within ~1 bfloat16
    ULP of each other the rounded comparison can legitimately go either
    way — the same seam as cross-platform (MXU order) and cross-process
    (gloo order), measured by the round-4 fuzz campaigns at ~1 root-cause
    node per 160k (seed 197: candidate gains 0.00102997 vs 0.00102234).

    The checkable contract, enforced per tree by walking the heap from
    the root and PRUNING each divergent subtree:
      - every node whose ancestors all matched must either match
        bitwise in its decision (feature, threshold_bin, is_leaf; leaf
        values to float tolerance, gains to bf16 tolerance), or be a
        PROVABLE tie: competing gains within 2 bf16 ULPs (cross-feature
        or cross-bin flip), or a gain within 2 ULPs of min_split_gain
        (split-vs-leaf flip at the floor);
      - descendants of a flipped decision legitimately diverge and are
        excluded (different rows reach them);
      - root causes stay rare (they are measured to be). The default
        rarity cap is calibrated for the fuzz suites' scales;
        million-row witnesses pass explicit `max_root_causes`
        (boundary-tie incidence grows with row count — the config-3
        witness, experiments/config3_scale.py, documents the measured
        rates).

    Leaf values pass when EITHER bound holds: the relative/absolute
    allclose (leaf_rtol/leaf_atol), or a pred-CONTRIBUTION bound
    lr * |dv| <= leaf_contrib_atol. The second models legitimate drift
    cascade, found by the round-5 sampling campaign (case 1063): with
    reg_lambda=0 a near-empty leaf carries |value| ~ 1/min_child_weight
    (~1600 there), so an in-contract RELATIVE drift of 2e-4 is ~0.33
    absolute; times lr*sigmoid' it shifts the next round's gradients
    and moves downstream leaves by absolute amounts that blow past any
    fixed RELATIVE tolerance exactly where |v| is small (measured:
    3.5e-3 on a 0.79 leaf — 4.4e-3 relative, but only 3.5e-4 of pred
    contribution). What propagates — and what a real leaf-aggregation
    bug inflates — is lr * |dv|; the adversarial suite's perturbations
    (lr * 0.1 = 1e-2) stay firmly rejected.

    Gains get the cascade treatment too (round-5 campaign case 10030):
    once a root cause is ACCEPTED in round r0, every later round trains
    on legitimately-diverged predictions (the flipped node routes real
    rows differently), so matched decisions there carry small ABSOLUTE
    gain drift that the relative bf16 window rejects exactly where
    gains are small (measured: |dg| = 1.5e-4 on a 0.004 gain, 3.9%
    relative, trees 0-6 bit-identical and the tree-7 flip a proven
    tie). Post-root-cause rounds therefore accept EITHER the relative
    TIE or |dg| <= cascade_gain_atol (2e-3 — 13x the measured cascade,
    25x under the adversarial suite's 5e-2 corruption, which also has
    no root cause and so never activates the allowance). Rounds at or
    before the first root cause keep the strict window.

    The LEAF bounds scale by cascade_leaf_scale (5x) in post-root-cause
    rounds for the same reason: different real rows flow through later
    trees once a flip is accepted, and case 10030's tree-8 leaves
    measured dv=5.6e-3 on |v|=3.85 — relative 1.47e-3 and contribution
    1.69e-3, each ~1.5x past the tight bounds. At 5x, the adversarial
    leaf perturbation (relative 5e-2, contribution 1e-2) stays
    rejected with >= 2x margin — and scoped to cascade rounds only."""
    TIE = 2 ** -6                     # 2 bf16 ULPs, relative
    T, N = full.feature.shape
    n_root_causes = 0
    first_rc_round = None
    trees_per_round = (full.n_classes if full.loss == "softmax" else 1)
    for t in range(T):
        cascade = (first_rc_round is not None
                   and t // trees_per_round > first_rc_round)

        def gain_ok(ga, gb):
            d = abs(ga - gb)
            return (d <= TIE * max(abs(ga), abs(gb), 1e-12)
                    or (cascade and d <= cascade_gain_atol))

        queue = [0]
        while queue:
            s_ = queue.pop()
            fa, fb = int(full.feature[t, s_]), int(streamed.feature[t, s_])
            ba = int(full.threshold_bin[t, s_])
            bb = int(streamed.threshold_bin[t, s_])
            la = bool(full.is_leaf[t, s_])
            lb = bool(streamed.is_leaf[t, s_])
            ga = float(full.split_gain[t, s_])
            gb = float(streamed.split_gain[t, s_])
            if (fa, ba, la) == (fb, bb, lb):
                va = float(full.leaf_value[t, s_])
                vb = float(streamed.leaf_value[t, s_])
                dv = abs(va - vb)
                ls = cascade_leaf_scale if cascade else 1.0
                assert (dv <= ls * (leaf_atol + leaf_rtol * abs(vb))
                        or dv * full.learning_rate
                        <= ls * leaf_contrib_atol), \
                    ("leaf value", t, s_, va, vb)
                assert gain_ok(ga, gb), (t, s_, ga, gb)
                if not la and 2 * s_ + 2 < N:
                    queue += [2 * s_ + 1, 2 * s_ + 2]
                continue
            # Divergent decision with matching ancestors: a root cause.
            n_root_causes += 1
            if first_rc_round is None:
                first_rc_round = t // trees_per_round
            if la != lb:
                # split-vs-leaf flip: the split side's gain must sit at
                # the min_split_gain floor (leaves record gain 0).
                g_split = gb if la else ga
                assert (abs(g_split - min_split_gain) <= TIE * max(
                            g_split, min_split_gain)
                        or (cascade and abs(g_split - min_split_gain)
                            <= cascade_gain_atol)), \
                    (t, s_, g_split, min_split_gain)
            else:
                # both split, different (feature, bin): candidate tie.
                assert gain_ok(ga, gb), (t, s_, ga, gb)
            # Subtree excluded: different rows flow below a flipped node.
    cap = (max(1, T * N // 500) if max_root_causes is None
           else max_root_causes)
    assert n_root_causes <= cap, (n_root_causes, cap, T, N)


def assert_prefix_identity_mod_ties(ens_a, ens_b, min_split_gain,
                                    leaf_rtol=1e-3, leaf_atol=1e-5,
                                    max_root_causes=4):
    """The at-scale cross-partition identity contract (ONE home — the
    config-3 witness, experiments/config3_scale.py, and its reduced-size
    suite twin must assert the SAME thing):

      - every tree BEFORE the first structural divergence is bitwise
        identical in its decisions AND carries equivalent leaf values
        (f32 psum-order drift only — a leaf-aggregation bug that
        preserves structure must not hide behind the structural test);
      - the first divergent tree's root causes are PROVABLE
        bf16-boundary ties (assert_trees_match_mod_ties, per-tree);
      - later trees legitimately cascade (they train on the residuals
        the tied choice changed) and are NOT asserted here — callers
        add a quality-equivalence check (e.g. holdout AUC).

    Returns (bitwise_prefix_tree_count, first_divergent_tree_or_None).
    """
    import dataclasses

    def one_tree(e, t):
        return dataclasses.replace(
            e, feature=e.feature[t:t + 1],
            threshold_bin=e.threshold_bin[t:t + 1],
            threshold_raw=e.threshold_raw[t:t + 1],
            is_leaf=e.is_leaf[t:t + 1],
            leaf_value=e.leaf_value[t:t + 1],
            split_gain=e.split_gain[t:t + 1],
            default_left=(None if e.default_left is None
                          else e.default_left[t:t + 1]))

    same = [
        bool(np.array_equal(ens_a.feature[t], ens_b.feature[t])
             and np.array_equal(ens_a.threshold_bin[t],
                                ens_b.threshold_bin[t])
             and np.array_equal(ens_a.is_leaf[t], ens_b.is_leaf[t]))
        for t in range(ens_a.n_trees)
    ]
    first = same.index(False) if False in same else None
    prefix_n = first if first is not None else ens_a.n_trees
    for t in range(prefix_n):
        np.testing.assert_allclose(
            ens_a.leaf_value[t], ens_b.leaf_value[t],
            rtol=leaf_rtol, atol=leaf_atol,
            err_msg=f"prefix tree {t} leaves")
    if first is not None:
        assert_trees_match_mod_ties(
            one_tree(ens_a, first), one_tree(ens_b, first),
            min_split_gain, leaf_rtol=leaf_rtol, leaf_atol=leaf_atol,
            max_root_causes=max_root_causes)
    return prefix_n, first
