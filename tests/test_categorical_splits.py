"""Categorical one-vs-rest splits (round-1 verdict item 9, SURVEY.md §2
"one-hot-gain variant"): features listed in cfg.cat_features split as
"bin == k goes left" with one-hot gain, instead of ordinal "bin <= t" on
the frequency-ranked bins. The split type derives from the model's
cat_features metadata — no per-node storage.
"""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.categorical import fit_categorical_encoder
from ddt_tpu.data.datasets import synthetic_ctr
from ddt_tpu.data.quantizer import fit_bin_mapper
from ddt_tpu.driver import Driver
from ddt_tpu.reference import numpy_trainer as ref


def _ctr_matrix(rows=4000, bins=63, seed=0):
    """(X float32 incl. encoded cat columns, y, cat feature indices)."""
    Xn, Xc, y = synthetic_ctr(rows, seed=seed)
    enc = fit_categorical_encoder(Xc, n_bins=bins)
    X = np.concatenate([Xn, enc.transform(Xc).astype(np.float32)], axis=1)
    return X, y, tuple(range(Xn.shape[1], X.shape[1]))


# ------------------------------------------------------------------ #
# kernel twins
# ------------------------------------------------------------------ #

def test_onehot_gain_matches_oracle_kernel():
    from ddt_tpu.ops.split import best_splits as jx_best

    rng = np.random.default_rng(3)
    hist = rng.standard_normal((4, 6, 16, 2)).astype(np.float32)
    hist[..., 1] = np.abs(hist[..., 1])
    cat = np.zeros(6, bool)
    cat[[1, 4]] = True
    want = ref.best_splits(hist, 1.0, 1e-3, cat_mask=cat)
    got = jx_best(hist, 1.0, 1e-3, cat_mask=cat)
    np.testing.assert_array_equal(np.asarray(got[1]), want[1])
    np.testing.assert_array_equal(np.asarray(got[2]), want[2])
    np.testing.assert_allclose(np.asarray(got[0]), want[0],
                               rtol=1e-2, atol=1e-2)


def test_onehot_gain_hand_computed():
    """One cat feature, 3 bins, best candidate = isolating the LAST
    category — expressible only as one-vs-rest (ordinal splits exclude
    the last bin and can only cut {0} | {1,2})."""
    hist = np.zeros((1, 1, 3, 2), np.float32)
    hist[0, 0, :, 0] = [1.0, 1.0, -4.0]    # category 2 carries the signal
    hist[0, 0, :, 1] = [1.0, 1.0, 2.0]
    cat = np.ones(1, bool)
    gains, feats, bins, _ = ref.best_splits(hist, 1.0, 0.0, cat_mask=cat)
    # one-vs-rest candidates (G=-2, H=4, parent=4/5):
    #   k=0: 0.5*(1/2 + 9/4 - 0.8)  = 0.975
    #   k=1: same by symmetry        = 0.975
    #   k=2: 0.5*(16/3 + 4/3 - 0.8) = 2.933   <- winner
    assert bins[0] == 2
    np.testing.assert_allclose(
        gains[0], 0.5 * (16 / 3 + 4 / 3 - 4 / 5), rtol=1 / 128)
    # Ordinal on the same histogram cannot isolate category 2.
    _, _, b_ord, _ = ref.best_splits(hist, 1.0, 0.0)
    assert b_ord[0] != 2


# ------------------------------------------------------------------ #
# end-to-end
# ------------------------------------------------------------------ #

def _fit(backend, Xb, y, cat_features, **kw):
    cfg = TrainConfig(n_trees=5, max_depth=4, n_bins=63, backend=backend,
                      cat_features=cat_features, **kw)
    be = get_backend(cfg)
    return Driver(be, cfg, log_every=10**9).fit(Xb, y)


def test_backend_parity_with_cat_splits():
    X, y, cat = _ctr_matrix()
    m = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    ec = _fit("cpu", Xb, y, cat)
    et = _fit("tpu", Xb, y, cat)
    np.testing.assert_array_equal(ec.feature, et.feature)
    np.testing.assert_array_equal(ec.threshold_bin, et.threshold_bin)
    np.testing.assert_array_equal(ec.is_leaf, et.is_leaf)
    np.testing.assert_allclose(ec.leaf_value, et.leaf_value,
                               rtol=2e-4, atol=2e-5)
    # Some categorical split was actually chosen.
    used = ec.feature[(~ec.is_leaf) & (ec.feature >= 0)]
    assert np.isin(used, cat).any()


def test_partitioned_cat_training_identical():
    X, y, cat = _ctr_matrix()
    m = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    e1 = _fit("tpu", Xb, y, cat)
    e8 = _fit("tpu", Xb, y, cat, n_partitions=8)
    np.testing.assert_array_equal(e1.feature, e8.feature)
    np.testing.assert_array_equal(e1.threshold_bin, e8.threshold_bin)


def test_onehot_beats_ordinal_on_ctr():
    """The verdict's acceptance bar: AUC improvement over ordinal splits
    on a CTR task whose signal is EXACT-CATEGORY effects: a handful of
    specific categories (scattered across the frequency ranking) carry
    the label. One one-vs-rest split isolates each; ordinal needs several
    cuts per category and burns depth."""
    from ddt_tpu.utils.metrics import evaluate

    rng = np.random.default_rng(11)
    rows = 12000
    Xn = rng.standard_normal((rows, 4)).astype(np.float32)
    ids = rng.integers(0, 40, size=(rows, 2))
    hot = np.isin(ids[:, 0], [7, 23, 31]) | np.isin(ids[:, 1], [4, 18])
    score = 1.8 * hot + 0.4 * Xn[:, 0] + rng.standard_normal(rows) * 0.8
    y = (score > np.quantile(score, 0.7)).astype(np.int32)
    enc = fit_categorical_encoder(ids, n_bins=63)
    X = np.concatenate([Xn, enc.transform(ids).astype(np.float32)], axis=1)
    cat = (4, 5)
    tr, va = slice(0, 9000), slice(9000, None)
    kw = dict(n_trees=30, max_depth=4, n_bins=63, backend="cpu",
              log_every=10**9)
    r_one = api.train(X[tr], y[tr], cat_features=cat, **kw)
    r_ord = api.train(X[tr], y[tr], **kw)
    auc_one = evaluate("auc", y[va], api.predict(
        r_one.ensemble, X[va], mapper=r_one.mapper, raw=True))
    auc_ord = evaluate("auc", y[va], api.predict(
        r_ord.ensemble, X[va], mapper=r_ord.mapper, raw=True))
    assert auc_one > auc_ord + 0.002, (auc_one, auc_ord)


def test_predict_paths_agree_with_cat_splits():
    X, y, cat = _ctr_matrix(rows=3000)
    res = api.train(X, y, n_trees=6, max_depth=4, n_bins=63, backend="cpu",
                    cat_features=cat, log_every=10**9)
    ens, mapper = res.ensemble, res.mapper
    Xb = mapper.transform(X)
    want = ens.predict_raw(Xb, binned=True)          # NumPy oracle

    be_t = get_backend(TrainConfig(backend="tpu", n_bins=63,
                                   cat_features=cat))
    got_dev = be_t.predict_raw(ens, Xb)
    np.testing.assert_allclose(got_dev, want, rtol=2e-4, atol=2e-5)

    # CPU backend (gated off the native traversal for cat models).
    be_c = get_backend(TrainConfig(backend="cpu", n_bins=63,
                                   cat_features=cat))
    np.testing.assert_allclose(be_c.predict_raw(ens, Xb), want,
                               rtol=1e-6, atol=1e-6)


def test_cat_model_artifact_roundtrip(tmp_path):
    X, y, cat = _ctr_matrix(rows=1000)
    res = api.train(X, y, n_trees=3, max_depth=3, n_bins=63, backend="cpu",
                    cat_features=cat, log_every=10**9)
    p = str(tmp_path / "m.npz")
    res.save(p)
    b = api.load_model(p)
    np.testing.assert_array_equal(b.ensemble.cat_features, list(cat))
    p1 = api.predict(res.ensemble, X, mapper=res.mapper)
    p2 = api.predict(b.ensemble, X, mapper=b.mapper)
    np.testing.assert_array_equal(p1, p2)


def test_cat_mapper_identity_edges():
    """Categorical columns pass through binning unchanged (no quantile
    merging of category ids)."""
    X, y, cat = _ctr_matrix(rows=2000, bins=31)
    m = fit_bin_mapper(X, n_bins=31, cat_features=cat)
    Xb = m.transform(X)
    for f in cat:
        np.testing.assert_array_equal(Xb[:, f], X[:, f].astype(np.uint8))


def test_cli_criteo_onehot(tmp_path, capsys):
    import json

    from ddt_tpu.cli import main

    model = str(tmp_path / "c.npz")
    rc = main(["train", "--backend=cpu", "--dataset=criteo", "--rows=2000",
               "--trees=3", "--depth=3", "--bins=63", "--cat-splits=onehot",
               f"--out={model}"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["final_train_loss"] < 0.60
    b = api.load_model(model)
    assert b.ensemble.cat_features is not None
    assert b.ensemble.cat_features[0] == 13


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_streaming_cat_matches_inmemory(backend):
    """Streamed training with categorical one-vs-rest splits grows trees
    bit-identical to the in-memory Driver (host and device stream paths
    route 'bin == k' semantics per chunk)."""
    from ddt_tpu.streaming import fit_streaming

    X, y, cat = _ctr_matrix(rows=2048)
    m = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    cfg = TrainConfig(n_trees=4, max_depth=4, n_bins=63, backend=backend,
                      cat_features=cat)
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)

    def chunk_fn(c):
        s = c * 512
        return Xb[s:s + 512], y[s:s + 512]

    streamed = fit_streaming(chunk_fn, 4, cfg)
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  streamed.threshold_bin)
    np.testing.assert_array_equal(full.is_leaf, streamed.is_leaf)
    np.testing.assert_allclose(full.leaf_value, streamed.leaf_value,
                               rtol=2e-4, atol=2e-5)
    used = full.feature[(~full.is_leaf) & (full.feature >= 0)]
    assert np.isin(used, cat).any()    # a cat split was actually exercised


def test_cat_eval_set_and_early_stopping():
    """The Driver's incremental validation traversal honors one-vs-rest
    routing (a mis-routed val set would corrupt early stopping)."""
    X, y, cat = _ctr_matrix(rows=4000)
    cfg = TrainConfig(n_trees=12, max_depth=4, n_bins=63, backend="cpu",
                      cat_features=cat)
    from ddt_tpu.data.quantizer import fit_bin_mapper as _fbm

    m = _fbm(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    be = get_backend(cfg)
    d = Driver(be, cfg, log_every=1)
    ens = d.fit(Xb[:3000], y[:3000], eval_set=(Xb[3000:], y[3000:]),
                eval_metric="auc")
    # The recorded validation AUC must equal scoring the truncated
    # ensemble with the (cat-aware) oracle at the same round.
    from ddt_tpu.utils.metrics import evaluate

    last = d.history[-1]
    part = ens.truncate(last["round"])
    want = evaluate("auc", y[3000:], part.predict_raw(Xb[3000:], binned=True))
    np.testing.assert_allclose(last["valid_auc"], want, rtol=1e-6)


def test_cat_config_guards():
    with pytest.raises(ValueError, match="missing_policy"):
        TrainConfig(cat_features=(1,), missing_policy="learn")
    cfg = TrainConfig(cat_features=[])        # list normalizes to tuple
    assert cfg.cat_features == ()
    with pytest.raises(ValueError, match="out of range"):
        X, y, _ = _ctr_matrix(rows=200)
        from ddt_tpu.data.quantizer import quantize as _q

        Xb, _ = _q(X, n_bins=63)
        _fit("cpu", Xb, y, (X.shape[1] + 3,))


def test_feature_sharded_cat_training_identical():
    """The feature-axis cat path (cat_vec_g sliced to the shard's columns,
    global cat-ness recomputed after the all_gather winner combine) must
    grow the same tree as unsharded training."""
    X, y, cat = _ctr_matrix()
    m = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    # Pad to a column count divisible by the shard count, keeping the cat
    # indices untouched (pad columns are constant -> never chosen).
    F = Xb.shape[1]
    fp = 4
    pad = (-F) % fp
    if pad:
        Xb = np.concatenate(
            [Xb, np.zeros((Xb.shape[0], pad), np.uint8)], axis=1)
    e1 = _fit("tpu", Xb, y, cat)
    eF = _fit("tpu", Xb, y, cat, feature_partitions=fp)
    np.testing.assert_array_equal(e1.feature, eF.feature)
    np.testing.assert_array_equal(e1.threshold_bin, eF.threshold_bin)
    np.testing.assert_array_equal(e1.is_leaf, eF.is_leaf)


def test_mapper_without_identity_cat_bins_rejected():
    """A user-supplied mapper fitted WITHOUT cat_features quantile-merges
    category ids; train and predict must fail loudly, not silently train
    on corrupted categories (round-2 review finding)."""
    X, y, cat = _ctr_matrix(rows=600)
    m_plain = fit_bin_mapper(X, n_bins=63)                   # no identity
    with pytest.raises(ValueError, match="identity-binned"):
        api.train(X, y, mapper=m_plain, cat_features=cat,
                  n_trees=2, max_depth=3, n_bins=63, backend="cpu",
                  log_every=10**9)
    m_cat = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    res = api.train(X, y, mapper=m_cat, cat_features=cat,
                    n_trees=2, max_depth=3, n_bins=63, backend="cpu",
                    log_every=10**9)
    with pytest.raises(ValueError, match="identity-bin"):
        api.predict(res.ensemble, X, mapper=m_plain)
    # The training-time mapper round-trips through save/load and scores.
    m_rt = type(m_cat).load(m_cat.save())
    assert m_rt.cat_features == m_cat.cat_features
    p = api.predict(res.ensemble, X, mapper=m_rt)
    assert p.shape[0] == X.shape[0]
    # A LEGACY artifact (saved before the cat_features field existed) whose
    # edges ARE identity must still be accepted: the guard checks the
    # edges, not the metadata.
    legacy = {k: v for k, v in m_cat.save().items() if k != "cat_features"}
    m_legacy = type(m_cat).load(legacy)
    assert m_legacy.cat_features == ()
    p2 = api.predict(res.ensemble, X, mapper=m_legacy)
    np.testing.assert_allclose(p2, p, rtol=1e-6)


def test_cat_eval_set_device_path():
    """The DEVICE-side eval traversal (TPUDevice.eval_round) honors
    one-vs-rest routing — twin of test_cat_eval_set_and_early_stopping,
    which exercises the host path."""
    X, y, cat = _ctr_matrix(rows=4000)
    cfg = TrainConfig(n_trees=12, max_depth=4, n_bins=63, backend="tpu",
                      cat_features=cat)
    from ddt_tpu.data.quantizer import fit_bin_mapper as _fbm

    m = _fbm(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    be = get_backend(cfg)
    d = Driver(be, cfg, log_every=1)
    ens = d.fit(Xb[:3000], y[:3000], eval_set=(Xb[3000:], y[3000:]),
                eval_metric="auc")
    from ddt_tpu.utils.metrics import evaluate

    last = d.history[-1]
    part = ens.truncate(last["round"])
    want = evaluate("auc", y[3000:], part.predict_raw(Xb[3000:], binned=True))
    # The recorded score now comes from the binned-rank DEVICE auc twin
    # (round 5 - auc rides the fused path); 5e-5 is its documented
    # within-bin tie tolerance vs the f64 host auc
    # (utils/metrics.DEVICE_AUC_BINS).
    np.testing.assert_allclose(last["valid_auc"], want, atol=5e-5)


def test_config3_partitioned_at_reduced_scale():
    """Reduced-size twin of the config-3 at-scale witness
    (experiments/config3_scale.py; PERF.md round-5): Criteo-shaped
    categorical training over 4 row partitions upholds the scale
    contract (tree_compare.assert_prefix_identity_mod_ties — ONE home,
    shared with the witness): bitwise-identical tree prefix, any
    first-divergence root cause a PROVABLE bf16-boundary tie (the
    cross-partition psum-order seam), later trees quality-equivalent
    (holdout AUC). At this size divergence usually doesn't occur at all
    and the whole run is bitwise."""
    from tree_compare import assert_prefix_identity_mod_ties

    X, y, cat = _ctr_matrix(rows=200_000, seed=5)
    m = fit_bin_mapper(X, n_bins=63, cat_features=cat)
    Xb = m.transform(X)
    ens = {}
    for parts in (1, 4):
        cfg = TrainConfig(n_trees=6, max_depth=5, n_bins=63,
                          backend="tpu", n_partitions=parts,
                          min_split_gain=1e-3, cat_features=cat)
        ens[parts] = Driver(get_backend(cfg), cfg,
                            log_every=10**9).fit(Xb, y)

    assert_prefix_identity_mod_ties(ens[1], ens[4], 1e-3)
    from ddt_tpu.utils.metrics import auc

    a1 = auc(y, ens[1].predict_raw(Xb, binned=True))
    a4 = auc(y, ens[4].predict_raw(Xb, binned=True))
    assert abs(a1 - a4) < 1e-3, (a1, a4)
    assert np.isin(ens[4].feature[~ens[4].is_leaf], list(cat)).any()
