"""Serving tier (ddt_tpu/serve/): coalescer correctness under
concurrency, hot-swap atomicity, SLO telemetry, and run-log
back-compat.

Everything runs in-process against the engine (the HTTP layer is a thin
adapter covered by scripts/serve_smoke.py); the CPU 'tpu' backend (XLA
CPU) scores for real. Timing-sensitive behavior is made deterministic
with thread barriers and generous admission windows — the tests assert
STRUCTURE (who got which rows, which model answered), never wall-clock.
"""

import threading

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.serve.batcher import MicroBatcher, ShuttingDown
from ddt_tpu.serve.engine import (ServeEngine, bucket_for,
                                  default_buckets)
from ddt_tpu.telemetry import report as tele_report
from ddt_tpu.telemetry.events import RunLog, validate_event


@pytest.fixture(scope="module")
def trained():
    """Two small models (same shape, different seeds) + config + offline
    reference scores, shared module-wide (training is the slow part)."""
    X, y = datasets.synthetic_binary(3000, seed=5)
    kw = dict(n_trees=6, max_depth=3, n_bins=31, backend="tpu",
              log_every=10**9)
    res_a = api.train(X, y, **kw)
    # A genuinely different model version (seed alone changes nothing
    # without bagging): halving the learning rate moves every leaf.
    res_b = api.train(X, y, learning_rate=0.05, **kw)
    cfg = TrainConfig(backend="tpu", n_bins=31)
    ref = {
        "a": np.asarray(api.predict(res_a.ensemble, X, mapper=res_a.mapper,
                                    cfg=cfg)),
        "b": np.asarray(api.predict(res_b.ensemble, X, mapper=res_b.mapper,
                                    cfg=cfg)),
    }
    return dict(X=X, res_a=res_a, res_b=res_b, cfg=cfg, ref=ref)


def _bundle(res):
    return api.ModelBundle(ensemble=res.ensemble, mapper=res.mapper)


def _engine(trained, **kw):
    kw.setdefault("max_wait_ms", 25.0)      # deterministic coalescing
    kw.setdefault("max_batch", 64)
    return ServeEngine(_bundle(trained["res_a"]), trained["cfg"], **kw)


# --------------------------------------------------------------------- #
# buckets
# --------------------------------------------------------------------- #
def test_bucket_ladder():
    assert default_buckets(64) == (1, 2, 4, 8, 16, 32, 64)
    assert default_buckets(48) == (1, 2, 4, 8, 16, 32, 48)
    bs = default_buckets(64)
    assert bucket_for(1, bs) == 1
    assert bucket_for(3, bs) == 4
    assert bucket_for(64, bs) == 64
    assert bucket_for(999, bs) == 64        # oversize: largest bucket


# --------------------------------------------------------------------- #
# coalescer correctness under concurrent submitters
# --------------------------------------------------------------------- #
def test_concurrent_submitters_coalesce_and_keep_rows_straight(trained):
    """16 barrier-synchronized single-row submitters: every response is
    the offline answer FOR THAT ROW (no drops, no duplicates, no
    permutation), and the batcher provably coalesced >= 8 of them into
    one dispatch (the ISSUE 8 acceptance bar)."""
    eng = _engine(trained)
    try:
        X, ref = trained["X"], trained["ref"]["a"]
        n = 16
        barrier = threading.Barrier(n)
        got = [None] * n

        def worker(i):
            barrier.wait()
            got[i] = eng.predict(X[i:i + 1], timeout=60.0)[0]

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        np.testing.assert_allclose(np.array(got), ref[:n],
                                   rtol=1e-6, atol=1e-7)
        assert eng.stats.coalesce_max >= 8, eng.stats.snapshot()
    finally:
        eng.close()


def test_mixed_size_requests_slice_back_positionally(trained):
    """Concurrent requests of different row counts: each gets exactly
    its own block back (the scatter is positional, not shape-matched)."""
    eng = _engine(trained)
    try:
        X, ref = trained["X"], trained["ref"]["a"]
        spans = [(0, 1), (1, 8), (9, 3), (12, 5), (17, 1), (18, 16)]
        barrier = threading.Barrier(len(spans))
        got = [None] * len(spans)

        def worker(k, start, cnt):
            barrier.wait()
            got[k] = eng.predict(X[start:start + cnt], timeout=60.0)

        threads = [threading.Thread(target=worker, args=(k, s, c))
                   for k, (s, c) in enumerate(spans)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        for k, (s, c) in enumerate(spans):
            assert got[k].shape[0] == c
            np.testing.assert_allclose(got[k], ref[s:s + c],
                                       rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_raw_float_rows_bin_with_the_training_mapper(trained):
    """Float rows submitted to a mapper-carrying model score identically
    to the offline mapper path (binning happens under the serving
    model, per-dispatch)."""
    eng = _engine(trained)
    try:
        X, ref = trained["X"], trained["ref"]["a"]
        out = eng.predict(X[:7].astype(np.float32), timeout=60.0)
        np.testing.assert_allclose(out, ref[:7], rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_dispatch_errors_reach_the_waiter_not_the_thread(trained):
    """A request the model cannot score fails ITS OWN waiter with the
    cause; the dispatcher thread survives and keeps serving."""
    eng = ServeEngine(
        api.ModelBundle(ensemble=trained["res_a"].ensemble, mapper=None),
        trained["cfg"], max_wait_ms=5.0)
    try:
        with pytest.raises(ValueError, match="bin mapper"):
            # Float rows but no mapper on the bundle: transform refuses.
            eng.predict(np.zeros((1, eng._model.n_features), np.float32),
                        timeout=60.0)
        # The engine still serves binned requests afterwards.
        Xb = trained["res_a"].mapper.transform(trained["X"][:3])
        out = eng.predict(Xb, timeout=60.0)
        np.testing.assert_allclose(out, trained["ref"]["a"][:3],
                                   rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_submit_validation_and_shutdown(trained):
    eng = _engine(trained)
    with pytest.raises(ValueError, match="features"):
        eng.predict(np.zeros((1, 3), np.uint8))
    eng.close()
    with pytest.raises(ShuttingDown):
        eng.predict_async(np.zeros((1, eng._model.n_features), np.uint8))


def test_oversize_request_scores_on_pretraced_shapes(trained):
    """A request larger than max_batch dispatches solo but must STILL
    ride pre-traced bucket shapes (chunked scoring) — and return the
    offline answer for every row."""
    eng = _engine(trained, max_batch=8, max_wait_ms=1.0)
    try:
        X, ref = trained["X"], trained["ref"]["a"]
        out = eng.predict(X[:21], timeout=60.0)   # 21 > max_batch=8
        assert out.shape[0] == 21
        np.testing.assert_allclose(out, ref[:21], rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_dispatch_validates_width_per_request(trained):
    """A stale-width request (the submit-vs-dispatch swap race) fails
    ITS OWN waiter at dispatch time; a valid request sharing the
    admission window still gets its answer."""
    eng = _engine(trained)
    try:
        F = eng._model.n_features
        # Bypass submit-time validation — exactly what a hot swap to a
        # different-width model does to an already-queued request.
        bad = eng._batcher.submit(np.zeros((1, F + 2), np.uint8), 1)
        good = eng.predict_async(
            trained["res_a"].mapper.transform(trained["X"][:1]))
        with pytest.raises(ValueError, match="features"):
            bad.result(timeout=60.0)
        np.testing.assert_allclose(good.result(timeout=60.0),
                                   trained["ref"]["a"][:1],
                                   rtol=1e-6, atol=1e-7)
    finally:
        eng.close()


def test_batcher_respects_row_budget():
    """Unit-level: requests never split, batches never exceed max_batch
    rows (except a lone oversize request, which dispatches solo)."""
    batches = []
    done = threading.Event()

    def dispatch(batch, depth):
        batches.append([r.n for r in batch])
        for r in batch:
            r.set_result(np.zeros(r.n))
        if sum(len(b) for b in batches) >= 4:
            done.set()

    mb = MicroBatcher(dispatch, max_wait_ms=30.0, max_batch=4)
    reqs = [mb.submit(np.zeros((n, 2)), n) for n in (3, 3, 4, 9)]
    for r in reqs:
        r.result(timeout=30.0)
    mb.close()
    flat = [n for b in batches for n in b]
    assert flat == [3, 3, 4, 9]             # FIFO, nothing dropped
    for b in batches:
        assert sum(b) <= 4 or (len(b) == 1 and b[0] > 4)


# --------------------------------------------------------------------- #
# hot swap
# --------------------------------------------------------------------- #
def test_hot_swap_mid_flight_returns_old_or_new_never_a_mix(trained):
    """Requests hammer the engine while the model swaps A -> B
    mid-flight: zero failures, and every multi-row response matches
    model A's answer for the WHOLE block or model B's — never a blend
    (one model reference per micro-batch)."""
    eng = _engine(trained, max_wait_ms=2.0)
    try:
        X = trained["X"]
        ra, rb = trained["ref"]["a"], trained["ref"]["b"]
        stop = threading.Event()
        results, errors = [], []

        def hammer(tid):
            rng = np.random.default_rng(tid)
            while not stop.is_set():
                s = int(rng.integers(0, 100))
                c = int(rng.integers(1, 6))
                try:
                    out = eng.predict(X[s:s + c], timeout=60.0)
                    results.append((s, c, np.asarray(out)))
                except Exception as e:  # ddtlint: disable=broad-except — collected and asserted empty below
                    errors.append(repr(e))

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        # let some A-era requests land, then swap, then more traffic
        import time as _time

        while len(results) < 20:
            _time.sleep(0.002)
        swap_info = eng.swap(_bundle(trained["res_b"]))
        while len(results) < 60:
            _time.sleep(0.002)
        stop.set()
        for t in threads:
            t.join(60)
        assert not errors, errors[:5]
        assert swap_info["old"] != swap_info["new"]

        n_b = 0
        for s, c, out in results:
            is_a = np.allclose(out, ra[s:s + c], rtol=1e-6, atol=1e-7)
            is_b = np.allclose(out, rb[s:s + c], rtol=1e-6, atol=1e-7)
            assert is_a or is_b, f"rows [{s}:{s + c}] match neither model"
            n_b += bool(is_b and not is_a)
        # Traffic after the swap exists, so SOME responses came from B.
        assert n_b > 0
        assert eng.model_token == swap_info["new"]
    finally:
        eng.close()


def test_swap_emits_counter_and_fault_event(trained):
    from ddt_tpu.telemetry import counters as tele_counters

    rl = RunLog()                            # ring-only
    eng = _engine(trained, run_log=rl)
    try:
        c0 = tele_counters.snapshot()
        eng.swap(_bundle(trained["res_b"]))
        assert tele_counters.delta(c0)["serve_hot_swaps"] == 1
        kinds = [e["kind"] for e in rl.events("fault")]
        assert "hot_swap" in kinds
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# SLO telemetry + schema back-compat
# --------------------------------------------------------------------- #
def test_serve_latency_event_emits_validates_and_renders(trained, tmp_path):
    path = str(tmp_path / "serve.jsonl")
    eng = _engine(trained, run_log=path)
    try:
        for i in range(10):
            eng.predict(trained["X"][i:i + 1], timeout=60.0)
        payload = eng.emit_latency()
        assert payload["requests"] == 10
        assert payload["p50_ms"] <= payload["p99_ms"] <= payload["p999_ms"]
    finally:
        eng.close()
    events = tele_report.read_events(path)
    sl = [e for e in events if e["event"] == "serve_latency"]
    assert len(sl) == 1                      # close() found an empty window
    validate_event(sl[0])
    summary = tele_report.summarize(events)
    s = summary["serving"]
    assert s["requests"] == 10 and s["windows"] == 1
    assert s["coalesce_max"] >= 1
    rendered = tele_report.render(summary)
    assert "serving: 10 requests" in rendered
    assert "p99=" in rendered


def test_empty_window_emits_nothing(trained):
    rl = RunLog()
    eng = _engine(trained, run_log=rl)
    try:
        assert eng.emit_latency() is None
        assert rl.events("serve_latency") == []
    finally:
        eng.close()


def _v3_log(path):
    """A minimal schema-3 log exactly as the pre-serving writer shaped
    it — the back-compat fixture (serve_latency must be purely
    additive)."""
    import json

    recs = [
        {"event": "run_manifest", "schema": 3, "t": 100.0, "seq": 0,
         "trainer": "driver", "backend": "tpu", "loss": "logloss",
         "n_trees": 2, "max_depth": 3, "rows": 10, "features": 4,
         "run_id": "cafe01234567", "host": 0},
        {"event": "round", "schema": 3, "t": 101.0, "seq": 1,
         "round": 1, "ms_per_round": 5.0, "train_loss": 0.6},
        {"event": "cost_analysis", "schema": 3, "t": 101.5, "seq": 2,
         "op": "hist", "flops": 1e9, "bytes_accessed": 1e8,
         "phase": "grow", "calls": 2},
        {"event": "phase_timings", "schema": 3, "t": 102.0, "seq": 3,
         "phases": [{"phase": "grow", "ms_total": 5.0,
                     "ms_per_call": 2.5, "calls": 2, "share": 1.0}]},
        {"event": "run_end", "schema": 3, "t": 103.0, "seq": 4,
         "completed_rounds": 1, "wallclock_s": 3.0},
    ]
    with open(path, "w", encoding="utf-8") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_old_schema_logs_parse_through_report_merge_trace(tmp_path):
    """Schema <= 3 logs (no serve_latency) keep parsing through
    report/merge/trace after the v4 bump, and their summaries carry
    serving=None so renderers change nothing."""
    from ddt_tpu.telemetry import merge as tele_merge
    from ddt_tpu.telemetry import perfetto

    p = str(tmp_path / "v3.jsonl")
    _v3_log(p)
    events = tele_merge.merge_paths([p])
    summary = tele_report.summarize(events)
    assert summary["serving"] is None
    rendered = tele_report.render(summary)
    assert "serving:" not in rendered
    out = str(tmp_path / "trace.json")
    assert perfetto.write_trace(events, out) > 0


# --------------------------------------------------------------------- #
# express lane (ISSUE 12)
# --------------------------------------------------------------------- #
def test_express_lane_dispatches_single_rows_at_empty_queue(trained):
    """A lone single-row request at an empty queue rides the express
    lane: correct score, stamped token, express counted in the stats
    window — and it never paid the admission window (structural: the
    window is absurdly long, the test would time out if it waited)."""
    eng = _engine(trained, max_wait_ms=60_000.0)
    try:
        X, ref = trained["X"], trained["ref"]["a"]
        got = eng.predict(X[:1], timeout=30.0)
        np.testing.assert_allclose(got, ref[:1], rtol=1e-6, atol=1e-7)
        w = eng.stats.window_summary(reset=False)
        assert w["express"] == 1 and w["requests"] == 1
        assert eng.health()["express_lane"] is True
    finally:
        eng.close()


def test_express_lane_closes_under_load(trained):
    """With the dispatch gate held (a batch 'mid-flight') and requests
    queued, a single-row submit must NOT express — it joins the queue
    and coalesces with the backlog once the gate frees."""
    eng = _engine(trained, max_wait_ms=5.0)
    try:
        X, ref = trained["X"], trained["ref"]["a"]
        eng._batcher._gate.acquire()          # simulate dispatch in flight
        try:
            queued = [eng.predict_async(X[i:i + 1]) for i in range(4)]
        finally:
            eng._batcher._gate.release()
        for i, p in enumerate(queued):
            np.testing.assert_allclose(p.result(timeout=30.0),
                                       ref[i:i + 1],
                                       rtol=1e-6, atol=1e-7)
        w = eng.stats.window_summary(reset=False)
        assert w["express"] == 0, w           # the lane stayed shut
        assert w["coalesce_max"] > 1          # the backlog coalesced
    finally:
        eng.close()


def test_express_lane_old_or_new_never_a_mix_under_hot_swap(trained):
    """Express responses under a mid-flight hot swap: every single-row
    answer matches model A's or model B's offline score exactly — the
    lane reads the model reference once, so a swap cannot blend."""
    eng = _engine(trained, max_wait_ms=1.0)
    try:
        X = trained["X"]
        ra, rb = trained["ref"]["a"], trained["ref"]["b"]
        stop = threading.Event()
        results, errors = [], []

        def hammer(tid):
            rng = np.random.default_rng(tid)
            while not stop.is_set():
                s = int(rng.integers(0, 100))
                try:
                    out = eng.predict(X[s:s + 1], timeout=60.0)
                    results.append((s, np.asarray(out)))
                except Exception as e:  # ddtlint: disable=broad-except — collected and asserted empty below
                    errors.append(repr(e))

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(3)]
        for t in threads:
            t.start()
        import time as _time

        while len(results) < 15:
            _time.sleep(0.002)
        eng.swap(_bundle(trained["res_b"]))
        while len(results) < 45:
            _time.sleep(0.002)
        stop.set()
        for t in threads:
            t.join(60)
        assert not errors, errors[:5]
        n_b = 0
        for s, out in results:
            is_a = np.allclose(out, ra[s:s + 1], rtol=1e-6, atol=1e-7)
            is_b = np.allclose(out, rb[s:s + 1], rtol=1e-6, atol=1e-7)
            assert is_a or is_b, f"row {s} matches neither model"
            n_b += bool(is_b and not is_a)
        assert n_b > 0
        assert eng.stats.express > 0          # some traffic took the lane
    finally:
        eng.close()


def test_express_lane_opt_out_and_shutdown(trained):
    """express_lane=False keeps every request on the queued path; a
    closed engine's express path raises ShuttingDown like submit."""
    eng = _engine(trained, max_wait_ms=2.0, express_lane=False)
    X = trained["X"]
    out = eng.predict(X[:1], timeout=30.0)
    assert out.shape[0] == 1
    assert eng.stats.window_summary(reset=False)["express"] == 0
    assert eng.health()["express_lane"] is False
    eng.close()
    eng2 = _engine(trained)
    eng2.close()
    with pytest.raises(ShuttingDown):
        eng2.predict_async(np.zeros((1, eng2.n_features), np.uint8))


def test_batcher_deadline_pinned_to_oldest_request_fake_clock():
    """The admission deadline is pinned to the OLDEST queued request
    when its window opens — later arrivals re-notify the Condition but
    must not re-arm the window (a re-arming batcher stretches a batch
    past the head request's budget under a steady trickle; this
    fake-clock drive would then never dispatch and the result() below
    would time out)."""
    fake = {"t": 0.0}
    batches = []

    def dispatch(batch, depth):
        batches.append([r.n for r in batch])
        for r in batch:
            r.set_result(np.zeros(r.n))

    mb = MicroBatcher(dispatch, max_wait_ms=50.0, max_batch=1000,
                      clock=lambda: fake["t"])
    try:
        a = mb.submit(np.zeros((1, 2)), 1)       # head: deadline t=0.05
        trickle = [mb.submit(np.zeros((1, 2)), 1) for _ in range(3)]
        # Advance PAST the head's deadline, then trickle one more
        # arrival: its notify wakes the dispatcher, which must see the
        # head's (expired) deadline — NOT a fresh one measured from
        # this arrival — and dispatch everything queued.
        fake["t"] = 0.06
        late = mb.submit(np.zeros((1, 2)), 1)
        a.result(timeout=10.0)
        late.result(timeout=10.0)
        for r in trickle:
            r.result(timeout=10.0)
        # Everything dispatched (a re-armer never gets here), and the
        # head request was not left waiting behind the trickle: its
        # batch is the FIRST one. (The real-time timeout wake can race
        # the late submit, legally splitting `late` into a second
        # batch — the pin under test is the head's deadline, not the
        # packing.)
        assert sum(len(b) for b in batches) == 5
        assert len(batches[0]) >= 4, batches
    finally:
        mb.close()


# --------------------------------------------------------------------- #
# zero-copy binned wire path (ISSUE 12)
# --------------------------------------------------------------------- #
def test_decode_raw_rows_contract():
    from ddt_tpu.serve.http import decode_raw_rows

    body = bytes(range(12))
    rows = decode_raw_rows(body, 4, 12)
    assert rows.shape == (3, 4) and rows.dtype == np.uint8
    np.testing.assert_array_equal(rows.reshape(-1),
                                  np.frombuffer(body, np.uint8))
    with pytest.raises(ValueError, match="Content-Length"):
        decode_raw_rows(body, 4, None)
    with pytest.raises(ValueError, match="declared"):
        decode_raw_rows(body, 4, 13)          # truncated body
    with pytest.raises(ValueError, match="whole number"):
        decode_raw_rows(body, 5, 12)          # width mismatch
    with pytest.raises(ValueError, match="empty"):
        decode_raw_rows(b"", 4, 0)


def test_binned_raw_wire_parity_with_float_body(trained):
    """End to end over real HTTP: POST /predict?binned=raw (the body IS
    the uint8 row block) scores bit-identically to the JSON float-body
    path on the same engine — the zero-copy path changes transport,
    never answers."""
    import json as _json
    import urllib.error
    import urllib.request

    from ddt_tpu.serve.http import serve_forever

    eng = _engine(trained, max_wait_ms=2.0)
    ready = threading.Event()
    th = threading.Thread(target=serve_forever, args=(eng,),
                          kwargs=dict(port=0, ready_event=ready),
                          daemon=True)
    th.start()
    assert ready.wait(60)
    port = eng.http_port
    try:
        X = trained["X"]
        Xb = trained["res_a"].mapper.transform(X[:5])

        def post(path, data, ctype):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=data,
                headers={"Content-Type": ctype}, method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                return _json.loads(r.read())

        r_raw = post("/predict?binned=raw", Xb.tobytes(),
                     "application/octet-stream")
        r_json = post("/predict",
                      _json.dumps({"rows": X[:5].tolist()}).encode(),
                      "application/json")
        assert r_raw["model"] == r_json["model"]
        np.testing.assert_array_equal(np.asarray(r_raw["scores"]),
                                      np.asarray(r_json["scores"]))
        # Width mismatch: 400, loudly.
        try:
            post("/predict?binned=raw", Xb.tobytes()[:-1],
                 "application/octet-stream")
            raise AssertionError("truncated raw body was accepted")
        except urllib.error.HTTPError as e:
            body = e.read()
            assert e.code == 400
            assert b"whole number" in body or b"declared" in body
        # /healthz reports the serving tier (f32 here — no quantize).
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
            h = _json.loads(r.read())
        assert h["predict_impl"] == "f32"
    finally:
        post_shutdown = urllib.request.Request(
            f"http://127.0.0.1:{port}/shutdown", data=b"{}",
            method="POST")
        urllib.request.urlopen(post_shutdown, timeout=30).read()
        th.join(30)


# --------------------------------------------------------------------- #
# per-request trace propagation + /metrics exposition (ISSUE 17)
# --------------------------------------------------------------------- #
def test_trace_breakdown_on_express_and_coalesced_lanes(trained):
    """Every completed request carries a trace id and a full timing
    breakdown (handler/queue/gate/device/wake summing into total), on
    BOTH lanes: the express lane's handler segment is structurally zero
    (accept and admit are the same stamp), the coalesced lane's queue
    segment covers the admission window. The ring at debug_traces()
    holds the records."""
    from ddt_tpu.serve.batcher import trace_breakdown

    eng = _engine(trained, max_wait_ms=2.0)
    try:
        X = trained["X"]
        p_express = eng.predict_async(X[:1])
        p_express.result(timeout=30.0)
        assert p_express.trace_id is not None
        bd = trace_breakdown(p_express)
        assert bd is not None
        assert set(bd) == {"handler_ms", "queue_ms", "gate_ms",
                           "device_ms", "wake_ms", "total_ms"}
        assert bd["handler_ms"] == 0.0        # express: accept == admit
        assert bd["device_ms"] > 0.0
        assert bd["total_ms"] >= bd["device_ms"]

        p_batch = eng.predict_async(X[:3])    # multi-row: queued lane
        p_batch.result(timeout=30.0)
        bd2 = trace_breakdown(p_batch)
        assert bd2 is not None and bd2["total_ms"] > 0.0
        ring = eng.debug_traces()
        assert set(ring) == {"default"}
        ids = [t["trace_id"] for t in ring["default"]]
        assert p_express.trace_id in ids and p_batch.trace_id in ids
        rec = next(t for t in ring["default"]
                   if t["trace_id"] == p_express.trace_id)
        assert rec["express"] is True and rec["rows"] == 1
        assert rec["device_ms"] == bd["device_ms"]
    finally:
        eng.close()


def test_trace_id_propagation_and_opt_out(trained):
    """A client-supplied trace id is honored verbatim; with
    request_traces=False no breakdown is measured (marks stay None) but
    a supplied id still rides through — propagation without
    measurement — and nothing lands in the ring."""
    from ddt_tpu.serve.batcher import trace_breakdown

    eng = _engine(trained, max_wait_ms=2.0)
    try:
        p = eng.predict_async(trained["X"][:1], trace_id="client-abc-1")
        p.result(timeout=30.0)
        assert p.trace_id == "client-abc-1"
        assert trace_breakdown(p) is not None
    finally:
        eng.close()
    eng2 = _engine(trained, max_wait_ms=2.0, request_traces=False)
    try:
        p = eng2.predict_async(trained["X"][:1], trace_id="client-abc-2")
        p.result(timeout=30.0)
        assert p.trace_id == "client-abc-2"   # echoed, not measured
        assert trace_breakdown(p) is None
        q = eng2.predict_async(trained["X"][:1])
        q.result(timeout=30.0)
        assert q.trace_id is None             # no server-minted ids
        assert eng2.debug_traces() == {"default": []}
    finally:
        eng2.close()


def test_serve_trace_flush_emits_validating_event(trained):
    """flush_traces() lands the ring as ONE schema-valid serve_trace
    event (reason stamped); an empty ring emits nothing."""
    rl = RunLog()
    eng = _engine(trained, max_wait_ms=2.0, run_log=rl)
    try:
        assert eng.flush_traces() == 0        # nothing served yet
        for i in range(3):
            eng.predict(trained["X"][i:i + 1], timeout=30.0)
        n = eng.flush_traces(reason="on_demand")
        assert n == 3
        evs = rl.events("serve_trace")
        assert len(evs) == 1
        validate_event(evs[0])
        assert evs[0]["count"] == 3 and evs[0]["reason"] == "on_demand"
        assert len(evs[0]["traces"]) == 3
        assert all(t["total_ms"] >= 0 for t in evs[0]["traces"])
    finally:
        eng.close()


def test_metrics_exposition_renders_and_parses(trained):
    """The /metrics body: every process counter becomes a
    ddt_*_total series, the per-model histogram is CUMULATIVE with
    le="+Inf" equal to _count, and _count equals the requests served."""
    from ddt_tpu.serve.metrics import parse_exposition, render_metrics
    from ddt_tpu.telemetry import counters as tele_counters

    eng = _engine(trained, max_wait_ms=2.0)
    try:
        for i in range(5):
            eng.predict(trained["X"][i:i + 1], timeout=30.0)
        text = render_metrics(tele_counters.snapshot(),
                              eng.metrics_snapshot())
        series = parse_exposition(text)
        for key, v in tele_counters.snapshot().items():
            name = f"ddt_{key}_total"
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                assert series[name][()] == float(v), name
        lab = lambda **kw: frozenset(kw.items())  # noqa: E731
        count = series["ddt_serve_latency_ms_count"][lab(model="default")]
        assert count == 5.0
        buckets = series["ddt_serve_latency_ms_bucket"]
        inf = buckets[lab(model="default", le="+Inf")]
        assert inf == count                   # +Inf == _count by contract
        finite = sorted(
            ((float(dict(k)["le"]), v) for k, v in buckets.items()
             if dict(k)["le"] != "+Inf"))
        vals = [v for _, v in finite]
        assert vals == sorted(vals)           # cumulative: monotone
        assert series["ddt_serve_backlog_rows"][lab(model="default")] == 0.0
        assert series["ddt_serve_resident_models"][()] == 1.0
        assert "ddt_serve_slo_objective_ms" not in series  # no SLO here
    finally:
        eng.close()


def test_metrics_scrape_is_read_only_vs_stats_emit(trained):
    """THE regression pin (ISSUE 17): /metrics never resets anything.
    Interleave scrapes with /stats?emit=1 over live HTTP — the emitted
    window still carries every request (scrapes stole none), back-to-
    back scrapes with no traffic are byte-identical, and the histogram
    count keeps running across the window reset. Trace id round trip
    rides the same storm."""
    import json as _json
    import urllib.request

    from ddt_tpu.serve.http import serve_forever

    eng = _engine(trained, max_wait_ms=2.0)
    ready = threading.Event()
    th = threading.Thread(target=serve_forever, args=(eng,),
                          kwargs=dict(port=0, ready_event=ready),
                          daemon=True)
    th.start()
    assert ready.wait(60)
    port = eng.http_port

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.read().decode()

    try:
        X = trained["X"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=_json.dumps({"rows": X[:1].tolist()}).encode(),
            headers={"Content-Type": "application/json",
                     "X-DDT-Trace-Id": "pin-roundtrip-7"},
            method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
            assert r.headers["X-DDT-Trace-Id"] == "pin-roundtrip-7"
            timing = r.headers["X-DDT-Timing"]
        segs = dict(kv.split("=") for kv in timing.split(","))
        assert set(segs) == {"handler", "queue", "gate", "device",
                             "wake", "total"}
        for i in range(1, 6):
            with urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/predict",
                    data=_json.dumps({"rows": X[i:i + 1].tolist()}
                                     ).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST"), timeout=30) as r:
                r.read()
        scrape_a = get("/metrics")
        assert _json.loads(get("/stats"))["requests"] == 6
        scrape_b = get("/metrics")
        assert scrape_a == scrape_b           # scrape-idempotent
        # The ?emit=1 window still owns ALL the traffic: the two
        # scrapes and the plain /stats read in between stole nothing.
        emitted = _json.loads(get("/stats?emit=1"))
        assert emitted["requests"] == 6
        assert _json.loads(get("/stats"))["requests"] == 0  # reset
        from ddt_tpu.serve.metrics import parse_exposition
        series = parse_exposition(get("/metrics"))
        key = frozenset({("model", "default")})
        assert series["ddt_serve_latency_ms_count"][key] == 6.0
        # /debug/requests: the ring over HTTP, id still addressable.
        dbg = _json.loads(get("/debug/requests"))
        ids = [t["trace_id"] for t in dbg["models"]["default"]]
        assert "pin-roundtrip-7" in ids
    finally:
        post_shutdown = urllib.request.Request(
            f"http://127.0.0.1:{port}/shutdown", data=b"{}",
            method="POST")
        urllib.request.urlopen(post_shutdown, timeout=30).read()
        th.join(30)


def test_single_model_healthz_unchanged_pre_slo(trained):
    """Satellite pin: a single-model server's health payload gained
    NOTHING from the SLO machinery (no slo keys, no fleet keys) — the
    operations plane is schema-additive and fleet-scoped."""
    eng = _engine(trained)
    try:
        h = eng.health()
        assert not any(k.startswith("slo") for k in h)
        assert "backlog_rows" not in h and "resident_models" not in h
    finally:
        eng.close()


def test_v4_serve_log_roundtrips_merge_and_trace(trained, tmp_path):
    """A log WITH serve_latency events survives merge + Perfetto export
    (the event rides as an instant marker)."""
    import json

    from ddt_tpu.telemetry import merge as tele_merge
    from ddt_tpu.telemetry import perfetto

    path = str(tmp_path / "serve.jsonl")
    eng = _engine(trained, run_log=path)
    try:
        for i in range(4):
            eng.predict(trained["X"][i:i + 1], timeout=60.0)
        eng.emit_latency()
    finally:
        eng.close()
    events = tele_merge.merge_paths([path])
    out = str(tmp_path / "trace.json")
    assert perfetto.write_trace(events, out) > 0
    with open(out, encoding="utf-8") as f:
        names = [e.get("name") for e in json.load(f)["traceEvents"]]
    assert "serve_latency" in names
